"""The reference-DIALECT prover closes the bit-parity loop on own circuits.

`compat.prove_reference.prove_reference_dialect` produces proofs in the
reference's transcript dialect; `compat.verifier.verify_reference_proof` —
the same byte-level reimplementation of the reference verification algorithm
(verifier.rs:888) that validates the golden Era artifacts — must accept them
INCLUDING the full quotient identity at z (which the golden Era circuit
cannot check, its gate config living in an external crate). Tampering with
any committed value must reject.
"""

import copy
import json

import numpy as np
import pytest

from boojum_tpu.compat.prove_reference import prove_reference_dialect
from boojum_tpu.compat.verifier import verify_reference_proof
from boojum_tpu.cs.gates import ConstantsAllocatorGate, FmaGate, PublicInputGate
from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.types import CSGeometry


def _fma_assembly(n_gates=300, capacity=1 << 9):
    geom = CSGeometry(8, 0, 6, 4)
    cs = ConstraintSystem(geom, capacity)
    a = ConstantsAllocatorGate.allocate_constant(cs, 3)
    b = ConstantsAllocatorGate.allocate_constant(cs, 5)
    out = a
    for _ in range(n_gates):
        out = FmaGate.fma(cs, out, b, a, 7, 11)
    PublicInputGate.place(cs, out)
    return cs.into_assembly()


def test_reference_dialect_fma_circuit_full_identity():
    asm = _fma_assembly()
    art = prove_reference_dialect(
        asm, fri_lde_factor=4, cap_size=8, security_level=40, pow_bits=0
    )
    assert verify_reference_proof(
        art.vk, art.proof, art.config, check_quotient_identity=True
    )
    # artifacts already round-tripped through the golden-artifact serde
    # loaders inside prove_reference_dialect; pin that the JSON is complete
    assert json.dumps(art.proof_json) and json.dumps(art.vk_json)


def test_reference_dialect_lookup_circuit_full_identity():
    from boojum_tpu.examples import build_xor_lookup_circuit

    cs, _, _ = build_xor_lookup_circuit(num_lookups=16, capacity=1 << 9)
    asm = cs.into_assembly()
    art = prove_reference_dialect(
        asm, fri_lde_factor=4, cap_size=8, security_level=40, pow_bits=0
    )
    assert verify_reference_proof(
        art.vk, art.proof, art.config, check_quotient_identity=True
    )


def test_reference_dialect_tamper_rejected():
    asm = _fma_assembly(n_gates=120)
    art = prove_reference_dialect(
        asm, fri_lde_factor=4, cap_size=8, security_level=40, pow_bits=0
    )
    # tampered opening at z
    p = copy.deepcopy(art.proof)
    c0, c1 = p.values_at_z[0]
    p.values_at_z[0] = ((c0 + 1) % ((1 << 64) - (1 << 32) + 1), c1)
    assert not verify_reference_proof(art.vk, p, art.config)
    # tampered public input
    p = copy.deepcopy(art.proof)
    p.public_inputs[0] = (p.public_inputs[0] + 1) % (
        (1 << 64) - (1 << 32) + 1
    )
    assert not verify_reference_proof(art.vk, p, art.config)
    # tampered FRI leaf
    p = copy.deepcopy(art.proof)
    q = p.queries_per_fri_repetition[0]
    q.fri[0].leaf_elements[0] = (q.fri[0].leaf_elements[0] + 1) % (
        (1 << 64) - (1 << 32) + 1
    )
    assert not verify_reference_proof(art.vk, p, art.config)


def test_reference_dialect_pow_grinding():
    asm = _fma_assembly(n_gates=60)
    # pow_bits=3 exercises the schedule's pow adjustment (raw=37 is not a
    # multiple of rate_log=2, so compute_fri_schedule lowers it to 2; the
    # recorded proof_config must carry the adjusted fixed point)
    art = prove_reference_dialect(
        asm, fri_lde_factor=4, cap_size=8, security_level=40, pow_bits=3
    )
    assert art.proof.proof_config["pow_bits"] == 2
    assert verify_reference_proof(
        art.vk, art.proof, art.config, check_quotient_identity=True
    )
    p = copy.deepcopy(art.proof)
    p.pow_challenge += 1
    assert not verify_reference_proof(art.vk, p, art.config)
