"""Field-layer parity tests vs python-int ground truth.

Mirrors the reference's field test strategy
(/root/reference/src/field/traits/field.rs:546 axioms,
 src/field/goldilocks/generic_impl.rs vector-op checks).
"""

import random

import jax.numpy as jnp
import numpy as np

from boojum_tpu.field import gl
from boojum_tpu.field import goldilocks as gf
from boojum_tpu.field import extension as ext

P = gl.P
rng = random.Random(1234)


def rand_vec(n, special_frac=0.25):
    """Random canonical elements, salted with boundary cases."""
    specials = [0, 1, P - 1, P - 2, 0xFFFFFFFF, 0x100000000, P // 2, 2**63]
    out = []
    for _ in range(n):
        if rng.random() < special_frac:
            out.append(rng.choice(specials))
        else:
            out.append(rng.randrange(P))
    return out


def as_arr(xs):
    return jnp.asarray(np.array(xs, dtype=np.uint64))


N = 4096


def test_add_sub_neg_parity():
    a, b = rand_vec(N), rand_vec(N)
    aa, bb = as_arr(a), as_arr(b)
    assert list(np.asarray(gf.add(aa, bb))) == [gl.add(x, y) for x, y in zip(a, b)]
    assert list(np.asarray(gf.sub(aa, bb))) == [gl.sub(x, y) for x, y in zip(a, b)]
    assert list(np.asarray(gf.neg(aa))) == [gl.neg(x) for x in a]


def test_mul_parity():
    a, b = rand_vec(N), rand_vec(N)
    aa, bb = as_arr(a), as_arr(b)
    assert list(np.asarray(gf.mul(aa, bb))) == [gl.mul(x, y) for x, y in zip(a, b)]
    assert list(np.asarray(gf.sqr(aa))) == [gl.sqr(x) for x in a]


def test_mul_small_and_pow():
    a = rand_vec(256)
    aa = as_arr(a)
    for k in [0, 1, 2, 3, 7, 11, 255]:
        assert list(np.asarray(gf.mul_small(aa, k))) == [gl.mul(x, k) for x in a]
    for e in [0, 1, 2, 5, 97, P - 2]:
        assert list(np.asarray(gf.pow_const(aa, e))) == [gl.pow_(x, e) for x in a]


def test_inverse():
    a = [x if x != 0 else 1 for x in rand_vec(512)]
    aa = as_arr(a)
    got = np.asarray(gf.inv(aa))
    for x, y in zip(a, got):
        assert gl.mul(x, int(y)) == 1


def test_batch_inverse():
    a = [x if x != 0 else 1 for x in rand_vec(1024)]
    aa = as_arr(a)
    got = np.asarray(gf.batch_inverse(aa))
    for x, y in zip(a, got):
        assert gl.mul(x, int(y)) == 1
    # 2-D shape: batches along last axis
    m = as_arr(a).reshape(4, 256)
    got2 = np.asarray(gf.batch_inverse(m)).reshape(-1)
    assert list(got2) == list(got)


def test_two_adic_generator():
    # RADIX_2_SUBGROUP_GENERATOR has order exactly 2^32
    g = gl.RADIX_2_SUBGROUP_GENERATOR
    assert gl.exp_power_of_2(g, 32) == 1
    assert gl.exp_power_of_2(g, 31) == P - 1
    w = gl.omega(4)
    assert gl.pow_(w, 16) == 1 and gl.pow_(w, 8) != 1


def test_extension_axioms_host():
    for _ in range(200):
        a = (rng.randrange(P), rng.randrange(P))
        b = (rng.randrange(P), rng.randrange(P))
        c = (rng.randrange(P), rng.randrange(P))
        # distributivity
        lhs = ext.mul_s(a, ext.add_s(b, c))
        rhs = ext.add_s(ext.mul_s(a, b), ext.mul_s(a, c))
        assert lhs == rhs
        # inverse
        if a != (0, 0):
            assert ext.mul_s(a, ext.inv_s(a)) == (1, 0)


def test_extension_device_matches_host():
    n = 512
    a0, a1 = rand_vec(n), rand_vec(n)
    b0, b1 = rand_vec(n), rand_vec(n)
    aa = (as_arr(a0), as_arr(a1))
    bb = (as_arr(b0), as_arr(b1))
    got = ext.mul(aa, bb)
    want = [ext.mul_s((x0, x1), (y0, y1)) for x0, x1, y0, y1 in zip(a0, a1, b0, b1)]
    assert list(np.asarray(got[0])) == [w[0] for w in want]
    assert list(np.asarray(got[1])) == [w[1] for w in want]
    # device ext inverse
    nz = [(x if (x, y) != (0, 0) else 1, y) for x, y in zip(a0, a1)]
    aa_nz = (as_arr([v[0] for v in nz]), as_arr([v[1] for v in nz]))
    ii = ext.inv(aa_nz)
    for i in range(n):
        got_i = (int(np.asarray(ii[0])[i]), int(np.asarray(ii[1])[i]))
        assert ext.mul_s(nz[i], got_i) == (1, 0)


def test_to_field():
    arr = gf.to_field([0, 1, P, P + 5, -1])
    assert list(np.asarray(arr)) == [0, 1, 0, 5, P - 1]
