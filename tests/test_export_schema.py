"""Reference-schema export loop on OWN circuits.

prove -> export into the reference proof.json/vk.json serde schema ->
reload through the SAME loaders used on the golden artifacts
(compat.serde.load_vk/load_proof) -> import back -> FULL own verification
(transcript replay, Merkle paths, FRI fold simulation, and the quotient
identity at z via the in-repo gate config) passes; tampering anywhere in
the schema round-trip fails. Schema citations: reference proof.rs:121,
verifier.rs:31, setup.rs:1374.
"""

import json

import pytest

from boojum_tpu.compat.export import (
    export_proof,
    export_vk,
    import_proof,
)
from boojum_tpu.compat.serde import load_proof, load_vk
from boojum_tpu.field import gl
from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify


@pytest.fixture(scope="module")
def proven():
    from test_e2e import build_fibonacci_circuit

    cs, _ = build_fibonacci_circuit(steps=60)
    asm = cs.into_assembly()
    cfg = ProofConfig(fri_lde_factor=4, num_queries=10, fri_final_degree=8)
    setup = generate_setup(asm, cfg)
    proof = prove(asm, setup, cfg)
    assert verify(setup.vk, proof, asm.gates)
    return asm, setup, proof


def test_vk_export_parses_with_golden_loader(proven, tmp_path):
    asm, setup, _proof = proven
    vk_json = export_vk(setup.vk, asm.gates)
    path = tmp_path / "vk.json"
    path.write_text(json.dumps(vk_json))
    ref_vk = load_vk(str(path))
    assert ref_vk.domain_size == setup.vk.trace_len
    assert ref_vk.fri_lde_factor == setup.vk.fri_lde_factor
    assert ref_vk.cap_size == setup.vk.cap_size
    assert ref_vk.quotient_degree == setup.vk.effective_quotient_degree()
    assert ref_vk.setup_merkle_tree_cap == [
        tuple(int(x) for x in d) for d in setup.vk.setup_merkle_cap
    ]
    # the serde selector tree must reproduce the VK's per-gate paths
    for gid in range(len(asm.gates)):
        placed = ref_vk.selectors_placement.output_placement(gid)
        if asm.gates[gid].num_terms == 0 and placed is None:
            continue
        assert placed == [bool(b) for b in setup.vk.selector_paths[gid]], gid


def test_proof_export_roundtrip_full_identity(proven, tmp_path):
    asm, setup, proof = proven
    pj = export_proof(proof)
    path = tmp_path / "proof.json"
    path.write_text(json.dumps(pj))
    # parses with the golden-artifact loader
    ref_proof = load_proof(str(path))
    assert ref_proof.pow_challenge == proof.pow_challenge
    assert len(ref_proof.queries_per_fri_repetition) == len(proof.queries)
    # round-trip back into the framework: FULL verification incl. the
    # quotient identity at z (verifier.py checks it for own circuits)
    back = import_proof(json.loads(path.read_text()))
    # field-level identity (json.loads: to_json key order is insertion
    # order, and the importer rebuilds config in a different order)
    assert json.loads(back.to_json()) == json.loads(proof.to_json())
    assert verify(setup.vk, back, asm.gates)


def test_tampered_schema_roundtrip_rejected(proven, tmp_path):
    asm, setup, proof = proven
    for mutate in (
        lambda o: o["values_at_z"][3]["coeffs"].__setitem__(
            0, str((int(o["values_at_z"][3]["coeffs"][0]) + 1) % gl.P)
        ),
        lambda o: o["public_inputs"].__setitem__(
            0, str((int(o["public_inputs"][0]) + 1) % gl.P)
        ),
        lambda o: o["queries_per_fri_repetition"][0]["witness_query"][
            "leaf_elements"
        ].__setitem__(0, "7"),
    ):
        obj = json.loads(json.dumps(export_proof(proof)))
        mutate(obj)
        bad = import_proof(obj)
        assert not verify(setup.vk, bad, asm.gates)


def test_vk_export_general_lookup_mode(tmp_path):
    """General-purpose-columns VK export: TableIdAsConstant carries only
    {width, share_table_id} (reference cs/mod.rs:233) and the table-id
    column index is the marker gate's selector-path length."""
    import sys as _sys

    _sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from test_lookup_general import CONFIG as GL_CONFIG, build_circuit

    cs, _ = build_circuit(num_lookups=8)
    asm = cs.into_assembly()
    setup = generate_setup(asm, GL_CONFIG)
    vk_json = export_vk(setup.vk, asm.gates)
    lk = vk_json["fixed_parameters"]["lookup_parameters"]
    assert set(lk) == {"TableIdAsConstant"}
    assert set(lk["TableIdAsConstant"]) == {"width", "share_table_id"}
    mk_gid = next(
        i for i, g in enumerate(asm.gates)
        if getattr(g, "is_lookup_marker", False)
    )
    assert vk_json["fixed_parameters"]["table_ids_column_idxes"] == [
        len(setup.vk.selector_paths[mk_gid])
    ]
    assert (
        vk_json["fixed_parameters"]["extra_constant_polys_for_selectors"] == 0
    )
    path = tmp_path / "vk.json"
    path.write_text(json.dumps(vk_json))
    ref_vk = load_vk(str(path))
    assert ref_vk.lookup_parameters.is_lookup
    assert ref_vk.table_ids_column_idxes == [
        len(setup.vk.selector_paths[mk_gid])
    ]
