"""Proving-as-a-service (ISSUE 6).

The tentpole added `boojum_tpu/service/`: a shape-bucketed admission
queue with priority lanes and bounded-queue backpressure, a
device-resident cache manager with byte-capped LRU eviction, a
scheduler picking shard-parallel vs proof-parallel placement per
request, and a worker loop emitting per-request SLO records through the
flight recorder. These tests pin the acceptance criteria on the virtual
8-device CPU mesh (conftest forces xla_force_host_platform_device_count):

- a MIXED batch — two geometries, both placements, a priority-lane job —
  drained through the service produces proof bytes AND digest-checkpoint
  streams bit-identical to sequential direct `prove()` per request;
- cache-manager hit/eviction accounting fires (service.cache.* in the
  request lines, LRU eviction at the byte cap);
- backpressure: admission above the queue bound raises QueueFullError
  and counts service.queue.rejects;
- `prove_report.py --check` validates the per-request SLO records
  (rejecting records missing queue-latency/placement) and `--slo`
  summarizes p50/p95 queue latency + proofs/sec;
- the shape-bucket key is ONE shared helper: admission queue, precompile
  enumeration and compile-ledger tags can never disagree.
"""

import functools
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from boojum_tpu.utils import report

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def _build_fma(log_n: int, seed: int = 0):
    from boojum_tpu.cs.gates import FmaGate, PublicInputGate
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.types import CSGeometry

    geom = CSGeometry(8, 0, 6, 4)
    cs = ConstraintSystem(geom, 1 << log_n)
    a = cs.alloc_variable_with_value(1 + seed)
    b = cs.alloc_variable_with_value(2 + seed)
    per_row = FmaGate.instance().num_repetitions(geom)
    for _ in range(((1 << log_n) - 8) * per_row):
        a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
    PublicInputGate.place(cs, b)
    return cs


def _parts_a():
    """Geometry A: the shared 2^10 circuit + smallest-honest config of
    test_limb_sweep/test_mesh_parity, so its kernel shapes are already
    in the tier-1 persistent compile cache."""
    from test_limb_sweep import _small_prove_parts

    return _small_prove_parts()


@functools.lru_cache(maxsize=1)
def _parts_b():
    """Geometry B: same gate set at 2^11 — a DIFFERENT shape bucket."""
    from boojum_tpu.prover import ProofConfig, generate_setup

    config = ProofConfig(
        fri_lde_factor=2,
        merkle_tree_cap_size=4,
        num_queries=4,
        fri_final_degree=16,
    )
    asm = _build_fma(11).into_assembly()
    assert asm.trace_len == 1 << 11
    return asm, generate_setup(asm, config), config


def _checkpoint_stream(rep):
    return [
        (e["seq"], e["round"], e["label"], e["digest"])
        for e in rep["checkpoints"]
    ]


def _direct_recorded(parts):
    from boojum_tpu.prover import prove

    asm, setup, config = parts
    with report.flight_recording(label="direct") as rec:
        proof = prove(asm, setup, config)
    return proof, report.build_report(rec)


@functools.lru_cache(maxsize=1)
def _e2e_runs(tmp_dir=None):
    """The acceptance run: direct sequential proves of both geometries,
    then the SAME requests as one mixed service batch — two shape
    buckets, both placements (B's 2^11 trace is at the forced shard
    threshold, A stays proof-parallel), a priority-lane job, a repeated
    same-setup job (the cache-hit path)."""
    import tempfile

    from boojum_tpu.service import ProvingService, ServiceConfig

    direct_a = _direct_recorded(_parts_a())
    direct_b = _direct_recorded(_parts_b())

    rpt = tempfile.mktemp(suffix=".service.jsonl")
    # precompile="off": the tier-1 persistent cache already holds every
    # kernel these proves dispatch; the warm-variant seam has its own
    # stubbed test (test_variant_warmer_warms_dispatched_set)
    svc = ProvingService(
        ServiceConfig(
            precompile="off",
            report_path=rpt,
            shard_threshold_rows=1 << 11,
            cache_bytes=2 << 30,
        )
    )
    asm_a, setup_a, cfg_a = _parts_a()
    asm_b, setup_b, cfg_b = _parts_b()
    reqs = {
        # two same-bucket batch jobs (second is the device-cache HIT)...
        "a1": svc.submit(asm_a, setup_a, cfg_a, tenant="t0"),
        "a2": svc.submit(asm_a, setup_a, cfg_a, tenant="t1"),
        # ...a heavy job placed shard-parallel across the mesh...
        "b1": svc.submit(asm_b, setup_b, cfg_b, priority="bulk"),
        # ...and an interactive-lane job admitted LAST but drained FIRST
        "ai": svc.submit(asm_a, setup_a, cfg_a, priority="interactive"),
    }
    summary = svc.run_worker()
    lines = report.load_reports(rpt)
    return {
        "direct": {"a": direct_a, "b": direct_b},
        "svc": svc,
        "summary": summary,
        "requests": reqs,
        "report_path": rpt,
        "lines": lines,
    }


# ---------------------------------------------------------------------------
# Shared shape-bucket key
# ---------------------------------------------------------------------------


def test_shape_bucket_key_is_shared(monkeypatch):
    """Same circuit STRUCTURE with different witness values -> same key;
    different trace length -> different key; the compile ledger's
    precompile entries carry the exact key the admission queue buckets
    on. (The full lower-sweep of the enumeration is test_precompile's
    job — here it is stubbed to one tiny kernel so only the ledger
    tagging seam is under test.)"""
    import importlib

    import jax.numpy as jnp

    # boojum_tpu.prover re-exports the precompile FUNCTION under the
    # module's name — resolve the module itself
    pc = importlib.import_module("boojum_tpu.prover.precompile")
    from boojum_tpu.prover.shape_key import bucket_key, shape_bucket
    from boojum_tpu.utils.profiling import CompileLedger

    asm_a, _setup, cfg = _parts_a()
    asm_same_shape = _build_fma(10, seed=5).into_assembly()
    assert bucket_key(asm_same_shape, cfg) == bucket_key(asm_a, cfg)
    asm_b, _sb, cfg_b = _parts_b()
    assert bucket_key(asm_b, cfg_b) != bucket_key(asm_a, cfg)

    sb = shape_bucket(asm_a, cfg)
    assert sb.trace_len == 1 << 10 and sb.lde_factor == 2
    assert sb.B_wit > 0 and sb.B_setup > 0 and sb.S > 0 and sb.B_q > 0
    # identity: cached per (assembly, config-fields)
    assert shape_bucket(asm_a, cfg) is sb

    probe = pc.KernelSpec(
        "probe", jax.jit(lambda x: x + 1),
        (jax.ShapeDtypeStruct((4,), jnp.uint64),),
    )
    monkeypatch.setattr(
        pc, "enumerate_kernels", lambda *a, **k: [probe]
    )
    led = CompileLedger()
    pc.precompile(asm_a, cfg, ledger=led, lower_only=True)
    assert [e.get("shape") for e in led.entries] == [sb.key]
    assert led.summary()["shapes"] == [sb.key]


# ---------------------------------------------------------------------------
# Admission queue
# ---------------------------------------------------------------------------


class _FakeReq:
    def __init__(self, key, priority="batch"):
        self.bucket_key = key
        self.priority = priority
        self.admit_ts = None


def test_queue_priority_lanes_and_bucket_batching():
    from boojum_tpu.service import AdmissionQueue

    q = AdmissionQueue(capacity=16)
    b1, b2 = _FakeReq("shapeX"), _FakeReq("shapeY")
    b3, b4 = _FakeReq("shapeX"), _FakeReq("shapeX")
    i1 = _FakeReq("shapeY", priority="interactive")
    for r in (b1, b2, b3, i1, b4):
        q.submit(r)
    assert q.depth() == 5
    assert q.occupancy("shapeX") == 3
    assert q.bucket_depths() == {"shapeX": 3, "shapeY": 2}
    # interactive lane drains FIRST even though admitted fourth
    assert q.pop_batch() == [i1]
    # then the batch lane head's bucket gathers ALL its followers...
    assert q.pop_batch() == [b1, b3, b4]
    # ...limit caps a batch; FIFO otherwise
    q2 = AdmissionQueue(capacity=4)
    for r in (_FakeReq("z"), _FakeReq("z"), _FakeReq("z")):
        q2.submit(r)
    assert len(q2.pop_batch(limit=2)) == 2
    assert q.pop_batch() == [b2]
    assert q.pop_batch() == []
    with pytest.raises(ValueError, match="priority lane"):
        q.submit(_FakeReq("w", priority="urgent"))


def test_queue_backpressure_rejects_above_bound():
    from boojum_tpu.service import AdmissionQueue, QueueFullError
    from boojum_tpu.utils import metrics as _metrics

    q = AdmissionQueue(capacity=2)
    reg = _metrics.MetricsRegistry()
    prev = _metrics.install_registry(reg)
    try:
        q.submit(_FakeReq("s"))
        q.submit(_FakeReq("s"))
        with pytest.raises(QueueFullError, match="capacity"):
            q.submit(_FakeReq("s"))
        with pytest.raises(QueueFullError):
            q.submit(_FakeReq("t", priority="interactive"))
    finally:
        _metrics.install_registry(prev)
    assert q.rejects == 2
    assert q.depth() == 2
    assert reg.counters["service.queue.rejects"] == 2
    assert reg.gauges["service.queue.depth"] == 2
    # draining reopens admission
    assert len(q.pop_batch()) == 2
    q.submit(_FakeReq("s"))
    assert q.depth() == 1


# ---------------------------------------------------------------------------
# Scheduler
# ---------------------------------------------------------------------------


def test_scheduler_placement_decision():
    from jax.sharding import Mesh

    from boojum_tpu.service import (
        PROOF_PARALLEL,
        SHARD_PARALLEL,
        choose_placement,
    )

    mesh = Mesh(
        np.array(jax.devices()[:8]).reshape(2, 4), axis_names=("col", "row")
    )

    class B:
        trace_len = 1 << 10
        log_n = 10

    class Big:
        trace_len = 1 << 20
        log_n = 20

    # small trace, queued siblings -> proof-parallel, packable
    p = choose_placement(B, 3, mesh, max_inflight=4, threshold_rows=1 << 17)
    assert p.kind == PROOF_PARALLEL and p.mesh is None and p.pack == 3
    assert 0 < p.occupancy < 1
    # lone small trace -> still meshless (collectives cost > win)
    p = choose_placement(B, 1, mesh, threshold_rows=1 << 17)
    assert p.kind == PROOF_PARALLEL and p.pack == 1
    # big trace -> the whole mesh, regardless of occupancy
    p = choose_placement(Big, 5, mesh, threshold_rows=1 << 17)
    assert p.kind == SHARD_PARALLEL and p.mesh is mesh
    assert p.occupancy == 1.0
    # no mesh at all -> everything proof-parallel
    p = choose_placement(Big, 1, None, threshold_rows=1 << 17)
    assert p.kind == PROOF_PARALLEL
    # env-driven threshold (junk raises)
    os.environ["BOOJUM_TPU_SERVICE_SHARD_ROWS"] = "1024"
    try:
        p = choose_placement(B, 1, mesh)
        assert p.kind == SHARD_PARALLEL
    finally:
        del os.environ["BOOJUM_TPU_SERVICE_SHARD_ROWS"]


def test_variant_warmer_warms_dispatched_set(monkeypatch):
    """The scheduler warms EXACTLY the kernel-library variant the chosen
    placement dispatches — mesh_shape=None for proof-parallel, the mesh
    for shard-parallel — and only once per (bucket, placement)."""
    import importlib

    from jax.sharding import Mesh

    pc = importlib.import_module("boojum_tpu.prover.precompile")
    from boojum_tpu.service.scheduler import Placement, VariantWarmer

    calls = []
    monkeypatch.setattr(
        pc, "precompile",
        lambda asm, cfg, max_workers=8, ledger=None, lower_only=False,
        mesh_shape=None: calls.append((mesh_shape, lower_only)),
    )
    mesh = Mesh(
        np.array(jax.devices()[:8]).reshape(2, 4), axis_names=("col", "row")
    )
    asm, _setup, cfg = _parts_a()
    from boojum_tpu.prover.shape_key import shape_bucket

    sb = shape_bucket(asm, cfg)
    w = VariantWarmer(mode="lower")
    pp = Placement("proof_parallel", None, total_devices=8)
    sp = Placement("shard_parallel", mesh, total_devices=8)
    assert w.warm(sb, asm, cfg, pp) is True
    assert w.warm(sb, asm, cfg, pp) is False  # deduped
    assert w.warm(sb, asm, cfg, sp) is True   # other placement: new warm
    assert calls == [(None, True), (mesh, True)]
    assert VariantWarmer(mode="off").warm(sb, asm, cfg, pp) is False
    with pytest.raises(ValueError, match="precompile mode"):
        VariantWarmer(mode="eager")


# ---------------------------------------------------------------------------
# Cache manager
# ---------------------------------------------------------------------------


class _FakeSetup:
    def __init__(self, nbytes):
        self._dev_cache = {
            "sigma": np.zeros(nbytes // 8, dtype=np.uint64)
        }


def test_cache_manager_lru_eviction_at_byte_cap():
    from boojum_tpu.service import DeviceCacheManager
    from boojum_tpu.utils import metrics as _metrics

    reg = _metrics.MetricsRegistry()
    prev = _metrics.install_registry(reg)
    try:
        mgr = DeviceCacheManager(capacity_bytes=1 << 20)  # 1 MiB cap
        s1, s2, s3 = (_FakeSetup(1 << 19) for _ in range(3))  # 512 KiB each
        a = type("A", (), {})()
        assert mgr.pin("k1", a, s1) is False  # miss
        assert mgr.pin("k1", a, s1) is True   # hit
        mgr.after_request()
        assert mgr.pin("k2", a, s2) is False
        mgr.after_request()
        assert mgr.stats()["evictions"] == 0  # 1 MiB exactly: at cap
        assert mgr.pin("k3", a, s3) is False
        mgr.after_request()  # 1.5 MiB > cap: evict LRU (s1)
        st = mgr.stats()
        assert st["evictions"] == 1
        assert st["evicted_bytes"] >= 1 << 19
        assert not s1._dev_cache  # residency actually released
        assert s2._dev_cache and s3._dev_cache
        # re-pinning the evicted setup is a MISS again
        assert mgr.pin("k1", a, s1) is False
    finally:
        _metrics.install_registry(prev)
    assert reg.counters["service.cache.hits"] == 1
    assert reg.counters["service.cache.misses"] == 4
    assert reg.counters["service.cache.evictions"] == 1
    assert reg.gauges["service.cache.evicted_bytes"] >= 1 << 19
    assert "service.cache.pinned_bytes" in reg.gauges


# ---------------------------------------------------------------------------
# E2E: the mixed batch acceptance run
# ---------------------------------------------------------------------------


def test_e2e_mixed_batch_bit_parity():
    """Acceptance: per request, proof bytes AND digest-checkpoint
    streams are bit-identical to sequential direct prove(), across BOTH
    placements."""
    runs = _e2e_runs()
    pa, ra = runs["direct"]["a"]
    pb, rb = runs["direct"]["b"]
    reqs = runs["requests"]
    assert runs["summary"]["failed"] == 0
    for name in ("a1", "a2", "ai"):
        assert reqs[name].result().to_json() == pa.to_json(), name
    assert reqs["b1"].result().to_json() == pb.to_json()

    by_id = {
        ln["request"]["id"]: ln
        for ln in runs["lines"]
        if "request" in ln
    }
    base_a = _checkpoint_stream(ra)
    assert base_a
    for name in ("a1", "a2", "ai"):
        ln = by_id[reqs[name].id]
        assert _checkpoint_stream(ln) == base_a, name
        assert ln["request"]["placement"] == "proof_parallel"
    ln_b = by_id[reqs["b1"].id]
    assert _checkpoint_stream(ln_b) == _checkpoint_stream(rb)
    assert ln_b["request"]["placement"] == "shard_parallel"
    # the shard-parallel prove really ran the mesh path: explicit
    # collectives billed to ici.* in ITS request line only
    assert ln_b["metrics"]["counters"].get("ici.all_to_alls", 0) > 0
    assert by_id[reqs["a1"].id]["metrics"]["counters"].get(
        "ici.all_to_alls", 0
    ) == 0
    # placements recorded in the service summary too
    assert runs["summary"]["placements"]["proof_parallel"] == 3
    assert runs["summary"]["placements"]["shard_parallel"] == 1


def test_e2e_priority_lane_drains_first():
    """The interactive job was admitted LAST but must be SERVED first
    (strict-priority lanes) — visible in the report line order."""
    runs = _e2e_runs()
    served_order = [
        ln["request"]["id"] for ln in runs["lines"] if "request" in ln
    ]
    assert served_order[0] == runs["requests"]["ai"].id
    # its queue latency is recorded and sane
    ln = runs["lines"][0]
    assert ln["request"]["queue_latency_s"] >= 0
    assert ln["request"]["priority"] == "interactive"


def test_e2e_cache_hits_fire():
    """Same-setup re-submissions hit the device-resident cache; the hit
    is charged to the request line's service.cache.* counters."""
    runs = _e2e_runs()
    st = runs["svc"].cache.stats()
    assert st["hits"] >= 2  # a2 and ai reuse a1's pinned setup
    assert st["misses"] >= 2  # a1 and b1
    assert st["pinned_bytes"] > 0
    by_id = {
        ln["request"]["id"]: ln for ln in runs["lines"] if "request" in ln
    }
    reqs = runs["requests"]
    a2 = by_id[reqs["a2"].id]
    assert a2["request"]["cache_hit"] is True
    assert a2["metrics"]["counters"]["service.cache.hits"] == 1
    a1_first = by_id[runs["lines"][0]["request"]["id"]]
    assert a1_first["request"]["cache_hit"] is False
    assert a1_first["metrics"]["counters"]["service.cache.misses"] == 1


def test_e2e_backpressure_at_service_bound():
    """Admission above the service queue bound rejects with
    QueueFullError (the backpressure contract) without disturbing
    admitted work."""
    from boojum_tpu.service import (
        ProvingService,
        QueueFullError,
        ServiceConfig,
    )

    asm, setup, cfg = _parts_a()
    svc = ProvingService(
        ServiceConfig(precompile="off", queue_capacity=2, report_path=None)
    )
    r1 = svc.submit(asm, setup, cfg)
    r2 = svc.submit(asm, setup, cfg)
    with pytest.raises(QueueFullError):
        svc.submit(asm, setup, cfg)
    assert svc.queue.rejects == 1
    summary = svc.run_worker()
    assert summary["served"] == 2
    assert summary["queue"]["rejects"] == 1
    assert r1.result().to_json() == r2.result().to_json()


def test_e2e_report_check_and_slo():
    """The per-request SLO records pass the prove_report.py --check
    gate, mutilated records FAIL it, and --slo summarizes the batch."""
    runs = _e2e_runs()
    req_lines = [ln for ln in runs["lines"] if "request" in ln]
    assert len(req_lines) == 4
    for ln in req_lines:
        assert report.validate_report(ln) == [], ln["request"]["id"]
        r = ln["request"]
        assert r["prove_wall_s"] > 0
        assert r["proofs_per_sec"] > 0
        assert 0 < r["occupancy"] <= 1.0
        assert r["bucket"].startswith("n2^")

    import copy

    bad = copy.deepcopy(req_lines[0])
    del bad["request"]["queue_latency_s"]
    assert any(
        "queue_latency_s" in p for p in report.validate_report(bad)
    )
    bad2 = copy.deepcopy(req_lines[0])
    bad2["request"]["placement"] = "warp_speed"
    assert any("placement" in p for p in report.validate_report(bad2))
    bad3 = copy.deepcopy(req_lines[0])
    bad3["metrics"]["gauges"]["service.occupancy"] = -2.0
    assert any(
        "service.occupancy" in p for p in report.validate_report(bad3)
    )

    # the stdlib-only CLI agrees, end to end
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cli = os.path.join(root, "scripts", "prove_report.py")
    chk = subprocess.run(
        [sys.executable, cli, "--check", runs["report_path"]],
        capture_output=True, text=True, timeout=120,
    )
    assert chk.returncode == 0, chk.stdout + chk.stderr
    slo = subprocess.run(
        [sys.executable, cli, "--slo", runs["report_path"]],
        capture_output=True, text=True, timeout=120,
    )
    assert slo.returncode == 0, slo.stdout + slo.stderr
    assert "queue latency p50=" in slo.stdout
    assert "proofs/sec" in slo.stdout

    summary = report.slo_summary(runs["lines"])
    assert summary["requests"] == 4 and summary["served"] == 4
    assert summary["queue_latency_p50_s"] >= 0
    assert summary["queue_latency_p95_s"] >= summary["queue_latency_p50_s"]
    assert summary["prove_wall_p50_s"] > 0
    assert summary["proofs_per_sec"] > 0
    assert summary["placements"] == {
        "proof_parallel": 3, "shard_parallel": 1
    }
    assert summary["priorities"]["interactive"] == 1
    assert summary["cache_hit_rate"] == 0.5


@pytest.mark.slow
def test_packed_proof_parallel_parity_with_recording(monkeypatch):
    """Satellite (ISSUE 9): max_inflight=2 packs same-bucket 2^10
    requests one-per-chip WITH flight recording ON — the combination
    the process-global collectors used to forbid. Proof bytes AND
    digest-checkpoint streams stay bit-identical to the sequential
    direct prove, each packed request writes its own well-formed report
    line, and a canary counter incremented inside request A's scoped
    context never appears on request B's line. Slow-marked: per-device
    placement re-traces the kernel library for the second chip (minutes
    on XLA:CPU), which tier-1's budget cannot absorb."""
    import tempfile

    from boojum_tpu.service import ProvingService, ServiceConfig
    from boojum_tpu.utils import metrics as _metrics

    runs = _e2e_runs()
    pa, ra = runs["direct"]["a"]
    asm, setup, cfg = _parts_a()
    rpt = tempfile.mktemp(suffix=".packed.jsonl")
    svc = ProvingService(
        ServiceConfig(precompile="off", max_inflight=2, report_path=rpt)
    )
    # canary: each request counts a counter named after ITSELF inside
    # its (scoped) recording window — any cross-request registry bleed
    # shows up as the other request's canary on this line
    orig = ProvingService._run_request

    def with_canary(self, req, placement, packed=1, device=None):
        _metrics.count(f"canary.{req.id}")
        return orig(self, req, placement, packed=packed, device=device)

    monkeypatch.setattr(ProvingService, "_run_request", with_canary)
    rs = [svc.submit(asm, setup, cfg) for _ in range(2)]
    summary = svc.run_worker()
    assert summary["served"] == 2
    for r in rs:
        assert r.result().to_json() == pa.to_json()
        assert r.slo["packed"] == 2
    assert summary["placements"]["proof_parallel"] == 2

    lines = report.load_reports(rpt)
    req_lines = [ln for ln in lines if "request" in ln]
    assert len(req_lines) == 2
    base = _checkpoint_stream(ra)
    assert base
    by_id = {ln["request"]["id"]: ln for ln in req_lines}
    for r in rs:
        other = next(o for o in rs if o is not r)
        ln = by_id[r.id]
        # bit-identical transcript: the packed request recorded the
        # SAME checkpoint stream as the sequential direct prove
        assert _checkpoint_stream(ln) == base, r.id
        assert report.validate_report(ln) == [], r.id
        counters = ln["metrics"]["counters"]
        assert counters.get(f"canary.{r.id}") == 1
        assert f"canary.{other.id}" not in counters, "counter bled"
        # exactly ONE prove per line — not its neighbor's too
        assert counters.get("prover.proves") == 1
        assert ln["request"]["packed"] == 2

    # the stdlib CLI gate agrees the artifact is clean
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    chk = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "prove_report.py"),
         "--check", rpt],
        capture_output=True, text=True, timeout=120,
    )
    assert chk.returncode == 0, chk.stdout + chk.stderr


def test_service_metrics_plane_exports_prove_families():
    """/metrics in SERVICE mode must render the prove counter families
    even though each request records into a scoped registry that dies
    with its report line: start_telemetry adopts the process-global
    default slot with the service-lifetime accumulator, _serve_one
    folds each request's registry in, stop_telemetry releases."""
    from boojum_tpu.service import ProvingService, ServiceConfig
    from boojum_tpu.utils import metrics as _metrics

    svc = ProvingService(
        ServiceConfig(precompile="off", report_path=None)
    )
    prev = _metrics.install_registry(None)
    try:
        port = svc.start_telemetry(metrics_port=0)
        assert port
        assert _metrics.current_registry() is svc.prove_registry
        # stand-in for a request's scoped registry (torn down with the
        # line): the fold keeps its families for the plane's merge
        req_reg = _metrics.MetricsRegistry()
        req_reg.count("fri.folds", 4)
        req_reg.count("transfer.h2d_bytes", 123)
        req_reg.gauge_set("cost.total.efficiency", 0.5)
        svc.prove_registry.fold(req_reg)
        text = svc.metrics_plane.render_metrics()
        assert "boojum_tpu_fri_folds 4" in text
        assert "boojum_tpu_transfer_h2d_bytes 123" in text
        assert "boojum_tpu_cost_total_efficiency 0.5" in text
        # a second fold ADDS counters, last-writes gauges
        svc.prove_registry.fold(req_reg)
        text = svc.metrics_plane.render_metrics()
        assert "boojum_tpu_fri_folds 8" in text
        assert "boojum_tpu_cost_total_efficiency 0.5" in text
        svc.stop_telemetry()
        assert _metrics.current_registry() is None
    finally:
        svc.stop_telemetry()
        _metrics.install_registry(prev)
