"""Flight recorder tests (ISSUE 2): hierarchical spans, metrics registry,
Fiat–Shamir digest checkpoints, ProveReport artifact + CLI — all on the
CPU backend with a 2^10 circuit (tier-1 safe)."""

import io
import json
import logging
import os
import subprocess
import sys

import pytest

from boojum_tpu.utils import metrics, profiling, report, spans

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------


def test_span_tree_nesting_and_ordering():
    rec = spans.start_recording()
    try:
        with spans.span("outer"):
            with spans.span("child_a"):
                pass
            with spans.span("child_b"):
                with spans.span("grandchild"):
                    pass
        with spans.span("second_root"):
            pass
    finally:
        spans.stop_recording()
    tree = rec.tree()
    assert [sp["name"] for sp in tree] == ["outer", "second_root"]
    outer = tree[0]
    assert [c["name"] for c in outer["children"]] == ["child_a", "child_b"]
    assert outer["children"][1]["children"][0]["name"] == "grandchild"
    # ordering: siblings start in sequence, children inside the parent
    a, b = outer["children"]
    assert outer["start_s"] <= a["start_s"] <= b["start_s"]
    assert all(sp["wall_s"] >= 0 for sp, _ in _walk(tree))
    # parent covers its children
    assert outer["wall_s"] >= a["wall_s"] + b["wall_s"] - 1e-6


def _walk(tree):
    for sp in tree:
        yield sp, None
        yield from _walk(sp["children"])


def test_error_span_recorded_partially():
    rec = spans.start_recording()
    try:
        with pytest.raises(ValueError, match="boom"):
            with spans.span("outer"):
                with spans.span("failing"):
                    raise ValueError("boom")
    finally:
        spans.stop_recording()
    outer = rec.tree()[0]
    assert outer["error"].startswith("ValueError")
    failing = outer["children"][0]
    assert failing["name"] == "failing"
    assert failing["error"].startswith("ValueError: boom")
    assert failing["wall_s"] is not None and failing["wall_s"] >= 0


def test_stage_timer_records_sink_entry_on_exception():
    """Satellite: a raising stage must not lose its timing line or its
    sink entry (the old stage_timer body was not try/finally-wrapped)."""
    sink = profiling.collect_stages()
    try:
        with pytest.raises(RuntimeError):
            with profiling.stage_timer("exploding_stage"):
                raise RuntimeError("mid-stage failure")
    finally:
        profiling.stop_collecting_stages()
    assert len(sink) == 1
    name, dt = sink[0]
    assert name == "exploding_stage" and dt >= 0


def test_span_disabled_is_noop():
    assert spans.current_recorder() is None
    with spans.span("nothing") as sp:
        assert sp is None


# ---------------------------------------------------------------------------
# Logging (satellite: profiling.log -> logging.getLogger("boojum_tpu"))
# ---------------------------------------------------------------------------


def test_log_composes_with_user_handlers():
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger = logging.getLogger("boojum_tpu")
    h = _Capture()
    logger.addHandler(h)
    try:
        profiling.log("user handler sees this")
    finally:
        logger.removeHandler(h)
    assert "user handler sees this" in records


def test_stderr_handler_install_is_idempotent():
    """Satellite (ISSUE 9): toggling BOOJUM_TPU_PROFILE twice in one
    process (set_profiling on/off/on), or re-running the module-level
    install, must never stack a second stderr handler — each stage line
    would then print once per toggle. The handler is keyed by NAME, not
    class identity, so even a re-executed module (stale class object)
    cannot defeat the guard."""
    logger = logging.getLogger("boojum_tpu")

    def gated_handlers():
        return [
            h for h in logger.handlers
            if getattr(h, "name", None) == profiling._STDERR_HANDLER_NAME
        ]

    assert len(gated_handlers()) == 1  # module import installed exactly one
    try:
        for _ in range(3):  # "toggled twice" and then some
            profiling.set_profiling(True)
            profiling.set_profiling(False)
        profiling.ensure_stderr_handler()
        profiling.ensure_stderr_handler()
        assert len(gated_handlers()) == 1
        # the line really prints ONCE, not once per toggle
        err = io.StringIO()
        old = sys.stderr
        sys.stderr = err
        try:
            profiling.set_profiling(True)
            profiling.log("single emission")
        finally:
            sys.stderr = old
            profiling.set_profiling(None)
        assert err.getvalue().count("single emission") == 1
    finally:
        profiling.set_profiling(None)


def test_log_stderr_gated_on_profiling_env():
    err = io.StringIO()
    old = sys.stderr
    sys.stderr = err
    try:
        profiling.set_profiling(False)
        profiling.log("hidden line")
        profiling.set_profiling(True)
        profiling.log("visible line")
    finally:
        sys.stderr = old
        profiling.set_profiling(None)
    out = err.getvalue()
    assert "hidden line" not in out
    assert "[boojum_tpu] visible line" in out


# ---------------------------------------------------------------------------
# Contextvars scoping (ISSUE 9): the packed-service concurrency contract
# ---------------------------------------------------------------------------


def test_scoped_collectors_isolate_concurrent_contexts():
    """Two 'requests' recording concurrently on pool threads — each
    scoped flight recorder must collect ONLY its own spans, counters
    (canary check) and checkpoint stream, with zero cross-bleed. This is
    the unit-level contract behind packed proof-parallel recording."""
    import threading
    from concurrent.futures import ThreadPoolExecutor

    gate = threading.Barrier(2, timeout=30)

    def request(i):
        with report.flight_recording(label=f"req-{i}", scoped=True) as rec:
            gate.wait()  # both contexts genuinely record AT THE SAME TIME
            metrics.count(f"canary.{i}")
            metrics.count("shared.counter")
            report.checkpoint(0, "setup_cap", [i])
            report.checkpoint(1, "witness_cap", [i, i])
            with spans.span("service_request", request=f"req-{i}"):
                with spans.span("inner"):
                    gate.wait()
        return report.build_report(
            rec,
            extra={
                "request": {
                    "id": f"req-{i}", "bucket": "n2^10",
                    "placement": "proof_parallel",
                    "queue_latency_s": 0.0, "prove_wall_s": 0.01,
                }
            },
        )

    with ThreadPoolExecutor(max_workers=2) as pool:
        reps = list(pool.map(request, range(2)))

    for i, rep in enumerate(reps):
        other = 1 - i
        counters = rep["metrics"]["counters"]
        assert counters[f"canary.{i}"] == 1
        assert f"canary.{other}" not in counters, "counter bled"
        assert counters["shared.counter"] == 1, "shared counter double-counted"
        digests = [e["digest"] for e in rep["checkpoints"]]
        assert len(digests) == 2
        assert digests != [
            e["digest"] for e in reps[other]["checkpoints"]
        ], "checkpoint stream bled"
        names = [sp["name"] for sp in rep["spans"]]
        assert names == ["service_request"], names
        assert rep["spans"][0]["attrs"]["request"] == f"req-{i}"
        # --check level: the line is well-formed and single-request
        assert report.validate_report(rep) == []


def test_scoped_collectors_override_global_default_and_restore():
    """The process-global default context (bench/CLI posture) keeps
    working: a scoped context overrides it locally, and recording falls
    back to the global collectors the moment the scope exits."""
    rec_global = spans.start_recording()
    reg_global = metrics.start_metrics()
    log_global = report.CheckpointLog()
    prev_log = report.install_checkpoint_log(log_global)
    try:
        with spans.span("before_scope"):
            pass
        metrics.count("global.counter")
        report.checkpoint(0, "setup_cap", [1])
        with report.flight_recording(label="scoped", scoped=True) as rec:
            with spans.span("scoped_span"):
                pass
            metrics.count("scoped.counter")
            report.checkpoint(0, "setup_cap", [2])
        with spans.span("after_scope"):
            pass
        metrics.count("global.counter")
    finally:
        report.install_checkpoint_log(prev_log)
        metrics.stop_metrics()
        spans.stop_recording()
    assert [sp["name"] for sp in rec_global.tree()] == [
        "before_scope", "after_scope"
    ]
    assert reg_global.counters == {"global.counter": 2}
    assert len(log_global.entries) == 1
    assert [sp["name"] for sp in rec.spans.tree()] == ["scoped_span"]
    assert rec.metrics.counters == {"scoped.counter": 1}
    assert len(rec.checkpoints.entries) == 1
    # and a thread spawned OUTSIDE any scope sees the global default
    # (threads start with an empty context -> fallback)
    import threading

    seen = {}

    def probe():
        seen["rec"] = spans.current_recorder()

    rec2 = spans.start_recording()
    try:
        t = threading.Thread(target=probe)
        t.start()
        t.join()
    finally:
        spans.stop_recording()
    assert seen["rec"] is rec2


def test_validate_report_rejects_mixed_request_ids():
    """--check satellite (ISSUE 9): one line carrying spans of TWO
    request ids means scoped collectors bled across packed requests —
    the exact corruption the contextvar scoping prevents — and must
    fail the gate."""
    base = {
        "kind": report.REPORT_KIND,
        "schema": report.REPORT_SCHEMA,
        "wall_s": 0.5,
        "spans": [
            {"name": "service_request", "start_s": 0.0, "wall_s": 0.1,
             "span_id": "11" * 8, "children": [],
             "attrs": {"request": "req-1"}},
        ],
        "metrics": {"counters": {}},
        "checkpoints": [],
        "request": {
            "id": "req-1", "bucket": "n2^10", "placement": "proof_parallel",
            "queue_latency_s": 0.0, "prove_wall_s": 0.1,
        },
    }
    assert report.validate_report(base) == []
    bad = dict(base)
    bad["spans"] = base["spans"] + [
        {"name": "service_request", "start_s": 0.2, "wall_s": 0.1,
         "span_id": "22" * 8, "children": [],
         "attrs": {"request": "req-2"}},
    ]
    probs = report.validate_report(bad)
    assert any("mixes request ids" in p for p in probs), probs


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------


def test_metrics_registry_counters_and_gauges():
    # disabled: module-level hooks are no-ops
    assert metrics.current_registry() is None
    metrics.count("never.recorded", 5)

    reg = metrics.start_metrics()
    try:
        metrics.count("ntt.calls")
        metrics.count("ntt.calls", 2)
        metrics.count_bytes_h2d(1024)
        metrics.gauge_max("mem.peak", 10)
        metrics.gauge_max("mem.peak", 7)  # lower: must not regress the max
        metrics.stage_boundary("round1")
    finally:
        metrics.stop_metrics()
    d = reg.to_dict()
    assert d["counters"]["ntt.calls"] == 3
    assert d["counters"]["transfer.h2d_bytes"] == 1024
    assert d["counters"]["transfer.h2d_ops"] == 1
    assert d["gauges"]["mem.peak"] == 10
    assert d["boundaries"][0]["label"] == "round1"
    assert "live_arrays" in d["boundaries"][0]
    assert metrics.count("after.stop") is None  # no raise after stop


# ---------------------------------------------------------------------------
# Checkpoint digests
# ---------------------------------------------------------------------------


def test_digest_of_nested_values_stable():
    a = report.digest_of([(1, 2), [3, [4]]])
    b = report.digest_of([1, 2, 3, 4])
    assert a == b  # flattening is structural, digest is over the sequence
    assert a != report.digest_of([1, 2, 3, 5])
    assert len(a) == 64


# ---------------------------------------------------------------------------
# End-to-end: recorded 2^10 proves
# ---------------------------------------------------------------------------


import functools


@functools.lru_cache(maxsize=1)
def _small_prove_parts():
    """A genuine 2^10-row trace (the acceptance geometry), with the same
    circuit + smallest-honest config as test_precompile's 2^10 e2e so the
    kernel shapes are already in the tier-1 persistent compile cache."""
    from boojum_tpu.cs.gates import FmaGate, PublicInputGate
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.types import CSGeometry
    from boojum_tpu.prover import ProofConfig, generate_setup

    geom = CSGeometry(8, 0, 6, 4)
    cs = ConstraintSystem(geom, 1 << 10)
    a = cs.alloc_variable_with_value(1)
    b = cs.alloc_variable_with_value(2)
    per_row = FmaGate.instance().num_repetitions(geom)
    for _ in range(((1 << 10) - 8) * per_row):
        a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
    PublicInputGate.place(cs, b)
    asm = cs.into_assembly()
    assert asm.trace_len == 1 << 10
    config = ProofConfig(
        fri_lde_factor=2,
        merkle_tree_cap_size=4,
        num_queries=4,
        fri_final_degree=16,
    )
    setup = generate_setup(asm, config)
    return asm, setup, config


def _recorded_prove(asm, setup, config, label):
    from boojum_tpu.prover import prove

    with report.flight_recording(label=label) as rec:
        proof = prove(asm, setup, config)
    return proof, report.build_report(rec)


def test_checkpoints_identical_across_reruns_and_diverge_on_flip():
    asm, setup, config = _small_prove_parts()
    _p1, rep1 = _recorded_prove(asm, setup, config, "run1")
    _p2, rep2 = _recorded_prove(asm, setup, config, "run2")

    assert report.validate_report(rep1) == []
    # every Fiat–Shamir round is checkpointed
    rounds = {e["round"] for e in rep1["checkpoints"]}
    assert rounds == {0, 1, 2, 3, 4, 5}
    labels = [e["label"] for e in rep1["checkpoints"]]
    for want in (
        "setup_cap", "witness_cap", "challenges", "stage2_cap", "alpha",
        "quotient_cap", "z", "evaluations", "deep_challenge",
        "fri_cap_0", "fri_challenge_0", "fri_final_monomials",
        "query_indices",
    ):
        assert want in labels, want

    d = report.diff_reports(rep1, rep2)
    assert d["first_checkpoint_divergence"] is None
    assert d["num_checkpoints"][0] == d["num_checkpoints"][1] > 0

    # flip one witness word: the diff must name round 1's witness commit
    # as the first diverging stage
    import numpy as np

    from boojum_tpu.field import gl

    wv = list(asm.witness_vec())
    placed = np.asarray(asm.copy_placement)
    place = int(placed[placed >= 0].min())  # a place wired into copy cols
    wv[place] = (int(wv[place]) + 1) % gl.P
    asm_flipped = asm.with_external_witness(wv)
    _p3, rep3 = _recorded_prove(asm_flipped, setup, config, "flipped")
    d2 = report.diff_reports(rep1, rep3)
    fd = d2["first_checkpoint_divergence"]
    assert fd is not None
    assert fd["label"] == "witness_cap" and fd["round"] == 1
    assert fd["a_digest"] != fd["b_digest"]


def test_report_env_emission_schema_and_cli(tmp_path, monkeypatch):
    """BOOJUM_TPU_REPORT=<path> makes a plain prove() emit a ProveReport
    line; the artifact passes --check, covers >= 90% of the prove wall in
    spans, and self-diffs clean (the post-bench smoke gate)."""
    asm, setup, config = _small_prove_parts()
    path = str(tmp_path / "prove_report.jsonl")
    monkeypatch.setenv("BOOJUM_TPU_REPORT", path)
    from boojum_tpu.prover import prove, verify

    proof = prove(asm, setup, config)
    assert verify(setup.vk, proof, asm.gates)
    monkeypatch.delenv("BOOJUM_TPU_REPORT")

    reports = report.load_reports(path)
    assert len(reports) == 1
    rep = reports[0]
    assert rep["kind"] == report.REPORT_KIND
    assert rep["schema"] == report.REPORT_SCHEMA
    assert report.validate_report(rep) == []
    assert report.span_coverage(rep) >= 0.90
    assert {e["round"] for e in rep["checkpoints"]} == {0, 1, 2, 3, 4, 5}
    counters = rep["metrics"]["counters"]
    assert counters.get("prover.proves") == 1
    assert counters.get("merkle.tree_builds", 0) >= 3
    assert counters.get("transfer.d2h_bytes", 0) > 0

    # CLI: render + check + self-diff, in-process (no jax import needed)
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import prove_report as cli
    finally:
        sys.path.pop(0)
    assert cli.main([path]) == 0
    assert cli.main(["--check", path]) == 0
    assert cli.main(["--diff", path, path]) == 0


def test_prove_report_cli_subprocess_is_light():
    """The CLI must work standalone (no boojum_tpu/jax import): --check an
    artifact written by hand."""
    rep = {
        "kind": report.REPORT_KIND,
        "schema": report.REPORT_SCHEMA,
        "label": "hand",
        "wall_s": 1.0,
        "spans": [
            {
                "name": "prove",
                "start_s": 0.0,
                "wall_s": 1.0,
                "span_id": "aa" * 8,
                "trace_id": "ab" * 16,
                "children": [
                    {
                        "name": "round1",
                        "start_s": 0.0,
                        "wall_s": 0.95,
                        "span_id": "bb" * 8,
                        "parent_span_id": "aa" * 8,
                        "children": [],
                    }
                ],
            }
        ],
        "metrics": {"counters": {}, "gauges": {}, "boundaries": []},
        "checkpoints": [
            {
                "seq": 0,
                "round": 0,
                "label": "setup_cap",
                "digest": "0" * 64,
            },
            {
                "seq": 1,
                "round": 1,
                "label": "witness_cap",
                "digest": "1" * 64,
            },
        ],
    }
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "r.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps(rep) + "\n")
        env = {
            k: v for k, v in os.environ.items() if k != "PYTHONSTARTUP"
        }
        out = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO_ROOT, "scripts", "prove_report.py"),
                "--check",
                path,
            ],
            capture_output=True,
            text=True,
            env=env,
            timeout=60,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "ok" in out.stdout

        # monotonicity violations must fail the gate
        bad = dict(rep)
        bad["checkpoints"] = [
            dict(rep["checkpoints"][1], seq=0, round=1),
            dict(rep["checkpoints"][0], seq=1, round=0),
        ]
        with open(path, "w") as f:
            f.write(json.dumps(bad) + "\n")
        out = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO_ROOT, "scripts", "prove_report.py"),
                "--check",
                path,
            ],
            capture_output=True,
            text=True,
            env=env,
            timeout=60,
        )
        assert out.returncode == 1
        assert "round" in out.stdout


def test_validate_report_flags_malformed():
    assert report.validate_report({}) != []
    ok = {
        "kind": report.REPORT_KIND,
        "schema": report.REPORT_SCHEMA,
        "wall_s": 0.5,
        "spans": [],
        "metrics": {"counters": {}},
        "checkpoints": [],
    }
    assert report.validate_report(ok) == []
    bad_digest = dict(
        ok,
        checkpoints=[
            {"seq": 0, "round": 0, "label": "x", "digest": "nothex"}
        ],
    )
    assert any("digest" in p for p in report.validate_report(bad_digest))
