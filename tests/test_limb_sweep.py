"""Limb-domain quotient sweep + FRI fold (ISSUE 4).

The tentpole moved the quotient-stage cores and the FRI fold off emulated
uint64 onto fused u32-limb Pallas kernels (`prover/pallas_sweep.py`, algebra
in `field/limb_ops.py`). These tests pin, on the CPU backend (kernels in
interpret mode):

- u64<->limb parity of every limb op `field/limb_ops.py` adds, over
  randomized inputs INCLUDING boundary values near p and non-canonical
  2^64-1 words (base ops mirror the u64 algorithms bit-for-bit even on
  non-canonical inputs; ext ops are canonical-domain);
- per-kernel parity of the standalone sweep wrappers (gate terms, copy
  permutation, both lookup modes, FRI fold) against the u64 stage cores,
  across tiled and non-tiled domain sizes;
- the 2^10 end-to-end acceptance: proof bytes AND the flight-recorder
  checkpoint stream are bit-identical under BOOJUM_TPU_LIMB_SWEEP=1 vs =0,
  and the metrics counters prove the limb kernels actually dispatched.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from boojum_tpu.field import extension as ext_f
from boojum_tpu.field import gl
from boojum_tpu.field import goldilocks as gf
from boojum_tpu.field import limb_ops as lop
from boojum_tpu.field import limbs
from boojum_tpu.utils import report

# values that stress every carry/borrow/canonicalization branch: around 0,
# around p, around the 2^32 limb seam, and the non-canonical top band
BOUNDARY = np.array(
    [
        0, 1, 2, 7,
        0xFFFFFFFF, 0x100000000, 0x100000001,
        gl.P - 2, gl.P - 1, gl.P, gl.P + 1,
        0xFFFFFFFF00000000, 2**64 - 2, 2**64 - 1,
    ],
    dtype=np.uint64,
)


def _full_range(rng, size):
    """Random u64 (incl. non-canonical >= p) with the boundary set mixed in."""
    x = rng.integers(0, 2**64, size=size, dtype=np.uint64)
    take = min(len(BOUNDARY), size)
    x[:take] = BOUNDARY[:take]
    return jnp.asarray(rng.permutation(x))


def _canonical(rng, size):
    x = rng.integers(0, gl.P, size=size, dtype=np.uint64)
    canon_boundary = BOUNDARY[BOUNDARY < gl.P]
    take = min(len(canon_boundary), size)
    x[:take] = canon_boundary[:take]
    return jnp.asarray(rng.permutation(x))


def _j(pair):
    return np.asarray(limbs.join(pair))


def _s(x):
    return limbs.split(x)


# ---------------------------------------------------------------------------
# Property parity: base-field limb ops (non-canonical inputs included)
# ---------------------------------------------------------------------------


def test_base_op_parity_full_range():
    """limbs mirrors goldilocks op-for-op, so parity holds BITWISE even on
    non-canonical inputs (both emulations walk the same wrap/borrow
    fixups)."""
    rng = np.random.default_rng(1)
    x = _full_range(rng, 257)
    y = _full_range(rng, 257)
    for name, lfn, ufn in [
        ("add", limbs.add, gf.add),
        ("sub", limbs.sub, gf.sub),
        ("mul", limbs.mul, gf.mul),
    ]:
        np.testing.assert_array_equal(
            _j(lfn(_s(x), _s(y))), np.asarray(ufn(x, y)), err_msg=name
        )
    for name, lfn, ufn in [
        ("neg", limbs.neg, gf.neg),
        ("double", limbs.double, gf.double),
        ("sqr", limbs.sqr, gf.sqr),
    ]:
        np.testing.assert_array_equal(
            _j(lfn(_s(x))), np.asarray(ufn(x)), err_msg=name
        )


def test_mul_small_and_powers_parity():
    rng = np.random.default_rng(2)
    x = _full_range(rng, 129)
    for k in (0, 1, 2, 3, 7, 12, 255):
        np.testing.assert_array_equal(
            _j(lop.mul_small(_s(x), k)),
            np.asarray(gf.mul_small(x, k)),
            err_msg=f"mul_small k={k}",
        )
    xc = _canonical(rng, 65)
    pows = lop.powers(_s(xc), 6)
    acc = jnp.ones_like(xc)
    for j, p in enumerate(pows):
        np.testing.assert_array_equal(_j(p), np.asarray(acc), err_msg=f"p^{j}")
        acc = gf.mul(acc, xc)


def test_horner_parity():
    rng = np.random.default_rng(3)
    x = _canonical(rng, 130)
    coeffs = [_canonical(rng, 130) for _ in range(5)]
    got = _j(lop.horner([_s(c) for c in coeffs], _s(x)))
    ref = jnp.zeros_like(x)
    for c in reversed(coeffs):
        ref = gf.add(gf.mul(ref, x), c)
    np.testing.assert_array_equal(got, np.asarray(ref))


def test_broadcast_helpers():
    rng = np.random.default_rng(4)
    x = _s(_canonical(rng, 33))
    np.testing.assert_array_equal(_j(lop.zeros_like(x)), np.zeros(33))
    np.testing.assert_array_equal(_j(lop.ones_like(x)), np.ones(33))
    v = gl.P - 5
    np.testing.assert_array_equal(_j(lop.full_like(x, v)), np.full(33, v))
    # const_ext bakes reduced numpy scalars
    c = lop.const_ext(gl.P + 3, 2**64 - 1)
    assert int(limbs.join((jnp.uint32(c[0][0]), jnp.uint32(c[0][1])))) == 3
    assert (
        int(limbs.join((jnp.uint32(c[1][0]), jnp.uint32(c[1][1]))))
        == (2**64 - 1) % gl.P
    )


# ---------------------------------------------------------------------------
# Property parity: GF(p^2) limb ops (canonical domain)
# ---------------------------------------------------------------------------


def _rand_ext(rng, size):
    return (_canonical(rng, size), _canonical(rng, size))


def _sx(e):
    return lop.ext_split(e)


def _jx(e):
    c0, c1 = lop.ext_join(e)
    return np.asarray(c0), np.asarray(c1)


def _assert_ext_equal(got, ref, msg=""):
    g0, g1 = _jx(got) if isinstance(got[0], tuple) else (
        np.asarray(got[0]), np.asarray(got[1])
    )
    np.testing.assert_array_equal(g0, np.asarray(ref[0]), err_msg=msg)
    np.testing.assert_array_equal(g1, np.asarray(ref[1]), err_msg=msg)


def test_ext_op_parity():
    rng = np.random.default_rng(5)
    a = _rand_ext(rng, 131)
    b = _rand_ext(rng, 131)
    base = _canonical(rng, 131)
    _assert_ext_equal(limbs.ext_add(_sx(a), _sx(b)), ext_f.add(a, b), "add")
    _assert_ext_equal(limbs.ext_sub(_sx(a), _sx(b)), ext_f.sub(a, b), "sub")
    _assert_ext_equal(limbs.ext_mul(_sx(a), _sx(b)), ext_f.mul(a, b), "mul")
    _assert_ext_equal(lop.ext_neg(_sx(a)), ext_f.neg(a), "neg")
    _assert_ext_equal(lop.ext_sqr(_sx(a)), ext_f.sqr(a), "sqr")
    _assert_ext_equal(
        lop.ext_mul_by_base(_sx(a), _s(base)),
        ext_f.mul_by_base(a, base),
        "mul_by_base",
    )


def test_ext_powers_and_horner_parity():
    rng = np.random.default_rng(6)
    g = _rand_ext(rng, 1)
    pows = lop.ext_powers(_sx(g), 5)
    acc = (jnp.ones_like(g[0]), jnp.zeros_like(g[1]))
    for j, p in enumerate(pows):
        _assert_ext_equal(p, acc, f"g^{j}")
        acc = ext_f.mul(acc, g)
    x = _rand_ext(rng, 67)
    coeffs = [_rand_ext(rng, 67) for _ in range(4)]
    got = lop.ext_horner([_sx(c) for c in coeffs], _sx(x))
    ref = ext_f.zeros(x[0].shape)
    for c in reversed(coeffs):
        ref = ext_f.add(ext_f.mul(ref, x), c)
    _assert_ext_equal(got, ref, "ext_horner")


def test_accumulate_parity():
    from boojum_tpu.prover.stages import accumulate_ext, accumulate_ext_ext

    rng = np.random.default_rng(7)
    term_b = _canonical(rng, 68)
    term_e = _rand_ext(rng, 68)
    ch = _rand_ext(rng, 1)
    acc0 = _rand_ext(rng, 68)
    # base-term accumulate, from None and from a live accumulator
    _assert_ext_equal(
        lop.accumulate(None, _s(term_b), _sx(ch)),
        accumulate_ext(None, term_b, ch),
        "accumulate None",
    )
    _assert_ext_equal(
        lop.accumulate(_sx(acc0), _s(term_b), _sx(ch)),
        accumulate_ext(acc0, term_b, ch),
        "accumulate",
    )
    _assert_ext_equal(
        lop.ext_accumulate(_sx(acc0), _sx(term_e), _sx(ch)),
        accumulate_ext_ext(acc0, term_e, ch),
        "ext_accumulate",
    )


def test_aggregate_columns_parity():
    from boojum_tpu.prover.stages import (
        _ext_powers_traced,
        aggregate_lookup_columns,
    )

    rng = np.random.default_rng(8)
    cols = [_canonical(rng, 69) for _ in range(3)]
    tid = _canonical(rng, 69)
    g = (jnp.uint64(11), jnp.uint64(13))
    beta = (jnp.uint64(17), jnp.uint64(19))
    gpow_u64 = _ext_powers_traced(g, 4)
    ref = aggregate_lookup_columns(cols, tid, gpow_u64, beta)
    got = lop.aggregate_columns(
        [_s(c) for c in cols],
        _s(tid),
        [_sx(p) for p in gpow_u64],
        _sx((beta[0], beta[1])),
    )
    _assert_ext_equal(got, ref, "aggregate_columns")
    # table_id_col=None branch
    ref2 = aggregate_lookup_columns(cols, None, gpow_u64, beta)
    got2 = lop.aggregate_columns(
        [_s(c) for c in cols], None, [_sx(p) for p in gpow_u64], _sx(beta)
    )
    _assert_ext_equal(got2, ref2, "aggregate_columns no-tid")


# ---------------------------------------------------------------------------
# Per-kernel parity: standalone sweep wrappers vs the u64 stage cores
# ---------------------------------------------------------------------------


def _rnd(rng, *s):
    return jnp.asarray(rng.integers(0, gl.P, s, dtype=np.uint64))


# 256 exercises the tiled pallas path (R=2 sublane rows); 96 the
# non-tiled plain-XLA fallback of the same cores
@pytest.mark.parametrize("n", [256, 96])
def test_cp_quotient_kernel_parity(n):
    from boojum_tpu.prover import pallas_sweep as ps
    from boojum_tpu.prover.stages import _cp_quotient_core, chunk_columns

    rng = np.random.default_rng(10)
    C = 7
    chunks = tuple(tuple(c) for c in chunk_columns(C, 4))
    z = (_rnd(rng, n), _rnd(rng, n))
    zs = (_rnd(rng, n), _rnd(rng, n))
    partials = [(_rnd(rng, n), _rnd(rng, n)) for _ in range(len(chunks) - 1)]
    copy, sigma = _rnd(rng, C, n), _rnd(rng, C, n)
    xs, l0 = _rnd(rng, n), _rnd(rng, n)
    b = (jnp.uint64(3), jnp.uint64(5))
    g = (jnp.uint64(7), jnp.uint64(11))
    a0, a1 = _rnd(rng, 1 + len(chunks)), _rnd(rng, 1 + len(chunks))
    ks = tuple(int(x) for x in rng.integers(1, gl.P, C, dtype=np.uint64))
    ref = _cp_quotient_core(
        z, zs, partials, copy, sigma, xs, l0, b, g, a0, a1, chunks, ks
    )
    # jitted like the prover dispatches it (eager interpret-mode pallas
    # pays per-op dispatch; the compiled form also persists in the tier-1
    # compile cache)
    got = jax.jit(lambda *a: ps.cp_quotient(*a, chunks, ks))(
        z, zs, partials, copy, sigma, xs, l0, b, g, a0, a1
    )
    _assert_ext_equal(got, ref, f"cp n={n}")


@pytest.mark.parametrize("general", [False, True])
def test_lookup_quotient_kernel_parity(general):
    from boojum_tpu.prover import pallas_sweep as ps
    from boojum_tpu.prover.stages import (
        _lookup_quotient_core,
        _lookup_quotient_core_general,
    )

    rng = np.random.default_rng(11)
    n, R, w = 256, 3, 4
    a_ldes = [(_rnd(rng, n), _rnd(rng, n)) for _ in range(R)]
    b_lde = (_rnd(rng, n), _rnd(rng, n))
    cols, tid = _rnd(rng, R * w, n), _rnd(rng, n)
    tbl, mult = _rnd(rng, w + 1, n), _rnd(rng, n)
    b = (jnp.uint64(3), jnp.uint64(5))
    g = (jnp.uint64(7), jnp.uint64(11))
    a0, a1 = _rnd(rng, R + 1), _rnd(rng, R + 1)
    if general:
        sel = _rnd(rng, n)
        ref = _lookup_quotient_core_general(
            a_ldes, b_lde, cols, tid, tbl, mult, sel, b, g, a0, a1, R, w
        )
        got = jax.jit(lambda *a: ps.lookup_quotient_general(*a, R, w))(
            a_ldes, b_lde, cols, tid, tbl, mult, sel, b, g, a0, a1
        )
    else:
        ref = _lookup_quotient_core(
            a_ldes, b_lde, cols, tid, tbl, mult, b, g, a0, a1, R, w
        )
        got = jax.jit(lambda *a: ps.lookup_quotient(*a, R, w))(
            a_ldes, b_lde, cols, tid, tbl, mult, b, g, a0, a1
        )
    _assert_ext_equal(got, ref, f"lookup general={general}")


@pytest.mark.parametrize("scan_threshold", [None, 1])
def test_gate_terms_kernel_parity(scan_threshold, monkeypatch):
    """Direct-trace gates AND the packed-program SMEM scan replay
    (threshold 1 forces even the 3-op FMA program through _scan_replay)."""
    from boojum_tpu.cs.gate_capture import _PACKED_CACHE
    from boojum_tpu.cs.gates import FmaGate
    from boojum_tpu.cs.types import CSGeometry
    from boojum_tpu.prover import pallas_sweep as ps
    from boojum_tpu.prover.stages import _build_gate_sweep

    if scan_threshold is not None:
        monkeypatch.setenv("BOOJUM_TPU_SCAN_GATE_THRESHOLD", str(scan_threshold))
    saved = dict(_PACKED_CACHE)
    try:
        geom = CSGeometry(8, 0, 6, 4)
        gates = (FmaGate.instance(),)
        paths = ((),)
        rng = np.random.default_rng(12)
        n = 256
        copy, const = _rnd(rng, 8, n), _rnd(rng, 6, n)
        reps = FmaGate.instance().num_repetitions(geom)
        a0, a1 = _rnd(rng, reps), _rnd(rng, reps)
        ref = _build_gate_sweep(gates, paths, geom)(copy, None, const, a0, a1)
        limb_fn = ps.gate_terms_fn(gates, paths, geom)
        got = jax.jit(lambda c, k, x, y: limb_fn(c, None, k, x, y))(
            copy, const, a0, a1
        )
        _assert_ext_equal(got, ref, f"gate threshold={scan_threshold}")
    finally:
        _PACKED_CACHE.clear()
        _PACKED_CACHE.update(saved)


@pytest.mark.parametrize("m", [512, 64])
def test_fri_fold_kernel_parity(m):
    from boojum_tpu.prover import pallas_sweep as ps
    from boojum_tpu.prover.fri import _fold_once_jit
    from boojum_tpu.prover.stages import ext_scalar

    rng = np.random.default_rng(13)
    vals = (_rnd(rng, m), _rnd(rng, m))
    invx = _rnd(rng, m // 2)
    ch = ext_scalar(
        tuple(int(v) for v in rng.integers(0, gl.P, 2, dtype=np.uint64))
    )
    ref = _fold_once_jit(vals, ch, invx)
    got = jax.jit(ps.fri_fold)(vals, ch, invx)
    _assert_ext_equal(got, ref, f"fold m={m}")


def test_limb_sweep_enabled_dispatch(monkeypatch):
    """On a non-TPU backend the limb sweep is opt-in (=1, interpret mode);
    =0 always restores the u64 path; unset keeps the CPU default off."""
    from boojum_tpu.prover import pallas_sweep as ps

    monkeypatch.delenv("BOOJUM_TPU_LIMB_SWEEP", raising=False)
    on_tpu = jax.default_backend() == "tpu"
    assert ps.limb_sweep_enabled() is on_tpu
    for v in ("1", "true", "on", "yes"):
        monkeypatch.setenv("BOOJUM_TPU_LIMB_SWEEP", v)
        assert ps.limb_sweep_enabled() is True
    for v in ("0", "false", "off", "no"):
        monkeypatch.setenv("BOOJUM_TPU_LIMB_SWEEP", v)
        assert ps.limb_sweep_enabled() is False
    monkeypatch.setenv("BOOJUM_TPU_LIMB_SWEEP", "maybe")
    with pytest.raises(ValueError, match="BOOJUM_TPU_LIMB_SWEEP"):
        ps.limb_sweep_enabled()
    # the sharded pipeline must keep plain XLA (GSPMD cannot partition a
    # pallas_call)
    monkeypatch.setenv("BOOJUM_TPU_LIMB_SWEEP", "1")
    from boojum_tpu.utils.pallas_util import force_xla

    with force_xla():
        assert ps.limb_sweep_enabled() is False


# ---------------------------------------------------------------------------
# End-to-end acceptance: 2^10 proof bytes + checkpoint stream identical
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=1)
def _small_prove_parts():
    """Same 2^10 circuit + smallest-honest config as test_overlap /
    test_precompile, so kernel shapes are already in the tier-1 persistent
    compile cache."""
    from boojum_tpu.cs.gates import FmaGate, PublicInputGate
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.types import CSGeometry
    from boojum_tpu.prover import ProofConfig, generate_setup

    geom = CSGeometry(8, 0, 6, 4)
    cs = ConstraintSystem(geom, 1 << 10)
    a = cs.alloc_variable_with_value(1)
    b = cs.alloc_variable_with_value(2)
    per_row = FmaGate.instance().num_repetitions(geom)
    for _ in range(((1 << 10) - 8) * per_row):
        a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
    PublicInputGate.place(cs, b)
    asm = cs.into_assembly()
    assert asm.trace_len == 1 << 10
    config = ProofConfig(
        fri_lde_factor=2,
        merkle_tree_cap_size=4,
        num_queries=4,
        fri_final_degree=16,
    )
    setup = generate_setup(asm, config)
    return asm, setup, config


def _recorded_prove(label, env):
    from boojum_tpu.prover import prove

    asm, setup, config = _small_prove_parts()
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        with report.flight_recording(label=label) as rec:
            proof = prove(asm, setup, config)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return proof, report.build_report(rec)


@functools.lru_cache(maxsize=1)
def _both_path_runs():
    # u64 FIRST so its caches never benefit from limb-run state
    u64 = _recorded_prove("u64", {"BOOJUM_TPU_LIMB_SWEEP": "0"})
    limb = _recorded_prove("limb", {"BOOJUM_TPU_LIMB_SWEEP": "1"})
    return {"u64": u64, "limb": limb}


def _checkpoint_stream(rep):
    return [
        (e["seq"], e["round"], e["label"], e["digest"])
        for e in rep["checkpoints"]
    ]


def test_bit_parity_limb_vs_u64_2pow10():
    """Acceptance: proof bytes AND the report.py checkpoint stream are
    bit-identical with BOOJUM_TPU_LIMB_SWEEP=1 vs =0 — the limb kernels
    change the REPRESENTATION the sweep computes in, never a value that
    crosses the transcript."""
    from boojum_tpu.prover import verify

    runs = _both_path_runs()
    p_u64, r_u64 = runs["u64"]
    p_limb, r_limb = runs["limb"]
    base = _checkpoint_stream(r_u64)
    assert base, "no checkpoints recorded"
    assert _checkpoint_stream(r_limb) == base
    assert p_limb.to_json() == p_u64.to_json()
    asm, setup, _config = _small_prove_parts()
    assert verify(setup.vk, p_limb, asm.gates)
    for rep in (r_u64, r_limb):
        assert report.validate_report(rep) == []


def test_limb_kernels_actually_dispatched():
    """Metrics guard: the =1 run must have gone through the limb coset
    sweep and the limb FRI folds (a silent fallback to u64 would make the
    parity test vacuous)."""
    runs = _both_path_runs()
    c_u64 = runs["u64"][1]["metrics"]["counters"]
    c_limb = runs["limb"][1]["metrics"]["counters"]
    assert c_u64.get("quotient.limb_coset_sweeps", 0) == 0
    assert c_u64.get("fri.limb_folds", 0) == 0
    assert c_limb["quotient.limb_coset_sweeps"] == c_limb["quotient.coset_sweeps"]
    assert c_limb["fri.limb_folds"] == c_limb["fri.folds"]
    assert c_limb["quotient.limb_coset_sweeps"] > 0
    assert c_limb["fri.limb_folds"] > 0
