"""Overlapped prove pipeline (ISSUE 3): async transfer helper, chunked
H2D upload, double-buffered streamed commits, challenge-independent
prefetch — all on the CPU backend with the 2^10 acceptance circuit.

Pins the acceptance criteria:
- proof bytes AND the Fiat–Shamir digest checkpoint stream are
  bit-identical across the overlapped / sequenced / streamed paths;
- the overlapped prove issues STRICTLY FEWER blocking host syncs than
  the sequenced baseline (metrics guard — the win can't silently
  regress);
- a raise inside a streamed commit block still yields a partial
  ProveReport (error-annotated span tree + the checkpoints up to the
  failure).
"""

import functools
import os

import jax.numpy as jnp
import numpy as np
import pytest

from boojum_tpu.utils import metrics, report, transfer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Async transfer helper units
# ---------------------------------------------------------------------------


def test_overlap_enabled_parsing(monkeypatch):
    monkeypatch.delenv("BOOJUM_TPU_OVERLAP", raising=False)
    assert transfer.overlap_enabled() is True  # default on
    for v in ("1", "true", "on", "yes"):
        monkeypatch.setenv("BOOJUM_TPU_OVERLAP", v)
        assert transfer.overlap_enabled() is True
    for v in ("0", "false", "off", "no"):
        monkeypatch.setenv("BOOJUM_TPU_OVERLAP", v)
        assert transfer.overlap_enabled() is False
    monkeypatch.setenv("BOOJUM_TPU_OVERLAP", "maybe")
    with pytest.raises(ValueError, match="BOOJUM_TPU_OVERLAP"):
        transfer.overlap_enabled()


def test_to_host_passthrough_and_device_counting():
    host = np.arange(7, dtype=np.uint64)
    reg = metrics.start_metrics()
    try:
        out = transfer.to_host(host)
        np.testing.assert_array_equal(out, host)
        assert reg.counters.get("host.blocking_syncs", 0) == 0  # host value
        dev = jnp.asarray(host)
        out = transfer.to_host(dev)
        np.testing.assert_array_equal(out, host)
        assert reg.counters["host.blocking_syncs"] == 1
        assert reg.counters["transfer.d2h_bytes"] == host.nbytes
    finally:
        metrics.stop_metrics()


def test_fetch_batches_one_blocking_sync(monkeypatch):
    arrays = [
        jnp.asarray(np.arange(16, dtype=np.uint64)),
        jnp.asarray(np.arange(16, 48, dtype=np.uint64)),
        jnp.asarray(np.arange(3, dtype=np.uint64)),
    ]
    monkeypatch.setenv("BOOJUM_TPU_OVERLAP", "1")
    reg = metrics.start_metrics()
    try:
        got = transfer.fetch_np(*arrays, label="unit")
        assert reg.counters["host.blocking_syncs"] == 1  # ONE for the batch
        assert reg.counters["transfer.d2h_batches"] == 1
        assert reg.counters["transfer.d2h_bytes"] == sum(
            a.size * 8 for a in arrays
        )
        assert "transfer.overlap_s" in reg.gauges
    finally:
        metrics.stop_metrics()
    for a, h in zip(arrays, got):
        np.testing.assert_array_equal(np.asarray(a), h)

    # sequenced twin: one blocking sync PER array
    monkeypatch.setenv("BOOJUM_TPU_OVERLAP", "0")
    reg = metrics.start_metrics()
    try:
        got2 = transfer.fetch_np(*arrays)
        assert reg.counters["host.blocking_syncs"] == len(arrays)
    finally:
        metrics.stop_metrics()
    for a, b in zip(got, got2):
        np.testing.assert_array_equal(a, b)

    # wait() is idempotent
    f = transfer.start_fetch(arrays)
    assert f.wait() is f.wait()


def test_chunked_upload_parity(monkeypatch):
    rng = np.random.default_rng(5)
    groups = [
        rng.integers(0, 1 << 63, (5, 64), dtype=np.uint64),
        rng.integers(0, 1 << 63, (3, 64), dtype=np.uint64),
        rng.integers(0, 1 << 63, (1, 64), dtype=np.uint64),
    ]
    ref = np.concatenate(groups, axis=0)
    # force multi-chunk uploads (2 rows per chunk at n=64)
    monkeypatch.setattr(transfer, "H2D_CHUNK_BYTES", 2 * 64 * 8)
    monkeypatch.setenv("BOOJUM_TPU_OVERLAP", "1")
    got = transfer.chunked_upload(groups)
    np.testing.assert_array_equal(np.asarray(got), ref)
    # the chunk plan helper mirrors the dispatch exactly
    shapes = transfer.upload_chunk_shapes([g.shape[0] for g in groups], 64)
    assert sum(shapes) == ref.shape[0]
    assert shapes == [2, 2, 1, 2, 1, 1]
    # overlap off: the legacy single synchronous upload, same bytes
    monkeypatch.setenv("BOOJUM_TPU_OVERLAP", "0")
    got_seq = transfer.chunked_upload(groups)
    np.testing.assert_array_equal(np.asarray(got_seq), ref)


def test_render_report_shows_occupancy():
    rep = {
        "kind": report.REPORT_KIND,
        "schema": report.REPORT_SCHEMA,
        "label": "occ",
        "wall_s": 2.0,
        "spans": [
            {
                "name": "prove",
                "start_s": 0.0,
                "wall_s": 2.0,
                "children": [
                    {
                        "name": "round4",
                        "start_s": 0.1,
                        "wall_s": 1.0,
                        "sync_s": 0.25,
                        "overlap_s": 0.5,
                        "children": [],
                    }
                ],
            }
        ],
        "metrics": {"counters": {}, "gauges": {}, "boundaries": []},
        "checkpoints": [],
    }
    text = report.render_report(rep)
    assert "occ=25%" in text  # sync_s/wall in the tree
    assert "ovl=0.500s" in text
    # top-N leaf table carries the sync/occ column too
    assert "sync=0.250s" in text


# ---------------------------------------------------------------------------
# End-to-end: overlapped vs sequenced vs streamed 2^10 proves
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=1)
def _small_prove_parts():
    """Same 2^10 circuit + smallest-honest config as test_flight_recorder
    / test_precompile, so the kernel shapes are already in the tier-1
    persistent compile cache."""
    from boojum_tpu.cs.gates import FmaGate, PublicInputGate
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.types import CSGeometry
    from boojum_tpu.prover import ProofConfig, generate_setup

    geom = CSGeometry(8, 0, 6, 4)
    cs = ConstraintSystem(geom, 1 << 10)
    a = cs.alloc_variable_with_value(1)
    b = cs.alloc_variable_with_value(2)
    per_row = FmaGate.instance().num_repetitions(geom)
    for _ in range(((1 << 10) - 8) * per_row):
        a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
    PublicInputGate.place(cs, b)
    asm = cs.into_assembly()
    assert asm.trace_len == 1 << 10
    config = ProofConfig(
        fri_lde_factor=2,
        merkle_tree_cap_size=4,
        num_queries=4,
        fri_final_degree=16,
    )
    setup = generate_setup(asm, config)
    return asm, setup, config


def _recorded_prove(label, env):
    from boojum_tpu.prover import prove

    asm, setup, config = _small_prove_parts()
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        with report.flight_recording(label=label) as rec:
            proof = prove(asm, setup, config)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return proof, report.build_report(rec)


@functools.lru_cache(maxsize=1)
def _three_path_runs():
    # sequenced FIRST so its counters never benefit from state the
    # overlapped run warmed
    seq = _recorded_prove("sequenced", {"BOOJUM_TPU_OVERLAP": "0"})
    ovl = _recorded_prove("overlapped", {"BOOJUM_TPU_OVERLAP": "1"})
    streamed = _recorded_prove(
        "streamed",
        {"BOOJUM_TPU_OVERLAP": "1", "BOOJUM_TPU_STREAM_LDE": "1"},
    )
    return {"sequenced": seq, "overlapped": ovl, "streamed": streamed}


def _checkpoint_stream(rep):
    return [
        (e["seq"], e["round"], e["label"], e["digest"])
        for e in rep["checkpoints"]
    ]


def test_bit_parity_overlapped_sequenced_streamed():
    """Acceptance: proof bytes and the PR-2 checkpoint stream are
    bit-identical across all three dispatch orders — the overlap layer
    changes WHEN work is enqueued, never what is absorbed."""
    from boojum_tpu.prover import verify

    runs = _three_path_runs()
    p_seq, r_seq = runs["sequenced"]
    p_ovl, r_ovl = runs["overlapped"]
    p_str, r_str = runs["streamed"]

    base = _checkpoint_stream(r_seq)
    assert base, "no checkpoints recorded"
    assert _checkpoint_stream(r_ovl) == base
    assert _checkpoint_stream(r_str) == base
    assert p_ovl.to_json() == p_seq.to_json()
    assert p_str.to_json() == p_seq.to_json()

    asm, setup, _config = _small_prove_parts()
    assert verify(setup.vk, p_ovl, asm.gates)
    for _label, (_p, rep) in runs.items():
        assert report.validate_report(rep) == []


def test_overlapped_prove_strictly_fewer_blocking_syncs():
    """CI guard (acceptance): the overlapped path must issue strictly
    fewer blocking host syncs than the sequenced path — counted at the
    single d2h seam (utils/transfer.py), so a regression that quietly
    re-serializes a pull flips this test."""
    runs = _three_path_runs()
    seq = runs["sequenced"][1]["metrics"]["counters"]
    ovl = runs["overlapped"][1]["metrics"]["counters"]
    assert seq.get("host.blocking_syncs", 0) > 0
    assert ovl.get("host.blocking_syncs", 0) > 0
    assert ovl["host.blocking_syncs"] < seq["host.blocking_syncs"]
    # the saving must come from batching, not from skipped transfers:
    # both paths move the same d2h bytes
    assert ovl["transfer.d2h_bytes"] == seq["transfer.d2h_bytes"]
    assert ovl.get("transfer.d2h_batches", 0) >= 2  # round 4 + FRI final


def test_overlapped_report_carries_overlap_metrics():
    runs = _three_path_runs()
    r_ovl = runs["overlapped"][1]
    gauges = r_ovl["metrics"]["gauges"]
    assert gauges.get("transfer.overlap_s", 0) > 0
    # the streamed run exercised the double-buffered commit path
    r_str = runs["streamed"][1]
    assert (
        r_str["metrics"]["counters"].get("stream.double_buffered_blocks", 0)
        >= 2
    )


def test_error_in_streamed_block_yields_partial_report(monkeypatch):
    """A raise inside a streamed commit block must still produce a
    ProveReport: error-annotated spans for the failing stage and every
    checkpoint recorded before the failure."""
    from boojum_tpu.prover import prove
    from boojum_tpu.prover import streaming

    asm, setup, config = _small_prove_parts()
    monkeypatch.setenv("BOOJUM_TPU_OVERLAP", "1")
    monkeypatch.setenv("BOOJUM_TPU_STREAM_LDE", "1")

    real_absorb = streaming._absorb_cols
    calls = {"n": 0}

    def exploding_absorb(state, cols):
        calls["n"] += 1
        if calls["n"] >= 2:  # witness block passes, stage-2 block raises
            raise RuntimeError("injected block failure")
        return real_absorb(state, cols)

    monkeypatch.setattr(streaming, "_absorb_cols", exploding_absorb)
    with report.flight_recording(label="injected") as rec:
        with pytest.raises(RuntimeError, match="injected block failure"):
            prove(asm, setup, config)
    rep = report.build_report(rec)

    # round 0 + round 1 checkpoints made it; the failing round did not
    labels = [e["label"] for e in rep["checkpoints"]]
    assert "setup_cap" in labels and "witness_cap" in labels
    assert "stage2_cap" not in labels
    # the span tree records the failure instead of dropping the stage
    errors = [
        (path, sp["error"])
        for path, sp in report.flatten_spans(rep)
        if sp.get("error")
    ]
    assert errors, "no error-annotated span recorded"
    assert any("injected block failure" in e for _p, e in errors)
    assert any("round2" in p for p, _e in errors)
