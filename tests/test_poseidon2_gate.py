"""Poseidon2 flattened gate tests: parity vs the host permutation,
satisfiability, tamper rejection, sponge parity (reference test model:
cs/gates/poseidon2.rs tests + algebraic_props/sponge.rs)."""

import random

from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.types import CSGeometry
from boojum_tpu.field import gl
from boojum_tpu.gadgets.poseidon2_rf import (
    CircuitPoseidon2Sponge,
    circuit_hash_leaf,
    circuit_permutation,
)
from boojum_tpu.hashes.poseidon2 import (
    Poseidon2SpongeHost,
    poseidon2_permutation_host,
)
from boojum_tpu.prover.satisfiability import check_if_satisfied

GEOM = CSGeometry(
    num_columns_under_copy_permutation=130,
    num_witness_columns=0,
    num_constant_columns=8,
    max_allowed_constraint_degree=7,
)


def test_flattened_gate_parity_and_satisfiable():
    rng = random.Random(7)
    inputs = [rng.randrange(gl.P) for _ in range(12)]
    cs = ConstraintSystem(GEOM, 1 << 10)
    in_vars = [cs.alloc_variable_with_value(v) for v in inputs]
    out_vars = circuit_permutation(cs, in_vars)
    got = [cs.get_value(v) for v in out_vars]
    assert got == poseidon2_permutation_host(inputs)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_flattened_gate_rejects_tampering():
    cs = ConstraintSystem(GEOM, 1 << 10)
    in_vars = [cs.alloc_variable_with_value(i + 1) for i in range(12)]
    circuit_permutation(cs, in_vars)
    asm = cs.into_assembly()
    # corrupt one aux cell of the poseidon2 row
    for r in range(asm.trace_len):
        g = asm.gates[int(asm.row_gate[r])]
        if g.name == "poseidon2_flat":
            asm.copy_cols_values[40, r] = (
                int(asm.copy_cols_values[40, r]) + 1
            ) % gl.P
            break
    assert not check_if_satisfied(asm)


def test_circuit_sponge_matches_host():
    rng = random.Random(11)
    for length in (3, 8, 11, 16, 20):
        values = [rng.randrange(gl.P) for _ in range(length)]
        cs = ConstraintSystem(GEOM, 1 << 12)
        in_vars = [cs.alloc_variable_with_value(v) for v in values]
        digest_vars = circuit_hash_leaf(cs, in_vars)
        got = [cs.get_value(v) for v in digest_vars]
        assert got == Poseidon2SpongeHost.hash_leaf(values)


def test_circuit_sponge_incremental_absorb():
    values = list(range(1, 14))
    cs = ConstraintSystem(GEOM, 1 << 12)
    sp = CircuitPoseidon2Sponge(cs)
    for v in values:
        sp.absorb([cs.alloc_variable_with_value(v)])
    got = [cs.get_value(v) for v in sp.finalize()]
    host = Poseidon2SpongeHost()
    host.absorb(values)
    assert got == host.finalize()


class TestLegacyPoseidonFlattenedGate:
    """Legacy PoseidonFlattenedGate (reference poseidon.rs:1249): the
    witness trace must equal the standalone legacy permutation, and the
    placed gate must satisfy/violate exactly like its Poseidon2 sibling."""

    def test_witness_matches_permutation(self):
        from boojum_tpu.cs.gates.poseidon_flat import _witness_trace
        from boojum_tpu.hashes.poseidon import poseidon_permutation_host

        import random

        rng = random.Random(3)
        ins = [rng.randrange(gl.P) for _ in range(12)]
        outs, aux = _witness_trace(ins)
        assert outs == poseidon_permutation_host(ins)
        assert len(aux) == 106

    def test_gate_satisfiable_and_tamper_detected(self):
        from boojum_tpu.cs.gates import PoseidonFlattenedGate
        from boojum_tpu.cs.implementations import ConstraintSystem
        from boojum_tpu.cs.types import CSGeometry
        from boojum_tpu.hashes.poseidon import poseidon_permutation_host
        from boojum_tpu.prover.satisfiability import check_if_satisfied

        geom = CSGeometry(
            num_columns_under_copy_permutation=130,
            num_witness_columns=0,
            num_constant_columns=8,
            max_allowed_constraint_degree=7,
        )
        cs = ConstraintSystem(geom, 256)
        ins = [cs.alloc_variable_with_value(i + 1) for i in range(12)]
        outs = PoseidonFlattenedGate.permutation(cs, ins)
        got = [cs.get_value(v) for v in outs]
        assert got == poseidon_permutation_host(list(range(1, 13)))
        asm = cs.into_assembly()
        assert check_if_satisfied(asm)
        # tamper one output value
        cs2 = ConstraintSystem(geom, 256)
        ins2 = [cs2.alloc_variable_with_value(i + 1) for i in range(12)]
        outs2 = PoseidonFlattenedGate.permutation(cs2, ins2)
        asm2 = cs2.into_assembly()
        # find the placement of the first output var and bump its value
        import numpy as np

        tgt = outs2[0]
        loc = np.argwhere(asm2.copy_placement == tgt)
        assert loc.size
        c, r = loc[0]
        asm2.copy_cols_values[c, r] = (
            int(asm2.copy_cols_values[c, r]) + 1
        ) % gl.P
        assert not check_if_satisfied(asm2)

    def test_gate_proves_e2e(self):
        from boojum_tpu.cs.gates import PoseidonFlattenedGate, PublicInputGate
        from boojum_tpu.cs.implementations import ConstraintSystem
        from boojum_tpu.cs.types import CSGeometry
        from boojum_tpu.prover import (
            ProofConfig,
            generate_setup,
            prove,
            verify,
        )

        geom = CSGeometry(
            num_columns_under_copy_permutation=130,
            num_witness_columns=0,
            num_constant_columns=8,
            max_allowed_constraint_degree=7,
        )
        cs = ConstraintSystem(geom, 1 << 10)
        state = [cs.alloc_variable_with_value(i) for i in range(12)]
        for _ in range(8):
            state = PoseidonFlattenedGate.permutation(cs, state)
        PublicInputGate.place(cs, state[0])
        asm = cs.into_assembly()
        cfg = ProofConfig(
            fri_lde_factor=8,
            merkle_tree_cap_size=4,
            num_queries=6,
            fri_final_degree=8,
        )
        setup = generate_setup(asm, cfg)
        proof = prove(asm, setup, cfg)
        assert verify(setup.vk, proof, asm.gates)
