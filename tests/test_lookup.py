"""Lookup argument tests: placement, satisfiability, e2e prove/verify with a
log-derivative lookup (reference test model: gadget tests +
prove_sha256-style full pipeline with specialized lookup columns)."""

import numpy as np
import pytest

from boojum_tpu.cs.types import CSGeometry, LookupParameters
from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify
from boojum_tpu.prover.satisfiability import check_if_satisfied
from boojum_tpu.prover.proof import Proof
from boojum_tpu.field import gl

GEOM = CSGeometry(
    num_columns_under_copy_permutation=8,
    num_witness_columns=0,
    num_constant_columns=6,
    max_allowed_constraint_degree=4,
)

LOOKUP = LookupParameters(width=3, num_repetitions=2)

CONFIG = ProofConfig(
    fri_lde_factor=8,
    merkle_tree_cap_size=4,
    num_queries=20,
    pow_bits=0,
    fri_final_degree=4,
)


def build_circuit(num_lookups=30):
    from boojum_tpu.examples import build_xor_lookup_circuit

    return build_xor_lookup_circuit(
        num_lookups, geometry=GEOM, lookup_params=LOOKUP
    )


def test_lookup_satisfiability():
    cs, _, _ = build_circuit()
    asm = cs.into_assembly()
    assert asm.lookups_enabled
    assert check_if_satisfied(asm, verbose=True)


def test_lookup_witness_values():
    cs, _, out = build_circuit(num_lookups=5)
    # xor semantics via resolver
    assert 0 <= cs.get_value(out) < 16


def test_lookup_e2e_prove_verify():
    cs, acc, _ = build_circuit()
    expected = cs.get_value(acc)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)
    setup = generate_setup(asm, CONFIG)
    proof = prove(asm, setup, CONFIG)
    assert proof.public_inputs == [expected]
    assert len(proof.values_at_0) == LOOKUP.num_repetitions + 1
    assert verify(setup.vk, proof, asm.gates), "honest lookup proof must verify"


def test_lookup_rejects_tampering():
    cs, _, _ = build_circuit(num_lookups=8)
    asm = cs.into_assembly()
    setup = generate_setup(asm, CONFIG)
    proof = prove(asm, setup, CONFIG)
    assert verify(setup.vk, proof, asm.gates)
    # tamper a value at 0 (breaks the A/B sum check or transcript)
    p2 = Proof.from_json(proof.to_json())
    v = list(p2.values_at_0[0])
    v[0] = (v[0] + 1) % gl.P
    p2.values_at_0[0] = tuple(v)
    assert not verify(setup.vk, p2, asm.gates)
    # tamper a multiplicity opening
    p3 = Proof.from_json(proof.to_json())
    q = p3.queries[0].witness
    q.leaf_values[-1] = (q.leaf_values[-1] + 1) % gl.P
    assert not verify(setup.vk, p3, asm.gates)


def test_bad_multiplicities_fail_satisfiability():
    cs, _, _ = build_circuit(num_lookups=6)
    asm = cs.into_assembly()
    asm.multiplicities = asm.multiplicities.copy()
    asm.multiplicities[0] += 1
    assert not check_if_satisfied(asm, verbose=False)


def test_spurious_multiplicity_on_unused_row_fails():
    """A nonzero multiplicity on a table row no lookup touches must fail
    (it breaks the B(0) = sum A_i(0) sum check in the real argument)."""
    import numpy as np

    cs, _, _ = build_circuit(num_lookups=6)
    asm = cs.into_assembly()
    asm.multiplicities = asm.multiplicities.copy()
    untouched = np.nonzero(np.asarray(asm.multiplicities) == 0)[0]
    assert untouched.size > 0
    asm.multiplicities[int(untouched[0])] = 5
    assert not check_if_satisfied(asm, verbose=False)
