"""AOT executable artifact store (ISSUE 8).

The tentpole made compilation a build step: `prover/aot.py` serializes
the compiled executables of the whole dispatch surface (persistent-cache
bundle + jax.export StableHLO artifacts, manifest with integrity
hashes), and a cold process loads them instead of compiling. These tests
pin the acceptance criteria at 2^10 on CPU:

- artifact roundtrip across REAL process boundaries: one subprocess
  builds the bundle, a second FRESH subprocess (empty persistent cache)
  loads it and proves — proof bytes AND digest-checkpoint stream are
  bit-identical to an in-process JIT prove, the CompileLedger records
  ZERO cache misses / dispatch compiles, and every enumerated kernel is
  an `aot_hit`;
- the serve process's ProveReport line passes `validate_report`
  (aot.* gauge schema), and a line whose ledger claims all-aot_hit
  kernels while counting cache misses FAILS it;
- a stale bundle (wrong jaxlib in the manifest) degrades to JIT with a
  logged warning — and raises under BOOJUM_TPU_AOT_REQUIRE;
- a corrupt cache entry is skipped (counted, not fatal);
- jax.export artifacts in the bundle deserialize and name the build
  platform;
- bench.py's size-capped cache prune never evicts entries touched by
  the current run or installed from a loaded bundle.

The build/serve circuit is the same 2^10 fma circuit + smallest-honest
config as test_limb_sweep._small_prove_parts, so the in-process
reference prove reuses the tier-1 persistent compile cache.
"""

import functools
import json
import logging
import os
import subprocess
import sys
import tempfile
import textwrap

import pytest

from boojum_tpu.utils import report

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the SAME circuit/config as test_limb_sweep._small_prove_parts, as
# standalone source both subprocess drivers embed — synthesis only, no
# jit dispatch before build_bundle redirects the cache
_CIRCUIT_SRC = textwrap.dedent(
    '''
    def build_parts():
        from boojum_tpu.cs.gates import FmaGate, PublicInputGate
        from boojum_tpu.cs.implementations import ConstraintSystem
        from boojum_tpu.cs.types import CSGeometry
        from boojum_tpu.prover import ProofConfig

        geom = CSGeometry(8, 0, 6, 4)
        cs = ConstraintSystem(geom, 1 << 10)
        a = cs.alloc_variable_with_value(1)
        b = cs.alloc_variable_with_value(2)
        per_row = FmaGate.instance().num_repetitions(geom)
        for _ in range(((1 << 10) - 8) * per_row):
            a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
        PublicInputGate.place(cs, b)
        asm = cs.into_assembly()
        config = ProofConfig(
            fri_lde_factor=2, merkle_tree_cap_size=4,
            num_queries=4, fri_final_degree=16,
        )
        return asm, config
    '''
)

_BUILD_SRC = (
    _CIRCUIT_SRC
    + textwrap.dedent(
        '''
    import json, sys

    asm, config = build_parts()
    from boojum_tpu.prover.aot import build_bundle
    from boojum_tpu.utils.profiling import start_compile_ledger

    led = start_compile_ledger()
    manifest = build_bundle(asm, config, OUT_ROOT, ledger=led)
    json.dump(
        {
            "dir": manifest["dir"],
            "bucket": manifest["bucket"],
            "num_kernels": manifest["num_kernels"],
            "num_exports": manifest["num_exports"],
            "kernels": manifest["kernels"],
            "num_cache_entries": len(manifest["cache_entries"]),
        },
        open(OUT_JSON, "w"),
    )
    '''
    )
)

_SERVE_SRC = (
    _CIRCUIT_SRC
    + textwrap.dedent(
        '''
    import json, sys

    asm, config = build_parts()
    from boojum_tpu.prover import generate_setup, prove
    from boojum_tpu.prover import aot as _aot
    from boojum_tpu.utils import report as _report
    from boojum_tpu.utils.profiling import start_compile_ledger

    led = start_compile_ledger()
    # ONE recording over load + warm + setup + prove, so the report
    # line carries the aot.* counters/gauges the validator checks
    with _report.flight_recording(label="aot_serve") as rec:
        stats = _aot.maybe_load_for_prove(asm, config)
        setup = generate_setup(asm, config)
        proof = prove(asm, setup, config)
    line = _report.build_report(rec)
    json.dump(
        {
            "proof": proof.to_json(),
            "checkpoints": [
                (e["seq"], e["round"], e["label"], e["digest"])
                for e in line["checkpoints"]
            ],
            "report_line": line,
            "stats": stats,
            "summary": led.summary(),
            "aot_entries": {
                e["name"]: e["aot_hit"]
                for e in led.entries
                if "aot_hit" in e
            },
        },
        open(OUT_JSON, "w"),
    )
    '''
    )
)


def _run_driver(src: str, tmp: str, name: str, env_extra: dict) -> dict:
    """Write `src` (prefixed with OUT_* constants) as a driver script and
    run it in a FRESH python process; returns the JSON it wrote."""
    out_json = os.path.join(tmp, f"{name}.json")
    path = os.path.join(tmp, f"{name}.py")
    with open(path, "w") as f:
        f.write(
            "import sys\n"
            f"sys.path.insert(0, {REPO!r})\n"
            f"OUT_ROOT = {os.path.join(tmp, 'bundles')!r}\n"
            f"OUT_JSON = {out_json!r}\n"
        )
        f.write(src)
    env = dict(os.environ)
    for k in (
        "BOOJUM_TPU_REPORT", "BOOJUM_TPU_AOT_DIR",
        "BOOJUM_TPU_AOT_REQUIRE", "BOOJUM_TPU_PROFILE",
    ):
        env.pop(k, None)
    env.update(env_extra)
    proc = subprocess.run(
        [sys.executable, path],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, (
        f"{name} driver failed:\n{proc.stdout}\n{proc.stderr[-4000:]}"
    )
    with open(out_json) as f:
        return json.load(f)


@functools.lru_cache(maxsize=1)
def _roundtrip():
    """Build the bundle in one subprocess, serve from it in a second
    FRESH subprocess whose persistent cache starts EMPTY."""
    tmp = tempfile.mkdtemp(prefix="boojum_aot_")
    build = _run_driver(
        _BUILD_SRC, tmp, "build",
        {"BOOJUM_TPU_COMPILE_CACHE": os.path.join(tmp, "build_cache")},
    )
    serve = _run_driver(
        _SERVE_SRC, tmp, "serve",
        {
            "BOOJUM_TPU_AOT_DIR": os.path.join(tmp, "bundles"),
            # an EMPTY cache dir: the only warm state is the bundle
            "BOOJUM_TPU_COMPILE_CACHE": os.path.join(tmp, "fresh_cache"),
        },
    )
    return tmp, build, serve


def _reference():
    """In-process JIT prove of the identical circuit (shares the tier-1
    persistent cache with test_limb_sweep/test_overlap)."""
    from test_limb_sweep import _small_prove_parts

    from boojum_tpu.prover import prove

    asm, setup, config = _small_prove_parts()
    with report.flight_recording(label="ref") as rec:
        proof = prove(asm, setup, config)
    return proof, report.build_report(rec)


def test_roundtrip_zero_compile_bit_parity():
    """Acceptance: with a pre-built bundle, a cold process records ZERO
    XLA compiles (no cache misses, no dispatch compiles), every
    enumerated kernel is an aot_hit, and proof bytes + checkpoint
    stream are bit-identical to the JIT path."""
    _tmp, build, serve = _roundtrip()
    summary = serve["summary"]
    assert summary["cache_misses"] == 0, summary
    assert summary["num_dispatch_compiles"] == 0, summary
    assert summary["aot_misses"] == 0, summary
    assert summary["aot_hits"] == build["num_kernels"], summary
    assert summary["aot_deserialize_s"] > 0.0
    # every enumerated kernel present and hit
    assert len(serve["aot_entries"]) == build["num_kernels"]
    misses = [k for k, v in serve["aot_entries"].items() if not v]
    assert not misses, f"kernels that escaped the artifact store: {misses}"

    ref_proof, ref_line = _reference()
    assert serve["proof"] == ref_proof.to_json()
    ref_ckpts = [
        (e["seq"], e["round"], e["label"], e["digest"])
        for e in ref_line["checkpoints"]
    ]
    assert ref_ckpts, "reference recorded no checkpoints"
    assert [tuple(c) for c in serve["checkpoints"]] == ref_ckpts


def test_serve_report_line_validates_aot_schema():
    """The serve line carries aot.* counters/gauges and passes --check;
    tampered variants (missing deserialize gauge, negative counter,
    all-hit claim with nonzero compile count) FAIL it."""
    _tmp, _build, serve = _roundtrip()
    line = serve["report_line"]
    problems = report.validate_report(line)
    assert problems == [], problems
    counters = line["metrics"]["counters"]
    assert counters.get("aot.hits", 0) > 0
    assert "aot.deserialize_s" in line["metrics"]["gauges"]

    # missing deserialize gauge
    bad = json.loads(json.dumps(line))
    bad["metrics"]["gauges"].pop("aot.deserialize_s")
    assert any(
        "aot.deserialize_s" in p for p in report.validate_report(bad)
    )
    # malformed negative counter
    bad = json.loads(json.dumps(line))
    bad["metrics"]["counters"]["aot.hits"] = -3
    assert any(
        "aot metric aot.hits" in p for p in report.validate_report(bad)
    )
    # the lying line: all-aot_hit ledger with a nonzero compile count
    bad = json.loads(json.dumps(line))
    bad["compile_ledger"]["cache_misses"] = 7
    probs = report.validate_report(bad)
    assert any("cache misses" in p for p in probs), probs


def test_cold_process_carries_cost_actuals():
    """ISSUE 12 acceptance: the AOT bundle manifest persists per-kernel
    XLA cost actuals captured at BUILD time, and the zero-compile cold
    serve process still stamps a fully-attributed `cost` record — no
    recompilation needed to attribute flops/bytes."""
    _tmp, build, serve = _roundtrip()
    with_cost = [k for k in build["kernels"] if k.get("cost")]
    assert len(with_cost) >= 0.8 * build["num_kernels"], (
        f"only {len(with_cost)}/{build['num_kernels']} manifest kernels "
        f"carry cost actuals"
    )
    assert all(
        isinstance(k["cost"].get("bytes_accessed"), (int, float))
        for k in with_cost
    )
    line = serve["report_line"]
    cost = line.get("cost")
    assert isinstance(cost, dict), "cold serve line missing cost record"
    mc = cost.get("model_check")
    assert mc and mc["covered_kernels"] >= 0.8 * build["num_kernels"], mc
    ledger = line["compile_ledger"]
    assert set(cost.get("attributed_kernels") or []) <= set(
        ledger["kernel_names"]
    )
    # still a zero-compile process — the actuals came from the warm
    # pass / manifest, not from fresh compiles
    assert serve["summary"]["cache_misses"] == 0


def test_slo_view_surfaces_artifact_hit_rate():
    _tmp, build, serve = _roundtrip()
    summary = report.slo_summary([serve["report_line"]])
    assert summary["aot_kernels_warmed"] == build["num_kernels"]
    assert summary["aot_hit_rate"] == 1.0
    assert "aot artifacts" in report.render_slo(summary)


def test_export_artifacts_deserialize():
    """The jax.export half of the bundle: every kernel recorded as
    kind=export round-trips through jax.export.deserialize and names
    the build platform."""
    import jax
    from jax import export as jexport

    tmp, build, _serve = _roundtrip()
    exported = [k for k in build["kernels"] if k.get("kind") == "export"]
    assert exported, "no kernels were exported"
    ent = exported[0]
    with open(os.path.join(build["dir"], ent["file"]), "rb") as f:
        data = f.read()
    assert len(data) == ent["bytes"]
    rt = jexport.deserialize(data)
    assert jax.default_backend() in rt.platforms


def _stale_root(tmp_path, asm, config, jaxlib_version="0.0.0-stale"):
    """A bundle dir for (asm, config) whose manifest claims a different
    jaxlib — the canonical stale artifact."""
    from boojum_tpu.prover import aot

    root = str(tmp_path)
    bdir = aot.bundle_dir_for(root, asm, config)
    os.makedirs(bdir, exist_ok=True)
    plat = aot.platform_info()
    plat["jaxlib"] = jaxlib_version
    manifest = {
        "kind": aot.AOT_KIND,
        "schema": aot.AOT_SCHEMA,
        "platform": plat,
        "cache_entries": [],
        "kernels": [],
    }
    with open(os.path.join(bdir, aot.MANIFEST_NAME), "w") as f:
        json.dump(manifest, f)
    return root


def test_stale_bundle_graceful_jit_fallback(tmp_path):
    """Wrong jaxlib version in the manifest: load_bundle warns and
    returns None (counted as aot.stale_bundles), and prove() under
    BOOJUM_TPU_AOT_DIR still proves bit-identically via JIT."""
    from test_limb_sweep import _small_prove_parts

    from boojum_tpu.prover import aot, prove
    from boojum_tpu.utils import metrics as _metrics

    asm, setup, config = _small_prove_parts()
    root = _stale_root(tmp_path, asm, config)

    records = []
    handler = logging.Handler()
    handler.emit = lambda rec: records.append(rec.getMessage())
    lg = logging.getLogger("boojum_tpu")
    lg.addHandler(handler)
    reg = _metrics.start_metrics()
    try:
        out = aot.load_bundle(root, asm, config, require=False)
    finally:
        lg.removeHandler(handler)
        _metrics.stop_metrics()
    assert out is None
    assert reg.counters.get("aot.stale_bundles") == 1
    stale_msgs = [m for m in records if "stale bundle" in m]
    assert stale_msgs and "jaxlib" in stale_msgs[0], records

    # the prove-side consult degrades to JIT, not a crash
    ref_proof, _ = _reference()
    prev = os.environ.get("BOOJUM_TPU_AOT_DIR")
    os.environ["BOOJUM_TPU_AOT_DIR"] = root
    try:
        proof = prove(asm, setup, config)
    finally:
        if prev is None:
            os.environ.pop("BOOJUM_TPU_AOT_DIR", None)
        else:
            os.environ["BOOJUM_TPU_AOT_DIR"] = prev
    assert proof.to_json() == ref_proof.to_json()


def test_stale_bundle_raises_under_require(tmp_path, monkeypatch):
    from test_limb_sweep import _small_prove_parts

    from boojum_tpu.prover import aot

    asm, _setup, config = _small_prove_parts()
    root = _stale_root(tmp_path, asm, config)
    monkeypatch.setenv("BOOJUM_TPU_AOT_REQUIRE", "1")
    with pytest.raises(aot.AotBundleError, match="stale bundle"):
        aot.load_bundle(root, asm, config)
    # missing bundle entirely is also a hard error under REQUIRE
    with pytest.raises(aot.AotBundleError, match="no artifact bundle"):
        aot.load_bundle(str(tmp_path / "empty"), asm, config)


def test_corrupt_entry_skipped(tmp_path):
    """A flipped byte in one cache entry: the entry is skipped (and
    counted), the rest of the bundle still installs."""
    import shutil

    import jax

    from boojum_tpu.prover import aot
    from boojum_tpu.utils import metrics as _metrics

    tmp, build, _serve = _roundtrip()
    bdir_src = build["dir"]
    root = str(tmp_path / "bundles")
    bdir = os.path.join(root, os.path.basename(bdir_src))
    shutil.copytree(bdir_src, bdir)
    manifest = json.load(open(os.path.join(bdir, aot.MANIFEST_NAME)))
    victim = next(
        e for e in manifest["cache_entries"] if e["file"].endswith("-cache")
    )
    vpath = os.path.join(bdir, victim["file"])
    blob = bytearray(open(vpath, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(vpath, "wb").write(bytes(blob))

    # the serve subprocesses own the sticky cache-key flip; restore this
    # process's value so later tier-1 tests keep their cache keys
    prev_flag = jax.config.jax_persistent_cache_enable_xla_caches
    from test_limb_sweep import _small_prove_parts

    asm, _setup, config = _small_prove_parts()
    reg = _metrics.start_metrics()
    try:
        out = aot.load_bundle(root, asm, config, require=False)
    finally:
        _metrics.stop_metrics()
        jax.config.update(
            "jax_persistent_cache_enable_xla_caches", prev_flag
        )
    assert out is not None
    assert out.skipped == 1
    assert reg.counters.get("aot.corrupt_entries") == 1
    assert os.path.basename(victim["file"]) not in out.cache_files
    assert len(out.cache_files) == len(manifest["cache_entries"]) - 1


def test_bench_prune_protects_current_run_and_bundle_entries(tmp_path):
    """Satellite: the BENCH_CACHE_MAX_BYTES prune evicts old stems but
    never entries touched since process start or installed from a
    loaded artifact bundle (runs bench's prune in a subprocess — bench
    import reconfigures jax caches)."""
    root = str(tmp_path)
    d = os.path.join(root, ".jax_cache_bench_test_fp")
    os.makedirs(d)
    names = {
        "old1-cache": -86400, "old1-atime": -86400,
        "old2-cache": -86400, "old2-atime": -86400,
        "bundle1-cache": -86400, "bundle1-atime": -86400,
        "fresh1-cache": +3600,
    }
    for name, dt in names.items():
        p = os.path.join(d, name)
        with open(p, "wb") as f:
            f.write(b"x" * 1024)
        ts = __import__("time").time() + dt
        os.utime(p, (ts, ts))
    driver = os.path.join(root, "prune_driver.py")
    with open(driver, "w") as f:
        f.write(
            textwrap.dedent(
                f"""
                import sys
                sys.path.insert(0, {REPO!r})
                import bench
                from boojum_tpu.prover import aot
                aot._LOADED_CACHE_FILES.update(
                    ["bundle1-cache", "bundle1-atime"]
                )
                bench._prune_bench_caches({root!r})
                """
            )
        )
    env = dict(os.environ)
    env["BENCH_CACHE_MAX_BYTES"] = "2048"  # force eviction pressure
    proc = subprocess.run(
        [sys.executable, driver], capture_output=True, text=True,
        timeout=300, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    left = set(os.listdir(d))
    # bundle-installed and freshly-touched stems survive; old ones die
    assert {"bundle1-cache", "bundle1-atime", "fresh1-cache"} <= left
    assert "old1-cache" not in left and "old2-cache" not in left


def test_platform_info_does_not_memoize_failed_probe(monkeypatch):
    """A first call racing device availability (backend not up yet)
    must not pin device_kind='unknown' for the process lifetime — that
    would reject every bundle load and mis-identify every report
    line. Only a successful probe is memoized."""
    import jax

    from boojum_tpu.prover import aot

    saved = aot._PLATFORM_INFO
    try:
        aot._PLATFORM_INFO = None

        def _boom():
            raise RuntimeError("backend not initialized")

        monkeypatch.setattr(jax, "devices", _boom)
        monkeypatch.setattr(jax, "device_count", _boom)
        bad = aot.platform_info()
        assert bad["device_kind"] == "unknown"
        assert bad["num_devices"] == 0
        assert aot._PLATFORM_INFO is None  # failure NOT cached
        monkeypatch.undo()
        good = aot.platform_info()
        assert good["device_kind"] != "unknown"
        assert good["num_devices"] >= 1
        assert aot._PLATFORM_INFO is not None  # success memoized
    finally:
        aot._PLATFORM_INFO = saved
