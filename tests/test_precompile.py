"""Precompile subsystem + split-graph parity (ISSUE 1).

The tentpole split the fused prover round graphs (`_commit_fused`, the
stage-2 tail, the unrolled chunk products) into a library of shape-keyed
top-level kernels plus a parallel precompiler. These tests pin:

- the kernel enumeration for the SHA-256 bench geometry lowers cleanly on
  CPU (no tracing errors) and feeds the compile ledger one entry per
  kernel with monotonic timestamps;
- the split pipelines are BIT-identical to the pre-split monolithic
  graphs they replaced, both as unit parities (commit pipeline, streamed
  digests, chunk scan) and as a round-output check on a 2^10 circuit's
  actual proof.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.types import CSGeometry, LookupParameters
from boojum_tpu.field import gl
from boojum_tpu.field import extension as ext_f
from boojum_tpu.field import goldilocks as gf
from boojum_tpu.ntt import lde_from_monomial, monomial_from_values
from boojum_tpu.prover import ProofConfig, generate_setup, prove
from boojum_tpu.utils.profiling import CompileLedger

SHA_GEOM = CSGeometry(
    num_columns_under_copy_permutation=60,
    num_witness_columns=0,
    num_constant_columns=8,
    max_allowed_constraint_degree=7,
)
SHA_LOOKUP = LookupParameters(width=4, num_repetitions=8)
# the bench's proof shape (bench.py), at tier-1-friendly query count
SHA_CONFIG = ProofConfig(
    fri_lde_factor=8,
    merkle_tree_cap_size=16,
    num_queries=4,
    pow_bits=0,
    fri_final_degree=16,
)


def _sha_assembly():
    from boojum_tpu.gadgets import allocate_u8_input, sha256

    cs = ConstraintSystem(SHA_GEOM, 1 << 15, lookup_params=SHA_LOOKUP)
    sha256(cs, allocate_u8_input(cs, b"precompile me"))
    return cs.into_assembly()


def test_sha_geometry_enumeration_lowers_with_ledger():
    from boojum_tpu.prover.precompile import enumerate_kernels, precompile

    asm = _sha_assembly()
    specs = enumerate_kernels(asm, SHA_CONFIG)
    assert len(specs) > 20, "kernel library unexpectedly small"
    names = [s.name for s in specs]
    assert len(set(names)) == len(names), "duplicate kernel names"

    ledger = CompileLedger()
    out = precompile(asm, SHA_CONFIG, ledger=ledger, lower_only=True)
    assert out is ledger
    errors = [e for e in ledger.entries if "error" in e]
    assert not errors, f"kernels failed to lower: {errors}"
    assert len(ledger.entries) == len(specs)
    stamps = [e["ts"] for e in ledger.entries]
    assert stamps == sorted(stamps), "ledger timestamps not monotonic"
    assert all(e["trace_s"] >= 0.0 for e in ledger.entries)
    # lower-only must not claim compile work happened
    assert all(e["compile_s"] == 0.0 for e in ledger.entries)
    summary = ledger.summary()
    assert summary["num_kernels"] == len(specs)


def test_limb_sweep_kernels_enumerate_and_lower(monkeypatch):
    """ISSUE 4 satellite: with BOOJUM_TPU_LIMB_SWEEP=1 the enumeration
    swaps in the limb-variant sweep kernels (the fused u32-limb Pallas
    coset sweep and the limb FRI folds), they LOWER on CPU (interpret
    mode traces cleanly) and land in the compile ledger under their
    limb-tagged names."""
    from boojum_tpu.cs.gates import FmaGate, PublicInputGate
    from boojum_tpu.prover.precompile import enumerate_kernels, precompile

    monkeypatch.setenv("BOOJUM_TPU_LIMB_SWEEP", "1")
    geom = CSGeometry(8, 0, 6, 4)
    cs = ConstraintSystem(geom, 1 << 10)
    a = cs.alloc_variable_with_value(1)
    b = cs.alloc_variable_with_value(2)
    per_row = FmaGate.instance().num_repetitions(geom)
    for _ in range(((1 << 10) - 8) * per_row):
        a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
    PublicInputGate.place(cs, b)
    asm = cs.into_assembly()
    cfg = ProofConfig(
        fri_lde_factor=2,
        merkle_tree_cap_size=4,
        num_queries=4,
        fri_final_degree=16,
    )
    specs = enumerate_kernels(asm, cfg)
    names = [s.name for s in specs]
    assert "coset_sweep_terms_limb" in names
    assert "coset_sweep_terms" not in names  # only the dispatched variant
    limb_folds = [n for n in names if n.startswith("fri_fold_limb_")]
    assert limb_folds, names
    assert not any(
        n.startswith("fri_fold_k") for n in names
    ), "u64 fold variant enumerated alongside the limb one"

    ledger = CompileLedger()
    precompile(asm, cfg, ledger=ledger, lower_only=True)
    by_name = {e["name"]: e for e in ledger.entries}
    for name in ["coset_sweep_terms_limb"] + limb_folds:
        assert name in by_name, name
        assert "error" not in by_name[name], by_name[name]

    # flag off: the same enumeration returns to the u64 names
    monkeypatch.setenv("BOOJUM_TPU_LIMB_SWEEP", "0")
    names_u64 = [s.name for s in enumerate_kernels(asm, cfg)]
    assert "coset_sweep_terms" in names_u64


def test_limb_resident_kernels_enumerate_and_lower(monkeypatch):
    """ISSUE 10 satellite: with BOOJUM_TPU_LIMB_RESIDENT=1 the enumeration
    swaps to the RESIDENT plane-kernel set (`*_limbres` ledger names —
    plane NTTs, plane sponges/commits, the resident sweep and FRI chain,
    the stage-2/DEEP plane twins), it LOWERS on CPU, and the converting
    names disappear (only the dispatched variant is enumerated)."""
    from boojum_tpu.cs.gates import FmaGate, PublicInputGate
    from boojum_tpu.prover.precompile import enumerate_kernels, precompile

    monkeypatch.setenv("BOOJUM_TPU_LIMB_RESIDENT", "1")
    geom = CSGeometry(8, 0, 6, 4)
    cs = ConstraintSystem(geom, 1 << 10)
    a = cs.alloc_variable_with_value(1)
    b = cs.alloc_variable_with_value(2)
    per_row = FmaGate.instance().num_repetitions(geom)
    for _ in range(((1 << 10) - 8) * per_row):
        a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
    PublicInputGate.place(cs, b)
    asm = cs.into_assembly()
    cfg = ProofConfig(
        fri_lde_factor=2,
        merkle_tree_cap_size=4,
        num_queries=4,
        fri_final_degree=16,
    )
    specs = enumerate_kernels(asm, cfg)
    names = [s.name for s in specs]
    assert "coset_sweep_terms_limbres" in names
    assert "coset_sweep_terms" not in names
    assert "coset_sweep_terms_limb" not in names
    res_folds = [n for n in names if n.startswith("fri_fold_limbres_")]
    assert res_folds, names
    assert not any(n.startswith("fri_fold_k") for n in names)
    assert "chunk_num_den_limbres" in names
    assert "z_and_partials_limbres" in names
    assert "evals_limbres" in names
    assert "deep_combine_limbres" in names
    assert "node_layers_limbres" in names
    assert any(n.startswith("wit:imono_limbres_") for n in names), names
    assert any(n.startswith("wit:lde_limbres_") for n in names), names
    # every resident spec lowers cleanly on CPU
    ledger = CompileLedger()
    precompile(asm, cfg, ledger=ledger, lower_only=True)
    by_name = {e["name"]: e for e in ledger.entries}
    for name in (
        ["coset_sweep_terms_limbres", "chunk_num_den_limbres",
         "z_and_partials_limbres", "evals_limbres",
         "deep_combine_limbres", "deep_extras_limbres",
         "node_layers_limbres", "quotient_interp_limbres",
         "deep_denoms_limbres", "zshift_limbres"]
        + res_folds
    ):
        assert name in by_name, name
        assert "error" not in by_name[name], by_name[name]

    # the AOT bundle key separates the variants (a resident bundle must
    # never serve a converting process)
    from boojum_tpu.prover.aot import variant_fingerprint

    assert variant_fingerprint()["limb_resident"] is True
    monkeypatch.setenv("BOOJUM_TPU_LIMB_RESIDENT", "0")
    assert variant_fingerprint()["limb_resident"] is False
    names_u64 = [s.name for s in enumerate_kernels(asm, cfg)]
    assert "coset_sweep_terms" in names_u64
    assert "coset_sweep_terms_limbres" not in names_u64
    assert "coset_sweep_terms_limb" not in names_u64


def test_mesh_shard_map_kernels_enumerate_and_lower(monkeypatch):
    """ISSUE 5 satellite: enumerate_kernels(mesh_shape=(2,4)) swaps in the
    shard_map `_sm` kernel variants (per-chip iNTT + fused LDE/pivot/leaf
    graph, per-coset eval with explicit all_to_all, the sm terms sweep,
    the per-chip FRI leaf/fold graphs, the one-graph DEEP codeword), they
    LOWER on the forced-8-device CPU, and the ledger records ONLY the
    dispatched variant — none of the meshless twins ride along."""
    import jax as _jax

    if len(_jax.devices()) < 8:
        import pytest

        pytest.skip("needs 8 virtual devices")
    from boojum_tpu.cs.gates import FmaGate, PublicInputGate
    from boojum_tpu.prover.precompile import enumerate_kernels, precompile

    monkeypatch.delenv("BOOJUM_TPU_LIMB_SWEEP", raising=False)
    geom = CSGeometry(8, 0, 6, 4)
    cs = ConstraintSystem(geom, 1 << 10)
    a = cs.alloc_variable_with_value(1)
    b = cs.alloc_variable_with_value(2)
    per_row = FmaGate.instance().num_repetitions(geom)
    for _ in range(((1 << 10) - 8) * per_row):
        a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
    PublicInputGate.place(cs, b)
    asm = cs.into_assembly()
    cfg = ProofConfig(
        fri_lde_factor=2,
        merkle_tree_cap_size=4,
        num_queries=4,
        fri_final_degree=16,
    )
    specs = enumerate_kernels(asm, cfg, mesh_shape=(2, 4))
    names = [s.name for s in specs]
    for want in (
        "wit:mono_sm", "wit:lde_pivot_leaf_sm", "coset_eval_wit_sm",
        "coset_sweep_terms_sm", "deep_codeword_sm",
    ):
        assert want in names, names
    assert any(n.startswith("fri_leaf_k") and n.endswith("_sm")
               for n in names), names
    assert any(n.startswith("fri_fold_k") and n.endswith("_sm")
               for n in names), names
    # only the dispatched variant: the meshless twins must be absent
    assert "coset_sweep_terms" not in names
    assert "coset_eval_wit" not in names
    assert not any(
        n.startswith("fri_commit_k") for n in names
    ), "meshless FRI commit enumerated alongside the sm one"
    assert "deep_combine" not in names
    assert "node_layers" not in names

    ledger = CompileLedger()
    precompile(asm, cfg, ledger=ledger, lower_only=True, mesh_shape=(2, 4))
    by_name = {e["name"]: e for e in ledger.entries}
    for name in names:
        assert name in by_name, name
        assert "error" not in by_name[name], by_name[name]

    # meshless enumeration is untouched: no _sm names
    names0 = [s.name for s in enumerate_kernels(asm, cfg)]
    assert not any(n.endswith("_sm") for n in names0)


# ---------------------------------------------------------------------------
# Pre-split monolithic forms, kept verbatim as parity oracles
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnums=(1, 2))
def _presplit_commit(values, L: int, cap: int):
    """The round-3 `_commit_fused` materialized path, one graph."""
    from boojum_tpu.merkle import _tree_layers

    mono = monomial_from_values(values)
    lde = lde_from_monomial(mono, L)
    B = lde.shape[0]
    return mono, lde, _tree_layers(lde.reshape(B, -1).T, cap)


@partial(jax.jit, static_argnums=(6,))
def _presplit_chunk_num_den(copy_vals, sigma_vals, ks, xs, b, g, chunks):
    """The fully unrolled `_all_chunk_num_den` (pre-scan form)."""
    nums0, nums1, dens0, dens1 = [], [], [], []
    for chunk in chunks:
        num_p = den_p = None
        for col in chunk:
            w = copy_vals[col]
            kx = gf.mul(xs, ks[col])
            num = (
                gf.add(gf.add(w, gf.mul(kx, b[0])), g[0]),
                gf.add(gf.mul(kx, b[1]), g[1]),
            )
            s = sigma_vals[col]
            den = (
                gf.add(gf.add(w, gf.mul(s, b[0])), g[0]),
                gf.add(gf.mul(s, b[1]), g[1]),
            )
            num_p = num if num_p is None else ext_f.mul(num_p, num)
            den_p = den if den_p is None else ext_f.mul(den_p, den)
        nums0.append(num_p[0])
        nums1.append(num_p[1])
        dens0.append(den_p[0])
        dens1.append(den_p[1])
    return (
        (jnp.stack(nums0), jnp.stack(nums1)),
        (jnp.stack(dens0), jnp.stack(dens1)),
    )


def _rand(rng, *shape):
    return jnp.asarray(rng.integers(0, gl.P, shape, dtype=np.uint64))


def test_commit_pipeline_parity_vs_presplit():
    from boojum_tpu.prover.prover import _commit_pipeline

    rng = np.random.default_rng(7)
    values = _rand(rng, 10, 1 << 8)
    mono_ref, lde_ref, layers_ref = _presplit_commit(values, 4, 4)
    mono, lde, layers = _commit_pipeline(values, 4, 4, stream=False)
    np.testing.assert_array_equal(np.asarray(mono_ref), np.asarray(mono))
    np.testing.assert_array_equal(np.asarray(lde_ref), np.asarray(lde))
    assert len(layers_ref) == len(layers)
    for a, b in zip(layers_ref, layers):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_streamed_digest_blocks_parity():
    """Block-dispatched streamed digests == the traceable one-graph form,
    including the trailing-partial-chunk sponge padding (B % 8 != 0) and
    a ragged final column block (B % COL_BLOCK != 0)."""
    from boojum_tpu.prover.streaming import (
        COL_BLOCK,
        streamed_leaf_digests,
        streamed_leaf_digests_blocks,
    )

    rng = np.random.default_rng(11)
    for B in (8, 13, COL_BLOCK + 5):
        mono = _rand(rng, B, 1 << 8)
        ref = streamed_leaf_digests(mono, 2)
        got = streamed_leaf_digests_blocks(mono, 2)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_stream_commit_pipeline_parity_vs_presplit():
    from boojum_tpu.prover.prover import _commit_pipeline

    rng = np.random.default_rng(13)
    values = _rand(rng, 9, 1 << 8)
    _mono_ref, _lde_ref, layers_ref = _presplit_commit(values, 4, 4)
    mono, lde, layers = _commit_pipeline(values, 4, 4, stream=True)
    assert lde is None  # streamed mode never materializes the storage
    assert len(layers_ref) == len(layers)
    for a, b in zip(layers_ref, layers):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_chunk_num_den_scan_parity_vs_presplit():
    from boojum_tpu.prover.stages import _all_chunk_num_den, chunk_columns

    rng = np.random.default_rng(3)
    n = 1 << 8
    for C, deg in ((18, 7), (8, 4), (5, 7), (7, 7)):
        cv, sv = _rand(rng, C, n), _rand(rng, C, n)
        ks = _rand(rng, C)
        xs = _rand(rng, n)
        b = (jnp.uint64(3), jnp.uint64(5))
        g = (jnp.uint64(7), jnp.uint64(11))
        chunks = tuple(tuple(c) for c in chunk_columns(C, deg))
        ref = _presplit_chunk_num_den(cv, sv, ks, xs, b, g, chunks)
        got = _all_chunk_num_den(cv, sv, ks, xs, b, g, chunks)
        for i in range(2):
            for j in range(2):
                np.testing.assert_array_equal(
                    np.asarray(ref[i][j]), np.asarray(got[i][j])
                )


def test_prove_round_outputs_match_presplit_2pow10():
    """End-to-end: the split prover's round-1 commitment on a real 2^10
    circuit equals the PRE-SPLIT monolithic commit graph applied to the
    same witness columns — the proof's witness cap is a round output, so
    this pins the whole split pipeline (iNTT -> LDE -> leaf sponge -> node
    stack) against the fused original on proof bytes, not just arrays."""
    from boojum_tpu.cs.gates import FmaGate, PublicInputGate

    geom = CSGeometry(8, 0, 6, 4)
    cs = ConstraintSystem(geom, 1 << 10)
    a = cs.alloc_variable_with_value(1)
    b = cs.alloc_variable_with_value(2)
    per_row = FmaGate.instance().num_repetitions(geom)
    for _ in range(((1 << 10) - 8) * per_row):
        a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
    PublicInputGate.place(cs, b)
    asm = cs.into_assembly()
    assert asm.trace_len == 1 << 10
    # smallest honest config (L=2, few queries, shallow FRI): the parity
    # claim is about commit bytes, not proof strength, and the tier-1
    # suite's compile budget is tight on XLA:CPU
    cfg = ProofConfig(
        fri_lde_factor=2,
        merkle_tree_cap_size=4,
        num_queries=4,
        fri_final_degree=16,
    )
    setup = generate_setup(asm, cfg)
    proof = prove(asm, setup, cfg)
    # no lookups / witness columns in this geometry: the committed stack
    # is exactly the copy columns (prover._upload_witness)
    wit = jnp.asarray(np.asarray(asm.copy_cols_values))
    _mono, _lde, layers = _presplit_commit(
        wit, cfg.fri_lde_factor, cfg.merkle_tree_cap_size
    )
    presplit_cap = [
        tuple(int(x) for x in row) for row in np.asarray(layers[-1])
    ]
    assert [tuple(c) for c in proof.witness_cap] == presplit_cap
