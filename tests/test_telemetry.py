"""Live telemetry plane tests (ISSUE 9): background sampler, stdlib
HTTP endpoints (/metrics /healthz /slo), on-demand jax.profiler capture
(BOOJUM_TPU_XPROF), report schema 2 `telemetry` records, the module-
level-state guard over utils/, and the service e2e with the plane up.

Everything here runs on the virtual 8-device CPU mesh; the only tests
paying a real prove are the service e2e ones (2^10, cache-warm)."""

import io
import json
import os
import re
import subprocess
import sys
import time
import tokenize
import urllib.request

import pytest

from boojum_tpu.utils import metrics, profiling, report, telemetry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Sampler
# ---------------------------------------------------------------------------


def test_sampler_time_series_gauges_and_providers():
    s = telemetry.TelemetrySampler(interval_s=0.05)
    s.add_provider("service.queue.depth", lambda: 7)
    s.add_provider(
        "service.queue.lane", lambda: {"interactive": 1, "batch": 2}
    )
    s.add_provider("broken", lambda: 1 / 0)
    s.add_provider("junk", lambda: {"state": None})  # unconvertible value
    first = s.sample_once()
    s.add_provider("service.queue.depth", lambda: 3)  # re-register wins
    second = s.sample_once()
    # built-in census + provider values, flat and numeric
    assert first["live_arrays"] >= 0 and first["live_bytes"] >= 0
    assert first["service.queue.depth"] == 7
    assert second["service.queue.depth"] == 3
    assert first["service.queue.lane.interactive"] == 1
    assert "broken" not in first
    assert "junk.state" not in first  # junk VALUES are skipped too, not
    #                                   just raising providers
    assert s.provider_errors == 4  # 2 samples x (broken + junk)
    # current-value + high-water gauges on the sampler's registry
    g = s.registry.to_dict()["gauges"]
    assert g["telemetry.service.queue.depth"] == 3
    assert g["telemetry.service.queue.depth_high_water"] == 7
    assert s.registry.to_dict()["counters"]["telemetry.provider_errors"] == 4
    # snapshot = the report-line `telemetry` record, and it validates
    snap = s.snapshot()
    assert snap["interval_s"] == 0.05 and snap["ticks"] == 2
    assert [x["t_s"] for x in snap["samples"]] == sorted(
        x["t_s"] for x in snap["samples"]
    )
    line = {
        "kind": report.REPORT_KIND, "schema": report.REPORT_SCHEMA,
        "wall_s": 0.1, "spans": [], "metrics": {"counters": {}},
        "checkpoints": [], "telemetry": snap,
    }
    assert report.validate_report(line) == []
    # series view for one key
    assert [v for _t, v in s.series("service.queue.depth")] == [7, 3]


def test_sampler_background_thread_ticks_and_stops():
    s = telemetry.TelemetrySampler(interval_s=0.02)
    s.start()
    try:
        deadline = time.time() + 5.0
        while s.ticks < 3 and time.time() < deadline:
            time.sleep(0.02)
        assert s.ticks >= 3
        assert s.running()
    finally:
        s.stop()
    assert not s.running()
    ticks = s.ticks
    time.sleep(0.08)
    assert s.ticks == ticks  # really stopped


def test_sampler_interval_env_and_validation(monkeypatch):
    monkeypatch.setenv("BOOJUM_TPU_TELEMETRY_INTERVAL", "0.25")
    assert telemetry.telemetry_interval_s() == 0.25
    assert telemetry.TelemetrySampler().interval_s == 0.25
    monkeypatch.setenv("BOOJUM_TPU_TELEMETRY_INTERVAL", "0")
    with pytest.raises(ValueError, match="must be > 0"):
        telemetry.telemetry_interval_s()
    monkeypatch.delenv("BOOJUM_TPU_TELEMETRY_INTERVAL")
    assert telemetry.telemetry_interval_s() == telemetry.DEFAULT_INTERVAL_S


def test_installed_sampler_rides_report_lines():
    s = telemetry.TelemetrySampler(interval_s=0.05)
    s.sample_once()
    prev = telemetry.install_sampler(s)
    try:
        with report.flight_recording(label="with_telemetry") as rec:
            metrics.count("x")
        line = report.build_report(rec)
    finally:
        telemetry.install_sampler(prev)
    assert line["schema"] == report.REPORT_SCHEMA
    assert line["telemetry"]["ticks"] == 1
    assert report.validate_report(line) == []
    # without a sampler, no record (and schema-1 lines stay valid)
    with report.flight_recording(label="bare") as rec:
        pass
    assert "telemetry" not in report.build_report(rec)


# ---------------------------------------------------------------------------
# HTTP plane
# ---------------------------------------------------------------------------


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read().decode()


def test_prometheus_text_rendering():
    from boojum_tpu.service.http_metrics import prometheus_text

    text = prometheus_text(
        {
            "counters": {"service.queue.rejects": 2},
            "gauges": {
                "telemetry.service.queue.depth": 5.0,
                "bad value": float("nan"),
            },
        }
    )
    assert "# TYPE boojum_tpu_service_queue_rejects counter" in text
    assert "boojum_tpu_service_queue_rejects 2" in text
    assert "boojum_tpu_telemetry_service_queue_depth 5.0" in text
    assert "nan" not in text  # NaN readings are dropped, not exported
    assert prometheus_text({}) == "\n"


def test_metrics_plane_endpoints():
    from boojum_tpu.service.http_metrics import MetricsPlane

    s = telemetry.TelemetrySampler(interval_s=0.05)
    s.add_provider("service.queue.depth", lambda: 4)
    s.sample_once()
    plane = MetricsPlane(
        s,
        health_fn=lambda: {"served": 9},
        slo_fn=lambda: {"requests": 1, "proofs_per_sec": 2.5},
        port=0,
    )
    port = plane.start()
    try:
        assert port > 0
        status, ctype, body = _get(plane.url("/metrics"))
        assert status == 200 and "text/plain" in ctype
        assert "boojum_tpu_telemetry_service_queue_depth 4.0" in body
        assert "boojum_tpu_telemetry_live_bytes" in body
        status, ctype, body = _get(plane.url("/healthz"))
        health = json.loads(body)
        assert status == 200 and health["status"] == "ok"
        assert health["served"] == 9 and health["telemetry_ticks"] == 1
        status, _ctype, body = _get(plane.url("/slo"))
        assert status == 200 and json.loads(body)["proofs_per_sec"] == 2.5
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(plane.url("/nonsense"))
        assert exc.value.code == 404
    finally:
        plane.stop()
    # stopped: the port no longer accepts
    with pytest.raises(Exception):
        _get(plane.url("/healthz"), timeout=2)


def test_metrics_plane_survives_callback_failure():
    from boojum_tpu.service.http_metrics import MetricsPlane

    s = telemetry.TelemetrySampler(interval_s=0.05)
    plane = MetricsPlane(
        s, health_fn=lambda: 1 / 0, slo_fn=lambda: 1 / 0, port=0
    )
    plane.start()
    try:
        status, _c, body = _get(plane.url("/healthz"))
        assert status == 200
        assert "health_fn_error" in json.loads(body)
        status, _c, body = _get(plane.url("/slo"))
        assert status == 200 and "error" in json.loads(body)
    finally:
        plane.stop()


# ---------------------------------------------------------------------------
# On-demand jax.profiler capture (BOOJUM_TPU_XPROF)
# ---------------------------------------------------------------------------


def test_xprof_spec_parsing():
    assert profiling._parse_xprof("/tmp/x") == ("/tmp/x", 1)
    assert profiling._parse_xprof("/tmp/x:3") == ("/tmp/x", 3)
    assert profiling._parse_xprof("/tmp/x:0") == ("/tmp/x", 0)
    # a non-numeric tail is part of the path, not a budget
    assert profiling._parse_xprof("rel:dir") == ("rel:dir", 1)


def test_xprof_budget_captures_next_n_proves(tmp_path, monkeypatch):
    import jax.numpy as jnp

    xdir = str(tmp_path / "traces")
    monkeypatch.setenv("BOOJUM_TPU_XPROF", f"{xdir}:2")
    assert profiling.xprof_remaining() == 2
    dirs = []
    for i in range(3):
        with profiling.maybe_trace_capture(f"unit_{i}") as td:
            if td is not None:
                jnp.zeros(8).block_until_ready()
            dirs.append(td)
    # exactly N=2 captures, each into its own labeled subdirectory
    assert dirs[2] is None
    assert dirs[0] != dirs[1]
    for td in dirs[:2]:
        assert td is not None and td.startswith(xdir)
        assert os.path.isdir(td)
    assert profiling.xprof_remaining() == 0
    # forced capture (the service's per-request flag) ignores the spent
    # budget and still lands under the armed dir
    with profiling.maybe_trace_capture("forced", force=True) as td:
        assert td is not None and td.startswith(xdir)
        jnp.zeros(8).block_until_ready()
    # ...and a forced capture never BURNS an armed budget: the budget
    # is for the next N un-flagged proves
    monkeypatch.setenv("BOOJUM_TPU_XPROF", f"{xdir}-rearm:1")
    assert profiling.xprof_remaining() == 1
    with profiling.maybe_trace_capture("forced2", force=True) as td:
        assert td is not None
    assert profiling.xprof_remaining() == 1
    # CHANGING the env re-arms; re-exporting the same value does not
    monkeypatch.setenv("BOOJUM_TPU_XPROF", f"{xdir}:1")
    assert profiling.xprof_remaining() == 1
    monkeypatch.delenv("BOOJUM_TPU_XPROF")
    assert profiling.xprof_remaining() == 0


def test_xprof_failed_start_refunds_budget(tmp_path, monkeypatch):
    """A transient start_trace failure must not eat the armed budget —
    the operator asked for N captures and should still get them."""
    import jax

    monkeypatch.setenv("BOOJUM_TPU_XPROF", f"{tmp_path / 'refund'}:1")
    assert profiling.xprof_remaining() == 1

    def boom(*a, **k):
        raise RuntimeError("profiler busy")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    with profiling.maybe_trace_capture("failing") as td:
        assert td is None
    assert profiling.xprof_remaining() == 1  # refunded
    monkeypatch.undo()
    monkeypatch.setenv("BOOJUM_TPU_XPROF", f"{tmp_path / 'refund'}:1")
    with profiling.maybe_trace_capture("retry") as td:
        assert td is not None
    assert profiling.xprof_remaining() == 0
    monkeypatch.delenv("BOOJUM_TPU_XPROF")
    profiling.xprof_remaining()


def test_xprof_no_nested_capture(tmp_path, monkeypatch):
    monkeypatch.setenv("BOOJUM_TPU_XPROF", f"{tmp_path / 't'}:5")
    profiling.xprof_remaining()  # refresh budget from env
    with profiling.maybe_trace_capture("outer") as outer:
        assert outer is not None
        # a packed sibling / inner prove() must not double-capture
        with profiling.maybe_trace_capture("inner") as inner:
            assert inner is None
        with profiling.maybe_trace_capture("inner_forced", force=True) as f:
            assert f is None
    monkeypatch.delenv("BOOJUM_TPU_XPROF")
    profiling.xprof_remaining()


# ---------------------------------------------------------------------------
# Service report lines carry the SERVICE's time series
# ---------------------------------------------------------------------------


def test_request_lines_use_service_sampler_not_foreign_global(
    eight_devices, tmp_path, monkeypatch
):
    """bench.py --service installs its own (provider-less) sampler in
    the process-global slot BEFORE the service exists; the per-request
    lines must still carry the service sampler's queue/lane/in-flight
    axes, not the foreign sampler's bare census."""
    from boojum_tpu.service import ProvingService, ServiceConfig
    from boojum_tpu.service.scheduler import Placement

    foreign = telemetry.TelemetrySampler(interval_s=9.0)
    foreign.sample_once()
    prev = telemetry.install_sampler(foreign)
    try:
        rpt = str(tmp_path / "svc.jsonl")
        svc = ProvingService(
            ServiceConfig(precompile="off", report_path=rpt,
                          telemetry_interval_s=7.0)
        )
        svc.sampler.sample_once()

        def fake_run(req, placement, packed=1, device=None):
            req.slo = {
                "id": req.id, "bucket": req.bucket_key,
                "placement": placement.kind,
                "queue_latency_s": 0.0, "prove_wall_s": 0.01,
            }
            req._done.set()
            return 1

        monkeypatch.setattr(svc, "_run_request", fake_run)
        req = svc.submit(*_parts_small())
        svc.queue.pop_batch()
        placement = Placement("proof_parallel", None, total_devices=8)
        assert svc._serve_one(req, placement) == 1
    finally:
        telemetry.install_sampler(prev)
    (line,) = report.load_reports(rpt)
    sample_keys = {
        k for s in line["telemetry"]["samples"] for k in s
    }
    assert "service.queue.depth" in sample_keys
    assert line["telemetry"]["interval_s"] == 7.0  # the service's, not 9.0
    assert report.validate_report(line) == []


# ---------------------------------------------------------------------------
# Guard: no new module-level mutable collector state in utils/
# ---------------------------------------------------------------------------


def test_no_module_level_mutable_collector_state_in_utils():
    """CI satellite (ISSUE 9): the scoping refactor holds only while
    utils/ keeps ALL mutable collector state inside instances resolved
    through the contextvar-first accessors. A new module-level mutable
    collector (list/dict/set/deque/registry at import scope) reopens
    the packed-recording corruption — fail it at review time."""
    utils_dir = os.path.join(REPO_ROOT, "boojum_tpu", "utils")
    assign = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*(?:\s*:[^=]+)?\s*=\s*(.+)$")
    mutable = re.compile(
        r"(\[\s*\]|\{\s*\}|\bset\(\s*\)|\bdeque\(|\blist\(\s*\)"
        r"|\bdict\(\s*\)|\bOrderedDict\(|Registry\(\s*\)"
        r"|SpanRecorder\(|CheckpointLog\(|FlightRecorder\("
        r"|TelemetrySampler\()"
    )
    offenders = []
    for fname in sorted(os.listdir(utils_dir)):
        if not fname.endswith(".py"):
            continue
        path = os.path.join(utils_dir, fname)
        with open(path) as f:
            src = f.read()
        # strings/comments (docstring examples) must not false-positive
        code_starts = set()
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type not in (
                tokenize.STRING, tokenize.COMMENT, tokenize.NL,
                tokenize.NEWLINE, tokenize.INDENT, tokenize.DEDENT,
            ):
                code_starts.add(tok.start[0])
        for lineno, line in enumerate(src.splitlines(), 1):
            if lineno not in code_starts or line[:1] in (" ", "\t"):
                continue
            m = assign.match(line)
            if not m:
                continue
            rhs = m.group(1)
            if "ContextVar(" in rhs:  # the sanctioned scoping mechanism
                continue
            if mutable.search(rhs):
                offenders.append(f"{fname}:{lineno}: {line.strip()}")
    assert not offenders, (
        "module-level mutable collector state in utils/ (must live in "
        "instances behind the contextvar-first accessors):\n"
        + "\n".join(offenders)
    )


# ---------------------------------------------------------------------------
# prove_report CLI: --slo with zero request records, telemetry --check
# ---------------------------------------------------------------------------


def _cli():
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import prove_report
    finally:
        sys.path.pop(0)
    return prove_report


def _plain_line():
    return {
        "kind": report.REPORT_KIND, "schema": report.REPORT_SCHEMA,
        "label": "bench_rep0", "wall_s": 1.0, "spans": [],
        "metrics": {"counters": {}}, "checkpoints": [],
    }


def test_slo_with_zero_request_records_exits_zero(tmp_path, capsys):
    """Satellite (ISSUE 9): --slo on an artifact of plain proves (no
    `request` records) has no serving span to divide over — that is an
    explicit message and exit 0, not a crash or a failure."""
    path = str(tmp_path / "plain.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(_plain_line()) + "\n")
        f.write(json.dumps(_plain_line()) + "\n")
    rc = _cli().main(["--slo", path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "no serving span" in out
    assert "0 request records in 2 line(s)" in out
    # the library-level aggregation is also total on empty input
    summary = report.slo_summary([_plain_line()])
    assert summary["requests"] == 0 and summary["proofs_per_sec"] is None


def test_check_validates_telemetry_record(tmp_path, capsys):
    good = dict(_plain_line())
    s = telemetry.TelemetrySampler(interval_s=0.05)
    s.sample_once()
    good["telemetry"] = s.snapshot()
    bad = dict(_plain_line())
    bad["telemetry"] = {
        "interval_s": -1,
        "ticks": 1,
        "samples": [{"t_s": 2.0}, {"t_s": 1.0, "live_bytes": -5}],
    }
    path = str(tmp_path / "mixed.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(good) + "\n")
        f.write(json.dumps(bad) + "\n")
    rc = _cli().main(["--check", path])
    out = capsys.readouterr().out
    assert rc == 1
    assert "line 0" in out and "ok" in out
    assert "interval_s" in out and "decreases" in out and "live_bytes" in out


# ---------------------------------------------------------------------------
# Service e2e: the live plane around real proves (cache-warm 2^10)
# ---------------------------------------------------------------------------


def _parts_small():
    from test_limb_sweep import _small_prove_parts

    return _small_prove_parts()


@pytest.fixture
def eight_devices():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")


def test_service_worker_loop_serves_live_plane(eight_devices, tmp_path):
    """E2E acceptance slice: a service with the telemetry plane up
    serves real requests; /metrics during the queued phase shows
    service.queue depth + device-memory/census gauges, the report lines
    carry `telemetry` records and pass --check IN A SUBPROCESS, and
    /slo reflects the drained batch."""
    from boojum_tpu.service import ProvingService, ServiceConfig

    asm, setup, cfg = _parts_small()
    rpt = str(tmp_path / "svc.jsonl")
    svc = ProvingService(
        ServiceConfig(
            precompile="off", report_path=rpt,
            telemetry_interval_s=0.1, metrics_port=0,
        )
    )
    port = svc.start_telemetry(0)
    try:
        reqs = [svc.submit(asm, setup, cfg) for _ in range(2)]
        svc.sampler.sample_once()  # deterministic queued-phase sample
        _status, _ctype, body = _get(f"http://127.0.0.1:{port}/metrics")
        assert "boojum_tpu_telemetry_service_queue_depth 2.0" in body
        assert "boojum_tpu_telemetry_live_bytes" in body
        assert "boojum_tpu_telemetry_service_inflight" in body
        summary = svc.run_worker()
        assert summary["served"] == 2
        # run_worker leaves the caller-started plane running
        _status, _c, body = _get(f"http://127.0.0.1:{port}/healthz")
        health = json.loads(body)
        assert health["served"] == 2 and health["queue_depth"] == 0
        _status, _c, body = _get(f"http://127.0.0.1:{port}/slo")
        slo = json.loads(body)
        assert slo["requests"] == 2 and slo["served"] == 2
        for r in reqs:
            r.result()
    finally:
        svc.stop_telemetry()
    assert not svc.sampler.running()

    lines = report.load_reports(rpt)
    req_lines = [ln for ln in lines if "request" in ln]
    assert len(req_lines) == 2
    for ln in req_lines:
        assert ln["schema"] == report.REPORT_SCHEMA
        assert ln["telemetry"]["ticks"] >= 1
        assert report.validate_report(ln) == [], ln["request"]["id"]
    # the satellite's tier-1 gate: --check the freshly generated
    # artifact in a SUBPROCESS (stdlib-only CLI, no jax import)
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "prove_report.py"),
            "--check", rpt,
        ],
        capture_output=True, text=True, timeout=120,
        env={k: v for k, v in os.environ.items() if k != "PYTHONSTARTUP"},
    )
    assert out.returncode == 0, out.stdout + out.stderr
    slo_out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "prove_report.py"),
            "--slo", rpt,
        ],
        capture_output=True, text=True, timeout=120,
        env={k: v for k, v in os.environ.items() if k != "PYTHONSTARTUP"},
    )
    assert slo_out.returncode == 0
    assert "proofs/sec" in slo_out.stdout


def test_service_capture_trace_per_request(eight_devices, tmp_path):
    """The per-request capture_trace flag records a jax.profiler trace
    attributable to exactly that request (trace record in ITS line)."""
    from boojum_tpu.service import ProvingService, ServiceConfig

    asm, setup, cfg = _parts_small()
    rpt = str(tmp_path / "trace.jsonl")
    os.environ["BOOJUM_TPU_XPROF"] = str(tmp_path / "xprof")
    try:
        profiling.xprof_remaining()  # refresh: arms budget=1
        os.environ.pop("BOOJUM_TPU_XPROF")
        profiling.xprof_remaining()  # disarm again: force flag only
        svc = ProvingService(
            ServiceConfig(precompile="off", report_path=rpt,
                          telemetry_interval_s=5.0)
        )
        r_traced = svc.submit(asm, setup, cfg, capture_trace=True)
        r_plain = svc.submit(asm, setup, cfg)
        summary = svc.run_worker()
        assert summary["served"] == 2
    finally:
        os.environ.pop("BOOJUM_TPU_XPROF", None)
    assert "trace_dir" in r_traced.slo
    assert os.path.isdir(r_traced.slo["trace_dir"])
    assert "trace_dir" not in r_plain.slo
    by_id = {
        ln["request"]["id"]: ln
        for ln in report.load_reports(rpt) if "request" in ln
    }
    traced_line = by_id[r_traced.id]
    assert traced_line["trace"]["dir"] == r_traced.slo["trace_dir"]
    assert "trace" not in by_id[r_plain.id]
    assert report.validate_report(traced_line) == []
