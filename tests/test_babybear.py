"""BabyBear field backend (ISSUE 19): one u32 lane = one field element.

The tentpole swaps the limb-plane machinery for a plane-free kernel set
when BOOJUM_TPU_FIELD=babybear: p = 2^31 - 2^27 + 1 fits a single u32
lane, so every HBM-bound kernel moves HALF the bytes of its Goldilocks
(lo, hi)-plane twin and no split/join conversion exists anywhere. These
tests pin:

- field ops: scalar / numpy / device parity on random AND near-p
  boundary values; inverse/pow identities; the GF(p^4) = GF(p)[w]/(w^4
  - 11) extension tower (w^4 = 11, mul assoc/commute, ext_inv * x = 1,
  Frobenius-based device inverse == scalar inverse);
- NTT/LDE: device transforms match the numpy reference twins and
  round-trip;
- the 2^10 mini-STARK e2e: device prove accepted by its own verifier,
  Fiat-Shamir checkpoint stream DETERMINISTIC across runs and
  bit-identical between the device and NumPy-reference backends, the
  verifier actually rejecting a corrupted proof;
- ZERO limb.splits / limb.joins during a BabyBear prove (there are no
  planes to convert) while the `_bb` kernel counters move;
- the dispatcher: `enumerate_kernels` emits the `_bb` set under the env
  var (and never otherwise), the set lowers on CPU via
  `precompile(lower_only=True)`, limb residency is vetoed, the shape
  bucket key / AOT variant fingerprint carry the field, and the
  Goldilocks key stays byte-identical with the env unset;
- the cost model: `_bb` kernels are costed at elem_bytes=4 — exactly
  half the HBM bytes of the same-geometry Goldilocks kernel (the >= 2x
  byte-reduction claim, pinned per family) — and the report validator
  REJECTS a line claiming field=babybear while counting limb
  conversions.
"""

import functools
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from boojum_tpu.field import babybear as bb
from boojum_tpu.field.spec import BABYBEAR, GOLDILOCKS

# near-p boundary values: wraparound coverage for every binary op
EDGE = [0, 1, 2, bb.P - 1, bb.P - 2, bb.P // 2, (1 << 27), (1 << 27) - 1]


def _rng(seed=0):
    return np.random.default_rng(seed)


def _rand_vals(k=64, seed=0):
    return [int(v) for v in _rng(seed).integers(0, bb.P, k)] + EDGE


# ---------------------------------------------------------------------------
# Base field ops: scalar / numpy / device parity + identities
# ---------------------------------------------------------------------------


def test_spec_constants():
    assert bb.P == 2013265921 == (1 << 31) - (1 << 27) + 1
    assert BABYBEAR.two_adicity == 27
    assert BABYBEAR.half == (bb.P + 1) // 2
    assert pow(BABYBEAR.radix2_subgroup_generator, 1 << 27, bb.P) == 1
    assert pow(BABYBEAR.radix2_subgroup_generator, 1 << 26, bb.P) != 1
    # one u32 lane per element vs the Goldilocks 64-bit element
    assert BABYBEAR.elem_bytes == 4 and GOLDILOCKS.elem_bytes == 8
    # report.py re-declares the backend names (standalone-load rule,
    # like its id-format regexes) — keep them in lockstep with SPECS
    from boojum_tpu.field.spec import SPECS
    from boojum_tpu.utils.report import FIELD_NAMES

    assert set(FIELD_NAMES) == set(SPECS)


def test_scalar_numpy_device_parity():
    import jax.numpy as jnp

    vals = _rand_vals(seed=1)
    a = np.array(vals, dtype=np.uint32)
    b = np.array(list(reversed(vals)), dtype=np.uint32)
    aj, bj = jnp.asarray(a), jnp.asarray(b)
    for name, s_fn, np_fn, dev_fn in [
        ("add", bb.add_s, bb.add_np, bb.add),
        ("sub", bb.sub_s, bb.sub_np, bb.sub),
        ("mul", bb.mul_s, bb.mul_np, bb.mul),
    ]:
        want = np.array(
            [s_fn(int(x), int(y)) for x, y in zip(a, b)], dtype=np.uint32
        )
        assert np.array_equal(np_fn(a, b), want), name
        assert np.array_equal(np.asarray(dev_fn(aj, bj)), want), name
    want_neg = np.array([bb.neg_s(int(x)) for x in a], dtype=np.uint32)
    assert np.array_equal(np.asarray(bb.neg(aj)), want_neg)
    want_sqr = np.array([bb.mul_s(int(x), int(x)) for x in a], np.uint32)
    assert np.array_equal(np.asarray(bb.sqr(aj)), want_sqr)


def test_inverse_and_pow_identities():
    import jax.numpy as jnp

    vals = [v for v in _rand_vals(seed=2) if v != 0]
    for v in vals:
        assert bb.mul_s(v, bb.inv_s(v)) == 1
        assert bb.pow_s(v, bb.P - 1) == 1
    arr = jnp.asarray(np.array(vals, dtype=np.uint32))
    inv = np.asarray(bb.batch_inverse_xla(arr))
    for v, iv in zip(vals, inv):
        assert bb.mul_s(int(v), int(iv)) == 1
    # device pow_const against scalar pow on an awkward exponent
    e = (bb.P - 3) // 5
    want = np.array([bb.pow_s(int(v), e) for v in vals], dtype=np.uint32)
    assert np.array_equal(np.asarray(bb.pow_const(arr, e)), want)


# ---------------------------------------------------------------------------
# GF(p^4) extension tower
# ---------------------------------------------------------------------------


def _rand_ext(seed):
    r = _rng(seed)
    return tuple(int(v) for v in r.integers(0, bb.P, 4))


def test_ext_tower_nonresidue():
    w = (0, 1, 0, 0)
    w2 = bb.ext_mul_s(w, w)
    w4 = bb.ext_mul_s(w2, w2)
    assert w4 == (bb.EXT_NONRESIDUE, 0, 0, 0) == (11, 0, 0, 0)


def test_ext_mul_commutes_and_associates():
    a, b, c = _rand_ext(3), _rand_ext(4), _rand_ext(5)
    assert bb.ext_mul_s(a, b) == bb.ext_mul_s(b, a)
    assert bb.ext_mul_s(bb.ext_mul_s(a, b), c) == bb.ext_mul_s(
        a, bb.ext_mul_s(b, c)
    )
    # distributes over add
    assert bb.ext_mul_s(a, bb.ext_add_s(b, c)) == bb.ext_add_s(
        bb.ext_mul_s(a, b), bb.ext_mul_s(a, c)
    )


def test_ext_inverse_scalar_and_device():
    import jax.numpy as jnp

    cases = [_rand_ext(s) for s in range(6, 14)]
    # boundary coords too: elements with near-p coordinates
    cases += [(bb.P - 1, 0, bb.P - 2, 1), (1, bb.P - 1, 0, bb.P - 1)]
    for x in cases:
        assert bb.ext_mul_s(x, bb.ext_inv_s(x)) == bb.ONE_S
    # device Frobenius/norm inverse == scalar inverse, vectorized
    arrs = tuple(
        jnp.asarray(np.array([c[k] for c in cases], np.uint32))
        for k in range(4)
    )
    inv = bb.ext_inv(arrs)
    for i, x in enumerate(cases):
        got = tuple(int(np.asarray(inv[k])[i]) for k in range(4))
        assert got == bb.ext_inv_s(x), x
    # numpy twin
    inv_np = bb.ext_inv_np(tuple(np.array([c[k] for c in cases],
                                          np.uint32) for k in range(4)))
    for i, x in enumerate(cases):
        got = tuple(int(inv_np[k][i]) for k in range(4))
        assert got == bb.ext_inv_s(x), x


def test_ext_frobenius_fixes_base():
    base = bb.ext_from_base_s(123456789)
    for k in range(1, 4):
        assert bb.ext_frobenius_s(base, k) == base
    x = _rand_ext(15)
    # frobenius^4 = identity
    y = x
    for _ in range(4):
        y = bb.ext_frobenius_s(y, 1)
    assert y == x


# ---------------------------------------------------------------------------
# NTT / LDE twins
# ---------------------------------------------------------------------------


def test_ntt_roundtrip_and_numpy_parity():
    from boojum_tpu.ntt import bb_ntt

    log_n, B = 8, 3
    n = 1 << log_n
    x = _rng(7).integers(0, bb.P, (B, n)).astype(np.uint32)
    mono_np = bb_ntt.ntt_np(x, inverse=True)
    back = bb_ntt.ntt_np(mono_np, inverse=False)
    assert np.array_equal(back, x)
    import jax.numpy as jnp

    mono_dev = np.asarray(
        bb_ntt.monomial_from_values_bb(jnp.asarray(x), log_n)
    )
    assert np.array_equal(mono_dev, mono_np)
    vals_dev = np.asarray(
        bb_ntt.values_from_monomial_bb(jnp.asarray(mono_np), log_n)
    )
    assert np.array_equal(vals_dev, x)


def test_lde_device_numpy_parity_and_pointwise():
    from boojum_tpu.ntt import bb_ntt
    import jax.numpy as jnp

    log_n, L = 6, 4
    n = 1 << log_n
    shift = BABYBEAR.multiplicative_generator
    mono = _rng(8).integers(0, bb.P, (2, n)).astype(np.uint32)
    lde_np = bb_ntt.lde_np(mono, L, shift)
    lde_dev = np.asarray(
        bb_ntt.lde_from_monomial_bb(jnp.asarray(mono), log_n, L, shift)
    )
    assert np.array_equal(lde_dev, lde_np)
    # natural-order contract: out[j] = f(shift * w_N^j)
    wN = bb.omega(log_n + 2)
    coeffs = [int(c) for c in mono[0]]
    for j in [0, 1, 5, n * L - 1]:
        xj = bb.mul_s(shift, bb.pow_s(wN, j))
        want = 0
        for i in reversed(range(n)):
            want = bb.add_s(bb.mul_s(want, xj), coeffs[i])
        assert int(lde_np[0, j]) == want, j


# ---------------------------------------------------------------------------
# 2^10 e2e: prove -> verify, checkpoint determinism, backend parity
# ---------------------------------------------------------------------------


def _checkpointed_prove(backend_factory):
    from boojum_tpu.prover.bb_prover import BBProofConfig, prove_babybear
    from boojum_tpu.utils.report import (
        CheckpointLog,
        install_checkpoint_log,
    )

    log = CheckpointLog()
    prev = install_checkpoint_log(log)
    try:
        proof = prove_babybear(
            pub=5, cfg=BBProofConfig(log_n=10),
            backend=backend_factory(),
        )
    finally:
        install_checkpoint_log(prev)
    return proof, log.entries


@functools.lru_cache(maxsize=1)
def _reference_runs():
    from boojum_tpu.compat.prove_reference_bb import NumpyBackendBB

    return (
        _checkpointed_prove(NumpyBackendBB),
        _checkpointed_prove(NumpyBackendBB),
    )


@functools.lru_cache(maxsize=1)
def _device_run():
    """ONE device-backend 2^10 prove shared by the e2e tests, recorded
    under a metrics registry (the zero-conversion guard reads it)."""
    from boojum_tpu.prover.bb_prover import DeviceBackendBB
    from boojum_tpu.utils import metrics

    reg = metrics.start_metrics()
    try:
        proof, entries = _checkpointed_prove(DeviceBackendBB)
    finally:
        metrics.stop_metrics()
    return proof, entries, reg.to_dict()


def test_e2e_device_prove_verifies():
    from boojum_tpu.prover.bb_verifier import check_babybear

    proof, _, _ = _device_run()
    ok, reason = check_babybear(proof)
    assert ok, reason


def test_e2e_reference_prove_verifies_and_is_deterministic():
    from boojum_tpu.prover.bb_verifier import check_babybear

    (p1, e1), (p2, e2) = _reference_runs()
    ok, reason = check_babybear(p1)
    assert ok, reason
    # Fiat-Shamir checkpoint stream: deterministic across runs
    assert e1 == e2
    assert [e["label"] for e in e1][:4] == [
        "bb_params", "witness_cap", "alpha", "quotient_cap",
    ]
    assert e1[-1]["label"] == "query_indices"


def test_e2e_device_matches_reference_checkpoints():
    """Backend parity by construction: the device and numpy backends
    must produce the same checkpoint stream (same transcript, same
    challenges, same committed caps) — any device-kernel divergence from
    the reference leg lands here."""
    _, dev_entries, _ = _device_run()
    (_, ref_entries), _ = _reference_runs()
    assert dev_entries == ref_entries


def test_e2e_verifier_rejects_corruption():
    import dataclasses

    from boojum_tpu.prover.bb_verifier import check_babybear

    (proof, _), _ = _reference_runs()
    bad = dataclasses.replace(
        proof,
        evals={**proof.evals, "wz": bb.ext_add_s(proof.evals["wz"],
                                                 bb.ONE_S)},
    )
    ok, _ = check_babybear(bad)
    assert not ok
    bad2 = dataclasses.replace(proof, pub=(proof.pub + 1) % bb.P)
    ok2, _ = check_babybear(bad2)
    assert not ok2


def test_zero_limb_conversions_during_bb_prove():
    """THE plane-free guard: a BabyBear prove records ZERO limb
    conversions of any kind — interior OR edge — because there are no
    (lo, hi) planes anywhere on the path; meanwhile the `_bb` kernel
    counters all moved (the guard is not vacuous)."""
    _, _, md = _device_run()
    c = md["counters"]
    for k in ("limb.splits", "limb.joins", "limb.host_splits",
              "limb.host_joins"):
        assert c.get(k, 0) == 0, (k, c)
    assert c["quotient.bb_coset_sweeps"] >= 1
    assert c["deep.bb_accumulates"] >= 1
    assert c["fri.bb_folds"] >= 6
    assert c["merkle.bb_commits"] >= 8


# ---------------------------------------------------------------------------
# Dispatcher: variant selection, lowering, cache keys
# ---------------------------------------------------------------------------


def _fma_cfg_asm():
    from boojum_tpu.cs.gates import FmaGate, PublicInputGate
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.types import CSGeometry
    from boojum_tpu.prover import ProofConfig

    geom = CSGeometry(8, 0, 6, 4)
    cs = ConstraintSystem(geom, 1 << 10)
    a = cs.alloc_variable_with_value(1)
    b = cs.alloc_variable_with_value(2)
    per_row = FmaGate.instance().num_repetitions(geom)
    for _ in range(((1 << 10) - 8) * per_row):
        a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
    PublicInputGate.place(cs, b)
    asm = cs.into_assembly()
    cfg = ProofConfig(
        fri_lde_factor=2, merkle_tree_cap_size=4,
        num_queries=4, fri_final_degree=16,
    )
    return asm, cfg


def test_dispatcher_selects_bb_set_and_vetoes_limbs(monkeypatch):
    from boojum_tpu.prover.precompile import enumerate_kernels
    from boojum_tpu.prover.pallas_sweep import limb_resident_enabled
    from boojum_tpu.prover.aot import variant_fingerprint
    from boojum_tpu.prover.shape_key import shape_bucket

    asm, cfg = _fma_cfg_asm()
    monkeypatch.delenv("BOOJUM_TPU_FIELD", raising=False)
    key_gl = shape_bucket(asm, cfg).key
    assert ":F" not in key_gl  # byte-identical pre-seam Goldilocks key
    assert variant_fingerprint()["field"] == "goldilocks"
    assert not any("_bb" in s.name for s in enumerate_kernels(asm, cfg))

    monkeypatch.setenv("BOOJUM_TPU_FIELD", "babybear")
    # even with limb residency forced on, babybear vetoes it
    monkeypatch.setenv("BOOJUM_TPU_LIMB_RESIDENT", "1")
    assert limb_resident_enabled() is False
    asm._shape_bucket_cache = {}
    assert shape_bucket(asm, cfg).key == key_gl + ":Fbabybear"
    assert variant_fingerprint()["field"] == "babybear"
    specs = enumerate_kernels(asm, cfg)
    names = [s.name for s in specs]
    assert names and all("_bb" in n for n in names)
    assert any(n.startswith("coset_sweep_terms_bb") for n in names)
    assert any(n.startswith("fri_fold_bb_k1") for n in names)
    asm._shape_bucket_cache = {}


def test_bb_enumeration_lowers_on_cpu(monkeypatch):
    from boojum_tpu.prover.precompile import enumerate_kernels, precompile

    monkeypatch.setenv("BOOJUM_TPU_FIELD", "babybear")
    asm, cfg = _fma_cfg_asm()
    asm._shape_bucket_cache = {}
    specs = enumerate_kernels(asm, cfg)
    assert all("_bb" in s.name for s in specs)
    precompile(asm, cfg, specs=specs, lower_only=True, max_workers=2)
    asm._shape_bucket_cache = {}


# ---------------------------------------------------------------------------
# Cost model: half the HBM bytes, stamped field, lying lines rejected
# ---------------------------------------------------------------------------


def test_bb_kernels_cost_half_the_hbm_bytes():
    """The perf claim, pinned analytically per family: every byte term
    of the plane-free kernels scales by elem_bytes=4 against the
    8-byte Goldilocks element — exactly 2x fewer HBM bytes for the
    same geometry (flops deliberately reuse the u64 calibration as a
    conservative upper bound, so only bytes are pinned)."""
    from boojum_tpu.utils import costmodel as cm

    for fam_gl, fam_bb in [
        (cm.ntt_cost(16, 1 << 10), cm.ntt_cost(16, 1 << 10, 4.0)),
        (cm.lde_cost(16, 1 << 10, 4), cm.lde_cost(16, 1 << 10, 4, 4.0)),
        (cm.sweep_cost(1 << 12, 8.0), cm.sweep_cost(1 << 12, 8.0, 4.0)),
        (cm.deep_cost(5, 1 << 12), cm.deep_cost(5, 1 << 12, 4.0)),
        (cm.fold_cost(1 << 12), cm.fold_cost(1 << 12, 1, 4.0)),
        (cm.binv_cost(1 << 12), cm.binv_cost(1 << 12, 4.0)),
    ]:
        assert fam_bb["hbm_bytes"] * 2 == fam_gl["hbm_bytes"]
        assert fam_bb["hbm_bytes"] > 0


def test_bb_cost_sheet_covers_enumeration(monkeypatch):
    from boojum_tpu.prover.precompile import enumerate_kernels
    from boojum_tpu.utils import costmodel as cm

    monkeypatch.setenv("BOOJUM_TPU_FIELD", "babybear")
    asm, cfg = _fma_cfg_asm()
    asm._shape_bucket_cache = {}
    specs = enumerate_kernels(asm, cfg)
    sheet = cm.cost_sheet(specs)
    assert set(sheet) == {s.name for s in specs}
    for name, ent in sheet.items():
        assert ent["hbm_bytes"] > 0, name
        assert ent["family"] not in ("fallback", "error"), name
        assert ent["field"] == "babybear", name
        assert ent["elem_bytes"] == 4, name
    asm._shape_bucket_cache = {}


def test_check_gate_rejects_babybear_lie():
    """`prove_report.py --check` FAILS a line whose cost record claims
    field=babybear while the same line counted limb conversions — the
    one thing a BabyBear prove can never do — and rejects unknown field
    names outright."""
    from boojum_tpu.utils.report import validate_report

    line = {
        "kind": "x", "schema": 0, "wall_s": 0.0,
        "cost": {"field": "babybear"},
        "metrics": {"counters": {"limb.splits": 3, "limb.joins": 0}},
    }
    probs = validate_report(line)
    assert any("claims field=babybear" in p for p in probs), probs
    line["metrics"]["counters"] = {"limb.splits": 0, "limb.joins": 0}
    assert not any(
        "claims field=babybear" in p for p in validate_report(line)
    )
    line["cost"]["field"] = "mersenne31"
    assert any("cost record field" in p for p in validate_report(line))
