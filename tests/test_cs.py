"""CS synthesis + witness resolution + satisfiability tests (gate-level test
strategy per reference testing_tools.rs harness)."""

import numpy as np

from boojum_tpu.cs.types import CSGeometry
from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.gates import (
    BooleanConstraintGate,
    ConditionalSwapGate,
    ConstantsAllocatorGate,
    DotProductGate,
    FmaGate,
    PublicInputGate,
    ReductionGate,
    ReductionByPowersGate,
    SelectionGate,
    SimpleNonlinearityGate,
    U32AddGate,
    U32FmaGate,
    U32SubGate,
    ZeroCheckGate,
)
from boojum_tpu.prover.satisfiability import check_if_satisfied
from boojum_tpu.field import gl

GEOM = CSGeometry(
    num_columns_under_copy_permutation=16,
    num_witness_columns=0,
    num_constant_columns=6,
    max_allowed_constraint_degree=4,
)


def fresh_cs(max_len=64):
    return ConstraintSystem(GEOM, max_len)


def test_fma_gate_and_resolver():
    cs = fresh_cs()
    a = cs.alloc_variable_with_value(3)
    b = cs.alloc_variable_with_value(5)
    c = cs.alloc_variable_with_value(7)
    d = FmaGate.fma(cs, a, b, c, 2, 11)
    assert cs.get_value(d) == (2 * 3 * 5 + 11 * 7) % gl.P
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_deferred_resolution_order():
    cs = fresh_cs()
    a = cs.alloc_variable_without_value()
    b = cs.alloc_variable_without_value()
    # register a resolution depending on unset inputs first
    out = cs.alloc_variable_without_value()
    cs.set_values_with_dependencies([a, b], [out], lambda v: [gl.add(v[0], v[1])])
    cs.resolver.set_value(a, 10)
    assert not cs.resolver.is_resolved(out)
    cs.resolver.set_value(b, 20)
    assert cs.get_value(out) == 30


def test_gate_zoo_satisfiable():
    cs = fresh_cs(256)
    x = cs.alloc_variable_with_value(9)
    y = cs.alloc_variable_with_value(12)
    FmaGate.fma(cs, x, y, x, 1, 1)
    five = ConstantsAllocatorGate.allocate_constant(cs, 5)
    bool_v = cs.alloc_variable_with_value(1)
    BooleanConstraintGate.enforce(cs, bool_v)
    ReductionGate.reduce(cs, [x, y, five, bool_v], [1, 2, 3, 4])
    ReductionByPowersGate.reduce(cs, [x, y, five, bool_v], 1 << 8)
    SelectionGate.select(cs, bool_v, x, y)
    ConditionalSwapGate.swap(cs, bool_v, x, y)
    DotProductGate.dot(cs, [(x, y), (x, x), (y, y), (five, x)])
    ZeroCheckGate.is_zero(cs, x)
    z0 = cs.alloc_variable_with_value(0)
    ZeroCheckGate.is_zero(cs, z0)
    SimpleNonlinearityGate.apply(cs, x, 42)
    a32 = cs.alloc_variable_with_value(0xFFFFFFFF)
    b32 = cs.alloc_variable_with_value(0x12345678)
    zero = cs.zero_var()
    U32AddGate.add(cs, a32, b32, zero)
    U32SubGate.sub(cs, b32, a32, zero)
    U32FmaGate.fma(cs, a32, b32, b32, zero)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_unsatisfied_detected():
    cs = fresh_cs()
    a = cs.alloc_variable_with_value(3)
    b = cs.alloc_variable_with_value(5)
    c = cs.alloc_variable_with_value(7)
    d = FmaGate.fma(cs, a, b, c)
    # corrupt the witness after the fact (read first: with the native tape
    # engine the value materializes lazily, and an unflushed write would be
    # overwritten by the flush)
    assert cs.get_value(d) == (3 * 5 + 7)
    cs.resolver.values[d] = 999
    asm = cs.into_assembly()
    assert not check_if_satisfied(asm)


def test_public_input():
    cs = fresh_cs()
    v = cs.alloc_variable_with_value(1234)
    PublicInputGate.place(cs, v)
    asm = cs.into_assembly()
    assert asm.public_inputs == [(0, 0, 1234)] or len(asm.public_inputs) == 1
    assert check_if_satisfied(asm)


def test_row_amortization():
    # 4 fma instances with same constants share one row (16 cols / width 4)
    cs = fresh_cs()
    for _ in range(4):
        a = cs.alloc_variable_with_value(2)
        FmaGate.fma(cs, a, a, a)
    rows_used = cs.next_row
    # one row for fma, plus zero/one constant rows if any
    fma_rows = sum(
        1
        for r in range(rows_used)
        if cs.gates[cs.row_gate[r]].name == "fma"
    )
    assert fma_rows == 1
    asm = cs.into_assembly()
    assert check_if_satisfied(asm)


def test_ext_fma_gate():
    import random

    from boojum_tpu.cs.gates.ext_fma import ExtFmaGate
    from boojum_tpu.field import extension as ext_host

    geom = CSGeometry(16, 0, 6, 4)
    cs = ConstraintSystem(geom, 64)
    rng = random.Random(3)
    a = tuple(cs.alloc_variable_with_value(rng.randrange(gl.P)) for _ in range(2))
    b = tuple(cs.alloc_variable_with_value(rng.randrange(gl.P)) for _ in range(2))
    c = tuple(cs.alloc_variable_with_value(rng.randrange(gl.P)) for _ in range(2))
    d = ExtFmaGate.fma(cs, a, b, c, coeff_ab=(2, 3), coeff_c=(5, 7))
    av = (cs.get_value(a[0]), cs.get_value(a[1]))
    bv = (cs.get_value(b[0]), cs.get_value(b[1]))
    cv = (cs.get_value(c[0]), cs.get_value(c[1]))
    expect = ext_host.add_s(
        ext_host.mul_s(ext_host.mul_s((2, 3), av), bv),
        ext_host.mul_s((5, 7), cv),
    )
    assert (cs.get_value(d[0]), cs.get_value(d[1])) == tuple(expect)
    iv = ExtFmaGate.inversion(cs, a)
    assert ext_host.mul_s(av, (cs.get_value(iv[0]), cs.get_value(iv[1]))) == (1, 0)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)
    # tamper
    asm.copy_cols_values[6, 0] = (int(asm.copy_cols_values[6, 0]) + 1) % gl.P
    assert not check_if_satisfied(asm)


def test_native_flush_with_far_waiter():
    """A python closure parked on a place beyond the arena capacity must not
    crash the native tape flush (regression: unguarded resolved[p] index)."""
    from boojum_tpu.dag import make_resolver

    r = make_resolver(capacity=16)
    out = 2
    r.add_resolution([100000], [out], lambda v: [v[0] + 1])
    r.set_value(0, 7)  # benign
    # native op -> tape; flush via get_value must not IndexError
    from boojum_tpu.native import OP_CONST, get_lib

    if get_lib() is None:
        return
    r.add_resolution([], [1], lambda _: [5], native=(OP_CONST, (5,)))
    assert r.get_value(1) == 5
    r.set_value(100000, 9)
    assert r.get_value(out) == 10


def test_native_resolver_poison_on_failed_batch():
    """A failed native batch (lookup miss) poisons the resolver: the original
    error surfaces (chained) on every later read instead of a misleading
    'place unresolved' assert."""
    import pytest

    from boojum_tpu.dag import make_resolver
    from boojum_tpu.dag.resolver import NativeTapeResolver
    from boojum_tpu.native import OP_LOOKUP
    from boojum_tpu.examples import xor4_table

    r = make_resolver(capacity=64)
    if not isinstance(r, NativeTapeResolver):
        pytest.skip("native engine unavailable")
    table = xor4_table()
    r.set_value(0, 99)  # not a valid xor4 key (keys are 0..15)
    r.set_value(1, 3)
    r.add_resolution([0, 1], [2], None, native=(OP_LOOKUP, (1,)), table=table)
    with pytest.raises(RuntimeError, match="native"):
        r.get_value(2)
    # subsequent reads surface the poisoning, chained to the root cause
    with pytest.raises(RuntimeError, match="native") as ei:
        r.get_value(2)
    assert ei.value.__cause__ is not None
    with pytest.raises(RuntimeError, match="native"):
        r.wait_till_resolved()
    with pytest.raises(RuntimeError, match="native"):
        r.values_flat(3)


def test_resolution_record_playback():
    """Record/playback of the witness-resolution order (reference
    mt/sorters/sorter_playback.rs): a recorded live run replayed through
    PlaybackResolver reproduces the identical witness with zero dependency
    tracking, and diverging synthesis is detected."""
    from boojum_tpu.cs.types import CSGeometry
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.gates import FmaGate, ZeroCheckGate
    from boojum_tpu.dag.resolver import PlaybackResolver, WitnessResolver

    geom = CSGeometry(
        num_columns_under_copy_permutation=8,
        num_witness_columns=0,
        num_constant_columns=6,
        max_allowed_constraint_degree=4,
    )

    def synthesize(cs):
        a = cs.alloc_variable_with_value(3)
        b = cs.alloc_variable_with_value(5)
        for _ in range(20):
            a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
        flag = ZeroCheckGate.is_zero(cs, b)
        return FmaGate.fma(cs, b, b, flag, 1, 1)

    rec_resolver = WitnessResolver()
    rec_resolver.start_recording()
    cs1 = ConstraintSystem(geom, 1 << 10, resolver=rec_resolver)
    out1 = synthesize(cs1)
    asm1 = cs1.into_assembly()  # padding resolutions are part of the record
    record = rec_resolver.resolution_record()
    assert record, "live run must record resolutions"

    cs2 = ConstraintSystem(
        geom, 1 << 10, resolver=PlaybackResolver(record)
    )
    out2 = synthesize(cs2)
    assert cs2.get_value(out2) == cs1.get_value(out1)
    asm2 = cs2.into_assembly()
    import numpy as np

    np.testing.assert_array_equal(asm1.copy_cols_values, asm2.copy_cols_values)

    # diverging synthesis (extra resolutions) must be detected
    cs3 = ConstraintSystem(geom, 1 << 10, resolver=PlaybackResolver(record))
    synthesize(cs3)
    cs3.alloc_variable_with_value(7)
    synthesize(cs3)  # registers resolutions beyond the record
    import pytest

    with pytest.raises(RuntimeError, match="playback divergence"):
        cs3.resolver.wait_till_resolved()


def test_bounded_gate_wrapper():
    """Row-capped placement (reference BoundedGateWrapper / Bounded*
    allocator variants): instances amortize into rows normally, and the
    wrapper rejects placements beyond the row budget."""
    import pytest

    from boojum_tpu.cs.gates import BoundedGateWrapper, FmaGate

    cs = fresh_cs(64)
    bounded = BoundedGateWrapper(FmaGate.instance(), max_rows=2)
    per_row = FmaGate.instance().num_repetitions(GEOM)
    for _ in range(2 * per_row):  # exactly fills the budget
        a = cs.alloc_variable_with_value(2)
        b = cs.alloc_variable_with_value(3)
        c = cs.alloc_variable_with_value(4)
        d = cs.alloc_variable_without_value()
        cs.set_values_with_dependencies(
            [a, b, c], [d], lambda v: [(v[0] * v[1] + v[2]) % gl.P]
        )
        bounded.place(cs, [a, b, c, d], (1, 1))
    # the budget is exactly full: the next placement would open a third
    # row and must be refused BEFORE the CS is mutated
    rows_before = cs.next_row
    a = cs.alloc_variable_with_value(5)
    d = cs.alloc_variable_without_value()
    cs.set_values_with_dependencies(
        [a], [d], lambda v: [(v[0] * v[0] + v[0]) % gl.P]
    )
    with pytest.raises(RuntimeError, match="row budget"):
        bounded.place(cs, [a, a, a, d], (1, 1))
    assert cs.next_row == rows_before  # nothing was placed
    assert check_if_satisfied(cs.into_assembly(), verbose=True)


def test_explicit_constants_allocator_gate():
    """ExplicitConstantsAllocatorGate (reference
    constants_allocator_as_explicit_constraint.rs): allocates 0/1/-1 plus a
    set as baked-literal constraints with ZERO constant columns; proves
    e2e and rejects a tampered constant."""
    from boojum_tpu.cs.gates import (
        ExplicitConstantsAllocatorGate,
        FmaGate,
        PublicInputGate,
    )
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.types import CSGeometry
    from boojum_tpu.field import gl
    from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify
    from boojum_tpu.prover.satisfiability import check_if_satisfied

    geom = CSGeometry(8, 0, 6, 4)
    cs = ConstraintSystem(geom, 1 << 10)
    table = ExplicitConstantsAllocatorGate.allocate(cs, (5, 1 << 32))
    assert cs.get_value(table[0]) == 0
    assert cs.get_value(table[1]) == 1
    assert cs.get_value(table[gl.P - 1]) == gl.P - 1
    assert cs.get_value(table[5]) == 5
    a = table[5]
    b = table[1 << 32]
    out = a
    for _ in range(300):
        out = FmaGate.fma(cs, out, b, a, 1, 1)
    PublicInputGate.place(cs, out)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm)
    cfg = ProofConfig(fri_lde_factor=4, num_queries=8, fri_final_degree=8)
    setup = generate_setup(asm, cfg)
    proof = prove(asm, setup, cfg)
    assert verify(setup.vk, proof, asm.gates)

    # tamper the allocated constant's witness value -> unsatisfiable
    import numpy as np

    loc = np.argwhere(asm.copy_placement == table[5])
    c, r = loc[0]
    asm.copy_cols_values[c, r] = 6
    assert not check_if_satisfied(asm)
