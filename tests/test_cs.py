"""CS synthesis + witness resolution + satisfiability tests (gate-level test
strategy per reference testing_tools.rs harness)."""

import numpy as np

from boojum_tpu.cs.types import CSGeometry
from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.gates import (
    BooleanConstraintGate,
    ConditionalSwapGate,
    ConstantsAllocatorGate,
    DotProductGate,
    FmaGate,
    PublicInputGate,
    ReductionGate,
    ReductionByPowersGate,
    SelectionGate,
    SimpleNonlinearityGate,
    U32AddGate,
    U32FmaGate,
    U32SubGate,
    ZeroCheckGate,
)
from boojum_tpu.prover.satisfiability import check_if_satisfied
from boojum_tpu.field import gl

GEOM = CSGeometry(
    num_columns_under_copy_permutation=16,
    num_witness_columns=0,
    num_constant_columns=6,
    max_allowed_constraint_degree=4,
)


def fresh_cs(max_len=64):
    return ConstraintSystem(GEOM, max_len)


def test_fma_gate_and_resolver():
    cs = fresh_cs()
    a = cs.alloc_variable_with_value(3)
    b = cs.alloc_variable_with_value(5)
    c = cs.alloc_variable_with_value(7)
    d = FmaGate.fma(cs, a, b, c, 2, 11)
    assert cs.get_value(d) == (2 * 3 * 5 + 11 * 7) % gl.P
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_deferred_resolution_order():
    cs = fresh_cs()
    a = cs.alloc_variable_without_value()
    b = cs.alloc_variable_without_value()
    # register a resolution depending on unset inputs first
    out = cs.alloc_variable_without_value()
    cs.set_values_with_dependencies([a, b], [out], lambda v: [gl.add(v[0], v[1])])
    cs.resolver.set_value(a, 10)
    assert not cs.resolver.is_resolved(out)
    cs.resolver.set_value(b, 20)
    assert cs.get_value(out) == 30


def test_gate_zoo_satisfiable():
    cs = fresh_cs(256)
    x = cs.alloc_variable_with_value(9)
    y = cs.alloc_variable_with_value(12)
    FmaGate.fma(cs, x, y, x, 1, 1)
    five = ConstantsAllocatorGate.allocate_constant(cs, 5)
    bool_v = cs.alloc_variable_with_value(1)
    BooleanConstraintGate.enforce(cs, bool_v)
    ReductionGate.reduce(cs, [x, y, five, bool_v], [1, 2, 3, 4])
    ReductionByPowersGate.reduce(cs, [x, y, five, bool_v], 1 << 8)
    SelectionGate.select(cs, bool_v, x, y)
    ConditionalSwapGate.swap(cs, bool_v, x, y)
    DotProductGate.dot(cs, [(x, y), (x, x), (y, y), (five, x)])
    ZeroCheckGate.is_zero(cs, x)
    z0 = cs.alloc_variable_with_value(0)
    ZeroCheckGate.is_zero(cs, z0)
    SimpleNonlinearityGate.apply(cs, x, 42)
    a32 = cs.alloc_variable_with_value(0xFFFFFFFF)
    b32 = cs.alloc_variable_with_value(0x12345678)
    zero = cs.zero_var()
    U32AddGate.add(cs, a32, b32, zero)
    U32SubGate.sub(cs, b32, a32, zero)
    U32FmaGate.fma(cs, a32, b32, b32, zero)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_unsatisfied_detected():
    cs = fresh_cs()
    a = cs.alloc_variable_with_value(3)
    b = cs.alloc_variable_with_value(5)
    c = cs.alloc_variable_with_value(7)
    d = FmaGate.fma(cs, a, b, c)
    # corrupt the witness after the fact
    cs.resolver.values[d] = 999
    asm = cs.into_assembly()
    assert not check_if_satisfied(asm)


def test_public_input():
    cs = fresh_cs()
    v = cs.alloc_variable_with_value(1234)
    PublicInputGate.place(cs, v)
    asm = cs.into_assembly()
    assert asm.public_inputs == [(0, 0, 1234)] or len(asm.public_inputs) == 1
    assert check_if_satisfied(asm)


def test_row_amortization():
    # 4 fma instances with same constants share one row (16 cols / width 4)
    cs = fresh_cs()
    for _ in range(4):
        a = cs.alloc_variable_with_value(2)
        FmaGate.fma(cs, a, a, a)
    rows_used = cs.next_row
    # one row for fma, plus zero/one constant rows if any
    fma_rows = sum(
        1
        for r in range(rows_used)
        if cs.gates[cs.row_gate[r]].name == "fma"
    )
    assert fma_rows == 1
    asm = cs.into_assembly()
    assert check_if_satisfied(asm)
