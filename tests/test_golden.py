"""Golden-artifact verification against the reference's shipped proof.

`/root/reference/proof.json` + `vk.json` are a REAL Era main-VM proof
produced by the Rust prover (domain 2^20, 155 variable polys, lookup width
3 x 8, LDE 2, cap 32, 100 queries). Verifying them byte-for-byte pins our
Poseidon2 permutation, sponge construction, transcript semantics,
BoolsBuffer query drawing, Merkle/cap hashing order, FRI folding schedule,
DEEP quotening, and challenge derivation to the Rust implementation
(reference test model: recursive_verifier.rs:2280 loads the same files).
"""

import os

import pytest

from boojum_tpu.compat import (
    BoolsBuffer,
    ReferenceTranscript,
    compute_fri_schedule,
    load_proof,
    load_vk,
    verify_reference_proof,
)
from boojum_tpu.compat.serde import TreeNode
from boojum_tpu.compat.verifier import (
    _compute_selector_subpath_at_z,
    make_non_residues,
)
from boojum_tpu.compat.gates import ONE, ZERO, e_add
from boojum_tpu.field import gl

VK_PATH = "/root/reference/vk.json"
PROOF_PATH = "/root/reference/proof.json"

pytestmark = pytest.mark.skipif(
    not (os.path.exists(VK_PATH) and os.path.exists(PROOF_PATH)),
    reason="golden artifacts unavailable",
)


def test_golden_artifacts_verify_byte_level():
    """The full reference verification chain over the golden artifacts:
    transcript replay, challenge derivation, lookup sumcheck, shape checks,
    100 queries x 4 oracle Merkle checks, DEEP quotening consistency, FRI
    fold simulation per the computed schedule, final monomial evaluation.

    The algebraic quotient identity at z is excluded: it requires the exact
    gate configuration of the Era main-VM circuit, which lives in the
    external era-zkevm_circuits crate (not in the VK; the reference repo's
    own reconstruction in recursive_verifier.rs:2290 lists a gate set whose
    selector tree contradicts this VK's, so the artifacts predate it)."""
    vk = load_vk(VK_PATH)
    proof = load_proof(PROOF_PATH)
    assert verify_reference_proof(
        vk, proof, check_quotient_identity=False
    )


@pytest.mark.xfail(
    reason="needs the external era-zkevm_circuits gate configuration; "
    "the in-repo era_main_vm_verifier_config reconstruction does not "
    "reproduce the artifact circuit's quotient term layout",
    strict=True,
)
def test_golden_artifacts_full_identity():
    vk = load_vk(VK_PATH)
    proof = load_proof(PROOF_PATH)
    assert verify_reference_proof(vk, proof)


def test_golden_tamper_rejected():
    """Byte-level checks must catch tampering: a flipped cap element breaks
    the transcript -> query indices -> Merkle checks."""
    vk = load_vk(VK_PATH)
    proof = load_proof(PROOF_PATH)
    digest = list(proof.witness_oracle_cap[0])
    digest[0] = (digest[0] + 1) % gl.P
    proof.witness_oracle_cap[0] = tuple(digest)
    assert not verify_reference_proof(
        vk, proof, check_quotient_identity=False
    )


def test_fri_schedule_matches_artifacts():
    """compute_fri_schedule (prover.rs:2281 port) reproduces the golden
    proof's observed layout: 6 FRI oracles folding [3,3,3,3,3,1] down to 16
    final monomials with 100 queries."""
    new_pow, num_queries, schedule, final_degree = compute_fri_schedule(
        security_bits=100,
        cap_size=32,
        pow_bits=0,
        rate_log_two=1,
        initial_degree_log_two=20,
    )
    assert new_pow == 0
    assert num_queries == 100
    assert schedule == [3, 3, 3, 3, 3, 1]
    assert final_degree == 16
    proof = load_proof(PROOF_PATH)
    assert len(proof.fri_intermediate_oracles_caps) == len(schedule) - 1
    assert len(proof.final_fri_monomials[0]) == final_degree
    for q in proof.queries_per_fri_repetition[:3]:
        assert [len(f.leaf_elements) for f in q.fri] == [
            2 * (1 << s) for s in schedule
        ]


def test_selector_tree_parse_and_partition_of_unity():
    """The VK's selector tree parses, round-trips, and its 11 selector
    polynomials form a partition of unity — their values at the (random)
    challenge z sum to exactly 1, pinning tree-path semantics and the
    selector-constant indexing."""
    vk = load_vk(VK_PATH)
    tree = vk.selectors_placement
    assert TreeNode.from_json(tree.to_json()).to_json() == tree.to_json()
    deg, consts = tree.compute_stats()
    assert deg == vk.quotient_degree == 8
    assert (
        consts
        == vk.num_constant_columns + vk.extra_constant_polys_for_selectors
        == 7
    )
    paths = [tree.output_placement(gi) for gi in range(11)]
    assert all(p is not None for p in paths)
    assert tree.output_placement(11) is None
    proof = load_proof(PROOF_PATH)
    constants = proof.values_at_z[155:163]
    buf = {}
    for p in paths:
        _compute_selector_subpath_at_z(p, buf, constants)
    total = ZERO
    for p in paths:
        total = e_add(total, buf[tuple(p)])
    assert total == ONE


def test_reference_non_residues():
    """make_non_residues (utils.rs:636 port): all entries are quadratic
    non-residues in pairwise-distinct cosets of the 2^20 domain."""
    nr = make_non_residues(12, 1 << 20)
    legendre = (gl.P - 1) // 2
    seen = set()
    for k in nr:
        assert gl.pow_(k, legendre) == gl.P - 1
        coset_tag = gl.pow_(k, 1 << 20)
        assert coset_tag != 1
        assert coset_tag not in seen
        seen.add(coset_tag)


def test_transcript_determinism():
    """Same absorbs -> same challenges; rescue padding distinguishes
    lengths."""
    a = ReferenceTranscript()
    b = ReferenceTranscript()
    a.witness_field_elements([1, 2, 3])
    b.witness_field_elements([1, 2, 3])
    assert a.get_challenge() == b.get_challenge()
    # rescue padding (trailing ONE marker) must distinguish [1,2,3] from
    # [1,2,3,0]: without the marker both zero-pad to the same block
    c = ReferenceTranscript()
    c.witness_field_elements([1, 2, 3, 0])
    d = ReferenceTranscript()
    d.witness_field_elements([1, 2, 3])
    assert c.get_challenge() != d.get_challenge()
    # BoolsBuffer takes 43 LSBs per element at max_needed=21
    bb = BoolsBuffer(max_needed=21)
    t = ReferenceTranscript()
    t.witness_field_elements([7])
    bits = bb.get_bits(t, 21)
    assert len(bits) == 21 and len(bb.available) == 43 - 21
