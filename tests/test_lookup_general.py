"""General-purpose-columns lookup mode (reference
enforce_lookup_over_general_purpose_columns, lookup_placement.rs:21 and the
base-field lookup argument lookup_argument.rs): tuples live on selector-gated
marker rows in the GENERAL copy columns, the table id is the marker row's
gate constant, and A_i = selector/agg_i."""

import numpy as np
import pytest

from boojum_tpu.cs.types import CSGeometry, LookupParameters
from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.lookup_table import range_check_table
from boojum_tpu.cs.gates import FmaGate, PublicInputGate
from boojum_tpu.examples import xor4_table
from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify
from boojum_tpu.prover.satisfiability import check_if_satisfied
from boojum_tpu.prover.proof import Proof
from boojum_tpu.field import gl

GEOM = CSGeometry(
    num_columns_under_copy_permutation=8,
    num_witness_columns=0,
    num_constant_columns=6,
    max_allowed_constraint_degree=4,
)

LOOKUP = LookupParameters(width=3, use_specialized_columns=False)

CONFIG = ProofConfig(
    fri_lde_factor=8,
    merkle_tree_cap_size=4,
    num_queries=8,
    pow_bits=0,
    fri_final_degree=4,
)


def build_circuit(num_lookups=20):
    cs = ConstraintSystem(GEOM, 1 << 10, lookup_params=LOOKUP)
    xor_id = cs.add_lookup_table(xor4_table())
    rc_id = cs.add_lookup_table(range_check_table(4))
    rng = np.random.default_rng(11)
    acc = cs.alloc_variable_with_value(1)
    for _ in range(num_lookups):
        a = cs.alloc_variable_with_value(int(rng.integers(16)))
        b = cs.alloc_variable_with_value(int(rng.integers(16)))
        (out,) = cs.perform_lookup(xor_id, [a, b])
        cs.enforce_lookup(rc_id, [out, cs.zero_var()])
        acc = FmaGate.fma(cs, acc, out, a, 1, 1)
    PublicInputGate.place(cs, acc)
    return cs, acc


def test_general_lookup_satisfiability():
    cs, _ = build_circuit()
    asm = cs.into_assembly()
    assert asm.lookup_mode == "general"
    assert asm.num_lookup_cols == 0  # no specialized columns
    assert asm.num_lookup_subargs == 8 // 3
    assert check_if_satisfied(asm, verbose=True)


def test_general_lookup_bad_tuple_detected():
    cs, _ = build_circuit(num_lookups=5)
    asm = cs.into_assembly()
    mk_gid = asm.lookup_marker_gid()
    rows = np.nonzero(asm.row_gate == mk_gid)[0]
    asm.copy_cols_values = asm.copy_cols_values.copy()
    asm.copy_cols_values[0, rows[0]] = 17  # outside the xor4 key range
    assert not check_if_satisfied(asm, verbose=False)


def test_general_lookup_e2e_prove_verify():
    cs, acc = build_circuit()
    expected = cs.get_value(acc)
    asm = cs.into_assembly()
    setup = generate_setup(asm, CONFIG)
    proof = prove(asm, setup, CONFIG)
    assert proof.public_inputs == [expected]
    assert len(proof.values_at_0) == asm.num_lookup_subargs + 1
    assert verify(setup.vk, proof, asm.gates), (
        "honest general-mode lookup proof must verify"
    )
    # tampered lookup opening at 0 must be rejected
    p2 = Proof.from_json(proof.to_json())
    v = list(p2.values_at_0[0])
    v[0] = (v[0] + 1) % gl.P
    p2.values_at_0[0] = tuple(v)
    assert not verify(setup.vk, p2, asm.gates)
    # tampered multiplicity opening must be rejected
    p3 = Proof.from_json(proof.to_json())
    q = p3.queries[0].witness
    q.leaf_values[-1] = (q.leaf_values[-1] + 1) % gl.P
    assert not verify(setup.vk, p3, asm.gates)
