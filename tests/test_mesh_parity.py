"""Mesh-native prover parity (ISSUE 5).

The tentpole replaced the GSPMD-only mesh path with a shard_map-based one
(`parallel/shard_sweep.py`): every chip runs the native limb Pallas
kernels on its local shard and the collectives are explicit (one
all_to_all per col->row Merkle pivot, one all_gather per cap), charged to
`ici.*` gauges. These tests pin the acceptance criteria on the virtual
8-device CPU mesh (conftest forces xla_force_host_platform_device_count):

- a 2^10 e2e prove produces bit-identical proof bytes AND digest
  checkpoint streams across {no mesh, 2x4 GSPMD mesh, 2x4 shard_map mesh
  with the limb kernels in interpret mode};
- metrics guards that the shard_map limb kernels actually dispatched
  (quotient.limb_coset_sweeps / fri.limb_folds / merkle.limb_leaf_sponges
  nonzero) — without them the parity assertions would be vacuous;
- the new ici.* byte/time gauges appear in the ProveReport line and
  report.validate_report (the `prove_report.py --check` gate) validates
  them;
- shard_cols' divisibility fallback warns once through the
  boojum_tpu logger and records the chosen axis as a span attribute.
"""

import functools
import logging
import os

import jax
import numpy as np
import pytest

from boojum_tpu.utils import report

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def _mesh():
    from jax.sharding import Mesh

    return Mesh(
        np.array(jax.devices()[:8]).reshape(2, 4), axis_names=("col", "row")
    )


def _small_prove_parts():
    from test_limb_sweep import _small_prove_parts as parts

    return parts()


def _recorded_prove(label, env, mesh=None):
    from boojum_tpu.prover import prove

    asm, setup, config = _small_prove_parts()
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        with report.flight_recording(label=label) as rec:
            proof = prove(asm, setup, config, mesh=mesh)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return proof, report.build_report(rec)


@functools.lru_cache(maxsize=1)
def _three_mode_runs():
    # meshless FIRST so its caches never benefit from mesh-run state; the
    # shard_map run forces the limb kernels (interpret mode on CPU) so the
    # parity covers the per-chip Pallas path, not an XLA fallback
    nomesh = _recorded_prove("nomesh", {})
    gspmd = _recorded_prove(
        "gspmd", {"BOOJUM_TPU_MESH_MODE": "gspmd"}, mesh=_mesh()
    )
    sm = _recorded_prove(
        "sm",
        {"BOOJUM_TPU_MESH_MODE": "shard_map", "BOOJUM_TPU_LIMB_SWEEP": "1"},
        mesh=_mesh(),
    )
    return {"nomesh": nomesh, "gspmd": gspmd, "sm": sm}


def _checkpoint_stream(rep):
    return [
        (e["seq"], e["round"], e["label"], e["digest"])
        for e in rep["checkpoints"]
    ]


def test_three_mode_bit_parity_2pow10():
    """Acceptance: proof bytes AND the digest-checkpoint stream are
    bit-identical across no-mesh / GSPMD-mesh / shard_map-mesh."""
    from boojum_tpu.prover import verify

    runs = _three_mode_runs()
    p0, r0 = runs["nomesh"]
    base_ck = _checkpoint_stream(r0)
    assert base_ck, "no checkpoints recorded"
    for mode in ("gspmd", "sm"):
        p, r = runs[mode]
        assert _checkpoint_stream(r) == base_ck, mode
        assert p.to_json() == p0.to_json(), mode
    asm, setup, _config = _small_prove_parts()
    assert verify(setup.vk, runs["sm"][0], asm.gates)


def test_sm_limb_kernels_actually_dispatched():
    """Metrics guard: the shard_map run must have gone through the
    per-chip limb coset sweep, the limb FRI folds AND the fused limb leaf
    sponges — a silent fallback to u64/XLA or to GSPMD would make the
    parity test vacuous."""
    runs = _three_mode_runs()
    c_sm = runs["sm"][1]["metrics"]["counters"]
    c_g = runs["gspmd"][1]["metrics"]["counters"]
    assert c_sm["quotient.limb_coset_sweeps"] == c_sm["quotient.coset_sweeps"]
    assert c_sm["quotient.limb_coset_sweeps"] > 0
    assert c_sm["fri.limb_folds"] == c_sm["fri.folds"] > 0
    assert c_sm["merkle.limb_leaf_sponges"] > 0
    assert c_sm["merkle.sm_commits"] > 0
    assert c_sm["fri.sm_commits"] > 0
    assert c_sm["fri.sm_folds"] > 0
    assert c_sm["deep.sm_codewords"] == 1
    # GSPMD cannot partition a pallas_call: the legacy mode must NOT have
    # dispatched any limb or shard_map kernel
    for k in (
        "quotient.limb_coset_sweeps", "fri.limb_folds",
        "merkle.limb_leaf_sponges", "merkle.sm_commits", "fri.sm_commits",
    ):
        assert c_g.get(k, 0) == 0, k


def test_ici_gauges_present_and_checked():
    """Acceptance: ici.all_to_all_bytes / ici.pivot_s appear in the
    shard_map ProveReport line, validate_report (the prove_report.py
    --check gate) passes it, and a report whose collective counters lack
    their gauges FAILS the gate."""
    runs = _three_mode_runs()
    rep = runs["sm"][1]
    gauges = rep["metrics"]["gauges"]
    counters = rep["metrics"]["counters"]
    assert gauges["ici.all_to_all_bytes"] > 0
    assert gauges["ici.pivot_s"] > 0
    assert gauges["ici.all_gather_bytes"] > 0
    assert counters["ici.all_to_alls"] > 0
    assert counters["ici.all_gathers"] > 0
    assert report.validate_report(rep) == []
    # the meshless / gspmd runs never touch the explicit-collective seam
    for mode in ("nomesh", "gspmd"):
        c = runs[mode][1]["metrics"]["counters"]
        assert c.get("ici.all_to_alls", 0) == 0, mode
        assert report.validate_report(runs[mode][1]) == []
    # mutilated report: counter without gauge must be flagged
    import copy

    bad = copy.deepcopy(rep)
    del bad["metrics"]["gauges"]["ici.all_to_all_bytes"]
    problems = report.validate_report(bad)
    assert any("ici.all_to_all_bytes" in p for p in problems)
    bad2 = copy.deepcopy(rep)
    bad2["metrics"]["gauges"]["ici.pivot_s"] = -1.0
    assert any("ici.pivot_s" in p for p in report.validate_report(bad2))


def test_streamed_sm_bit_parity_2pow10():
    """The streamed commit path under a shard_map mesh (BOOJUM_TPU_
    STREAM_LDE=1: shard_sweep.streamed_leaf_digests_sm per-chip absorbs
    + the de-meshed round-5/FRI fallback for the streamed regens) routes
    DIFFERENT graphs than the materialized path the three-mode tests pin
    — its proof bytes and checkpoints must still be bit-identical, with
    the per-chip streamed blocks actually dispatched."""
    runs = _three_mode_runs()
    p0, r0 = runs["nomesh"]
    p, r = _recorded_prove(
        "sm_stream",
        {
            "BOOJUM_TPU_MESH_MODE": "shard_map",
            "BOOJUM_TPU_LIMB_SWEEP": "1",
            "BOOJUM_TPU_STREAM_LDE": "1",
        },
        mesh=_mesh(),
    )
    assert _checkpoint_stream(r) == _checkpoint_stream(r0)
    assert p.to_json() == p0.to_json()
    c = r["metrics"]["counters"]
    assert c["stream.sm_blocks"] > 0
    assert c["merkle.streamed_commits"] > 0
    assert report.validate_report(r) == []


def test_mesh_mode_dispatch(monkeypatch):
    """mesh_mode(): None without a mesh; shard_map by default on a
    single-process mesh; BOOJUM_TPU_MESH_MODE forces either mode and junk
    raises (a typo must never silently pick a mode)."""
    from boojum_tpu.parallel.sharding import (
        mesh_mode,
        prover_mesh,
        shard_map_mesh,
    )

    monkeypatch.delenv("BOOJUM_TPU_MESH_MODE", raising=False)
    assert mesh_mode() is None
    assert shard_map_mesh() is None
    m = _mesh()
    with prover_mesh(m):
        assert mesh_mode() == "shard_map"
        assert shard_map_mesh() is m
        monkeypatch.setenv("BOOJUM_TPU_MESH_MODE", "gspmd")
        assert mesh_mode() == "gspmd"
        assert shard_map_mesh() is None
        monkeypatch.setenv("BOOJUM_TPU_MESH_MODE", "sm")
        assert mesh_mode() == "shard_map"
        monkeypatch.setenv("BOOJUM_TPU_MESH_MODE", "fast")
        with pytest.raises(ValueError, match="BOOJUM_TPU_MESH_MODE"):
            mesh_mode()


def test_shard_cols_fallback_warning(caplog):
    """shard_cols must log ONE warning per (shape, mesh) when 'col' does
    not divide the batch axis, and record the chosen axis as an attribute
    on the current span."""
    import jax.numpy as jnp

    from boojum_tpu.parallel import sharding as sh
    from boojum_tpu.utils.spans import SpanRecorder, install_recorder, span

    m = _mesh()
    sh._SHARD_COLS_WARNED.clear()
    rec = SpanRecorder()
    prev = install_recorder(rec)
    # the boojum_tpu logger does not propagate (profiling.py owns its
    # handler pipeline) — attach caplog's handler directly
    lg = logging.getLogger("boojum_tpu")
    lg.addHandler(caplog.handler)
    try:
        with sh.prover_mesh(m):
            with caplog.at_level(logging.WARNING, logger="boojum_tpu"):
                with span("fallback_probe"):
                    # 15 columns over the 2-way 'col' axis: falls back to
                    # the (power-of-two) domain axis
                    sh.shard_cols(jnp.zeros((15, 256), jnp.uint64))
                    # repeat: the warning must NOT repeat
                    sh.shard_cols(jnp.zeros((15, 256), jnp.uint64))
                with span("clean_probe"):
                    sh.shard_cols(jnp.zeros((16, 256), jnp.uint64))
    finally:
        install_recorder(prev)
        lg.removeHandler(caplog.handler)
    warnings = [
        r for r in caplog.records if "shard_cols" in r.getMessage()
    ]
    assert len(warnings) == 1
    spans = {s["name"]: s for s in rec.roots}
    assert (
        spans["fallback_probe"]["attrs"]["shard_cols_axis"]
        == "domain(col,row)"
    )
    assert spans["clean_probe"]["attrs"]["shard_cols_axis"] == "col"


def test_fold_shards_ok():
    from boojum_tpu.parallel.shard_sweep import fold_shards_ok

    m = _mesh()  # 8 devices
    assert fold_shards_ok(2048, 3, m)  # 2048 % 64 == 0
    assert fold_shards_ok(256, 3, m)
    assert not fold_shards_ok(32, 3, m)  # 32 % 64 != 0
    assert not fold_shards_ok(2048 + 8, 1, m)
