"""BabyBear under the full PLONKish prover (ISSUE 20).

The tentpole makes the REAL prove() pipeline field-generic: under
BOOJUM_TPU_FIELD=babybear the same rounds, Fiat-Shamir checkpoints and
clock stages run on the plane-free u32 kernel set (prover/prover_bb.py)
— witness ingestion as bare u32 lanes, stage-2 copy-permutation/lookup
via BabyBear batch inversion, the fused coset quotient sweep, Poseidon2-
BB Merkle commits, DEEP at a GF(p^4) z, the FRI chain. These tests pin
the acceptance criteria:

- full prove() at 2^10 on the fma AND xor4-lookup circuits: proof bytes
  and checkpoint streams bit-identical between the device backend and
  the NumPy reference twin, deterministic across runs;
- ZERO limb.splits / limb.joins during a BabyBear full prove while the
  `_bb` kernel counters move (the plane-free guard is not vacuous);
- the quotient identity at z re-checked from the proof's own openings
  via BBExtScalarOps (prover_bb.quotient_identity_at_z);
- the poseidon-rf e2e leg through the REAL prove() entry: dispatch,
  cost record stamped field=babybear, report validator accepts it;
- the analytic cost sheet: per-stage HBM bytes under babybear exactly
  HALF the Goldilocks sheet for the same geometry, flops identical;
- goldilocks untouched when the env var is unset (the GL path still
  proves and verifies, no babybear stamp anywhere);
- Poseidon2-BB: the Pallas kernel (forced interpret=True on CPU)
  matches the XLA twin permutation;
- sha256-over-babybear REJECTED at synthesis by the field-capacity
  guard with a clear error (satellite: cs.require_field_bits);
- trend/SLO plumbing (satellites): _trend_identity splits series by
  field, slo_summary counts lines per field, warm_geometry warms the
  bb_ntt table set under its field-aware key.
"""

import contextlib
import functools
import os
import sys
import types

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from boojum_tpu.examples import (
    build_fma_chain_circuit,
    build_poseidon_rf_circuit,
    build_xor_lookup_circuit,
)


@contextlib.contextmanager
def _bb_field():
    prev = os.environ.get("BOOJUM_TPU_FIELD")
    os.environ["BOOJUM_TPU_FIELD"] = "babybear"
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop("BOOJUM_TPU_FIELD", None)
        else:
            os.environ["BOOJUM_TPU_FIELD"] = prev


def _cfg():
    from boojum_tpu.prover import ProofConfig

    return ProofConfig(fri_lde_factor=2, num_queries=8, fri_final_degree=8)


@functools.lru_cache(maxsize=None)
def _circuit(kind):
    """(assembly, setup) synthesized UNDER the babybear env var — the CS
    stamps its field at synthesis, generate_setup dispatches on it."""
    with _bb_field():
        if kind == "fma":
            cs, _ = build_fma_chain_circuit(num_rows=(1 << 10) - 8)
        elif kind == "xor4":
            cs, _, _ = build_xor_lookup_circuit(
                num_lookups=600, capacity=1 << 11
            )
        else:  # poseidon-rf
            cs, _ = build_poseidon_rf_circuit(num_rounds=48)
        asm = cs.into_assembly()
        assert asm.field == "babybear"
        from boojum_tpu.prover import generate_setup

        return asm, generate_setup(asm, _cfg())


def _checkpointed(fn, *args):
    from boojum_tpu.utils.report import CheckpointLog, install_checkpoint_log

    log = CheckpointLog()
    prev = install_checkpoint_log(log)
    try:
        proof = fn(*args)
    finally:
        install_checkpoint_log(prev)
    return proof, log.entries


@functools.lru_cache(maxsize=None)
def _device_run(kind):
    """ONE device-backend full prove per circuit, shared by the parity /
    determinism / zero-conversion tests, recorded under metrics."""
    from boojum_tpu.prover.prover_bb import prove_full_babybear
    from boojum_tpu.utils import metrics

    asm, setup = _circuit(kind)
    with _bb_field():
        reg = metrics.start_metrics()
        try:
            proof, entries = _checkpointed(
                prove_full_babybear, asm, setup, _cfg()
            )
        finally:
            metrics.stop_metrics()
    return proof, entries, reg.to_dict()["counters"]


@functools.lru_cache(maxsize=None)
def _reference_run(kind):
    from boojum_tpu.compat.prove_reference_bb import (
        prove_full_babybear_reference,
    )

    asm, setup = _circuit(kind)
    with _bb_field():
        return _checkpointed(prove_full_babybear_reference, asm, setup, _cfg())


# ---------------------------------------------------------------------------
# Device / numpy parity at 2^10 (the acceptance bar)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", ["fma", "xor4"])
def test_full_prover_proof_bytes_parity(kind):
    asm, _ = _circuit(kind)
    assert asm.trace_len == 1 << 10
    dev, _, _ = _device_run(kind)
    ref, _ = _reference_run(kind)
    assert dev.to_json() == ref.to_json()
    assert dev.config.get("field") == "babybear"


@pytest.mark.parametrize("kind", ["fma", "xor4"])
def test_full_prover_checkpoint_stream_parity(kind):
    _, dev_entries, _ = _device_run(kind)
    _, ref_entries = _reference_run(kind)
    assert dev_entries == ref_entries
    labels = [e["label"] for e in dev_entries]
    # the GL round protocol, replayed verbatim: commits, challenges,
    # FRI chain, grinding, query sampling
    for must in (
        "setup_cap", "witness_cap", "stage2_cap", "quotient_cap",
        "evaluations", "deep_challenge", "fri_final_monomials",
        "pow_nonce", "query_indices",
    ):
        assert must in labels, (must, labels)


def test_full_prover_deterministic_across_runs():
    from boojum_tpu.prover.prover_bb import prove_full_babybear

    asm, setup = _circuit("fma")
    dev, entries, _ = _device_run("fma")
    with _bb_field():
        again, entries2 = _checkpointed(
            prove_full_babybear, asm, setup, _cfg()
        )
    assert again.to_json() == dev.to_json()
    assert entries2 == entries


def test_zero_limb_conversions_during_full_prove():
    """THE plane-free guard at full-prover scope: no (lo, hi) planes
    exist anywhere on the babybear prove() path — and the `_bb` twins
    all dispatched, so the zero is not vacuous."""
    for kind in ("fma", "xor4"):
        _, _, c = _device_run(kind)
        for k in ("limb.splits", "limb.joins", "limb.host_splits",
                  "limb.host_joins"):
            assert c.get(k, 0) == 0, (kind, k, c)
        for k in ("ntt.bb_dispatches", "lde.bb_dispatches",
                  "merkle.bb_commits", "stage2.bb_scans",
                  "gate_sweep.bb_builds", "quotient.bb_full_sweeps",
                  "deep.bb_accumulates", "fri.bb_folds"):
            assert c.get(k, 0) >= 1, (kind, k, c)
    _, _, c = _device_run("xor4")
    assert c.get("lookup.bb_polys", 0) >= 1, c


@pytest.mark.parametrize("kind", ["fma", "xor4"])
def test_quotient_identity_at_z(kind):
    """Self-check straight from the proof's openings: the gate + copy +
    lookup terms recombined over GF(p^4) scalar ops must equal
    T(z)·(z^n − 1) — any mis-wired column ordering or challenge replay
    lands here, not in a downstream consumer."""
    from boojum_tpu.prover.prover_bb import quotient_identity_at_z

    asm, setup = _circuit(kind)
    proof, _, _ = _device_run(kind)
    with _bb_field():
        assert quotient_identity_at_z(asm, setup, proof)


# ---------------------------------------------------------------------------
# The REAL prove() entry: dispatch, clock, cost record (poseidon-rf leg)
# ---------------------------------------------------------------------------


def test_prove_entry_poseidon_rf_dispatches_and_stamps_cost():
    from boojum_tpu.prover import prove
    from boojum_tpu.prover.prover_bb import quotient_identity_at_z
    from boojum_tpu.utils.report import (
        build_report,
        flight_recording,
        validate_report,
    )

    asm, setup = _circuit("poseidon")
    with _bb_field():
        with flight_recording(label="bb-full-e2e") as rec:
            proof = prove(asm, setup, _cfg())
        report = build_report(rec)
        assert quotient_identity_at_z(asm, setup, proof)
    assert proof.config.get("field") == "babybear"
    cost = report.get("cost")
    assert cost is not None and cost.get("field") == "babybear"
    # the artifact passes the same validator prove_report.py --check runs
    assert validate_report(report) == []


def test_cost_sheet_hbm_bytes_exactly_half_of_goldilocks():
    """The >= 2x byte-reduction claim at full-prover scope: the same
    geometry costed under babybear moves exactly HALF the HBM/ICI bytes
    of the Goldilocks sheet in EVERY stage — flops unchanged (the op
    count does not depend on the element width)."""
    from boojum_tpu.prover.shape_key import shape_bucket
    from boojum_tpu.utils.costmodel import stage_costs

    asm, _ = _circuit("fma")
    sb = shape_bucket(asm, _cfg())
    prev = os.environ.pop("BOOJUM_TPU_FIELD", None)
    try:
        gl = stage_costs(sb, _cfg())
    finally:
        if prev is not None:
            os.environ["BOOJUM_TPU_FIELD"] = prev
    with _bb_field():
        bbc = stage_costs(sb, _cfg())
    assert set(gl) == set(bbc) and gl
    for st, g in gl.items():
        b = bbc[st]
        assert b["hbm_bytes"] == pytest.approx(g["hbm_bytes"] * 0.5), st
        assert b["ici_bytes"] == pytest.approx(g["ici_bytes"] * 0.5), st
        assert b["flops"] == pytest.approx(g["flops"]), st


# ---------------------------------------------------------------------------
# Goldilocks untouched with the env unset
# ---------------------------------------------------------------------------


def test_goldilocks_path_unaffected_when_env_unset(monkeypatch):
    from boojum_tpu.field.spec import active_field
    from boojum_tpu.prover import (
        ProofConfig,
        generate_setup,
        prove,
        verify,
    )

    monkeypatch.delenv("BOOJUM_TPU_FIELD", raising=False)
    assert active_field() == "goldilocks"
    cs, _ = build_fma_chain_circuit(num_rows=56, capacity=1 << 6)
    asm = cs.into_assembly()
    assert asm.field == "goldilocks"
    cfg = ProofConfig(
        fri_lde_factor=2, merkle_tree_cap_size=4,
        num_queries=4, fri_final_degree=8,
    )
    setup = generate_setup(asm, cfg)
    assert setup.vk.transcript == "poseidon2"  # not the _babybear twin
    proof = prove(asm, setup, cfg)
    assert proof.config.get("field") != "babybear"
    assert verify(setup.vk, proof, asm.gates)


# ---------------------------------------------------------------------------
# Poseidon2-BB: Pallas (interpret) vs XLA parity on CPU
# ---------------------------------------------------------------------------


def test_poseidon2_bb_pallas_interpret_matches_xla():
    import jax.numpy as jnp

    from boojum_tpu.field import babybear as bb
    from boojum_tpu.hashes.poseidon2_bb import (
        poseidon2_permutation_bb_pallas,
        poseidon2_permutation_bb_xla,
    )

    rng = np.random.default_rng(20)
    states = rng.integers(0, bb.P, (64, 16), dtype=np.uint64).astype(
        np.uint32
    )
    # boundary rows: all zeros, all p-1
    states[0] = 0
    states[1] = bb.P - 1
    x = jnp.asarray(states)
    got = np.asarray(poseidon2_permutation_bb_pallas(x, interpret=True))
    want = np.asarray(poseidon2_permutation_bb_xla(x))
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# Field-capacity guard: sha256 over babybear is a synthesis error
# ---------------------------------------------------------------------------


def test_sha256_over_babybear_rejected_at_synthesis():
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.implementations.reference_cs import (
        FieldCapacityError,
    )
    from boojum_tpu.cs.types import CSGeometry, LookupParameters
    from boojum_tpu.gadgets import allocate_u8_input, sha256

    geom = CSGeometry(60, 0, 8, 7)
    with _bb_field():
        cs = ConstraintSystem(
            geom, 1 << 15,
            lookup_params=LookupParameters(width=4, num_repetitions=8),
        )
        with pytest.raises(FieldCapacityError) as exc:
            sha256(cs, allocate_u8_input(cs, b"abc"))
    msg = str(exc.value)
    assert "babybear" in msg and "goldilocks" in msg


# ---------------------------------------------------------------------------
# Satellites: trend identity, SLO field axis, field-aware geometry warm
# ---------------------------------------------------------------------------


def test_trend_identity_splits_series_by_field():
    from boojum_tpu.utils.report import _trend_identity

    host = {"host_fp": "fp0", "device_kind": "cpu", "backend": "cpu",
            "jax": "1", "jaxlib": "1"}
    gl = _trend_identity({"host": host})
    bb_line = _trend_identity({"host": host, "field": "babybear"})
    bb_cost = _trend_identity(
        {"host": host, "cost": {"field": "babybear"}}
    )
    assert gl != bb_line
    assert bb_line == bb_cost
    assert bb_line.endswith("field=babybear")
    # goldilocks stays UNSUFFIXED: the repo's pre-field history (and the
    # ""-identity legacy-adoption path) keeps gating new GL lines
    assert gl == _trend_identity({"host": host, "field": "goldilocks"})
    assert "field=" not in gl
    assert _trend_identity({}) == ""


def test_trend_series_do_not_cross_gate_between_fields():
    """A synthetic mixed history: GL rounds at one wall, a babybear
    round 2x slower — with the field folded into the identity the BB
    point opens its OWN series instead of regressing the GL one."""
    from boojum_tpu.utils.report import trend_gate, trend_series

    host = {"host_fp": "fp0", "device_kind": "cpu", "backend": "cpu",
            "jax": "1", "jaxlib": "1"}

    def pt(label, wall, field=None):
        d = {"label": label, "identity": None,
             "values": {"total_wall": {"value": wall, "unit": "s"}}}
        src = {"host": host}
        if field:
            src["field"] = field
        from boojum_tpu.utils.report import _trend_identity

        d["identity"] = _trend_identity(src)
        return d

    points = [pt("r1", 1.0), pt("r2", 1.02), pt("r3", 2.2, "babybear")]
    series = trend_series(points)
    assert len(series) == 2  # one GL series, one BB series
    assert trend_gate(series) == []  # the BB point gates nothing


def test_slo_summary_counts_lines_per_field():
    from boojum_tpu.utils.report import render_slo, slo_summary

    reports = [
        {"field": "babybear"},
        {"cost": {"field": "babybear", "stages": {}}},
        {"cost": {"field": "goldilocks", "stages": {}}},
    ]
    summary = slo_summary(reports)
    assert summary["fields"] == {"babybear": 2, "goldilocks": 1}
    assert "field backend babybear=2, goldilocks=1" in render_slo(summary)


def test_warm_geometry_is_field_aware():
    """The same shape bucket warmed under goldilocks must warm AGAIN
    under babybear (different table set), and the babybear leg must
    actually populate the bb_ntt twiddle / scale caches the full prover
    reads."""
    from boojum_tpu.ntt import bb_ntt
    from boojum_tpu.prover import bb_kernels as BK
    from boojum_tpu.service.cache import DeviceCacheManager

    bucket = types.SimpleNamespace(
        log_n=8, trace_len=1 << 8, lde_factor=2, quotient_degree=8,
        fri_final_degree=8, fri_schedule=(), lookups=False,
    )
    mgr = DeviceCacheManager()
    with _bb_field():
        before = bb_ntt._twiddles.cache_info().hits + \
            bb_ntt._twiddles.cache_info().misses
        assert mgr.warm_geometry(bucket) is True
        after = bb_ntt._twiddles.cache_info().hits + \
            bb_ntt._twiddles.cache_info().misses
        assert after > before  # the bb table set was touched
        assert BK.domain_xs_bb.cache_info().currsize >= 1
        assert mgr.warm_geometry(bucket) is False  # idempotent
    # goldilocks: SAME geometry, DIFFERENT key — warms its own set
    assert mgr.warm_geometry(bucket) is True
    assert mgr.warm_geometry(bucket) is False
