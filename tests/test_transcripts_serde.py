"""Byte transcripts, PoW runners, serialization, convenience drivers
(reference test model: transcript.rs / pow.rs / fast_serialization.rs)."""

import os

from boojum_tpu.field import gl
from boojum_tpu.prover.pow import (
    blake2s_pow_grind,
    blake2s_pow_verify,
    keccak256_pow_grind,
    keccak256_pow_verify,
)
from boojum_tpu.serialization import (
    load_setup,
    save_setup,
    vk_from_json,
    vk_to_json,
)
from boojum_tpu.transcript import (
    Blake2sTranscript,
    Keccak256Transcript,
    make_transcript,
)


def test_byte_transcripts_deterministic_and_sensitive():
    for kind in ("blake2s", "keccak256"):
        t1 = make_transcript(kind)
        t2 = make_transcript(kind)
        t1.witness_field_elements([1, 2, 3])
        t2.witness_field_elements([1, 2, 3])
        c1 = t1.get_multiple_challenges(5)
        c2 = t2.get_multiple_challenges(5)
        assert c1 == c2
        assert all(0 <= c < gl.P for c in c1)
        t3 = make_transcript(kind)
        t3.witness_field_elements([1, 2, 4])
        assert t3.get_challenge() != c1[0]
        # absorbing after squeezing reseeds
        t1.witness_field_elements([9])
        more = t1.get_challenge()
        assert more != c1[0]


def test_transcript_kinds_differ():
    b = Blake2sTranscript()
    k = Keccak256Transcript()
    b.witness_field_elements([7])
    k.witness_field_elements([7])
    assert b.get_challenge() != k.get_challenge()


def test_byte_pow_runners():
    for grind, check in (
        (blake2s_pow_grind, blake2s_pow_verify),
        (keccak256_pow_grind, keccak256_pow_verify),
    ):
        t = Blake2sTranscript()
        t.witness_field_elements([42])
        nonce = grind(t, 8)
        after_grind = t.get_challenge()
        tv = Blake2sTranscript()
        tv.witness_field_elements([42])
        assert check(tv, 8, nonce)
        assert tv.get_challenge() == after_grind
        tb = Blake2sTranscript()
        tb.witness_field_elements([42])
        assert not check(tb, 8, nonce + 1)


def test_vk_json_roundtrip_and_setup_serde(tmp_path):
    from test_e2e import CONFIG, build_fibonacci_circuit
    from boojum_tpu.prover import (
        generate_setup,
        prove,
        prove_from_precomputations,
        verify,
    )

    cs, _ = build_fibonacci_circuit(steps=5)
    asm = cs.into_assembly()
    setup = generate_setup(asm, CONFIG)
    # vk json roundtrip
    vk2 = vk_from_json(vk_to_json(setup.vk))
    assert vk2.to_dict() == setup.vk.to_dict()
    # setup fast-serialization roundtrip; prove with the LOADED setup and
    # verify against the ORIGINAL vk
    path = os.path.join(tmp_path, "setup.npz")
    save_setup(path, setup)
    setup2 = load_setup(path)
    assert setup2.vk.to_dict() == setup.vk.to_dict()
    proof = prove_from_precomputations(asm, setup2, CONFIG)
    assert verify(setup.vk, proof, asm.gates)


def test_prove_one_shot_driver():
    from test_e2e import CONFIG, build_fibonacci_circuit
    from boojum_tpu.prover import prove_one_shot, verify_circuit

    cs, _ = build_fibonacci_circuit(steps=5)
    asm, setup, proof = prove_one_shot(cs, CONFIG)
    assert verify_circuit(setup.vk, proof, asm.gates)


def test_legacy_poseidon_permutation_device_host_parity():
    import numpy as np
    import jax.numpy as jnp

    from boojum_tpu.field import gl
    from boojum_tpu.hashes.poseidon import (
        PoseidonSpongeHost,
        leaf_hash as p_leaf_hash,
        poseidon_permutation,
        poseidon_permutation_host,
    )

    rng = np.random.default_rng(50)
    st = rng.integers(0, gl.P, size=(4, 12), dtype=np.uint64)
    dev = np.asarray(poseidon_permutation(jnp.asarray(st)))
    for i in range(4):
        assert [int(x) for x in dev[i]] == poseidon_permutation_host(
            list(st[i])
        )
    vals = rng.integers(0, gl.P, size=(3, 11), dtype=np.uint64)
    dev = np.asarray(p_leaf_hash(jnp.asarray(vals)))
    for i in range(3):
        assert [int(x) for x in dev[i]] == PoseidonSpongeHost.hash_leaf(
            list(vals[i])
        )
    # distinct from Poseidon2 (different round functions, shared constants)
    from boojum_tpu.hashes.poseidon2 import poseidon2_permutation_host

    assert poseidon_permutation_host([1] * 12) != poseidon2_permutation_host(
        [1] * 12
    )


def test_pluggable_transcript_prove_verify():
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.types import CSGeometry
    from boojum_tpu.cs.gates import FmaGate, PublicInputGate
    from boojum_tpu.prover import (
        ProofConfig,
        prove_one_shot,
        verify_circuit,
    )

    def build():
        cs = ConstraintSystem(CSGeometry(8, 0, 6, 4), 1 << 10)
        x = cs.alloc_variable_with_value(3)
        y = cs.alloc_variable_with_value(4)
        for _ in range(300):
            x, y = y, FmaGate.fma(cs, x, y, x, 1, 1)
        PublicInputGate.place(cs, y)
        return cs

    for kind in ("poseidon", "blake2s"):
        cfg = ProofConfig(
            num_queries=10, fri_final_degree=8, transcript=kind
        )
        asm, setup, proof = prove_one_shot(build(), cfg)
        assert setup.vk.transcript == kind
        assert verify_circuit(setup.vk, proof, asm.gates), kind
        # transcript must be load-bearing: verifying with the wrong kind
        # (a fresh vk clone) must fail
        import dataclasses

        wrong = dataclasses.replace(setup.vk, transcript="poseidon2")
        assert not verify_circuit(wrong, proof, asm.gates), kind
