"""Black-box forensics tests (ISSUE 15): heartbeat sidecar, stall /
deadline / signal stack dumps, --check routing of blackbox records, and
the fleet aggregation layer (`prove_report.py --fleet`) — all CPU-only
and tier-1 fast.

The two subprocess tests are the acceptance criteria verbatim: a
simulated stall (injected sleep inside a stage) and a SIGTERM'd
subprocess must BOTH leave a report artifact whose blackbox records pass
`prove_report.py --check` and name the exact open span.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from boojum_tpu.utils import blackbox, report, spans

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def _cli(argv):
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import prove_report as cli
    finally:
        sys.path.pop(0)
    return cli.main(argv)


# ---------------------------------------------------------------------------
# Heartbeats + progress
# ---------------------------------------------------------------------------


def test_heartbeat_stream_shape_and_validation(tmp_path):
    side = str(tmp_path / "bb.jsonl")
    bb = blackbox.BlackBox(
        sidecar=side, interval_s=0.05, stall_s=None, label="unit"
    )
    bb.set_phase("warmup")
    bb.start()
    try:
        time.sleep(0.25)
    finally:
        bb.stop()
    lines = _read_jsonl(side)
    assert len(lines) >= 2
    seqs = []
    for rec in lines:
        assert report.validate_line(rec) == [], rec
        assert rec["kind"] == report.BLACKBOX_KIND
        assert rec["record"] == "heartbeat"
        assert rec["phase"] == "warmup"
        assert rec["label"] == "unit"
        seqs.append(rec["seq"])
    assert seqs == sorted(seqs)
    # rss is best-effort but always present on linux
    assert "rss_kb" in lines[0]


def test_progress_ticks_from_spans_and_checkpoints():
    before = blackbox.progress()
    with spans.span("anything"):
        pass
    assert blackbox.progress() > before
    # checkpoint() ticks only on the recording path
    log = report.CheckpointLog()
    prev = report.install_checkpoint_log(log)
    try:
        before = blackbox.progress()
        report.checkpoint(1, "witness_cap", [1, 2, 3])
        assert blackbox.progress() > before
    finally:
        report.install_checkpoint_log(prev)


def test_no_stall_dump_while_progress_flows(tmp_path):
    side = str(tmp_path / "bb.jsonl")
    bb = blackbox.BlackBox(sidecar=side, interval_s=0.05, stall_s=0.4)
    bb.start()
    try:
        for _ in range(12):
            with spans.span("busy"):
                time.sleep(0.05)
    finally:
        bb.stop()
    assert all(r["record"] == "heartbeat" for r in _read_jsonl(side))


# ---------------------------------------------------------------------------
# Stall / deadline dumps (in-process)
# ---------------------------------------------------------------------------


def test_stall_dump_names_innermost_open_span(tmp_path):
    side = str(tmp_path / "bb.jsonl")
    art = str(tmp_path / "report.jsonl")
    bb = blackbox.BlackBox(
        sidecar=side, interval_s=0.05, stall_s=0.25, report_path=art
    )
    bb.set_phase("warmup_prove")
    rec = spans.SpanRecorder(sync=False)
    prev = spans.install_recorder(rec)
    bb.start()
    try:
        with spans.span("prove"):
            with spans.span("round3_quotient"):
                time.sleep(0.8)  # injected stall inside a stage
    finally:
        spans.install_recorder(prev)
        bb.stop()
    dumps = [r for r in _read_jsonl(side) if r["record"] == "dump"]
    assert len(dumps) == 1, "stall must dump exactly once per freeze"
    d = dumps[0]
    assert report.validate_line(d) == [], d
    assert d["reason"] == "stall"
    assert d["span"] == "prove/round3_quotient"
    assert d["phase"] == "warmup_prove"
    assert d["stall_s"] == 0.25
    # forensic payload: all-thread stacks, faulthandler text, partial
    # span tree, recent heartbeat trail
    assert any("MainThread" in s["thread"] for s in d["stacks"])
    assert any(
        "time.sleep" in ln or "test_stall" in ln
        for s in d["stacks"]
        for ln in s["stack"]
    )
    assert "Thread" in d["faulthandler"]
    assert d["heartbeats"] and all(
        h["record"] == "heartbeat" for h in d["heartbeats"]
    )
    names = {sp.get("name") for sp in d.get("spans", ())}
    assert "prove" in names
    # the dump was mirrored into the report artifact
    art_dumps = _read_jsonl(art)
    assert len(art_dumps) == 1
    assert art_dumps[0]["reason"] == "stall"


def test_stall_dump_rearms_after_progress_resumes(tmp_path):
    side = str(tmp_path / "bb.jsonl")
    bb = blackbox.BlackBox(sidecar=side, interval_s=0.05, stall_s=0.2)
    bb.start()
    try:
        time.sleep(0.5)  # first freeze
        with spans.span("woke_up"):
            pass
        time.sleep(0.5)  # second freeze
    finally:
        bb.stop()
    dumps = [r for r in _read_jsonl(side) if r["record"] == "dump"]
    assert len(dumps) == 2
    assert all(d["reason"] == "stall" for d in dumps)


def test_deadline_dump_localizes_to_named_phase(tmp_path):
    side = str(tmp_path / "bb.jsonl")
    bb = blackbox.BlackBox(sidecar=side, interval_s=0.05, stall_s=None)
    bb.start()
    try:
        with bb.deadline("setup", 0.15):
            time.sleep(0.5)
        # an expired-and-exited deadline must not fire again
        time.sleep(0.3)
    finally:
        bb.stop()
    dumps = [r for r in _read_jsonl(side) if r["record"] == "dump"]
    assert len(dumps) == 1
    assert dumps[0]["reason"] == "deadline"
    assert dumps[0]["deadline"] == "setup"
    assert dumps[0]["overdue_s"] >= 0
    assert report.validate_line(dumps[0]) == []


def test_deadline_inside_budget_never_fires(tmp_path):
    side = str(tmp_path / "bb.jsonl")
    bb = blackbox.BlackBox(sidecar=side, interval_s=0.05, stall_s=None)
    bb.start()
    try:
        with bb.deadline("fast_phase", 5.0):
            time.sleep(0.15)
    finally:
        bb.stop()
    assert all(r["record"] == "heartbeat" for r in _read_jsonl(side))


# ---------------------------------------------------------------------------
# Env-driven arming
# ---------------------------------------------------------------------------


def test_ensure_started_disabled_without_env(monkeypatch):
    monkeypatch.delenv("BOOJUM_TPU_BLACKBOX", raising=False)
    monkeypatch.delenv("BOOJUM_TPU_STALL_S", raising=False)
    assert blackbox.current_blackbox() is None
    assert blackbox.ensure_started(label="x") is None
    assert blackbox.current_blackbox() is None


def test_ensure_started_arms_from_env_and_is_idempotent(
    tmp_path, monkeypatch
):
    side = str(tmp_path / "side.jsonl")
    monkeypatch.setenv("BOOJUM_TPU_BLACKBOX", side)
    monkeypatch.setenv("BOOJUM_TPU_BLACKBOX_INTERVAL", "0.05")
    monkeypatch.setenv("BOOJUM_TPU_STALL_S", "30")
    bb = blackbox.ensure_started(label="first")
    try:
        assert bb is not None and bb.running()
        assert bb.sidecar == side
        assert bb.stall_s == 30.0
        assert blackbox.ensure_started(label="second") is bb
        blackbox.set_phase("p1")
        assert bb.phase == "p1"
    finally:
        bb.stop()
        blackbox.install_blackbox(None)
    assert _read_jsonl(side)


# ---------------------------------------------------------------------------
# Validators reject garbage
# ---------------------------------------------------------------------------


def test_validate_blackbox_rejects_malformed():
    ok = {
        "kind": report.BLACKBOX_KIND, "schema": 1, "record": "heartbeat",
        "seq": 1, "t_s": 0.1, "unix_ts": 1000.0, "pid": 1,
        "phase": "x", "progress": 0,
    }
    assert report.validate_blackbox(ok) == []
    assert report.validate_blackbox({**ok, "kind": "nope"})
    assert report.validate_blackbox({**ok, "schema": 99})
    assert report.validate_blackbox({**ok, "record": "pulse"})
    assert report.validate_blackbox({**ok, "seq": 0})
    assert report.validate_blackbox({**ok, "progress": -1})
    assert report.validate_blackbox({**ok, "t_s": float("nan")})
    # a dump without its forensic payload must FAIL — an empty dump
    # reading as valid is how an incident report goes silently blind
    bare_dump = {**ok, "record": "dump", "reason": "stall", "stall_s": 5.0}
    probs = report.validate_blackbox(bare_dump)
    assert any("stacks" in p for p in probs)
    assert any("faulthandler" in p for p in probs)
    assert any("heartbeat trail" in p for p in probs)
    full_dump = {
        **bare_dump,
        "stacks": [{"thread": "MainThread", "stack": ["File x, line 1"]}],
        "faulthandler": "Thread 0x1 ...",
        "heartbeats": [ok],
    }
    assert report.validate_blackbox(full_dump) == []
    assert report.validate_blackbox({**full_dump, "stall_s": 0})
    assert report.validate_blackbox(
        {**full_dump, "reason": "deadline"}
    )  # deadline dump without the deadline name


def test_validate_fleet_rejects_inconsistencies():
    rec = report.fleet_merge([
        ("host0", [_mk_report({"round3_quotient": 1.0}, 2.0)]),
        ("host1", [_mk_report({"round3_quotient": 1.1}, 2.2)]),
    ])
    assert report.validate_fleet(rec) == []
    bad = json.loads(json.dumps(rec))
    bad["stages"]["round3_quotient"]["max_host"] = "ghost"
    assert any("max_host" in p for p in report.validate_fleet(bad))
    bad2 = json.loads(json.dumps(rec))
    bad2["n_hosts"] = 5
    assert any("n_hosts" in p for p in report.validate_fleet(bad2))
    bad3 = json.loads(json.dumps(rec))
    bad3["stragglers"] = [{
        "stage": "nope", "host": "host0", "wall_s": 1, "median_s": 1,
        "ratio": 2.0,
    }]
    assert any("unknown" in p for p in report.validate_fleet(bad3))


# ---------------------------------------------------------------------------
# Fleet merge
# ---------------------------------------------------------------------------


def _mk_report(stage_walls, wall, gauges=None):
    children = [
        {"name": n, "start_s": 0.0, "wall_s": w, "children": []}
        for n, w in stage_walls.items()
    ]
    return {
        "kind": report.REPORT_KIND, "schema": 3, "wall_s": wall,
        "spans": [{
            "name": "prove", "start_s": 0.0, "wall_s": wall,
            "children": children,
        }],
        "metrics": {"counters": {}, "gauges": dict(gauges or {})},
        "checkpoints": [],
    }


def test_fleet_merge_clock_alignment_and_straggler():
    h0 = [
        {"pid": 0, "process_count": 2, "proofs": {},
         "clock_sync": {"barrier_unix_ts": 5000.0,
                        "method": "sync_global_devices"}},
        _mk_report(
            {"round1_witness_commit": 1.0, "round3_quotient": 2.0}, 3.5,
            gauges={"ici.all_gather.bytes": 1e6,
                    "transfer.h2d_bytes": 2e6},
        ),
    ]
    h1 = [
        {"pid": 1, "process_count": 2, "proofs": {},
         "clock_sync": {"barrier_unix_ts": 5000.75,
                        "method": "sync_global_devices"}},
        _mk_report(
            {"round1_witness_commit": 1.1, "round3_quotient": 6.0}, 8.0,
            gauges={"ici.all_gather.bytes": 3e6},
        ),
    ]
    rec = report.fleet_merge([("host0", h0), ("host1", h1)])
    assert report.validate_fleet(rec) == []
    assert report.validate_line(rec) == []
    # clock: barrier stamps -> offsets relative to the earliest host
    assert rec["clock"]["method"] == "barrier"
    assert rec["clock"]["max_skew_s"] == pytest.approx(0.75)
    offs = {h["host"]: h["clock_offset_s"] for h in rec["hosts"]}
    assert offs == {"host0": 0.0, "host1": pytest.approx(0.75)}
    # straggler: round3 on host1 is 3x the median and > 50ms over
    assert [s["stage"] for s in rec["stragglers"]] == ["round3_quotient"]
    s = rec["stragglers"][0]
    assert s["host"] == "host1" and s["ratio"] == pytest.approx(3.0)
    # round1's 10% spread is NOT a straggler
    assert "round1_witness_commit" in rec["stages"]
    # byte rollups per host
    by_host = {h["host"]: h for h in rec["hosts"]}
    assert by_host["host0"]["ici_bytes"] == pytest.approx(1e6)
    assert by_host["host0"]["transfer_bytes"] == pytest.approx(2e6)
    assert by_host["host1"]["ici_bytes"] == pytest.approx(3e6)
    # render names the straggler and the host columns
    text = report.render_fleet(rec)
    assert "STRAGGLER" in text and "round3_quotient" in text
    assert "host0" in text and "host1" in text


def test_fleet_merge_without_clock_stamps_degrades_explicitly():
    rec = report.fleet_merge([
        ("a", [_mk_report({"queries": 1.0}, 1.0)]),
        ("b", [_mk_report({"queries": 1.0}, 1.0)]),
    ])
    assert rec["clock"]["method"] == "none"
    assert "note" in rec["clock"]
    assert report.validate_fleet(rec) == []


def test_fleet_cli_merges_hosts_and_output_passes_check(
    tmp_path, capsys
):
    # per-host result files pointing at per-host report artifacts —
    # exactly what a multihost run leaves behind
    for pid, (quot, ts) in enumerate([(2.0, 7000.0), (6.5, 7000.25)]):
        rep_path = tmp_path / f"report.jsonl.host{pid}"
        with open(rep_path, "w") as f:
            f.write(json.dumps(_mk_report(
                {"round1_witness_commit": 1.0, "round3_quotient": quot},
                quot + 1.5,
                gauges={"ici.psum.bytes": 1e5 * (pid + 1)},
            )) + "\n")
        with open(tmp_path / f"mh_{pid}.json", "w") as f:
            json.dump({
                "pid": pid, "process_count": 2, "proofs": {},
                "clock_sync": {"barrier_unix_ts": ts,
                               "method": "sync_global_devices"},
                "prove_report_path": str(rep_path),
            }, f)
    out = tmp_path / "fleet.json"
    rc = _cli([
        "--fleet", str(tmp_path / "mh_0.json"), str(tmp_path / "mh_1.json"),
        "--out", str(out),
    ])
    text = capsys.readouterr().out
    assert rc == 0, text
    assert "2 hosts" in text and "clock=barrier" in text
    assert "STRAGGLER" in text and "host1" in text
    # the emitted fleet record round-trips through --check
    rc = _cli(["--check", str(out)])
    text = capsys.readouterr().out
    assert rc == 0, text
    assert "fleet — 2 hosts" in text and "1 straggler" in text


def test_check_routes_mixed_artifact_and_rejects_corruption(
    tmp_path, capsys
):
    art = tmp_path / "mixed.jsonl"
    hb = {
        "kind": report.BLACKBOX_KIND, "schema": 1, "record": "heartbeat",
        "seq": 1, "t_s": 0.1, "unix_ts": 1000.0, "pid": 4,
        "phase": "warmup", "progress": 2,
    }
    with open(art, "w") as f:
        f.write(json.dumps(_mk_report({"queries": 0.5}, 1.0)) + "\n")
        f.write(json.dumps(hb) + "\n")
    rc = _cli(["--check", str(art)])
    text = capsys.readouterr().out
    assert rc == 0, text
    assert "blackbox heartbeat" in text
    # corrupt blackbox line -> --check fails
    with open(art, "a") as f:
        f.write(json.dumps({**hb, "seq": -3, "record": "dump"}) + "\n")
    rc = _cli(["--check", str(art)])
    capsys.readouterr()
    assert rc == 1


# ---------------------------------------------------------------------------
# Acceptance subprocess tests: injected stall + SIGTERM mid-stage
# ---------------------------------------------------------------------------

_CHILD_SRC = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, {root!r})
    from boojum_tpu.utils import blackbox, spans

    bb = blackbox.ensure_started(label="child")
    assert bb is not None and bb.running(), "env did not arm the blackbox"
    spans.install_recorder(spans.SpanRecorder(sync=False))
    print("armed", flush=True)
    with spans.span("prove"):
        with spans.span("round3_quotient"):
            time.sleep({sleep_s})
    bb.stop()
    print("done", flush=True)
""")


def _spawn_child(tmp_path, sleep_s, stall_s=None):
    art = str(tmp_path / "report.jsonl")
    side = art + ".blackbox"
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BOOJUM_TPU_REPORT": art,
        "BOOJUM_TPU_BLACKBOX": "1",
        "BOOJUM_TPU_BLACKBOX_INTERVAL": "0.1",
    })
    if stall_s is not None:
        env["BOOJUM_TPU_STALL_S"] = str(stall_s)
    proc = subprocess.Popen(
        [sys.executable, "-c",
         _CHILD_SRC.format(root=REPO_ROOT, sleep_s=sleep_s)],
        env=env, cwd=REPO_ROOT,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    return proc, art, side


def _wait_for_beats(side, n, timeout_s=60.0):
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        try:
            if len(_read_jsonl(side)) >= n:
                return
        except (OSError, ValueError):
            pass
        time.sleep(0.05)
    raise AssertionError(f"sidecar {side} never reached {n} beats")


def test_simulated_stall_subprocess_localizes_to_stalled_span(tmp_path):
    """Acceptance: an injected sleep inside a stage produces a blackbox
    stack dump + heartbeat trail in the report artifact that --check
    accepts and that names the stalled span."""
    proc, art, side = _spawn_child(tmp_path, sleep_s=1.5, stall_s=0.4)
    out, _ = proc.communicate(timeout=120)
    assert proc.returncode == 0, out
    dumps = [r for r in _read_jsonl(art) if r.get("record") == "dump"]
    assert dumps, f"no dump in report artifact; child said: {out}"
    d = dumps[0]
    assert d["reason"] == "stall"
    assert d["span"] == "prove/round3_quotient"
    assert d["stacks"] and d["heartbeats"]
    # the sidecar carries the heartbeat trail around the dump
    side_recs = _read_jsonl(side)
    assert [r for r in side_recs if r["record"] == "heartbeat"]
    # the full artifact and the sidecar both pass --check
    assert _cli(["--check", art]) == 0
    assert _cli(["--check", side]) == 0


def test_sigterm_subprocess_leaves_valid_flushed_artifact(tmp_path):
    """Acceptance: a subprocess killed mid-stage (SIGTERM — the
    `timeout -k` kill path) still leaves fsynced forensics naming the
    open span."""
    proc, art, side = _spawn_child(tmp_path, sleep_s=60)
    _wait_for_beats(side, 2)
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    # the handler re-delivers with default disposition: killed-by-TERM
    assert proc.returncode == -signal.SIGTERM, (proc.returncode, out)
    dumps = [r for r in _read_jsonl(art) if r.get("record") == "dump"]
    assert dumps, f"no dump in report artifact; child said: {out}"
    d = dumps[0]
    assert d["reason"] == "sigterm"
    assert d["span"] == "prove/round3_quotient"
    assert d["stacks"] and isinstance(d["faulthandler"], str)
    assert _cli(["--check", art]) == 0
    assert _cli(["--check", side]) == 0


# ---------------------------------------------------------------------------
# Trend ingestion of MULTICHIP wrappers (satellite)
# ---------------------------------------------------------------------------


def test_trend_ingests_multichip_wrappers_ordered_by_round(tmp_path):
    host = {"host_fp": "fp1", "device_kind": "cpu", "backend": "cpu"}
    def bench_line(v):
        return {"metric": "e2e_prove_wall", "value": v, "unit": "s",
                "status": "ok", "host": host}
    # BENCH wrappers carry n + parsed; MULTICHIP wrappers carry neither
    # (round from the filename, metric line recovered from the tail)
    with open(tmp_path / "BENCH_r01.json", "w") as f:
        json.dump({"n": 1, "rc": 0, "tail": "", "parsed": bench_line(10.0)}, f)
    with open(tmp_path / "MULTICHIP_r02.json", "w") as f:
        json.dump({
            "n_devices": 8, "rc": 0, "ok": True, "skipped": False,
            "tail": "xla noise\n" + json.dumps(bench_line(11.0)) + "\n",
        }, f)
    # a dead round (r03-style: empty tail) is skipped with a note, not
    # a crash and not a bogus 0-valued point
    with open(tmp_path / "MULTICHIP_r03.json", "w") as f:
        json.dump({"n_devices": 8, "rc": 124, "ok": False,
                   "skipped": False, "tail": ""}, f)
    points, notes = report.load_trend_points([
        str(tmp_path / "MULTICHIP_r02.json"),   # CLI order scrambled:
        str(tmp_path / "MULTICHIP_r03.json"),   # round order must win
        str(tmp_path / "BENCH_r01.json"),
    ])
    assert len(points) == 2
    assert [p["label"] for p in points] == [
        "BENCH_r01.json", "MULTICHIP_r02.json",
    ]
    assert any("MULTICHIP_r03" in n for n in notes)
    # identity grouping is reused: both rounds share one gated series
    series = report.trend_series(points)
    key = [(i, n) for (i, n) in series if n == "total_wall"]
    assert len(key) == 1
    vals = [v for _l, v in series[key[0]]["points"]]
    assert vals == [10.0, 11.0]
