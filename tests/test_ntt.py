"""NTT correctness vs naive host DFT (mirrors /root/reference/src/fft tests)."""

import random

import jax.numpy as jnp
import numpy as np

from boojum_tpu.field import gl
from boojum_tpu.field import extension as ext_f
from boojum_tpu import ntt

rng = random.Random(42)


def naive_dft(coeffs, omega, shift=1):
    """Evaluate poly at shift*omega^i for all i (host, python ints)."""
    n = len(coeffs)
    out = []
    for i in range(n):
        x = gl.mul(shift, gl.pow_(omega, i))
        acc = 0
        xp = 1
        for c in coeffs:
            acc = gl.add(acc, gl.mul(c, xp))
            xp = gl.mul(xp, x)
        out.append(acc)
    return out


def rand_poly(n):
    return [rng.randrange(gl.P) for _ in range(n)]


def test_fft_matches_naive_dft():
    log_n = 5
    n = 1 << log_n
    coeffs = rand_poly(n)
    a = jnp.asarray(np.array(coeffs, dtype=np.uint64))
    got = np.asarray(ntt.fft_natural_to_bitreversed(a))
    want = naive_dft(coeffs, gl.omega(log_n))
    brev = ntt.bitreverse_indices(log_n)
    for i in range(n):
        assert int(got[brev[i]]) == want[i]


def test_fft_ifft_roundtrip_batched():
    log_n = 10
    n = 1 << log_n
    cols = 4
    vals = np.random.randint(0, gl.P, size=(cols, n), dtype=np.uint64)
    a = jnp.asarray(vals)
    fwd = ntt.fft_natural_to_bitreversed(a)
    back = np.asarray(ntt.ifft_bitreversed_to_natural(fwd))
    assert (back == vals).all()
    # natural->natural interpolation roundtrip
    mono = ntt.monomial_from_values(a)
    evals = ntt.fft_natural_to_bitreversed(mono)
    ctx = ntt.get_ntt_context(log_n)
    renat = np.asarray(evals)[:, np.asarray(ctx.brev)]
    assert (renat == vals).all()


def test_lde_layout_and_values():
    log_n, lde = 4, 4
    n = 1 << log_n
    coeffs = rand_poly(n)
    a = jnp.asarray(np.array(coeffs, dtype=np.uint64))
    out = np.asarray(ntt.lde_from_monomial(a, lde))  # (lde, n)
    g = gl.MULTIPLICATIVE_GENERATOR
    w_full = gl.omega(log_n + 2)
    # full-domain bitreversed check: flat[brev_N(i)] == f(g * w_full^i)
    flat = out.reshape(-1)
    brev_full = ntt.bitreverse_indices(log_n + 2)
    want = naive_dft(coeffs + [0] * (len(flat) - n), w_full, shift=g)
    for i in range(len(flat)):
        assert int(flat[brev_full[i]]) == want[i]


def test_distribute_powers():
    n = 16
    coeffs = rand_poly(n)
    a = jnp.asarray(np.array(coeffs, dtype=np.uint64))
    shifted = np.asarray(ntt.distribute_powers(a, 7))
    for i in range(n):
        assert int(shifted[i]) == gl.mul(coeffs[i], gl.pow_(7, i))


def test_eval_monomial_at_ext_point():
    n = 64
    coeffs = rand_poly(n)
    a = jnp.asarray(np.array(coeffs, dtype=np.uint64))
    z = (rng.randrange(gl.P), rng.randrange(gl.P))
    got = ntt.eval_monomial_at_ext_point(a, z)
    want = ext_f.ZERO_S
    zp = ext_f.ONE_S
    for c in coeffs:
        want = ext_f.add_s(want, ext_f.mul_by_base_s(zp, c))
        zp = ext_f.mul_s(zp, z)
    assert (int(np.asarray(got[0])), int(np.asarray(got[1]))) == want
