"""Multi-host helpers degrade correctly to the single-process case, and the
hybrid mesh drives a full sharded prove (the virtual 8-device CPU mesh —
process-count > 1 behavior uses the identical GSPMD code paths)."""

import numpy as np

import jax

from boojum_tpu.parallel.multihost import (
    distribute_proofs,
    hybrid_mesh,
    initialize_multihost,
)


def test_initialize_single_process_noop():
    assert initialize_multihost() is False
    assert jax.process_count() == 1


def test_hybrid_mesh_single_process_equals_local_mesh():
    mesh = hybrid_mesh()
    assert mesh.axis_names == ("col", "row")
    assert mesh.size == len(jax.devices())


def test_distribute_proofs_partitioning():
    jobs = list(range(7))
    # simulate 3 processes without a distributed runtime
    seen = {}
    for pid in range(3):
        for i, res in distribute_proofs(
            jobs, lambda j: j * 10, process_id=pid, process_count=3
        ):
            assert i not in seen
            seen[i] = res
    assert seen == {i: i * 10 for i in range(7)}


def test_hybrid_mesh_proves_sharded():
    from boojum_tpu.examples import build_xor_lookup_circuit
    from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify

    cfg = ProofConfig(
        fri_lde_factor=8,
        merkle_tree_cap_size=4,
        num_queries=4,
        pow_bits=0,
        fri_final_degree=4,
    )
    cs, _, _ = build_xor_lookup_circuit(num_lookups=8)
    asm = cs.into_assembly()
    setup = generate_setup(asm, cfg)
    proof = prove(asm, setup, cfg, mesh=hybrid_mesh())
    assert verify(setup.vk, proof, asm.gates)
