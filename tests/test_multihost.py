"""Multi-host helpers degrade correctly to the single-process case, and the
hybrid mesh drives a full sharded prove (the virtual 8-device CPU mesh —
process-count > 1 behavior uses the identical GSPMD code paths)."""

import numpy as np

import jax

from boojum_tpu.parallel.multihost import (
    distribute_proofs,
    hybrid_mesh,
    initialize_multihost,
)


def test_initialize_single_process_noop():
    assert initialize_multihost() is False
    assert jax.process_count() == 1


def test_hybrid_mesh_single_process_equals_local_mesh():
    mesh = hybrid_mesh()
    assert mesh.axis_names == ("col", "row")
    assert mesh.size == len(jax.devices())


def test_distribute_proofs_partitioning():
    jobs = list(range(7))
    # simulate 3 processes without a distributed runtime
    seen = {}
    for pid in range(3):
        for i, res in distribute_proofs(
            jobs, lambda j: j * 10, process_id=pid, process_count=3
        ):
            assert i not in seen
            seen[i] = res
    assert seen == {i: i * 10 for i in range(7)}


def test_hybrid_mesh_proves_sharded():
    from boojum_tpu.examples import build_xor_lookup_circuit
    from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify

    cfg = ProofConfig(
        fri_lde_factor=8,
        merkle_tree_cap_size=4,
        num_queries=4,
        pow_bits=0,
        fri_final_degree=4,
    )
    cs, _, _ = build_xor_lookup_circuit(num_lookups=8)
    asm = cs.into_assembly()
    setup = generate_setup(asm, cfg)
    proof = prove(asm, setup, cfg, mesh=hybrid_mesh())
    assert verify(setup.vk, proof, asm.gates)


def _spawn_workers(mode, tmp_path, nprocs=2, mesh_mode=None, tag=""):
    import json
    import socket
    import subprocess
    import sys as _sys

    # pick a free port for the coordinator
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outs = [
        str(tmp_path / f"{mode}{tag}_{i}.json") for i in range(nprocs)
    ]
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env.pop("BOOJUM_TPU_MESH_MODE", None)
    extra = (
        [f"--mesh-mode={mesh_mode}"] if mesh_mode is not None else []
    )
    procs = [
        subprocess.Popen(
            [
                _sys.executable,
                os.path.join(root, "scripts", "multihost_worker.py"),
                mode, str(port), str(i), str(nprocs), outs[i],
            ]
            + extra,
            env=env,
            cwd=root,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for i in range(nprocs)
    ]
    logs = []
    for p in procs:
        out, _ = p.communicate(timeout=2700)
        logs.append(out.decode(errors="replace")[-2000:])
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log
    return [json.load(open(o)) for o in outs]


import os
import pytest

_TWO_PROC = bool(os.environ.get("BOOJUM_TPU_SLOW_TESTS")) or bool(
    os.environ.get("BOOJUM_TPU_TWO_PROC_TESTS")
)
two_proc = pytest.mark.skipif(
    not _TWO_PROC,
    reason="spawns 2 jax.distributed processes (minutes of CPU compile); "
    "BOOJUM_TPU_SLOW_TESTS=1 or BOOJUM_TPU_TWO_PROC_TESTS=1 to run",
)


@two_proc
def test_two_process_proof_parallel(tmp_path):
    """GENUINELY multi-process: two jax.distributed processes split a
    3-job queue via distribute_proofs; their independently proved slices
    interleave round-robin, and each proof verifies in-process."""
    r0, r1 = _spawn_workers("proofs", tmp_path)
    assert r0["process_count"] == 2 and r1["process_count"] == 2
    assert set(r0["proofs"]) == {"0", "2"}
    assert set(r1["proofs"]) == {"1"}


@two_proc
def test_two_process_hybrid_mesh_byte_identical(tmp_path):
    """The trace-sharded DCN mode for real: both processes jointly prove
    ONE circuit over a hybrid_mesh whose 'col' axis spans the process
    boundary; each emits the SAME byte-identical proof, which also equals
    the single-process (no-mesh) proof of the same circuit."""
    r0, r1 = _spawn_workers("hybrid", tmp_path)
    assert r0["proof"] == r1["proof"]

    from boojum_tpu.prover import ProofConfig, generate_setup, prove
    import json as _json
    import subprocess
    import sys as _sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # single-process reference proof of the same circuit, fresh process to
    # keep backend state clean
    out = tmp_path / "single.json"
    code = (
        "import sys, json; sys.path.insert(0, %r);\n"
        "import scripts.multihost_worker as w\n"
        "from boojum_tpu.prover import ProofConfig, generate_setup, prove\n"
        "cfg = ProofConfig(fri_lde_factor=4, num_queries=8, fri_final_degree=8)\n"
        "asm = w.build_circuit(0).into_assembly()\n"
        "setup = generate_setup(asm, cfg)\n"
        "json.dump(prove(asm, setup, cfg).to_json(), open(%r, 'w'))\n"
        % (root, str(out))
    )
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    p = subprocess.run(
        [_sys.executable, "-c", code], env=env, cwd=root,
        capture_output=True, timeout=1500,
    )
    assert p.returncode == 0, p.stderr.decode(errors="replace")[-2000:]
    single = _json.load(open(out))
    assert r0["proof"] == single


@two_proc
@pytest.mark.slow
@pytest.mark.multihost
def test_two_process_parity_gspmd_vs_shard_map(tmp_path):
    """ISSUE 16 acceptance: the 2^10 circuit proved jointly by two
    jax.distributed processes over a DCN-spanning hybrid mesh yields
    bit-identical proof bytes AND Fiat-Shamir checkpoint streams under
    the native shard_map path and the legacy gspmd path — with metrics
    proving the native limb kernels (explicit collectives, ici/dcn
    gauges) actually dispatched on EVERY host, and the cost record
    carrying a non-empty DCN column."""
    sm0, sm1 = _spawn_workers(
        "hybrid", tmp_path, mesh_mode="shard_map", tag="_sm"
    )
    gs0, gs1 = _spawn_workers(
        "hybrid", tmp_path, mesh_mode="gspmd", tag="_gs"
    )

    # which path ran, per host
    assert sm0["mesh_mode"] == sm1["mesh_mode"] == "shard_map"
    assert gs0["mesh_mode"] == gs1["mesh_mode"] == "gspmd"

    # proof bytes: identical across hosts AND across paths
    assert sm0["proof"] == sm1["proof"]
    assert gs0["proof"] == gs1["proof"]
    assert sm0["proof"] == gs0["proof"]

    # Fiat-Shamir digest checkpoint streams: identical label+digest
    # sequences across paths (first divergence would name the round)
    def _stream(r):
        cps = r.get("checkpoints") or []
        return [(c.get("label"), c.get("digest")) for c in cps]

    assert _stream(sm0), "shard_map leg recorded no checkpoints"
    assert _stream(sm0) == _stream(sm1) == _stream(gs0) == _stream(gs1)

    # native limb kernels on every host: the shard_map legs billed
    # explicit collectives, split intra-host (ici) vs cross-host (dcn)
    for r in (sm0, sm1):
        assert r["ici"].get("ici.all_to_alls", 0) > 0, r["ici"]
        assert r["ici"].get("ici.all_to_all_bytes", 0) > 0, r["ici"]
        dcn_bytes = sum(
            v for k, v in (r.get("dcn") or {}).items() if "bytes" in k
        )
        assert dcn_bytes > 0, r.get("dcn")
    # the gspmd legs never touch the explicit-collective seams
    for r in (gs0, gs1):
        assert not r["ici"].get("ici.all_to_alls"), r["ici"]

    # the per-host report carries a cost record with a non-empty DCN
    # column (measured cross-host bytes) on the shard_map path
    import json as _json

    found_dcn_cost = False
    for r in (sm0, sm1):
        with open(r["prove_report_path"]) as f:
            lines = [ln for ln in f if ln.strip()]
        last = _json.loads(lines[-1])
        cost = last.get("cost") or {}
        total = cost.get("total") or {}
        if total.get("dcn_bytes_measured", 0) > 0:
            found_dcn_cost = True
        assert total.get("dcn_bytes", 0) > 0, total
    assert found_dcn_cost
