"""Multi-host helpers degrade correctly to the single-process case, and the
hybrid mesh drives a full sharded prove (the virtual 8-device CPU mesh —
process-count > 1 behavior uses the identical GSPMD code paths)."""

import numpy as np

import jax

from boojum_tpu.parallel.multihost import (
    distribute_proofs,
    hybrid_mesh,
    initialize_multihost,
)


def test_initialize_single_process_noop():
    assert initialize_multihost() is False
    assert jax.process_count() == 1


def test_hybrid_mesh_single_process_equals_local_mesh():
    mesh = hybrid_mesh()
    assert mesh.axis_names == ("col", "row")
    assert mesh.size == len(jax.devices())


def test_distribute_proofs_partitioning():
    jobs = list(range(7))
    # simulate 3 processes without a distributed runtime
    seen = {}
    for pid in range(3):
        for i, res in distribute_proofs(
            jobs, lambda j: j * 10, process_id=pid, process_count=3
        ):
            assert i not in seen
            seen[i] = res
    assert seen == {i: i * 10 for i in range(7)}


def test_hybrid_mesh_proves_sharded():
    from boojum_tpu.examples import build_xor_lookup_circuit
    from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify

    cfg = ProofConfig(
        fri_lde_factor=8,
        merkle_tree_cap_size=4,
        num_queries=4,
        pow_bits=0,
        fri_final_degree=4,
    )
    cs, _, _ = build_xor_lookup_circuit(num_lookups=8)
    asm = cs.into_assembly()
    setup = generate_setup(asm, cfg)
    proof = prove(asm, setup, cfg, mesh=hybrid_mesh())
    assert verify(setup.vk, proof, asm.gates)


def _spawn_workers(mode, tmp_path, nprocs=2):
    import json
    import socket
    import subprocess
    import sys as _sys

    # pick a free port for the coordinator
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outs = [str(tmp_path / f"{mode}_{i}.json") for i in range(nprocs)]
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [
                _sys.executable,
                os.path.join(root, "scripts", "multihost_worker.py"),
                mode, str(port), str(i), str(nprocs), outs[i],
            ],
            env=env,
            cwd=root,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for i in range(nprocs)
    ]
    logs = []
    for p in procs:
        out, _ = p.communicate(timeout=2700)
        logs.append(out.decode(errors="replace")[-2000:])
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log
    return [json.load(open(o)) for o in outs]


import os
import pytest

_TWO_PROC = bool(os.environ.get("BOOJUM_TPU_SLOW_TESTS")) or bool(
    os.environ.get("BOOJUM_TPU_TWO_PROC_TESTS")
)
two_proc = pytest.mark.skipif(
    not _TWO_PROC,
    reason="spawns 2 jax.distributed processes (minutes of CPU compile); "
    "BOOJUM_TPU_SLOW_TESTS=1 or BOOJUM_TPU_TWO_PROC_TESTS=1 to run",
)


@two_proc
def test_two_process_proof_parallel(tmp_path):
    """GENUINELY multi-process: two jax.distributed processes split a
    3-job queue via distribute_proofs; their independently proved slices
    interleave round-robin, and each proof verifies in-process."""
    r0, r1 = _spawn_workers("proofs", tmp_path)
    assert r0["process_count"] == 2 and r1["process_count"] == 2
    assert set(r0["proofs"]) == {"0", "2"}
    assert set(r1["proofs"]) == {"1"}


@two_proc
def test_two_process_hybrid_mesh_byte_identical(tmp_path):
    """The trace-sharded DCN mode for real: both processes jointly prove
    ONE circuit over a hybrid_mesh whose 'col' axis spans the process
    boundary; each emits the SAME byte-identical proof, which also equals
    the single-process (no-mesh) proof of the same circuit."""
    r0, r1 = _spawn_workers("hybrid", tmp_path)
    assert r0["proof"] == r1["proof"]

    from boojum_tpu.prover import ProofConfig, generate_setup, prove
    import json as _json
    import subprocess
    import sys as _sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # single-process reference proof of the same circuit, fresh process to
    # keep backend state clean
    out = tmp_path / "single.json"
    code = (
        "import sys, json; sys.path.insert(0, %r);\n"
        "import scripts.multihost_worker as w\n"
        "from boojum_tpu.prover import ProofConfig, generate_setup, prove\n"
        "cfg = ProofConfig(fri_lde_factor=4, num_queries=8, fri_final_degree=8)\n"
        "asm = w.build_circuit(0).into_assembly()\n"
        "setup = generate_setup(asm, cfg)\n"
        "json.dump(prove(asm, setup, cfg).to_json(), open(%r, 'w'))\n"
        % (root, str(out))
    )
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    p = subprocess.run(
        [_sys.executable, "-c", code], env=env, cwd=root,
        capture_output=True, timeout=1500,
    )
    assert p.returncode == 0, p.stderr.decode(errors="replace")[-2000:]
    single = _json.load(open(out))
    assert r0["proof"] == single
