"""Network admission plane (ISSUE 11).

The tentpole put an HTTP front door (`service/gateway.py`) over the
proving service — tenant bearer-token auth, idempotency-key replay,
429-with-Retry-After quotas charged from the flight-recorder records,
telemetry-driven load-shed, graceful drain and hot AOT reload — and
replaced the admission queue's intra-lane FIFO with deficit-round-robin
weighted fairness across tenants (`service/queue.py` + tenant.py).

Coverage here, cheapest first:

- DRR unit: a 3-tenant unequal-weight drain converges EXACTLY to the
  configured ratios with no proving; lanes stay strict-priority above
  the tenant rings; big batches borrow deficit and are paid back.
- QuotaLedger window math with injected clocks (no sleeping).
- The `tenant` report record's --check rules and the per-tenant --slo.
- Socket-free gateway routing (Gateway.handle): auth, specs, tickets,
  idempotent replay, 429 + reject lines, shed, spool, drain, reload.
- @gateway-marked socket tests (excludable via -m 'not gateway'):
  the http_metrics 500-with-body + service.http.errors satellite, and
  the E2E acceptance run — two tenants over real loopback HTTP, proof
  bytes + Fiat-Shamir checkpoint streams bit-identical to direct
  prove(), replay served from the ledger without a second prove, one
  tenant 429-throttled while the other completes, drain -> artifact
  passes prove_report.py --check.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import jax
import pytest

from boojum_tpu.utils import report

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# DRR fairness (unit, no proving)
# ---------------------------------------------------------------------------


class _FakeReq:
    def __init__(self, tenant, key="k", priority="batch"):
        self.tenant = tenant
        self.bucket_key = key
        self.priority = priority
        self.admit_ts = None


def test_queue_drr_fairness_converges_to_weights():
    """Satellite acceptance: 3 backlogged tenants at weights 3:2:1
    drain in EXACTLY those ratios (unit-cost DRR, quantum = weight),
    and nobody starves — every tenant is served within each
    weight-sum-sized window."""
    from boojum_tpu.service import AdmissionQueue

    q = AdmissionQueue(
        capacity=256, weights={"a": 3.0, "b": 2.0, "c": 1.0}
    )
    for i in range(60):
        for t in ("a", "b", "c"):
            # distinct buckets: one request per pop even without limit
            q.submit(_FakeReq(t, key=f"{t}{i}"))
    order = []
    for _ in range(60):
        (r,) = q.pop_batch(limit=1)
        order.append(r.tenant)
    counts = {t: order.count(t) for t in ("a", "b", "c")}
    assert counts == {"a": 30, "b": 20, "c": 10}
    # no starvation: every weight-sum window serves every tenant
    for i in range(0, 60, 6):
        assert set(order[i:i + 6]) == {"a", "b", "c"}
    assert q.served == counts
    # a weight must be positive — a zero-quantum ring would never turn
    with pytest.raises(ValueError, match="weight"):
        q.set_weight("d", 0)


def test_queue_drr_borrowing_and_lane_priority():
    """A tenant draining a big same-bucket batch borrows against its
    deficit and is skipped for proportionally many rounds; strict lane
    priority still trumps every tenant weight."""
    from boojum_tpu.service import AdmissionQueue

    q = AdmissionQueue(capacity=64, weights={"heavy": 1.0, "light": 1.0})
    for _ in range(6):
        q.submit(_FakeReq("heavy", key="same"))
    for i in range(3):
        q.submit(_FakeReq("light", key=f"l{i}"))
    first = q.pop_batch()  # heavy joined first: its whole bucket drains
    assert [r.tenant for r in first] == ["heavy"] * 6
    # heavy borrowed 6 units at weight 1: light's 3 singles all pre-empt
    out = [q.pop_batch(limit=1)[0].tenant for _ in range(3)]
    assert out == ["light"] * 3
    # an INTERACTIVE job from the most indebted tenant still wins: lanes
    # are strict-priority above the per-lane tenant rings
    q.submit(_FakeReq("heavy", key="same"))
    q.submit(_FakeReq("light", key="lx"))
    q.submit(_FakeReq("heavy", key="now", priority="interactive"))
    assert q.pop_batch(limit=1)[0].priority == "interactive"
    # introspection aggregates across tenants
    assert q.depth() == 2
    assert q.tenant_depths() == {"heavy": 1, "light": 1}
    assert q.lane_depths()["batch"] == 2


def test_queue_drr_debt_survives_emptied_backlog():
    """A bursty tenant that drains a big batch and RESUBMITS after its
    backlog emptied still owes its debt while the lane stays contended
    — resubmit-after-drain must not evade the weight ratios. Only when
    the whole lane goes idle does the fairness state reset."""
    from boojum_tpu.service import AdmissionQueue

    q = AdmissionQueue(capacity=256)
    for _ in range(10):
        q.submit(_FakeReq("bursty", key="same"))
    for i in range(12):
        q.submit(_FakeReq("steady", key=f"s{i}"))
    assert len(q.pop_batch()) == 10  # bursty: whole batch, debt -9
    # bursty rejoins immediately; steady (still backlogged) must now be
    # served ~9 ahead before bursty sees service again
    for _ in range(10):
        q.submit(_FakeReq("bursty", key="same"))
    pre = []
    while True:
        (r,) = q.pop_batch(limit=1)
        if r.tenant == "bursty":
            break
        pre.append(r.tenant)
    assert len(pre) >= 9, f"bursty evaded its debt after {len(pre)} pops"
    # lane going fully idle clears the debts: a later epoch starts fair
    while q.pop_batch(limit=None):
        pass
    assert q.depth() == 0
    q.submit(_FakeReq("bursty", key="fresh"))
    q.submit(_FakeReq("steady", key="fresh2"))
    assert q.pop_batch(limit=1)[0].tenant == "bursty"  # no stale debt


# ---------------------------------------------------------------------------
# Quota ledger (unit, injected clock)
# ---------------------------------------------------------------------------


def test_quota_ledger_window_math():
    from boojum_tpu.service import QuotaLedger, TenantSpec

    led = QuotaLedger(
        [
            TenantSpec("metered", "tok-m", quota_bytes=1000,
                       quota_compute_s=10.0),
            TenantSpec("free", "tok-f"),
        ],
        window_s=60.0,
    )
    ok, ra = led.admit("metered", now=0.0)
    assert ok and ra == 0.0
    rec = led.charge("metered", 700, 2.0, now=1.0)
    assert rec["charged_bytes"] == 700
    assert rec["window_used_bytes"] == 700
    ok, _ = led.admit("metered", now=2.0)
    assert ok  # under both axes
    led.charge("metered", 400, 1.0, now=3.0)  # bytes now 1100 >= 1000
    ok, ra = led.admit("metered", now=10.0)
    assert not ok and abs(ra - 50.0) < 1e-9  # window resets at t=60
    assert led.throttled["metered"] == 1
    # the window turning over re-admits
    ok, _ = led.admit("metered", now=61.0)
    assert ok
    # compute axis throttles independently
    led.charge("metered", 0, 11.0, now=62.0)
    ok, _ = led.admit("metered", now=63.0)
    assert not ok
    # spec-less and unlimited tenants never throttle, but are metered
    assert led.admit("free", now=0.0)[0]
    assert led.admit("stranger", now=0.0)[0]
    led.charge("stranger", 5, 0.1, now=1.0)
    snap = led.snapshot()
    assert snap["stranger.used_bytes"] == 5.0
    assert snap["metered.throttled"] == 2.0
    with pytest.raises(ValueError, match="window_s"):
        QuotaLedger([], window_s=0)


def test_parse_tenant_specs_forms(tmp_path):
    from boojum_tpu.service import parse_tenant_specs

    specs = parse_tenant_specs("a:ta:3,b:tb:1:1000:5.5,root:tr:2:admin")
    assert [(s.id, s.weight) for s in specs] == [
        ("a", 3.0), ("b", 1.0), ("root", 2.0)
    ]
    assert specs[1].quota_bytes == 1000
    assert specs[1].quota_compute_s == 5.5
    assert specs[2].admin and not specs[0].admin
    inline = parse_tenant_specs(
        '[{"id": "x", "token": "tx", "weight": 4, "quota_bytes": 9}]'
    )
    assert inline[0].weight == 4.0 and inline[0].quota_bytes == 9
    p = tmp_path / "tenants.json"
    p.write_text('[{"id": "y", "token": "ty", "admin": true}]')
    from_file = parse_tenant_specs(f"@{p}")
    assert from_file[0].id == "y" and from_file[0].admin
    assert parse_tenant_specs("") == []
    with pytest.raises(ValueError, match="id:token"):
        parse_tenant_specs("lonely")
    # a tenant whose shared secret is literally "admin" keeps it: the
    # flag only strips PAST the mandatory id:token prefix
    (ops,) = parse_tenant_specs("ops:admin")
    assert ops.token == "admin" and not ops.admin


# ---------------------------------------------------------------------------
# Report record: --check rules + per-tenant --slo
# ---------------------------------------------------------------------------


def _line(**extra):
    base = {
        "kind": report.REPORT_KIND, "schema": report.REPORT_SCHEMA,
        "label": "t", "wall_s": 0.1, "spans": [],
        "metrics": {"counters": {}}, "checkpoints": [],
        # schema 4: gateway lines without a trace context fail --check
        "trace_ctx": {"trace_id": "ef" * 16},
    }
    base.update(extra)
    return base


def _req_record(tenant="a", **extra):
    rec = {
        "id": "gw-000001", "tenant": tenant, "bucket": "b",
        "placement": "proof_parallel", "queue_latency_s": 0.01,
        "prove_wall_s": 0.5, "gateway": True,
    }
    rec.update(extra)
    return rec


def test_check_validates_tenant_record():
    good = _line(
        request=_req_record(),
        tenant={"id": "a", "charged_bytes": 10, "charged_compute_s": 0.5,
                "window_used_bytes": 10, "window_used_compute_s": 0.5},
    )
    assert report.validate_report(good) == []
    # gateway-admitted line MISSING the tenant record fails
    naked = _line(request=_req_record())
    assert any(
        "missing its tenant record" in p
        for p in report.validate_report(naked)
    )
    # ...but a plain in-process service line (no gateway flag) is fine
    local = _line(request={k: v for k, v in _req_record().items()
                           if k != "gateway"})
    assert report.validate_report(local) == []
    # negative charges fail
    neg = _line(request=_req_record(),
                tenant={"id": "a", "charged_bytes": -3})
    assert any("charged_bytes" in p for p in report.validate_report(neg))
    # a rejection line never proves
    rej = _line(tenant={"id": "b", "rejected": 429, "reason": "throttled",
                        "retry_after_s": 12.5})
    assert report.validate_report(rej) == []
    lying = _line(
        tenant={"id": "b", "rejected": 429, "reason": "throttled"},
        request=_req_record(tenant="b"),
    )
    assert any(
        "must never prove" in p for p in report.validate_report(lying)
    )
    # malformed shapes are named
    assert any(
        "tenant record malformed" in p
        for p in report.validate_report(_line(tenant=[1, 2]))
    )
    assert any(
        "id invalid" in p
        for p in report.validate_report(_line(tenant={"id": ""}))
    )


def test_slo_summarizes_tenants_and_shed_counts():
    lines = [
        _line(request=_req_record(tenant="a", prove_wall_s=1.0),
              tenant={"id": "a", "charged_bytes": 1,
                      "charged_compute_s": 1.0}),
        _line(request=_req_record(tenant="a", prove_wall_s=3.0,
                                  queue_latency_s=0.2),
              tenant={"id": "a", "charged_bytes": 1,
                      "charged_compute_s": 3.0}),
        _line(request=_req_record(tenant="b", prove_wall_s=2.0),
              tenant={"id": "b", "charged_bytes": 1,
                      "charged_compute_s": 2.0}),
        _line(tenant={"id": "b", "rejected": 429, "reason": "throttled",
                      "retry_after_s": 5.0}),
        _line(tenant={"id": "c", "rejected": 503, "reason": "shed"}),
    ]
    s = report.slo_summary(lines)
    assert s["requests"] == 3
    assert s["rejected"] == {"throttled": 1, "shed": 1}
    assert s["tenants"]["a"]["requests"] == 2
    assert s["tenants"]["a"]["prove_wall_p95_s"] == 3.0
    assert s["tenants"]["b"] == {
        "requests": 1, "rejected": 1,
        "queue_latency_p95_s": 0.01, "prove_wall_p95_s": 2.0,
    }
    assert s["tenants"]["c"]["requests"] == 0
    assert s["tenants"]["c"]["rejected"] == 1
    text = report.render_slo(s)
    assert "throttled(429)=1" in text and "shed=1" in text
    assert "tenant a" in text and "tenant c" in text


# ---------------------------------------------------------------------------
# Socket-free gateway routing
# ---------------------------------------------------------------------------


class _FakeProof:
    def __init__(self, payload):
        self._payload = payload

    def to_json(self):
        return json.dumps({"proof": self._payload})


def _parts_small():
    from test_limb_sweep import _small_prove_parts

    return _small_prove_parts()


def _fake_run_request(self, req, placement, packed=1, device=None):
    """Stands in for ProvingService._run_request: stamps a well-formed
    SLO record + a deterministic fake proof, no proving."""
    req.slo = {
        "schema": 1, "id": req.id, "tenant": req.tenant,
        "priority": req.priority, "bucket": req.bucket_key,
        "placement": placement.kind, "packed": packed,
        "occupancy": 0.125, "queue_latency_s": 0.001,
        "cache_hit": False, "prove_wall_s": 0.25,
    }
    if req.gateway:
        req.slo["gateway"] = True
    if req.trace:
        req.slo["trace_id"] = req.trace["trace_id"]
    req.proof = _FakeProof(req.bucket_key)
    with self._stats_lock:
        self.stats["served"] += 1
    req._done.set()
    return 1


@pytest.fixture
def stub_gateway(tmp_path, monkeypatch):
    """A Gateway over a ProvingService whose prove is stubbed out —
    routing, quotas, idempotency and drain logic without sockets or
    XLA. The worker loop is NOT started; tests drain explicitly."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from boojum_tpu.service import (
        Gateway,
        GatewayConfig,
        ProvingService,
        ServiceConfig,
        TenantSpec,
    )

    monkeypatch.setattr(
        ProvingService, "_run_request", _fake_run_request
    )
    rpt = str(tmp_path / "gw.jsonl")
    svc = ProvingService(
        ServiceConfig(precompile="off", report_path=rpt)
    )
    cfg = GatewayConfig(
        tenants=[
            TenantSpec("alice", "tok-alice", weight=2.0),
            TenantSpec("bob", "tok-bob", quota_bytes=1),
            TenantSpec("ops", "tok-ops", admin=True),
        ],
        spool_dir=str(tmp_path / "spool"),
        shed_mem_bytes=None,
    )
    gw = Gateway(svc, cfg, resolver=lambda spec: _parts_small())
    return gw, svc, rpt


def _post(gw, path, token=None, body=b"{}", idem=None):
    headers = {}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    if idem:
        headers["Idempotency-Key"] = idem
    out = gw.handle("POST", path, headers, body)
    code, payload = out[0], json.loads(out[1])
    return code, payload, (out[3] if len(out) > 3 else {})


def _get(gw, path, token=None):
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    out = gw.handle("GET", path, headers, b"")
    return out[0], out[1], out[2]


def test_gateway_auth_and_spec_validation(stub_gateway):
    gw, svc, _rpt = stub_gateway
    assert _post(gw, "/prove")[0] == 401
    assert _post(gw, "/prove", token="wrong")[0] == 401
    code, payload, _ = _post(gw, "/prove", token="tok-alice",
                             body=b"not json")
    assert code == 400 and "bad job spec" in payload["error"]
    code, payload, _ = _post(
        gw, "/prove", token="tok-alice",
        body=json.dumps({"priority": "warp"}).encode(),
    )
    assert code == 400 and "priority" in payload["error"]
    assert _post(gw, "/nope", token="tok-alice")[0] == 404
    assert gw.handle("PUT", "/prove", {}, b"")[0] == 405
    # admin verbs refuse non-admin tenants
    assert _post(gw, "/admin/drain", token="tok-alice")[0] == 403
    reg = svc.sampler.registry.to_dict()["counters"]
    assert reg["service.gateway.auth_failures"] >= 2


def test_gateway_ticket_status_proof_and_isolation(stub_gateway):
    gw, svc, rpt = stub_gateway
    code, ticket, _ = _post(gw, "/prove", token="tok-alice")
    assert code == 202 and ticket["status"] == "queued"
    job = ticket["job"]
    # queued: proof download is a 409, status visible to the owner only
    assert _get(gw, f"/jobs/{job}/proof", token="tok-alice")[0] == 409
    assert _get(gw, f"/jobs/{job}", token="tok-bob")[0] == 404
    assert svc.run_worker()["served"] == 1  # drain (stubbed prove)
    code, body, _ = _get(gw, f"/jobs/{job}", token="tok-alice")
    status = json.loads(body)
    assert code == 200 and status["status"] == "done"
    assert status["request"]["gateway"] is True
    code, proof_bytes, ctype = _get(
        gw, f"/jobs/{job}/proof", token="tok-alice"
    )
    assert code == 200 and ctype == "application/json"
    assert json.loads(proof_bytes)["proof"]
    # the admin tenant sees foreign jobs; strangers see 404
    assert _get(gw, f"/jobs/{job}", token="tok-ops")[0] == 200
    assert _get(gw, "/jobs/gw-999999", token="tok-alice")[0] == 404
    # the composed read plane answers under the same router
    assert _get(gw, "/healthz")[0] == 200
    assert b"boojum_tpu_" in _get(gw, "/metrics")[1]
    # the request line carries the tenant record and passes --check
    lines = report.load_reports(rpt)
    (req_line,) = [ln for ln in lines if "request" in ln]
    assert req_line["tenant"]["id"] == "alice"
    assert req_line["tenant"]["charged_bytes"] > 0
    assert report.validate_report(req_line) == []


def test_gateway_idempotent_replay_never_reproves(stub_gateway):
    gw, svc, _rpt = stub_gateway
    code, t1, _ = _post(gw, "/prove", token="tok-alice", idem="key-1")
    assert code == 202
    svc.run_worker()
    served = svc.summary()["served"]
    code, t2, _ = _post(gw, "/prove", token="tok-alice", idem="key-1")
    assert code == 200 and t2["replay"] is True
    assert t2["job"] == t1["job"] and t2["status"] == "done"
    assert svc.summary()["served"] == served  # no second prove
    assert svc.queue.depth() == 0
    # proof bytes identical across replayed downloads
    p1 = _get(gw, f"/jobs/{t1['job']}/proof", token="tok-alice")[1]
    p2 = _get(gw, f"/jobs/{t2['job']}/proof", token="tok-alice")[1]
    assert p1 == p2
    # same key, DIFFERENT tenant: a fresh job (keys are tenant-scoped)
    code, t3, _ = _post(gw, "/prove", token="tok-ops", idem="key-1")
    assert code == 202 and t3["job"] != t1["job"]
    counters = svc.sampler.registry.to_dict()["counters"]
    assert counters["service.gateway.replays"] == 1


def test_gateway_idempotency_reserved_before_serving(stub_gateway):
    """The (tenant, key) reservation happens atomically with the check:
    a duplicate POST arriving while the original is still QUEUED gets
    the original ticket (status queued) — never a second job. And a
    REJECTED admission rolls its reservation back so the key can be
    retried."""
    gw, svc, _rpt = stub_gateway
    code, t1, _ = _post(gw, "/prove", token="tok-alice", idem="dup")
    assert code == 202 and t1["status"] == "queued"
    # duplicate while the original is in flight: replay of the SAME
    # ticket, still queued, nothing new enters the service queue
    code, t2, _ = _post(gw, "/prove", token="tok-alice", idem="dup")
    assert code == 200 and t2["replay"] is True
    assert t2["job"] == t1["job"] and t2["status"] == "queued"
    assert svc.queue.depth() == 1
    assert svc.run_worker()["served"] == 1
    # a rejected admission releases its key: bad spec now, good later
    code, _p, _ = _post(gw, "/prove", token="tok-alice",
                        body=b"not json", idem="retry-me")
    assert code == 400
    code, t3, _ = _post(gw, "/prove", token="tok-alice", idem="retry-me")
    assert code == 202  # the key was NOT burnt by the 400
    svc.run_worker()
    # a duplicate landing while the winner is BETWEEN reservation and
    # admission gets 409-retry, never a ticket that might evaporate
    placeholder_id = None
    with gw._lock:
        placeholder_id = f"gw-{next(gw._ids):06d}"
        from boojum_tpu.service import GatewayJob

        gw._jobs[placeholder_id] = GatewayJob(
            id=placeholder_id, tenant="alice", spec={},
            idem_key="racing", created_ts=0.0,
        )
        gw._idem[("alice", "racing")] = placeholder_id
    code, payload, headers = _post(gw, "/prove", token="tok-alice",
                                   idem="racing")
    assert code == 409 and headers["Retry-After"]
    gw._unreserve(gw._jobs[placeholder_id])


def test_gateway_job_ledger_is_bounded(stub_gateway):
    """Finished tickets (and their idempotency keys) are evicted above
    max_jobs, oldest first; live tickets are never evicted."""
    gw, svc, _rpt = stub_gateway
    gw.config.max_jobs = 3
    ids = []
    for i in range(3):
        code, t, _ = _post(gw, "/prove", token="tok-alice",
                           idem=f"k{i}")
        assert code == 202
        ids.append(t["job"])
        svc.run_worker()  # finish each before the next admission
    code, t, _ = _post(gw, "/prove", token="tok-alice")
    assert code == 202
    ids.append(t["job"])
    # the oldest finished ticket fell off the ledger...
    assert ids[0] not in gw._jobs
    assert _get(gw, f"/jobs/{ids[0]}", token="tok-alice")[0] == 404
    assert set(ids[1:]) <= set(gw._jobs)
    # ...and its idempotency key with it: the key is reusable
    code, t_new, _ = _post(gw, "/prove", token="tok-alice", idem="k0")
    assert code == 202 and t_new["job"] != ids[0]
    svc.run_worker()


def test_gateway_quota_429_with_retry_after(stub_gateway):
    gw, svc, rpt = stub_gateway
    code, ticket, _ = _post(gw, "/prove", token="tok-bob")
    assert code == 202
    svc.run_worker()
    # bob's 1-byte budget is burnt by the first request's charge
    assert svc.quota.snapshot()["bob.used_bytes"] > 0
    code, payload, headers = _post(gw, "/prove", token="tok-bob")
    assert code == 429
    assert payload["retry_after_s"] > 0
    assert int(headers["Retry-After"]) >= 1
    # alice is untouched by bob's throttle
    assert _post(gw, "/prove", token="tok-alice")[0] == 202
    svc.run_worker()
    # the rejection rode the artifact and the whole file still checks
    lines = report.load_reports(rpt)
    rejects = [
        ln for ln in lines
        if (ln.get("tenant") or {}).get("rejected")
    ]
    assert len(rejects) == 1
    assert rejects[0]["tenant"]["id"] == "bob"
    assert rejects[0]["tenant"]["reason"] == "throttled"
    assert "request" not in rejects[0]
    for ln in lines:
        assert report.validate_report(ln) == [], ln.get("label")
    s = report.slo_summary(lines)
    assert s["rejected"]["throttled"] == 1
    assert s["tenants"]["bob"]["rejected"] == 1


def test_gateway_load_shed_bulk_only(stub_gateway):
    gw, svc, rpt = stub_gateway
    gw.config.shed_queue_depth = 1
    assert _post(gw, "/prove", token="tok-alice")[0] == 202  # depth -> 1
    code, payload, headers = _post(
        gw, "/prove", token="tok-alice",
        body=json.dumps({"priority": "bulk"}).encode(),
    )
    assert code == 503 and "shed" in payload["error"]
    assert headers["Retry-After"]
    # non-bulk lanes are exempt: load-shed protects latency work
    assert _post(gw, "/prove", token="tok-alice")[0] == 202
    counters = svc.sampler.registry.to_dict()["counters"]
    assert counters["service.gateway.shed"] == 1
    svc.run_worker()
    shed_lines = [
        ln for ln in report.load_reports(rpt)
        if (ln.get("tenant") or {}).get("reason") == "shed"
    ]
    assert len(shed_lines) == 1
    assert report.validate_report(shed_lines[0]) == []


def test_gateway_spools_bulk_jobs_for_the_fleet(stub_gateway):
    gw, svc, _rpt = stub_gateway
    from boojum_tpu.service import read_spool

    spec = {"priority": "bulk", "seed": 7}
    code, ticket, _ = _post(
        gw, "/prove", token="tok-alice", body=json.dumps(spec).encode()
    )
    assert code == 202 and ticket["status"] == "spooled"
    ((fname, spooled),) = read_spool(gw.config.spool_dir)
    assert fname == f"{ticket['job']}.json"
    assert spooled["job"] == ticket["job"]
    assert spooled["tenant"] == "alice"
    assert spooled["seed"] == 7 and spooled["priority"] == "bulk"
    # nothing entered the local queue: the fleet owns this job...
    assert svc.queue.depth() == 0
    # ...but the spool-file bytes WERE charged to alice's byte quota at
    # admission (the fleet owns only the compute axis)
    assert svc.quota.snapshot()["alice.used_bytes"] > 0
    # bob's 1-byte budget: his second spooled job throttles — spool
    # mode cannot bypass the quota
    assert _post(gw, "/prove", token="tok-bob",
                 body=json.dumps(spec).encode())[0] == 202
    assert _post(gw, "/prove", token="tok-bob",
                 body=json.dumps(spec).encode())[0] == 429
    # ticket remains queryable; corrupt spool entries are skipped
    assert _get(gw, f"/jobs/{ticket['job']}", token="tok-alice")[0] == 200
    with open(os.path.join(gw.config.spool_dir, "junk.json"), "w") as f:
        f.write("{truncated")
    assert len(read_spool(gw.config.spool_dir)) == 2


def test_gateway_admin_token_and_denial_counters(stub_gateway):
    """The standalone admin_token (no tenant row) can read any ticket
    AND call admin verbs; a known tenant probing admin verbs counts on
    admin_denied, not on the bad-token auth_failures alarm."""
    gw, svc, _rpt = stub_gateway
    gw.config.admin_token = "op5"
    code, ticket, _ = _post(gw, "/prove", token="tok-alice")
    assert code == 202
    svc.run_worker()
    assert _get(gw, f"/jobs/{ticket['job']}", token="op5")[0] == 200
    code, payload, _ = _post(gw, "/admin/reload-artifacts", token="op5")
    assert code == 200 and payload["reloaded"] is True
    before = dict(svc.sampler.registry.to_dict()["counters"])
    assert _post(gw, "/admin/drain", token="tok-alice")[0] == 403
    after = svc.sampler.registry.to_dict()["counters"]
    assert after["service.gateway.admin_denied"] == 1
    assert after.get("service.gateway.auth_failures", 0) == before.get(
        "service.gateway.auth_failures", 0
    )


def test_gateway_wait_jobs_api(stub_gateway):
    """The public harness surface: wait_jobs blocks for local jobs and
    refuses spooled ones; job() looks tickets up."""
    gw, svc, _rpt = stub_gateway
    code, t1, _ = _post(gw, "/prove", token="tok-alice")
    assert code == 202
    svc.run_worker()
    (req,) = gw.wait_jobs([t1["job"]], timeout_s=5.0)
    assert req.done() and gw.job(t1["job"]).status() == "done"
    with pytest.raises(KeyError):
        gw.wait_jobs(["gw-999999"])
    code, ts, _ = _post(
        gw, "/prove", token="tok-alice",
        body=json.dumps({"priority": "bulk"}).encode(),
    )
    assert code == 202
    with pytest.raises(ValueError, match="spooled"):
        gw.wait_jobs([ts["job"]])


def test_gateway_drain_and_reload_verbs(stub_gateway):
    gw, svc, rpt = stub_gateway
    code, ticket, _ = _post(gw, "/prove", token="tok-alice")
    assert code == 202
    svc.run_worker()
    # hot AOT reload: warm keys forgotten, queue untouched
    svc.warmer._warmed.add(("bucket", "proof_parallel"))
    code, payload, _ = _post(gw, "/admin/reload-artifacts",
                             token="tok-ops")
    assert code == 200 and payload["warm_keys_cleared"] == 1
    assert svc.warmer._warmed == set()
    # graceful drain: finishes (nothing in flight), flags drained,
    # then refuses new admissions 503 while replays still answer
    code, payload, _ = _post(gw, "/admin/drain", token="tok-ops")
    assert code == 200 and payload["drained"] is True
    assert gw.drained.is_set()
    assert payload["summary"]["served"] == 1
    code, payload, headers = _post(gw, "/prove", token="tok-alice")
    assert code == 503 and "draining" in payload["error"]
    assert headers["Retry-After"]
    counters = svc.sampler.registry.to_dict()["counters"]
    assert counters["service.gateway.drains"] == 1


def test_gateway_trace_propagation(stub_gateway):
    """ISSUE 17 tentpole: the gateway mints ONE trace at POST /prove
    (honoring an inbound X-Boojum-Trace header) and that id rides the
    ticket, the response header, the request line's trace_ctx, the
    queue.wait span, the 429 rejection line and the spool file — so a
    single request's whole story stitches under one trace_id."""
    gw, svc, rpt = stub_gateway
    from boojum_tpu.service import read_spool
    from boojum_tpu.utils import spans as spans_mod

    tid = "ab" * 16
    psid = "cd" * 8
    traced_headers = {
        "Authorization": "Bearer tok-alice",
        "X-Boojum-Trace": f"{tid}:{psid}",
    }
    out = gw.handle("POST", "/prove", traced_headers, b"{}")
    assert out[0] == 202
    ticket = json.loads(out[1])
    assert ticket["trace"] == tid
    assert out[3]["X-Boojum-Trace"] == tid
    # a header-less admission mints a fresh, distinct, well-formed id
    code, t2, h2 = _post(gw, "/prove", token="tok-alice")
    assert code == 202
    assert spans_mod.valid_trace_id(t2["trace"]) and t2["trace"] != tid
    assert h2["X-Boojum-Trace"] == t2["trace"]
    svc.run_worker()

    lines = report.load_reports(rpt)
    req_lines = [ln for ln in lines if "request" in ln]
    assert len(req_lines) == 2
    by_tid = {ln["trace_ctx"]["trace_id"]: ln for ln in req_lines}
    assert set(by_tid) == {tid, t2["trace"]}
    for ln in req_lines:
        # admission queueing is a REAL (backdated) span: queue.wait
        # roots the line's tree, chained to the gateway's admit span
        (qw,) = [sp for sp in ln["spans"] if sp["name"] == "queue.wait"]
        assert report.SPAN_ID_RE.match(qw["span_id"])
        assert qw["trace_id"] == ln["trace_ctx"]["trace_id"]
        assert qw["parent_span_id"] == ln["trace_ctx"]["parent_span_id"]
        assert qw["attrs"]["request"] == ln["request"]["id"]
        assert ln["request"]["trace_id"] == ln["trace_ctx"]["trace_id"]

    # bob's second request 429s AFTER his quota charge lands; the
    # rejection line still tells the trace's story
    assert _post(gw, "/prove", token="tok-bob")[0] == 202
    svc.run_worker()
    out = gw.handle(
        "POST", "/prove",
        {"Authorization": "Bearer tok-bob", "X-Boojum-Trace": tid}, b"{}",
    )
    assert out[0] == 429

    # a spooled bulk job: the trace context rides the spool file for
    # the fleet AND the admit span materializes in a gateway line
    out = gw.handle(
        "POST", "/prove", traced_headers,
        json.dumps({"priority": "bulk"}).encode(),
    )
    assert out[0] == 202 and json.loads(out[1])["status"] == "spooled"
    ((_fname, spooled),) = read_spool(gw.config.spool_dir)
    assert spooled["trace"]["trace_id"] == tid

    lines = report.load_reports(rpt)
    rejects = [
        ln for ln in lines if (ln.get("tenant") or {}).get("rejected")
    ]
    assert len(rejects) == 1
    assert rejects[0]["trace_ctx"]["trace_id"] == tid
    (spool_line,) = [
        ln for ln in lines if ln.get("label") == "gateway:spool"
    ]
    (admit,) = spool_line["spans"]
    assert admit["name"] == "gateway.admit"
    assert admit["trace_id"] == tid
    assert admit["parent_span_id"] == psid  # inbound header's parent
    (sw,) = admit["children"]
    assert sw["name"] == "gateway.spool_write"
    assert sw["parent_span_id"] == admit["span_id"]
    assert spool_line["trace_ctx"] == {
        "trace_id": tid, "parent_span_id": psid,
    }
    # every line validates and NO span_id repeats across the artifact
    for ln in lines:
        assert report.validate_report(ln) == [], ln.get("label")
    assert report.validate_artifact(lines) == []


def test_gateway_line_trace_rules_fail_closed():
    """--check's trace rules: a schema-4 gateway line WITHOUT trace_ctx
    fails, and two report lines sharing a span_id fail the artifact."""
    base = {
        "kind": report.REPORT_KIND,
        "schema": report.REPORT_SCHEMA,
        "unix_ts": 1.0,
        "wall_s": 0.0,
        "spans": [],
        "metrics": {"counters": {}, "gauges": {}},
        "checkpoints": [],
    }
    naked = dict(base, label="gateway:throttled",
                 tenant={"id": "t", "rejected": 429, "reason": "throttled"})
    assert any(
        "missing trace_ctx" in p for p in report.validate_report(naked)
    )
    assert report.validate_report(
        dict(naked, trace_ctx={"trace_id": "ab" * 16})
    ) == []
    sp = {
        "name": "s", "start_s": 0.0, "wall_s": 0.0,
        "span_id": "11" * 8, "children": [],
    }
    a = dict(base, label="a", spans=[dict(sp)])
    b = dict(base, label="b", spans=[dict(sp)])
    assert report.validate_report(a) == []
    probs = report.validate_artifact([a, b])
    assert probs and "collides" in probs[0]


# ---------------------------------------------------------------------------
# Sockets: the error-counting satellite + the E2E acceptance run
# ---------------------------------------------------------------------------


def _http(url, method="GET", token=None, body=None, idem=None, timeout=30,
          trace=None):
    headers = {"Content-Type": "application/json"}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    if idem:
        headers["Idempotency-Key"] = idem
    if trace:
        headers["X-Boojum-Trace"] = trace
    req = urllib.request.Request(
        url, data=body, headers=headers, method=method
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read(), dict(r.headers)


@pytest.mark.gateway
def test_http_metrics_500_body_and_error_counter(monkeypatch):
    """Satellite: a read-plane handler exception answers 500 WITH a
    JSON body and is charged to service.http.errors (visible on the
    next /metrics scrape) — never a dropped connection."""
    from boojum_tpu.service.http_metrics import MetricsPlane
    from boojum_tpu.utils import telemetry

    s = telemetry.TelemetrySampler(interval_s=5.0)
    s.sample_once()
    plane = MetricsPlane(s, port=0)
    plane.start()
    try:
        real = plane.render_metrics
        monkeypatch.setattr(
            plane, "render_metrics",
            lambda: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            _http(plane.url("/metrics"))
        assert exc.value.code == 500
        assert "boom" in json.loads(exc.value.read())["error"]
        monkeypatch.setattr(plane, "render_metrics", real)
        _status, body, _ = _http(plane.url("/metrics"))
        assert b"boojum_tpu_service_http_errors 1" in body
    finally:
        plane.stop()


def _checkpoint_stream(rep):
    return [
        (e["seq"], e["round"], e["label"], e["digest"])
        for e in rep["checkpoints"]
    ]


def _wait_done(base, job, token, deadline_s=300.0):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        _status, body, _ = _http(f"{base}/jobs/{job}", token=token)
        ticket = json.loads(body)
        if ticket["status"] in ("done", "failed"):
            return ticket
        time.sleep(0.1)
    raise TimeoutError(f"job {job} still {ticket['status']}")


@pytest.mark.gateway
def test_e2e_two_tenants_over_http(tmp_path):
    """ISSUE 11 acceptance: two tenants over real loopback HTTP —
    proof bytes + checkpoint streams bit-identical to direct prove(),
    idempotent replay from the ledger without a second prove, one
    tenant 429-throttled while the other completes, drain -> the
    artifact passes prove_report.py --check and --slo shows tenants."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from boojum_tpu.prover import prove
    from boojum_tpu.service import (
        Gateway,
        GatewayConfig,
        ProvingService,
        ServiceConfig,
        TenantSpec,
    )

    asm, setup, cfg = _parts_small()
    with report.flight_recording(label="direct") as rec:
        direct = prove(asm, setup, cfg)
    direct_line = report.build_report(rec)

    rpt = str(tmp_path / "gw_e2e.jsonl")
    svc = ProvingService(
        ServiceConfig(precompile="off", report_path=rpt,
                      telemetry_interval_s=0.2)
    )
    gw = Gateway(
        svc,
        GatewayConfig(
            tenants=[
                TenantSpec("alice", "tok-alice", weight=2.0),
                # bob's byte budget dies with his first proof download
                TenantSpec("bob", "tok-bob", quota_bytes=1),
            ],
            admin_token="tok-admin",
        ),
        resolver=lambda spec: (asm, setup, cfg),
    )
    port = gw.start()
    base = f"http://127.0.0.1:{port}"
    try:
        e2e_tid = "5a" * 16  # a client-minted trace id, honored end to end
        code, body, hdrs = _http(
            f"{base}/prove", "POST", token="tok-alice", body=b"{}",
            idem="alice-req-1", trace=e2e_tid,
        )
        assert code == 202
        assert hdrs["X-Boojum-Trace"] == e2e_tid
        ticket_a1 = json.loads(body)
        assert ticket_a1["trace"] == e2e_tid
        job_a1 = ticket_a1["job"]
        code, body, _ = _http(
            f"{base}/prove", "POST", token="tok-bob", body=b"{}"
        )
        assert code == 202
        job_b = json.loads(body)["job"]

        ta1 = _wait_done(base, job_a1, "tok-alice")
        tb = _wait_done(base, job_b, "tok-bob")
        assert ta1["status"] == "done" and tb["status"] == "done"

        # bit-parity over the wire: downloaded proof == direct prove()
        for job, tok in ((job_a1, "tok-alice"), (job_b, "tok-bob")):
            _s, proof_bytes, _h = _http(f"{base}/jobs/{job}/proof",
                                        token=tok)
            assert proof_bytes.decode() == direct.to_json(), job

        # idempotent replay: original ticket, zero extra proves
        served_before = svc.summary()["served"]
        code, body, _ = _http(
            f"{base}/prove", "POST", token="tok-alice", body=b"{}",
            idem="alice-req-1",
        )
        replay = json.loads(body)
        assert code == 200 and replay["replay"] is True
        assert replay["job"] == job_a1
        assert svc.summary()["served"] == served_before

        # bob exhausted his byte quota with that one proof; the charge
        # lands right after his line is written — wait for it, then the
        # next submit must 429 while alice keeps being served
        deadline = time.time() + 60
        while time.time() < deadline:
            if svc.quota.snapshot().get("bob.used_bytes", 0) > 0:
                break
            time.sleep(0.05)
        with pytest.raises(urllib.error.HTTPError) as exc:
            _http(f"{base}/prove", "POST", token="tok-bob", body=b"{}")
        assert exc.value.code == 429
        assert int(exc.value.headers["Retry-After"]) >= 1
        code, body, _ = _http(
            f"{base}/prove", "POST", token="tok-alice", body=b"{}"
        )
        assert code == 202
        job_a2 = json.loads(body)["job"]
        assert _wait_done(base, job_a2, "tok-alice")["status"] == "done"

        # per-tenant telemetry rides /metrics
        svc.sampler.sample_once()
        _s, metrics_body, _h = _http(f"{base}/metrics")
        text = metrics_body.decode()
        assert "boojum_tpu_service_gateway_admitted 3" in text
        assert "boojum_tpu_service_gateway_throttled 1" in text
        assert "boojum_tpu_telemetry_service_tenant_alice_used_bytes" \
            in text

        # graceful drain finishes in-flight work and stops admission
        code, body, _ = _http(
            f"{base}/admin/drain", "POST", token="tok-admin", body=b"{}"
        )
        drain = json.loads(body)
        assert code == 200 and drain["drained"] is True
        assert drain["summary"]["served"] == 3
        with pytest.raises(urllib.error.HTTPError) as exc:
            _http(f"{base}/prove", "POST", token="tok-alice", body=b"{}")
        assert exc.value.code == 503
    finally:
        gw.stop()

    # the artifact: 3 gateway request lines (tenant records attached,
    # checkpoint streams bit-identical to direct) + 1 rejection line
    lines = report.load_reports(rpt)
    req_lines = [ln for ln in lines if "request" in ln]
    assert len(req_lines) == 3
    base_stream = _checkpoint_stream(direct_line)
    assert base_stream
    for ln in req_lines:
        assert _checkpoint_stream(ln) == base_stream, ln["request"]["id"]
        assert ln["request"]["gateway"] is True
        assert ln["tenant"]["charged_bytes"] > 0
        assert report.validate_report(ln) == [], ln["request"]["id"]
    rejects = [ln for ln in lines
               if (ln.get("tenant") or {}).get("rejected")]
    assert len(rejects) == 1 and rejects[0]["tenant"]["id"] == "bob"

    # ISSUE 17 acceptance: ONE trace_id spans admission -> prove ->
    # proof download — the client-minted id tags exactly alice's first
    # request line, whose tree holds both the backdated queue.wait and
    # the real prove stages, and no span_id repeats across the artifact
    traced = [
        ln for ln in req_lines
        if (ln.get("trace_ctx") or {}).get("trace_id") == e2e_tid
    ]
    assert len(traced) == 1
    tr_names = {name.split("/")[-1]
                for name, _sp in report.flatten_spans(traced[0])}
    assert "queue.wait" in tr_names and "prove" in tr_names
    assert report.validate_artifact(lines) == []

    # the stdlib CLI gate agrees, end to end
    cli = os.path.join(REPO_ROOT, "scripts", "prove_report.py")
    env = {k: v for k, v in os.environ.items() if k != "PYTHONSTARTUP"}
    chk = subprocess.run(
        [sys.executable, cli, "--check", rpt],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert chk.returncode == 0, chk.stdout + chk.stderr
    slo = subprocess.run(
        [sys.executable, cli, "--slo", rpt],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert slo.returncode == 0, slo.stdout + slo.stderr
    assert "tenant alice" in slo.stdout
    assert "throttled(429)=1" in slo.stdout

    # --timeline stitches the artifact and the Perfetto export is valid
    # trace-event JSON carrying the queue-wait and prove-stage spans
    perfetto_out = str(tmp_path / "e2e_perfetto.json")
    tl = subprocess.run(
        [sys.executable, cli, "--timeline", rpt, "--perfetto",
         perfetto_out],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert tl.returncode == 0, tl.stdout + tl.stderr
    assert f"trace {e2e_tid[:8]}" in tl.stdout
    with open(perfetto_out) as f:
        doc = json.load(f)
    names = {e["name"] for e in doc["traceEvents"]}
    assert "queue.wait" in names and "prove" in names
    assert report.validate_perfetto(doc) == []
