"""End-to-end prove -> verify on toy circuits (reference test model:
prove_sha256-style full-pipeline runs, sha256/mod.rs:296)."""

import numpy as np

from boojum_tpu.cs.types import CSGeometry
from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.gates import (
    BooleanConstraintGate,
    ConstantsAllocatorGate,
    FmaGate,
    PublicInputGate,
    SelectionGate,
)
from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify
from boojum_tpu.prover.satisfiability import check_if_satisfied
from boojum_tpu.prover.proof import Proof
from boojum_tpu.field import gl

GEOM = CSGeometry(
    num_columns_under_copy_permutation=8,
    num_witness_columns=0,
    num_constant_columns=6,
    max_allowed_constraint_degree=4,
)

CONFIG = ProofConfig(
    fri_lde_factor=8,
    merkle_tree_cap_size=4,
    num_queries=20,
    pow_bits=0,
    fri_final_degree=4,
)


def build_fibonacci_circuit(steps=40, with_public_input=True):
    """Fibonacci-ish chain: x_{i+1} = x_i * x_{i-1} + x_i, mixed with
    booleans and selects; exposes the final value as a public input."""
    cs = ConstraintSystem(GEOM, 1 << 10)
    a = cs.alloc_variable_with_value(1)
    b = cs.alloc_variable_with_value(2)
    flag = cs.alloc_variable_with_value(1)
    BooleanConstraintGate.enforce(cs, flag)
    for _ in range(steps):
        c = FmaGate.fma(cs, a, b, a, 1, 1)
        a, b = b, c
    sel = SelectionGate.select(cs, flag, a, b)
    if with_public_input:
        PublicInputGate.place(cs, sel)
    return cs, sel


def test_e2e_prove_verify():
    cs, out_var = build_fibonacci_circuit()
    expected = cs.get_value(out_var)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)
    setup = generate_setup(asm, CONFIG)
    proof = prove(asm, setup, CONFIG)
    assert proof.public_inputs == [expected]
    gates = asm.gates
    assert verify(setup.vk, proof, gates), "honest proof must verify"


def test_e2e_rejects_tampering():
    cs, _ = build_fibonacci_circuit(steps=10)
    asm = cs.into_assembly()
    setup = generate_setup(asm, CONFIG)
    proof = prove(asm, setup, CONFIG)
    gates = asm.gates
    assert verify(setup.vk, proof, gates)
    # tamper public input
    p2 = Proof.from_json(proof.to_json())
    p2.public_inputs[0] = (p2.public_inputs[0] + 1) % gl.P
    assert not verify(setup.vk, p2, gates)
    # tamper an opened value
    p3 = Proof.from_json(proof.to_json())
    v = list(p3.values_at_z[3])
    v[0] = (v[0] + 1) % gl.P
    p3.values_at_z[3] = tuple(v)
    assert not verify(setup.vk, p3, gates)
    # tamper a cap
    p4 = Proof.from_json(proof.to_json())
    c = list(p4.witness_cap[0])
    c[0] = (c[0] + 1) % gl.P
    p4.witness_cap[0] = tuple(c)
    assert not verify(setup.vk, p4, gates)
    # tamper FRI final monomials
    p5 = Proof.from_json(proof.to_json())
    m = list(p5.final_fri_monomials[0])
    m[0] = (m[0] + 1) % gl.P
    p5.final_fri_monomials[0] = tuple(m)
    assert not verify(setup.vk, p5, gates)


def test_empty_queries_rejected():
    cs, _ = build_fibonacci_circuit(steps=5)
    asm = cs.into_assembly()
    setup = generate_setup(asm, CONFIG)
    proof = prove(asm, setup, CONFIG)
    p = Proof.from_json(proof.to_json())
    p.queries = []
    assert not verify(setup.vk, p, asm.gates)


def test_pow_grinding():
    cfg = ProofConfig(
        fri_lde_factor=8, merkle_tree_cap_size=4, num_queries=4,
        pow_bits=4, fri_final_degree=4,
    )
    cs, _ = build_fibonacci_circuit(steps=5)
    asm = cs.into_assembly()
    setup = generate_setup(asm, cfg)
    proof = prove(asm, setup, cfg)
    assert verify(setup.vk, proof, asm.gates)
    bad = Proof.from_json(proof.to_json())
    bad.pow_challenge += 1
    assert not verify(setup.vk, bad, asm.gates)


def test_proof_json_roundtrip():
    cs, _ = build_fibonacci_circuit(steps=5)
    asm = cs.into_assembly()
    setup = generate_setup(asm, CONFIG)
    proof = prove(asm, setup, CONFIG)
    p2 = Proof.from_json(proof.to_json())
    assert verify(setup.vk, p2, asm.gates)


def test_fri_folding_schedules():
    """Grouped FRI (reference folding schedules + leaf regrouping): an
    explicit schedule and the derived greedy one both prove and verify;
    schedule shape shows up in the proof (oracle count, leaf sizes)."""
    from boojum_tpu.prover.fri import fold_schedule

    assert fold_schedule(1 << 10, 4) == [3, 3, 2]
    assert fold_schedule(1 << 10, 4, [2, 2, 2, 2]) == [2, 2, 2, 2]

    cs, _ = build_fibonacci_circuit(steps=40)
    asm = cs.into_assembly()
    num_folds = (asm.trace_len // 4).bit_length() - 1
    assert num_folds >= 2
    for schedule in (None, [1] * num_folds, [num_folds - 1, 1]):
        cfg = ProofConfig(
            fri_lde_factor=8,
            merkle_tree_cap_size=4,
            num_queries=4,
            pow_bits=0,
            fri_final_degree=4,
            fri_folding_schedule=schedule,
        )
        setup = generate_setup(asm, cfg)
        proof = prove(asm, setup, cfg)
        expect = fold_schedule(asm.trace_len, 4, schedule)
        assert len(proof.fri_caps) == len(expect)
        for q in proof.queries:
            assert [len(f.leaf_values) for f in q.fri] == [
                2 * (1 << k) for k in expect
            ]
        assert verify(setup.vk, proof, asm.gates)
