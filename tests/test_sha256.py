"""SHA-256 gadget tests: digest parity vs hashlib + full e2e prove/verify
(reference test model: gadgets/sha256/mod.rs:160 parity test, :296 e2e)."""

import hashlib

import pytest

from boojum_tpu.cs.types import CSGeometry, LookupParameters
from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.gadgets import allocate_u8_input, sha256, sha256_digest_bytes
from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify
from boojum_tpu.prover.satisfiability import check_if_satisfied

GEOM = CSGeometry(
    num_columns_under_copy_permutation=60,
    num_witness_columns=0,
    num_constant_columns=8,
    max_allowed_constraint_degree=7,
)

LOOKUP = LookupParameters(width=4, num_repetitions=8)

CONFIG = ProofConfig(
    fri_lde_factor=8,
    merkle_tree_cap_size=16,
    num_queries=30,
    pow_bits=0,
    fri_final_degree=16,
)


def build_sha_circuit(data: bytes):
    cs = ConstraintSystem(GEOM, 1 << 15, lookup_params=LOOKUP)
    inp = allocate_u8_input(cs, data)
    digest = sha256(cs, inp)
    return cs, digest


def test_sha256_parity_one_block():
    data = b"abc"
    cs, digest = build_sha_circuit(data)
    got = sha256_digest_bytes(cs, digest)
    assert got == hashlib.sha256(data).digest()


def test_sha256_parity_two_blocks():
    data = bytes(range(100))
    cs, digest = build_sha_circuit(data)
    got = sha256_digest_bytes(cs, digest)
    assert got == hashlib.sha256(data).digest()


def test_sha256_satisfiable():
    data = b"tpu-native boojum"
    cs, _ = build_sha_circuit(data)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_sha256_e2e_prove_verify():
    data = b"abc"
    cs, digest = build_sha_circuit(data)
    got = sha256_digest_bytes(cs, digest)
    assert got == hashlib.sha256(data).digest()
    asm = cs.into_assembly()
    setup = generate_setup(asm, CONFIG)
    proof = prove(asm, setup, CONFIG)
    assert verify(setup.vk, proof, asm.gates), "SHA-256 proof must verify"
