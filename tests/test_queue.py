"""Circuit queue tests (reference test model: queue gadget tests —
push/pop roundtrip, consistency enforcement, tamper rejection)."""

from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.types import CSGeometry, LookupParameters
from boojum_tpu.field import gl
from boojum_tpu.gadgets.queue import CircuitQueue, FullStateCircuitQueue
from boojum_tpu.prover.satisfiability import check_if_satisfied

GEOM = CSGeometry(
    num_columns_under_copy_permutation=130,
    num_witness_columns=0,
    num_constant_columns=8,
    max_allowed_constraint_degree=7,
)

LOOKUP = LookupParameters(width=4, num_repetitions=8)


def make_cs():
    return ConstraintSystem(GEOM, 1 << 14, lookup_params=LOOKUP)


def test_queue_roundtrip():
    cs = make_cs()
    q = CircuitQueue(cs, element_width=3)
    items = [[1, 2, 3], [4, 5, 6], [7, 8, 9]]
    for it in items:
        q.push(cs, [cs.alloc_variable_with_value(v) for v in it])
    assert not q.is_empty(cs).get_value(cs)
    popped = []
    while q._witness:
        el = q.pop_front(cs)
        popped.append([cs.get_value(v) for v in el])
    assert popped == items
    assert q.is_empty(cs).get_value(cs)
    q.enforce_consistency(cs)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_full_state_queue_roundtrip():
    cs = make_cs()
    q = FullStateCircuitQueue(cs, element_width=8)
    items = [[i * 8 + j for j in range(8)] for i in range(3)]
    for it in items:
        q.push(cs, [cs.alloc_variable_with_value(v) for v in it])
    popped = []
    while q._witness:
        el = q.pop_front(cs)
        popped.append([cs.get_value(v) for v in el])
    assert popped == items
    q.enforce_consistency(cs)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_queue_tamper_rejected():
    """Popping a different sequence than was pushed must break the final
    head==tail consistency constraint."""
    cs = make_cs()
    q = CircuitQueue(cs, element_width=2)
    q.push(cs, [cs.alloc_variable_with_value(v) for v in (10, 20)])
    # tamper the stored witness before popping
    q._witness[0] = [10, 21]
    q.pop_front(cs)
    q.enforce_consistency(cs)
    asm = cs.into_assembly()
    assert not check_if_satisfied(asm)
