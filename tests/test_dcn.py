"""DCN/ICI split of the collective bill (ISSUE 16).

The multi-host native prover splits every explicit collective's
crossing bytes into intra-host ICI vs cross-process DCN portions
(parallel/multihost.dcn_fraction), bills host gathers of
non-addressable arrays to dcn.host_gather_*, prices DCN in the cost
model (peak/column), validates the new gauge families on report lines,
rolls them into fleet host entries, and gates dcn:* byte series in the
trend. These are the FAST (single-process, no jax.distributed) pins of
that plumbing; tests/test_multihost.py's two-process parity test drives
the real cross-host path.
"""

import numpy as np

from boojum_tpu.utils import costmodel as cm
from boojum_tpu.utils import metrics as M
from boojum_tpu.utils import report


class _FakeDev:
    def __init__(self, process_index):
        self.process_index = process_index


class _FakeMesh:
    def __init__(self, pids):
        self.devices = np.array([_FakeDev(p) for p in pids], dtype=object)


# ---------------------------------------------------------------------------
# topology math
# ---------------------------------------------------------------------------


def test_dcn_fraction_single_process_is_zero():
    import jax

    from boojum_tpu.parallel.multihost import (
        dcn_fraction,
        hybrid_mesh,
        mesh_process_topology,
    )

    mesh = hybrid_mesh()
    topo = mesh_process_topology(mesh)
    assert topo["devices"] == len(jax.devices())
    assert topo["processes"] == 1
    assert dcn_fraction(mesh) == 0.0


def test_dcn_fraction_two_hosts_two_chips():
    from boojum_tpu.parallel.multihost import dcn_fraction

    # D=4 over 2 processes x 2 devices: crossing pairs 4^2-4=12, of
    # which 4^2 - (2^2 + 2^2) = 8 cross the process boundary -> 2/3
    mesh = _FakeMesh([0, 0, 1, 1])
    assert abs(dcn_fraction(mesh) - 2.0 / 3.0) < 1e-12


def test_dcn_fraction_heterogeneous_hosts():
    from boojum_tpu.parallel.multihost import dcn_fraction

    # D=4 split 3+1: (16 - (9+1)) / 12 = 0.5
    mesh = _FakeMesh([0, 0, 0, 1])
    assert abs(dcn_fraction(mesh) - 0.5) < 1e-12
    # one device: no crossing at all
    assert dcn_fraction(_FakeMesh([0])) == 0.0


def test_shard_sweep_accounting_splits_by_fraction():
    """_ici_all_to_all / _ici_all_gather route the dcn_fraction split
    through the metrics seams: ici gauges carry the intra-host portion,
    dcn gauges the cross-host remainder, and their sum is the full
    crossing bill."""
    from boojum_tpu.parallel import shard_sweep as ss

    class _Shaped(_FakeMesh):
        # shard_sweep.mesh_devices reads mesh.shape
        shape = {"col": 4, "row": 1}

        def __hash__(self):
            return id(self)

        def __eq__(self, other):
            return self is other

    mesh = _Shaped([0, 0, 1, 1])
    reg = M.MetricsRegistry()
    tok = M.install_scoped_registry(reg)
    try:
        ss._ici_all_to_all(1200, mesh)  # crossing = 1200*3/4 = 900
        ss._ici_all_gather(100, mesh)   # crossing = 100*3 = 300
    finally:
        M.reset_scoped_registry(tok)
    g = reg.to_dict()["gauges"]
    c = reg.to_dict()["counters"]
    assert c["ici.all_to_alls"] == 1 and c["dcn.all_to_alls"] == 1
    assert c["ici.all_gathers"] == 1 and c["dcn.all_gathers"] == 1
    assert abs(g["ici.all_to_all_bytes"] - 300.0) < 1e-6
    assert abs(g["dcn.all_to_all_bytes"] - 600.0) < 1e-6
    assert abs(g["ici.all_gather_bytes"] - 100.0) < 1e-6
    assert abs(g["dcn.all_gather_bytes"] - 200.0) < 1e-6


def test_metrics_seams_no_dcn_on_single_host():
    reg = M.MetricsRegistry()
    tok = M.install_scoped_registry(reg)
    try:
        M.count_ici_all_to_all(100.0)       # no dcn arg: single-host
        M.count_ici_all_gather(50.0, 0.0)   # explicit zero
    finally:
        M.reset_scoped_registry(tok)
    snap = reg.to_dict()
    assert not any(k.startswith("dcn.") for k in snap["counters"])
    assert not any(k.startswith("dcn.") for k in snap["gauges"])


# ---------------------------------------------------------------------------
# cost model: DCN column + peak
# ---------------------------------------------------------------------------


def test_device_peaks_carry_dcn(monkeypatch):
    assert "peak_dcn_gbps" in cm.device_peaks()
    monkeypatch.setenv("BOOJUM_TPU_COST_PEAKS", "100,50,10,25")
    p = cm.device_peaks()
    assert p["source"] == "env"
    assert p["peak_ici_gbps"] == 10.0 and p["peak_dcn_gbps"] == 25.0


def test_stage_costs_dcn_split_preserves_crossing_total():
    from boojum_tpu.prover.shape_key import shape_bucket
    from tests.test_costmodel import _fma_cfg_asm

    asm, cfg = _fma_cfg_asm()
    sb = shape_bucket(asm, cfg)
    base = cm.stage_costs(sb, cfg, mesh_devices=8)
    split = cm.stage_costs(sb, cfg, mesh_devices=8, dcn_fraction=0.25)
    for name, ent in base.items():
        s = split[name]
        if ent["ici_bytes"] == 0:
            assert "dcn_bytes" not in s
            continue
        assert s["dcn_bytes"] > 0
        assert abs(
            (s["ici_bytes"] + s["dcn_bytes"]) - ent["ici_bytes"]
        ) < 1e-6
        assert abs(s["dcn_bytes"] - ent["ici_bytes"] * 0.25) < 1e-6
    # no fraction -> no dcn key anywhere (single-host records unchanged)
    assert all("dcn_bytes" not in e for e in base.values())


def test_roofline_achieved_dcn_gbps():
    peaks = {"peak_gflops": 100.0, "peak_hbm_gbps": 50.0}
    out = cm.roofline(
        {"flops": 1e9, "hbm_bytes": 1e9, "ici_bytes": 2e9,
         "dcn_bytes": 1e9},
        1.0, peaks,
    )
    assert out["achieved_ici_gbps"] == 2.0
    assert out["achieved_dcn_gbps"] == 1.0
    no_dcn = cm.roofline(
        {"flops": 1e9, "hbm_bytes": 1e9, "ici_bytes": 2e9}, 1.0, peaks
    )
    assert "achieved_dcn_gbps" not in no_dcn


def test_build_cost_record_measured_dcn_and_validator():
    from boojum_tpu.prover.shape_key import shape_bucket
    from tests.test_costmodel import STAGES, _fma_cfg_asm, _synthetic_tree

    asm, cfg = _fma_cfg_asm()
    sb = shape_bucket(asm, cfg)
    walls = {nm: 0.5 for nm in STAGES}
    peaks = {
        "kind": "test", "peak_gflops": 100.0, "peak_hbm_gbps": 50.0,
        "peak_ici_gbps": 10.0, "peak_dcn_gbps": 25.0, "source": "env",
    }
    metrics = {
        "counters": {},
        "gauges": {
            "dcn.all_to_all_bytes": 1000.0,
            "dcn.all_gather_bytes": 200.0,
            "dcn.host_gather_bytes": 300.0,
        },
    }
    rec = cm.build_cost_record(
        sb, cfg, _synthetic_tree(walls), metrics, peaks=peaks,
        mesh_devices=4, dcn_fraction=0.5,
    )
    assert rec["total"]["dcn_bytes_measured"] == 1500.0
    assert rec["total"]["dcn_bytes"] > 0
    assert rec["stages"]["round1_witness_commit"]["dcn_bytes"] > 0
    assert report._validate_cost(rec, None) == []
    bad = {**rec, "stages": dict(rec["stages"])}
    bad["stages"]["round1_witness_commit"] = dict(
        bad["stages"]["round1_witness_commit"], dcn_bytes=-1.0
    )
    assert any(
        "dcn_bytes" in p for p in report._validate_cost(bad, None)
    )


def test_measured_baseline_covers_dcn_gauges():
    reg = M.MetricsRegistry()
    tok = M.install_scoped_registry(reg)
    try:
        M.count_ici_all_to_all(100.0, 40.0)
        M.count_dcn_host_gather(10.0)
        base = cm.measured_baseline()
    finally:
        M.reset_scoped_registry(tok)
    assert base["gauges"]["dcn.all_to_all_bytes"] == 40.0
    assert base["gauges"]["dcn.host_gather_bytes"] == 10.0


# ---------------------------------------------------------------------------
# report line validator
# ---------------------------------------------------------------------------


def _minimal_report(counters, gauges):
    return {
        "kind": report.REPORT_KIND,
        "schema": report.REPORT_SCHEMA,
        "wall_s": 0.5,
        "spans": [],
        "metrics": {"counters": counters, "gauges": gauges},
        "checkpoints": [],
    }


def test_validator_accepts_dcn_only_crossing_bytes():
    """A 1-local-device-per-host mesh moves ALL crossing bytes over DCN:
    a counted all_to_all with zero ici bytes but positive dcn bytes must
    pass (and vice versa keeps passing)."""
    rep = _minimal_report(
        {"ici.all_to_alls": 2},
        {
            "ici.all_to_all_bytes": 0.0,
            "dcn.all_to_all_bytes": 512.0,
            "ici.pivot_s": 0.01,
        },
    )
    assert report.validate_report(rep) == []


def test_validator_rejects_counted_dcn_without_bytes():
    rep = _minimal_report(
        {"dcn.host_gathers": 1}, {"dcn.host_gather_bytes": 0.0}
    )
    assert any(
        "dcn.host_gather_bytes" in p for p in report.validate_report(rep)
    )
    neg = _minimal_report({}, {"dcn.all_gather_bytes": -4.0})
    assert any(
        "dcn.all_gather_bytes" in p for p in report.validate_report(neg)
    )


def test_validator_still_rejects_zero_byte_collectives():
    rep = _minimal_report(
        {"ici.all_to_alls": 1},
        {"ici.all_to_all_bytes": 0.0, "ici.pivot_s": 0.01},
    )
    assert any(
        "all_to_all_bytes" in p for p in report.validate_report(rep)
    )


# ---------------------------------------------------------------------------
# fleet: per-host dcn column
# ---------------------------------------------------------------------------


def test_fleet_host_entry_and_render_carry_dcn():
    h0 = [{
        "pid": 0,
        "proofs": {},
        "clock_sync": {"barrier_unix_ts": 100.0},
        "ici": {"ici.all_to_all_bytes": 1e6, "ici.all_to_alls": 3},
        "dcn": {"dcn.all_to_all_bytes": 2e6, "dcn.all_to_alls": 3},
        "mesh_mode": "shard_map",
    }]
    h1 = [{
        "kind": report.REPORT_KIND,
        "schema": report.REPORT_SCHEMA,
        "wall_s": 1.0,
        "spans": [],
        "metrics": {
            "counters": {},
            "gauges": {
                "ici.all_gather_bytes": 5e5,
                "dcn.all_gather_bytes": 7e5,
                "dcn.host_gather_bytes": 1e5,
            },
        },
        "checkpoints": [],
    }]
    rec = report.fleet_merge([("host0", h0), ("host1", h1)])
    assert report.validate_fleet(rec) == []
    hosts = {h["host"]: h for h in rec["hosts"]}
    assert hosts["host0"]["dcn_bytes"] == 2e6
    assert hosts["host0"]["ici_bytes"] == 1e6
    assert hosts["host0"]["mesh_mode"] == "shard_map"
    assert hosts["host1"]["dcn_bytes"] == 8e5
    text = report.render_fleet(rec)
    assert "dcn_MB" in text
    assert "2.00" in text  # host0's 2e6 B column

    bad = {**rec, "hosts": [dict(rec["hosts"][0], dcn_bytes=-1.0)]}
    bad["n_hosts"] = 1
    assert any("dcn_bytes" in p for p in report.validate_fleet(bad))


# ---------------------------------------------------------------------------
# trend: dcn:* byte series gate lower-is-better
# ---------------------------------------------------------------------------


def test_trend_learns_dcn_series_and_gates_regressions():
    def _point(label, nbytes):
        rep = _minimal_report(
            {"ici.all_to_alls": 1},
            {
                "ici.all_to_all_bytes": 10.0,
                "ici.pivot_s": 0.01,
                "dcn.all_to_all_bytes": float(nbytes),
            },
        )
        return {
            "label": label,
            "identity": "hostA",
            "values": report._point_values_from_report(rep),
        }

    points = [
        _point("r1", 1e6), _point("r2", 1.1e6), _point("r3", 2e6)
    ]
    series = report.trend_series(points)
    key = ("hostA", "dcn:all_to_all_bytes")
    assert key in series and series[key]["unit"] == "B"
    regs = report.trend_gate(series, threshold=0.2)
    assert any(r["series"] == "dcn:all_to_all_bytes" for r in regs)
    # sub-1KiB wobble on a tiny series is noise, not a regression
    tiny = report.trend_series(
        [_point("r1", 100), _point("r2", 100), _point("r3", 400)]
    )
    assert not report.trend_gate(tiny, threshold=0.2)


def test_trend_bench_line_dcn_dict():
    line = {
        "metric": "multichip_prove_wall",
        "value": 2.0,
        "unit": "s",
        "dcn": {"dcn.all_to_all_bytes": 5e5, "dcn.all_to_alls": 3},
    }
    vals = report._point_values_from_bench(line)
    assert vals["dcn:all_to_all_bytes"] == {"value": 5e5, "unit": "B"}
    assert "dcn:all_to_alls" not in vals


# ---------------------------------------------------------------------------
# AOT fingerprint: process topology keys
# ---------------------------------------------------------------------------


def test_platform_info_keys_process_topology():
    from boojum_tpu.prover import aot

    info = aot.platform_info()
    assert info["num_local_devices"] >= 1
    assert info["process_count"] == 1
    # the legacy global count stays stamped (report identity consumers)
    assert info["num_devices"] >= info["num_local_devices"]
    for k in ("num_local_devices", "process_count"):
        assert k in aot._PLATFORM_FIELDS
    assert "num_devices" not in aot._PLATFORM_FIELDS
