"""End-to-end limb residency (ISSUE 10).

The tentpole makes (lo, hi) u32 limb planes the canonical on-device
representation for the whole prove (BOOJUM_TPU_LIMB_RESIDENT): witness
columns enter as planes at H2D, stay planes through iNTT/LDE, Poseidon2
sponges, the fused quotient sweep, DEEP and FRI, and `limbs.join`
survives only at the API edge. These tests pin the acceptance criteria:

- 2^10 e2e proof bytes AND the Fiat–Shamir checkpoint stream are
  bit-identical under `=1` vs `=0`, on no-mesh AND the 8-device CPU
  shard_map mesh;
- metrics guards that the resident kernels actually dispatched
  (quotient.resident_coset_sweeps / fri.resident_folds /
  merkle.resident_commits / ntt.resident_transforms nonzero);
- ZERO interior `limb.splits`/`limb.joins` during a resident prove —
  the device-op counters charged inside field/limbs.py split/join; the
  allowlisted edges are host conversions (limb.host_*) plus the
  per-setup `limbs.edge("ingest:*")` splits;
- `prove_report.py --check` (report.validate_report) FAILS a line
  claiming resident dispatch while counting interior splits/joins;
- the resident flag surfaces as a span attribute and in --slo.
"""

import functools
import os

import jax
import numpy as np
import pytest

from boojum_tpu.utils import report


def _small_prove_parts():
    from test_limb_sweep import _small_prove_parts as parts

    return parts()


def _recorded_prove(label, env, mesh=None):
    from boojum_tpu.prover import prove

    asm, setup, config = _small_prove_parts()
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        with report.flight_recording(label=label) as rec:
            proof = prove(asm, setup, config, mesh=mesh)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return proof, report.build_report(rec)


@functools.lru_cache(maxsize=1)
def _both_runs():
    # u64 FIRST so its caches never benefit from resident-run state
    u64 = _recorded_prove("u64", {"BOOJUM_TPU_LIMB_RESIDENT": "0"})
    res = _recorded_prove("res", {"BOOJUM_TPU_LIMB_RESIDENT": "1"})
    return {"u64": u64, "res": res}


def _checkpoint_stream(rep):
    return [
        (e["seq"], e["round"], e["label"], e["digest"])
        for e in rep["checkpoints"]
    ]


# ---------------------------------------------------------------------------
# Dispatch predicate
# ---------------------------------------------------------------------------


def test_resident_flag_dispatch(monkeypatch):
    """Tri-state: =0 off everywhere; =1 on (and implies the limb kernel
    family) even on CPU; unset follows the native default (off on CPU);
    junk raises; every limb-sweep veto also vetoes residency."""
    from boojum_tpu.prover import pallas_sweep as ps
    from boojum_tpu.utils.pallas_util import force_xla

    monkeypatch.delenv("BOOJUM_TPU_LIMB_RESIDENT", raising=False)
    monkeypatch.delenv("BOOJUM_TPU_LIMB_SWEEP", raising=False)
    if jax.default_backend() != "tpu":
        assert ps.limb_resident_enabled() is False
    monkeypatch.setenv("BOOJUM_TPU_LIMB_RESIDENT", "1")
    assert ps.limb_resident_enabled() is True
    # residency implies the limb kernels
    assert ps.limb_sweep_enabled() is True
    monkeypatch.setenv("BOOJUM_TPU_LIMB_RESIDENT", "0")
    assert ps.limb_resident_enabled() is False
    monkeypatch.setenv("BOOJUM_TPU_LIMB_RESIDENT", "1")
    monkeypatch.setenv("BOOJUM_TPU_LIMB_SWEEP", "0")
    assert ps.limb_resident_enabled() is False  # no kernels, no residency
    monkeypatch.delenv("BOOJUM_TPU_LIMB_SWEEP", raising=False)
    with force_xla():
        assert ps.limb_resident_enabled() is False
    monkeypatch.setenv("BOOJUM_TPU_LIMB_RESIDENT", "maybe")
    with pytest.raises(ValueError, match="BOOJUM_TPU_LIMB_RESIDENT"):
        ps.limb_resident_enabled()


# ---------------------------------------------------------------------------
# No-mesh acceptance: bit parity + dispatch guards + zero interior
# ---------------------------------------------------------------------------


def test_bit_parity_resident_vs_u64_2pow10():
    """Acceptance: proof bytes AND the checkpoint stream are bit-identical
    with BOOJUM_TPU_LIMB_RESIDENT=1 vs =0 — residency changes WHERE the
    representation converts (nowhere interior), never a value that
    crosses the transcript."""
    from boojum_tpu.prover import verify

    runs = _both_runs()
    p_u, r_u = runs["u64"]
    p_r, r_r = runs["res"]
    base = _checkpoint_stream(r_u)
    assert base, "no checkpoints recorded"
    assert _checkpoint_stream(r_r) == base
    assert p_r.to_json() == p_u.to_json()
    asm, setup, _config = _small_prove_parts()
    assert verify(setup.vk, p_r, asm.gates)
    for rep in (r_u, r_r):
        assert report.validate_report(rep) == []


def test_resident_kernels_actually_dispatched():
    """Metrics guard: the =1 run must have gone through the resident
    coset sweeps, FRI folds, plane commits and plane transforms — a
    silent fallback to the converting path would make the parity test
    (and the zero-conversion guard) vacuous."""
    runs = _both_runs()
    c_u = runs["u64"][1]["metrics"]["counters"]
    c_r = runs["res"][1]["metrics"]["counters"]
    assert c_u.get("quotient.resident_coset_sweeps", 0) == 0
    assert c_u.get("fri.resident_folds", 0) == 0
    assert c_u.get("merkle.resident_commits", 0) == 0
    assert (
        c_r["quotient.resident_coset_sweeps"] == c_r["quotient.coset_sweeps"]
    )
    assert c_r["quotient.resident_coset_sweeps"] > 0
    assert c_r["fri.resident_folds"] == c_r["fri.folds"] > 0
    assert c_r["merkle.resident_commits"] > 0
    assert c_r["ntt.resident_transforms"] > 0
    assert c_r["deep.resident_codewords"] >= 1


def test_zero_interior_conversions_guard():
    """THE residency guard: a resident prove records ZERO interior
    limb.splits / limb.joins (the device-op counters charged inside
    field/limbs.py). Only allowlisted edges may convert: host-side
    splits/joins (H2D witness, host tables, transcript/query joins) and
    the per-setup `ingest:*` edge splits."""
    runs = _both_runs()
    c_r = runs["res"][1]["metrics"]["counters"]
    assert c_r.get("limb.splits", 0) == 0, c_r
    assert c_r.get("limb.joins", 0) == 0, c_r
    # the edges actually ran: host joins happen at every transcript pull
    # and query opening of a resident prove
    assert c_r.get("limb.host_joins", 0) > 0
    assert c_r.get("limb.host_splits", 0) > 0
    # the u64 run (limb kernels off on CPU) never converts at all — and
    # never claims residency
    c_u = runs["u64"][1]["metrics"]["counters"]
    assert c_u.get("quotient.resident_coset_sweeps", 0) == 0


def test_check_gate_rejects_lying_resident_line():
    """report.validate_report (the prove_report.py --check gate) FAILS a
    line claiming resident dispatch while counting interior conversions,
    and accepts the honest resident line."""
    import copy

    runs = _both_runs()
    rep = runs["res"][1]
    assert report.validate_report(rep) == []
    bad = copy.deepcopy(rep)
    bad["metrics"]["counters"]["limb.splits"] = 3
    problems = report.validate_report(bad)
    assert any("interior limb.splits" in p for p in problems), problems
    bad2 = copy.deepcopy(rep)
    bad2["metrics"]["counters"]["limb.joins"] = 1
    assert any(
        "interior limb.joins" in p for p in report.validate_report(bad2)
    )
    # malformed limb counter values fail too
    bad3 = copy.deepcopy(rep)
    bad3["metrics"]["counters"]["limb.host_joins"] = -2
    assert any("limb metric" in p for p in report.validate_report(bad3))


def test_resident_flag_surfaces_in_spans_and_slo():
    """The resident flag rides the round-3/FRI spans as an attribute
    (rendered in the span tree) and --slo counts resident lines."""
    runs = _both_runs()
    rep = runs["res"][1]
    found = []
    for _path, sp in report.flatten_spans(rep):
        a = sp.get("attrs") or {}
        if a.get("resident"):
            found.append(sp.get("name"))
    assert any("round3_coset_sweeps" in (n or "") for n in found), found
    assert any((n or "").startswith("fri_oracle") for n in found), found
    rendered = report.render_report(rep)
    assert " resident" in rendered
    slo = report.slo_summary([rep, runs["u64"][1]])
    assert slo["limb_resident_lines"] == 1


# ---------------------------------------------------------------------------
# shard_map mesh acceptance (8 virtual CPU devices)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=1)
def _mesh_run():
    from jax.sharding import Mesh

    mesh = Mesh(
        np.array(jax.devices()[:8]).reshape(2, 4), axis_names=("col", "row")
    )
    return _recorded_prove(
        "res_sm",
        {
            "BOOJUM_TPU_MESH_MODE": "shard_map",
            "BOOJUM_TPU_LIMB_RESIDENT": "1",
        },
        mesh=mesh,
    )


@pytest.mark.slow  # a fresh streamed plane-kernel compile sweep: beyond
# the tier-1 watchdog on the 1-core CPU box; full/standalone runs run it
def test_streamed_resident_bit_parity_2pow10():
    """The resident STREAMED commit path (BOOJUM_TPU_STREAM_LDE=1:
    plane double-buffered blocks, MonomialPlanesSource regens in DEEP and
    queries, the de-meshed FRI entry) routes different graphs than the
    materialized path the main parity tests pin — its proof bytes and
    checkpoints must still be bit-identical, streamed blocks dispatched,
    zero interior conversions."""
    runs = _both_runs()
    p0, r0 = runs["u64"]
    p, r = _recorded_prove(
        "res_stream",
        {"BOOJUM_TPU_LIMB_RESIDENT": "1", "BOOJUM_TPU_STREAM_LDE": "1"},
    )
    assert _checkpoint_stream(r) == _checkpoint_stream(r0)
    assert p.to_json() == p0.to_json()
    c = r["metrics"]["counters"]
    assert c["stream.double_buffered_blocks"] > 0
    assert c["merkle.streamed_commits"] > 0
    assert c["quotient.resident_coset_sweeps"] > 0
    assert c.get("limb.splits", 0) == 0
    assert c.get("limb.joins", 0) == 0
    assert report.validate_report(r) == []


@pytest.mark.slow  # a fresh sm plane-kernel compile sweep: far beyond the
# tier-1 watchdog on the 1-core CPU box; full/standalone runs execute it
@pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)
def test_resident_mesh_bit_parity_2pow10():
    """Acceptance: the resident prove on the 2x4 shard_map mesh —
    per-chip plane kernels, collectives moving lo/hi u32 planes — is
    bit-identical to the meshless u64 prove, with the resident per-chip
    kernels actually dispatched and the ici gauges charged."""
    runs = _both_runs()
    p0, r0 = runs["u64"]
    p, r = _mesh_run()
    assert _checkpoint_stream(r) == _checkpoint_stream(r0)
    assert p.to_json() == p0.to_json()
    c = r["metrics"]["counters"]
    g = r["metrics"]["gauges"]
    assert c["quotient.resident_coset_sweeps"] > 0
    assert c["fri.resident_folds"] > 0
    assert c["merkle.resident_commits"] > 0
    assert c["merkle.sm_commits"] > 0
    assert c["deep.sm_codewords"] == 1
    assert c["deep.resident_codewords"] == 1
    assert c["ici.all_to_alls"] > 0
    assert g["ici.all_to_all_bytes"] > 0
    assert g["ici.all_gather_bytes"] > 0
    assert c.get("limb.splits", 0) == 0
    assert c.get("limb.joins", 0) == 0
    assert report.validate_report(r) == []
