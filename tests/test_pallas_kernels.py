"""Parity tests for the u32-limb field forms and the Pallas TPU kernels.

The limb ops are pure jnp and run anywhere; the kernels run in interpret
mode here (the CPU suite) and as real Mosaic kernels on TPU — dispatchers in
hashes/poseidon2.py and ntt/ntt.py route to them only on the TPU backend, so
everything below pins bit-parity between the two implementations.
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest

# interpret-mode kernel runs compile slowly on XLA:CPU (~30-90s each); the
# full set runs under BOOJUM_TPU_SLOW_TESTS=1 and on real TPU hardware via
# the bench + scripts, while the default suite keeps one per kernel family.
_SLOW = bool(os.environ.get("BOOJUM_TPU_SLOW_TESTS"))
slow_only = pytest.mark.skipif(
    not _SLOW, reason="interpret-mode compile heavy; BOOJUM_TPU_SLOW_TESTS=1"
)

from boojum_tpu.field import gl, limbs
from boojum_tpu.field import goldilocks as gf
from boojum_tpu.field import extension as ext


def _rand(shape, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, gl.P, size=shape, dtype=np.uint64)


EDGE = np.array(
    [0, 1, 2, gl.P - 1, gl.P - 2, 0xFFFFFFFF, 0x100000000, gl.P >> 1],
    dtype=np.uint64,
)


class TestLimbOps:
    def setup_method(self, _):
        a64 = np.concatenate([_rand(1 << 10, 10), EDGE, EDGE])
        b64 = np.concatenate([_rand(1 << 10, 11), EDGE, EDGE[::-1].copy()])
        self.a64, self.b64 = jnp.asarray(a64), jnp.asarray(b64)
        self.a = limbs.split(self.a64)
        self.b = limbs.split(self.b64)

    def _eq(self, got_pair, want64):
        assert np.array_equal(
            np.asarray(limbs.join(got_pair)), np.asarray(want64)
        )

    def test_add_sub_mul(self):
        self._eq(limbs.add(self.a, self.b), gf.add(self.a64, self.b64))
        self._eq(limbs.sub(self.a, self.b), gf.sub(self.a64, self.b64))
        self._eq(limbs.mul(self.a, self.b), gf.mul(self.a64, self.b64))

    def test_unary(self):
        self._eq(limbs.sqr(self.a), gf.sqr(self.a64))
        self._eq(limbs.neg(self.a), gf.neg(self.a64))
        self._eq(limbs.double(self.a), gf.double(self.a64))

    def test_mul_const(self):
        c = gl.RADIX_2_SUBGROUP_GENERATOR
        self._eq(
            limbs.mul_const(self.a, limbs.const_pair(c)),
            gf.mul(self.a64, jnp.uint64(c)),
        )

    def test_ext_mul(self):
        got = limbs.ext_mul((self.a, self.b), (self.b, self.a))
        want = ext.mul((self.a64, self.b64), (self.b64, self.a64))
        for g, w in zip(got, want):
            self._eq(g, w)

    def test_split_join_roundtrip(self):
        self._eq(self.a, self.a64)


class TestPoseidon2Kernel:
    def test_permutation_interpret(self):
        from boojum_tpu.hashes import poseidon2 as p2
        from boojum_tpu.hashes import pallas_poseidon2 as pp2

        state = jnp.asarray(_rand((256, 12), 20))
        got = pp2.permutation(state, interpret=True)
        want = p2.poseidon2_permutation_xla(state)
        assert np.array_equal(np.asarray(got), np.asarray(want))

    @slow_only
    def test_sponge_interpret(self):
        from boojum_tpu.hashes import poseidon2 as p2
        from boojum_tpu.hashes import pallas_poseidon2 as pp2

        for width in (8, 9, 21):
            vals = jnp.asarray(_rand((256, width), 21))
            got = pp2.sponge_hash(vals, interpret=True)
            want = p2.leaf_hash_xla(vals)
            assert np.array_equal(np.asarray(got), np.asarray(want)), width

    @slow_only
    def test_node_hash_shape_via_sponge(self):
        from boojum_tpu.hashes import poseidon2 as p2
        from boojum_tpu.hashes import pallas_poseidon2 as pp2

        left = jnp.asarray(_rand((256, 4), 22))
        right = jnp.asarray(_rand((256, 4), 23))
        got = pp2.sponge_hash(
            jnp.concatenate([left, right], axis=-1), interpret=True
        )
        want = p2.node_hash_xla(left, right)
        assert np.array_equal(np.asarray(got), np.asarray(want))


class TestMXUNTTKernel:
    """Bit-parity of the MXU matmul-NTT (ntt/mxu_ntt.py) vs the staged-XLA
    path. Interpret mode executes the same exact-integer int8/i32 ops on
    CPU, so equality here pins the kernel's arithmetic, including the
    balanced-digit int8 dots and the biased 15-diagonal mod-p fold."""

    LOG_N = 14  # smallest MXU-dispatched size

    def test_balanced_digits_boundaries(self):
        """The host digit bake and the in-kernel extraction agree and
        reconstruct x mod p for every branch of the x -> x-p switch:
        x <= M (plain), x > M (two's-complement subtract), and the carry
        chain's saturating bytes."""
        from boojum_tpu.ntt.mxu_ntt import _M_BAL, _digits8_np

        cases = np.array(
            [0, 1, 127, 128, 255, 256, _M_BAL - 1, _M_BAL, _M_BAL + 1,
             (1 << 32) - 1, 1 << 32, (1 << 63) - 1, 1 << 63,
             gl.P - 1, gl.P - 2, gl.P - (1 << 32)],
            dtype=np.uint64,
        )
        digs = np.asarray(_digits8_np(cases)).astype(np.int64)
        for i, x in enumerate(cases):
            v = sum(int(digs[k, i]) * (1 << (8 * k)) for k in range(8))
            assert (v - int(x)) % gl.P == 0, hex(int(x))
            assert all(-128 <= int(digs[k, i]) <= 127 for k in range(8))

    def test_kernel_digit_planes_boundaries(self):
        """Pin the KERNEL-side digit extraction (_digit_planes: u32-pair
        gt comparison, lo!=0 carry, byte carry chain) at the exact _M_BAL
        tie-break — hi == 0x7F7F7F7F with lo on/around the boundary — and
        at the lo==0 carry special case; the host bake (_digits8_np) is the
        independently-implemented reference."""
        from boojum_tpu.field import limbs
        from boojum_tpu.ntt.mxu_ntt import _M_BAL, _digit_planes, _digits8_np

        cases = np.array(
            [_M_BAL - 1, _M_BAL, _M_BAL + 1,
             # hi exactly at the tie-break word, lo sweeping the switch
             (0x7F7F7F7F << 32) | 0x00000000,
             (0x7F7F7F7F << 32) | 0x7F7F7F7E,
             (0x7F7F7F7F << 32) | 0x7F7F7F7F,
             (0x7F7F7F7F << 32) | 0x7F7F7F80,
             (0x7F7F7F7F << 32) | 0xFFFFFFFF,
             # x > M with lo == 0: the (gt & lo != 0) carry branch
             1 << 63, (0x80000000 << 32),
             (0xFFFFFFFF << 32), gl.P - 1, gl.P - (1 << 32)],
            dtype=np.uint64,
        )
        want = np.asarray(_digits8_np(cases)).astype(np.int64)
        lo, hi = limbs.split_np(cases)
        got_planes = _digit_planes((jnp.asarray(lo), jnp.asarray(hi)))
        got = np.stack([np.asarray(p) for p in got_planes]).astype(np.int64)
        assert (got == want).all(), np.nonzero((got != want).any(axis=0))

    def _data(self, log_n, cols=2, seed=30):
        a = _rand((cols, 1 << log_n), seed)
        # adversarial rows: all p-1 (max limbs everywhere) and small values
        a[0, :] = gl.P - 1
        return jnp.asarray(a)

    def test_fwd_inv_interpret(self):
        from boojum_tpu.ntt import ntt
        from boojum_tpu.ntt import mxu_ntt

        a = self._data(self.LOG_N)
        want = ntt.fft_natural_to_bitreversed_xla(a)
        got = mxu_ntt.fft_natural_to_bitreversed(a, interpret=True)
        assert np.array_equal(np.asarray(got), np.asarray(want))
        wanti = ntt.ifft_bitreversed_to_natural_xla(want)
        goti = mxu_ntt.ifft_bitreversed_to_natural(want, interpret=True)
        assert np.array_equal(np.asarray(goti), np.asarray(wanti))

    @slow_only
    def test_fwd_inv_interpret_all_sizes(self):
        from boojum_tpu.ntt import ntt
        from boojum_tpu.ntt import mxu_ntt

        for log_n in (15, 16):
            a = self._data(log_n, cols=1, seed=31 + log_n)
            want = ntt.fft_natural_to_bitreversed_xla(a)
            got = mxu_ntt.fft_natural_to_bitreversed(a, interpret=True)
            assert np.array_equal(np.asarray(got), np.asarray(want)), log_n
            wanti = ntt.ifft_bitreversed_to_natural_xla(want)
            goti = mxu_ntt.ifft_bitreversed_to_natural(want, interpret=True)
            assert np.array_equal(np.asarray(goti), np.asarray(wanti)), log_n

    @slow_only
    def test_hybrid_interpret(self):
        """2^17: one XLA outer stage + two per-block 2^16 kernels."""
        from boojum_tpu.ntt import ntt
        from boojum_tpu.ntt import mxu_ntt

        a = self._data(17, cols=1, seed=33)
        want = ntt.fft_natural_to_bitreversed_xla(a)
        got = mxu_ntt.fft_natural_to_bitreversed(a, interpret=True)
        assert np.array_equal(np.asarray(got), np.asarray(want))
        wanti = ntt.ifft_bitreversed_to_natural_xla(want)
        goti = mxu_ntt.ifft_bitreversed_to_natural(want, interpret=True)
        assert np.array_equal(np.asarray(goti), np.asarray(wanti))

    def test_lde_interpret(self):
        from boojum_tpu.ntt import ntt
        from boojum_tpu.ntt import mxu_ntt

        co = self._data(self.LOG_N, cols=1, seed=34)
        want = ntt._lde_from_monomial_jit(co, 4)
        scale = ntt._lde_scale_cached(
            self.LOG_N, 4, gl.MULTIPLICATIVE_GENERATOR % gl.P
        )
        got = mxu_ntt.lde_from_monomial(co, scale, interpret=True)
        assert np.array_equal(np.asarray(got), np.asarray(want))
