"""Kernel cost model + roofline attribution + perf-trend gate (ISSUE 12).

The tentpole built the cost-attribution plane: an analytic per-kernel /
per-stage cost sheet (utils/costmodel.py) cross-checked against XLA's
own compile-time `cost_analysis()` actuals (captured into CompileLedger
entries by prover/precompile.py), joined with measured span walls into a
validated `cost` record on every ProveReport line, rendered by
`prove_report.py --roofline`, and a `--trend --gate` perf-regression
gate over report artifacts + the repo's BENCH_*.json history. These
tests pin:

- the analytic sheet covers every kernel `enumerate_kernels` emits (u64
  AND limb-resident variants) with no fallback-family holes;
- a 2^10 CPU prove emits a `cost` record that passes `--check`,
  renders under `--roofline`, exports `cost.*` gauges, and whose
  analytic model agrees with the XLA actuals within the documented
  tolerance band (BASELINE.md "Cost model & trend protocol": family
  aggregates within 4x, totals within 2.5x);
- the `--check` gate REJECTS fabricated records: negative efficiency,
  efficiency over a zero denominator (no wall / zero peak), and
  actuals attributed to kernels the compile ledger never recorded;
- `--diff` reports per-stage efficiency deltas;
- `--trend` ingests the real BENCH_*.json history plus synthetic
  report artifacts and `--gate` exits nonzero exactly on the regressed
  stage (the CI smoke), with machine-identity grouping and
  higher-is-better gating for throughput metrics;
- every registry counter family in use renders under `boojum_tpu_*` on
  /metrics, including the prove-side families the sampler registry
  never carried before the merge.
"""

import copy
import functools
import json
import os
import subprocess
import sys

from boojum_tpu.utils import report
from boojum_tpu.utils import costmodel as cm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STAGES = cm.STAGE_NAMES


def _fma_cfg_asm():
    from boojum_tpu.cs.gates import FmaGate, PublicInputGate
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.types import CSGeometry
    from boojum_tpu.prover import ProofConfig

    geom = CSGeometry(8, 0, 6, 4)
    cs = ConstraintSystem(geom, 1 << 10)
    a = cs.alloc_variable_with_value(1)
    b = cs.alloc_variable_with_value(2)
    per_row = FmaGate.instance().num_repetitions(geom)
    for _ in range(((1 << 10) - 8) * per_row):
        a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
    PublicInputGate.place(cs, b)
    asm = cs.into_assembly()
    cfg = ProofConfig(
        fri_lde_factor=2, merkle_tree_cap_size=4,
        num_queries=4, fri_final_degree=16,
    )
    return asm, cfg


@functools.lru_cache(maxsize=1)
def _proved_with_costs():
    """ONE precompile sweep (capturing per-kernel XLA actuals into a
    process-wide ledger) + ONE recorded 2^10 prove — the shared e2e
    artifact most tests here read. Same circuit/config as
    test_limb_sweep._small_prove_parts, so the persistent compile cache
    is shared with the rest of the tier-1 suite."""
    from test_limb_sweep import _small_prove_parts

    from boojum_tpu.prover import prove
    from boojum_tpu.prover.precompile import enumerate_kernels, precompile
    from boojum_tpu.utils.profiling import (
        start_compile_ledger,
        stop_compile_ledger,
    )

    asm, setup, config = _small_prove_parts()
    led = start_compile_ledger()
    specs = enumerate_kernels(asm, config)
    precompile(asm, config, ledger=led, max_workers=2, specs=specs)
    try:
        with report.flight_recording(label="cost_e2e") as rec:
            proof = prove(asm, setup, config)
        line = report.build_report(rec)
    finally:
        stop_compile_ledger()
    assert proof is not None
    return asm, config, [s.name for s in specs], led, line


# ---------------------------------------------------------------------------
# Analytic sheet coverage
# ---------------------------------------------------------------------------


def test_cost_sheet_covers_u64_enumeration():
    from boojum_tpu.prover.precompile import enumerate_kernels

    asm, cfg = _fma_cfg_asm()
    specs = enumerate_kernels(asm, cfg)
    sheet = cm.cost_sheet(specs)
    assert set(sheet) == {s.name for s in specs}
    for name, ent in sheet.items():
        assert ent["flops"] >= 0, name
        assert ent["hbm_bytes"] > 0, name
        assert ent["ici_bytes"] == 0, name  # meshless: no ICI
        assert ent["family"] not in ("fallback", "error"), (
            f"{name} fell out of every modeled family"
        )


def test_cost_sheet_covers_limb_resident_enumeration(monkeypatch):
    from boojum_tpu.prover.precompile import enumerate_kernels

    monkeypatch.setenv("BOOJUM_TPU_LIMB_RESIDENT", "1")
    asm, cfg = _fma_cfg_asm()
    specs = enumerate_kernels(asm, cfg)
    names = {s.name for s in specs}
    assert "coset_sweep_terms_limbres" in names
    sheet = cm.cost_sheet(specs)
    assert set(sheet) == names
    for name, ent in sheet.items():
        assert ent["hbm_bytes"] > 0, name
        assert ent["family"] not in ("fallback", "error"), name
    # plane pairs carry the same field-element payload: the resident
    # sweep must not price bytes wildly differently from the u64 one
    monkeypatch.setenv("BOOJUM_TPU_LIMB_RESIDENT", "0")
    sheet_u64 = cm.cost_sheet(enumerate_kernels(asm, cfg))
    a = sheet["coset_sweep_terms_limbres"]["hbm_bytes"]
    b = sheet_u64["coset_sweep_terms"]["hbm_bytes"]
    assert 0.2 <= a / b <= 5.0


def test_plane_pair_args_price_like_u64():
    """A (lo, hi) u32 plane pair is ONE logical argument: E-keyed
    kernels (binv, stage2, deep, fri) must price the resident variant
    identically to the u64 one — not at half, which _flatten_args-based
    sizing once produced by measuring a single plane."""
    import jax
    import jax.numpy as jnp

    n = 1 << 11
    u64 = jax.ShapeDtypeStruct((4, n), jnp.uint64)
    u32 = jax.ShapeDtypeStruct((4, n), jnp.uint32)
    pair = (u32, u32)
    base = cm.kernel_cost("ext_binv", [u64])
    res = cm.kernel_cost("ext_binv_limbres", [pair])
    assert res["flops"] == base["flops"]
    assert res["hbm_bytes"] == base["hbm_bytes"]
    # a bare u32 array is still its own (half-size) payload
    assert cm.kernel_cost("ext_binv", [u32])["flops"] == base["flops"] / 2
    # a general list of arrays is NOT a pair: largest single array wins
    assert cm.kernel_cost("ext_binv", [[u64, u64, u64]])["flops"] == (
        base["flops"]
    )


def test_stage_costs_positive_and_scale_with_trace():
    from boojum_tpu.prover.shape_key import shape_bucket

    asm, cfg = _fma_cfg_asm()
    sb = shape_bucket(asm, cfg)
    stages = cm.stage_costs(sb, cfg)
    assert set(stages) == set(STAGES)
    for name, ent in stages.items():
        assert ent["flops"] > 0, name
        assert ent["hbm_bytes"] > 0, name
        assert ent["ici_bytes"] == 0, name
    # a mesh adds ICI traffic to the commit stages
    stages_mesh = cm.stage_costs(sb, cfg, mesh_devices=8)
    assert stages_mesh["round1_witness_commit"]["ici_bytes"] > 0
    assert (
        stages_mesh["round1_witness_commit"]["flops"]
        == stages["round1_witness_commit"]["flops"]
    )


# ---------------------------------------------------------------------------
# Record assembly (synthetic — no jax work)
# ---------------------------------------------------------------------------


def _synthetic_tree(walls: dict) -> list:
    children = [
        {"name": nm, "start_s": float(i), "wall_s": w, "children": []}
        for i, (nm, w) in enumerate(walls.items())
    ]
    return [{
        "name": "prove", "start_s": 0.0,
        "wall_s": sum(walls.values()), "children": children,
    }]


def test_build_cost_record_from_synthetic_spans():
    from boojum_tpu.prover.shape_key import shape_bucket

    asm, cfg = _fma_cfg_asm()
    sb = shape_bucket(asm, cfg)
    walls = {nm: 0.5 for nm in STAGES}
    peaks = {
        "kind": "test", "peak_gflops": 100.0, "peak_hbm_gbps": 50.0,
        "peak_ici_gbps": 0.0, "source": "env",
    }
    rec = cm.build_cost_record(
        sb, cfg, _synthetic_tree(walls), {}, peaks=peaks
    )
    assert set(rec["stages"]) == set(STAGES)
    for nm, ent in rec["stages"].items():
        assert ent["wall_s"] == 0.5
        assert ent["achieved_gflops"] > 0, nm
        assert ent["regime"] in ("compute", "memory"), nm
        assert 0 <= ent["efficiency"], nm
    total = rec["total"]
    assert total["wall_s"] == round(0.5 * len(STAGES), 6)
    assert total["achieved_gflops"] > 0
    # a stage whose wall never landed gets NO achieved/efficiency
    # (the zero-denominator rule the validator enforces)
    rec2 = cm.build_cost_record(
        sb, cfg, _synthetic_tree({"round3_quotient": 0.5}), {},
        peaks=peaks,
    )
    r1 = rec2["stages"]["round1_witness_commit"]
    assert r1["wall_s"] is None
    assert "achieved_gflops" not in r1
    assert "efficiency" not in r1


def test_roofline_zero_wall_claims_nothing():
    peaks = {"peak_gflops": 10.0, "peak_hbm_gbps": 10.0}
    out = cm.roofline({"flops": 100.0, "hbm_bytes": 10.0}, 0.0, peaks)
    assert "achieved_gflops" not in out
    assert "efficiency" not in out
    out = cm.roofline({"flops": 100.0, "hbm_bytes": 10.0}, 2.0, peaks)
    assert out["achieved_gflops"] > 0
    assert out["efficiency"] > 0


def test_roofline_submicrosecond_wall_rounds_to_consistent_record():
    """A positive wall below the 6-decimal rounding floor must not
    produce wall_s=0.0 alongside achieved fields — the validator
    rightly rejects efficiency claimed over a zero wall, so the
    producer must gate on the SAME rounded value it records."""
    peaks = {"peak_gflops": 10.0, "peak_hbm_gbps": 10.0}
    out = cm.roofline({"flops": 1000.0, "hbm_bytes": 10.0}, 2e-7, peaks)
    assert out["wall_s"] == 0.0
    assert "achieved_gflops" not in out
    assert "efficiency" not in out


def test_stage_walls_takes_last_prove_span():
    """A long-lived recorder (bench/CLI bare-SpanRecorder path) can
    hold several prove roots — the cost record must join the walls of
    the prove that just FINISHED, not the first one."""
    tree = (
        _synthetic_tree({"round3_quotient": 1.0})
        + _synthetic_tree({"round3_quotient": 7.0})
    )
    walls = report.stage_walls(tree, names=report.PROVE_STAGES)
    assert walls == {"round3_quotient": 7.0}


def test_span_coverage_shares_stage_walls_root():
    """One report line's coverage= and stage numbers must describe the
    SAME prove: span_coverage reuses stage_walls' root selection (last
    prove span, found anywhere in the tree)."""
    # multi-prove recorder: first prove 50% covered, last 100%
    first = _synthetic_tree({"round3_quotient": 1.0})
    first[0]["wall_s"] = 2.0
    last = _synthetic_tree({"round3_quotient": 4.0})
    cov = report.span_coverage({"spans": first + last})
    assert cov == 1.0
    # service line: prove nested under the service_request root
    nested = [{
        "name": "service_request", "start_s": 0.0, "wall_s": 100.0,
        "children": _synthetic_tree({"round3_quotient": 3.0}),
    }]
    assert report.span_coverage({"spans": nested}) == 1.0


def test_kernel_costs_filter_by_shape_key():
    """The compile ledger is process-global and kernel names are not
    shape-qualified — a multi-bucket process must get ITS bucket's XLA
    actuals, never another bucket's (a 2^12 sweep's flops attributed to
    a 2^10 prove would skew model_check ~4x)."""
    from boojum_tpu.utils.profiling import CompileLedger

    led = CompileLedger()
    led.record("coset_sweep_terms", 0.1, 1.0, shape_key="bucket_a",
               xla_cost={"flops": 100.0})
    led.record("coset_sweep_terms", 0.1, 1.0, shape_key="bucket_b",
               xla_cost={"flops": 400.0})
    assert led.kernel_costs(shape_key="bucket_a") == {
        "coset_sweep_terms": {"flops": 100.0}
    }
    assert led.kernel_costs(shape_key="bucket_b") == {
        "coset_sweep_terms": {"flops": 400.0}
    }
    # unfiltered keeps the legacy last-wins union
    assert led.kernel_costs() == {
        "coset_sweep_terms": {"flops": 400.0}
    }


def test_platform_info_memoized_and_copy_safe():
    """platform_info rides every report/bench line — it must probe the
    stack once per process and hand out copies a caller can't poison."""
    from boojum_tpu.prover.aot import platform_info

    a = platform_info()
    b = platform_info()
    assert a == b and a is not b
    a["jax"] = "poisoned"
    assert platform_info()["jax"] != "poisoned"


# ---------------------------------------------------------------------------
# E2E: the 2^10 CPU prove's cost record (acceptance)
# ---------------------------------------------------------------------------


def test_e2e_prove_emits_valid_cost_record():
    _asm, _cfg, spec_names, _led, line = _proved_with_costs()
    cost = line.get("cost")
    assert isinstance(cost, dict), "prove emitted no cost record"
    assert line["schema"] == report.REPORT_SCHEMA
    problems = report.validate_report(line)
    assert problems == [], problems
    # every prover stage measured and attributed
    for nm in STAGES:
        ent = cost["stages"][nm]
        assert ent["wall_s"] > 0, nm
        assert ent["achieved_gflops"] >= 0, nm
        assert ent["regime"] in ("compute", "memory"), nm
    assert cost["total"]["achieved_gflops"] > 0
    # the sheet covers exactly the dispatched enumeration
    assert cost["kernels"] == sorted(spec_names)
    # ledger actuals attributed, and only to recorded kernels
    ledger = line["compile_ledger"]
    assert ledger["cost_kernels"] > 0
    assert set(cost["attributed_kernels"]) <= set(ledger["kernel_names"])
    # cost.* gauges rode the line's metrics (and therefore /metrics)
    gauges = line["metrics"]["gauges"]
    assert gauges.get("cost.total.achieved_gflops", 0) > 0
    assert any(k.startswith("cost.round3_quotient.") for k in gauges)


def test_analytic_model_within_tolerance_of_xla():
    """Acceptance: the analytic model agrees with XLA cost_analysis()
    within the documented band for the dispatched kernel set — family
    aggregates within 4x, totals within 2.5x (BASELINE.md "Cost model
    & trend protocol"). The `small` family (sub-microsecond power
    tables) is explicitly outside the band."""
    _asm, _cfg, spec_names, _led, line = _proved_with_costs()
    mc = line["cost"]["model_check"]
    assert mc["covered_kernels"] >= 0.8 * len(spec_names), mc
    assert 0.4 <= mc["flops_ratio"] <= 2.5, mc
    assert 0.4 <= mc["bytes_ratio"] <= 2.5, mc
    for fam, ent in mc["families"].items():
        if fam in ("small", "transfer", "fallback", "error"):
            continue
        for key in ("flops_ratio", "bytes_ratio"):
            r = ent.get(key)
            if r is None:
                continue
            assert 0.25 <= r <= 4.0, (fam, key, r, mc["families"])


def test_roofline_cli_and_check_cli(tmp_path):
    _asm, _cfg, _names, _led, line = _proved_with_costs()
    path = tmp_path / "cost.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps(line) + "\n")
    script = os.path.join(REPO, "scripts", "prove_report.py")
    chk = subprocess.run(
        [sys.executable, script, "--check", str(path)],
        capture_output=True, text=True, timeout=120,
    )
    assert chk.returncode == 0, chk.stdout + chk.stderr
    roof = subprocess.run(
        [sys.executable, script, "--roofline", str(path)],
        capture_output=True, text=True, timeout=120,
    )
    assert roof.returncode == 0, roof.stdout + roof.stderr
    assert "round3_quotient" in roof.stdout
    assert "GFLOP/s" in roof.stdout
    assert "model check" in roof.stdout


# ---------------------------------------------------------------------------
# --check gate: fabricated cost records FAIL
# ---------------------------------------------------------------------------


def test_check_rejects_negative_efficiency():
    *_, line = _proved_with_costs()
    bad = copy.deepcopy(line)
    bad["cost"]["stages"]["round3_quotient"]["efficiency"] = -0.5
    probs = report.validate_report(bad)
    assert any("efficiency invalid" in p for p in probs), probs


def test_check_rejects_zero_denominator_efficiency():
    *_, line = _proved_with_costs()
    # claimed over a zero wall
    bad = copy.deepcopy(line)
    bad["cost"]["stages"]["round3_quotient"]["wall_s"] = 0
    probs = report.validate_report(bad)
    assert any("zero/absent wall" in p for p in probs), probs
    # claimed over a zero peak
    bad = copy.deepcopy(line)
    bad["cost"]["device"]["peak_gflops"] = 0
    probs = report.validate_report(bad)
    assert any("zero/absent" in p and "peak" in p for p in probs), probs


def test_check_rejects_kernels_absent_from_ledger():
    *_, line = _proved_with_costs()
    bad = copy.deepcopy(line)
    bad["cost"]["attributed_kernels"] = list(
        bad["cost"].get("attributed_kernels") or []
    ) + ["bogus_kernel_nobody_compiled"]
    probs = report.validate_report(bad)
    assert any("absent from the compile ledger" in p for p in probs), probs
    # and the pristine line still passes
    assert report.validate_report(line) == []


def test_diff_reports_cost_efficiency_deltas():
    *_, line = _proved_with_costs()
    other = copy.deepcopy(line)
    st = other["cost"]["stages"]["round3_quotient"]
    if isinstance(st.get("efficiency"), (int, float)):
        st["efficiency"] = st["efficiency"] / 2
    diff = report.diff_reports(line, other)
    assert "round3_quotient" in diff["cost_deltas"]
    ent = diff["cost_deltas"]["round3_quotient"]
    assert ent["efficiency_delta"] is not None
    assert "cost (roofline) deltas" in report.render_diff(diff)


def test_slo_summary_carries_roofline():
    *_, line = _proved_with_costs()
    summary = report.slo_summary([line, line])
    roof = summary["roofline"]
    assert roof["lines"] == 2
    assert "round3_quotient" in roof["stages"]
    assert roof["stages"]["round3_quotient"]["mean_efficiency"] >= 0
    assert "roofline" in report.render_slo(summary)


# ---------------------------------------------------------------------------
# Trend + gate
# ---------------------------------------------------------------------------


def _report_artifact(path, total, stage_walls, label):
    line = {
        "kind": report.REPORT_KIND,
        "schema": report.REPORT_SCHEMA,
        "label": label,
        "unix_ts": 0,
        "wall_s": total,
        "spans": _synthetic_tree(stage_walls),
        "metrics": {"counters": {}, "gauges": {}, "boundaries": []},
        "checkpoints": [],
    }
    with open(path, "w") as f:
        f.write(json.dumps(line) + "\n")
    return path


def _bench_history_paths():
    return [
        os.path.join(REPO, f)
        for f in (
            "BENCH_BASELINE.json", "BENCH_r01.json", "BENCH_r02.json",
            "BENCH_r03.json", "BENCH_r04.json",
        )
    ]


def test_trend_gate_fires_exactly_on_regressed_stage(tmp_path):
    """Acceptance: BENCH_*.json history + synthetic report artifacts —
    the gate exits nonzero exactly on the regressed stage: round3 blew
    up 3x, every other series (including the totals fed by the real
    BENCH history and round5) stays quiet."""
    prev = _report_artifact(
        tmp_path / "prev.jsonl", 20.0,
        {"round3_quotient": 1.0, "round5_deep_fri": 2.0}, "prev",
    )
    last = _report_artifact(
        tmp_path / "last.jsonl", 20.3,
        {"round3_quotient": 3.0, "round5_deep_fri": 2.05}, "last",
    )
    points, notes = report.load_trend_points(
        _bench_history_paths() + [str(prev), str(last)]
    )
    # r03 (rc=124, parsed null) and r04 (timeout+no_prove) are skipped
    assert sum("BENCH_r03" in n for n in notes) == 1, notes
    assert sum("BENCH_r04" in n for n in notes) == 1, notes
    assert len(points) == 5  # BASELINE, r01, r02, prev, last
    series = report.trend_series(points)
    regressions = report.trend_gate(series)
    assert len(regressions) == 1, regressions
    assert regressions[0]["series"] == "stage:round3_quotient"
    assert regressions[0]["ratio"] == 3.0
    rendered = report.render_trend(series, regressions)
    assert "REGRESSED" in rendered
    assert "stage:round3_quotient" in rendered
    # without the regressed artifact, the gate stays green
    assert report.trend_gate(
        report.trend_series(points[:-1])
    ) == []


def test_trend_skips_trailing_reject_lines(tmp_path):
    """A gateway 429/shed reject line (wall_s=0.0, no spans) trailing
    an artifact must not become its trend point — the last line holding
    a real prove span does; an artifact of ONLY reject lines is
    skipped entirely."""
    reject = {
        "kind": report.REPORT_KIND, "schema": report.REPORT_SCHEMA,
        "label": "gateway:throttled", "unix_ts": 0, "wall_s": 0.0,
        "spans": [],
        "metrics": {
            "counters": {"service.gateway.throttled": 1}, "gauges": {},
        },
        "checkpoints": [],
    }
    p = tmp_path / "mixed.jsonl"
    _report_artifact(p, 10.0, {"round3_quotient": 1.0}, "rep")
    with open(p, "a") as f:
        f.write(json.dumps(reject) + "\n")
    points, _ = report.load_trend_points([str(p)])
    assert len(points) == 1
    assert points[0]["values"]["total_wall"]["value"] == 10.0
    only = tmp_path / "only_rejects.jsonl"
    with open(only, "w") as f:
        f.write(json.dumps(reject) + "\n")
    points, notes = report.load_trend_points([str(only)])
    assert points == []
    assert any("only_rejects" in n for n in notes)


def test_attach_subtracts_measured_traffic_baseline():
    """On a long-lived registry (bench multi-rep) the ici./transfer.
    families are cumulative — the prove-start baseline makes the cost
    record carry per-PROVE bytes, not the running total."""
    from boojum_tpu.utils import metrics as _metrics

    reg = _metrics.MetricsRegistry()
    reg.gauge_add("ici.all_to_all_bytes", 1000.0)
    reg.count("transfer.h2d_bytes", 600)
    tok = _metrics.install_scoped_registry(reg)
    try:
        base = cm.measured_baseline()
    finally:
        _metrics.reset_scoped_registry(tok)
    assert base["gauges"]["ici.all_to_all_bytes"] == 1000.0
    assert base["counters"]["transfer.h2d_bytes"] == 600.0
    # this prove adds 250 ICI + 100 h2d on top of the running totals
    reg.gauge_add("ici.all_to_all_bytes", 250.0)
    reg.count("transfer.h2d_bytes", 100)
    snap = cm._subtract_baseline(reg.to_dict(), base)
    assert snap["gauges"]["ici.all_to_all_bytes"] == 250.0
    assert snap["counters"]["transfer.h2d_bytes"] == 100.0
    # a registry swapped mid-prove (fresh, below baseline) clamps at 0
    fresh = _metrics.MetricsRegistry()
    fresh.gauge_add("ici.all_to_all_bytes", 10.0)
    snap = cm._subtract_baseline(fresh.to_dict(), base)
    assert snap["gauges"]["ici.all_to_all_bytes"] == 0.0


def test_trend_total_series_spans_bench_and_reports(tmp_path):
    prev = _report_artifact(
        tmp_path / "prev.jsonl", 20.0, {"round3_quotient": 1.0}, "prev"
    )
    points, _ = report.load_trend_points(
        _bench_history_paths() + [str(prev)]
    )
    series = report.trend_series(points)
    totals = series[("", "total_wall")]["points"]
    assert [round(v, 2) for _l, v in totals] == [35.62, 21.67, 19.79, 20.0]


def test_trend_skips_warm_only_bench_lines(tmp_path):
    """A watchdog line whose status carries +warm_only measured the
    compile-laden warm-up wall, not steady state — it must feed no
    trend series (same rule as +no_prove)."""
    p = tmp_path / "warm.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({
            "metric": "fma_2p10_prove_wall", "value": 280.0, "unit": "s",
            "status": "timeout+warm_only",
        }) + "\n")
        f.write(json.dumps({
            "metric": "fma_2p10_prove_wall", "value": 11.0, "unit": "s",
            "status": "ok",
        }) + "\n")
    points, _ = report.load_trend_points([str(p)])
    vals = [
        pt["values"]["total_wall"]["value"]
        for pt in points if "total_wall" in pt["values"]
    ]
    assert vals == [11.0]


def test_report_line_host_identity_feeds_trend_grouping():
    """ProveReport lines must carry the SAME five-field identity block
    bench/bench_micro stamp — an empty _trend_identity would collapse
    report artifacts from two machines into one gated series."""
    *_, line = _proved_with_costs()
    h = line.get("host") or {}
    for k in ("host_fp", "device_kind", "backend", "jax", "jaxlib"):
        assert h.get(k), f"host block missing {k}"
    assert report._trend_identity(line) != ""


def test_prime_sheet_skips_reenumeration(monkeypatch):
    """precompile primes the assembly's sheet cache from its own
    enumeration — the first recorded prove's cost seam must then hit
    the cache, never re-walking enumerate_kernels inside its span."""
    import importlib

    # the package re-exports the precompile FUNCTION under the same
    # name as the submodule — resolve the module itself
    pc = importlib.import_module("boojum_tpu.prover.precompile")

    asm, cfg = _fma_cfg_asm()
    specs = pc.enumerate_kernels(asm, cfg)
    cm.prime_sheet(asm, cfg, specs)

    def _boom(*a, **k):
        raise AssertionError("cost seam re-enumerated the kernel library")

    monkeypatch.setattr(pc, "enumerate_kernels", _boom)
    sheet = cm._cached_sheet(asm, cfg)
    assert set(sheet) == {s.name for s in specs}


def test_trend_legacy_history_adopts_sole_real_identity(tmp_path):
    """Pre-identity BENCH history (identity "") must keep gating new
    identity-stamped runs: with exactly one real identity in play the
    legacy points join its series; with two they stay split."""
    ident_a = {"host_fp": "aaaa", "device_kind": "cpu", "backend": "cpu",
               "jax": "0.4.37", "jaxlib": "0.4.36"}
    legacy = tmp_path / "legacy.jsonl"
    with open(legacy, "w") as f:
        for v in (10.0, 10.2):
            f.write(json.dumps({
                "metric": "fma_2p10_prove_wall", "value": v, "unit": "s",
            }) + "\n")
    new = tmp_path / "new.jsonl"
    with open(new, "w") as f:
        f.write(json.dumps({
            "metric": "fma_2p10_prove_wall", "value": 30.0, "unit": "s",
            "host": ident_a,
        }) + "\n")
    points, _ = report.load_trend_points([str(legacy), str(new)])
    series = report.trend_series(points)
    assert len(series) == 1  # merged under ident_a
    regs = report.trend_gate(series)
    assert len(regs) == 1 and regs[0]["last"] == 30.0
    # a SECOND real identity makes legacy attribution ambiguous: split
    ident_b = dict(ident_a, host_fp="bbbb")
    other = tmp_path / "other.jsonl"
    with open(other, "w") as f:
        f.write(json.dumps({
            "metric": "fma_2p10_prove_wall", "value": 9.0, "unit": "s",
            "host": ident_b,
        }) + "\n")
    points, _ = report.load_trend_points(
        [str(legacy), str(new), str(other)]
    )
    series = report.trend_series(points)
    assert len(series) == 3  # legacy "", ident_a, ident_b — no adoption
    assert report.trend_gate(series) == []


def test_deep_codeword_ici_matches_stage_convention():
    """The per-kernel deep_codeword ICI and the round5 stage total both
    price the SAME col->row plane re-layout: global payload with
    (D-1)/D crossing chips — they may never disagree by a factor of D."""
    import numpy as np

    class _Sds:
        def __init__(self, *shape):
            self.shape = shape
            self.dtype = np.dtype(np.uint32)

    N, D = 2048.0, 8
    ent = cm.kernel_cost(
        "deep_codeword_sm", [_Sds(16, int(N))], mesh_devices=D
    )
    assert ent["family"] == "deep"
    assert ent["ici_bytes"] == N * 8 * 2 * (D - 1) / D


def test_trend_identity_separates_backend_and_jaxlib():
    """The documented grouping contract is host_fp / device_kind /
    backend / jax / jaxlib — two jaxlib builds (or backends) on the
    same machine must never share a gated series."""
    base = {"host_fp": "aaaa", "device_kind": "cpu", "jax": "0.4.37"}
    a = report._trend_identity(
        {"host": {**base, "backend": "cpu", "jaxlib": "0.4.37"}}
    )
    b = report._trend_identity(
        {"host": {**base, "backend": "cpu", "jaxlib": "0.4.38"}}
    )
    c = report._trend_identity(
        {"host": {**base, "backend": "tpu", "jaxlib": "0.4.37"}}
    )
    assert len({a, b, c}) == 3


def test_trend_gates_throughput_drop_and_groups_identity(tmp_path):
    a = tmp_path / "micro_a.jsonl"
    b = tmp_path / "micro_b.jsonl"
    ident = {"host_fp": "aaaa", "device_kind": "cpu", "jax": "0.4.37"}
    other = {"host_fp": "bbbb", "device_kind": "tpu", "jax": "0.4.37"}
    with open(a, "w") as f:
        f.write(json.dumps({
            "metric": "ntt_pair_elems_per_s", "value": 1000,
            "unit": "elems/s", "host": ident,
        }) + "\n")
    with open(b, "w") as f:
        f.write(json.dumps({
            "metric": "ntt_pair_elems_per_s", "value": 400,
            "unit": "elems/s", "host": ident,
        }) + "\n")
    points, _ = report.load_trend_points([str(a), str(b)])
    regs = report.trend_gate(report.trend_series(points))
    assert len(regs) == 1 and regs[0]["direction"] == "higher"
    # a different machine's line starts its own series: no gate fires
    # across identities even with a "worse" number
    with open(b, "w") as f:
        f.write(json.dumps({
            "metric": "ntt_pair_elems_per_s", "value": 400,
            "unit": "elems/s", "host": other,
        }) + "\n")
    points, _ = report.load_trend_points([str(a), str(b)])
    assert report.trend_gate(report.trend_series(points)) == []


def test_stage_walls_finds_prove_nested_under_service_root():
    """Service-mode lines nest `prove` under the `service_request` root
    span: the shared extraction must find it anywhere in the tree, or
    every packed-service cost record silently loses its stage walls."""
    nested = [{
        "name": "service_request", "start_s": 0.0, "wall_s": 3.0,
        "children": _synthetic_tree({"round3_quotient": 1.5}),
    }]
    walls = report.stage_walls(nested, names=report.PROVE_STAGES)
    assert walls == {"round3_quotient": 1.5}
    # and costmodel's view is the same extraction
    assert cm.STAGE_NAMES == report.PROVE_STAGES


def test_trend_stage_series_exclude_cache_state_spans(tmp_path):
    """aot_load/aot_warm land under `prove` but are artifact-store
    temperature, not prover stages — gating them would fail CI on a
    cold cache. Only PROVE_STAGES become stage:<name> series."""
    walls = {"round3_quotient": 1.0, "aot_warm": 30.0}
    p = _report_artifact(tmp_path / "a.jsonl", 31.0, walls, "a")
    points, _ = report.load_trend_points([str(p)])
    series = report.trend_series(points)
    names = {name for _i, name in series}
    assert "stage:round3_quotient" in names
    assert "stage:aot_warm" not in names


def test_trend_total_wall_excludes_cache_state_spans(tmp_path):
    """A cold-cache artifact's wall is dominated by aot_load/aot_warm
    (compile/deserialize). The total_wall trend point subtracts those
    spans so the gate fires on prover performance, never on
    artifact-store temperature — cold head vs warm history stays
    green, and a cold baseline can't mask a warm-head regression."""
    warm = _report_artifact(
        tmp_path / "warm.jsonl", 10.0, {"round3_quotient": 9.0}, "warm"
    )
    cold = _report_artifact(
        tmp_path / "cold.jsonl", 41.0,
        {"aot_load": 1.0, "aot_warm": 30.0, "round3_quotient": 9.5},
        "cold",
    )
    points, _ = report.load_trend_points([str(warm), str(cold)])
    totals = {
        p["label"]: p["values"]["total_wall"]["value"] for p in points
    }
    assert totals["warm.jsonl"] == 10.0
    assert totals["cold.jsonl"] == 10.0  # 41.0 minus the 31s of cache
    assert report.trend_gate(report.trend_series(points)) == []


def test_trend_duplicate_labels_and_column_order(tmp_path):
    (tmp_path / "runA").mkdir()
    (tmp_path / "runB").mkdir()
    a = _report_artifact(
        tmp_path / "runA" / "report.jsonl", 10.0,
        {"round3_quotient": 1.0}, "x",
    )
    b = _report_artifact(
        tmp_path / "runB" / "report.jsonl", 12.0,
        {"round3_quotient": 1.1}, "x",
    )
    points, _ = report.load_trend_points([str(a), str(b)])
    labels = [p["label"] for p in points]
    assert labels == ["runA/report.jsonl", "runB/report.jsonl"]
    series = report.trend_series(points)
    rendered = report.render_trend(series, [], labels=labels)
    # both columns present, in artifact order
    assert rendered.index("runA/report.jsonl") < rendered.index(
        "runB/report.jsonl"
    )
    assert "10 " in rendered or "10\n" in rendered or "10 |" in rendered


def test_trend_gate_cli_smoke(tmp_path):
    """CI satellite: the fast CPU smoke — `--trend --gate` over two
    synthetic report artifacts exits 1 on the regression, 0 without."""
    prev = _report_artifact(
        tmp_path / "prev.jsonl", 10.0, {"round3_quotient": 1.0}, "prev"
    )
    last = _report_artifact(
        tmp_path / "last.jsonl", 10.1, {"round3_quotient": 2.4}, "last"
    )
    ok = _report_artifact(
        tmp_path / "ok.jsonl", 10.0, {"round3_quotient": 1.02}, "ok"
    )
    script = os.path.join(REPO, "scripts", "prove_report.py")
    bad = subprocess.run(
        [sys.executable, script, "--trend", str(prev), str(last),
         "--gate"],
        capture_output=True, text=True, timeout=120,
    )
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "GATE" in bad.stdout and "round3_quotient" in bad.stdout
    good = subprocess.run(
        [sys.executable, script, "--trend", str(prev), str(ok), "--gate"],
        capture_output=True, text=True, timeout=120,
    )
    assert good.returncode == 0, good.stdout + good.stderr
    assert "GATE: ok" in good.stdout


# ---------------------------------------------------------------------------
# /metrics Prometheus audit (satellite)
# ---------------------------------------------------------------------------


def test_prometheus_renders_every_family():
    from boojum_tpu.service.http_metrics import prometheus_text
    from boojum_tpu.utils.metrics import MetricsRegistry

    reg = MetricsRegistry()
    fams = (
        "ici", "limb", "aot", "quotient", "fri", "transfer", "service",
        "cost",
    )
    for fam in fams:
        reg.count(f"{fam}.things", 3)
        reg.gauge_set(f"{fam}.level", 1.5)
    text = prometheus_text(reg.to_dict())
    for fam in fams:
        assert f"boojum_tpu_{fam}_things 3" in text, (fam, text)
        assert f"boojum_tpu_{fam}_level 1.5" in text, (fam, text)


def test_metrics_plane_merges_prove_registry():
    from boojum_tpu.service.http_metrics import MetricsPlane
    from boojum_tpu.utils import metrics as _metrics
    from boojum_tpu.utils.telemetry import TelemetrySampler

    sampler = TelemetrySampler(interval_s=60.0)
    sampler.registry.gauge_set("telemetry.canary", 7.0)
    reg = _metrics.MetricsRegistry()
    reg.count("fri.folds", 3)
    reg.gauge_set("cost.total.efficiency", 0.25)
    plane = MetricsPlane(sampler)
    prev = _metrics.install_registry(reg)
    try:
        text = plane.render_metrics()
    finally:
        _metrics.install_registry(prev)
    assert "boojum_tpu_fri_folds 3" in text
    assert "boojum_tpu_cost_total_efficiency 0.25" in text
    assert "boojum_tpu_telemetry_canary 7.0" in text
    # without the global registry, the sampler view still renders
    text = plane.render_metrics()
    assert "boojum_tpu_telemetry_canary 7.0" in text


def test_post_prove_registry_snapshot_fully_exported():
    """Satellite: pin the exported set against a REAL post-prove
    registry snapshot — every counter/gauge family the 2^10 prove
    recorded renders under boojum_tpu_*."""
    from boojum_tpu.service.http_metrics import _prom_name, prometheus_text

    *_, line = _proved_with_costs()
    metrics = line["metrics"]
    text = prometheus_text(metrics)
    keys = list(metrics["counters"]) + list(metrics["gauges"])
    assert keys, "prove recorded no metrics"
    for k in keys:
        assert f"{_prom_name(k)} " in text, k
    families = {k.split(".")[0] for k in keys}
    assert {"prover", "transfer", "cost"} <= families, families


# ---------------------------------------------------------------------------
# Identity block (satellite)
# ---------------------------------------------------------------------------


def test_bench_micro_lines_carry_identity(capsys):
    sys.path.insert(0, REPO)
    try:
        import bench_micro
    finally:
        sys.path.remove(REPO)
    ident = bench_micro.host_identity()
    for key in ("host_fp", "device_kind", "jax", "jaxlib", "backend"):
        assert key in ident, ident
    bench_micro.emit("canary_metric", 1, "s")
    out = capsys.readouterr().out.strip().splitlines()[-1]
    line = json.loads(out)
    assert line["metric"] == "canary_metric"
    assert line["host"]["host_fp"] == ident["host_fp"]
    # the identity matches what the AOT bundle manifests validate on
    from boojum_tpu.prover.aot import platform_info

    assert ident == platform_info()


def test_cost_telemetry_provider_flattens_last_record():
    _asm, _cfg, _names, _led, line = _proved_with_costs()
    assert cm.last_cost_record() is not None
    flat = cm.telemetry_provider()
    assert flat, "provider returned nothing after a costed prove"
    for k, v in flat.items():
        assert isinstance(v, (int, float)) and v >= 0, (k, v)
    assert any(k.startswith("round") for k in flat)
