"""Recursive verification tests (reference test model:
recursive_verifier.rs:2213 — prove a circuit, synthesize the verifier circuit
over the proof, check satisfiability)."""

from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.types import CSGeometry
from boojum_tpu.field import gl
from boojum_tpu.gadgets.recursion import recursive_verify
from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify
from boojum_tpu.prover.proof import Proof
from boojum_tpu.prover.satisfiability import check_if_satisfied

from test_e2e import GEOM as INNER_GEOM, build_fibonacci_circuit

RECURSION_GEOM = CSGeometry(
    num_columns_under_copy_permutation=130,
    num_witness_columns=0,
    num_constant_columns=8,
    max_allowed_constraint_degree=7,
)

INNER_CONFIG = ProofConfig(
    fri_lde_factor=8,
    merkle_tree_cap_size=4,
    num_queries=8,
    pow_bits=0,
    fri_final_degree=4,
)


def _prove_inner():
    cs, _ = build_fibonacci_circuit(steps=20)
    asm = cs.into_assembly()
    setup = generate_setup(asm, INNER_CONFIG)
    proof = prove(asm, setup, INNER_CONFIG)
    assert verify(setup.vk, proof, asm.gates)
    return setup.vk, proof, asm.gates


def test_recursive_verifier_satisfiable():
    vk, proof, gates = _prove_inner()
    outer = ConstraintSystem(RECURSION_GEOM, 1 << 15)
    pi_vars, _cap_vars = recursive_verify(outer, vk, proof, gates)
    assert [outer.get_value(v) for v in pi_vars] == list(proof.public_inputs)
    outer_asm = outer.into_assembly()
    assert check_if_satisfied(outer_asm, verbose=True)


def test_recursive_verifier_lookup_pow():
    """The in-circuit verifier's lookup-argument and PoW branches, exercised
    with the geometry real Era-style circuits use (lookups on, pow_bits>0):
    satisfiable on the honest proof, unsatisfiable on a tampered lookup
    opening and on a tampered PoW nonce."""
    from boojum_tpu.examples import build_xor_lookup_circuit

    cfg = ProofConfig(
        fri_lde_factor=8,
        merkle_tree_cap_size=4,
        num_queries=4,
        pow_bits=4,
        fri_final_degree=4,
    )
    cs, _, _ = build_xor_lookup_circuit(num_lookups=8)
    asm = cs.into_assembly()
    setup = generate_setup(asm, cfg)
    proof = prove(asm, setup, cfg)
    assert verify(setup.vk, proof, asm.gates)

    outer = ConstraintSystem(RECURSION_GEOM, 1 << 15)
    pi_vars, _cap = recursive_verify(outer, setup.vk, proof, asm.gates)
    assert [outer.get_value(v) for v in pi_vars] == list(proof.public_inputs)
    assert check_if_satisfied(outer.into_assembly(), verbose=True)

    # tampered lookup sum opening (values at 0) must be unsatisfiable
    bad = Proof.from_json(proof.to_json())
    v = list(bad.values_at_0[0])
    v[0] = (v[0] + 1) % gl.P
    bad.values_at_0[0] = tuple(v)
    outer2 = ConstraintSystem(RECURSION_GEOM, 1 << 15)
    recursive_verify(outer2, setup.vk, bad, asm.gates)
    assert not check_if_satisfied(outer2.into_assembly())

    # tampered PoW nonce must be unsatisfiable
    bad2 = Proof.from_json(proof.to_json())
    bad2.pow_challenge += 1
    outer3 = ConstraintSystem(RECURSION_GEOM, 1 << 15)
    recursive_verify(outer3, setup.vk, bad2, asm.gates)
    assert not check_if_satisfied(outer3.into_assembly())


def test_recursive_verifier_rejects_bad_proof():
    vk, proof, gates = _prove_inner()
    bad = Proof.from_json(proof.to_json())
    bad.public_inputs[0] = (bad.public_inputs[0] + 1) % gl.P
    outer = ConstraintSystem(RECURSION_GEOM, 1 << 15)
    recursive_verify(outer, vk, bad, gates)
    outer_asm = outer.into_assembly()
    assert not check_if_satisfied(outer_asm)


import os
import pytest


def test_recursive_proof_proves_and_verifies():
    """The counterpart of the reference's recursive bench
    (sha256_bench_recursive_poseidon2.sh / recursive_verifier.rs:2213
    proving config): the 130-column recursive-verifier circuit itself goes
    through setup -> prove -> verify, so a proof-of-a-proof exists."""
    import time

    from boojum_tpu.cs.gates import PublicInputGate

    vk, proof, gates = _prove_inner()
    outer = ConstraintSystem(RECURSION_GEOM, 1 << 15)
    pi_vars, _cap = recursive_verify(outer, vk, proof, gates)
    # surface the inner public inputs as the outer circuit's own
    for v in pi_vars:
        PublicInputGate.place(outer, v)
    outer_asm = outer.into_assembly()
    outer_cfg = ProofConfig(
        # the degree-aware selector tree keeps the degree-7 flattened
        # Poseidon2 gate at depth 1, so LDE 8 suffices
        fri_lde_factor=8,
        merkle_tree_cap_size=8,
        num_queries=4,
        pow_bits=0,
        fri_final_degree=16,
    )
    t0 = time.time()
    outer_setup = generate_setup(outer_asm, outer_cfg)
    outer_proof = prove(outer_asm, outer_setup, outer_cfg)
    wall = time.time() - t0
    assert verify(outer_setup.vk, outer_proof, outer_asm.gates), (
        "recursive proof must verify"
    )
    print(f"recursive prove wall: {wall:.1f}s, trace {outer_asm.trace_len}")
    # the outer proof's public inputs surface the inner ones
    surfaced = [pi[2] for pi in outer_asm.public_inputs[: len(pi_vars)]]
    assert surfaced == list(proof.public_inputs)


def test_recursive_verifier_general_lookup_mode():
    """In-circuit verification of a GENERAL-purpose-columns lookup proof
    (reference lookup_placement.rs:21 + recursive_verifier.rs:380): the
    A-relations are gated by the marker gate's selector at z and the table
    id comes from the marker row's constant. Satisfiable on the honest
    proof; unsatisfiable when a lookup opening is tampered."""
    import sys as _sys

    _sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from test_lookup_general import CONFIG as GL_CONFIG, build_circuit

    cs, _ = build_circuit(num_lookups=12)
    asm = cs.into_assembly()
    setup = generate_setup(asm, GL_CONFIG)
    proof = prove(asm, setup, GL_CONFIG)
    assert verify(setup.vk, proof, asm.gates)

    outer = ConstraintSystem(RECURSION_GEOM, 1 << 15)
    pi_vars, _cap = recursive_verify(outer, setup.vk, proof, asm.gates)
    assert [outer.get_value(v) for v in pi_vars] == list(proof.public_inputs)
    assert check_if_satisfied(outer.into_assembly(), verbose=True)

    # tampered lookup A-opening must be unsatisfiable
    bad = Proof.from_json(proof.to_json())
    num_chunks = 2  # 8 copy cols at max degree 4 -> 2 chunks
    ab_off_abs = (
        2 * setup.vk.num_copy_cols
        + setup.vk.num_wit_cols
        + 1  # multiplicities column opening
        + setup.vk.geometry.num_constant_columns
        + (setup.vk.lookup_params.width + 1)
        + 2 * (1 + (num_chunks - 1))
    )
    c0, c1 = bad.values_at_z[ab_off_abs]
    bad.values_at_z[ab_off_abs] = ((c0 + 1) % gl.P, c1)
    outer2 = ConstraintSystem(RECURSION_GEOM, 1 << 15)
    recursive_verify(outer2, setup.vk, bad, asm.gates)
    assert not check_if_satisfied(outer2.into_assembly())


def test_recursive_verifier_legacy_poseidon_transcript():
    """Legacy-recursion-mode transcript (reference recursive_transcript.rs is
    generic over the round function; the legacy mode drives it with
    PoseidonFlattenedGate): an inner proof drawn with
    ProofConfig(transcript="poseidon") replays in-circuit through the
    legacy-Poseidon sponge gadget. Satisfiable on the honest proof;
    unsatisfiable on a tampered public input (which shifts every legacy
    transcript challenge)."""
    cfg = ProofConfig(
        fri_lde_factor=8,
        merkle_tree_cap_size=4,
        num_queries=8,
        pow_bits=0,
        fri_final_degree=4,
        transcript="poseidon",
    )
    cs, _ = build_fibonacci_circuit(steps=20)
    asm = cs.into_assembly()
    setup = generate_setup(asm, cfg)
    assert setup.vk.transcript == "poseidon"
    proof = prove(asm, setup, cfg)
    assert verify(setup.vk, proof, asm.gates)

    outer = ConstraintSystem(RECURSION_GEOM, 1 << 15)
    pi_vars, _cap = recursive_verify(outer, setup.vk, proof, asm.gates)
    assert [outer.get_value(v) for v in pi_vars] == list(proof.public_inputs)
    assert check_if_satisfied(outer.into_assembly(), verbose=True)

    bad = Proof.from_json(proof.to_json())
    bad.public_inputs[0] = (bad.public_inputs[0] + 1) % gl.P
    outer2 = ConstraintSystem(RECURSION_GEOM, 1 << 15)
    recursive_verify(outer2, setup.vk, bad, asm.gates)
    assert not check_if_satisfied(outer2.into_assembly())
