"""Blake2s gadget tests: digest parity vs hashlib + satisfiability
(reference test model: gadgets/blake2s/mod.rs:159)."""

import hashlib

from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.types import CSGeometry, LookupParameters
from boojum_tpu.gadgets import allocate_u8_input
from boojum_tpu.gadgets.blake2s import blake2s, blake2s_digest_bytes
from boojum_tpu.prover.satisfiability import check_if_satisfied

GEOM = CSGeometry(
    num_columns_under_copy_permutation=60,
    num_witness_columns=0,
    num_constant_columns=8,
    max_allowed_constraint_degree=7,
)

LOOKUP = LookupParameters(width=4, num_repetitions=8)


def build_blake_circuit(data: bytes):
    cs = ConstraintSystem(GEOM, 1 << 18, lookup_params=LOOKUP)
    inp = allocate_u8_input(cs, data)
    digest = blake2s(cs, inp)
    return cs, digest


def test_blake2s_parity_short():
    data = b"hello TPU blake2s"
    cs, digest = build_blake_circuit(data)
    assert blake2s_digest_bytes(cs, digest) == hashlib.blake2s(data).digest()


def test_blake2s_parity_two_blocks():
    data = bytes(range(100))
    cs, digest = build_blake_circuit(data)
    assert blake2s_digest_bytes(cs, digest) == hashlib.blake2s(data).digest()


def test_blake2s_satisfiable():
    data = b"graft blake"
    cs, _ = build_blake_circuit(data)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)
