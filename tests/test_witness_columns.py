"""End-to-end exercise of num_witness_columns > 0 (reference model:
ZeroCheckGate with use_witness_column_for_inversion, zero_check.rs:591).

Until now every circuit used num_witness_columns=0, leaving the prover's
W>0 branches dead; this covers witness commitment, the witness part of the
gate sweep, DEEP openings of witness columns, and verification.
"""

import numpy as np

from boojum_tpu.cs.types import CSGeometry
from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.gates import (
    FmaGate,
    PublicInputGate,
    ZeroCheckWitnessGate,
)
from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify
from boojum_tpu.prover.satisfiability import check_if_satisfied
from boojum_tpu.prover.proof import Proof
from boojum_tpu.field import gl

GEOM = CSGeometry(
    num_columns_under_copy_permutation=8,
    num_witness_columns=4,
    num_constant_columns=6,
    max_allowed_constraint_degree=4,
)

CONFIG = ProofConfig(
    fri_lde_factor=8,
    merkle_tree_cap_size=4,
    num_queries=8,
    pow_bits=0,
    fri_final_degree=4,
)


def build_circuit(steps=12):
    """Chain of is_zero checks over an FMA sequence: roughly half the
    is_zero inputs are 0 (hits both resolver branches)."""
    cs = ConstraintSystem(GEOM, 1 << 10)
    acc = cs.alloc_variable_with_value(3)
    flags_sum = cs.zero_var()
    for i in range(steps):
        x = cs.alloc_variable_with_value(i % 3)  # 0 every third step
        flag = ZeroCheckWitnessGate.is_zero(cs, x)
        acc = FmaGate.fma(cs, acc, acc, flag, 1, 1)
        flags_sum = FmaGate.fma(cs, flags_sum, cs.one_var(), flag, 1, 1)
    PublicInputGate.place(cs, flags_sum)
    return cs, flags_sum


def test_witness_column_values():
    cs, out = build_circuit(steps=6)
    # steps 0 and 3 have x == 0 -> two zero flags
    assert cs.get_value(out) == 2
    asm = cs.into_assembly()
    assert asm.wit_placement.shape[0] == 4
    assert (asm.wit_placement >= 0).any(), "witness columns must be used"
    assert check_if_satisfied(asm, verbose=True)


def test_witness_column_e2e_prove_verify():
    cs, out = build_circuit()
    expected = cs.get_value(out)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)
    setup = generate_setup(asm, CONFIG)
    proof = prove(asm, setup, CONFIG)
    assert proof.public_inputs == [expected]
    assert verify(setup.vk, proof, asm.gates), "witness-column proof must verify"


def test_witness_column_tamper_rejected():
    cs, _ = build_circuit(steps=6)
    asm = cs.into_assembly()
    setup = generate_setup(asm, CONFIG)
    proof = prove(asm, setup, CONFIG)
    assert verify(setup.vk, proof, asm.gates)
    # tamper a witness-column opening in a query leaf
    p2 = Proof.from_json(proof.to_json())
    q = p2.queries[0].witness
    q.leaf_values[asm.copy_placement.shape[0] + asm.num_lookup_cols] = (
        q.leaf_values[asm.copy_placement.shape[0] + asm.num_lookup_cols] + 1
    ) % gl.P
    assert not verify(setup.vk, p2, asm.gates)
    # tampered witness opening at z
    p3 = Proof.from_json(proof.to_json())
    idx = asm.copy_placement.shape[0]  # first witness poly opening
    v = list(p3.values_at_z[idx])
    v[0] = (v[0] + 1) % gl.P
    p3.values_at_z[idx] = tuple(v)
    assert not verify(setup.vk, p3, asm.gates)


def test_bad_witness_fails_satisfiability():
    cs, _ = build_circuit(steps=6)
    asm = cs.into_assembly()
    asm.wit_cols_values = asm.wit_cols_values.copy()
    # an aux cell of an x == 0 instance is legitimately unconstrained, so
    # bump EVERY used witness cell: the x != 0 instances' aux checks break
    used = asm.wit_placement >= 0
    assert used.any()
    asm.wit_cols_values[used] = (asm.wit_cols_values[used] + 1) % gl.P
    assert not check_if_satisfied(asm, verbose=False)
