"""Non-native field + curve gadget tests: parity vs python bigint / host EC
math + satisfiability (reference test model: non_native_field and curves
tests)."""

import random

from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.types import CSGeometry, LookupParameters
from boojum_tpu.gadgets.curves import SWProjectivePoint
from boojum_tpu.gadgets.non_native_field import (
    NNFParams,
    NonNativeField,
    SECP256K1_BASE,
)
from boojum_tpu.prover.satisfiability import check_if_satisfied

GEOM = CSGeometry(
    num_columns_under_copy_permutation=60,
    num_witness_columns=0,
    num_constant_columns=8,
    max_allowed_constraint_degree=7,
)

LOOKUP = LookupParameters(width=4, num_repetitions=8)

P = SECP256K1_BASE.modulus


def make_cs(size=1 << 15):
    return ConstraintSystem(GEOM, size, lookup_params=LOOKUP)


def test_nnf_ring_ops_parity():
    rng = random.Random(17)
    cs = make_cs()
    a, b = rng.randrange(P), rng.randrange(P)
    na = NonNativeField.allocate_checked(cs, a, SECP256K1_BASE)
    nb = NonNativeField.allocate_checked(cs, b, SECP256K1_BASE)
    assert na.add(cs, nb).get_value(cs) == (a + b) % P
    assert na.sub(cs, nb).get_value(cs) == (a - b) % P
    assert nb.sub(cs, na).get_value(cs) == (b - a) % P
    assert na.mul(cs, nb).get_value(cs) == (a * b) % P
    assert na.square(cs).get_value(cs) == (a * a) % P
    assert na.negated(cs).get_value(cs) == (-a) % P
    iv = na.inv(cs)
    assert iv.get_value(cs) == pow(a, -1, P)
    assert na.div(cs, nb).get_value(cs) == (a * pow(b, -1, P)) % P
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_nnf_predicates():
    cs = make_cs()
    a = 12345678901234567890
    na = NonNativeField.allocate_checked(cs, a, SECP256K1_BASE)
    nb = NonNativeField.allocate_checked(cs, a, SECP256K1_BASE)
    nc = NonNativeField.allocate_checked(cs, a + 1, SECP256K1_BASE)
    assert NonNativeField.equals(cs, na, nb).get_value(cs)
    assert not NonNativeField.equals(cs, na, nc).get_value(cs)
    assert NonNativeField.zero(cs, SECP256K1_BASE).is_zero(cs).get_value(cs)
    assert not na.is_zero(cs).get_value(cs)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_nnf_congruence_tamper_rejected():
    cs = make_cs()
    a, b = 3, 5
    na = NonNativeField.allocate_checked(cs, a, SECP256K1_BASE)
    nb = NonNativeField.allocate_checked(cs, b, SECP256K1_BASE)
    prod = na.mul(cs, nb)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm)
    # corrupt the first product-result limb in the trace
    place = prod.limbs[0]
    import numpy as np

    rows = np.argwhere(asm.copy_placement == place)
    assert len(rows) > 0
    col, row = rows[0]
    asm.copy_cols_values[col, row] = (
        int(asm.copy_cols_values[col, row]) + 1
    ) % (2**64 - 2**32 + 1)
    assert not check_if_satisfied(asm)


# -- curve tests -------------------------------------------------------------

GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


def _ec_add(p1, p2):
    """Affine secp256k1 addition (host reference)."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2 and (y1 + y2) % P == 0:
        return None
    if p1 == p2:
        lam = (3 * x1 * x1) * pow(2 * y1, -1, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, -1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def test_curve_double_add_parity():
    cs = make_cs(1 << 16)
    gx = NonNativeField.allocate_checked(cs, GX, SECP256K1_BASE)
    gy = NonNativeField.allocate_checked(cs, GY, SECP256K1_BASE)
    pt = SWProjectivePoint.from_xy_unchecked(cs, gx, gy, 7)
    pt.enforce_on_curve(cs)
    two_g = pt.double(cs)
    three_g = two_g.add_mixed(cs, gx, gy)
    (x2, y2), inf2 = two_g.convert_to_affine_or_default(cs, 0, 0)
    (x3, y3), inf3 = three_g.convert_to_affine_or_default(cs, 0, 0)
    e2 = _ec_add((GX, GY), (GX, GY))
    e3 = _ec_add(e2, (GX, GY))
    assert not inf2.get_value(cs) and not inf3.get_value(cs)
    assert (x2.get_value(cs), y2.get_value(cs)) == e2
    assert (x3.get_value(cs), y3.get_value(cs)) == e3
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_curve_identity_handling():
    cs = make_cs(1 << 16)
    zero_pt = SWProjectivePoint.zero(cs, SECP256K1_BASE, 7)
    gx = NonNativeField.allocate_checked(cs, GX, SECP256K1_BASE)
    gy = NonNativeField.allocate_checked(cs, GY, SECP256K1_BASE)
    g = zero_pt.add_mixed(cs, gx, gy)
    (x, y), inf = g.convert_to_affine_or_default(cs, 0, 0)
    assert not inf.get_value(cs)
    assert (x.get_value(cs), y.get_value(cs)) == (GX, GY)
    # G - G = identity
    g2 = SWProjectivePoint.from_xy_unchecked(cs, gx, gy, 7)
    diff = g2.sub_mixed(cs, gx, gy)
    _, inf_d = diff.convert_to_affine_or_default(cs, 0, 0)
    assert inf_d.get_value(cs)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)
