"""Quotient-rate decoupling: sweep at Q cosets, commit at fri_lde_factor.

Mirrors the reference's used_lde_degree (prover.rs:313) vs
subset_for_degree(fri_lde_factor) (setup.rs:1187) split — the Era main-VM
golden proof commits at LDE 2 while its quotient has 8 chunks. These tests
pin: Q derivation from constraint degrees, prove/verify at L < Q, proof
layout (2Q quotient leaf values), tamper rejection, and VK serde roundtrip.
"""

import numpy as np
import pytest

from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.types import CSGeometry, LookupParameters
from boojum_tpu.cs.gates import FmaGate, PublicInputGate
from boojum_tpu.prover import (
    ProofConfig,
    generate_setup,
    prove,
    prove_one_shot,
    verify,
    verify_circuit,
)


def _fma_circuit():
    cs = ConstraintSystem(CSGeometry(8, 0, 6, 4), 1 << 10)
    x = cs.alloc_variable_with_value(3)
    y = cs.alloc_variable_with_value(4)
    for _ in range(300):
        x, y = y, FmaGate.fma(cs, x, y, x, 1, 1)
    PublicInputGate.place(cs, y)
    return cs


def test_decoupled_commit_rate_below_quotient_degree():
    cfg = ProofConfig(fri_lde_factor=2, num_queries=20, fri_final_degree=8)
    asm, setup, proof = prove_one_shot(_fma_circuit(), cfg)
    # degree bound: max_allowed 4 + 1 -> next pow2 = 8
    assert setup.vk.quotient_degree == 8
    assert setup.vk.fri_lde_factor == 2
    assert len(proof.queries[0].quotient.leaf_values) == 2 * 8
    assert proof.config["quotient_degree"] == 8
    assert verify_circuit(setup.vk, proof, asm.gates)


def test_decoupled_tamper_rejected():
    cfg = ProofConfig(fri_lde_factor=2, num_queries=12, fri_final_degree=8)
    asm, setup, proof = prove_one_shot(_fma_circuit(), cfg)
    q = proof.queries[0].quotient
    q.leaf_values[0] = (q.leaf_values[0] + 1) % ((1 << 64) - (1 << 32) + 1)
    assert not verify_circuit(setup.vk, proof, asm.gates)


def test_explicit_quotient_degree_override():
    # force Q=16 > derived 8; still proves and verifies
    cfg = ProofConfig(
        fri_lde_factor=2,
        num_queries=12,
        fri_final_degree=8,
        quotient_degree=16,
    )
    asm, setup, proof = prove_one_shot(_fma_circuit(), cfg)
    assert setup.vk.quotient_degree == 16
    assert len(proof.queries[0].quotient.leaf_values) == 32
    assert verify_circuit(setup.vk, proof, asm.gates)


def test_vk_serde_roundtrip_quotient_degree():
    from boojum_tpu.serialization import vk_from_json, vk_to_json

    cfg = ProofConfig(fri_lde_factor=2, num_queries=8, fri_final_degree=8)
    cs = _fma_circuit()
    asm = cs.into_assembly()
    setup = generate_setup(asm, cfg)
    vk2 = vk_from_json(vk_to_json(setup.vk))
    assert vk2.quotient_degree == setup.vk.quotient_degree
    assert vk2.effective_quotient_degree() == 8


def test_decoupled_with_lookups():
    # the streamed per-coset sweep's lookup branches at L < Q (specialized
    # columns; the xor example circuit)
    from boojum_tpu.examples import build_xor_lookup_circuit

    cs, _, _ = build_xor_lookup_circuit(num_lookups=16)
    asm = cs.into_assembly()
    cfg = ProofConfig(fri_lde_factor=2, num_queries=16, fri_final_degree=8)
    setup = generate_setup(asm, cfg)
    assert setup.vk.quotient_degree > setup.vk.fri_lde_factor
    proof = prove(asm, setup, cfg)
    assert verify(setup.vk, proof, asm.gates)
    # lookup tamper: bump a multiplicity-ish stage-2 leaf -> reject
    q = proof.queries[0].stage2
    q.leaf_values[-1] = (q.leaf_values[-1] + 1) % ((1 << 64) - (1 << 32) + 1)
    assert not verify(setup.vk, proof, asm.gates)


def test_streamed_lde_proof_byte_identical(monkeypatch):
    """BOOJUM_TPU_STREAM_LDE=1 forces the streamed commit/DEEP/query path
    (load-bearing for the 2^20 result); its proof must be BYTE-identical to
    the materialized path's — block ordering, trailing-chunk sponge padding
    and the per-column query regeneration are all pinned by this."""
    cfg = ProofConfig(fri_lde_factor=2, num_queries=10, fri_final_degree=8)
    cs = _fma_circuit()
    asm = cs.into_assembly()
    setup = generate_setup(asm, cfg)
    baseline = prove(asm, setup, cfg)
    monkeypatch.setenv("BOOJUM_TPU_STREAM_LDE", "1")
    streamed = prove(asm, setup, cfg)
    assert streamed.to_json() == baseline.to_json()
    assert verify(setup.vk, streamed, asm.gates)
