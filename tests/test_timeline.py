"""Distributed-trace timeline stitching (ISSUE 17 tentpole).

Everything here is synthetic and stdlib-only: the report library is
loaded standalone (by file path, the same way scripts/prove_report.py
does) so these tests never import jax and run in milliseconds. Covered:

- two-host merge with INJECTED clock skew: barrier-derived offsets,
  collective span ordering after alignment (the skewed host's events
  land where they actually happened, the aligned barrier marks
  coincide), and the across-host straggler flagged per trace;
- the Perfetto (Chrome trace-event JSON) export validates and carries
  the queue-wait span, the stitched instants and the counter tracks;
- --check's trace rules fail closed: backdated negative starts pass
  only when flagged, a dump whose span path disagrees with its span_id
  is rejected, colliding span_ids fail the artifact;
- the prove_report.py CLI drives the whole path end to end.
"""

import importlib.util
import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_rl():
    path = os.path.join(REPO_ROOT, "boojum_tpu", "utils", "report.py")
    spec = importlib.util.spec_from_file_location("_tl_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


rl = _load_rl()

TID = "ab" * 16
SKEW_S = 7.0


def _span(name, start_s, wall_s, span_id, parent=None, trace=None, **extra):
    sp = {
        "name": name,
        "start_s": start_s,
        "wall_s": wall_s,
        "span_id": span_id,
        "children": [],
    }
    if parent:
        sp["parent_span_id"] = parent
    if trace:
        sp["trace_id"] = trace
    sp.update(extra)
    return sp


def _line(label, unix_ts, wall_s, spans, **extra):
    d = {
        "kind": rl.REPORT_KIND,
        "schema": rl.REPORT_SCHEMA,
        "label": label,
        "unix_ts": unix_ts,
        "wall_s": wall_s,
        "spans": spans,
        "metrics": {"counters": {}, "gauges": {}},
        "checkpoints": [],
        "trace_ctx": {"trace_id": TID},
    }
    d.update(extra)
    return d


def _result_line(pid, barrier_ts):
    return {
        "pid": pid,
        "process_count": 2,
        "clock_sync": {"barrier_unix_ts": barrier_ts},
    }


def _two_host_docs():
    """Two hosts proving one trace. host1's wall clock runs SKEW_S fast
    (its barrier stamp reads later), its spans carry raw timestamps on
    that fast clock, and its msm collective is a genuine straggler."""
    # host0: recorder closed at unix 1010 after a 10 s window -> t0 1000
    qw = _span("queue.wait", -0.5, 0.4, "11" * 8, parent="aa" * 8,
               trace=TID, backdated=True)
    prove0 = _span("prove", 0.0, 10.0, "22" * 8, trace=TID)
    prove0["children"].append(
        _span("msm", 1.0, 2.0, "33" * 8, parent="22" * 8)
    )
    host0 = [
        _result_line(0, 1000.0),
        _line("service:r-1", 1010.0, 10.0, [qw, prove0]),
    ]
    # host1: same work, stamps SKEW_S later on its fast clock; aligned,
    # its prove starts 2 s after host0's (1002), not 9 s (1009 raw)
    prove1 = _span("prove", 0.0, 10.0, "44" * 8, trace=TID)
    prove1["children"].append(
        _span("msm", 1.0, 8.0, "55" * 8, parent="44" * 8)
    )
    dump = {
        "kind": rl.BLACKBOX_KIND,
        "schema": rl.BLACKBOX_SCHEMAS[-1],
        "record": "dump",
        "reason": "stall",
        "unix_ts": 1012.0 + SKEW_S,
        "trace_id": TID,
        "span_id": "55" * 8,
        "span": "prove/msm",
    }
    host1 = [
        _result_line(1, 1000.0 + SKEW_S),
        _line("service:r-2", 1012.0 + SKEW_S, 10.0, [prove1]),
        dump,
    ]
    return [("host0", host0), ("host1", host1)]


def test_two_host_merge_aligns_skewed_clocks_and_flags_straggler():
    rec = _two_host_docs()
    merged = rl.timeline_merge(rec)
    assert merged["kind"] == rl.TIMELINE_KIND
    assert merged["clock"]["method"] == "barrier"
    assert merged["clock"]["max_skew_s"] == SKEW_S
    assert merged["offsets"] == {"host0": 0.0, "host1": SKEW_S}
    # the aligned barrier instants coincide by construction
    barrier_ts = {
        m["t_s"] for m in merged["marks"]
        if m["name"] == "clock_sync.barrier"
    }
    assert barrier_ts == {1000.0}
    (tr,) = merged["traces"]
    assert tr["trace_id"] == TID
    assert tr["hosts"] == ["host0", "host1"]
    evs = {(e["host"], e["name"]): e for e in tr["events"]
           if "wall_s" in e}
    # host0's backdated queue.wait sits BEFORE its recording window
    assert evs[("host0", "queue.wait")]["t_s"] == 999.5
    # collective ordering survives the skew: host1's prove started 2 s
    # after host0's on the shared clock, not 9 s as raw stamps claim
    assert evs[("host0", "prove")]["t_s"] == 1000.0
    assert evs[("host1", "prove")]["t_s"] == 1002.0
    # the slow msm on host1 (8 s vs 2 s median pair) is the straggler
    (st,) = tr["stragglers"]
    assert st["span"] == "msm" and st["host"] == "host1"
    assert evs[("host1", "msm")]["straggler"] is True
    assert "msm" in [s["span"] for s in merged["stragglers"]]
    # the blackbox dump joined the trace as an instant event
    instants = [e for e in tr["events"] if "wall_s" not in e]
    assert instants and instants[0]["name"] == "blackbox.stall"
    assert instants[0]["t_s"] == 1012.0  # skew removed
    # the swimlane names the straggler
    text = rl.render_timeline(merged)
    assert "straggler" in text and TID[:8] in text


def test_merge_without_barrier_stamps_stays_on_raw_clocks():
    (lbl, docs), _ = _two_host_docs()
    merged = rl.timeline_merge([(lbl, docs)])
    assert merged["clock"]["method"] == "none"
    assert merged["offsets"] == {}
    assert merged["n_traces"] == 1


def test_untraced_lines_bucket_last():
    host = [
        _line("old", 900.0, 1.0, [
            {"name": "legacy", "start_s": 0.0, "wall_s": 1.0,
             "children": []},
        ]),
        _line("new", 1010.0, 10.0, [
            _span("prove", 0.0, 10.0, "22" * 8, trace=TID),
        ]),
    ]
    host[0].pop("trace_ctx")
    merged = rl.timeline_merge([("host0", host)])
    assert [t["trace_id"] for t in merged["traces"]] == [TID, rl.UNTRACED]


def test_perfetto_export_validates_and_carries_the_story():
    docs = _two_host_docs()
    # a telemetry series rides host0's line as counter tracks
    docs[0][1][1]["telemetry"] = {
        "t0_unix_ts": 1000.5,
        "samples": [
            {"t_s": 0.0, "host_rss_bytes": 5.0},
            {"t_s": 1.0, "host_rss_bytes": 6.0},
        ],
    }
    doc = rl.perfetto_events(rl.timeline_merge(docs))
    assert rl.validate_perfetto(doc) == []
    evs = doc["traceEvents"]
    procs = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert procs == {"host0", "host1"}
    spans = {e["name"] for e in evs if e["ph"] == "X"}
    assert {"queue.wait", "prove", "msm"} <= spans
    (stall,) = [e for e in evs if e["name"] == "blackbox.stall"]
    assert stall["ph"] == "i" and stall["s"] == "t"
    counters = [e for e in evs if e["ph"] == "C"]
    assert len(counters) == 2
    assert min(e["ts"] for e in evs if e["ph"] != "M") == 0.0
    straggler_args = [
        e["args"].get("straggler") for e in evs
        if e["ph"] == "X" and e["name"] == "msm"
        and e["args"]["host"] == "host1"
    ]
    assert straggler_args == [True]


def test_validate_perfetto_rejects_garbage():
    assert rl.validate_perfetto({}) == ["traceEvents missing"]
    assert "traceEvents empty" in rl.validate_perfetto({"traceEvents": []})
    bad = {"traceEvents": [{"name": "x", "ph": "X", "pid": 1,
                            "ts": -1.0, "dur": 1.0}]}
    assert any("ts invalid" in p for p in rl.validate_perfetto(bad))
    bad = {"traceEvents": [{"name": "x", "ph": "Z", "pid": 1, "ts": 0.0}]}
    assert any("ph invalid" in p for p in rl.validate_perfetto(bad))


def test_backdated_negative_start_passes_only_when_flagged():
    flagged = _line("svc", 1010.0, 10.0, [
        _span("queue.wait", -0.5, 0.4, "11" * 8, trace=TID,
              backdated=True),
    ])
    assert rl.validate_report(flagged) == []
    unflagged = _line("svc", 1010.0, 10.0, [
        _span("queue.wait", -0.5, 0.4, "11" * 8, trace=TID),
    ])
    assert any(
        "start_s" in p for p in rl.validate_report(unflagged)
    )


def test_validate_report_rejects_malformed_trace_fields():
    bad_tid = _line("svc", 1.0, 1.0, [])
    bad_tid["trace_ctx"] = {"trace_id": "xyz"}
    assert any("trace_ctx" in p for p in rl.validate_report(bad_tid))
    dup = _line("svc", 1.0, 1.0, [
        _span("a", 0.0, 1.0, "11" * 8, trace=TID),
        _span("b", 0.0, 1.0, "11" * 8, trace=TID),
    ])
    assert any("span_id" in p for p in rl.validate_report(dup))


def _dump(span_path, span_id, spans):
    hb = {
        "kind": rl.BLACKBOX_KIND, "schema": 1, "record": "heartbeat",
        "seq": 1, "t_s": 1.0, "unix_ts": 1000.0, "progress": 3,
        "phase": "prove",
    }
    return {
        "kind": rl.BLACKBOX_KIND, "schema": 1, "record": "dump",
        "seq": 2, "t_s": 2.0, "unix_ts": 1001.0, "progress": 3,
        "phase": "prove", "reason": "stall", "stall_s": 5.0,
        "span": span_path, "span_id": span_id,
        "stacks": [{"thread": "MainThread", "stack": ["prove()"]}],
        "faulthandler": "...", "heartbeats": [hb], "spans": spans,
    }


def test_validate_blackbox_rejects_span_id_path_disagreement():
    tree = [_span("prove", 0.0, 1.0, "22" * 8, trace=TID)]
    tree[0]["children"].append(
        _span("msm", 0.1, 0.5, "33" * 8, parent="22" * 8)
    )
    ok = _dump("prove/msm", "33" * 8, tree)
    assert rl.validate_blackbox(ok) == []
    wrong_path = _dump("prove", "33" * 8, tree)
    assert any(
        "disagrees" in p for p in rl.validate_blackbox(wrong_path)
    )
    missing = _dump("prove/msm", "99" * 8, tree)
    assert any(
        "not present" in p for p in rl.validate_blackbox(missing)
    )


def _write_jsonl(path, docs):
    with open(path, "w") as f:
        for d in docs:
            f.write(json.dumps(d) + "\n")


def _run_cli(*argv):
    cli = os.path.join(REPO_ROOT, "scripts", "prove_report.py")
    env = {k: v for k, v in os.environ.items() if k != "PYTHONSTARTUP"}
    return subprocess.run(
        [sys.executable, cli, *argv],
        capture_output=True, text=True, timeout=120, env=env,
    )


def test_cli_timeline_merges_two_hosts_and_exports_perfetto(tmp_path):
    (l0, d0), (l1, d1) = _two_host_docs()
    p0 = tmp_path / "host0.jsonl"
    p1 = tmp_path / "host1.jsonl"
    _write_jsonl(p0, d0)
    _write_jsonl(p1, d1)
    out = tmp_path / "trace.json"
    res = _run_cli("--timeline", str(p0), str(p1), "--perfetto", str(out))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "straggler" in res.stdout
    with open(out) as f:
        doc = json.load(f)
    assert rl.validate_perfetto(doc) == []
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"queue.wait", "prove", "clock_sync.barrier"} <= names


def test_cli_check_fails_cross_line_span_id_collision(tmp_path):
    a = _line("a", 1.0, 1.0, [_span("s", 0.0, 1.0, "11" * 8, trace=TID)])
    b = _line("b", 2.0, 1.0, [_span("s", 0.0, 1.0, "11" * 8, trace=TID)])
    p = tmp_path / "collide.jsonl"
    _write_jsonl(p, [a, b])
    res = _run_cli("--check", str(p))
    assert res.returncode == 1
    assert "collides" in res.stdout
    # same two lines with distinct ids pass
    b["spans"][0]["span_id"] = "22" * 8
    _write_jsonl(p, [a, b])
    res = _run_cli("--check", str(p))
    assert res.returncode == 0, res.stdout + res.stderr


def test_cli_timeline_empty_artifact_exits_nonzero(tmp_path):
    p = tmp_path / "empty.jsonl"
    _write_jsonl(p, [])
    res = _run_cli("--timeline", str(p))
    assert res.returncode == 1
    assert "no events" in res.stdout
