"""Gadget-layer tests: Boolean / Num / UIntX semantics + satisfiability
(reference test model: per-gadget witness_hook parity + check_if_satisfied)."""

import numpy as np

from boojum_tpu.cs.types import CSGeometry, LookupParameters
from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.gadgets import Boolean, Num, UInt8, UInt32
from boojum_tpu.prover.satisfiability import check_if_satisfied
from boojum_tpu.field import gl

GEOM = CSGeometry(
    num_columns_under_copy_permutation=16,
    num_witness_columns=0,
    num_constant_columns=8,
    max_allowed_constraint_degree=4,
)

LOOKUP = LookupParameters(width=4, num_repetitions=2)


def mk_cs(lookups=False):
    return ConstraintSystem(
        GEOM, 1 << 13, lookup_params=LOOKUP if lookups else None
    )


def test_boolean_ops():
    cs = mk_cs()
    vals = [(a, b) for a in (0, 1) for b in (0, 1)]
    for av, bv in vals:
        a = Boolean.allocate(cs, bool(av))
        b = Boolean.allocate(cs, bool(bv))
        assert a.and_(cs, b).get_value(cs) == bool(av and bv)
        assert a.or_(cs, b).get_value(cs) == bool(av or bv)
        assert a.xor(cs, b).get_value(cs) == bool(av ^ bv)
        assert a.negate(cs).get_value(cs) == (not av)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_num_ops():
    cs = mk_cs()
    rng = np.random.default_rng(3)
    for _ in range(10):
        x = int(rng.integers(0, gl.P, dtype=np.uint64))
        y = int(rng.integers(0, gl.P, dtype=np.uint64))
        a, b = Num.allocate(cs, x), Num.allocate(cs, y)
        assert a.add(cs, b).get_value(cs) == (x + y) % gl.P
        assert a.sub(cs, b).get_value(cs) == (x - y) % gl.P
        assert a.mul(cs, b).get_value(cs) == (x * y) % gl.P
        assert a.equals(cs, b).get_value(cs) == (x == y)
        assert a.equals(cs, Num.allocate(cs, x)).get_value(cs)
    lc = Num.linear_combination(
        cs, [Num.allocate(cs, 5), Num.allocate(cs, 7), Num.allocate(cs, 11),
             Num.allocate(cs, 13)], [1, 2, 3, 4]
    )
    assert lc.get_value(cs) == 5 + 14 + 33 + 52
    bits = Num.allocate(cs, 0b1011).spread_into_bits(cs, 6)
    assert [b.get_value(cs) for b in bits] == [True, True, False, True, False, False]
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_uint_ops():
    cs = mk_cs(lookups=True)
    a = UInt32.allocate_checked(cs, 0xDEADBEEF)
    b = UInt32.allocate_checked(cs, 0x12345678)
    s, cout = a.add(cs, b)
    assert s.get_value(cs) == (0xDEADBEEF + 0x12345678) & 0xFFFFFFFF
    assert cout.get_value(cs) == ((0xDEADBEEF + 0x12345678) >> 32 == 1)
    d, bout = a.sub(cs, b)
    assert d.get_value(cs) == (0xDEADBEEF - 0x12345678) & 0xFFFFFFFF
    assert not bout.get_value(cs)
    lo, hi = a.fma(cs, b, UInt32.allocate_checked(cs, 7))
    full = 0xDEADBEEF * 0x12345678 + 7
    assert lo.get_value(cs) == full & 0xFFFFFFFF
    assert hi.get_value(cs) == full >> 32
    bs = [UInt8.allocate_checked(cs, v) for v in (0xDE, 0xAD, 0xBE, 0xEF)]
    w = UInt32.from_be_bytes(cs, bs)
    assert w.get_value(cs) == 0xDEADBEEF
    le = w.to_le_bytes(cs)
    assert [x.get_value(cs) for x in le] == [0xEF, 0xBE, 0xAD, 0xDE]
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_var_length_encodable():
    """CSVarLengthEncodable analog: deterministic field-recursive flattening
    to a variable list; pushing an encoded gadget through a commitment queue
    round-trips (reference cs_derive var_length_encodable)."""
    from dataclasses import dataclass

    from boojum_tpu.cs.types import CSGeometry
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.gadgets.derive import derive_gadget, encode_variables
    from boojum_tpu.gadgets.num import Num
    from boojum_tpu.gadgets.boolean import Boolean
    from boojum_tpu.gadgets.queue import CircuitQueue

    @derive_gadget
    @dataclass
    class Inner:
        a: Num
        flag: Boolean

    @derive_gadget
    @dataclass
    class Outer:
        p: Inner
        b: Num

    # queue hashing uses the 130-column flattened Poseidon2 gate
    geom = CSGeometry(
        num_columns_under_copy_permutation=130,
        num_witness_columns=0,
        num_constant_columns=8,
        max_allowed_constraint_degree=7,
    )
    cs = ConstraintSystem(geom, 1 << 12)
    o = Outer.allocate(cs, {"p": {"a": 7, "flag": 1}, "b": 9})
    enc = o.encode_vars()
    assert o.encoding_length() == 3 == len(enc)
    assert [cs.get_value(v) for v in enc] == [7, 1, 9]
    assert encode_variables([o, o]) == enc + enc

    q = CircuitQueue(cs, element_width=o.encoding_length())
    q.push(cs, enc)
    popped = q.pop_front(cs)
    q.enforce_consistency(cs)
    assert [cs.get_value(v) for v in popped] == [7, 1, 9]
    from boojum_tpu.prover.satisfiability import check_if_satisfied

    assert check_if_satisfied(cs.into_assembly())
