"""Poseidon2 / sponge / Merkle / transcript tests.

Mirrors the reference's hash test layering (state_generic_impl.rs tests,
merkle_tree.rs construct/verify, transcript determinism).
"""

import random

import jax.numpy as jnp
import numpy as np

from boojum_tpu.field import gl
from boojum_tpu.hashes import poseidon2_params as params
from boojum_tpu.hashes.poseidon2 import (
    Poseidon2SpongeHost,
    leaf_hash,
    node_hash,
    poseidon2_permutation,
    poseidon2_permutation_host,
)
from boojum_tpu.merkle import MerkleTreeWithCap, verify_proof_over_cap
from boojum_tpu.transcript import BitSource, Poseidon2Transcript

rng = random.Random(7)


def test_permutation_device_matches_host():
    batch = 16
    states = [[rng.randrange(gl.P) for _ in range(12)] for _ in range(batch)]
    dev = np.asarray(
        poseidon2_permutation(jnp.asarray(np.array(states, dtype=np.uint64)))
    )
    for i, s in enumerate(states):
        host = poseidon2_permutation_host(list(s))
        assert [int(x) for x in dev[i]] == host


def test_permutation_properties():
    # diffusion sanity: single-bit input change flips the whole state
    s0 = [0] * 12
    s1 = [1] + [0] * 11
    o0 = poseidon2_permutation_host(s0)
    o1 = poseidon2_permutation_host(s1)
    assert o0 != o1
    assert all(a != b for a, b in zip(o0, o1))
    # determinism
    assert poseidon2_permutation_host(s0) == o0


def test_mds_external_linearity():
    # permutation's external matrix is linear: check via the device
    # _external_mds through zero-sbox trick is private; test linearity of
    # full first matrix by comparing host block math against a naive matmul.
    from boojum_tpu.hashes.poseidon2 import _external_mds_s

    M4 = [[5, 7, 1, 3], [4, 6, 1, 1], [1, 3, 5, 7], [1, 1, 4, 6]]
    # full 12x12: circ(2*M4, M4, M4)
    full = [[0] * 12 for _ in range(12)]
    for bi in range(3):
        for bj in range(3):
            mult = 2 if bi == bj else 1
            for i in range(4):
                for j in range(4):
                    full[4 * bi + i][4 * bj + j] = M4[i][j] * mult
    vec = [rng.randrange(gl.P) for _ in range(12)]
    want = [
        sum(gl.mul(full[i][j], vec[j]) for j in range(12)) % gl.P for i in range(12)
    ]
    got = _external_mds_s(list(vec))
    assert got == want


def test_sponge_chunking_edges():
    # leaf widths around the rate boundary must agree device vs host
    for width in [1, 7, 8, 9, 16, 17, 24]:
        vals = [rng.randrange(gl.P) for _ in range(width)]
        dev = leaf_hash(jnp.asarray(np.array([vals], dtype=np.uint64)))[0]
        host = Poseidon2SpongeHost.hash_leaf(vals)
        assert [int(x) for x in np.asarray(dev)] == host


def test_node_hash_matches_host():
    l = [rng.randrange(gl.P) for _ in range(4)]
    r = [rng.randrange(gl.P) for _ in range(4)]
    dev = node_hash(
        jnp.asarray(np.array([l], dtype=np.uint64)),
        jnp.asarray(np.array([r], dtype=np.uint64)),
    )[0]
    assert [int(x) for x in np.asarray(dev)] == Poseidon2SpongeHost.hash_node(l, r)


def test_merkle_tree_with_cap_roundtrip():
    num_leaves, width, cap = 64, 5, 4
    leaves = np.random.randint(0, gl.P, size=(num_leaves, width), dtype=np.uint64)
    tree = MerkleTreeWithCap(jnp.asarray(leaves), cap)
    assert len(tree.get_cap()) == cap
    for idx in [0, 1, 31, 63, rng.randrange(num_leaves)]:
        proof = tree.get_proof(idx)
        assert len(proof) == 4  # log2(64/4)
        ok = verify_proof_over_cap(list(leaves[idx]), proof, tree.get_cap(), idx)
        assert ok
        # tampered leaf must fail
        bad = list(leaves[idx])
        bad[0] = (bad[0] + 1) % gl.P
        assert not verify_proof_over_cap(bad, proof, tree.get_cap(), idx)


def test_merkle_multi_elems_per_leaf():
    rows = np.random.randint(0, gl.P, size=(32, 3), dtype=np.uint64)
    tree = MerkleTreeWithCap(jnp.asarray(rows), cap_size=2, num_elems_per_leaf=2)
    assert tree.num_leaves == 16
    flat = rows.reshape(16, 6)
    proof = tree.get_proof(5)
    assert verify_proof_over_cap(list(flat[5]), proof, tree.get_cap(), 5)


def test_transcript_determinism_and_sensitivity():
    def run(els):
        t = Poseidon2Transcript()
        t.witness_field_elements(els)
        return t.get_multiple_challenges(20)

    a = run([1, 2, 3])
    assert a == run([1, 2, 3])
    assert a != run([1, 2, 4])
    # absorbing after drawing changes subsequent draws
    t = Poseidon2Transcript()
    t.witness_field_elements([5])
    c1 = t.get_challenge()
    t.witness_field_elements([9])
    c2 = t.get_challenge()
    t2 = Poseidon2Transcript()
    t2.witness_field_elements([5])
    assert t2.get_challenge() == c1
    assert t2.get_challenge() != c2  # squeeze vs absorb-then-squeeze differ


def test_bit_source():
    t = Poseidon2Transcript()
    t.witness_field_elements([42])
    bs = BitSource(max_needed_bits=20)
    idx = bs.get_index(t, 20)
    assert 0 <= idx < (1 << 20)
    # deterministic replay
    t2 = Poseidon2Transcript()
    t2.witness_field_elements([42])
    bs2 = BitSource(max_needed_bits=20)
    assert bs2.get_index(t2, 20) == idx
