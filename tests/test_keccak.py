"""Keccak-256 gadget tests: digest parity vs a host implementation + known
vectors + satisfiability (reference test model: gadgets/keccak256/mod.rs:136).
"""

from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.types import CSGeometry, LookupParameters
from boojum_tpu.gadgets import allocate_u8_input
from boojum_tpu.gadgets.keccak256 import keccak256, keccak256_digest_bytes
from boojum_tpu.prover.satisfiability import check_if_satisfied

GEOM = CSGeometry(
    num_columns_under_copy_permutation=60,
    num_witness_columns=0,
    num_constant_columns=8,
    max_allowed_constraint_degree=7,
)

LOOKUP = LookupParameters(width=4, num_repetitions=8)


# -- host reference (original Keccak, 0x01 padding — Ethereum keccak256) -----

from boojum_tpu.hashes.keccak_host import keccak256 as host_keccak256


def test_host_keccak_known_vectors():
    assert host_keccak256(b"").hex() == (
        "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
    )
    assert host_keccak256(b"abc").hex() == (
        "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"
    )


def build_keccak_circuit(data: bytes):
    cs = ConstraintSystem(GEOM, 1 << 18, lookup_params=LOOKUP)
    inp = allocate_u8_input(cs, data)
    digest = keccak256(cs, inp)
    return cs, digest


def test_keccak256_parity_short():
    data = b"hello TPU keccak"
    cs, digest = build_keccak_circuit(data)
    assert keccak256_digest_bytes(cs, digest) == host_keccak256(data)


def test_keccak256_parity_two_blocks():
    data = bytes(range(150))
    cs, digest = build_keccak_circuit(data)
    assert keccak256_digest_bytes(cs, digest) == host_keccak256(data)


def test_keccak256_satisfiable():
    data = b"graft"
    cs, digest = build_keccak_circuit(data)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)
