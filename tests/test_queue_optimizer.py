"""Sponge-optimizer tests (reference queue_optimizer/sponge_optimizer.rs:
batch the sponge rounds of mutually exclusive queue ops into shared
permutations; at-most-one-hot applies flags; conditional enforcement)."""

from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.types import CSGeometry
from boojum_tpu.gadgets.boolean import Boolean
from boojum_tpu.gadgets.queue import CircuitQueue
from boojum_tpu.gadgets.queue_optimizer import (
    SpongeOptimizer,
    variable_length_hash_with_optimizer,
)
from boojum_tpu.gadgets.poseidon2_rf import circuit_hash_leaf
from boojum_tpu.prover.satisfiability import check_if_satisfied

GEOM = CSGeometry(
    num_columns_under_copy_permutation=130,
    num_witness_columns=0,
    num_constant_columns=8,
    max_allowed_constraint_degree=7,
)


def _cs():
    return ConstraintSystem(GEOM, 1 << 12)


def test_optimizer_hash_matches_plain_sponge():
    """An executing optimizer hash commits to the same digest as the plain
    circuit sponge (the shared-permutation path is bit-compatible)."""
    cs = _cs()
    inputs = [cs.alloc_variable_with_value(v) for v in (3, 1, 4, 1, 5, 9, 2, 6)]
    execute = Boolean.allocated_constant(cs, True)
    opt = SpongeOptimizer(cs, capacity=2, num_ids=1)
    got = variable_length_hash_with_optimizer(cs, inputs, 0, execute, opt)
    opt.enforce()
    assert opt.is_fresh()
    want = circuit_hash_leaf(cs, inputs)
    assert [cs.get_value(v) for v in got] == [cs.get_value(v) for v in want]
    assert check_if_satisfied(cs.into_assembly(), verbose=True)


def test_mutually_exclusive_queue_pushes_share_permutations():
    """Two queues pushed in alternation under complementary flags: every
    step registers one request per stream, the optimizer lays down one
    permutation per slot, and both queues drain consistently."""
    cs = _cs()
    qa = CircuitQueue(cs, element_width=4)
    qb = CircuitQueue(cs, element_width=4)
    steps = 4
    opt = SpongeOptimizer(cs, capacity=steps, num_ids=2)
    for i in range(steps):
        to_a = Boolean.allocated_constant(cs, i % 2 == 0)
        to_b = to_a.negate(cs)
        el = [cs.alloc_variable_with_value(10 * i + j) for j in range(4)]
        qa.push_with_optimizer(cs, el, to_a, 0, opt)
        qb.push_with_optimizer(cs, el, to_b, 1, opt)
    opt.enforce()

    # drain: queue A saw steps 0,2; queue B saw 1,3
    got_a = [cs.get_value(v) for _ in range(2) for v in qa.pop_front(cs)]
    got_b = [cs.get_value(v) for _ in range(2) for v in qb.pop_front(cs)]
    assert got_a == [0, 1, 2, 3, 20, 21, 22, 23]
    assert got_b == [10, 11, 12, 13, 30, 31, 32, 33]
    qa.enforce_consistency(cs)
    qb.enforce_consistency(cs)
    assert check_if_satisfied(cs.into_assembly(), verbose=True)


def test_optimizer_rejects_two_hot_flags():
    """Two requests applying in the same slot violate the at-most-one-hot
    bitmask constraint (reference sponge_optimizer.rs enforce): the sum of
    flags is 2, which fails the boolean check."""
    cs = _cs()
    qa = CircuitQueue(cs, element_width=4)
    qb = CircuitQueue(cs, element_width=4)
    opt = SpongeOptimizer(cs, capacity=1, num_ids=2)
    both = Boolean(cs.alloc_variable_with_value(1))
    el = [cs.alloc_variable_with_value(j) for j in range(4)]
    qa.push_with_optimizer(cs, el, both, 0, opt)
    qb.push_with_optimizer(cs, el, both, 1, opt)
    opt.enforce()
    assert not check_if_satisfied(cs.into_assembly())


def test_conditional_pop_with_optimizer():
    """pop_with_optimizer under a false flag leaves the queue untouched;
    under a true flag it returns the pushed element."""
    cs = _cs()
    q = CircuitQueue(cs, element_width=2)
    el = [cs.alloc_variable_with_value(v) for v in (7, 8)]
    q.push(cs, el)
    opt = SpongeOptimizer(cs, capacity=2, num_ids=1)
    skip = Boolean.allocated_constant(cs, False)
    q.pop_with_optimizer(cs, skip, 0, opt)
    assert cs.get_value(q.length.var) == 1
    take = Boolean.allocated_constant(cs, True)
    got = q.pop_with_optimizer(cs, take, 0, opt)
    assert [cs.get_value(v) for v in got] == [7, 8]
    assert cs.get_value(q.length.var) == 0
    opt.enforce()
    q.enforce_consistency(cs)
    assert check_if_satisfied(cs.into_assembly(), verbose=True)


def test_legacy_poseidon_circuit_sponge_matches_host():
    """The legacy-Poseidon circuit sponge (gadgets/poseidon_rf.py) hashes
    bit-identically to the host PoseidonSpongeHost, including the
    partial-chunk zero-pad path, and the circuit is satisfiable."""
    from boojum_tpu.gadgets.poseidon_rf import (
        circuit_hash_leaf as legacy_hash_leaf,
        circuit_hash_node as legacy_hash_node,
    )
    from boojum_tpu.hashes.poseidon import PoseidonSpongeHost

    cs = _cs()
    vals = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]  # 10 elements: one full + one padded chunk
    ins = [cs.alloc_variable_with_value(v) for v in vals]
    got = legacy_hash_leaf(cs, ins)
    want = PoseidonSpongeHost.hash_leaf(vals)
    assert [cs.get_value(v) for v in got] == list(want)
    left = [cs.alloc_variable_with_value(v) for v in want]
    right = [cs.alloc_variable_with_value(v) for v in (7, 7, 7, 7)]
    got_n = legacy_hash_node(cs, left, right)
    want_n = PoseidonSpongeHost.hash_node(list(want), [7, 7, 7, 7])
    assert [cs.get_value(v) for v in got_n] == list(want_n)
    assert check_if_satisfied(cs.into_assembly(), verbose=True)
