"""Wide integer gadget tests (reference test model: u256/mod.rs tests —
random-value parity vs bigint + satisfiability)."""

import random

from boojum_tpu.cs.implementations import ConstraintSystem
from boojum_tpu.cs.types import CSGeometry, LookupParameters
from boojum_tpu.gadgets.boolean import Boolean
from boojum_tpu.gadgets.uint import UInt8
from boojum_tpu.gadgets.wide_int import UInt160, UInt256, UInt512
from boojum_tpu.prover.satisfiability import check_if_satisfied

GEOM = CSGeometry(
    num_columns_under_copy_permutation=60,
    num_witness_columns=0,
    num_constant_columns=8,
    max_allowed_constraint_degree=7,
)

LOOKUP = LookupParameters(width=4, num_repetitions=8)


def make_cs():
    return ConstraintSystem(GEOM, 1 << 14, lookup_params=LOOKUP)


def test_u256_add_sub_parity():
    rng = random.Random(3)
    cs = make_cs()
    M = 1 << 256
    for _ in range(3):
        a, b = rng.randrange(M), rng.randrange(M)
        ua = UInt256.allocate_checked(cs, a)
        ub = UInt256.allocate_checked(cs, b)
        s, c = ua.overflowing_add(cs, ub)
        assert s.get_value(cs) == (a + b) % M
        assert c.get_value(cs) == (a + b >= M)
        d, brw = ua.overflowing_sub(cs, ub)
        assert d.get_value(cs) == (a - b) % M
        assert brw.get_value(cs) == (a < b)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_u256_widening_mul_parity():
    rng = random.Random(5)
    cs = make_cs()
    M = 1 << 256
    a, b = rng.randrange(M), rng.randrange(M)
    ua = UInt256.allocate_checked(cs, a)
    ub = UInt256.allocate_checked(cs, b)
    p = ua.widening_mul(cs, ub)
    assert p.get_value(cs) == a * b
    assert p.to_low().get_value(cs) == (a * b) % M
    assert p.to_high().get_value(cs) == (a * b) >> 256
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_u256_predicates_and_bytes():
    rng = random.Random(9)
    cs = make_cs()
    a = rng.randrange(1 << 256)
    ua = UInt256.allocate_checked(cs, a)
    ub = UInt256.allocate_checked(cs, a)
    uc = UInt256.allocate_checked(cs, (a + 1) % (1 << 256))
    assert UInt256.equals(cs, ua, ub).get_value(cs)
    assert not UInt256.equals(cs, ua, uc).get_value(cs)
    assert UInt256.zero(cs).is_zero(cs).get_value(cs)
    assert not ua.is_zero(cs).get_value(cs) or a == 0
    # bytes roundtrip
    le = ua.to_le_bytes(cs)
    back = UInt256.from_le_bytes(cs, le)
    assert back.get_value(cs) == a
    assert bytes(v.get_value(cs) for v in le) == a.to_bytes(32, "little")
    # div2 / is_odd
    half, odd = ua.div2(cs)
    assert half.get_value(cs) == a >> 1
    assert odd.get_value(cs) == bool(a & 1)
    # mask/select
    t = Boolean.allocate(cs, True)
    f = Boolean.allocate(cs, False)
    assert ua.mask(cs, f).get_value(cs) == 0
    assert ua.mask(cs, t).get_value(cs) == a
    assert UInt256.select(cs, t, ua, uc).get_value(cs) == a
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)


def test_u160_u512_basic():
    rng = random.Random(13)
    cs = make_cs()
    a = rng.randrange(1 << 160)
    ua = UInt160.allocate_checked(cs, a)
    assert ua.get_value(cs) == a
    b = rng.randrange(1 << 512)
    ub = UInt512.allocate_checked(cs, b)
    s, c = ub.overflowing_add(cs, UInt512.allocated_constant(cs, b))
    assert s.get_value(cs) == (2 * b) % (1 << 512)
    asm = cs.into_assembly()
    assert check_if_satisfied(asm, verbose=True)
