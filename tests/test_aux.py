"""Aux subsystem tests: gate-program capture (offload seam), external-witness
repeated proving, profiling timers (reference test model: gpu_synthesizer +
witness.rs + observability, SURVEY.md §5)."""

import io
import sys

import numpy as np

from boojum_tpu.cs.gate_capture import capture_all, capture_gate_program
from boojum_tpu.cs.field_like import ScalarOps
from boojum_tpu.cs.gates import FmaGate, Poseidon2FlattenedGate, ReductionGate
from boojum_tpu.cs.gates.base import RowView, TermsCollector
from boojum_tpu.field import gl


def _row(vals, consts):
    return RowView(
        lambda i: vals[i], lambda i: 0,
        lambda i: consts[i] if i < len(consts) else 0,
    )


def test_capture_replay_matches_direct_eval():
    import random

    rng = random.Random(5)
    for gate, width, consts in (
        (FmaGate.instance(), 4, (3, 7)),
        (ReductionGate.instance(), 5, (1, 2, 3, 4)),
        (Poseidon2FlattenedGate.instance(), 130, ()),
    ):
        prog = capture_gate_program(gate)
        vals = [rng.randrange(gl.P) for _ in range(width)]
        row = _row(vals, consts)
        direct = TermsCollector()
        gate.evaluate(ScalarOps, row, direct)
        replayed = prog.evaluate(ScalarOps, row)
        assert replayed == direct.terms, gate.name
        stats = prog.stats()
        assert stats["terms"] == gate.num_terms


def test_capture_all_gate_set():
    progs = capture_all([FmaGate.instance(), ReductionGate.instance()])
    assert set(progs) == {"fma", "reduction4"}


def test_external_witness_reprove():
    from test_e2e import CONFIG, build_fibonacci_circuit
    from boojum_tpu.prover import generate_setup, prove, verify

    cs, _ = build_fibonacci_circuit(steps=5)
    asm = cs.into_assembly()
    setup = generate_setup(asm, CONFIG)
    wv = asm.witness_vec()
    asm2 = asm.with_external_witness(wv)
    proof = prove(asm2, setup, CONFIG)
    assert verify(setup.vk, proof, asm.gates)
    # identical witness -> identical proof
    assert proof.to_json() == prove(asm, setup, CONFIG).to_json()


def test_external_witness_reprove_changed_values():
    """A re-witnessed assembly must NOT inherit the prover's device-upload
    cache: proving asm (populating the cache) then proving a derived
    assembly with DIFFERENT witness values has to commit the new columns
    (regression: CSAssembly(**__dict__) shares the cache dict)."""
    from test_e2e import CONFIG, build_fibonacci_circuit
    from boojum_tpu.prover import generate_setup, prove, verify

    from test_e2e import GEOM
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.gates import (
        BooleanConstraintGate,
        FmaGate,
        PublicInputGate,
        SelectionGate,
    )

    def build(a0, b0):
        cs = ConstraintSystem(GEOM, 1 << 10)
        a = cs.alloc_variable_with_value(a0)
        b = cs.alloc_variable_with_value(b0)
        flag = cs.alloc_variable_with_value(1)
        BooleanConstraintGate.enforce(cs, flag)
        for _ in range(5):
            a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
        sel = SelectionGate.select(cs, flag, a, b)
        PublicInputGate.place(cs, sel)
        return cs

    asm = build(1, 2).into_assembly()
    setup = generate_setup(asm, CONFIG)
    p1 = prove(asm, setup, CONFIG)  # populates asm's device cache
    # identical circuit STRUCTURE, different witness values: the two
    # synthesis runs place variables identically, so the second circuit's
    # witness vector drops into the first assembly
    wv2 = build(5, 9).into_assembly().witness_vec()
    asm2 = asm.with_external_witness(wv2)
    p2 = prove(asm2, setup, CONFIG)
    assert verify(setup.vk, p2, asm.gates)
    assert p2.public_inputs != p1.public_inputs
    assert p2.witness_cap != p1.witness_cap


def test_stage_timers_emit():
    from boojum_tpu.utils import profiling

    profiling.set_profiling(True)
    try:
        err = io.StringIO()
        old = sys.stderr
        sys.stderr = err
        try:
            with profiling.stage_timer("unit_test_stage"):
                pass
        finally:
            sys.stderr = old
        assert "unit_test_stage" in err.getvalue()
    finally:
        profiling.set_profiling(None)


def test_derive_gadget():
    from dataclasses import dataclass

    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.types import CSGeometry
    from boojum_tpu.gadgets.boolean import Boolean
    from boojum_tpu.gadgets.derive import derive_gadget
    from boojum_tpu.gadgets.num import Num

    @derive_gadget
    @dataclass
    class Point:
        x: Num
        y: Num

    @derive_gadget
    @dataclass
    class Flagged:
        p: Point
        ok: Boolean

    cs = ConstraintSystem(CSGeometry(16, 0, 6, 4), 256)
    a = Flagged.allocate(cs, {"p": {"x": 3, "y": 4}, "ok": True})
    b = Flagged.allocate(cs, {"p": {"x": 30, "y": 40}, "ok": False})
    flag = Boolean.allocate(cs, True)
    sel = Flagged.select(cs, flag, a, b)
    hook = Flagged.witness_hook(cs, sel)
    assert hook() == {"p": {"x": 3, "y": 4}, "ok": True}
    flag2 = Boolean.allocate(cs, False)
    sel2 = Flagged.select(cs, flag2, a, b)
    assert Flagged.witness_hook(cs, sel2)() == {
        "p": {"x": 30, "y": 40}, "ok": False,
    }
    from boojum_tpu.prover.satisfiability import check_if_satisfied

    assert check_if_satisfied(cs.into_assembly())


def test_scan_playback_matches_direct_trace():
    """pack_for_scan + scan_evaluate must be bit-identical to tracing the
    gate evaluator directly over arrays — this is what lets the prover
    sweep permutation-sized gates with constant graph size."""
    import numpy as np
    import jax.numpy as jnp

    from boojum_tpu.cs.gate_capture import (
        capture_gate_program,
        pack_for_scan,
        scan_evaluate,
    )
    from boojum_tpu.cs.field_like import ArrayOps
    from boojum_tpu.cs.gates import FmaGate, Poseidon2FlattenedGate
    from boojum_tpu.cs.gates.base import RowView, TermsCollector
    from boojum_tpu.field import gl

    rng = np.random.default_rng(99)
    n = 128
    for gate, width, consts in (
        (FmaGate.instance(), 4, (5, 11)),
        (Poseidon2FlattenedGate.instance(), 130, ()),
    ):
        cols = jnp.asarray(
            rng.integers(0, gl.P, size=(width, n), dtype=np.uint64)
        )
        cvals = [jnp.full((n,), np.uint64(c)) for c in consts]
        row = RowView(
            lambda i, _c=cols: _c[i],
            lambda i: None,
            lambda i, _k=cvals: _k[i],
        )
        direct = TermsCollector()
        gate.evaluate(ArrayOps, row, direct)
        packed = pack_for_scan(capture_gate_program(gate))
        scanned = scan_evaluate(packed, row)
        assert len(scanned) == len(direct.terms), gate.name
        for s, d in zip(scanned, direct.terms):
            assert np.array_equal(np.asarray(s), np.asarray(d)), gate.name
