"""Multi-chip sharding tests on the virtual 8-device CPU mesh (conftest sets
XLA_FLAGS=--xla_force_host_platform_device_count=8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from boojum_tpu.field import gl
from boojum_tpu.parallel.sharding import (
    _prove_fragment,
    col_sharding,
    make_mesh,
    sharded_prove_fragment,
)
from boojum_tpu.prover.setup import non_residues_for_copy_permutation


def _inputs(C=8, n=64, seed=1):
    rng = np.random.default_rng(seed)
    copy_vals = rng.integers(0, gl.P, size=(C, n), dtype=np.uint64)
    sigma_vals = rng.integers(0, gl.P, size=(C, n), dtype=np.uint64)
    ks = np.array(non_residues_for_copy_permutation(C), dtype=np.uint64)
    beta = np.array([3, 5], dtype=np.uint64)
    gamma = np.array([7, 11], dtype=np.uint64)
    return copy_vals, sigma_vals, ks, beta, gamma


def test_sharded_matches_single_device():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    copy_vals, sigma_vals, ks, beta, gamma = _inputs()
    # single device reference (phased runner over a 1-device mesh)
    mesh1 = make_mesh(jax.devices()[:1])
    fn1 = sharded_prove_fragment(mesh1, lde_factor=2, cap_size=4)
    cap1, z1 = fn1(
        jnp.asarray(copy_vals), jnp.asarray(sigma_vals), jnp.asarray(ks),
        jnp.asarray(beta), jnp.asarray(gamma),
    )
    # 8-device 2D mesh
    mesh = make_mesh(jax.devices()[:8])
    assert mesh.shape["col"] * mesh.shape["row"] == 8
    fn = sharded_prove_fragment(mesh, lde_factor=2, cap_size=4)
    copy_dev = jax.device_put(jnp.asarray(copy_vals), col_sharding(mesh))
    sigma_dev = jax.device_put(jnp.asarray(sigma_vals), col_sharding(mesh))
    cap8, z8 = fn(copy_dev, sigma_dev, jnp.asarray(ks), jnp.asarray(beta),
                  jnp.asarray(gamma))
    np.testing.assert_array_equal(np.asarray(cap1), np.asarray(cap8))
    np.testing.assert_array_equal(np.asarray(z1[0]), np.asarray(z8[0]))
    np.testing.assert_array_equal(np.asarray(z1[1]), np.asarray(z8[1]))
    # z(w^0) = 1
    assert int(np.asarray(z8[0])[0]) == 1
    assert int(np.asarray(z8[1])[0]) == 0
    # parity with the real prover's stage-2 computation (guards the sharded
    # fragment against divergence from stages.py)
    from boojum_tpu.prover.stages import compute_copy_permutation_stage2

    z_ref, _, _ = compute_copy_permutation_stage2(
        jnp.asarray(copy_vals), jnp.asarray(sigma_vals),
        [int(k) for k in ks], (3, 5), (7, 11), max_degree=copy_vals.shape[0],
    )
    np.testing.assert_array_equal(np.asarray(z_ref[0]), np.asarray(z8[0]))
    np.testing.assert_array_equal(np.asarray(z_ref[1]), np.asarray(z8[1]))


def test_full_prove_sharded_byte_identical():
    """A full prove() over the 8-virtual-device mesh must produce the SAME
    proof bytes as single-device: every field op is exact integer math with
    a fixed reduction structure, so sharding may only change placement,
    never values. Uses a lookup circuit so rounds 2/3/5 cover the lookup
    paths too."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify
    from tests.test_lookup import build_circuit

    cfg = ProofConfig(
        fri_lde_factor=8,
        merkle_tree_cap_size=4,
        num_queries=4,
        pow_bits=0,
        fri_final_degree=4,
    )
    cs, _, _ = build_circuit(num_lookups=8)
    asm = cs.into_assembly()
    setup = generate_setup(asm, cfg)
    proof1 = prove(asm, setup, cfg)
    mesh = make_mesh(jax.devices()[:8])
    proof8 = prove(asm, setup, cfg, mesh=mesh)
    assert proof8.to_json() == proof1.to_json()
    assert verify(setup.vk, proof8, asm.gates)


def test_graft_entry_dryrun():
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "__graft_entry__.py")
    spec = importlib.util.spec_from_file_location("graft_entry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    # the driver compile-checks entry(); mirror that: lower + compile only
    # (running the fused single-module form is an XLA:CPU miscompile risk —
    # the phased path below is the executable one)
    jax.jit(fn).lower(*args).compile()
    mod.dryrun_multichip(min(8, len(jax.devices())))
