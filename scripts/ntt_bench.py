"""Quick NTT microbench on the ambient JAX backend (TPU via axon, or CPU).

Usage: python scripts/ntt_bench.py [log_n] [cols] [reps]
Prints XLA vs MXU throughput for fwd+inv pairs.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from boojum_tpu.field import gl
from boojum_tpu.ntt import ntt as ntt_mod
from boojum_tpu.ntt import mxu_ntt

log_n = int(sys.argv[1]) if len(sys.argv) > 1 else 16
cols = int(sys.argv[2]) if len(sys.argv) > 2 else 64
reps = int(sys.argv[3]) if len(sys.argv) > 3 else 4

rng = np.random.default_rng(0)
a = jnp.asarray(rng.integers(0, gl.P, size=(cols, 1 << log_n), dtype=np.uint64))
n_elems = cols * (1 << log_n)


def run(tag, fwd, inv):
    x = fwd(a)
    x = inv(x)
    jax.block_until_ready(x)
    t0 = time.perf_counter()
    x = a
    for _ in range(reps):
        x = inv(fwd(x))
    jax.block_until_ready(x)
    dt = time.perf_counter() - t0
    eps = 2 * reps * n_elems / dt
    print(f"{tag}: {dt/reps*1e3:.2f} ms/pair-rep, {eps:.3e} elems/s")
    return x, eps


want, eps_xla = run(
    "xla",
    lambda v: ntt_mod.fft_natural_to_bitreversed_xla(v),
    lambda v: ntt_mod.ifft_bitreversed_to_natural_xla(v),
)
got, eps_mxu = run(
    "mxu",
    lambda v: mxu_ntt.fft_natural_to_bitreversed(v),
    lambda v: mxu_ntt.ifft_bitreversed_to_natural(v),
)
print("match:", bool(jnp.array_equal(want, got)), "speedup:", round(eps_mxu / eps_xla, 2))
