"""Isolate MXU-NTT kernel cost components on the real TPU.

Variants (all grid=(B,), (256,256) tiles, B=64):
  dots:     64 bf16 dots only, i32-summed into one plane
  diag:     64 dots + 15-diagonal i32 accumulation (no fold)
  pass1:    limb extract + dots + diagonals + fold15  (one GL matmul)
  passes:   pass1 + twiddle mul + pass2 (the full fwd kernel)
"""

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from boojum_tpu.field import gl, limbs
from boojum_tpu.ntt import mxu_ntt as M
from boojum_tpu.utils.pallas_util import imap32

log_n = 16
ctx = M.get_mxu_ctx(log_n)
R, C = ctx.R, ctx.C
B = 64

rng = np.random.default_rng(0)
a = rng.integers(0, gl.P, size=(B, 1 << log_n), dtype=np.uint64)
planes = limbs.split(jnp.asarray(a.reshape(B, R, C)))


def _dots_kernel(mode, dr, dct, tlo, thi, xl, xh, ol, oh):
    x = (xl[0], xh[0])
    if mode == "dots":
        pl_ = M._digit_planes(x)
        acc = None
        for u in range(8):
            for v in range(8):
                p = jnp.dot(dr[u], pl_[v], preferred_element_type=jnp.int32)
                acc = p if acc is None else acc + p
        ol[0] = acc.astype(jnp.uint32)
        oh[0] = acc.astype(jnp.uint32)
    elif mode == "diag":
        pl_ = M._digit_planes(x)
        Q = [None] * 15
        for u in range(8):
            for v in range(8):
                p = jnp.dot(dr[u], pl_[v], preferred_element_type=jnp.int32)
                k = u + v
                Q[k] = p if Q[k] is None else Q[k] + p
        acc = Q[0]
        for k in range(1, 15):
            acc = acc + Q[k]
        ol[0] = acc.astype(jnp.uint32)
        oh[0] = acc.astype(jnp.uint32)
    elif mode == "pass1":
        y = M._gl_matmul(x, dr, "left")
        ol[0] = y[0]
        oh[0] = y[1]
    elif mode == "passes":
        y = M._gl_matmul(x, dr, "left")
        y = limbs.mul(y, (tlo[:], thi[:]))
        z = M._gl_matmul(y, dct, "right")
        ol[0] = z[0]
        oh[0] = z[1]
    elif mode == "fold":
        # extraction + fold cost without matmuls: fake diagonals from digits
        pl_ = M._digit_planes(x)
        Q = [pl_[k % 8].astype(jnp.int32) * 7 for k in range(15)]
        y = M._fold15_signed(Q)
        ol[0] = y[0]
        oh[0] = y[1]
    elif mode == "twiddle":
        y = limbs.mul(x, (tlo[:], thi[:]))
        ol[0] = y[0]
        oh[0] = y[1]


def make(mode):
    spec = M._data_spec(R, C)
    out_shape = jax.ShapeDtypeStruct((B, R, C), jnp.uint32)

    @jax.jit
    def run(lo, hi):
        return pl.pallas_call(
            partial(_dots_kernel, mode),
            grid=(B,),
            out_shape=[out_shape, out_shape],
            in_specs=[
                M._const_spec((8, R, R)),
                M._const_spec((8, C, C)),
                M._const_spec((R, C)),
                M._const_spec((R, C)),
                spec,
                spec,
            ],
            out_specs=[spec, spec],
            compiler_params=M._COMPILER_PARAMS,
        )(ctx.dr, ctx.dct, *ctx.tw, lo, hi)

    return run


for mode in ("twiddle", "fold", "dots", "diag", "pass1", "passes"):
    f = make(mode)
    out = f(*planes)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    reps = 8
    for _ in range(reps):
        out = f(*planes)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{mode:8s}: {dt*1e3:8.2f} ms  ({dt/B*1e6:7.1f} us/col)")
