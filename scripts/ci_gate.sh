#!/usr/bin/env bash
# ci_gate.sh — the one-command CI gate (ISSUE 15 satellite).
#
# Runs, in order:
#   1. the tier-1 pytest invocation (ROADMAP.md — CPU backend, fast
#      markers only), and
#   2. the perf-trend regression gate over the checked-in BENCH_*.json
#      and MULTICHIP_r*.json round history (scripts/prove_report.py
#      --trend --gate: last point of every stage/metric series vs the
#      median of its predecessors, 20% + 50 ms noise floor).
#
# With --multihost, a third leg runs the two-process jax.distributed
# parity tests (subprocess pairs over a loopback coordinator — proof
# bytes and Fiat-Shamir checkpoints must be bit-identical gspmd vs
# multi-host shard_map). Slow: real CPU proves per process; not part
# of the default invocation.
#
# Exits nonzero when any requested leg fails. Knobs:
#   CI_GATE_TIMEOUT_S     tier-1 budget in seconds (default 870, as in
#                         ROADMAP.md; the -k kill grace stays 10 s)
#   CI_GATE_THRESHOLD     relative regression threshold (default 0.2)
#   CI_GATE_MH_TIMEOUT_S  --multihost leg budget in seconds (default 3600)
set -u -o pipefail

root="$(cd "$(dirname "$0")/.." && pwd)"
cd "$root"

timeout_s="${CI_GATE_TIMEOUT_S:-870}"
threshold="${CI_GATE_THRESHOLD:-0.2}"
mh_timeout_s="${CI_GATE_MH_TIMEOUT_S:-3600}"
multihost=0
for arg in "$@"; do
    case "$arg" in
        --multihost) multihost=1 ;;
        *)
            echo "ci_gate: unknown argument $arg (supported: --multihost)" >&2
            exit 2
            ;;
    esac
done
rc=0

echo "== ci_gate: tier-1 tests (budget ${timeout_s}s) =="
timeout -k 10 "$timeout_s" env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly
t1_rc=$?
if [ "$t1_rc" -ne 0 ]; then
    echo "ci_gate: tier-1 tests FAILED (rc=$t1_rc)"
    rc=1
else
    echo "ci_gate: tier-1 tests ok"
fi

echo "== ci_gate: perf trend gate =="
# round history: BENCH wrappers + MULTICHIP wrappers (the trend loader
# orders both by round number and groups by machine identity)
history=()
for f in BENCH_r*.json MULTICHIP_r*.json; do
    [ -e "$f" ] && history+=("$f")
done
if [ "${#history[@]}" -eq 0 ]; then
    echo "ci_gate: no BENCH_*/MULTICHIP_* history checked in; skipping gate"
else
    python scripts/prove_report.py --trend "${history[@]}" \
        --gate --gate-threshold "$threshold"
    gate_rc=$?
    # rc=2 = no usable trend points (e.g. every wrapper predates the
    # metric line) — nothing to gate is not a regression
    if [ "$gate_rc" -eq 1 ]; then
        echo "ci_gate: perf trend gate FAILED"
        rc=1
    elif [ "$gate_rc" -eq 2 ]; then
        echo "ci_gate: no usable trend points; gate skipped"
    else
        echo "ci_gate: perf trend gate ok"
    fi
fi

if [ "$multihost" -eq 1 ]; then
    echo "== ci_gate: multihost parity leg (budget ${mh_timeout_s}s) =="
    # -m multihost selects the jax.distributed subprocess-pair tests
    # (registered in conftest.py); BOOJUM_TPU_TWO_PROC_TESTS lifts
    # their default skip
    timeout -k 10 "$mh_timeout_s" env JAX_PLATFORMS=cpu \
        BOOJUM_TPU_TWO_PROC_TESTS=1 \
        python -m pytest tests/test_multihost.py -q -m multihost \
        --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly
    mh_rc=$?
    if [ "$mh_rc" -ne 0 ]; then
        echo "ci_gate: multihost parity leg FAILED (rc=$mh_rc)"
        rc=1
    else
        echo "ci_gate: multihost parity leg ok"
    fi
fi

exit "$rc"
