#!/usr/bin/env bash
# ci_gate.sh — the one-command CI gate (ISSUE 15 satellite).
#
# Runs, in order:
#   1. the tier-1 pytest invocation (ROADMAP.md — CPU backend, fast
#      markers only), and
#   2. the perf-trend regression gate over the checked-in BENCH_*.json
#      and MULTICHIP_r*.json round history (scripts/prove_report.py
#      --trend --gate: last point of every stage/metric series vs the
#      median of its predecessors, 20% + 50 ms noise floor).
#
# Exits nonzero when either fails. Knobs:
#   CI_GATE_TIMEOUT_S   tier-1 budget in seconds (default 870, as in
#                       ROADMAP.md; the -k kill grace stays 10 s)
#   CI_GATE_THRESHOLD   relative regression threshold (default 0.2)
set -u -o pipefail

root="$(cd "$(dirname "$0")/.." && pwd)"
cd "$root"

timeout_s="${CI_GATE_TIMEOUT_S:-870}"
threshold="${CI_GATE_THRESHOLD:-0.2}"
rc=0

echo "== ci_gate: tier-1 tests (budget ${timeout_s}s) =="
timeout -k 10 "$timeout_s" env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly
t1_rc=$?
if [ "$t1_rc" -ne 0 ]; then
    echo "ci_gate: tier-1 tests FAILED (rc=$t1_rc)"
    rc=1
else
    echo "ci_gate: tier-1 tests ok"
fi

echo "== ci_gate: perf trend gate =="
# round history: BENCH wrappers + MULTICHIP wrappers (the trend loader
# orders both by round number and groups by machine identity)
history=()
for f in BENCH_r*.json MULTICHIP_r*.json; do
    [ -e "$f" ] && history+=("$f")
done
if [ "${#history[@]}" -eq 0 ]; then
    echo "ci_gate: no BENCH_*/MULTICHIP_* history checked in; skipping gate"
else
    python scripts/prove_report.py --trend "${history[@]}" \
        --gate --gate-threshold "$threshold"
    gate_rc=$?
    # rc=2 = no usable trend points (e.g. every wrapper predates the
    # metric line) — nothing to gate is not a regression
    if [ "$gate_rc" -eq 1 ]; then
        echo "ci_gate: perf trend gate FAILED"
        rc=1
    elif [ "$gate_rc" -eq 2 ]; then
        echo "ci_gate: no usable trend points; gate skipped"
    else
        echo "ci_gate: perf trend gate ok"
    fi
fi

exit "$rc"
