#!/usr/bin/env bash
# ci_gate.sh — the one-command CI gate (ISSUE 15 satellite).
#
# Runs, in order:
#   1. the tier-1 pytest invocation (ROADMAP.md — CPU backend, fast
#      markers only), and
#   2. the perf-trend regression gate over the checked-in BENCH_*.json
#      and MULTICHIP_r*.json round history (scripts/prove_report.py
#      --trend --gate: last point of every stage/metric series vs the
#      median of its predecessors, 20% + 50 ms noise floor).
#
# With --multihost, a third leg runs the two-process jax.distributed
# parity tests (subprocess pairs over a loopback coordinator — proof
# bytes and Fiat-Shamir checkpoints must be bit-identical gspmd vs
# multi-host shard_map). Slow: real CPU proves per process; not part
# of the default invocation.
#
# With --timeline, a smoke leg drives the distributed-tracing export
# (ISSUE 17): the gateway trace-propagation test produces a traced
# artifact, prove_report.py --check gates it, --timeline --perfetto
# exports Chrome trace-event JSON, and the leg fails when the JSON is
# invalid or the queue-wait span went missing.
#
# With --field, a smoke leg runs the BabyBear backend suite (ISSUE 19)
# plus the FULL-prover babybear parity suite (ISSUE 20): the 2^10
# mini-STARK e2e under BOOJUM_TPU_FIELD=babybear, and the real
# PLONKish prove() at 2^10 on the fma / xor4-lookup / poseidon-rf
# circuits — device vs numpy proof bytes and checkpoint streams
# bit-identical, zero limb conversions, quotient identity at z, the
# half-HBM cost sheet, sha256-over-babybear rejected at synthesis.
#
# Exits nonzero when any requested leg fails. Knobs:
#   CI_GATE_TIMEOUT_S     tier-1 budget in seconds (default 870, as in
#                         ROADMAP.md; the -k kill grace stays 10 s)
#   CI_GATE_THRESHOLD     relative regression threshold (default 0.2)
#   CI_GATE_MH_TIMEOUT_S  --multihost leg budget in seconds (default 3600)
#   CI_GATE_TL_TIMEOUT_S  --timeline leg budget in seconds (default 300)
#   CI_GATE_FD_TIMEOUT_S  --field leg budget in seconds (default 870)
set -u -o pipefail

root="$(cd "$(dirname "$0")/.." && pwd)"
cd "$root"

timeout_s="${CI_GATE_TIMEOUT_S:-870}"
threshold="${CI_GATE_THRESHOLD:-0.2}"
mh_timeout_s="${CI_GATE_MH_TIMEOUT_S:-3600}"
tl_timeout_s="${CI_GATE_TL_TIMEOUT_S:-300}"
fd_timeout_s="${CI_GATE_FD_TIMEOUT_S:-870}"
multihost=0
timeline=0
fieldleg=0
for arg in "$@"; do
    case "$arg" in
        --multihost) multihost=1 ;;
        --timeline) timeline=1 ;;
        --field) fieldleg=1 ;;
        *)
            echo "ci_gate: unknown argument $arg" \
                 "(supported: --multihost --timeline --field)" >&2
            exit 2
            ;;
    esac
done
rc=0

echo "== ci_gate: tier-1 tests (budget ${timeout_s}s) =="
timeout -k 10 "$timeout_s" env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly
t1_rc=$?
if [ "$t1_rc" -ne 0 ]; then
    echo "ci_gate: tier-1 tests FAILED (rc=$t1_rc)"
    rc=1
else
    echo "ci_gate: tier-1 tests ok"
fi

echo "== ci_gate: perf trend gate =="
# round history: BENCH wrappers + MULTICHIP wrappers (the trend loader
# orders both by round number and groups by machine identity)
history=()
for f in BENCH_r*.json MULTICHIP_r*.json; do
    [ -e "$f" ] && history+=("$f")
done
if [ "${#history[@]}" -eq 0 ]; then
    echo "ci_gate: no BENCH_*/MULTICHIP_* history checked in; skipping gate"
else
    python scripts/prove_report.py --trend "${history[@]}" \
        --gate --gate-threshold "$threshold"
    gate_rc=$?
    # rc=2 = no usable trend points (e.g. every wrapper predates the
    # metric line) — nothing to gate is not a regression
    if [ "$gate_rc" -eq 1 ]; then
        echo "ci_gate: perf trend gate FAILED"
        rc=1
    elif [ "$gate_rc" -eq 2 ]; then
        echo "ci_gate: no usable trend points; gate skipped"
    else
        echo "ci_gate: perf trend gate ok"
    fi
fi

if [ "$timeline" -eq 1 ]; then
    echo "== ci_gate: timeline export leg (budget ${tl_timeout_s}s) =="
    tl_tmp="$(mktemp -d)"
    # the trace-propagation test leaves its gateway artifact under the
    # pytest basetemp; the CLI then stitches + exports it
    timeout -k 10 "$tl_timeout_s" env JAX_PLATFORMS=cpu \
        python -m pytest tests/test_gateway.py -q \
        -k trace_propagation --basetemp "$tl_tmp/pytest" \
        -p no:cacheprovider -p no:xdist -p no:randomly
    tl_rc=$?
    if [ "$tl_rc" -ne 0 ]; then
        echo "ci_gate: timeline leg: trace-propagation test FAILED (rc=$tl_rc)"
        rc=1
    else
        artifact="$(find "$tl_tmp/pytest" -name 'gw.jsonl' | head -n 1)"
        if [ -z "$artifact" ]; then
            echo "ci_gate: timeline leg: no gateway artifact produced"
            rc=1
        else
            python scripts/prove_report.py --check "$artifact" \
                && python scripts/prove_report.py --timeline "$artifact" \
                       --perfetto "$tl_tmp/perfetto.json" \
                && python - "$tl_tmp/perfetto.json" <<'PYEOF'
import json, sys
with open(sys.argv[1]) as f:
    doc = json.load(f)
evs = doc.get("traceEvents")
assert isinstance(evs, list) and evs, "traceEvents missing/empty"
names = {e.get("name") for e in evs}
assert "queue.wait" in names, "queue.wait span missing from export"
print(f"ci_gate: perfetto export ok ({len(evs)} events)")
PYEOF
            if [ $? -ne 0 ]; then
                echo "ci_gate: timeline export leg FAILED"
                rc=1
            else
                echo "ci_gate: timeline export leg ok"
            fi
        fi
    fi
    rm -rf "$tl_tmp"
fi

if [ "$fieldleg" -eq 1 ]; then
    echo "== ci_gate: BabyBear field backend leg (budget ${fd_timeout_s}s) =="
    # the suite itself sets/clears BOOJUM_TPU_FIELD per test; the env
    # stays unset here so the Goldilocks-default tests in the same file
    # see a clean process
    timeout -k 10 "$fd_timeout_s" env JAX_PLATFORMS=cpu \
        python -m pytest tests/test_babybear.py \
        tests/test_bb_full_prover.py -q \
        --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly
    fd_rc=$?
    if [ "$fd_rc" -ne 0 ]; then
        echo "ci_gate: BabyBear field leg FAILED (rc=$fd_rc)"
        rc=1
    else
        echo "ci_gate: BabyBear field leg ok"
    fi
fi

if [ "$multihost" -eq 1 ]; then
    echo "== ci_gate: multihost parity leg (budget ${mh_timeout_s}s) =="
    # -m multihost selects the jax.distributed subprocess-pair tests
    # (registered in conftest.py); BOOJUM_TPU_TWO_PROC_TESTS lifts
    # their default skip
    timeout -k 10 "$mh_timeout_s" env JAX_PLATFORMS=cpu \
        BOOJUM_TPU_TWO_PROC_TESTS=1 \
        python -m pytest tests/test_multihost.py -q -m multihost \
        --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly
    mh_rc=$?
    if [ "$mh_rc" -ne 0 ]; then
        echo "ci_gate: multihost parity leg FAILED (rc=$mh_rc)"
        rc=1
    else
        echo "ci_gate: multihost parity leg ok"
    fi
fi

exit "$rc"
