"""Build an AOT executable artifact bundle (ISSUE 8 build step).

Compiles the full dispatch surface of one circuit/config — the
enumerated kernel library, the setup pipeline and one capture prove —
with the persistent compilation cache redirected into a deployment
bundle under --out (default: $BOOJUM_TPU_AOT_DIR or ./aot_artifacts),
plus a jax.export StableHLO artifact per exportable kernel and a
manifest with integrity hashes. After this, any process on the SAME
(jax, jaxlib, backend, device kind/count, host CPU) stack that sets
BOOJUM_TPU_AOT_DIR to the bundle root proves with ZERO XLA compiles:
`prove()`, the service VariantWarmer and bench.py all consult the
store before tracing.

Usage:
  python scripts/build_artifacts.py [--circuit sha256|fma]
      [--sha-bytes N] [--log-n N] [--lde N] [--queries N]
      [--out DIR] [--mesh C,R] [--workers N] [--no-prove]

Runs on whatever JAX_PLATFORMS the environment pins — build on the
deployment platform (the artifacts are platform-fingerprinted and a
mismatched consumer falls back to JIT with a warning). Equivalent
one-shot for the bench circuit: `python bench.py --build-artifacts`.

Prints one JSON summary line: bundle dir, kernel/export/cache-entry
counts, bytes, build wall and the compile-ledger summary.
"""

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="build_artifacts.py",
        description="Build an AOT executable artifact bundle",
    )
    ap.add_argument(
        "--circuit", default="sha256", choices=("sha256", "fma"),
        help="which bench circuit to build for (default sha256)",
    )
    ap.add_argument(
        "--sha-bytes", type=int,
        default=int(os.environ.get("BENCH_SHA_BYTES", "8192")),
        help="sha256 message size (default $BENCH_SHA_BYTES or 8192)",
    )
    ap.add_argument(
        "--log-n", type=int,
        default=int(os.environ.get("BENCH_LOG_N", "10")),
        help="fma-mode trace log2 size (default $BENCH_LOG_N or 10)",
    )
    ap.add_argument(
        "--lde", type=int, default=None,
        help="FRI commit rate (default: bench's per-circuit default)",
    )
    ap.add_argument(
        "--queries", type=int,
        default=int(os.environ.get("BENCH_QUERIES", "50")),
        help="FRI query count (default $BENCH_QUERIES or 50)",
    )
    ap.add_argument(
        "--cap", type=int, default=16,
        help="Merkle tree cap size (default 16, the bench config)",
    )
    ap.add_argument(
        "--final-degree", type=int, default=16,
        help="FRI final degree (default 16, the bench config)",
    )
    ap.add_argument(
        "--out", default=None,
        help="bundle root (default $BOOJUM_TPU_AOT_DIR or "
             "./aot_artifacts)",
    )
    ap.add_argument(
        "--mesh", default=None, metavar="C,R",
        help="build the shard_map mesh variant for a ('col','row') "
             "mesh of this shape (default: meshless)",
    )
    ap.add_argument(
        "--workers", type=int,
        default=int(os.environ.get("BENCH_PRECOMPILE_WORKERS", "8")),
        help="precompile thread-pool width (default 8)",
    )
    ap.add_argument(
        "--no-prove", action="store_true",
        help="skip the capture setup+prove (bundle covers only the "
             "enumerated kernel library — setup/query graphs will JIT)",
    )
    args = ap.parse_args(argv)

    # bench.py owns the circuit builders AND the fingerprint-salted
    # cache / compile-ledger process setup — reuse both
    import bench  # noqa: E402  (repo root on sys.path above)

    from boojum_tpu.prover import ProofConfig
    from boojum_tpu.prover.aot import build_bundle
    from boojum_tpu.utils.profiling import current_compile_ledger

    lde = args.lde
    if lde is None:
        lde = 8 if args.circuit == "sha256" else 4
    config = ProofConfig(
        fri_lde_factor=lde,
        merkle_tree_cap_size=args.cap,
        num_queries=args.queries,
        pow_bits=0,
        fri_final_degree=args.final_degree,
    )
    if args.circuit == "sha256":
        cs = bench.build_sha256(args.sha_bytes)
    else:
        cs = bench.build_fma(args.log_n)
    asm = cs.into_assembly()
    print(f"trace_len={asm.trace_len}", file=sys.stderr, flush=True)

    mesh_shape = None
    if args.mesh:
        c, r = args.mesh.split(",")
        mesh_shape = (int(c), int(r))

    out_root = args.out or os.environ.get(
        "BOOJUM_TPU_AOT_DIR", ""
    ).strip() or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "aot_artifacts",
    )

    ledger = current_compile_ledger()
    manifest = build_bundle(
        asm, config, out_root,
        mesh_shape=mesh_shape,
        ledger=ledger,
        max_workers=args.workers,
        include_prove=not args.no_prove,
    )
    line = {
        "status": "ok",
        "bundle": manifest["dir"],
        "bucket": manifest["bucket"],
        "variant": manifest["variant"],
        "num_kernels": manifest["num_kernels"],
        "num_exports": manifest["num_exports"],
        "num_cache_entries": len(manifest["cache_entries"]),
        "cache_bytes": manifest["cache_bytes"],
        "build_wall_s": manifest["build_wall_s"],
    }
    if ledger is not None:
        line["compile_ledger"] = ledger.summary()
    print(json.dumps(line), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
