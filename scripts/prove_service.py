"""Proving-service worker CLI: drain a batch of requests through
`boojum_tpu/service/` and emit per-request SLO records.

Usage:
  python scripts/prove_service.py --demo N [--report out.jsonl]
      Enqueue N demo jobs (mixed geometries + a priority-lane job),
      run the worker loop to drain, print the service summary JSON.

  python scripts/prove_service.py --jobs jobs.json [--report out.jsonl]
      Drive jobs from a spec file: a JSON list of
        {"circuit": "fma"|"sha256", "log_n": 10 | "bytes": 8192,
         "priority": "interactive"|"batch"|"bulk", "count": 1,
         "lde": 2, "queries": 4, "final_degree": 16}
      entries. Same-shape jobs bucket together in the admission queue.

  python scripts/prove_service.py --gateway [--port P] [--report out.jsonl]
      Serve the NETWORK admission plane (ISSUE 11): POST /prove with
      tenant bearer tokens + Idempotency-Key replay, GET /jobs/<id>
      (+ /proof download), POST /admin/drain and /admin/reload-artifacts,
      with /metrics, /healthz and /slo composed under the same server.
      Tenants come from BOOJUM_TPU_GATEWAY_TENANTS
      ("id:token[:weight[:quota_bytes[:quota_compute_s]]][,...]", inline
      JSON list, or @file.json); with none configured a single demo
      tenant is synthesized and its token printed to stderr. The process
      serves until POST /admin/drain completes (or Ctrl-C, which drains).
      Job specs over the wire are the same JSON objects --jobs takes.

Environment (see README "Environment flags"):
  BOOJUM_TPU_SERVICE_QUEUE_CAP    admission-queue bound (default 64)
  BOOJUM_TPU_SERVICE_CACHE_BYTES  device-cache LRU cap (default 2 GiB)
  BOOJUM_TPU_SERVICE_SHARD_ROWS   shard-parallel trace threshold (2^17)
  BOOJUM_TPU_SERVICE_MAX_INFLIGHT proof-parallel pack width (default 1);
                                  packed requests each record their own
                                  report line (contextvars-scoped
                                  flight recorder)
  BOOJUM_TPU_SERVICE_PRECOMPILE   full | lower | off (default full)
  BOOJUM_TPU_SERVICE_METRICS_PORT HTTP telemetry port (--metrics-port
                                  overrides; 0 = any free port)
  BOOJUM_TPU_TELEMETRY_INTERVAL   background sampler cadence, seconds
                                  (default 1.0)
  BOOJUM_TPU_XPROF                <dir>[:N] — capture jax.profiler
                                  traces of the next N proves
  BOOJUM_TPU_REPORT               default report path (per-request SLO
                                  JSONL; --report overrides)

Each served request appends one ProveReport JSONL line carrying the
`request` SLO record (queue latency, placement, occupancy, prove wall,
proofs/sec, cache hit, trace dir when captured) on top of the flight
recorder's span/metrics/checkpoint axes and the sampler's `telemetry`
time series. Validate with `scripts/prove_report.py --check`, summarize
with `--slo`. With `--metrics-port P` the worker loop serves live
telemetry on 127.0.0.1:P — `/metrics` (Prometheus text: queue depth,
lane occupancy, in-flight count, device memory, live-buffer census),
`/healthz`, `/slo` — while it drains.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_fma(log_n: int):
    from boojum_tpu.cs.gates import FmaGate, PublicInputGate
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.types import CSGeometry

    geom = CSGeometry(8, 0, 6, 4)
    cs = ConstraintSystem(geom, 1 << log_n)
    a = cs.alloc_variable_with_value(1)
    b = cs.alloc_variable_with_value(2)
    per_row = FmaGate.instance().num_repetitions(geom)
    for _ in range(((1 << log_n) - 8) * per_row):
        a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
    PublicInputGate.place(cs, b)
    return cs


def build_sha256(num_bytes: int):
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.types import CSGeometry, LookupParameters
    from boojum_tpu.gadgets import allocate_u8_input, sha256

    geom = CSGeometry(60, 0, 8, 7)
    capacity = 1 << max(17, (num_bytes // 8192).bit_length() + 16)
    cs = ConstraintSystem(
        geom, capacity,
        lookup_params=LookupParameters(width=4, num_repetitions=8),
    )
    data = bytes(i % 255 for i in range(num_bytes))
    sha256(cs, allocate_u8_input(cs, data))
    return cs


def _job_parts(spec: dict):
    """(assembly, setup, config) for one job spec; setup generation is
    the caller's cost, exactly as for a direct prove."""
    from boojum_tpu.prover import ProofConfig, generate_setup

    kind = spec.get("circuit", "fma")
    if kind == "sha256":
        cs = build_sha256(int(spec.get("bytes", 8192)))
        lde_default = 8
    else:
        cs = build_fma(int(spec.get("log_n", 10)))
        lde_default = 2
    config = ProofConfig(
        fri_lde_factor=int(spec.get("lde", lde_default)),
        merkle_tree_cap_size=int(spec.get("cap", 4)),
        num_queries=int(spec.get("queries", 4)),
        fri_final_degree=int(spec.get("final_degree", 16)),
    )
    asm = cs.into_assembly()
    return asm, generate_setup(asm, config), config


def make_spec_resolver():
    """spec dict -> (assembly, setup, config), memoized per distinct
    circuit spec (the gateway's resolver: repeated specs re-submit the
    same parts — the device-cache hit path, exactly like --jobs)."""
    parts_cache: dict[str, tuple] = {}

    def resolve(spec: dict):
        key = json.dumps(
            {
                k: v for k, v in spec.items()
                if k not in ("priority", "count", "capture_trace")
            },
            sort_keys=True,
        )
        if key not in parts_cache:
            parts_cache[key] = _job_parts(spec)
        return parts_cache[key]

    return resolve


def run_gateway(svc, args) -> int:
    """--gateway: serve the admission plane until drained."""
    import secrets

    from boojum_tpu.service import Gateway, GatewayConfig, TenantSpec

    cfg = GatewayConfig.from_env()
    if args.port is not None:
        cfg.port = args.port
    if not cfg.tenants:
        token = secrets.token_hex(16)
        cfg.tenants = [TenantSpec(id="default", token=token, admin=True)]
        print(f"gateway: no tenants configured — demo tenant 'default' "
              f"token={token} (admin)", file=sys.stderr)
    gw = Gateway(svc, cfg, make_spec_resolver())
    port = gw.start()
    print(
        f"gateway: serving http://{cfg.host}:{port} — POST /prove, "
        f"GET /jobs/<id>[/proof], /metrics /healthz /slo, "
        f"POST /admin/drain | /admin/reload-artifacts",
        file=sys.stderr,
    )
    try:
        while not gw.drained.wait(timeout=1.0):
            pass
    except KeyboardInterrupt:
        print("gateway: interrupt — draining", file=sys.stderr)
        gw.drain()
    finally:
        gw.stop()
    print(json.dumps(svc.summary()))
    return 0


def demo_jobs(n: int) -> list[dict]:
    """A mixed demo batch: two geometries, alternating lanes, so the
    queue buckets, the scheduler sees occupancy, and the cache manager
    sees both hits and misses."""
    jobs = []
    for i in range(n):
        jobs.append(
            {
                "circuit": "fma",
                "log_n": 10 if i % 3 else 11,
                "priority": "interactive" if i == n - 1 else "batch",
            }
        )
    return jobs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="prove_service.py",
        description="Drain proving jobs through boojum_tpu/service/",
    )
    ap.add_argument("--demo", type=int, metavar="N",
                    help="enqueue N mixed demo jobs")
    ap.add_argument("--jobs", metavar="JOBS_JSON",
                    help="job spec file (JSON list)")
    ap.add_argument("--gateway", action="store_true",
                    help="serve the HTTP admission plane until drained "
                         "(tenants: BOOJUM_TPU_GATEWAY_TENANTS)")
    ap.add_argument("--port", type=int, metavar="PORT",
                    help="--gateway bind port (default: "
                         "BOOJUM_TPU_GATEWAY_PORT, 0 = any free port)")
    ap.add_argument("--report", metavar="OUT_JSONL",
                    help="per-request SLO report path "
                         "(default: BOOJUM_TPU_REPORT)")
    ap.add_argument("--metrics-port", type=int, metavar="PORT",
                    help="serve live telemetry over HTTP while the "
                         "worker drains: /metrics (Prometheus text), "
                         "/healthz, /slo (0 = any free port; default: "
                         "BOOJUM_TPU_SERVICE_METRICS_PORT)")
    ap.add_argument("--capture-trace", action="store_true",
                    help="record a jax.profiler trace of the FIRST "
                         "submitted job (per-request capture_trace "
                         "flag; see also BOOJUM_TPU_XPROF)")
    ap.add_argument("--verify", action="store_true",
                    help="verify every proof after the drain")
    args = ap.parse_args(argv)
    if not args.demo and not args.jobs and not args.gateway:
        ap.print_usage()
        return 2

    from boojum_tpu.service import (
        ProvingService,
        QueueFullError,
        ServiceConfig,
    )

    cfg = ServiceConfig.from_env()
    if args.report:
        cfg.report_path = args.report
    if args.metrics_port is not None:
        cfg.metrics_port = args.metrics_port
    svc = ProvingService(cfg)
    print(
        f"service up: {len(svc.devices)} devices, "
        f"mesh={None if svc.mesh is None else dict(svc.mesh.shape)}, "
        f"queue cap {svc.queue.capacity}, "
        f"cache cap {svc.cache.capacity_bytes >> 20} MiB, "
        f"precompile={svc.warmer.mode}",
        file=sys.stderr,
    )
    if cfg.metrics_port is not None:
        # start the plane BEFORE admission so an operator can watch the
        # queue fill; run_worker leaves a caller-started plane running
        port = svc.start_telemetry(cfg.metrics_port)
        if port is not None:
            print(
                f"telemetry: http://127.0.0.1:{port}/metrics "
                f"(/healthz /slo)",
                file=sys.stderr,
            )
        else:
            print(
                "telemetry: endpoint failed to bind — sampler-only "
                "(see service log)",
                file=sys.stderr,
            )

    if args.gateway:
        return run_gateway(svc, args)

    specs = demo_jobs(args.demo) if args.demo else json.load(open(args.jobs))
    requests = []
    # one (assembly, setup) per distinct circuit spec; repeated specs
    # re-submit the same pair — that is the device-cache hit path
    parts_cache: dict[str, tuple] = {}
    for spec in specs:
        key = json.dumps(
            {k: v for k, v in spec.items() if k not in ("priority", "count")},
            sort_keys=True,
        )
        if key not in parts_cache:
            parts_cache[key] = _job_parts(spec)
        asm, setup, config = parts_cache[key]
        for _ in range(int(spec.get("count", 1))):
            submit = lambda: svc.submit(  # noqa: E731
                asm, setup, config,
                priority=spec.get("priority", "batch"),
                tenant=spec.get("tenant", "default"),
                # first job only: one attributable trace, not N
                capture_trace=bool(args.capture_trace and not requests),
            )
            try:
                requests.append(submit())
            except QueueFullError:
                # backpressure: drain the admitted work, then resubmit —
                # the in-process analogue of a client's retry-after
                print(
                    f"queue full at {svc.queue.capacity}: draining before "
                    "resubmitting",
                    file=sys.stderr,
                )
                svc.run_worker()
                requests.append(submit())
    summary = svc.run_worker()
    svc.stop_telemetry()
    print(json.dumps(summary))

    failed = [r for r in requests if r.error is not None]
    for r in failed:
        print(f"{r.id}: FAILED {r.error!r}", file=sys.stderr)
    if args.verify and not failed:
        from boojum_tpu.prover import verify

        for r in requests:
            assert verify(
                r.setup.vk, r.proof, r.assembly.gates
            ), f"{r.id}: proof did not verify"
        print(f"verified {len(requests)} proofs", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
