"""ProveReport CLI: render, diff and validate flight-recorder artifacts.

Usage:
  python scripts/prove_report.py <report.jsonl> [--index -1] [--top 10]
      Render one report line: span tree with per-span wall/%, sync time
      and OCCUPANCY (occ = sync_s/wall, the fraction of the span the
      host spent blocked on the device — the overlapped pipeline's
      regression signal) plus ovl (async-transfer in-flight time), the
      top-N leaf spans with the same sync/occ columns, metrics
      counters/gauges (incl. host.blocking_syncs and the
      transfer.overlap_s/sync_s totals), digest checkpoints and the
      compile-ledger summary.

  python scripts/prove_report.py --diff <a.jsonl> <b.jsonl> [--index ...]
      Regression triage between two reports: per-span wall deltas
      (matched by tree path) and the FIRST diverging Fiat–Shamir digest
      checkpoint — a bit-parity break names the stage where the
      transcript forked instead of just a mismatching proof blob.
      Exits 1 when the digest streams diverge.

  python scripts/prove_report.py --check <report.jsonl>
      Validate schema + digest-checkpoint monotonicity for EVERY line of
      the artifact (the cheap post-bench gate) — including the proving
      service's per-request SLO records (a request line missing its
      queue latency or placement, or carrying malformed service.*
      gauges, fails), the AOT artifact-store gauges (malformed
      aot.* values, warmed kernels without the aot.deserialize_s
      gauge, or a line whose ledger claims every kernel was an
      `aot_hit` while also counting cache misses — i.e. real compiles
      escaped the artifact store — all fail), the schema-2 `telemetry`
      record (background-sampler time series: malformed cadence,
      negative readings or time-disordered samples fail), and the
      per-tenant `tenant` record of gateway lines (ISSUE 11: a
      gateway-admitted request line MISSING its tenant record fails,
      quota charges must be finite and non-negative, and a 429/load-shed
      rejection line must never carry a prove wall — nothing was
      proved), the context-scoping invariant — a line whose
      spans/request record mix TWO request ids means the packed
      service's scoped collectors bled across requests, and FAILS —
      and the schema-3 `cost` record (ISSUE 12): a negative or
      zero-denominator efficiency claim (achieved rates over a
      zero/absent wall, efficiency against a zero/absent device peak)
      FAILS, and a record claiming XLA actuals for kernels the compile
      ledger never recorded FAILS (attribution must never outrun the
      evidence). Lines are routed by kind (ISSUE 15): blackbox
      heartbeat/dump lines (utils/blackbox.py — a dump missing its
      stacks or heartbeat trail fails) and fleet records are validated
      by their own rules, so a report artifact interleaving forensics
      with prove lines checks end to end. Exits 1 on any problem.

  python scripts/prove_report.py --fleet HOST_FILE HOST_FILE... [--out F]
      Merge per-host artifacts (multihost_worker result files — their
      `prove_report_path` per-host report is followed automatically —
      and/or per-host report .jsonl) into ONE mesh-wide fleet record:
      clock-skew-aligned host roster (barrier-synchronized clock_sync
      stamps, no NTP assumption), per-stage walls side by side with
      across-host median/max, straggler detection (slowest host named
      when it exceeds 1.5x the median by >= 50 ms), and per-host
      ici/transfer byte rollups. --out writes the fleet record as JSON
      (checkable with --check). Exits 1 when the merged record fails
      its own validation.

  python scripts/prove_report.py --timeline PATH [PATH...] [--perfetto F]
      Stitch per-host artifacts (report .jsonl and/or multihost_worker
      result files, same inputs as --fleet) into ONE distributed trace
      timeline (ISSUE 17): host clocks are aligned via the
      barrier-synchronized clock_sync stamps (no NTP assumption), spans
      are grouped per trace_id into parent/child trees across hosts, and
      an ASCII swimlane is rendered — queue.wait next to the prove
      stages it delayed, blackbox instants pinned on the same axis, the
      slowest host of every across-host span flagged as a straggler.
      --perfetto additionally writes the merged timeline as Chrome
      trace-event JSON (open in Perfetto / chrome://tracing); the export
      is validated before writing and the command exits 1 when the
      merge yields no events or the JSON fails validation.

  python scripts/prove_report.py --slo <report.jsonl>
      Aggregate the per-request SLO records of a proving-service
      artifact: p50/p95 queue latency and prove wall, proofs/sec over
      the serving span, per-placement/priority counts, cache hit rate,
      and the AOT artifact hit rate over every warmed kernel in the
      stream. Gateway artifacts additionally get per-tenant p95s and
      the rejected-admission counts (429 quota throttles, load-sheds).
      An artifact with ZERO request records (plain proves,
      bench reps) has no serving span to aggregate — that is reported
      explicitly and exits 0 (nothing to summarize is not a failure).

  python scripts/prove_report.py --roofline <report.jsonl> [--index -1]
      Render the line's `cost` record (ISSUE 12): per-stage achieved
      GFLOP/s & GB/s against the device's nominal peaks, arithmetic
      intensity, compute-vs-memory roofline regime and efficiency
      fraction, plus the analytic-model-vs-XLA-actuals agreement
      ratios. Exits 1 when the line has no cost record.

  python scripts/prove_report.py --trend PATH [PATH...] [--gate]
      Per-stage perf trajectory over a history of artifacts — report
      .jsonl files, bench.py JSON lines, BENCH_*.json round wrappers,
      MULTICHIP_r*.json wrappers (the metric line is recovered from the
      captured tail; the round number from the filename),
      bench_micro.py line files; directories expand to their
      *.json/*.jsonl sorted by name. Series are grouped by the
      machine/software identity block when lines carry one, so micro
      numbers from different hosts or jax versions never gate each
      other. With --gate, the LAST point of every series is compared
      against the MEDIAN of its predecessors and the command exits 1
      when any stage/metric regresses beyond --gate-threshold (default
      0.2 = 20%, plus a 50 ms absolute floor for wall series) — the
      CI-able perf gate.

Reports come from BOOJUM_TPU_REPORT=<path> (any prove), bench.py (labeled
warm-up/rep lines), scripts/multihost_worker.py (per-host files) or
scripts/prove_service.py (per-request service lines).

The report library (boojum_tpu/utils/report.py) is loaded standalone —
by file path, stdlib only — so this CLI never imports boojum_tpu or jax;
it works on machines without an accelerator stack and costs milliseconds.
"""

import argparse
import importlib.util
import json
import os
import sys


def _load_report_lib():
    """Load boojum_tpu/utils/report.py WITHOUT importing the package (the
    package __init__ pulls in jax and configures compilation caches —
    pointless weight for reading JSON). Falls back to the package import
    if the standalone load ever breaks."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "boojum_tpu", "utils", "report.py")
    try:
        spec = importlib.util.spec_from_file_location(
            "_boojum_tpu_report_standalone", path
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        sys.path.insert(0, root)
        from boojum_tpu.utils import report as mod  # type: ignore

        return mod


def _load_fleet_host(path: str) -> tuple:
    """Parse one per-host artifact into (label, docs). Accepts a
    multihost_worker result file (single JSON object) or a per-host
    report/blackbox JSONL; a result line's `prove_report_path` is
    followed (also tried relative to the result file's directory, for
    artifacts copied off the pod) so stage walls come along for free."""
    base = os.path.basename(path)
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return os.path.splitext(base)[0], []
    docs = []
    try:
        docs = [json.loads(text)]
    except ValueError:
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln:
                continue
            try:
                docs.append(json.loads(ln))
            except ValueError:
                continue
    extra = []
    for d in docs:
        if not isinstance(d, dict):
            continue
        rp = d.get("prove_report_path")
        if not isinstance(rp, str) or not rp:
            continue
        for cand in (
            rp,
            os.path.join(os.path.dirname(path), os.path.basename(rp)),
        ):
            if not os.path.isfile(cand):
                continue
            try:
                with open(cand) as f:
                    for ln in f:
                        ln = ln.strip()
                        if not ln:
                            continue
                        try:
                            extra.append(json.loads(ln))
                        except ValueError:
                            continue
            except OSError:
                pass
            break
    docs.extend(extra)
    label = None
    for d in docs:
        if (
            isinstance(d, dict)
            and isinstance(d.get("pid"), int)
            and "process_count" in d
        ):
            label = f"host{d['pid']}"
            break
    if label is None:
        label = os.path.splitext(base)[0]
    return label, docs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="prove_report.py",
        description="Render / diff / validate ProveReport JSONL artifacts",
    )
    ap.add_argument("paths", nargs="*", help="report artifact path(s)")
    ap.add_argument(
        "--diff", nargs=2, metavar=("A", "B"),
        help="diff two report artifacts (span deltas + first diverging "
             "digest checkpoint)",
    )
    ap.add_argument(
        "--check", metavar="REPORT",
        help="validate schema + checkpoint monotonicity of every line",
    )
    ap.add_argument(
        "--slo", metavar="REPORT",
        help="summarize per-request SLO records (p50/p95 queue latency, "
             "proofs/sec, placements)",
    )
    ap.add_argument(
        "--roofline", metavar="REPORT",
        help="render the line's cost record: per-stage achieved "
             "GFLOP/s & GB/s, roofline regime, efficiency vs peak",
    )
    ap.add_argument(
        "--trend", nargs="+", metavar="PATH",
        help="per-stage perf trajectory over report artifacts / "
             "BENCH_*.json + MULTICHIP_r*.json history / bench_micro "
             "line files (directories expand to *.json|*.jsonl)",
    )
    ap.add_argument(
        "--gate", action="store_true",
        help="with --trend: exit 1 when the last point of any series "
             "regresses beyond the noise threshold",
    )
    ap.add_argument(
        "--gate-threshold", type=float, default=0.2,
        help="relative regression threshold for --gate (default 0.2)",
    )
    ap.add_argument(
        "--fleet", nargs="+", metavar="HOST_FILE",
        help="merge per-host artifacts (multihost result files and/or "
             "per-host report .jsonl) into one mesh-wide fleet record "
             "with clock alignment and straggler detection",
    )
    ap.add_argument(
        "--out", metavar="PATH",
        help="with --fleet: also write the fleet record as JSON here",
    )
    ap.add_argument(
        "--timeline", nargs="+", metavar="PATH",
        help="stitch per-host artifacts into one clock-aligned "
             "distributed-trace timeline (ASCII swimlane per trace)",
    )
    ap.add_argument(
        "--perfetto", metavar="OUT_JSON",
        help="with --timeline: also write the merged timeline as Chrome "
             "trace-event JSON (Perfetto / chrome://tracing)",
    )
    ap.add_argument(
        "--index", type=int, default=-1,
        help="which JSONL line to use (default: last)",
    )
    ap.add_argument(
        "--top", type=int, default=10,
        help="how many top spans / deltas to show (default 10)",
    )
    args = ap.parse_args(argv)
    rl = _load_report_lib()

    if args.check:
        reports = rl.load_reports(args.check)
        if not reports:
            print(f"{args.check}: no report lines")
            return 1
        bad = 0
        for i, rep in enumerate(reports):
            problems = rl.validate_line(rep)
            kind = rep.get("kind")
            if kind == rl.BLACKBOX_KIND:
                where = rep.get("span") or rep.get("phase") or "?"
                desc = f"blackbox {rep.get('record')}"
                if rep.get("record") == "dump":
                    desc += f" [{rep.get('reason')}] at {where}"
                else:
                    desc += f" seq {rep.get('seq')} at {where}"
            elif kind == rl.FLEET_KIND:
                desc = (
                    f"fleet — {rep.get('n_hosts')} hosts, "
                    f"{len(rep.get('stragglers') or ())} straggler(s)"
                )
            else:
                desc = None
            label = rep.get("label")
            if problems:
                bad += 1
                print(f"line {i} ({label!r}): INVALID")
                for p in problems:
                    print(f"  - {p}")
            elif desc is not None:
                print(f"line {i} ({label!r}): ok — {desc}")
            else:
                cov = rl.span_coverage(rep)
                print(
                    f"line {i} ({label!r}): ok — wall {rep.get('wall_s')}s, "
                    f"{len(rep.get('checkpoints') or [])} checkpoints, "
                    f"span coverage {cov * 100:.1f}%"
                )
        # cross-line pass: a span_id shared by two report lines means the
        # trace stitcher would merge unrelated spans — fail the artifact
        cross = rl.validate_artifact(reports)
        if cross:
            bad += 1
            print("artifact: INVALID")
            for p in cross:
                print(f"  - {p}")
        return 1 if bad else 0

    if args.fleet:
        host_docs = []
        seen: dict = {}
        for p in args.fleet:
            label, docs = _load_fleet_host(p)
            # two result files from the same pid (copied runs) must stay
            # distinct columns
            if label in seen:
                seen[label] += 1
                label = f"{label}.{seen[label]}"
            else:
                seen[label] = 0
            host_docs.append((label, docs))
        rec = rl.fleet_merge(host_docs)
        print(rl.render_fleet(rec))
        if args.out:
            with open(args.out, "w") as f:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
            print(f"fleet record -> {args.out}")
        problems = rl.validate_fleet(rec)
        if problems:
            print("PROBLEMS:")
            for p in problems:
                print(f"  - {p}")
            return 1
        return 0

    if args.timeline:
        host_docs = []
        seen_tl: dict = {}
        for p in args.timeline:
            label, docs = _load_fleet_host(p)
            if label in seen_tl:
                seen_tl[label] += 1
                label = f"{label}.{seen_tl[label]}"
            else:
                seen_tl[label] = 0
            host_docs.append((label, docs))
        rec = rl.timeline_merge(host_docs)
        print(rl.render_timeline(rec))
        if not rec.get("traces") and not rec.get("marks"):
            print("timeline: no events — nothing to export")
            return 1
        if args.perfetto:
            doc = rl.perfetto_events(rec)
            problems = rl.validate_perfetto(doc)
            if problems:
                print("PERFETTO EXPORT INVALID:")
                for p in problems:
                    print(f"  - {p}")
                return 1
            with open(args.perfetto, "w") as f:
                f.write(json.dumps(doc, sort_keys=True))
            print(
                f"perfetto trace ({len(doc['traceEvents'])} events) "
                f"-> {args.perfetto}"
            )
        return 0

    if args.slo:
        reports = rl.load_reports(args.slo)
        summary = rl.slo_summary(reports)
        if not summary["requests"]:
            # zero request records = no serving span to divide over —
            # an expected state for plain-prove/bench artifacts, not an
            # error (the old exit-1 failed pipelines that --slo every
            # artifact indiscriminately)
            print(
                f"{args.slo}: no serving span — 0 request records in "
                f"{len(reports)} line(s); nothing to summarize"
            )
            return 0
        print(rl.render_slo(summary))
        return 0

    if args.roofline:
        rep = rl.load_report(args.roofline, args.index)
        print(rl.render_roofline(rep))
        return 0 if isinstance(rep.get("cost"), dict) else 1

    if args.trend:
        points, notes = rl.load_trend_points(args.trend)
        for n in notes:
            print(n, file=sys.stderr)
        if not points:
            print("no usable trend points")
            return 2
        series = rl.trend_series(points)
        regressions = rl.trend_gate(
            series, threshold=args.gate_threshold
        )
        print(rl.render_trend(
            series, regressions, labels=[p["label"] for p in points]
        ))
        if args.gate:
            if regressions:
                print(
                    f"GATE: {len(regressions)} series regressed beyond "
                    f"{args.gate_threshold:.0%}"
                )
                return 1
            print("GATE: ok")
        return 0

    if args.diff:
        a = rl.load_report(args.diff[0], args.index)
        b = rl.load_report(args.diff[1], args.index)
        diff = rl.diff_reports(a, b, top=args.top)
        print(rl.render_diff(diff))
        return 1 if diff["first_checkpoint_divergence"] is not None else 0

    if len(args.paths) == 2:
        # convenience: two positional paths behave like --diff
        a = rl.load_report(args.paths[0], args.index)
        b = rl.load_report(args.paths[1], args.index)
        diff = rl.diff_reports(a, b, top=args.top)
        print(rl.render_diff(diff))
        return 1 if diff["first_checkpoint_divergence"] is not None else 0

    if len(args.paths) != 1:
        ap.print_usage()
        return 2
    rep = rl.load_report(args.paths[0], args.index)
    print(rl.render_report(rep, top=args.top))
    problems = rl.validate_report(rep)
    if problems:
        print("PROBLEMS:")
        for p in problems:
            print(f"  - {p}")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
