"""SHA-256 2^20-row driver: synthesize once (pickled checkpoint), then
prove at the Era commit rate with live-HBM logging between stages.

Usage: BENCH_REPS=N python scripts/sha2_20_driver.py
Checkpoint: /tmp/sha2_20_asm.pkl (delete to re-synthesize).
"""

import os
import pickle
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CKPT = os.environ.get("SHA20_CKPT", "/tmp/sha2_20_asm.pkl")

# the 2^20 geometry runs at the HBM ceiling: queueing all Q coset sweeps
# async lets neighbors' working sets overlap and OOM (the round-3 finding),
# so THIS driver opts into the per-coset barrier the overlapped prover no
# longer applies by default (export =0 to experiment without it)
os.environ.setdefault("BOOJUM_TPU_SYNC_SWEEPS", "1")

# persist remote compiles (the tunnel compiler is ~1 graph/min); importing
# bench configures the platform-salted cache dir as an import side effect
import bench  # noqa: E402,F401


def log_mem(tag):
    import jax

    live = jax.live_arrays()
    total = sum(a.size * a.dtype.itemsize for a in live)
    print(f"[mem] {tag}: {total / 2**30:.2f} GiB across {len(live)} arrays",
          flush=True)


def get_assembly():
    if os.path.exists(CKPT):
        t0 = time.perf_counter()
        with open(CKPT, "rb") as f:
            asm = pickle.load(f)
        print(f"loaded checkpoint in {time.perf_counter()-t0:.1f}s", flush=True)
        return asm
    from bench import build_sha256

    t0 = time.perf_counter()
    cs = build_sha256(131072)
    print(f"synthesis: {time.perf_counter()-t0:.1f}s", flush=True)
    t0 = time.perf_counter()
    asm = cs.into_assembly()
    print(f"freeze: {time.perf_counter()-t0:.1f}s; trace_len={asm.trace_len}",
          flush=True)
    with open(CKPT + ".tmp", "wb") as f:
        pickle.dump(asm, f, protocol=4)
    os.replace(CKPT + ".tmp", CKPT)
    print("checkpoint saved", flush=True)
    return asm


def main():
    reps = int(os.environ.get("BENCH_REPS", "1"))
    asm = get_assembly()
    from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify

    cfg = ProofConfig(
        fri_lde_factor=int(os.environ.get("BENCH_LDE", "2")),
        merkle_tree_cap_size=32,
        num_queries=int(os.environ.get("BENCH_QUERIES", "100")),
        pow_bits=0,
        fri_final_degree=int(os.environ.get("BENCH_FINAL", "16")),
    )
    log_mem("before setup")
    t0 = time.perf_counter()
    setup = generate_setup(asm, cfg)
    print(f"setup: {time.perf_counter()-t0:.1f}s "
          f"(Q={setup.vk.quotient_degree}, L={setup.vk.fri_lde_factor})",
          flush=True)
    log_mem("after setup")
    t0 = time.perf_counter()
    proof = prove(asm, setup, cfg)
    print(f"prove (cold): {time.perf_counter()-t0:.1f}s", flush=True)
    log_mem("after prove")
    t0 = time.perf_counter()
    ok = verify(setup.vk, proof, asm.gates)
    print(f"verify: {ok} in {time.perf_counter()-t0:.1f}s", flush=True)
    assert ok
    for r in range(reps):
        t0 = time.perf_counter()
        proof = prove(asm, setup, cfg)
        print(f"prove (warm {r}): {time.perf_counter()-t0:.2f}s", flush=True)


if __name__ == "__main__":
    main()
