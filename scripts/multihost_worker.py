"""Worker process for the 2-process jax.distributed multihost tests.

Launched by tests/test_multihost.py with:
  python scripts/multihost_worker.py <mode> <port> <pid> <nprocs> <out.json>

Brings up jax.distributed over localhost (CPU backend, 2 virtual devices per
process), runs the requested DCN mode, and writes its result JSON. Modes:
  proofs  — proof-parallel through the SERVICE worker loop: this process
            submits its distribute_proofs slice of a 3-job queue to a
            local ProvingService (boojum_tpu/service/) and drains it —
            shape-bucketed admission, device-resident caches, per-request
            SLO records; no cross-process collectives. The per-host
            result-line format (proofs dict, ici gauges) is unchanged.
            With BOOJUM_TPU_GATEWAY_SPOOL set (ISSUE 11), the process
            ALSO takes its distribute_proofs slice of the gateway's
            spool directory — one JSON job file per request, written by
            service/gateway.py for bulk-lane admissions — so the
            horizontal tier has a feed path from the network front door.
            Spool specs carry {"job", "tenant", "seed", "priority"};
            each proved job lands in the result line's "spool" dict.
  hybrid  — hybrid_mesh: one proof whose mesh 'col' axis spans both
            processes (GSPMD collectives cross the process boundary)

Every result line carries a `clock_sync` record (ISSUE 15): time.time()
stamped immediately after a global device barrier, so
`prove_report.py --fleet` aligns per-host timelines from the stamps'
pairwise differences instead of assuming NTP-synchronized clocks.
"""

import json
import os
import sys

# must run BEFORE jax import: local CPU with 2 devices per process
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2"
).strip()
os.environ.pop("PYTHONSTARTUP", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
from jax._src import xla_bridge

jax.config.update("jax_platforms", "cpu")
xla_bridge._backend_factories.pop("axon", None)
# same host-fingerprint-salted dir as conftest.py, and for the same reason
# (cross-host XLA:CPU AOT entries segfault — see boojum_tpu/_hostfp.py);
# sharing the name keeps the worker warm from test-suite compiles. Executed
# by file path (runpy) so boojum_tpu/__init__'s side effects don't fire.
import runpy

_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_fp = runpy.run_path(
    os.path.join(_root, "boojum_tpu", "_hostfp.py")
)["load_host_fingerprint"](_root)
_cache = os.path.join(_root, f".jax_cache-{_fp}")
jax.config.update("jax_compilation_cache_dir", _cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def build_circuit(seed: int):
    from boojum_tpu.cs.gates import FmaGate, PublicInputGate
    from boojum_tpu.cs.implementations import ConstraintSystem
    from boojum_tpu.cs.types import CSGeometry

    cs = ConstraintSystem(CSGeometry(8, 0, 6, 4), 1 << 10)
    a = cs.alloc_variable_with_value(1 + seed)
    b = cs.alloc_variable_with_value(2 + seed)
    for _ in range(300):
        a, b = b, FmaGate.fma(cs, a, b, a, 1, 1)
    PublicInputGate.place(cs, b)
    return cs


def main():
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("mode", choices=["proofs", "hybrid"])
    ap.add_argument("port", type=int)
    ap.add_argument("pid", type=int)
    ap.add_argument("nprocs", type=int)
    ap.add_argument("out_path")
    ap.add_argument(
        "--mesh-mode", choices=["shard_map", "gspmd"], default=None,
        help="force the hybrid prove's mesh execution mode (sets "
        "BOOJUM_TPU_MESH_MODE before the prove; default: the prover's "
        "own default, shard_map on every topology)",
    )
    args = ap.parse_args()
    mode, port, pid, nprocs, out_path = (
        args.mode, args.port, args.pid, args.nprocs, args.out_path
    )
    if args.mesh_mode:
        os.environ["BOOJUM_TPU_MESH_MODE"] = args.mesh_mode
    from boojum_tpu.parallel.multihost import (
        distribute_proofs,
        hybrid_mesh,
        initialize_multihost,
    )

    active = initialize_multihost(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=pid,
    )
    assert active, "jax.distributed did not come up multi-process"
    assert jax.process_count() == nprocs

    # flight recorder, per-host: point each process at its own ProveReport
    # artifact (JSONL appends from two processes into one file would
    # interleave); prove() auto-records once the env var is set. With no
    # BOOJUM_TPU_REPORT configured the recorder is armed anyway, next to
    # the result file — MULTICHIP rounds must always record which path
    # (mesh_mode) and which fabric (ici/dcn gauges) actually ran
    report_base = os.environ.get("BOOJUM_TPU_REPORT") or (
        out_path + ".report.jsonl"
    )
    report_path = f"{report_base}.host{pid}"
    os.environ["BOOJUM_TPU_REPORT"] = report_path

    # black-box forensics (ISSUE 15): with BOOJUM_TPU_BLACKBOX /
    # BOOJUM_TPU_STALL_S armed, a host wedged inside a cross-process
    # collective leaves a heartbeat trail + stack dump behind — the
    # per-host artifact `--fleet` aggregates
    try:
        from boojum_tpu.utils import blackbox as _blackbox

        _blackbox.ensure_started(
            label=f"multihost{pid}", report_path=report_path
        )
        _blackbox.set_phase(f"multihost_{mode}")
    except Exception:
        pass

    # hard deadline (ISSUE 16): XLA:CPU's gloo collectives have NO
    # timeout — a cross-process rendezvous whose peer never arrives
    # (observed once on a cold compile cache) blocks forever with zero
    # CPU. Exit 3 with stacks after BOOJUM_TPU_MH_DEADLINE_S (default
    # 1800 s, generous for cold cross-host compiles; 0 disables) so a
    # wedged pair fails the CI leg fast and with forensics instead of
    # silently burning the harness timeout.
    deadline_s = float(os.environ.get("BOOJUM_TPU_MH_DEADLINE_S", "1800"))
    if deadline_s > 0:
        import faulthandler
        import threading

        def _deadline_abort():
            print(
                f"multihost_worker pid={pid}: deadline "
                f"{deadline_s}s exceeded, dumping stacks and exiting",
                file=sys.stderr,
            )
            faulthandler.dump_traceback(file=sys.stderr)
            try:
                from boojum_tpu.utils import blackbox as _bb

                bb = _bb.current_blackbox()
                if bb is not None:
                    bb.dump("deadline", deadline_s=deadline_s)
            except Exception:
                pass
            sys.stderr.flush()
            os._exit(3)

        _t = threading.Timer(deadline_s, _deadline_abort)
        _t.daemon = True
        _t.start()

    # barrier-synchronized wall-clock stamp (ISSUE 15 satellite): every
    # process reads time.time() immediately after passing the SAME
    # global device barrier, so the pairwise differences of these stamps
    # ARE the hosts' wall-clock skews — prove_report.py --fleet aligns
    # per-host timelines from them without assuming NTP
    clock_sync = None
    try:
        import time as _time

        from jax.experimental import multihost_utils as _mhu

        _mhu.sync_global_devices("boojum_tpu_clock_sync")
        clock_sync = {
            "barrier_unix_ts": _time.time(),
            "method": "sync_global_devices",
        }
    except Exception as e:
        print(f"clock sync barrier failed: {e!r}", file=sys.stderr)

    from boojum_tpu.prover import ProofConfig, generate_setup, prove, verify

    cfg = ProofConfig(fri_lde_factor=4, num_queries=8, fri_final_degree=8)

    result = {"pid": pid, "process_count": jax.process_count()}
    if clock_sync is not None:
        result["clock_sync"] = clock_sync
    if mode == "proofs":
        # proof-parallel across hosts: distribute_proofs slices the job
        # queue per process; WITHIN the process the jobs drain through
        # the service worker loop (meshless placement on a multi-process
        # runtime — cross-host parallelism needs no device collectives)
        from boojum_tpu.service import ProvingService, ServiceConfig

        jobs = [0, 1, 2]
        svc = ProvingService(
            ServiceConfig(precompile="off", report_path=report_path)
        )
        assert svc.mesh is None, "multi-process service must stay meshless"

        def submit_job(seed):
            asm = build_circuit(seed).into_assembly()
            setup = generate_setup(asm, cfg)
            return svc.submit(asm, setup, cfg, request_id=f"job-{seed}")

        mine = distribute_proofs(jobs, submit_job)

        # gateway spool feed (ISSUE 11): this host's slice of the front
        # door's bulk-lane spool rides the same service drain
        spool_dir = os.environ.get("BOOJUM_TPU_GATEWAY_SPOOL")
        mine_spool = []
        if spool_dir and os.path.isdir(spool_dir):
            from boojum_tpu.service.gateway import read_spool

            def submit_spool(item):
                _fname, spec = item
                asm = build_circuit(int(spec.get("seed", 0))).into_assembly()
                setup = generate_setup(asm, cfg)
                priority = spec.get("priority", "bulk")
                # trace propagation (ISSUE 17): the spool record carries
                # the trace the GATEWAY minted at POST /prove — submit
                # under it so the fleet's prove lines stitch back to the
                # admission instead of orphaning
                trace = spec.get("trace")
                return svc.submit(
                    asm, setup, cfg,
                    request_id=str(spec.get("job", _fname)),
                    tenant=str(spec.get("tenant", "default")),
                    priority=priority if priority in (
                        "interactive", "batch", "bulk"
                    ) else "bulk",
                    trace=trace if isinstance(trace, dict) else None,
                )

            mine_spool = distribute_proofs(read_spool(spool_dir),
                                           submit_spool)

        summary = svc.run_worker()
        result["service"] = summary
        assert summary["failed"] == 0, summary
        for _i, req in mine:
            assert verify(req.setup.vk, req.result(), req.assembly.gates)
        result["proofs"] = {str(i): req.result().to_json() for i, req in mine}
        # per-job trace ids on the result line (ISSUE 17): fleet-proved
        # jobs must not be orphan traces — the gateway side joins its
        # tickets to the fleet's proves through this map, and the
        # timeline stitcher gets it for free via each prove line's
        # trace_ctx
        result["traces"] = {
            req.id: (req.trace or {}).get("trace_id")
            for _i, req in list(mine) + list(mine_spool)
        }
        if mine_spool:
            for _i, req in mine_spool:
                assert verify(
                    req.setup.vk, req.result(), req.assembly.gates
                )
            result["spool"] = {
                req.id: req.result().to_json() for _i, req in mine_spool
            }
    elif mode == "hybrid":
        mesh = hybrid_mesh(col_axis_per_host=2)
        assert mesh.shape["col"] == nprocs * 2, dict(mesh.shape)
        # record which execution path this prove will take (shard_map =
        # native limb kernels + explicit collectives; gspmd = legacy
        # XLA-partitioned u64) — the parity test and MULTICHIP triage
        # both key on this stamp
        from boojum_tpu.parallel.sharding import (
            mesh_mode as _mesh_mode,
            prover_mesh as _prover_mesh,
        )

        with _prover_mesh(mesh):
            result["mesh_mode"] = _mesh_mode()
        asm = build_circuit(0).into_assembly()
        setup = generate_setup(asm, cfg)
        proof = prove(asm, setup, cfg, mesh=mesh)
        result["proof"] = proof.to_json()
    else:
        raise SystemExit(f"unknown mode {mode}")
    result.setdefault("mesh_mode", "none")

    if report_path is not None:
        result["prove_report_path"] = report_path
        # surface the explicit-collective bill (ISSUE 5) and its
        # cross-host split (ISSUE 16) on the per-host line itself: the
        # ici.*/dcn.* gauges/counters of the LAST prove of this host,
        # plus its Fiat-Shamir digest checkpoints, so multi-host runs
        # are triageable (and parity-checkable) without opening every
        # ProveReport artifact
        try:
            with open(report_path) as f:
                lines = [ln for ln in f if ln.strip()]
            last = json.loads(lines[-1])
            metrics = last.get("metrics") or {}
            for fam in ("ici", "dcn"):
                result[fam] = {
                    k: v
                    for src in ("gauges", "counters")
                    for k, v in (metrics.get(src) or {}).items()
                    if k.startswith(f"{fam}.")
                }
            if isinstance(last.get("checkpoints"), list):
                result["checkpoints"] = last["checkpoints"]
        except (OSError, ValueError, IndexError):
            result["ici"] = {}
            result["dcn"] = {}

    with open(out_path, "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main()
