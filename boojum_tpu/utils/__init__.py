from .profiling import (
    CompileLedger,
    current_compile_ledger,
    log,
    profiling_enabled,
    stage_timer,
    start_compile_ledger,
    stop_compile_ledger,
)

__all__ = [
    "CompileLedger",
    "current_compile_ledger",
    "log",
    "profiling_enabled",
    "stage_timer",
    "start_compile_ledger",
    "stop_compile_ledger",
]
