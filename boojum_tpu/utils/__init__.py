from .profiling import stage_timer, profiling_enabled, log

__all__ = ["stage_timer", "profiling_enabled", "log"]
