"""Lightweight tracing/profiling.

Counterpart of the reference's observability layer (SURVEY.md §5): the
`firestorm` scoped profiling macros (`profile_fn!/profile_section!`,
reference src/lib.rs:80, used throughout prover.rs) and the `log!` macro
(src/log_utils.rs). Here: a `stage_timer` context manager emitting per-stage
wall-clock lines, enabled by BOOJUM_TPU_PROFILE=1 (or programmatically), and
a `log` helper gated the same way. TPU-side kernel profiles come from
`jax.profiler` traces (set BOOJUM_TPU_JAX_TRACE=<dir> around a prove call).
"""

from __future__ import annotations

import contextlib
import os
import sys
import time

_FORCED: bool | None = None


def profiling_enabled() -> bool:
    if _FORCED is not None:
        return _FORCED
    return bool(os.environ.get("BOOJUM_TPU_PROFILE"))


def set_profiling(on: bool | None):
    """Programmatic override (None = follow the environment)."""
    global _FORCED
    _FORCED = on


def log(msg: str):
    if profiling_enabled():
        print(f"[boojum_tpu] {msg}", file=sys.stderr, flush=True)


_STAGE_SINK: list | None = None


def collect_stages() -> list:
    """Start collecting (stage, seconds) tuples from stage_timer into a
    fresh list (bench.py uses this for the per-stage split it emits)."""
    global _STAGE_SINK
    _STAGE_SINK = []
    return _STAGE_SINK


def stop_collecting_stages():
    global _STAGE_SINK
    _STAGE_SINK = None


@contextlib.contextmanager
def stage_timer(name: str):
    """Wall-clock a prover stage; also opens a jax.profiler trace context
    when BOOJUM_TPU_JAX_TRACE points at a directory."""
    trace_dir = os.environ.get("BOOJUM_TPU_JAX_TRACE")
    if not profiling_enabled() and not trace_dir and _STAGE_SINK is None:
        yield
        return
    ctx = contextlib.nullcontext()
    if trace_dir:
        import jax

        ctx = jax.profiler.TraceAnnotation(name)
    t0 = time.perf_counter()
    with ctx:
        yield
    dt = time.perf_counter() - t0
    if _STAGE_SINK is not None:
        _STAGE_SINK.append((name, dt))
    log(f"{name}: {dt:.3f}s")
