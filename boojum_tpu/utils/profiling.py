"""Lightweight tracing/profiling.

Counterpart of the reference's observability layer (SURVEY.md §5): the
`firestorm` scoped profiling macros (`profile_fn!/profile_section!`,
reference src/lib.rs:80, used throughout prover.rs) and the `log!` macro
(src/log_utils.rs). Here: a `stage_timer` context manager emitting per-stage
wall-clock lines, enabled by BOOJUM_TPU_PROFILE=1 (or programmatically), and
a `log` helper gated the same way. TPU-side kernel profiles come from
`jax.profiler` traces (set BOOJUM_TPU_JAX_TRACE=<dir> around a prove call).

Also home of the COMPILE LEDGER: per-graph trace/compile timings and
persistent-cache hit/miss counts, fed from three sources — explicit
`record()` calls (prover/precompile.py times every lower/compile itself),
`jax.monitoring` duration/count events (backend_compile_duration, cache
hits/misses), and, when `jax_log_compiles` is on, the per-graph
"Finished XLA compilation of <name> in <t> sec" log lines that carry the
only per-graph attribution jax exposes for compiles triggered by ordinary
dispatch. bench.py emits the ledger as a JSON artifact so compile-bill
regressions are visible in every round's output.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import sys
import threading
import time

_FORCED: bool | None = None


def profiling_enabled() -> bool:
    if _FORCED is not None:
        return _FORCED
    return bool(os.environ.get("BOOJUM_TPU_PROFILE"))


def set_profiling(on: bool | None):
    """Programmatic override (None = follow the environment). Turning
    profiling ON (re)asserts the stderr handler — idempotently: toggling
    twice in one process must never stack a second handler (each stage
    line would print once per toggle)."""
    global _FORCED
    _FORCED = on
    if on:
        ensure_stderr_handler()


class _GatedStderrHandler(logging.Handler):
    """stderr handler gated on BOOJUM_TPU_PROFILE (kept out of the stream
    when profiling is off) that resolves sys.stderr at EMIT time, so
    redirected/captured stderr (tests, bench wrappers) still sees the
    lines."""

    def emit(self, record):
        if not profiling_enabled():
            return
        try:
            print(self.format(record), file=sys.stderr, flush=True)
        except Exception:
            pass


logger = logging.getLogger("boojum_tpu")

# the stderr handler is identified by NAME, not class identity: an
# isinstance guard breaks the moment this module is re-executed (reload,
# a second standalone load) because the re-defined class is a different
# object — and every per-stage line then prints once per stale handler
_STDERR_HANDLER_NAME = "boojum_tpu.gated_stderr"


def ensure_stderr_handler(
    target_logger: logging.Logger | None = None,
    _set_defaults: bool = False,
) -> logging.Handler:
    """Install the gated stderr handler on `target_logger` (default: the
    library logger) exactly once per logger, keyed by handler name so
    repeated installs — BOOJUM_TPU_PROFILE toggled twice, a module
    re-execution — are no-ops returning the live handler.

    `_set_defaults` applies the library's level/propagate posture ONLY
    on a fresh install: a re-execution must not clobber an embedder
    that re-raised the level or flipped propagate back on."""
    lg = target_logger if target_logger is not None else logger
    for h in lg.handlers:
        if getattr(h, "name", None) == _STDERR_HANDLER_NAME:
            return h
    h = _GatedStderrHandler()
    h.name = _STDERR_HANDLER_NAME
    h.setFormatter(logging.Formatter("[boojum_tpu] %(message)s"))
    lg.addHandler(h)
    if _set_defaults:
        lg.setLevel(logging.INFO)
        # quiet by default: per-stage INFO records must not leak into an
        # application's root handlers (propagation skips ancestor LOGGER
        # levels, so a plain basicConfig() would otherwise print every
        # stage line even with profiling off). Handlers attached
        # directly to the "boojum_tpu" logger still receive everything;
        # an embedder that wants the records in its root pipeline flips
        # propagate back on.
        lg.propagate = False
    return h


ensure_stderr_handler(logger, _set_defaults=True)


def log(msg: str):
    """Library log line. Routed through logging.getLogger("boojum_tpu") so
    user handlers ON THAT LOGGER compose; the built-in stderr handler only
    prints under BOOJUM_TPU_PROFILE=1, preserving the quiet default."""
    logger.info(msg)


_STAGE_SINK: list | None = None


def collect_stages() -> list:
    """Start collecting (stage, seconds) tuples from stage_timer into a
    fresh list (bench.py uses this for the per-stage split it emits)."""
    global _STAGE_SINK
    _STAGE_SINK = []
    return _STAGE_SINK


def stop_collecting_stages():
    global _STAGE_SINK
    _STAGE_SINK = None


@contextlib.contextmanager
def stage_timer(name: str):
    """Wall-clock a prover stage. Now a thin shim over the hierarchical
    span recorder (utils/spans.py): same flat sink/log behavior as before,
    plus tree recording when a SpanRecorder is installed, plus exception
    safety — a stage that raises still records its timing (with an
    `error` field on the span) instead of losing the line."""
    from .spans import span

    with span(name, stage=True):
        yield


# ---------------------------------------------------------------------------
# Compile ledger
# ---------------------------------------------------------------------------

# jax.monitoring event keys this ledger understands (jax 0.4.x):
#   /jax/core/compile/backend_compile_duration        (duration)
#   /jax/core/compile/jaxpr_trace_duration            (duration)
#   /jax/compilation_cache/cache_hits                 (count)
#   /jax/compilation_cache/cache_misses               (count)
#   /jax/compilation_cache/compile_time_saved_sec     (duration)
_DURATION_KEYS = (
    "/jax/core/compile/backend_compile_duration",
    "/jax/core/compile/jaxpr_trace_duration",
    "/jax/core/compile/jaxpr_to_mlir_module_duration",
    "/jax/compilation_cache/compile_time_saved_sec",
)
_COUNT_KEYS = (
    "/jax/compilation_cache/cache_hits",
    "/jax/compilation_cache/cache_misses",
)


class CompileLedger:
    """Per-graph compile accounting.

    `entries` holds one dict per recorded kernel:
      {name, trace_s, compile_s, cache_hit, ts}
    appended under a lock so timestamps are monotonic in list order even
    when compiles run on a thread pool. `events` aggregates the passive
    jax.monitoring stream (whole-process durations/counts, no per-graph
    names); `dispatch_compiles` collects the named per-graph compile times
    parsed from jax's "Finished XLA compilation of <name>" log lines —
    the only attribution available for graphs compiled by ordinary
    dispatch rather than through precompile().

    Caveat on that log line: jax emits it around compile_or_get_cached,
    INCLUDING persistent-cache HITS — after a healthy precompile, a
    prove's first dispatch of each kernel still logs one (fast) line for
    the cache load. Parsed lines therefore split by elapsed time:
    >= _DISPATCH_COMPILE_MIN_S lands in `dispatch_compiles`, smaller ones
    are only counted/summed as cache loads in the summary. The split is a
    heuristic — deserializing a BIG cached executable can also cross the
    threshold — so treat `dispatch_compiles` as attribution (which graph,
    when) and the monitoring `cache_misses` counter as the authoritative
    did-anything-escape-the-precompiler signal: a prove that raises no
    new misses compiled nothing, however slow its loads."""

    # below this, a "Finished XLA compilation" line is a persistent-cache
    # load, not a compile: loads are local-disk reads (well under a
    # second) while even a cheap real compile on the tunneled service is
    # a multi-second RPC
    _DISPATCH_COMPILE_MIN_S = 1.0

    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.entries: list[dict] = []
        self.events: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self.dispatch_compiles: list[dict] = []
        self._cache_loads = 0
        self._cache_load_s = 0.0
        # while the precompile sweep runs, its own .compile() calls also
        # emit "Finished XLA compilation" log lines — suppressed here so
        # dispatch_compiles only lists graphs that ESCAPED the library
        # (the regression signal BASELINE.md documents), not every kernel
        # counted twice
        self.suppress_log_capture = False

    # -- explicit source (precompile.py / service warm-up) -----------------
    def record(self, name: str, trace_s: float, compile_s: float,
               cache_hit: bool | None = None, error: str | None = None,
               shape_key: str | None = None, aot_hit: bool | None = None,
               xla_cost: dict | None = None):
        """`shape_key` is the canonical shape-bucket key of the
        (assembly, config) pair this kernel belongs to
        (prover/shape_key.py) — the SAME key the service admission queue
        buckets on, so a compile-bill regression is attributable to the
        bucket that paid it. `aot_hit` (prover/aot.py's warm pass) marks
        whether this kernel came back as an AOT-artifact
        DESERIALIZATION (True) or escaped to a real compile (False) —
        the summary splits `aot_hits`/`aot_misses`/`aot_deserialize_s`
        from ordinary compiles so a warm-up wall is attributable to the
        right bill. `xla_cost` (ISSUE 12) is the executable's
        compile-time actuals — `compiled.cost_analysis()` flops /
        bytes-accessed plus `memory_analysis()` sizes, captured by
        precompile/aot warm via costmodel.xla_cost_of — the axis the
        analytic cost sheet cross-checks against."""
        with self._lock:
            entry = {
                "name": name,
                "trace_s": round(float(trace_s), 4),
                "compile_s": round(float(compile_s), 4),
                "cache_hit": cache_hit,
                "ts": round(time.monotonic() - self._t0, 4),
            }
            if shape_key is not None:
                entry["shape"] = shape_key
            if aot_hit is not None:
                entry["aot_hit"] = bool(aot_hit)
            if xla_cost:
                entry["cost"] = dict(xla_cost)
            if error is not None:
                entry["error"] = error
            self.entries.append(entry)

    def kernel_costs(self, shape_key: str | None = None) -> dict:
        """{kernel_name: xla_cost dict} over every entry that captured
        compile-time actuals (last recording of a name wins — a re-warm
        refreshes the actuals). The ledger is process-global and kernel
        names are not shape-qualified, so a multi-bucket process MUST
        pass its bucket's `shape_key` or another bucket's compiles get
        attributed to this one."""
        with self._lock:
            return {
                e["name"]: e["cost"]
                for e in self.entries
                if "cost" in e
                and (shape_key is None or e.get("shape") == shape_key)
            }

    # -- passive sources ---------------------------------------------------
    def _on_duration(self, event: str, duration: float, **kw):
        if event not in _DURATION_KEYS:
            return
        with self._lock:
            self.events[event] = self.events.get(event, 0.0) + duration
            self.counts[event] = self.counts.get(event, 0) + 1

    def _on_event(self, event: str, **kw):
        if event not in _COUNT_KEYS:
            return
        with self._lock:
            self.counts[event] = self.counts.get(event, 0) + 1

    def _on_log(self, record: logging.LogRecord):
        if self.suppress_log_capture:
            return
        # dispatch.log_elapsed_time formats lazily; getMessage() renders
        # "Finished XLA compilation of <fun_name> in <elapsed> sec"
        try:
            msg = record.getMessage()
        except Exception:
            return
        marker = "Finished XLA compilation of "
        if marker not in msg:
            return
        try:
            rest = msg.split(marker, 1)[1]
            name, _, tail = rest.rpartition(" in ")
            secs = float(tail.split(" sec")[0])
        except Exception:
            return
        with self._lock:
            if secs < self._DISPATCH_COMPILE_MIN_S:
                self._cache_loads += 1
                self._cache_load_s += secs
                return
            self.dispatch_compiles.append({
                "name": name,
                "compile_s": round(secs, 4),
                "ts": round(time.monotonic() - self._t0, 4),
            })

    # -- reporting ---------------------------------------------------------
    def summary(self) -> dict:
        with self._lock:
            entries = list(self.entries)
            dispatch = list(self.dispatch_compiles)
            counts = dict(self.counts)
            events = dict(self.events)
            cache_loads = self._cache_loads
            cache_load_s = self._cache_load_s
        compile_total = sum(e["compile_s"] for e in entries)
        worst = max(
            entries + dispatch, key=lambda e: e["compile_s"], default=None
        )
        shapes = sorted({e["shape"] for e in entries if e.get("shape")})
        aot_entries = [e for e in entries if "aot_hit" in e]
        aot_hits = sum(1 for e in aot_entries if e["aot_hit"])
        return {
            "num_kernels": len(entries),
            # the recorded kernel-name set: the report validator rejects
            # a `cost` record claiming kernels this ledger never saw
            # (ISSUE 12) — attribution must never outrun the evidence
            "kernel_names": sorted({e["name"] for e in entries}),
            # how many kernels carry compile-time XLA cost actuals
            "cost_kernels": sum(1 for e in entries if "cost" in e),
            "shapes": shapes,
            # AOT artifact accounting (prover/aot.py warm pass): kernels
            # satisfied by executable DESERIALIZATION vs ones that
            # escaped to a compile, and the total deserialize wall — the
            # field a warm-up line's wall is attributed to when a bundle
            # served it
            "aot_hits": aot_hits,
            "aot_misses": len(aot_entries) - aot_hits,
            "aot_deserialize_s": round(
                sum(e["compile_s"] for e in aot_entries if e["aot_hit"]), 3
            ),
            "precompile_total_s": round(compile_total, 3),
            "num_dispatch_compiles": len(dispatch),
            "dispatch_compile_total_s": round(
                sum(e["compile_s"] for e in dispatch), 3
            ),
            "dispatch_cache_loads": cache_loads,
            "dispatch_cache_load_s": round(cache_load_s, 3),
            "worst_graph": None if worst is None else {
                "name": worst["name"], "compile_s": worst["compile_s"]
            },
            "cache_hits": counts.get(
                "/jax/compilation_cache/cache_hits", 0
            ),
            "cache_misses": counts.get(
                "/jax/compilation_cache/cache_misses", 0
            ),
            "backend_compile_total_s": round(
                events.get("/jax/core/compile/backend_compile_duration", 0.0),
                3,
            ),
            "compile_time_saved_s": round(
                events.get(
                    "/jax/compilation_cache/compile_time_saved_sec", 0.0
                ),
                3,
            ),
        }

    def to_dict(self) -> dict:
        with self._lock:
            d = {
                "entries": list(self.entries),
                "dispatch_compiles": list(self.dispatch_compiles),
                "monitoring_durations_s": {
                    k: round(v, 3) for k, v in self.events.items()
                },
                "monitoring_counts": dict(self.counts),
            }
        d["summary"] = self.summary()
        return d

    def dump_json(self, path: str) -> dict:
        d = self.to_dict()
        with open(path, "w") as f:
            json.dump(d, f, indent=1)
        return d


_LEDGER: CompileLedger | None = None
_LISTENERS_INSTALLED = False
_LOG_HANDLER: logging.Handler | None = None


class _LedgerLogHandler(logging.Handler):
    def emit(self, record):
        led = _LEDGER
        if led is not None:
            led._on_log(record)


def start_compile_ledger(capture_logs: bool = True) -> CompileLedger:
    """Install a fresh process-wide ledger and return it.

    jax.monitoring offers no listener deregistration short of clearing ALL
    listeners, so the listeners are installed once and route to whatever
    ledger is current (no-ops when stopped). With `capture_logs`, a handler
    on the jax dispatch/pxla loggers parses the per-graph compile lines;
    pair it with jax.config jax_log_compiles=True (or JAX_LOG_COMPILES=1)
    to get per-graph names for dispatch-time compiles."""
    global _LEDGER, _LISTENERS_INSTALLED, _LOG_HANDLER
    _LEDGER = CompileLedger()
    if not _LISTENERS_INSTALLED:
        try:
            from jax import monitoring as _mon

            _mon.register_event_duration_secs_listener(
                lambda ev, dur, **kw: (
                    _LEDGER._on_duration(ev, dur) if _LEDGER else None
                )
            )
            _mon.register_event_listener(
                lambda ev, **kw: (_LEDGER._on_event(ev) if _LEDGER else None)
            )
            _LISTENERS_INSTALLED = True
        except Exception:
            pass
    if capture_logs and _LOG_HANDLER is None:
        _LOG_HANDLER = _LedgerLogHandler(level=logging.DEBUG)
        for name in ("jax._src.dispatch", "jax._src.interpreters.pxla"):
            logging.getLogger(name).addHandler(_LOG_HANDLER)
    return _LEDGER


def current_compile_ledger() -> CompileLedger | None:
    return _LEDGER


def stop_compile_ledger() -> CompileLedger | None:
    """Detach and return the current ledger (listeners become no-ops)."""
    global _LEDGER
    led = _LEDGER
    _LEDGER = None
    return led


# ---------------------------------------------------------------------------
# On-demand jax.profiler trace capture (BOOJUM_TPU_XPROF)
# ---------------------------------------------------------------------------

# BOOJUM_TPU_XPROF=<dir>[:N] arms a process-wide capture budget: the
# next N proves (default 1) each record a jax.profiler trace into a
# fresh subdirectory of <dir>, and the directory lands in the prove's
# ProveReport line (`trace` record) so every trace is attributable to
# the request that produced it. The budget is claimed under a lock —
# packed concurrent proves never double-capture — and re-arms whenever
# the env value CHANGES (re-exporting the same value keeps the spent
# budget). All state is immutable-valued globals rebound under
# _XPROF_LOCK; the profiler itself is a process singleton, so `_ACTIVE`
# additionally guarantees no nested/overlapping capture attempts.
_XPROF_ENV: str | None = None
_XPROF_DIR: str | None = None
_XPROF_REMAINING: int = 0
_XPROF_SEQ: int = 0
_XPROF_ACTIVE: bool = False
_XPROF_LOCK = threading.Lock()


def _parse_xprof(raw: str) -> tuple[str, int]:
    """"<dir>[:N]" -> (dir, N); a trailing :N only counts when numeric,
    so paths containing colons stay usable."""
    raw = raw.strip()
    n = 1
    head, sep, tail = raw.rpartition(":")
    if sep and tail.isdigit():
        raw, n = head, int(tail)
    return raw, max(0, n)


def xprof_remaining() -> int:
    """Captures left in the armed budget (0 = disarmed) — refreshes
    from the environment first, like maybe_trace_capture does."""
    with _XPROF_LOCK:
        _xprof_refresh_locked()
        return _XPROF_REMAINING


def _xprof_refresh_locked():
    global _XPROF_ENV, _XPROF_DIR, _XPROF_REMAINING
    env = os.environ.get("BOOJUM_TPU_XPROF", "").strip()
    if env == (_XPROF_ENV or ""):
        return
    _XPROF_ENV = env
    if not env:
        _XPROF_DIR = None
        _XPROF_REMAINING = 0
        return
    _XPROF_DIR, _XPROF_REMAINING = _parse_xprof(env)


def _xprof_claim(label: str, force: bool) -> tuple[str | None, bool]:
    """Claim one capture slot; returns (trace directory or None,
    whether a budget slot was consumed — so a failed start can refund
    it)."""
    global _XPROF_REMAINING, _XPROF_SEQ, _XPROF_ACTIVE
    import re as _re

    with _XPROF_LOCK:
        if _XPROF_ACTIVE:
            if force:
                # the caller EXPLICITLY asked for this trace — losing it
                # to an in-flight sibling capture must be visible, not a
                # silently missing `trace` record
                log(
                    f"xprof: capture_trace for {label!r} skipped — "
                    f"another capture is in flight (profiler is a "
                    f"process singleton)"
                )
            return None, False
        _xprof_refresh_locked()
        base = _XPROF_DIR
        consumed = False
        if force:
            # a forced (per-request) capture never burns the ambient
            # BOOJUM_TPU_XPROF budget — that budget is armed for the
            # next N un-flagged proves
            if base is None:
                import tempfile

                base = os.path.join(
                    tempfile.gettempdir(), "boojum_tpu_xprof"
                )
        elif _XPROF_REMAINING > 0:
            _XPROF_REMAINING -= 1
            consumed = True
        else:
            return None, False
        seq = _XPROF_SEQ
        _XPROF_SEQ += 1
        _XPROF_ACTIVE = True
    safe = _re.sub(r"[^A-Za-z0-9_.-]", "_", label) or "capture"
    return os.path.join(base, f"{safe}-{seq:03d}"), consumed


def _xprof_refund():
    """Give a consumed budget slot back (the trace failed to START, so
    the armed capture should still cover a later prove)."""
    global _XPROF_REMAINING
    with _XPROF_LOCK:
        _XPROF_REMAINING += 1


@contextlib.contextmanager
def maybe_trace_capture(label: str, force: bool = False):
    """Capture a jax.profiler trace around the block when the
    BOOJUM_TPU_XPROF budget has captures remaining, or unconditionally
    with `force=True` (the service's per-request capture_trace flag —
    without an armed env dir, forced traces land under the system temp
    dir). Yields the trace directory, or None when not capturing.
    Capture failures log and degrade to None — profiling must never
    fail a prove."""
    global _XPROF_ACTIVE
    trace_dir, consumed = _xprof_claim(label, force)
    if trace_dir is None:
        yield None
        return
    started = False
    try:
        try:
            import jax

            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir)
            started = True
            log(f"xprof: capturing {label!r} -> {trace_dir}")
        except Exception as e:
            log(f"xprof: trace capture failed to start: {e!r}")
            if consumed:
                _xprof_refund()  # the armed budget still owes a capture
            # nothing is capturing: release the singleton NOW, not at
            # the end of the (possibly minutes-long) wrapped prove —
            # a concurrent forced capture must not be refused against
            # a phantom in-flight trace. The finally below then only
            # clears ACTIVE for a capture WE started, so it can never
            # stomp a sibling's claim made after this release.
            with _XPROF_LOCK:
                _XPROF_ACTIVE = False
        yield trace_dir if started else None
    finally:
        if started:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                log(f"xprof: stop_trace failed: {e!r}")
            with _XPROF_LOCK:
                _XPROF_ACTIVE = False
