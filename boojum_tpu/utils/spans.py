"""Hierarchical prover spans — the flight recorder's time axis.

Counterpart of the reference's scoped `firestorm` profiling macros
(`profile_fn!/profile_section!`, reference src/lib.rs:80): where the old
`stage_timer` emitted a FLAT per-stage wall-clock list, `span()` records a
parent/child TREE. Every span carries wall time, start offset, optional
attributes, an `error` field when its body raised (partial spans are
recorded, never lost), and — when BOOJUM_TPU_JAX_TRACE points at a
directory — a `jax.profiler.TraceAnnotation` so device traces carry the
same names.

Recording is opt-in: with no `SpanRecorder` installed and profiling off,
`span()` is a handful of attribute reads and one `os.environ.get` — cheap
enough to leave threaded through every prover stage permanently. Stage
spans (``stage=True``) additionally feed the legacy flat stage sink
(`profiling.collect_stages`) and the per-stage stderr log line, so
`bench.py`'s stage split keeps working unchanged.

Scoping (ISSUE 9): the ACTIVE recorder is resolved contextvar-first —
`install_scoped_recorder` binds a recorder to the current execution
context (one packed proving-service request on its pool thread), while
`install_recorder` keeps setting the process-global DEFAULT context that
bench/CLI flows rely on. Concurrent scoped contexts record into disjoint
trees; code that never scopes sees exactly the old process-global
behavior.

Explicit device sync points: `sync_point(x, label)` calls
`jax.block_until_ready` when an installed recorder asks for synced spans,
charging asynchronously-dispatched device work to the stage that issued it
instead of whichever later stage first touches the result.

Trace context (ISSUE 17): every recorder owns a Dapper-style trace —
a 32-hex `trace_id` minted at construction (or adopted from an inbound
context bound via `set_inbound_trace` / the BOOJUM_TPU_TRACE env var),
and every span opened under it carries a fresh 16-hex `span_id` plus a
`parent_span_id` (the enclosing span's id; for roots, the inbound
parent — e.g. the gateway's admission span). The ids are what
`prove_report.py --timeline` stitches cross-host artifacts on.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import secrets
import threading
import time

from . import profiling as _prof

# Dapper-mold id formats (BASELINE.md "Trace protocol"): trace ids are
# 128-bit, span ids 64-bit, both lowercase hex — the same widths the
# W3C traceparent header uses, so external drivers can mint compatible
# ids without knowing anything about this codebase.
TRACE_ID_HEX = 32
SPAN_ID_HEX = 16


def new_trace_id() -> str:
    return secrets.token_hex(TRACE_ID_HEX // 2)


def new_span_id() -> str:
    return secrets.token_hex(SPAN_ID_HEX // 2)


def _is_hex_id(s, width: int) -> bool:
    return (
        isinstance(s, str)
        and len(s) == width
        and all(c in "0123456789abcdef" for c in s)
    )


def valid_trace_id(s) -> bool:
    return _is_hex_id(s, TRACE_ID_HEX)


def valid_span_id(s) -> bool:
    return _is_hex_id(s, SPAN_ID_HEX)


# inbound trace context: bound to the current execution context by
# whoever dispatches work on behalf of an already-minted trace (the
# proving service serving a gateway-admitted request). A SpanRecorder
# constructed while a context is bound ADOPTS it instead of minting a
# fresh trace — that is the whole propagation mechanism; nothing else
# needs to know where the recorder came from.
_INBOUND_TRACE: contextvars.ContextVar[dict | None] = contextvars.ContextVar(
    "boojum_tpu.inbound_trace", default=None
)


def set_inbound_trace(ctx: dict | None):
    """Bind an inbound trace context ({"trace_id": ..,
    "parent_span_id": ..?}) to the CURRENT execution context; returns a
    token for reset_inbound_trace. A malformed context is treated as
    absent (recorders mint a fresh trace) rather than poisoning ids."""
    if not (isinstance(ctx, dict) and valid_trace_id(ctx.get("trace_id"))):
        ctx = None
    return _INBOUND_TRACE.set(ctx)


def reset_inbound_trace(token):
    _INBOUND_TRACE.reset(token)


def inbound_trace() -> dict | None:
    """The trace context a new recorder should adopt: contextvar first,
    then the BOOJUM_TPU_TRACE env var ("<trace_id>[:<parent_span_id>]")
    — the latter lets an external driver hand a trace to a bare
    `prove()` CLI/bench process without touching its code."""
    ctx = _INBOUND_TRACE.get()
    if ctx is not None:
        return ctx
    env = os.environ.get("BOOJUM_TPU_TRACE")
    if env:
        tid, _, psid = env.partition(":")
        if valid_trace_id(tid):
            out = {"trace_id": tid}
            if valid_span_id(psid):
                out["parent_span_id"] = psid
            return out
    return None


class SpanRecorder:
    """Collects a span tree. Spans opened on the installing thread nest via
    a per-thread stack; spans opened from other threads (e.g. the
    precompile pool) become additional roots of that thread's own tree and
    are merged into `roots` on close."""

    def __init__(self, sync: bool = True):
        self.t0 = time.perf_counter()
        self.roots: list[dict] = []
        self.sync = sync
        self._tls = threading.local()
        self._lock = threading.Lock()
        # trace context: adopt the inbound one when the constructing
        # context carries it (the scoped-collector path — one gateway
        # request on its pool thread), else mint a fresh root trace
        ctx = inbound_trace()
        if ctx is not None:
            self.trace_id = ctx["trace_id"]
            psid = ctx.get("parent_span_id")
            self.parent_span_id = psid if valid_span_id(psid) else None
        else:
            self.trace_id = new_trace_id()
            self.parent_span_id = None

    def adopt_trace(self, trace_id: str, parent_span_id: str | None = None):
        """Rebind this recorder (and any roots already opened) to an
        externally-minted trace — for callers that learn the context
        only after constructing the recorder."""
        if not valid_trace_id(trace_id):
            return
        self.trace_id = trace_id
        self.parent_span_id = (
            parent_span_id if valid_span_id(parent_span_id) else None
        )
        with self._lock:
            for r in self.roots:
                r["trace_id"] = trace_id
                if self.parent_span_id:
                    r["parent_span_id"] = self.parent_span_id

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current(self) -> dict | None:
        st = self._stack()
        return st[-1] if st else None

    def open(self, name: str, start_at: float | None = None, **attrs) -> dict:
        """Open a span. `start_at` (a time.perf_counter stamp) backdates
        the span to an instant BEFORE open() ran — how the queue.wait
        span covers the admission→dispatch gap even though the request's
        scoped recorder is only constructed at dispatch. A backdated
        span that predates the recorder itself carries a negative
        start_s and a `backdated` marker so validation can tell it from
        a corrupt clock."""
        now = time.perf_counter()
        t0 = start_at if (start_at is not None and start_at <= now) else now
        sp: dict = {
            "name": name,
            "start_s": round(t0 - self.t0, 6),
            "wall_s": None,
            "span_id": new_span_id(),
            "children": [],
        }
        if t0 < self.t0:
            sp["backdated"] = True
        if attrs:
            sp["attrs"] = dict(attrs)
        st = self._stack()
        if st:
            sp["parent_span_id"] = st[-1]["span_id"]
            st[-1]["children"].append(sp)
        else:
            sp["trace_id"] = self.trace_id
            if self.parent_span_id:
                sp["parent_span_id"] = self.parent_span_id
            with self._lock:
                self.roots.append(sp)
        st.append(sp)
        sp["_t0"] = t0
        return sp

    def close(self, sp: dict, error: str | None = None):
        now = time.perf_counter()
        sp["wall_s"] = round(now - sp.pop("_t0", now), 6)
        if error is not None:
            sp["error"] = error
        st = self._stack()
        # an exception can unwind past child spans whose cms have not run
        # their own close yet in start/stop (non-with) usage — drop them
        while st and st[-1] is not sp:
            st.pop()
        if st:
            st.pop()

    def add_sync(self, seconds: float):
        sp = self.current()
        if sp is not None:
            sp["sync_s"] = round(sp.get("sync_s", 0.0) + seconds, 6)

    def add_overlap(self, seconds: float):
        """Charge time an async transfer batch spent in flight WHILE the
        host kept dispatching (utils/transfer.py) — the counterpart of
        `sync_s` (blocked time): together they make the overlap win
        visible per span in every ProveReport."""
        sp = self.current()
        if sp is not None:
            sp["overlap_s"] = round(sp.get("overlap_s", 0.0) + seconds, 6)

    def tree(self) -> list[dict]:
        """The recorded roots, sanitized (no open-span bookkeeping keys)."""

        def _clean(sp: dict) -> dict:
            d = {k: v for k, v in sp.items() if k != "_t0"}
            if "_t0" in sp and d.get("wall_s") is None:
                d["error"] = d.get("error") or "unclosed"
                d["wall_s"] = round(time.perf_counter() - sp["_t0"], 6)
            d["children"] = [_clean(c) for c in sp["children"]]
            return d

        with self._lock:
            return [_clean(r) for r in self.roots]


# process-global DEFAULT context (bench/CLI posture: one recorder owns
# the whole process) — immutable None or a SpanRecorder reference; all
# mutable collector state lives inside recorder instances
_RECORDER: SpanRecorder | None = None
# contextvar override: a scoped recorder bound to one execution context
# (e.g. one packed proving-service request on its pool thread). Threads
# start with an EMPTY context, so a freshly spawned worker falls back to
# the process-global default unless it scopes its own recorder.
_RECORDER_CTX: contextvars.ContextVar[SpanRecorder | None] = (
    contextvars.ContextVar("boojum_tpu.span_recorder", default=None)
)


def current_recorder() -> SpanRecorder | None:
    """The ACTIVE recorder: context-scoped when one is bound, else the
    process-global default."""
    rec = _RECORDER_CTX.get()
    return rec if rec is not None else _RECORDER


def install_recorder(rec: SpanRecorder | None) -> SpanRecorder | None:
    """Swap the process-wide DEFAULT recorder; returns the previous one.
    Scoped recorders (install_scoped_recorder) override this within
    their context."""
    global _RECORDER
    prev = _RECORDER
    _RECORDER = rec
    return prev


def install_scoped_recorder(rec: SpanRecorder | None):
    """Bind `rec` to the CURRENT execution context only (this thread /
    task); returns a token for reset_scoped_recorder. Other contexts —
    including the process-global default — are untouched, so concurrent
    packed requests each record into their own tree."""
    return _RECORDER_CTX.set(rec)


def reset_scoped_recorder(token):
    _RECORDER_CTX.reset(token)


def start_recording(sync: bool = True) -> SpanRecorder:
    rec = SpanRecorder(sync=sync)
    install_recorder(rec)
    return rec


def stop_recording() -> SpanRecorder | None:
    return install_recorder(None)


def span_attr(name: str, value):
    """Attach an attribute to the CURRENTLY OPEN span (no-op when nothing
    records) — for call sites that learn something mid-span worth auditing
    per report, e.g. which axis shard_cols actually sharded."""
    rec = current_recorder()
    if rec is None:
        return
    sp = rec.current()
    if sp is not None:
        sp.setdefault("attrs", {})[name] = value


@contextlib.contextmanager
def span(name: str, stage: bool = False, **attrs):
    """Record one span. Yields the span dict (or None when not recording).

    ``stage=True`` marks a top-level prover stage: on close it also feeds
    the flat stage sink and the per-stage log line (the pre-flight-recorder
    observable surface). Exception-safe: a raising body still records the
    span, with an ``error`` field (ISSUE 2 satellite: the old stage_timer
    lost the timing line entirely)."""
    from . import blackbox as _bb

    # any span open is Python-level forward motion: reset the blackbox
    # stall clock even on the cheap not-recording path
    _bb.tick()
    rec = current_recorder()
    trace_dir = os.environ.get("BOOJUM_TPU_JAX_TRACE")
    if (
        rec is None
        and trace_dir is None
        and not _prof.profiling_enabled()
        and _prof._STAGE_SINK is None
    ):
        yield None
        return
    ctx = contextlib.nullcontext()
    if trace_dir:
        import jax

        ctx = jax.profiler.TraceAnnotation(name)
    sp = rec.open(name, **attrs) if rec is not None else None
    t0 = time.perf_counter()
    err: BaseException | None = None
    try:
        with ctx:
            yield sp
    except BaseException as e:
        err = e
        raise
    finally:
        dt = time.perf_counter() - t0
        error_s = None
        if err is not None:
            error_s = f"{type(err).__name__}: {err}"[:200]
        if rec is not None:
            rec.close(sp, error=error_s)
        if stage:
            sink = _prof._STAGE_SINK
            if sink is not None:
                sink.append((name, dt))
            _prof.log(
                f"{name}: {dt:.3f}s"
                + (f" [error: {error_s}]" if error_s else "")
            )


def sync_point(x, label: str | None = None):
    """Block on `x` (jax.block_until_ready) when the installed recorder
    wants synced spans, charging the wait to the current span as `sync_s`.
    Passes `x` through unchanged; a no-op without a recorder."""
    rec = current_recorder()
    if rec is None or not rec.sync or x is None:
        return x
    import jax

    t0 = time.perf_counter()
    try:
        jax.block_until_ready(x)
    except Exception:
        return x
    rec.add_sync(time.perf_counter() - t0)
    if label:
        sp = rec.current()
        if sp is not None:
            sp.setdefault("sync_points", []).append(label)
    return x
