"""ProveReport: the flight recorder's versioned JSONL artifact.

One report line per prove:
  {kind, schema, label, unix_ts, wall_s,
   spans:      [hierarchical span tree, utils/spans.py],
   metrics:    {counters, gauges, boundaries}, (utils/metrics.py),
   checkpoints:[{seq, round, label, digest}, ...]  — Fiat–Shamir state,
   compile_ledger: summary (when a CompileLedger is installed),
   host:       {platform, process_index}}

Transcript DIGEST CHECKPOINTS are the parity-triage axis: at every
Fiat–Shamir round the prover records blake2s(canonical LE64 encoding) of
what crossed the transcript — per-stage Merkle caps, drawn challenges, FRI
fold challenges, final monomials, query indices. Two proves of the same
witness produce byte-identical checkpoint streams; a bit-parity break
against compat/prove_reference.py (or a past report) localizes to the
FIRST diverging (round, label) instead of the final proof blob.

This module is intentionally stdlib-only at import time: the report CLI
(scripts/prove_report.py) loads it standalone — without importing
boojum_tpu (and therefore jax) — for render/diff/check of existing
artifacts. The recording entry points import spans/metrics lazily.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import json
import os
import time

REPORT_KIND = "boojum_tpu.prove_report"
# schema 2 (ISSUE 9): lines may carry a `telemetry` record (background
# sampler time series, utils/telemetry.py) and a `trace` record (an
# on-demand jax.profiler capture attributable to the line); schema-1
# lines remain valid for --check/--diff
REPORT_SCHEMA = 2
ACCEPTED_SCHEMAS = (1, 2)

# canonical Fiat–Shamir round order; validation checks checkpoint rounds
# never decrease along the stream
ROUND_ORDER = (0, 1, 2, 3, 4, 5)

# the proving service's placements (service/scheduler.py) — a request
# record carrying anything else fails validation
REQUEST_PLACEMENTS = ("shard_parallel", "proof_parallel")
# fields every per-request SLO record must carry (service/service.py);
# prove_wall_s is additionally required unless the record carries an
# error (a failed request may die before its wall is measured)
REQUEST_REQUIRED = ("id", "bucket", "placement", "queue_latency_s")


def _flatten_ints(values):
    out = []
    stack = [values]
    while stack:
        v = stack.pop()
        if isinstance(v, (list, tuple)):
            stack.extend(reversed(v))
        else:
            out.append(int(v))
    return out


def digest_of(values) -> str:
    """blake2s over the 8-byte little-endian words of the (possibly
    nested) integer sequence — the canonical checkpoint digest."""
    h = hashlib.blake2s()
    for v in _flatten_ints(values):
        h.update((v & ((1 << 64) - 1)).to_bytes(8, "little"))
    return h.hexdigest()


class CheckpointLog:
    def __init__(self):
        self.entries: list[dict] = []

    def add(self, round_: int, label: str, values):
        self.entries.append(
            {
                "seq": len(self.entries),
                "round": int(round_),
                "label": label,
                "digest": digest_of(values),
            }
        )


# process-global DEFAULT context; scoped logs (install_scoped_* /
# flight_recording(scoped=True)) override per execution context so
# packed concurrent proves keep disjoint checkpoint streams
_CHECKPOINTS: CheckpointLog | None = None
_CHECKPOINTS_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "boojum_tpu.checkpoint_log", default=None
)


def current_checkpoint_log() -> CheckpointLog | None:
    log = _CHECKPOINTS_CTX.get()
    return log if log is not None else _CHECKPOINTS


def install_checkpoint_log(log: CheckpointLog | None):
    """Swap the process-wide DEFAULT checkpoint log; returns the
    previous one."""
    global _CHECKPOINTS
    prev = _CHECKPOINTS
    _CHECKPOINTS = log
    return prev


def install_scoped_checkpoint_log(log: CheckpointLog | None):
    """Bind `log` to the CURRENT execution context only; returns a token
    for reset_scoped_checkpoint_log."""
    return _CHECKPOINTS_CTX.set(log)


def reset_scoped_checkpoint_log(token):
    _CHECKPOINTS_CTX.reset(token)


def checkpoint(round_: int, label: str, values):
    """Record one Fiat–Shamir digest checkpoint; no-op-cheap (one
    contextvar read, one global read) when nothing is recording."""
    log = current_checkpoint_log()
    if log is not None:
        log.add(round_, label, values)


# ---------------------------------------------------------------------------
# Flight recording: spans + metrics + checkpoints as one unit
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bundles the three collectors for one recorded prove."""

    def __init__(self, label: str | None = None, sync: bool = True):
        from . import metrics as _metrics
        from . import spans as _spans

        self.label = label
        self.spans = _spans.SpanRecorder(sync=sync)
        self.metrics = _metrics.MetricsRegistry()
        self.checkpoints = CheckpointLog()
        self._t0 = time.perf_counter()
        self.wall_s: float | None = None
        # an on-demand jax.profiler capture directory for this recorded
        # window (profiling.maybe_trace_capture) — lands in the report
        # line's `trace` record so the trace is attributable
        self.trace_dir: str | None = None

    def close(self):
        if self.wall_s is None:
            self.wall_s = round(time.perf_counter() - self._t0, 6)


_FLIGHT: FlightRecorder | None = None
_FLIGHT_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "boojum_tpu.flight_recorder", default=None
)


def current_flight_recorder() -> FlightRecorder | None:
    rec = _FLIGHT_CTX.get()
    return rec if rec is not None else _FLIGHT


@contextlib.contextmanager
def flight_recording(
    label: str | None = None, sync: bool = True, scoped: bool = False
):
    """Install a FlightRecorder (spans + metrics + checkpoints) for the
    duration of the block; restores whatever was installed before.

    `scoped=True` binds the collectors to the CURRENT execution context
    via contextvars instead of swapping the process-global defaults —
    the packed proving-service posture, where several requests record
    concurrently on pool threads without corrupting each other's spans,
    counters or checkpoint streams. The default (scoped=False) keeps the
    process-global swap bench/CLI flows rely on: threads they spawn
    mid-recording (the precompile pool) still see the recorder."""
    global _FLIGHT
    from . import metrics as _metrics
    from . import spans as _spans

    rec = FlightRecorder(label=label, sync=sync)
    if scoped:
        tok_flight = _FLIGHT_CTX.set(rec)
        tok_spans = _spans.install_scoped_recorder(rec.spans)
        tok_metrics = _metrics.install_scoped_registry(rec.metrics)
        tok_ckpt = install_scoped_checkpoint_log(rec.checkpoints)
        try:
            yield rec
        finally:
            rec.close()
            _spans.reset_scoped_recorder(tok_spans)
            _metrics.reset_scoped_registry(tok_metrics)
            reset_scoped_checkpoint_log(tok_ckpt)
            _FLIGHT_CTX.reset(tok_flight)
        return
    prev_flight = _FLIGHT
    _FLIGHT = rec
    prev_spans = _spans.install_recorder(rec.spans)
    prev_metrics = _metrics.install_registry(rec.metrics)
    prev_ckpt = install_checkpoint_log(rec.checkpoints)
    try:
        yield rec
    finally:
        rec.close()
        _spans.install_recorder(prev_spans)
        _metrics.install_registry(prev_metrics)
        install_checkpoint_log(prev_ckpt)
        _FLIGHT = prev_flight


def build_report(rec: FlightRecorder, extra: dict | None = None) -> dict:
    rec.close()
    d: dict = {
        "kind": REPORT_KIND,
        "schema": REPORT_SCHEMA,
        "label": rec.label,
        "unix_ts": round(time.time(), 3),
        "wall_s": rec.wall_s,
        "spans": rec.spans.tree(),
        "metrics": rec.metrics.to_dict(),
        "checkpoints": list(rec.checkpoints.entries),
    }
    if rec.trace_dir:
        d["trace"] = {"dir": rec.trace_dir}
    try:
        # the live telemetry plane's time series (utils/telemetry.py):
        # when a sampler is running, every report line carries the
        # service-wide memory/queue/in-flight samples that overlapped it
        from . import telemetry as _telemetry

        sampler = _telemetry.current_sampler()
        if sampler is not None:
            d["telemetry"] = sampler.snapshot()
    except Exception:
        pass
    try:
        from .profiling import current_compile_ledger

        ledger = current_compile_ledger()
        if ledger is not None:
            d["compile_ledger"] = ledger.summary()
    except Exception:
        pass
    try:
        import jax

        d["host"] = {
            "platform": jax.default_backend(),
            "process_index": jax.process_index(),
        }
    except Exception:
        pass
    if extra:
        d.update(extra)
    return d


def append_jsonl(path: str, report: dict):
    line = json.dumps(report, separators=(",", ":"))
    with open(path, "a") as f:
        f.write(line + "\n")


def load_reports(path: str) -> list[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def load_report(path: str, index: int = -1) -> dict:
    reports = load_reports(path)
    if not reports:
        raise ValueError(f"{path}: no report lines")
    return reports[index]


# ---------------------------------------------------------------------------
# Validation / analysis (pure dict functions — usable standalone)
# ---------------------------------------------------------------------------


def _walk_spans(spans, prefix=()):
    """Yield (path_tuple, span) depth-first."""
    for sp in spans:
        path = prefix + (sp.get("name", "?"),)
        yield path, sp
        yield from _walk_spans(sp.get("children", ()), path)


def flatten_spans(report: dict) -> list[tuple[str, dict]]:
    return [
        ("/".join(path), sp)
        for path, sp in _walk_spans(report.get("spans", ()))
    ]


def span_coverage(report: dict) -> float:
    """Fraction of the root prove span's wall covered by its direct
    children (the stage spans). 0.0 when there is no usable tree."""
    spans = report.get("spans") or []
    root = next((s for s in spans if s.get("name") == "prove"), None)
    if root is None and spans:
        root = spans[0]
    if not root or not root.get("wall_s"):
        return 0.0
    covered = sum(
        c.get("wall_s") or 0.0 for c in root.get("children", ())
    )
    return min(1.0, covered / root["wall_s"])


def validate_report(report: dict) -> list[str]:
    """Schema + monotonicity checks; returns a list of problems (empty =
    valid). This is the `prove_report.py --check` gate."""
    problems: list[str] = []
    if report.get("kind") != REPORT_KIND:
        problems.append(f"kind is {report.get('kind')!r}, want {REPORT_KIND!r}")
    if report.get("schema") not in ACCEPTED_SCHEMAS:
        problems.append(
            f"schema is {report.get('schema')!r}, want one of "
            f"{ACCEPTED_SCHEMAS}"
        )
    wall = report.get("wall_s")
    if not isinstance(wall, (int, float)) or wall < 0:
        problems.append(f"wall_s invalid: {wall!r}")
    # context-scoped recording invariant (ISSUE 9): one report line is
    # ONE request's flight data. Span attrs carrying two distinct
    # request ids on a single line mean a scoped collector bled across
    # packed requests — the corruption mode the contextvar scoping
    # exists to prevent, so it must fail the gate loudly.
    span_request_ids = set()
    for path, sp in _walk_spans(report.get("spans", ())):
        attrs = sp.get("attrs")
        if isinstance(attrs, dict) and attrs.get("request") is not None:
            span_request_ids.add(str(attrs["request"]))
        w = sp.get("wall_s")
        if not isinstance(w, (int, float)) or w < 0:
            problems.append(f"span {'/'.join(path)}: wall_s invalid: {w!r}")
        st = sp.get("start_s")
        if not isinstance(st, (int, float)) or st < 0:
            problems.append(f"span {'/'.join(path)}: start_s invalid: {st!r}")
        for c in sp.get("children", ()):
            cst = c.get("start_s")
            if (
                isinstance(cst, (int, float))
                and isinstance(st, (int, float))
                and cst + 1e-6 < st
            ):
                problems.append(
                    f"span {'/'.join(path)}: child {c.get('name')!r} starts "
                    f"before its parent"
                )
    ckpts = report.get("checkpoints")
    if not isinstance(ckpts, list):
        problems.append("checkpoints missing")
        ckpts = []
    last_seq = -1
    last_round = -1
    seen_labels = set()
    for e in ckpts:
        seq, rnd, label = e.get("seq"), e.get("round"), e.get("label")
        dg = e.get("digest")
        if not isinstance(seq, int) or seq <= last_seq:
            problems.append(f"checkpoint {label!r}: seq {seq!r} not increasing")
        else:
            last_seq = seq
        if not isinstance(rnd, int) or rnd < last_round:
            problems.append(
                f"checkpoint {label!r}: round {rnd!r} decreases "
                f"(after round {last_round})"
            )
        else:
            last_round = rnd
        if (rnd, label) in seen_labels:
            problems.append(f"checkpoint {label!r}: duplicate in round {rnd}")
        seen_labels.add((rnd, label))
        if not (
            isinstance(dg, str)
            and len(dg) == 64
            and all(c in "0123456789abcdef" for c in dg)
        ):
            problems.append(f"checkpoint {label!r}: digest malformed: {dg!r}")
    metrics = report.get("metrics")
    if not isinstance(metrics, dict) or "counters" not in metrics:
        problems.append("metrics missing or malformed")
    else:
        # ici.* — the explicit mesh collectives' bill (ISSUE 5). Every
        # gauge must be a finite non-negative number, and a nonzero
        # collective COUNTER must come with its byte gauge (and the pivot
        # timer for all_to_alls): a pivot that moved zero bytes means the
        # accounting seam in parallel/shard_sweep.py was bypassed.
        counters = metrics.get("counters")
        if not isinstance(counters, dict):
            if counters is not None:
                problems.append(
                    "metrics.counters malformed: "
                    f"{type(counters).__name__}"
                )
            counters = {}
        gauges = metrics.get("gauges")
        if not isinstance(gauges, dict):
            if gauges is not None:
                problems.append(
                    f"metrics.gauges malformed: {type(gauges).__name__}"
                )
            gauges = {}

        def _num(v):
            # non-numerics were flagged above; compare as 0 so one bad
            # value yields its problem line instead of a TypeError
            return v if isinstance(v, (int, float)) and v == v else 0

        for k, v in gauges.items():
            if not k.startswith("ici."):
                continue
            if not isinstance(v, (int, float)) or v != v or v < 0:
                problems.append(f"gauge {k}: invalid value {v!r}")
        if _num(counters.get("ici.all_to_alls", 0)) > 0:
            if not _num(gauges.get("ici.all_to_all_bytes", 0)) > 0:
                problems.append(
                    "ici.all_to_alls counted but ici.all_to_all_bytes "
                    "gauge is missing/zero"
                )
            if "ici.pivot_s" not in gauges:
                problems.append(
                    "ici.all_to_alls counted but ici.pivot_s gauge missing"
                )
        if _num(counters.get("ici.all_gathers", 0)) > 0 and not _num(
            gauges.get("ici.all_gather_bytes", 0)
        ) > 0:
            problems.append(
                "ici.all_gathers counted but ici.all_gather_bytes "
                "gauge is missing/zero"
            )
        # service.* — the proving service's queue/cache/SLO axis. Every
        # value must be a finite non-negative number, and evictions must
        # carry their byte gauge (an eviction that freed zero bytes means
        # the cache manager's accounting seam was bypassed).
        for src in (counters, gauges):
            for k, v in src.items():
                if not k.startswith("service."):
                    continue
                if not isinstance(v, (int, float)) or v != v or v < 0:
                    problems.append(
                        f"service metric {k}: invalid value {v!r}"
                    )
        if _num(counters.get("service.cache.evictions", 0)) > 0 and not _num(
            gauges.get("service.cache.evicted_bytes", 0)
        ) > 0:
            problems.append(
                "service.cache.evictions counted but "
                "service.cache.evicted_bytes gauge is missing/zero"
            )
        # aot.* — the AOT artifact store's axis (prover/aot.py). Every
        # value must be a finite non-negative number; warmed kernels
        # (hits+misses > 0) must carry the deserialize-time gauge; and a
        # line claiming every kernel was an artifact hit while its
        # compile ledger still counted cache misses (real compiles) is
        # LYING about its warm-up bill and must fail the gate.
        for src in (counters, gauges):
            for k, v in src.items():
                if not k.startswith("aot."):
                    continue
                if not isinstance(v, (int, float)) or v != v or v < 0:
                    problems.append(f"aot metric {k}: invalid value {v!r}")
        aot_hits = _num(counters.get("aot.hits", 0))
        aot_misses = _num(counters.get("aot.misses", 0))
        if (aot_hits + aot_misses) > 0 and "aot.deserialize_s" not in gauges:
            problems.append(
                "aot.hits/aot.misses counted but aot.deserialize_s "
                "gauge missing"
            )
        # the aot_hit-vs-compile cross-check compares LEDGER fields with
        # LEDGER fields (both process-cumulative): a line whose ledger
        # claims every warmed kernel deserialized from an artifact
        # (aot_hits > 0, aot_misses == 0) while the same ledger counted
        # persistent-cache misses means real compiles escaped the
        # artifact store — the zero-compile claim is false
        ledger = report.get("compile_ledger")
        if isinstance(ledger, dict):
            ledger_hits = _num(ledger.get("aot_hits", 0))
            ledger_misses = _num(ledger.get("aot_misses", 0))
            num_kernels = _num(ledger.get("num_kernels", 0))
            # fires only when the ledger claims FULL aot coverage —
            # every recorded kernel an artifact hit. A mixed-bucket
            # process (bucket A bundled, bucket B precompiled normally)
            # has num_kernels > aot_hits and is a supported state, not
            # a lie.
            if (
                ledger_hits > 0
                and ledger_misses == 0
                and ledger_hits == num_kernels
            ):
                compiles = _num(ledger.get("cache_misses", 0))
                if compiles > 0:
                    problems.append(
                        f"prove claims all-aot_hit kernels but the "
                        f"compile ledger records {int(compiles)} cache "
                        f"misses (real compiles escaped the artifact "
                        f"store)"
                    )
        # limb.* — the u64<->limb conversion tax (ISSUE 10). Counters
        # must be finite non-negative ints, and a line whose kernels
        # claim LIMB-RESIDENT dispatch (quotient.resident_coset_sweeps /
        # fri.resident_folds) while counting INTERIOR splits/joins is
        # lying about residency — the whole point of the resident mode
        # is that those are zero (edges are allowlisted under
        # limb.edge_*/limb.host_*).
        for k, v in counters.items():
            if not k.startswith("limb."):
                continue
            if not isinstance(v, int) or v < 0:
                problems.append(f"limb metric {k}: invalid value {v!r}")
        resident_claimed = (
            _num(counters.get("quotient.resident_coset_sweeps", 0)) > 0
            or _num(counters.get("fri.resident_folds", 0)) > 0
        )
        if resident_claimed:
            for k in ("limb.splits", "limb.joins"):
                if _num(counters.get(k, 0)) > 0:
                    problems.append(
                        f"resident-mode prove counted interior {k} = "
                        f"{counters.get(k)} (conversions must survive "
                        f"only at allowlisted edges)"
                    )
    # per-request SLO record (proving-service lines): the record the
    # --slo summary and dashboards key on — a request line missing its
    # queue latency or placement is unusable for SLO accounting and
    # must fail the --check gate
    request = report.get("request")
    if request is not None:
        if not isinstance(request, dict):
            problems.append(
                f"request record malformed: {type(request).__name__}"
            )
        else:
            for k in REQUEST_REQUIRED:
                if k not in request:
                    problems.append(f"request record missing {k!r}")
            ql = request.get("queue_latency_s")
            if "queue_latency_s" in request and (
                not isinstance(ql, (int, float)) or ql != ql or ql < 0
            ):
                problems.append(
                    f"request queue_latency_s invalid: {ql!r}"
                )
            pl = request.get("placement")
            if "placement" in request and pl not in REQUEST_PLACEMENTS:
                problems.append(
                    f"request placement {pl!r}: want one of "
                    f"{REQUEST_PLACEMENTS}"
                )
            pw = request.get("prove_wall_s")
            if "error" not in request and (
                not isinstance(pw, (int, float)) or pw != pw or pw < 0
            ):
                problems.append(
                    f"request prove_wall_s invalid: {pw!r}"
                )
            if request.get("id") is not None:
                span_request_ids.add(str(request["id"]))
    # per-tenant record (gateway lines, ISSUE 11): quota charges must be
    # sane non-negative numbers, a gateway-ADMITTED request line must
    # carry the record at all (the quota axis is the whole point of
    # admitting through the front door), and a REJECTED line (429 /
    # load-shed) must never claim a prove wall — nothing was proved.
    tenant = report.get("tenant")
    if tenant is not None:
        if not isinstance(tenant, dict):
            problems.append(
                f"tenant record malformed: {type(tenant).__name__}"
            )
            tenant = None
        else:
            tid = tenant.get("id")
            if not isinstance(tid, str) or not tid:
                problems.append(f"tenant record id invalid: {tid!r}")
            for k in (
                "charged_bytes", "charged_compute_s",
                "window_used_bytes", "window_used_compute_s",
                "retry_after_s",
            ):
                if k not in tenant:
                    continue
                v = tenant.get(k)
                if not isinstance(v, (int, float)) or v != v or v < 0:
                    problems.append(f"tenant {k} invalid: {v!r}")
            if tenant.get("rejected"):
                pw = (
                    request.get("prove_wall_s")
                    if isinstance(request, dict) else None
                )
                if isinstance(pw, (int, float)):
                    problems.append(
                        "rejected admission carries prove_wall_s "
                        f"({pw!r}): a 429/shed line must never prove"
                    )
    if (
        isinstance(request, dict)
        and request.get("gateway")
        and tenant is None
    ):
        problems.append(
            "gateway-admitted request line missing its tenant record"
        )
    if len(span_request_ids) > 1:
        problems.append(
            "line mixes request ids "
            f"{sorted(span_request_ids)}: scoped collectors bled "
            "across packed requests"
        )
    # telemetry record (schema 2, utils/telemetry.py): the background
    # sampler's time series. Samples must be time-ordered with finite
    # non-negative readings — a sampler writing junk would poison every
    # dashboard fed from these lines.
    telemetry = report.get("telemetry")
    if telemetry is not None:
        problems.extend(_validate_telemetry(telemetry))
    trace = report.get("trace")
    if trace is not None and not (
        isinstance(trace, dict) and isinstance(trace.get("dir"), str)
        and trace["dir"]
    ):
        problems.append(f"trace record malformed: {trace!r}")
    return problems


def _validate_telemetry(telemetry) -> list[str]:
    if not isinstance(telemetry, dict):
        return [f"telemetry record malformed: {type(telemetry).__name__}"]
    problems: list[str] = []
    iv = telemetry.get("interval_s")
    if not isinstance(iv, (int, float)) or iv != iv or iv <= 0:
        problems.append(f"telemetry interval_s invalid: {iv!r}")
    ticks = telemetry.get("ticks")
    if not isinstance(ticks, int) or ticks < 0:
        problems.append(f"telemetry ticks invalid: {ticks!r}")
    samples = telemetry.get("samples")
    if not isinstance(samples, list):
        return problems + [
            f"telemetry samples missing/malformed: {type(samples).__name__}"
        ]
    last_t = float("-inf")
    for i, s in enumerate(samples):
        if not isinstance(s, dict):
            problems.append(f"telemetry sample {i}: not a dict")
            continue
        t = s.get("t_s")
        if not isinstance(t, (int, float)) or t != t or t < 0:
            problems.append(f"telemetry sample {i}: t_s invalid: {t!r}")
        elif t < last_t:
            problems.append(
                f"telemetry sample {i}: t_s {t} decreases (after {last_t})"
            )
        else:
            last_t = t
        for k, v in s.items():
            if k == "t_s":
                continue
            if not isinstance(v, (int, float)) or v != v or v < 0:
                problems.append(
                    f"telemetry sample {i}: {k} invalid: {v!r}"
                )
    return problems


def diff_reports(a: dict, b: dict, top: int = 10) -> dict:
    """Regression-triage diff: per-span wall deltas (matched by tree path,
    repeated paths summed) and the FIRST diverging digest checkpoint."""

    def _span_walls(report):
        walls: dict[str, float] = {}
        for path, sp in flatten_spans(report):
            walls[path] = walls.get(path, 0.0) + (sp.get("wall_s") or 0.0)
        return walls

    wa, wb = _span_walls(a), _span_walls(b)
    deltas = []
    for path in sorted(set(wa) | set(wb)):
        va, vb = wa.get(path), wb.get(path)
        deltas.append(
            {
                "span": path,
                "a_s": None if va is None else round(va, 6),
                "b_s": None if vb is None else round(vb, 6),
                "delta_s": (
                    None
                    if va is None or vb is None
                    else round(vb - va, 6)
                ),
            }
        )
    # real deltas first (largest |delta| on top); spans present in only one
    # report sort LAST — they must never crowd genuine regressions out of
    # the top-N window
    deltas.sort(
        key=lambda d: (
            d["delta_s"] is None,
            -abs(d["delta_s"]) if d["delta_s"] is not None else 0.0,
        )
    )

    ca = a.get("checkpoints") or []
    cb = b.get("checkpoints") or []
    first_div = None
    for ea, eb in zip(ca, cb):
        if (
            ea.get("label") != eb.get("label")
            or ea.get("round") != eb.get("round")
            or ea.get("digest") != eb.get("digest")
        ):
            first_div = {
                "seq": ea.get("seq"),
                "round": ea.get("round"),
                "label": ea.get("label"),
                "a_digest": ea.get("digest"),
                "b_digest": eb.get("digest"),
                "b_label": eb.get("label"),
            }
            break
    if first_div is None and len(ca) != len(cb):
        longer = ca if len(ca) > len(cb) else cb
        e = longer[min(len(ca), len(cb))]
        first_div = {
            "seq": e.get("seq"),
            "round": e.get("round"),
            "label": e.get("label"),
            "a_digest": e.get("digest") if len(ca) > len(cb) else None,
            "b_digest": e.get("digest") if len(cb) > len(ca) else None,
            "length_mismatch": [len(ca), len(cb)],
        }

    def _counters(r):
        return (r.get("metrics") or {}).get("counters") or {}

    na, nb = _counters(a), _counters(b)
    counter_deltas = {
        k: [na.get(k), nb.get(k)]
        for k in sorted(set(na) | set(nb))
        if na.get(k) != nb.get(k)
    }
    return {
        "wall_a_s": a.get("wall_s"),
        "wall_b_s": b.get("wall_s"),
        "span_deltas": deltas[:top],
        "first_checkpoint_divergence": first_div,
        "num_checkpoints": [len(ca), len(cb)],
        "counter_deltas": counter_deltas,
    }


def _percentile(sorted_vals: list[float], q: float) -> float | None:
    """Nearest-rank percentile over an already-sorted list (stdlib-only,
    deterministic; None on empty input)."""
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def slo_summary(reports: list[dict]) -> dict:
    """Aggregate the per-request SLO records of a proving-service report
    artifact: p50/p95 queue latency and prove wall, overall proofs/sec
    (served count over the submit-to-done span), per-placement and
    per-priority counts, cache hit rate. Lines without a `request`
    record (plain proves, bench reps) are ignored."""
    reqs = [r["request"] for r in reports
            if isinstance(r.get("request"), dict)]
    ok = [q for q in reqs if "error" not in q]
    lat = sorted(
        q["queue_latency_s"] for q in reqs
        if isinstance(q.get("queue_latency_s"), (int, float))
    )
    walls = sorted(
        q["prove_wall_s"] for q in ok
        if isinstance(q.get("prove_wall_s"), (int, float))
    )
    # the artifact's serving span: earliest request START (each line is
    # stamped at completion, so start = unix_ts - the recording wall) to
    # the last completion — anchoring at the first COMPLETION would drop
    # that request's entire service time and overstate proofs/sec by
    # N/(N-1)
    starts = []
    ends = []
    for r in reports:
        if not isinstance(r.get("request"), dict):
            continue
        ts = r.get("unix_ts")
        if not isinstance(ts, (int, float)):
            continue
        wall = r.get("wall_s")
        starts.append(ts - (wall if isinstance(wall, (int, float)) else 0))
        ends.append(ts)
    span_s = (max(ends) - min(starts)) if ends else None
    total_wall = sum(walls)
    placements: dict[str, int] = {}
    priorities: dict[str, int] = {}
    cache_hits = 0
    for q in reqs:
        placements[str(q.get("placement"))] = (
            placements.get(str(q.get("placement")), 0) + 1
        )
        priorities[str(q.get("priority"))] = (
            priorities.get(str(q.get("priority")), 0) + 1
        )
        if q.get("cache_hit"):
            cache_hits += 1

    def r6(v):
        return None if v is None else round(v, 6)

    # per-tenant axis (ISSUE 11): latency/wall percentiles per tenant id
    # over the request records, plus the gateway's rejected admissions
    # (tenant records with `rejected` set: 429 quota throttles and
    # load-sheds) — the fairness/quota numbers a multi-tenant deploy
    # watches
    tenants: dict[str, dict] = {}

    def _tslot(tid: str) -> dict:
        return tenants.setdefault(
            tid, {"requests": 0, "lat": [], "walls": [], "rejected": 0}
        )

    for q in reqs:
        slot = _tslot(str(q.get("tenant", "default")))
        slot["requests"] += 1
        if isinstance(q.get("queue_latency_s"), (int, float)):
            slot["lat"].append(q["queue_latency_s"])
        if "error" not in q and isinstance(
            q.get("prove_wall_s"), (int, float)
        ):
            slot["walls"].append(q["prove_wall_s"])
    shed = {"throttled": 0, "shed": 0}
    for r in reports:
        t = r.get("tenant")
        if not isinstance(t, dict) or not t.get("rejected"):
            continue
        _tslot(str(t.get("id", "default")))["rejected"] += 1
        reason = t.get("reason")
        if reason not in shed:
            # legacy/foreign lines without a reason: classify by code
            reason = "throttled" if t.get("rejected") == 429 else "shed"
        shed[reason] += 1
    tenant_summary = {
        tid: {
            "requests": s["requests"],
            "rejected": s["rejected"],
            "queue_latency_p95_s": r6(_percentile(sorted(s["lat"]), 0.95)),
            "prove_wall_p95_s": r6(_percentile(sorted(s["walls"]), 0.95)),
        }
        for tid, s in sorted(tenants.items())
    }

    # artifact-hit rate over the artifact's lines: every aot.hits /
    # aot.misses counter recorded anywhere in the stream (service warm
    # phases, bench warm-ups) — the deployment-health axis the AOT
    # bundle store adds
    aot_hits = aot_misses = 0
    resident_lines = 0
    for r in reports:
        c = (r.get("metrics") or {}).get("counters") or {}
        if isinstance(c, dict):
            h, m = c.get("aot.hits", 0), c.get("aot.misses", 0)
            # skip malformed values like every other field here — one
            # junk line must not kill the whole --slo summary
            aot_hits += h if isinstance(h, (int, float)) else 0
            aot_misses += m if isinstance(m, (int, float)) else 0
            rs = c.get("quotient.resident_coset_sweeps", 0)
            if isinstance(rs, (int, float)) and rs > 0:
                resident_lines += 1

    return {
        # which representation served: lines whose kernels dispatched
        # limb-RESIDENT (ISSUE 10) — BENCH/SLO deltas are attributable
        "limb_resident_lines": resident_lines,
        "requests": len(reqs),
        "served": len(ok),
        "failed": len(reqs) - len(ok),
        "queue_latency_p50_s": r6(_percentile(lat, 0.50)),
        "queue_latency_p95_s": r6(_percentile(lat, 0.95)),
        "prove_wall_p50_s": r6(_percentile(walls, 0.50)),
        "prove_wall_p95_s": r6(_percentile(walls, 0.95)),
        # proofs/sec over the serving span when the artifact covers more
        # than one completion; else the sequential-throughput bound
        "proofs_per_sec": r6(
            len(ok) / span_s if span_s and span_s > 0
            else (len(ok) / total_wall if total_wall > 0 else None)
        ),
        "placements": dict(sorted(placements.items())),
        "priorities": dict(sorted(priorities.items())),
        "cache_hit_rate": (
            round(cache_hits / len(reqs), 4) if reqs else None
        ),
        "tenants": tenant_summary,
        "rejected": shed,
        "aot_kernels_warmed": aot_hits + aot_misses,
        "aot_hit_rate": (
            round(aot_hits / (aot_hits + aot_misses), 4)
            if (aot_hits + aot_misses)
            else None
        ),
    }


def render_slo(summary: dict) -> str:
    lines = [
        f"service SLO: {summary['requests']} requests "
        f"({summary['served']} served, {summary['failed']} failed)",
        f"  queue latency p50={summary['queue_latency_p50_s']}s "
        f"p95={summary['queue_latency_p95_s']}s",
        f"  prove wall    p50={summary['prove_wall_p50_s']}s "
        f"p95={summary['prove_wall_p95_s']}s",
        f"  proofs/sec    {summary['proofs_per_sec']}",
        f"  cache hit rate {summary['cache_hit_rate']}",
    ]
    if summary.get("aot_kernels_warmed"):
        lines.append(
            f"  aot artifacts {summary['aot_hit_rate']} hit rate over "
            f"{summary['aot_kernels_warmed']} warmed kernels"
        )
    if summary.get("limb_resident_lines"):
        lines.append(
            f"  limb-resident {summary['limb_resident_lines']} lines "
            f"dispatched the resident kernel set"
        )
    if summary.get("placements"):
        lines.append(
            "  placements    "
            + ", ".join(
                f"{k}={v}" for k, v in summary["placements"].items()
            )
        )
    if summary.get("priorities"):
        lines.append(
            "  priorities    "
            + ", ".join(
                f"{k}={v}" for k, v in summary["priorities"].items()
            )
        )
    rejected = summary.get("rejected") or {}
    if any(rejected.values()):
        lines.append(
            f"  rejected      throttled(429)={rejected.get('throttled', 0)} "
            f"shed={rejected.get('shed', 0)}"
        )
    for tid, t in (summary.get("tenants") or {}).items():
        lines.append(
            f"  tenant {tid:<12} {t['requests']} requests, "
            f"queue p95={t['queue_latency_p95_s']}s "
            f"wall p95={t['prove_wall_p95_s']}s, "
            f"rejected={t['rejected']}"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------


def render_report(report: dict, top: int = 10) -> str:
    lines = []
    wall = report.get("wall_s") or 0.0
    lines.append(
        f"ProveReport schema={report.get('schema')} "
        f"label={report.get('label')!r} wall={wall:.3f}s "
        f"coverage={span_coverage(report) * 100:.1f}%"
    )
    spans = report.get("spans") or []

    def _emit(sp, depth):
        w = sp.get("wall_s") or 0.0
        pct = f"{100 * w / wall:5.1f}%" if wall else "     "
        extras = ""
        if sp.get("sync_s"):
            extras += f" sync={sp['sync_s']:.3f}s"
            if w:
                # occupancy: how much of the span the host spent BLOCKED
                # on the device (sync_s/wall) — the overlapped pipeline's
                # regression signal
                extras += f" occ={100 * sp['sync_s'] / w:.0f}%"
        if sp.get("overlap_s"):
            extras += f" ovl={sp['overlap_s']:.3f}s"
        attrs = sp.get("attrs")
        if isinstance(attrs, dict) and attrs.get("resident"):
            # the limb-residency flag (ISSUE 10): which representation
            # this span's kernels computed in, visible in the tree
            extras += " resident"
        if sp.get("error"):
            extras += f" ERROR={sp['error']!r}"
        lines.append(
            f"  {'  ' * depth}{sp.get('name'):<{max(4, 40 - 2 * depth)}}"
            f"{w:9.3f}s {pct}{extras}"
        )
        for c in sp.get("children", ()):
            _emit(c, depth + 1)

    for sp in spans:
        _emit(sp, 0)

    flat = [
        (path, sp.get("wall_s") or 0.0, sp.get("sync_s") or 0.0)
        for path, sp in flatten_spans(report)
        if not sp.get("children")
    ]
    flat.sort(key=lambda t: -t[1])
    if flat:
        lines.append(f"  top {min(top, len(flat))} leaf spans:")
        for path, w, s in flat[:top]:
            occ = f" sync={s:.3f}s occ={100 * s / w:.0f}%" if s and w else ""
            lines.append(f"    {w:9.3f}s{occ}  {path}")

    counters = (report.get("metrics") or {}).get("counters") or {}
    if counters:
        lines.append("  counters:")
        for k, v in counters.items():
            lines.append(f"    {k} = {v}")
    gauges = (report.get("metrics") or {}).get("gauges") or {}
    if gauges:
        lines.append("  gauges:")
        for k, v in gauges.items():
            lines.append(f"    {k} = {v}")
    ckpts = report.get("checkpoints") or []
    lines.append(f"  checkpoints: {len(ckpts)}")
    for e in ckpts:
        lines.append(
            f"    [{e.get('seq'):>3}] r{e.get('round')} "
            f"{e.get('label'):<28} {str(e.get('digest'))[:16]}…"
        )
    telemetry = report.get("telemetry")
    if isinstance(telemetry, dict):
        samples = telemetry.get("samples") or []
        keys = sorted(
            {k for s in samples if isinstance(s, dict) for k in s}
            - {"t_s"}
        )
        lines.append(
            f"  telemetry: {len(samples)} samples @ "
            f"{telemetry.get('interval_s')}s "
            f"({telemetry.get('ticks')} ticks) keys={keys}"
        )
    trace = report.get("trace")
    if isinstance(trace, dict):
        lines.append(f"  profiler trace: {trace.get('dir')}")
    request = report.get("request")
    if isinstance(request, dict):
        lines.append(
            f"  request: {request.get('id')} "
            f"[{request.get('priority')}/{request.get('tenant')}] "
            f"bucket={request.get('bucket')} "
            f"placement={request.get('placement')} "
            f"queue={request.get('queue_latency_s')}s "
            f"wall={request.get('prove_wall_s')}s "
            f"cache_hit={request.get('cache_hit')}"
        )
    ledger = report.get("compile_ledger")
    if ledger:
        lines.append(
            f"  compile ledger: {ledger.get('num_kernels')} kernels, "
            f"precompile {ledger.get('precompile_total_s')}s, "
            f"{ledger.get('num_dispatch_compiles')} dispatch compiles"
        )
        hits = ledger.get("aot_hits") or 0
        misses = ledger.get("aot_misses") or 0
        if hits + misses:
            lines.append(
                f"  aot artifacts: {hits}/{hits + misses} kernels "
                f"deserialized "
                f"({100 * hits / (hits + misses):.1f}% hit rate), "
                f"deserialize {ledger.get('aot_deserialize_s')}s"
            )
    return "\n".join(lines)


def render_diff(diff: dict) -> str:
    lines = [
        f"wall: {diff.get('wall_a_s')}s -> {diff.get('wall_b_s')}s",
        f"checkpoints: {diff['num_checkpoints'][0]} vs "
        f"{diff['num_checkpoints'][1]}",
    ]
    fd = diff.get("first_checkpoint_divergence")
    if fd is None:
        lines.append("digest checkpoints: IDENTICAL (no divergence)")
    else:
        lines.append(
            f"FIRST DIVERGING CHECKPOINT: seq={fd.get('seq')} "
            f"round={fd.get('round')} label={fd.get('label')!r}"
        )
        lines.append(
            f"  a={fd.get('a_digest')}\n  b={fd.get('b_digest')}"
        )
        if fd.get("length_mismatch"):
            lines.append(f"  (length mismatch: {fd['length_mismatch']})")
    lines.append("span wall deltas (top by |delta|):")
    for d in diff.get("span_deltas", ()):
        a = "-" if d["a_s"] is None else f"{d['a_s']:.3f}"
        b = "-" if d["b_s"] is None else f"{d['b_s']:.3f}"
        dl = "-" if d["delta_s"] is None else f"{d['delta_s']:+.3f}"
        lines.append(f"  {dl:>10}s  {a:>9} -> {b:<9}  {d['span']}")
    if diff.get("counter_deltas"):
        lines.append("counter deltas:")
        for k, (a, b) in diff["counter_deltas"].items():
            lines.append(f"  {k}: {a} -> {b}")
    return "\n".join(lines)


def default_report_path() -> str | None:
    """The BOOJUM_TPU_REPORT env target (None = reporting off)."""
    p = os.environ.get("BOOJUM_TPU_REPORT")
    return p or None
