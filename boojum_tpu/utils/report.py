"""ProveReport: the flight recorder's versioned JSONL artifact.

One report line per prove:
  {kind, schema, label, unix_ts, wall_s,
   spans:      [hierarchical span tree, utils/spans.py],
   metrics:    {counters, gauges, boundaries}, (utils/metrics.py),
   checkpoints:[{seq, round, label, digest}, ...]  — Fiat–Shamir state,
   compile_ledger: summary (when a CompileLedger is installed),
   host:       {platform, process_index}}

Transcript DIGEST CHECKPOINTS are the parity-triage axis: at every
Fiat–Shamir round the prover records blake2s(canonical LE64 encoding) of
what crossed the transcript — per-stage Merkle caps, drawn challenges, FRI
fold challenges, final monomials, query indices. Two proves of the same
witness produce byte-identical checkpoint streams; a bit-parity break
against compat/prove_reference.py (or a past report) localizes to the
FIRST diverging (round, label) instead of the final proof blob.

This module is intentionally stdlib-only at import time: the report CLI
(scripts/prove_report.py) loads it standalone — without importing
boojum_tpu (and therefore jax) — for render/diff/check of existing
artifacts. The recording entry points import spans/metrics lazily.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import json
import os
import re
import time

REPORT_KIND = "boojum_tpu.prove_report"
# schema 2 (ISSUE 9): lines may carry a `telemetry` record (background
# sampler time series, utils/telemetry.py) and a `trace` record (an
# on-demand jax.profiler capture attributable to the line); schema 3
# (ISSUE 12): lines may carry a `cost` record (utils/costmodel.py —
# per-stage analytic flops/bytes joined with measured walls into
# achieved GFLOP/s & GB/s, roofline regime and efficiency-vs-peak);
# schema 4 (ISSUE 17): every line carries a `trace_ctx` record
# ({"trace_id": 32-hex, "parent_span_id"?: 16-hex}) and every span a
# `span_id` (utils/spans.py) — the distributed-tracing plane
# `prove_report.py --timeline` stitches on. Older-schema lines remain
# valid for --check/--diff.
REPORT_SCHEMA = 4
ACCEPTED_SCHEMAS = (1, 2, 3, 4)

# id formats (BASELINE.md "Trace protocol"). Re-declared here rather
# than imported from utils/spans.py because report.py must stay
# loadable standalone (scripts/prove_report.py file-loads it with no
# package, no jax).
TRACE_ID_RE = re.compile(r"^[0-9a-f]{32}$")
SPAN_ID_RE = re.compile(r"^[0-9a-f]{16}$")

# field backends (field/spec.py SPECS keys, ISSUE 19). Re-declared for
# the same standalone-load reason as the id formats above.
FIELD_NAMES = ("goldilocks", "babybear")

# black-box forensics records (utils/blackbox.py): heartbeat/dump lines
# interleave with prove lines in the same JSONL artifact; fleet records
# are what `prove_report.py --fleet` emits from per-host artifacts.
# --check routes every line by kind (validate_line)
BLACKBOX_KIND = "boojum_tpu.blackbox"
BLACKBOX_SCHEMAS = (1,)
FLEET_KIND = "boojum_tpu.fleet"
FLEET_SCHEMAS = (1,)

# canonical Fiat–Shamir round order; validation checks checkpoint rounds
# never decrease along the stream
ROUND_ORDER = (0, 1, 2, 3, 4, 5)

# the proving service's placements (service/scheduler.py) — a request
# record carrying anything else fails validation
REQUEST_PLACEMENTS = ("shard_parallel", "proof_parallel")
# fields every per-request SLO record must carry (service/service.py);
# prove_wall_s is additionally required unless the record carries an
# error (a failed request may die before its wall is measured)
REQUEST_REQUIRED = ("id", "bucket", "placement", "queue_latency_s")


def _flatten_ints(values):
    out = []
    stack = [values]
    while stack:
        v = stack.pop()
        if isinstance(v, (list, tuple)):
            stack.extend(reversed(v))
        else:
            out.append(int(v))
    return out


def digest_of(values) -> str:
    """blake2s over the 8-byte little-endian words of the (possibly
    nested) integer sequence — the canonical checkpoint digest."""
    h = hashlib.blake2s()
    for v in _flatten_ints(values):
        h.update((v & ((1 << 64) - 1)).to_bytes(8, "little"))
    return h.hexdigest()


class CheckpointLog:
    def __init__(self):
        self.entries: list[dict] = []

    def add(self, round_: int, label: str, values):
        self.entries.append(
            {
                "seq": len(self.entries),
                "round": int(round_),
                "label": label,
                "digest": digest_of(values),
            }
        )


# process-global DEFAULT context; scoped logs (install_scoped_* /
# flight_recording(scoped=True)) override per execution context so
# packed concurrent proves keep disjoint checkpoint streams
_CHECKPOINTS: CheckpointLog | None = None
_CHECKPOINTS_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "boojum_tpu.checkpoint_log", default=None
)


def current_checkpoint_log() -> CheckpointLog | None:
    log = _CHECKPOINTS_CTX.get()
    return log if log is not None else _CHECKPOINTS


def install_checkpoint_log(log: CheckpointLog | None):
    """Swap the process-wide DEFAULT checkpoint log; returns the
    previous one."""
    global _CHECKPOINTS
    prev = _CHECKPOINTS
    _CHECKPOINTS = log
    return prev


def install_scoped_checkpoint_log(log: CheckpointLog | None):
    """Bind `log` to the CURRENT execution context only; returns a token
    for reset_scoped_checkpoint_log."""
    return _CHECKPOINTS_CTX.set(log)


def reset_scoped_checkpoint_log(token):
    _CHECKPOINTS_CTX.reset(token)


def checkpoint(round_: int, label: str, values):
    """Record one Fiat–Shamir digest checkpoint; no-op-cheap (one
    contextvar read, one global read) when nothing is recording."""
    log = current_checkpoint_log()
    if log is not None:
        log.add(round_, label, values)
        # a new transcript digest is forward motion — reset the
        # blackbox stall clock (utils/blackbox.py); only on the
        # recording path, the no-op path stays two reads
        from . import blackbox as _bb

        _bb.tick()


# ---------------------------------------------------------------------------
# Flight recording: spans + metrics + checkpoints as one unit
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bundles the three collectors for one recorded prove."""

    def __init__(self, label: str | None = None, sync: bool = True):
        from . import metrics as _metrics
        from . import spans as _spans

        self.label = label
        self.spans = _spans.SpanRecorder(sync=sync)
        self.metrics = _metrics.MetricsRegistry()
        self.checkpoints = CheckpointLog()
        self._t0 = time.perf_counter()
        self.wall_s: float | None = None
        # an on-demand jax.profiler capture directory for this recorded
        # window (profiling.maybe_trace_capture) — lands in the report
        # line's `trace` record so the trace is attributable
        self.trace_dir: str | None = None
        # the roofline cost record (utils/costmodel.attach_cost_record
        # stamps it at the end of a successful prove) — lands as the
        # line's schema-3 `cost` record
        self.cost: dict | None = None

    def close(self):
        if self.wall_s is None:
            self.wall_s = round(time.perf_counter() - self._t0, 6)


_FLIGHT: FlightRecorder | None = None
_FLIGHT_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "boojum_tpu.flight_recorder", default=None
)


def current_flight_recorder() -> FlightRecorder | None:
    rec = _FLIGHT_CTX.get()
    return rec if rec is not None else _FLIGHT


@contextlib.contextmanager
def flight_recording(
    label: str | None = None, sync: bool = True, scoped: bool = False
):
    """Install a FlightRecorder (spans + metrics + checkpoints) for the
    duration of the block; restores whatever was installed before.

    `scoped=True` binds the collectors to the CURRENT execution context
    via contextvars instead of swapping the process-global defaults —
    the packed proving-service posture, where several requests record
    concurrently on pool threads without corrupting each other's spans,
    counters or checkpoint streams. The default (scoped=False) keeps the
    process-global swap bench/CLI flows rely on: threads they spawn
    mid-recording (the precompile pool) still see the recorder."""
    global _FLIGHT
    from . import metrics as _metrics
    from . import spans as _spans

    rec = FlightRecorder(label=label, sync=sync)
    if scoped:
        tok_flight = _FLIGHT_CTX.set(rec)
        tok_spans = _spans.install_scoped_recorder(rec.spans)
        tok_metrics = _metrics.install_scoped_registry(rec.metrics)
        tok_ckpt = install_scoped_checkpoint_log(rec.checkpoints)
        try:
            yield rec
        finally:
            rec.close()
            _spans.reset_scoped_recorder(tok_spans)
            _metrics.reset_scoped_registry(tok_metrics)
            reset_scoped_checkpoint_log(tok_ckpt)
            _FLIGHT_CTX.reset(tok_flight)
        return
    prev_flight = _FLIGHT
    _FLIGHT = rec
    prev_spans = _spans.install_recorder(rec.spans)
    prev_metrics = _metrics.install_registry(rec.metrics)
    prev_ckpt = install_checkpoint_log(rec.checkpoints)
    try:
        yield rec
    finally:
        rec.close()
        _spans.install_recorder(prev_spans)
        _metrics.install_registry(prev_metrics)
        install_checkpoint_log(prev_ckpt)
        _FLIGHT = prev_flight


def build_report(rec: FlightRecorder, extra: dict | None = None) -> dict:
    rec.close()
    d: dict = {
        "kind": REPORT_KIND,
        "schema": REPORT_SCHEMA,
        "label": rec.label,
        "unix_ts": round(time.time(), 3),
        "wall_s": rec.wall_s,
        "spans": rec.spans.tree(),
        "metrics": rec.metrics.to_dict(),
        "checkpoints": list(rec.checkpoints.entries),
    }
    # trace context (schema 4): the recorder's Dapper-style identity —
    # adopted from the gateway/spool/env when this line serves a
    # propagated trace, freshly minted otherwise. Either way every line
    # is stitchable; `--check` fails a gateway line without it.
    tid = getattr(rec.spans, "trace_id", None)
    if isinstance(tid, str) and TRACE_ID_RE.match(tid):
        tctx = {"trace_id": tid}
        psid = getattr(rec.spans, "parent_span_id", None)
        if isinstance(psid, str) and SPAN_ID_RE.match(psid):
            tctx["parent_span_id"] = psid
        d["trace_ctx"] = tctx
    if rec.trace_dir:
        d["trace"] = {"dir": rec.trace_dir}
    if getattr(rec, "cost", None):
        d["cost"] = rec.cost
    try:
        # the live telemetry plane's time series (utils/telemetry.py):
        # when a sampler is running, every report line carries the
        # service-wide memory/queue/in-flight samples that overlapped it
        from . import telemetry as _telemetry

        sampler = _telemetry.current_sampler()
        if sampler is not None:
            d["telemetry"] = sampler.snapshot()
    except Exception:
        pass
    try:
        from .profiling import current_compile_ledger

        ledger = current_compile_ledger()
        if ledger is not None:
            d["compile_ledger"] = ledger.summary()
    except Exception:
        pass
    try:
        import jax

        # the SAME identity block bench/bench_micro stamp — the five
        # fields _trend_identity groups gated series by, so report
        # artifacts from two machines never share a series
        from ..prover.aot import platform_info

        d["host"] = dict(
            platform_info(),
            platform=jax.default_backend(),
            process_index=jax.process_index(),
        )
    except Exception:
        pass
    if extra:
        d.update(extra)
    return d


def append_jsonl(path: str, report: dict):
    line = json.dumps(report, separators=(",", ":"))
    with open(path, "a") as f:
        f.write(line + "\n")


def load_reports(path: str) -> list[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def load_report(path: str, index: int = -1) -> dict:
    reports = load_reports(path)
    if not reports:
        raise ValueError(f"{path}: no report lines")
    return reports[index]


# ---------------------------------------------------------------------------
# Validation / analysis (pure dict functions — usable standalone)
# ---------------------------------------------------------------------------


def _walk_spans(spans, prefix=()):
    """Yield (path_tuple, span) depth-first."""
    for sp in spans:
        path = prefix + (sp.get("name", "?"),)
        yield path, sp
        yield from _walk_spans(sp.get("children", ()), path)


def flatten_spans(report: dict) -> list[tuple[str, dict]]:
    return [
        ("/".join(path), sp)
        for path, sp in _walk_spans(report.get("spans", ()))
    ]


# the prover's stage spans (prover._StageClock start calls) — the
# canonical per-round series both the roofline record
# (utils/costmodel.STAGE_NAMES aliases this) and the trend gate key on.
# Cache-state spans that also land under `prove` (aot_load, aot_warm,
# overlap_prefetch) are deliberately NOT stages: gating them would fail
# CI on artifact-store temperature, not prover performance.
PROVE_STAGES = (
    "round1_witness_commit",
    "round2_stage2_commit",
    "round3_quotient",
    "round4_evaluations",
    "round5_deep_fri",
    "queries",
)

# the cache-state spans themselves, for the places that must SUBTRACT
# them (the trend total_wall point) rather than merely not enumerate
# them (stage series)
CACHE_STATE_SPANS = ("aot_load", "aot_warm", "overlap_prefetch")


def _prove_root(spans):
    """The span a line's stage/coverage numbers describe: the `prove`
    span found ANYWHERE in the tree (the proving service nests it under
    its `service_request` root), first root as fallback. The LAST
    matching `prove` span wins: a long-lived bench/CLI recorder can
    hold several proves, and the numbers must come from the prove that
    just finished, not the first one."""
    spans = spans or []
    root = None
    for _path, sp in _walk_spans(spans):
        if sp.get("name") == "prove":
            root = sp
    if root is None and spans:
        root = spans[0]
    return root


def stage_walls(spans, names=None) -> dict:
    """{stage_name: summed wall_s} over the DIRECT children of the
    prove root (_prove_root). The one span-tree extraction both the
    roofline record (utils/costmodel.py) and the trend series share, so
    the perf gate and the cost record can never disagree about what a
    stage's wall is. `names` filters to a known stage set; None takes
    every child."""
    root = _prove_root(spans)
    walls: dict = {}
    for c in (root or {}).get("children", ()):
        nm = c.get("name")
        w = c.get("wall_s")
        if names is not None and nm not in names:
            continue
        if isinstance(w, (int, float)):
            walls[nm] = walls.get(nm, 0.0) + float(w)
    return walls


def span_coverage(report: dict) -> float:
    """Fraction of the prove root's wall covered by its direct children
    (the stage spans) — the SAME root selection as stage_walls, so one
    line's coverage and stage numbers always describe the same prove.
    0.0 when there is no usable tree."""
    root = _prove_root(report.get("spans") or [])
    if not root or not root.get("wall_s"):
        return 0.0
    covered = sum(
        c.get("wall_s") or 0.0 for c in root.get("children", ())
    )
    return min(1.0, covered / root["wall_s"])


def validate_report(report: dict) -> list[str]:
    """Schema + monotonicity checks; returns a list of problems (empty =
    valid). This is the `prove_report.py --check` gate."""
    problems: list[str] = []
    if report.get("kind") != REPORT_KIND:
        problems.append(f"kind is {report.get('kind')!r}, want {REPORT_KIND!r}")
    if report.get("schema") not in ACCEPTED_SCHEMAS:
        problems.append(
            f"schema is {report.get('schema')!r}, want one of "
            f"{ACCEPTED_SCHEMAS}"
        )
    wall = report.get("wall_s")
    if not isinstance(wall, (int, float)) or wall < 0:
        problems.append(f"wall_s invalid: {wall!r}")
    # context-scoped recording invariant (ISSUE 9): one report line is
    # ONE request's flight data. Span attrs carrying two distinct
    # request ids on a single line mean a scoped collector bled across
    # packed requests — the corruption mode the contextvar scoping
    # exists to prevent, so it must fail the gate loudly.
    span_request_ids = set()
    line_span_ids: dict = {}
    for path, sp in _walk_spans(report.get("spans", ())):
        attrs = sp.get("attrs")
        if isinstance(attrs, dict) and attrs.get("request") is not None:
            span_request_ids.add(str(attrs["request"]))
        w = sp.get("wall_s")
        if not isinstance(w, (int, float)) or w < 0:
            problems.append(f"span {'/'.join(path)}: wall_s invalid: {w!r}")
        st = sp.get("start_s")
        # a `backdated` span (queue.wait — utils/spans.py) legitimately
        # starts before its recorder's t0, i.e. at a negative offset
        if not isinstance(st, (int, float)) or (
            st < 0 and not sp.get("backdated")
        ):
            problems.append(f"span {'/'.join(path)}: start_s invalid: {st!r}")
        # span identity (schema 4): ids must be well-formed and unique
        # within the line — a collision means two spans would stitch
        # into the same timeline node
        sid = sp.get("span_id")
        if sid is not None or (
            isinstance(report.get("schema"), int) and report["schema"] >= 4
        ):
            if not (isinstance(sid, str) and SPAN_ID_RE.match(sid)):
                problems.append(
                    f"span {'/'.join(path)}: span_id malformed: {sid!r}"
                )
            elif sid in line_span_ids:
                problems.append(
                    f"span {'/'.join(path)}: span_id {sid} collides with "
                    f"span {line_span_ids[sid]}"
                )
            else:
                line_span_ids[sid] = "/".join(path)
        psid = sp.get("parent_span_id")
        if psid is not None and not (
            isinstance(psid, str) and SPAN_ID_RE.match(psid)
        ):
            problems.append(
                f"span {'/'.join(path)}: parent_span_id malformed: {psid!r}"
            )
        stid = sp.get("trace_id")
        if stid is not None and not (
            isinstance(stid, str) and TRACE_ID_RE.match(stid)
        ):
            problems.append(
                f"span {'/'.join(path)}: trace_id malformed: {stid!r}"
            )
        for c in sp.get("children", ()):
            cst = c.get("start_s")
            if (
                isinstance(cst, (int, float))
                and isinstance(st, (int, float))
                and cst + 1e-6 < st
            ):
                problems.append(
                    f"span {'/'.join(path)}: child {c.get('name')!r} starts "
                    f"before its parent"
                )
    ckpts = report.get("checkpoints")
    if not isinstance(ckpts, list):
        problems.append("checkpoints missing")
        ckpts = []
    last_seq = -1
    last_round = -1
    seen_labels = set()
    for e in ckpts:
        seq, rnd, label = e.get("seq"), e.get("round"), e.get("label")
        dg = e.get("digest")
        if not isinstance(seq, int) or seq <= last_seq:
            problems.append(f"checkpoint {label!r}: seq {seq!r} not increasing")
        else:
            last_seq = seq
        if not isinstance(rnd, int) or rnd < last_round:
            problems.append(
                f"checkpoint {label!r}: round {rnd!r} decreases "
                f"(after round {last_round})"
            )
        else:
            last_round = rnd
        if (rnd, label) in seen_labels:
            problems.append(f"checkpoint {label!r}: duplicate in round {rnd}")
        seen_labels.add((rnd, label))
        if not (
            isinstance(dg, str)
            and len(dg) == 64
            and all(c in "0123456789abcdef" for c in dg)
        ):
            problems.append(f"checkpoint {label!r}: digest malformed: {dg!r}")
    metrics = report.get("metrics")
    if not isinstance(metrics, dict) or "counters" not in metrics:
        problems.append("metrics missing or malformed")
    else:
        # ici.* — the explicit mesh collectives' bill (ISSUE 5). Every
        # gauge must be a finite non-negative number, and a nonzero
        # collective COUNTER must come with its byte gauge (and the pivot
        # timer for all_to_alls): a pivot that moved zero bytes means the
        # accounting seam in parallel/shard_sweep.py was bypassed.
        counters = metrics.get("counters")
        if not isinstance(counters, dict):
            if counters is not None:
                problems.append(
                    "metrics.counters malformed: "
                    f"{type(counters).__name__}"
                )
            counters = {}
        gauges = metrics.get("gauges")
        if not isinstance(gauges, dict):
            if gauges is not None:
                problems.append(
                    f"metrics.gauges malformed: {type(gauges).__name__}"
                )
            gauges = {}

        def _num(v):
            # non-numerics were flagged above; compare as 0 so one bad
            # value yields its problem line instead of a TypeError
            return v if isinstance(v, (int, float)) and v == v else 0

        for k, v in gauges.items():
            if not (k.startswith("ici.") or k.startswith("dcn.")):
                continue
            if not isinstance(v, (int, float)) or v != v or v < 0:
                problems.append(f"gauge {k}: invalid value {v!r}")
        # a collective's crossing bytes may split intra-host (ici.*) vs
        # cross-process (dcn.*) on a multi-host mesh — a counted
        # collective must have moved bytes on at least one fabric
        if _num(counters.get("ici.all_to_alls", 0)) > 0:
            if not (
                _num(gauges.get("ici.all_to_all_bytes", 0))
                + _num(gauges.get("dcn.all_to_all_bytes", 0))
            ) > 0:
                problems.append(
                    "ici.all_to_alls counted but the ici.all_to_all_bytes "
                    "+ dcn.all_to_all_bytes gauges are missing/zero"
                )
            if "ici.pivot_s" not in gauges:
                problems.append(
                    "ici.all_to_alls counted but ici.pivot_s gauge missing"
                )
        if _num(counters.get("ici.all_gathers", 0)) > 0 and not (
            _num(gauges.get("ici.all_gather_bytes", 0))
            + _num(gauges.get("dcn.all_gather_bytes", 0))
        ) > 0:
            problems.append(
                "ici.all_gathers counted but the ici.all_gather_bytes "
                "+ dcn.all_gather_bytes gauges are missing/zero"
            )
        # dcn.* counters carry the same counted-but-zero-bytes invariant
        for fam, gname in (
            ("dcn.all_to_alls", "dcn.all_to_all_bytes"),
            ("dcn.all_gathers", "dcn.all_gather_bytes"),
            ("dcn.host_gathers", "dcn.host_gather_bytes"),
        ):
            if _num(counters.get(fam, 0)) > 0 and not _num(
                gauges.get(gname, 0)
            ) > 0:
                problems.append(
                    f"{fam} counted but {gname} gauge is missing/zero"
                )
        # service.* — the proving service's queue/cache/SLO axis. Every
        # value must be a finite non-negative number, and evictions must
        # carry their byte gauge (an eviction that freed zero bytes means
        # the cache manager's accounting seam was bypassed).
        for src in (counters, gauges):
            for k, v in src.items():
                if not k.startswith("service."):
                    continue
                if not isinstance(v, (int, float)) or v != v or v < 0:
                    problems.append(
                        f"service metric {k}: invalid value {v!r}"
                    )
        if _num(counters.get("service.cache.evictions", 0)) > 0 and not _num(
            gauges.get("service.cache.evicted_bytes", 0)
        ) > 0:
            problems.append(
                "service.cache.evictions counted but "
                "service.cache.evicted_bytes gauge is missing/zero"
            )
        # aot.* — the AOT artifact store's axis (prover/aot.py). Every
        # value must be a finite non-negative number; warmed kernels
        # (hits+misses > 0) must carry the deserialize-time gauge; and a
        # line claiming every kernel was an artifact hit while its
        # compile ledger still counted cache misses (real compiles) is
        # LYING about its warm-up bill and must fail the gate.
        for src in (counters, gauges):
            for k, v in src.items():
                if not k.startswith("aot."):
                    continue
                if not isinstance(v, (int, float)) or v != v or v < 0:
                    problems.append(f"aot metric {k}: invalid value {v!r}")
        aot_hits = _num(counters.get("aot.hits", 0))
        aot_misses = _num(counters.get("aot.misses", 0))
        if (aot_hits + aot_misses) > 0 and "aot.deserialize_s" not in gauges:
            problems.append(
                "aot.hits/aot.misses counted but aot.deserialize_s "
                "gauge missing"
            )
        # the aot_hit-vs-compile cross-check compares LEDGER fields with
        # LEDGER fields (both process-cumulative): a line whose ledger
        # claims every warmed kernel deserialized from an artifact
        # (aot_hits > 0, aot_misses == 0) while the same ledger counted
        # persistent-cache misses means real compiles escaped the
        # artifact store — the zero-compile claim is false
        ledger = report.get("compile_ledger")
        if isinstance(ledger, dict):
            ledger_hits = _num(ledger.get("aot_hits", 0))
            ledger_misses = _num(ledger.get("aot_misses", 0))
            num_kernels = _num(ledger.get("num_kernels", 0))
            # fires only when the ledger claims FULL aot coverage —
            # every recorded kernel an artifact hit. A mixed-bucket
            # process (bucket A bundled, bucket B precompiled normally)
            # has num_kernels > aot_hits and is a supported state, not
            # a lie.
            if (
                ledger_hits > 0
                and ledger_misses == 0
                and ledger_hits == num_kernels
            ):
                compiles = _num(ledger.get("cache_misses", 0))
                if compiles > 0:
                    problems.append(
                        f"prove claims all-aot_hit kernels but the "
                        f"compile ledger records {int(compiles)} cache "
                        f"misses (real compiles escaped the artifact "
                        f"store)"
                    )
        # limb.* — the u64<->limb conversion tax (ISSUE 10). Counters
        # must be finite non-negative ints, and a line whose kernels
        # claim LIMB-RESIDENT dispatch (quotient.resident_coset_sweeps /
        # fri.resident_folds) while counting INTERIOR splits/joins is
        # lying about residency — the whole point of the resident mode
        # is that those are zero (edges are allowlisted under
        # limb.edge_*/limb.host_*).
        for k, v in counters.items():
            if not k.startswith("limb."):
                continue
            if not isinstance(v, int) or v < 0:
                problems.append(f"limb metric {k}: invalid value {v!r}")
        resident_claimed = (
            _num(counters.get("quotient.resident_coset_sweeps", 0)) > 0
            or _num(counters.get("fri.resident_folds", 0)) > 0
        )
        if resident_claimed:
            for k in ("limb.splits", "limb.joins"):
                if _num(counters.get(k, 0)) > 0:
                    problems.append(
                        f"resident-mode prove counted interior {k} = "
                        f"{counters.get(k)} (conversions must survive "
                        f"only at allowlisted edges)"
                    )
    # per-request SLO record (proving-service lines): the record the
    # --slo summary and dashboards key on — a request line missing its
    # queue latency or placement is unusable for SLO accounting and
    # must fail the --check gate
    request = report.get("request")
    if request is not None:
        if not isinstance(request, dict):
            problems.append(
                f"request record malformed: {type(request).__name__}"
            )
        else:
            for k in REQUEST_REQUIRED:
                if k not in request:
                    problems.append(f"request record missing {k!r}")
            ql = request.get("queue_latency_s")
            if "queue_latency_s" in request and (
                not isinstance(ql, (int, float)) or ql != ql or ql < 0
            ):
                problems.append(
                    f"request queue_latency_s invalid: {ql!r}"
                )
            pl = request.get("placement")
            if "placement" in request and pl not in REQUEST_PLACEMENTS:
                problems.append(
                    f"request placement {pl!r}: want one of "
                    f"{REQUEST_PLACEMENTS}"
                )
            pw = request.get("prove_wall_s")
            if "error" not in request and (
                not isinstance(pw, (int, float)) or pw != pw or pw < 0
            ):
                problems.append(
                    f"request prove_wall_s invalid: {pw!r}"
                )
            rf = request.get("field")
            if rf is not None and rf not in FIELD_NAMES:
                problems.append(
                    f"request field {rf!r}: want one of "
                    f"{sorted(FIELD_NAMES)}"
                )
            if request.get("id") is not None:
                span_request_ids.add(str(request["id"]))
    # per-tenant record (gateway lines, ISSUE 11): quota charges must be
    # sane non-negative numbers, a gateway-ADMITTED request line must
    # carry the record at all (the quota axis is the whole point of
    # admitting through the front door), and a REJECTED line (429 /
    # load-shed) must never claim a prove wall — nothing was proved.
    tenant = report.get("tenant")
    if tenant is not None:
        if not isinstance(tenant, dict):
            problems.append(
                f"tenant record malformed: {type(tenant).__name__}"
            )
            tenant = None
        else:
            tid = tenant.get("id")
            if not isinstance(tid, str) or not tid:
                problems.append(f"tenant record id invalid: {tid!r}")
            for k in (
                "charged_bytes", "charged_compute_s",
                "window_used_bytes", "window_used_compute_s",
                "retry_after_s",
            ):
                if k not in tenant:
                    continue
                v = tenant.get(k)
                if not isinstance(v, (int, float)) or v != v or v < 0:
                    problems.append(f"tenant {k} invalid: {v!r}")
            if tenant.get("rejected"):
                pw = (
                    request.get("prove_wall_s")
                    if isinstance(request, dict) else None
                )
                if isinstance(pw, (int, float)):
                    problems.append(
                        "rejected admission carries prove_wall_s "
                        f"({pw!r}): a 429/shed line must never prove"
                    )
    if (
        isinstance(request, dict)
        and request.get("gateway")
        and tenant is None
    ):
        problems.append(
            "gateway-admitted request line missing its tenant record"
        )
    if len(span_request_ids) > 1:
        problems.append(
            "line mixes request ids "
            f"{sorted(span_request_ids)}: scoped collectors bled "
            "across packed requests"
        )
    # trace context (schema 4, ISSUE 17): when present it must be
    # well-formed, and a GATEWAY line (an admitted request or a
    # gateway-authored reject/spool line) must carry it at all — an
    # orphan gateway trace defeats the entire propagation chain, so it
    # fails the gate rather than silently dropping off timelines.
    tctx = report.get("trace_ctx")
    if tctx is not None:
        if not isinstance(tctx, dict):
            problems.append(
                f"trace_ctx malformed: {type(tctx).__name__}"
            )
        else:
            tid = tctx.get("trace_id")
            if not (isinstance(tid, str) and TRACE_ID_RE.match(tid)):
                problems.append(f"trace_ctx trace_id malformed: {tid!r}")
            psid = tctx.get("parent_span_id")
            if psid is not None and not (
                isinstance(psid, str) and SPAN_ID_RE.match(psid)
            ):
                problems.append(
                    f"trace_ctx parent_span_id malformed: {psid!r}"
                )
    is_gateway_line = bool(
        (isinstance(request, dict) and request.get("gateway"))
        or str(report.get("label") or "").startswith("gateway")
    )
    if (
        tctx is None
        and is_gateway_line
        and isinstance(report.get("schema"), int)
        and report["schema"] >= 4
    ):
        problems.append(
            "gateway line missing trace_ctx: the admission that minted "
            "the trace failed to propagate it"
        )
    # telemetry record (schema 2, utils/telemetry.py): the background
    # sampler's time series. Samples must be time-ordered with finite
    # non-negative readings — a sampler writing junk would poison every
    # dashboard fed from these lines.
    telemetry = report.get("telemetry")
    if telemetry is not None:
        problems.extend(_validate_telemetry(telemetry))
    # cost record (schema 3, utils/costmodel.py): the roofline numbers
    # dashboards and the trend gate key on. A record claiming an
    # efficiency over a zero/absent denominator (no wall, no positive
    # peak) or attributing kernels the compile ledger never recorded is
    # fabricating attribution and must fail the gate.
    cost = report.get("cost")
    if cost is not None:
        problems.extend(
            _validate_cost(cost, report.get("compile_ledger"))
        )
        # field-claim cross-check (ISSUE 19): the BabyBear backend's
        # whole value is ONE u32 lane per element end-to-end — a line
        # whose cost record claims field=babybear while the same line's
        # counters record interior limb-plane conversions is running
        # Goldilocks plumbing under a BabyBear label and must fail the
        # gate (the limb.* counters only ever move on the (lo, hi)
        # plane paths).
        if isinstance(cost, dict) and cost.get("field") == "babybear":
            m = report.get("metrics")
            counters = (
                m.get("counters")
                if isinstance(m, dict)
                and isinstance(m.get("counters"), dict)
                else {}
            )
            for k in ("limb.splits", "limb.joins"):
                v = counters.get(k, 0)
                if isinstance(v, (int, float)) and v > 0:
                    problems.append(
                        f"cost record claims field=babybear but the "
                        f"line counted {k} = {counters.get(k)} (limb "
                        f"conversions are a Goldilocks-plane artifact "
                        f"— the babybear path must never touch them)"
                    )
    trace = report.get("trace")
    if trace is not None and not (
        isinstance(trace, dict) and isinstance(trace.get("dir"), str)
        and trace["dir"]
    ):
        problems.append(f"trace record malformed: {trace!r}")
    return problems


def _validate_cost(cost, ledger) -> list[str]:
    if not isinstance(cost, dict):
        return [f"cost record malformed: {type(cost).__name__}"]
    problems: list[str] = []
    device = cost.get("device")
    if not isinstance(device, dict):
        problems.append("cost record missing device peaks")
        device = {}

    def _bad(v):
        return not isinstance(v, (int, float)) or v != v

    field = cost.get("field")
    if field is not None and field not in FIELD_NAMES:
        problems.append(
            f"cost record field {field!r}: want one of "
            f"{sorted(FIELD_NAMES)}"
        )
    stages = cost.get("stages")
    if not isinstance(stages, dict) or not stages:
        problems.append("cost record has no stages")
        stages = {}
    entries = dict(stages)
    if isinstance(cost.get("total"), dict):
        entries["total"] = cost["total"]
    peak_by_regime = {
        "compute": device.get("peak_gflops"),
        "memory": device.get("peak_hbm_gbps"),
    }
    for name, st in entries.items():
        if not isinstance(st, dict):
            problems.append(f"cost stage {name}: not a dict")
            continue
        for k in ("flops", "hbm_bytes", "ici_bytes", "dcn_bytes"):
            v = st.get(k)
            if v is not None and (_bad(v) or v < 0):
                problems.append(f"cost stage {name}: {k} invalid: {v!r}")
        wall = st.get("wall_s")
        if wall is not None and (_bad(wall) or wall < 0):
            problems.append(f"cost stage {name}: wall_s invalid: {wall!r}")
        claimed = [
            k for k in ("achieved_gflops", "achieved_gbps", "efficiency")
            if st.get(k) is not None
        ]
        if claimed and not (
            isinstance(wall, (int, float)) and wall == wall and wall > 0
        ):
            problems.append(
                f"cost stage {name}: {claimed[0]} claimed over a "
                f"zero/absent wall (denominator) — wall_s={wall!r}"
            )
        for k in claimed:
            v = st.get(k)
            if _bad(v) or v < 0:
                problems.append(f"cost stage {name}: {k} invalid: {v!r}")
        eff = st.get("efficiency")
        if eff is not None:
            regime = st.get("regime")
            peak = peak_by_regime.get(regime)
            if not (
                isinstance(peak, (int, float)) and peak == peak and peak > 0
            ):
                problems.append(
                    f"cost stage {name}: efficiency claimed against a "
                    f"zero/absent {regime!r} peak (denominator) — "
                    f"device={peak!r}"
                )
    # the attribution cross-check: a cost record may only claim XLA
    # actuals for kernels the compile ledger actually recorded (the
    # `kernels` list is the analytic sheet's coverage — informational;
    # `attributed_kernels` is the evidence claim). Older ledgers without
    # a kernel-name set skip the check.
    kernels = cost.get("attributed_kernels")
    if kernels is not None and not isinstance(kernels, list):
        problems.append(
            f"cost attributed_kernels malformed: {type(kernels).__name__}"
        )
        kernels = None
    ledger_names = (
        ledger.get("kernel_names") if isinstance(ledger, dict) else None
    )
    if isinstance(kernels, list) and isinstance(ledger_names, list):
        alien = sorted(set(map(str, kernels)) - set(map(str, ledger_names)))
        if alien:
            problems.append(
                f"cost record claims XLA actuals for {len(alien)} "
                f"kernel(s) absent from the compile ledger (attribution "
                f"outran the evidence): {alien[:5]}"
            )
    return problems


def _validate_telemetry(telemetry) -> list[str]:
    if not isinstance(telemetry, dict):
        return [f"telemetry record malformed: {type(telemetry).__name__}"]
    problems: list[str] = []
    iv = telemetry.get("interval_s")
    if not isinstance(iv, (int, float)) or iv != iv or iv <= 0:
        problems.append(f"telemetry interval_s invalid: {iv!r}")
    ticks = telemetry.get("ticks")
    if not isinstance(ticks, int) or ticks < 0:
        problems.append(f"telemetry ticks invalid: {ticks!r}")
    samples = telemetry.get("samples")
    if not isinstance(samples, list):
        return problems + [
            f"telemetry samples missing/malformed: {type(samples).__name__}"
        ]
    last_t = float("-inf")
    for i, s in enumerate(samples):
        if not isinstance(s, dict):
            problems.append(f"telemetry sample {i}: not a dict")
            continue
        t = s.get("t_s")
        if not isinstance(t, (int, float)) or t != t or t < 0:
            problems.append(f"telemetry sample {i}: t_s invalid: {t!r}")
        elif t < last_t:
            problems.append(
                f"telemetry sample {i}: t_s {t} decreases (after {last_t})"
            )
        else:
            last_t = t
        for k, v in s.items():
            if k == "t_s":
                continue
            if not isinstance(v, (int, float)) or v != v or v < 0:
                problems.append(
                    f"telemetry sample {i}: {k} invalid: {v!r}"
                )
    return problems


# ---------------------------------------------------------------------------
# Black-box forensics records (utils/blackbox.py) + fleet aggregation
# ---------------------------------------------------------------------------


def validate_blackbox(rec: dict) -> list[str]:
    """--check gate for one blackbox heartbeat/dump line. The bar the
    forensics must clear to be trusted during an incident: monotonic
    seq, sane timestamps, and — for dumps — actual stacks plus a
    machine-usable reason, so a stall dump that lost its payload fails
    loudly instead of reading as 'no problem found'."""
    problems: list[str] = []
    if rec.get("kind") != BLACKBOX_KIND:
        problems.append(
            f"kind is {rec.get('kind')!r}, want {BLACKBOX_KIND!r}"
        )
    if rec.get("schema") not in BLACKBOX_SCHEMAS:
        problems.append(
            f"schema is {rec.get('schema')!r}, want one of "
            f"{BLACKBOX_SCHEMAS}"
        )
    record = rec.get("record")
    if record not in ("heartbeat", "dump"):
        problems.append(f"record invalid: {record!r}")
    seq = rec.get("seq")
    if not isinstance(seq, int) or seq < 1:
        problems.append(f"seq invalid: {seq!r}")
    for k in ("t_s", "unix_ts"):
        v = rec.get(k)
        if not isinstance(v, (int, float)) or v != v or v < 0:
            problems.append(f"{k} invalid: {v!r}")
    prog = rec.get("progress")
    if not isinstance(prog, int) or prog < 0:
        problems.append(f"progress invalid: {prog!r}")
    if not isinstance(rec.get("phase"), str):
        problems.append(f"phase invalid: {rec.get('phase')!r}")
    if "span" in rec and not (
        isinstance(rec["span"], str) and rec["span"]
    ):
        problems.append(f"span invalid: {rec['span']!r}")
    # trace stamps (ISSUE 17): incidents join the timeline by carrying
    # the live recorder's trace id and the innermost OPEN span's id
    tid = rec.get("trace_id")
    if tid is not None and not (
        isinstance(tid, str) and TRACE_ID_RE.match(tid)
    ):
        problems.append(f"trace_id malformed: {tid!r}")
    sid = rec.get("span_id")
    if sid is not None and not (
        isinstance(sid, str) and SPAN_ID_RE.match(sid)
    ):
        problems.append(f"span_id malformed: {sid!r}")
    if record != "dump":
        return problems
    reason = rec.get("reason")
    if not (isinstance(reason, str) and reason):
        problems.append(f"dump reason invalid: {reason!r}")
    if reason == "stall":
        ss = rec.get("stall_s")
        if not isinstance(ss, (int, float)) or ss <= 0:
            problems.append(f"stall dump: stall_s invalid: {ss!r}")
    if reason == "deadline" and not rec.get("deadline"):
        problems.append("deadline dump: deadline name missing")
    stacks = rec.get("stacks")
    if not isinstance(stacks, list) or not stacks:
        problems.append("dump stacks missing/empty")
    else:
        for i, st in enumerate(stacks):
            if not (
                isinstance(st, dict)
                and isinstance(st.get("thread"), str)
                and isinstance(st.get("stack"), list)
                and st["stack"]
            ):
                problems.append(f"dump stack {i} malformed")
    if not isinstance(rec.get("faulthandler"), str):
        problems.append("dump faulthandler text missing")
    hbs = rec.get("heartbeats")
    if not isinstance(hbs, list):
        problems.append("dump heartbeat trail missing")
    else:
        for i, hb in enumerate(hbs):
            if not (
                isinstance(hb, dict) and hb.get("record") == "heartbeat"
            ):
                problems.append(f"dump heartbeat {i} malformed")
    if "spans" in rec and not isinstance(rec["spans"], list):
        problems.append("dump spans malformed")
    # the dump's span path and span_id name the SAME span: both were
    # read from the live tree the dump also embeds. A disagreement means
    # the forensics raced the recorder and the dump's attribution cannot
    # be trusted — reject it rather than let an incident pin the wrong
    # stage.
    if (
        isinstance(sid, str)
        and SPAN_ID_RE.match(sid)
        and isinstance(rec.get("span"), str)
        and isinstance(rec.get("spans"), list)
    ):
        found = None
        for path, sp in _walk_spans(rec["spans"]):
            if sp.get("span_id") == sid:
                found = "/".join(path)
                break
        if found is None:
            problems.append(
                f"dump span_id {sid} not present in the embedded span tree"
            )
        elif found != rec["span"]:
            problems.append(
                f"dump span path {rec['span']!r} disagrees with span_id "
                f"{sid} (tree says {found!r})"
            )
    return problems


def validate_fleet(rec: dict) -> list[str]:
    """--check gate for a fleet record (`prove_report.py --fleet`
    output): host entries named and unique, stage stats internally
    consistent (max >= median, max_host a real host), stragglers
    referring to real stages/hosts."""
    problems: list[str] = []
    if rec.get("kind") != FLEET_KIND:
        problems.append(f"kind is {rec.get('kind')!r}, want {FLEET_KIND!r}")
    if rec.get("schema") not in FLEET_SCHEMAS:
        problems.append(
            f"schema is {rec.get('schema')!r}, want one of {FLEET_SCHEMAS}"
        )
    hosts = rec.get("hosts")
    if not isinstance(hosts, list) or not hosts:
        return problems + ["hosts missing/empty"]
    names = []
    for i, h in enumerate(hosts):
        if not isinstance(h, dict) or not h.get("host"):
            problems.append(f"host {i}: entry malformed")
            continue
        names.append(h["host"])
        off = h.get("clock_offset_s")
        if off is not None and (
            not isinstance(off, (int, float)) or off != off or off < 0
        ):
            problems.append(f"host {h['host']}: clock_offset_s invalid: {off!r}")
        stages = h.get("stages")
        if stages is not None and not isinstance(stages, dict):
            problems.append(f"host {h['host']}: stages malformed")
        for k in ("ici_bytes", "dcn_bytes", "transfer_bytes", "wall_s"):
            v = h.get(k)
            if v is not None and (
                not isinstance(v, (int, float)) or v != v or v < 0
            ):
                problems.append(f"host {h['host']}: {k} invalid: {v!r}")
    if len(set(names)) != len(names):
        problems.append(f"duplicate host names: {names}")
    n = rec.get("n_hosts")
    if n != len(hosts):
        problems.append(f"n_hosts {n!r} != len(hosts) {len(hosts)}")
    stages = rec.get("stages")
    if not isinstance(stages, dict):
        problems.append("stages missing")
        stages = {}
    for nm, st in stages.items():
        if not isinstance(st, dict):
            problems.append(f"stage {nm}: malformed")
            continue
        med, mx = st.get("median_s"), st.get("max_s")
        if not isinstance(med, (int, float)) or med < 0:
            problems.append(f"stage {nm}: median_s invalid: {med!r}")
        if not isinstance(mx, (int, float)) or mx < 0:
            problems.append(f"stage {nm}: max_s invalid: {mx!r}")
        if (
            isinstance(med, (int, float))
            and isinstance(mx, (int, float))
            and mx + 1e-9 < med
        ):
            problems.append(f"stage {nm}: max_s {mx} < median_s {med}")
        if st.get("max_host") not in names:
            problems.append(
                f"stage {nm}: max_host {st.get('max_host')!r} not a host"
            )
        walls = st.get("walls")
        if not isinstance(walls, dict):
            problems.append(f"stage {nm}: walls missing")
        else:
            for hn in walls:
                if hn not in names:
                    problems.append(f"stage {nm}: wall host {hn!r} unknown")
    for i, s in enumerate(rec.get("stragglers") or ()):
        if not isinstance(s, dict):
            problems.append(f"straggler {i}: malformed")
            continue
        if s.get("stage") not in stages:
            problems.append(f"straggler {i}: stage {s.get('stage')!r} unknown")
        if s.get("host") not in names:
            problems.append(f"straggler {i}: host {s.get('host')!r} unknown")
        r = s.get("ratio")
        if not isinstance(r, (int, float)) or r < 1.0:
            problems.append(f"straggler {i}: ratio invalid: {r!r}")
    clock = rec.get("clock")
    if not isinstance(clock, dict) or clock.get("method") not in (
        "barrier",
        "none",
    ):
        problems.append(f"clock malformed: {clock!r}")
    return problems


def validate_line(doc: dict) -> list[str]:
    """Route one artifact line to its kind's validator — the --check
    entry point now that blackbox dumps and fleet records interleave
    with prove lines in the same JSONL files."""
    kind = doc.get("kind")
    if kind == BLACKBOX_KIND:
        return validate_blackbox(doc)
    if kind == FLEET_KIND:
        return validate_fleet(doc)
    return validate_report(doc)


def validate_artifact(docs: list) -> list[str]:
    """Cross-LINE invariants over a whole artifact (the per-line checks
    are validate_line): span ids must be unique across every prove
    line's span tree — two lines sharing a span_id would stitch into
    one timeline node and silently merge two requests' history. Only
    REPORT_KIND trees define ids; blackbox dumps EMBED a snapshot of a
    live tree whose spans reappear in that recorder's final line, so
    they are references, not definitions."""
    problems: list[str] = []
    seen: dict = {}
    for i, d in enumerate(docs):
        if not isinstance(d, dict) or d.get("kind") != REPORT_KIND:
            continue
        for path, sp in _walk_spans(d.get("spans") or ()):
            sid = sp.get("span_id")
            if not (isinstance(sid, str) and SPAN_ID_RE.match(sid)):
                continue
            key = f"line {i} span {'/'.join(path)}"
            if sid in seen:
                problems.append(
                    f"span_id {sid} collides: {seen[sid]} vs {key}"
                )
            else:
                seen[sid] = key
    return problems


def _sum_gauges(metrics: dict, prefixes: tuple, contains: str) -> float | None:
    total = 0.0
    found = False
    for k, v in (metrics.get("gauges") or {}).items():
        if contains in k and any(k.startswith(p) for p in prefixes):
            if isinstance(v, (int, float)):
                total += float(v)
                found = True
    return total if found else None


def _fleet_host_entry(label: str, docs: list[dict]) -> dict:
    """Distill one host's artifact lines (multihost result line and/or
    per-host ProveReport JSONL and/or blackbox records) into one fleet
    host entry."""
    entry: dict = {"host": label}
    dumps = 0
    for d in docs:
        if not isinstance(d, dict):
            continue
        kind = d.get("kind")
        if kind == BLACKBOX_KIND:
            if d.get("record") == "dump":
                dumps += 1
            if d.get("phase"):
                entry["phase"] = d["phase"]
            continue
        if kind == REPORT_KIND:
            spans = d.get("spans") or []
            if any(
                sp.get("name") == "prove" for _p, sp in _walk_spans(spans)
            ):
                walls = stage_walls(spans)
                if walls:
                    entry["stages"] = {
                        k: round(v, 6) for k, v in walls.items()
                    }
                if isinstance(d.get("wall_s"), (int, float)):
                    entry["wall_s"] = d["wall_s"]
            m = d.get("metrics")
            if isinstance(m, dict):
                ici = _sum_gauges(m, ("ici.",), "bytes")
                if ici is not None:
                    entry["ici_bytes"] = entry.get("ici_bytes", 0.0) + ici
                dcn = _sum_gauges(m, ("dcn.",), "bytes")
                if dcn is not None:
                    entry["dcn_bytes"] = entry.get("dcn_bytes", 0.0) + dcn
                xfer = _sum_gauges(m, ("transfer.", "limb."), "bytes")
                if xfer is not None:
                    entry["transfer_bytes"] = (
                        entry.get("transfer_bytes", 0.0) + xfer
                    )
            continue
        # multihost_worker result line: {pid, proofs, ici, dcn, clock_sync}
        if "pid" in d and ("proofs" in d or "clock_sync" in d or "ici" in d):
            if isinstance(d.get("pid"), int):
                entry["pid"] = d["pid"]
            if isinstance(d.get("mesh_mode"), str):
                entry["mesh_mode"] = d["mesh_mode"]
            cs = d.get("clock_sync")
            if isinstance(cs, dict) and isinstance(
                cs.get("barrier_unix_ts"), (int, float)
            ):
                entry["barrier_unix_ts"] = cs["barrier_unix_ts"]
            for key, field in (("ici", "ici_bytes"), ("dcn", "dcn_bytes")):
                fam = d.get(key)
                if isinstance(fam, dict):
                    tot = sum(
                        float(v)
                        for k, v in fam.items()
                        if "bytes" in k and isinstance(v, (int, float))
                    )
                    if tot:
                        entry.setdefault(field, tot)
            rp = d.get("prove_report_path")
            if isinstance(rp, str) and rp:
                entry["prove_report_path"] = rp
    if dumps:
        entry["dumps"] = dumps
    return entry


def fleet_merge(
    host_docs: list,
    straggler_ratio: float = 1.5,
    min_abs_s: float = 0.05,
) -> dict:
    """Merge per-host artifacts into ONE mesh-wide fleet record
    (DIZK's lesson: cluster proving lives or dies on per-node straggler
    attribution). `host_docs` is [(label, [parsed lines...]), ...] —
    one element per host, typically a multihost_worker result file or
    its per-host ProveReport.

    Clock alignment: hosts that stamped a barrier-synchronized
    `clock_sync.barrier_unix_ts` (scripts/multihost_worker.py) all
    passed the same collective at the same instant, so the pairwise
    differences of those stamps ARE the wall-clock skews — no NTP
    assumption. Offsets are reported relative to the earliest host.

    Straggler rule: a stage straggles when its slowest host exceeds
    straggler_ratio x the across-host median AND by at least min_abs_s
    (sub-50ms spread is scheduling jitter, not a straggler)."""
    hosts = [_fleet_host_entry(lbl, docs) for lbl, docs in host_docs]
    # clock skew from barrier stamps
    stamps = {
        h["host"]: h["barrier_unix_ts"]
        for h in hosts
        if isinstance(h.get("barrier_unix_ts"), (int, float))
    }
    if len(stamps) >= 2:
        t0 = min(stamps.values())
        for h in hosts:
            if h["host"] in stamps:
                h["clock_offset_s"] = round(stamps[h["host"]] - t0, 6)
        clock = {
            "method": "barrier",
            "max_skew_s": round(max(stamps.values()) - t0, 6),
        }
    else:
        clock = {
            "method": "none",
            "note": (
                "fewer than 2 hosts carry clock_sync.barrier_unix_ts; "
                "stage walls are durations (skew-free) but timelines "
                "are unaligned"
            ),
        }
    # per-stage across-host stats
    stage_hosts: dict = {}
    for h in hosts:
        for nm, w in (h.get("stages") or {}).items():
            if isinstance(w, (int, float)):
                stage_hosts.setdefault(nm, {})[h["host"]] = float(w)
    stages: dict = {}
    stragglers: list = []
    for nm in sorted(stage_hosts):
        walls = stage_hosts[nm]
        med = _percentile(sorted(walls.values()), 0.5)
        max_host = max(walls, key=walls.get)
        mx = walls[max_host]
        stages[nm] = {
            "median_s": round(med, 6),
            "max_s": round(mx, 6),
            "max_host": max_host,
            "walls": {k: round(v, 6) for k, v in sorted(walls.items())},
        }
        if (
            len(walls) >= 2
            and med > 0
            and mx > med * straggler_ratio
            and (mx - med) >= min_abs_s
        ):
            stragglers.append(
                {
                    "stage": nm,
                    "host": max_host,
                    "wall_s": round(mx, 6),
                    "median_s": round(med, 6),
                    "ratio": round(mx / med, 4),
                }
            )
    return {
        "kind": FLEET_KIND,
        "schema": FLEET_SCHEMAS[-1],
        "unix_ts": time.time(),
        "n_hosts": len(hosts),
        "hosts": hosts,
        "stages": stages,
        "stragglers": stragglers,
        "clock": clock,
        "straggler_ratio": straggler_ratio,
    }


def render_fleet(rec: dict) -> str:
    """Text view of a fleet record: host roster with clock offsets and
    byte rollups, then the per-stage wall table (one column per host)
    with stragglers flagged."""
    lines = []
    clock = rec.get("clock") or {}
    skew = clock.get("max_skew_s")
    lines.append(
        f"fleet: {rec.get('n_hosts')} hosts, clock={clock.get('method')}"
        + (f" (max skew {skew}s)" if skew is not None else "")
    )
    if clock.get("note"):
        lines.append(f"  note: {clock['note']}")
    hosts = rec.get("hosts") or []
    lines.append(
        f"  {'host':<16} {'offset_s':>9} {'wall_s':>9} "
        f"{'ici_MB':>9} {'dcn_MB':>9} {'xfer_MB':>9} {'dumps':>6}"
    )
    for h in hosts:
        def _mb(v):
            return f"{v / 1e6:.2f}" if isinstance(v, (int, float)) else "-"

        off = h.get("clock_offset_s")
        wall = h.get("wall_s")
        lines.append(
            f"  {h.get('host', '?'):<16} "
            f"{off if off is not None else '-':>9} "
            f"{f'{wall:.3f}' if isinstance(wall, (int, float)) else '-':>9} "
            f"{_mb(h.get('ici_bytes')):>9} "
            f"{_mb(h.get('dcn_bytes')):>9} "
            f"{_mb(h.get('transfer_bytes')):>9} "
            f"{h.get('dumps', 0):>6}"
        )
    stages = rec.get("stages") or {}
    if stages:
        names = [h.get("host", "?") for h in hosts]
        header = "  " + f"{'stage':<26}" + "".join(
            f"{n[:12]:>13}" for n in names
        ) + f"{'median':>10}{'max':>10}"
        lines.append("stage walls (s):")
        lines.append(header)
        flagged = {
            (s["stage"], s["host"]) for s in rec.get("stragglers") or ()
        }
        for nm, st in stages.items():
            cells = []
            for n in names:
                w = (st.get("walls") or {}).get(n)
                cells.append(
                    f"{w:.3f}" if isinstance(w, (int, float)) else "-"
                )
            row = f"  {nm:<26}" + "".join(f"{c:>13}" for c in cells)
            row += f"{st.get('median_s'):>10}{st.get('max_s'):>10}"
            if any((nm, n) in flagged for n in names):
                row += "  << STRAGGLER"
            lines.append(row)
    for s in rec.get("stragglers") or ():
        lines.append(
            f"STRAGGLER: {s['stage']} on {s['host']}: {s['wall_s']}s "
            f"vs median {s['median_s']}s (x{s['ratio']})"
        )
    if not rec.get("stragglers"):
        lines.append("no stragglers")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Distributed-trace timeline (ISSUE 17) — pure dict functions; the
# `prove_report.py --timeline` payoff surface. Merge N per-host
# artifacts, align their clocks with the same barrier stamps fleet_merge
# uses, stitch spans into per-trace event lists, and render either an
# ASCII swimlane or Chrome trace-event JSON loadable in Perfetto.
# ---------------------------------------------------------------------------

TIMELINE_KIND = "boojum_tpu.timeline"
TIMELINE_SCHEMAS = (1,)
# bucket for events whose line predates schema 4 (or lost its context):
# still rendered, clearly labeled as unstitched
UNTRACED = "untraced"


def _timeline_line_events(label: str, d: dict, off: float) -> list:
    """Flatten one ProveReport line's span tree into absolute-time span
    events. The line's `unix_ts` is stamped when the recorder CLOSES,
    so the recording started at unix_ts - wall_s; each span sits at its
    start_s offset from there (queue.wait's negative, backdated start
    lands it before the recording window — exactly where the wait
    happened). `off` is the host's barrier-derived clock offset."""
    unix_ts, wall = d.get("unix_ts"), d.get("wall_s")
    if not (
        isinstance(unix_ts, (int, float)) and isinstance(wall, (int, float))
    ):
        return []
    t0_abs = float(unix_ts) - float(wall) - off
    line_tid = (d.get("trace_ctx") or {}).get("trace_id")
    out: list = []

    def _walk(sp, tid):
        if not isinstance(sp, dict):
            return
        if isinstance(sp.get("trace_id"), str):
            tid = sp["trace_id"]
        attrs = sp.get("attrs") or {}
        # batch-scoped work (scheduler warm spans) recorded OUTSIDE the
        # request's scoped recorder joins the trace via an explicit
        # `trace` attr stamped by the scheduler
        a_tid = attrs.get("trace")
        if isinstance(a_tid, str) and TRACE_ID_RE.match(a_tid):
            tid = a_tid
        start, w = sp.get("start_s"), sp.get("wall_s")
        if isinstance(start, (int, float)) and isinstance(w, (int, float)):
            ev = {
                "name": sp.get("name"),
                "host": label,
                "label": d.get("label"),
                "trace_id": tid,
                "span_id": sp.get("span_id"),
                "parent_span_id": sp.get("parent_span_id"),
                "t_s": round(t0_abs + float(start), 6),
                "wall_s": float(w),
            }
            for k in ("sync_s", "overlap_s", "error"):
                if k in sp:
                    ev[k] = sp[k]
            out.append(ev)
        for c in sp.get("children") or ():
            _walk(c, tid)

    for root in d.get("spans") or ():
        _walk(root, line_tid)
    return out


def _timeline_line_counters(label: str, d: dict, off: float) -> list:
    """Telemetry samples as absolute-time counter points (Perfetto "C"
    tracks). Needs the sampler's `t0_unix_ts` anchor (schema 4,
    utils/telemetry.py) — samples only carry monotonic offsets."""
    tele = d.get("telemetry")
    if not isinstance(tele, dict):
        return []
    anchor = tele.get("t0_unix_ts")
    if not isinstance(anchor, (int, float)):
        return []
    out = []
    for s in tele.get("samples") or ():
        if not isinstance(s, dict):
            continue
        t = s.get("t_s")
        if not isinstance(t, (int, float)):
            continue
        ts = round(float(anchor) + float(t) - off, 6)
        for k, v in s.items():
            if k == "t_s" or not isinstance(v, (int, float)):
                continue
            out.append({"host": label, "name": k, "t_s": ts, "value": v})
    return out


def _timeline_blackbox_event(label: str, d: dict, off: float):
    """A heartbeat/dump line as an instant event: incidents join the
    timeline via the trace/open-span ids the blackbox stamps."""
    unix_ts = d.get("unix_ts")
    if not isinstance(unix_ts, (int, float)):
        return None
    record = d.get("record")
    name = f"blackbox.{record}"
    if record == "dump" and d.get("reason"):
        name = f"blackbox.{d['reason']}"
    ev = {
        "instant": record,
        "name": name,
        "host": label,
        "t_s": round(float(unix_ts) - off, 6),
    }
    for k in ("trace_id", "span_id", "span", "phase", "reason"):
        if d.get(k):
            ev[k] = d[k]
    return ev


def timeline_merge(
    host_docs: list,
    straggler_ratio: float = 1.5,
    min_abs_s: float = 0.05,
) -> dict:
    """Stitch per-host artifacts into ONE timeline record. `host_docs`
    is [(label, [parsed lines...]), ...] — report JSONL, multihost
    result lines, blackbox sidecars, in any mix.

    Clock alignment: identical to fleet_merge — hosts that stamped a
    barrier-synchronized `clock_sync.barrier_unix_ts` all passed the
    same collective at the same instant, so stamp differences ARE the
    skews; every host's events shift by its offset from the earliest
    host. Without two stamped hosts, events stay on raw wall clocks
    (noted in `clock`).

    Straggler rule (per trace): a span name appearing on >= 2 hosts
    flags its slowest host when it exceeds straggler_ratio x the
    across-host median by at least min_abs_s."""
    stamps: dict = {}
    for lbl, docs in host_docs:
        for d in docs:
            if not isinstance(d, dict):
                continue
            cs = d.get("clock_sync")
            if isinstance(cs, dict) and isinstance(
                cs.get("barrier_unix_ts"), (int, float)
            ):
                stamps[lbl] = float(cs["barrier_unix_ts"])
    if len(stamps) >= 2:
        t0c = min(stamps.values())
        offsets = {h: round(s - t0c, 6) for h, s in stamps.items()}
        clock = {
            "method": "barrier",
            "max_skew_s": round(max(stamps.values()) - t0c, 6),
        }
    else:
        offsets = {}
        clock = {
            "method": "none",
            "note": (
                "fewer than 2 hosts carry clock_sync.barrier_unix_ts; "
                "events are on raw per-host wall clocks"
            ),
        }
    events: list = []
    marks: list = []
    counters: list = []
    for lbl, docs in host_docs:
        off = offsets.get(lbl, 0.0)
        for d in docs:
            if not isinstance(d, dict):
                continue
            kind = d.get("kind")
            if kind == REPORT_KIND:
                events.extend(_timeline_line_events(lbl, d, off))
                counters.extend(_timeline_line_counters(lbl, d, off))
            elif kind == BLACKBOX_KIND:
                ev = _timeline_blackbox_event(lbl, d, off)
                if ev is not None:
                    events.append(ev)
            elif "pid" in d and isinstance(d.get("clock_sync"), dict):
                ts = d["clock_sync"].get("barrier_unix_ts")
                if isinstance(ts, (int, float)):
                    # aligned barrier instants from every host coincide
                    # by construction — the visual proof the alignment
                    # worked when loaded in Perfetto
                    marks.append(
                        {
                            "instant": "clock_sync",
                            "name": "clock_sync.barrier",
                            "host": lbl,
                            "t_s": round(float(ts) - off, 6),
                        }
                    )
    # telemetry snapshots overlap across lines from the same sampler —
    # dedupe counter points on (host, series, timestamp)
    seen_pts = set()
    uniq_counters = []
    for c in counters:
        key = (c["host"], c["name"], c["t_s"])
        if key not in seen_pts:
            seen_pts.add(key)
            uniq_counters.append(c)
    counters = sorted(uniq_counters, key=lambda c: c["t_s"])
    # group into per-trace event lists; instants without a trace id are
    # global marks
    by_trace: dict = {}
    for ev in events:
        tid = ev.get("trace_id")
        if not tid and ev.get("instant"):
            marks.append(ev)
            continue
        by_trace.setdefault(tid or UNTRACED, []).append(ev)
    traces: list = []
    all_stragglers: list = []
    for tid, evs in by_trace.items():
        evs.sort(key=lambda e: (e["t_s"], -e.get("wall_s", 0.0)))
        t0 = min(e["t_s"] for e in evs)
        t1 = max(e["t_s"] + e.get("wall_s", 0.0) for e in evs)
        span_evs = [e for e in evs if "wall_s" in e]
        # per-name across-host straggler attribution within the trace
        by_name: dict = {}
        for e in span_evs:
            walls = by_name.setdefault(e["name"], {})
            walls[e["host"]] = max(walls.get(e["host"], 0.0), e["wall_s"])
        stragglers = []
        for nm in sorted(by_name):
            walls = by_name[nm]
            if len(walls) < 2:
                continue
            med = _percentile(sorted(walls.values()), 0.5)
            max_host = max(walls, key=walls.get)
            mx = walls[max_host]
            if (
                med > 0
                and mx > med * straggler_ratio
                and (mx - med) >= min_abs_s
            ):
                stragglers.append(
                    {
                        "span": nm,
                        "host": max_host,
                        "wall_s": round(mx, 6),
                        "median_s": round(med, 6),
                        "ratio": round(mx / med, 4),
                    }
                )
                for e in span_evs:
                    if (
                        e["name"] == nm
                        and e["host"] == max_host
                        and e["wall_s"] == mx
                    ):
                        e["straggler"] = True
        for s in stragglers:
            all_stragglers.append(dict(s, trace_id=tid))
        traces.append(
            {
                "trace_id": tid,
                "t0_unix_ts": round(t0, 6),
                "wall_s": round(t1 - t0, 6),
                "hosts": sorted({e["host"] for e in evs}),
                "n_spans": len(span_evs),
                "n_instants": len(evs) - len(span_evs),
                "events": evs,
                "stragglers": stragglers,
            }
        )
    # chronological, with the untraced bucket last
    traces.sort(
        key=lambda t: (t["trace_id"] == UNTRACED, t["t0_unix_ts"])
    )
    hosts = sorted({lbl for lbl, _docs in host_docs})
    return {
        "kind": TIMELINE_KIND,
        "schema": TIMELINE_SCHEMAS[-1],
        "unix_ts": time.time(),
        "hosts": hosts,
        "clock": clock,
        "offsets": offsets,
        "n_traces": len(traces),
        "traces": traces,
        "marks": sorted(marks, key=lambda m: m["t_s"]),
        "counters": counters,
        "stragglers": all_stragglers,
    }


def _event_depth(ev: dict, by_id: dict, limit: int = 12) -> int:
    depth = 0
    cur = ev
    while depth < limit:
        psid = cur.get("parent_span_id")
        if not psid or psid not in by_id:
            break
        cur = by_id[psid]
        depth += 1
    return depth


def render_timeline(rec: dict, width: int = 48, max_rows: int = 48) -> str:
    """ASCII swimlane per trace: one row per span (indented by stitch
    depth), a scaled `=` bar positioned in the trace's window, instants
    as `!` markers, stragglers flagged."""
    lines = []
    clock = rec.get("clock") or {}
    skew = clock.get("max_skew_s")
    lines.append(
        f"timeline: {len(rec.get('hosts') or ())} hosts, "
        f"{rec.get('n_traces')} traces, clock={clock.get('method')}"
        + (f" (max skew {skew}s)" if skew is not None else "")
    )
    if clock.get("note"):
        lines.append(f"  note: {clock['note']}")
    for off_host in sorted(rec.get("offsets") or {}):
        lines.append(
            f"  offset {off_host}: +{rec['offsets'][off_host]}s"
        )
    for tr in rec.get("traces") or ():
        tid = tr.get("trace_id") or "?"
        head = tid if tid == UNTRACED else tid[:8]
        lines.append(
            f"trace {head}: {len(tr.get('hosts') or ())} host(s), "
            f"{tr.get('wall_s')}s, {tr.get('n_spans')} spans, "
            f"{tr.get('n_instants')} instants"
        )
        evs = tr.get("events") or []
        by_id = {
            e["span_id"]: e for e in evs if e.get("span_id")
        }
        t0 = tr.get("t0_unix_ts", 0.0)
        dur = max(tr.get("wall_s") or 0.0, 1e-9)
        shown = evs[:max_rows]
        for ev in shown:
            sidx = int((ev["t_s"] - t0) / dur * width)
            sidx = min(max(sidx, 0), width - 1)
            if "wall_s" in ev:
                slen = max(1, int(ev["wall_s"] / dur * width))
                slen = min(slen, width - sidx)
                bar = "." * sidx + "=" * slen
                tail = f" {ev['wall_s']:.3f}s"
            else:
                bar = "." * sidx + "!"
                tail = ""
            bar = bar.ljust(width, ".")
            depth = _event_depth(ev, by_id)
            name = "  " * depth + str(ev.get("name"))
            flag = ""
            if ev.get("straggler"):
                flag = " <- straggler"
            if ev.get("error"):
                flag += f" [error: {ev['error']}]"
            lines.append(
                f"  {ev.get('host', '?'):<12} {name:<28.28} "
                f"[{bar}]{tail}{flag}"
            )
        if len(evs) > len(shown):
            lines.append(f"  ... {len(evs) - len(shown)} more events")
        for s in tr.get("stragglers") or ():
            lines.append(
                f"  straggler: {s['span']} on {s['host']} "
                f"({s['wall_s']}s vs median {s['median_s']}s, "
                f"x{s['ratio']})"
            )
    return "\n".join(lines)


def perfetto_events(rec: dict) -> dict:
    """A timeline record as Chrome trace-event JSON (the format Perfetto
    and chrome://tracing load): hosts become processes, traces become
    threads, spans become "X" complete events, dumps/heartbeats/barrier
    marks become "i" instants, telemetry series become "C" counters.
    Timestamps are microseconds from the earliest stitched event."""
    traces = rec.get("traces") or []
    marks = rec.get("marks") or []
    counters = rec.get("counters") or []
    all_ts = (
        [e["t_s"] for tr in traces for e in tr.get("events") or ()]
        + [m["t_s"] for m in marks]
        + [c["t_s"] for c in counters]
    )
    base = min(all_ts) if all_ts else 0.0

    def _us(t):
        return round(max(t - base, 0.0) * 1e6, 3)

    host_pid = {h: i + 1 for i, h in enumerate(rec.get("hosts") or ())}
    out = []
    for h, pid in host_pid.items():
        out.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "args": {"name": h},
            }
        )
    for ti, tr in enumerate(traces):
        tid_n = ti + 1
        label = tr.get("trace_id") or "?"
        if label != UNTRACED:
            label = f"trace {label[:8]}"
        for h in tr.get("hosts") or ():
            out.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": host_pid.get(h, 0),
                    "tid": tid_n,
                    "args": {"name": label},
                }
            )
        for ev in tr.get("events") or ():
            pid = host_pid.get(ev.get("host"), 0)
            args = {
                k: ev[k]
                for k in (
                    "trace_id",
                    "span_id",
                    "parent_span_id",
                    "host",
                    "label",
                    "sync_s",
                    "overlap_s",
                    "error",
                    "straggler",
                    "span",
                    "phase",
                    "reason",
                )
                if ev.get(k) is not None
            }
            if "wall_s" in ev:
                out.append(
                    {
                        "name": str(ev.get("name")),
                        "ph": "X",
                        "cat": "span",
                        "ts": _us(ev["t_s"]),
                        "dur": round(max(ev["wall_s"], 0.0) * 1e6, 3),
                        "pid": pid,
                        "tid": tid_n,
                        "args": args,
                    }
                )
            else:
                out.append(
                    {
                        "name": str(ev.get("name")),
                        "ph": "i",
                        "s": "t",
                        "cat": "blackbox",
                        "ts": _us(ev["t_s"]),
                        "pid": pid,
                        "tid": tid_n,
                        "args": args,
                    }
                )
    for m in marks:
        out.append(
            {
                "name": str(m.get("name")),
                "ph": "i",
                "s": "p",
                "cat": "mark",
                "ts": _us(m["t_s"]),
                "pid": host_pid.get(m.get("host"), 0),
                "tid": 0,
                "args": {
                    k: m[k]
                    for k in ("host", "span", "phase", "reason")
                    if m.get(k) is not None
                },
            }
        )
    for c in counters:
        out.append(
            {
                "name": str(c["name"]),
                "ph": "C",
                "ts": _us(c["t_s"]),
                "pid": host_pid.get(c.get("host"), 0),
                "tid": 0,
                "args": {"value": c["value"]},
            }
        )
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def validate_perfetto(doc: dict) -> list[str]:
    """Sanity gate for emitted Chrome trace-event JSON (the ci_gate
    --timeline leg's bar): a traceEvents list whose every event has a
    name, a known phase, non-negative numeric timestamps, and — for
    "X" complete events — a non-negative duration."""
    problems: list[str] = []
    if not isinstance(doc, dict) or not isinstance(
        doc.get("traceEvents"), list
    ):
        return ["traceEvents missing"]
    evs = doc["traceEvents"]
    if not evs:
        problems.append("traceEvents empty")
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        if not (isinstance(ev.get("name"), str) and ev["name"]):
            problems.append(f"event {i}: name missing")
        ph = ev.get("ph")
        if ph not in ("X", "i", "M", "C"):
            problems.append(f"event {i}: ph invalid: {ph!r}")
        if not isinstance(ev.get("pid"), int):
            problems.append(f"event {i}: pid invalid: {ev.get('pid')!r}")
        if ph == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts != ts or ts < 0:
            problems.append(f"event {i}: ts invalid: {ts!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur != dur or dur < 0:
                problems.append(f"event {i}: dur invalid: {dur!r}")
        if ph == "i" and ev.get("s") not in ("t", "p", "g"):
            problems.append(f"event {i}: instant scope invalid: {ev.get('s')!r}")
        if len(problems) > 25:
            problems.append("... (truncated)")
            break
    return problems


def diff_reports(a: dict, b: dict, top: int = 10) -> dict:
    """Regression-triage diff: per-span wall deltas (matched by tree path,
    repeated paths summed) and the FIRST diverging digest checkpoint."""

    def _span_walls(report):
        walls: dict[str, float] = {}
        for path, sp in flatten_spans(report):
            walls[path] = walls.get(path, 0.0) + (sp.get("wall_s") or 0.0)
        return walls

    wa, wb = _span_walls(a), _span_walls(b)
    deltas = []
    for path in sorted(set(wa) | set(wb)):
        va, vb = wa.get(path), wb.get(path)
        deltas.append(
            {
                "span": path,
                "a_s": None if va is None else round(va, 6),
                "b_s": None if vb is None else round(vb, 6),
                "delta_s": (
                    None
                    if va is None or vb is None
                    else round(vb - va, 6)
                ),
            }
        )
    # real deltas first (largest |delta| on top); spans present in only one
    # report sort LAST — they must never crowd genuine regressions out of
    # the top-N window
    deltas.sort(
        key=lambda d: (
            d["delta_s"] is None,
            -abs(d["delta_s"]) if d["delta_s"] is not None else 0.0,
        )
    )

    ca = a.get("checkpoints") or []
    cb = b.get("checkpoints") or []
    first_div = None
    for ea, eb in zip(ca, cb):
        if (
            ea.get("label") != eb.get("label")
            or ea.get("round") != eb.get("round")
            or ea.get("digest") != eb.get("digest")
        ):
            first_div = {
                "seq": ea.get("seq"),
                "round": ea.get("round"),
                "label": ea.get("label"),
                "a_digest": ea.get("digest"),
                "b_digest": eb.get("digest"),
                "b_label": eb.get("label"),
            }
            break
    if first_div is None and len(ca) != len(cb):
        longer = ca if len(ca) > len(cb) else cb
        e = longer[min(len(ca), len(cb))]
        first_div = {
            "seq": e.get("seq"),
            "round": e.get("round"),
            "label": e.get("label"),
            "a_digest": e.get("digest") if len(ca) > len(cb) else None,
            "b_digest": e.get("digest") if len(cb) > len(ca) else None,
            "length_mismatch": [len(ca), len(cb)],
        }

    def _counters(r):
        return (r.get("metrics") or {}).get("counters") or {}

    na, nb = _counters(a), _counters(b)
    counter_deltas = {
        k: [na.get(k), nb.get(k)]
        for k in sorted(set(na) | set(nb))
        if na.get(k) != nb.get(k)
    }

    # cost-record diff (ISSUE 12 satellite): per-stage roofline
    # efficiency deltas alongside the wall deltas — "round3 got slower"
    # and "round3 got FURTHER from peak" are different regressions
    def _cost_stages(r):
        c = r.get("cost")
        return (c.get("stages") or {}) if isinstance(c, dict) else {}

    sa, sb_ = _cost_stages(a), _cost_stages(b)
    cost_deltas = {}
    for st in sorted(set(sa) | set(sb_)):
        ea = sa.get(st) if isinstance(sa.get(st), dict) else {}
        eb = sb_.get(st) if isinstance(sb_.get(st), dict) else {}
        fa, fb = ea.get("efficiency"), eb.get("efficiency")
        ga, gb = ea.get("achieved_gflops"), eb.get("achieved_gflops")
        if fa is None and fb is None and ga is None and gb is None:
            continue
        ent = {
            "efficiency": [fa, fb],
            "achieved_gflops": [ga, gb],
            "regime": [ea.get("regime"), eb.get("regime")],
        }
        if isinstance(fa, (int, float)) and isinstance(fb, (int, float)):
            ent["efficiency_delta"] = round(fb - fa, 6)
        cost_deltas[st] = ent

    return {
        "wall_a_s": a.get("wall_s"),
        "wall_b_s": b.get("wall_s"),
        "span_deltas": deltas[:top],
        "first_checkpoint_divergence": first_div,
        "num_checkpoints": [len(ca), len(cb)],
        "counter_deltas": counter_deltas,
        "cost_deltas": cost_deltas,
    }


def _percentile(sorted_vals: list[float], q: float) -> float | None:
    """Nearest-rank percentile over an already-sorted list (stdlib-only,
    deterministic; None on empty input)."""
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def slo_summary(reports: list[dict]) -> dict:
    """Aggregate the per-request SLO records of a proving-service report
    artifact: p50/p95 queue latency and prove wall, overall proofs/sec
    (served count over the submit-to-done span), per-placement and
    per-priority counts, cache hit rate. Lines without a `request`
    record (plain proves, bench reps) are ignored."""
    reqs = [r["request"] for r in reports
            if isinstance(r.get("request"), dict)]
    ok = [q for q in reqs if "error" not in q]
    lat = sorted(
        q["queue_latency_s"] for q in reqs
        if isinstance(q.get("queue_latency_s"), (int, float))
    )
    walls = sorted(
        q["prove_wall_s"] for q in ok
        if isinstance(q.get("prove_wall_s"), (int, float))
    )
    # the artifact's serving span: earliest request START (each line is
    # stamped at completion, so start = unix_ts - the recording wall) to
    # the last completion — anchoring at the first COMPLETION would drop
    # that request's entire service time and overstate proofs/sec by
    # N/(N-1)
    starts = []
    ends = []
    for r in reports:
        if not isinstance(r.get("request"), dict):
            continue
        ts = r.get("unix_ts")
        if not isinstance(ts, (int, float)):
            continue
        wall = r.get("wall_s")
        starts.append(ts - (wall if isinstance(wall, (int, float)) else 0))
        ends.append(ts)
    span_s = (max(ends) - min(starts)) if ends else None
    total_wall = sum(walls)
    placements: dict[str, int] = {}
    priorities: dict[str, int] = {}
    cache_hits = 0
    for q in reqs:
        placements[str(q.get("placement"))] = (
            placements.get(str(q.get("placement")), 0) + 1
        )
        priorities[str(q.get("priority"))] = (
            priorities.get(str(q.get("priority")), 0) + 1
        )
        if q.get("cache_hit"):
            cache_hits += 1

    def r6(v):
        return None if v is None else round(v, 6)

    # per-tenant axis (ISSUE 11): latency/wall percentiles per tenant id
    # over the request records, plus the gateway's rejected admissions
    # (tenant records with `rejected` set: 429 quota throttles and
    # load-sheds) — the fairness/quota numbers a multi-tenant deploy
    # watches
    tenants: dict[str, dict] = {}

    def _tslot(tid: str) -> dict:
        return tenants.setdefault(
            tid, {"requests": 0, "lat": [], "walls": [], "rejected": 0}
        )

    for q in reqs:
        slot = _tslot(str(q.get("tenant", "default")))
        slot["requests"] += 1
        if isinstance(q.get("queue_latency_s"), (int, float)):
            slot["lat"].append(q["queue_latency_s"])
        if "error" not in q and isinstance(
            q.get("prove_wall_s"), (int, float)
        ):
            slot["walls"].append(q["prove_wall_s"])
    shed = {"throttled": 0, "shed": 0}
    for r in reports:
        t = r.get("tenant")
        if not isinstance(t, dict) or not t.get("rejected"):
            continue
        _tslot(str(t.get("id", "default")))["rejected"] += 1
        reason = t.get("reason")
        if reason not in shed:
            # legacy/foreign lines without a reason: classify by code
            reason = "throttled" if t.get("rejected") == 429 else "shed"
        shed[reason] += 1
    tenant_summary = {
        tid: {
            "requests": s["requests"],
            "rejected": s["rejected"],
            "queue_latency_p95_s": r6(_percentile(sorted(s["lat"]), 0.95)),
            "prove_wall_p95_s": r6(_percentile(sorted(s["walls"]), 0.95)),
        }
        for tid, s in sorted(tenants.items())
    }

    # artifact-hit rate over the artifact's lines: every aot.hits /
    # aot.misses counter recorded anywhere in the stream (service warm
    # phases, bench warm-ups) — the deployment-health axis the AOT
    # bundle store adds
    aot_hits = aot_misses = 0
    resident_lines = 0
    for r in reports:
        c = (r.get("metrics") or {}).get("counters") or {}
        if isinstance(c, dict):
            h, m = c.get("aot.hits", 0), c.get("aot.misses", 0)
            # skip malformed values like every other field here — one
            # junk line must not kill the whole --slo summary
            aot_hits += h if isinstance(h, (int, float)) else 0
            aot_misses += m if isinstance(m, (int, float)) else 0
            rs = c.get("quotient.resident_coset_sweeps", 0)
            if isinstance(rs, (int, float)) and rs > 0:
                resident_lines += 1

    # roofline axis (ISSUE 12): aggregate per-stage efficiency over the
    # lines carrying a cost record — the "how far from the hardware"
    # number next to the wall percentiles
    cost_lines = 0
    stage_eff: dict[str, list] = {}
    stage_regimes: dict[str, dict] = {}
    # field-backend axis (ISSUE 20): which field each line proved under —
    # bench lines stamp it top-level, report lines carry it in the cost
    # record; a babybear deploy's wall/byte numbers are not comparable to
    # goldilocks ones, so the summary names the split
    field_lines: dict[str, int] = {}
    for r in reports:
        c = r.get("cost")
        fld = r.get("field") or (
            c.get("field") if isinstance(c, dict) else None
        )
        if isinstance(fld, str):
            field_lines[fld] = field_lines.get(fld, 0) + 1
        if not isinstance(c, dict):
            continue
        cost_lines += 1
        for st, ent in (c.get("stages") or {}).items():
            if not isinstance(ent, dict):
                continue
            eff = ent.get("efficiency")
            if isinstance(eff, (int, float)) and eff == eff:
                stage_eff.setdefault(st, []).append(float(eff))
            reg = ent.get("regime")
            if isinstance(reg, str):
                slot = stage_regimes.setdefault(st, {})
                slot[reg] = slot.get(reg, 0) + 1
    roofline_summary = {
        "lines": cost_lines,
        "stages": {
            st: {
                "mean_efficiency": round(sum(v) / len(v), 6),
                "regimes": dict(sorted(stage_regimes.get(st, {}).items())),
            }
            for st, v in sorted(stage_eff.items())
        },
    }

    return {
        # which representation served: lines whose kernels dispatched
        # limb-RESIDENT (ISSUE 10) — BENCH/SLO deltas are attributable
        "limb_resident_lines": resident_lines,
        # field backend per line (ISSUE 20), e.g. {"babybear": 3}
        "fields": dict(sorted(field_lines.items())),
        "requests": len(reqs),
        "served": len(ok),
        "failed": len(reqs) - len(ok),
        "queue_latency_p50_s": r6(_percentile(lat, 0.50)),
        "queue_latency_p95_s": r6(_percentile(lat, 0.95)),
        "prove_wall_p50_s": r6(_percentile(walls, 0.50)),
        "prove_wall_p95_s": r6(_percentile(walls, 0.95)),
        # proofs/sec over the serving span when the artifact covers more
        # than one completion; else the sequential-throughput bound
        "proofs_per_sec": r6(
            len(ok) / span_s if span_s and span_s > 0
            else (len(ok) / total_wall if total_wall > 0 else None)
        ),
        "placements": dict(sorted(placements.items())),
        "priorities": dict(sorted(priorities.items())),
        "cache_hit_rate": (
            round(cache_hits / len(reqs), 4) if reqs else None
        ),
        "tenants": tenant_summary,
        "rejected": shed,
        "roofline": roofline_summary,
        "aot_kernels_warmed": aot_hits + aot_misses,
        "aot_hit_rate": (
            round(aot_hits / (aot_hits + aot_misses), 4)
            if (aot_hits + aot_misses)
            else None
        ),
    }


def render_slo(summary: dict) -> str:
    lines = [
        f"service SLO: {summary['requests']} requests "
        f"({summary['served']} served, {summary['failed']} failed)",
        f"  queue latency p50={summary['queue_latency_p50_s']}s "
        f"p95={summary['queue_latency_p95_s']}s",
        f"  prove wall    p50={summary['prove_wall_p50_s']}s "
        f"p95={summary['prove_wall_p95_s']}s",
        f"  proofs/sec    {summary['proofs_per_sec']}",
        f"  cache hit rate {summary['cache_hit_rate']}",
    ]
    if summary.get("aot_kernels_warmed"):
        lines.append(
            f"  aot artifacts {summary['aot_hit_rate']} hit rate over "
            f"{summary['aot_kernels_warmed']} warmed kernels"
        )
    if summary.get("limb_resident_lines"):
        lines.append(
            f"  limb-resident {summary['limb_resident_lines']} lines "
            f"dispatched the resident kernel set"
        )
    if summary.get("fields"):
        lines.append(
            "  field backend "
            + ", ".join(
                f"{k}={v}" for k, v in summary["fields"].items()
            )
        )
    if summary.get("placements"):
        lines.append(
            "  placements    "
            + ", ".join(
                f"{k}={v}" for k, v in summary["placements"].items()
            )
        )
    if summary.get("priorities"):
        lines.append(
            "  priorities    "
            + ", ".join(
                f"{k}={v}" for k, v in summary["priorities"].items()
            )
        )
    roof = summary.get("roofline") or {}
    if roof.get("lines"):
        lines.append(
            f"  roofline      {roof['lines']} line(s) with cost records"
        )
        for st, ent in (roof.get("stages") or {}).items():
            regimes = ",".join(
                f"{k}={v}" for k, v in (ent.get("regimes") or {}).items()
            )
            lines.append(
                f"    {st:<24} mean eff "
                f"{100 * ent['mean_efficiency']:.2f}%"
                + (f" [{regimes}]" if regimes else "")
            )
    rejected = summary.get("rejected") or {}
    if any(rejected.values()):
        lines.append(
            f"  rejected      throttled(429)={rejected.get('throttled', 0)} "
            f"shed={rejected.get('shed', 0)}"
        )
    for tid, t in (summary.get("tenants") or {}).items():
        lines.append(
            f"  tenant {tid:<12} {t['requests']} requests, "
            f"queue p95={t['queue_latency_p95_s']}s "
            f"wall p95={t['prove_wall_p95_s']}s, "
            f"rejected={t['rejected']}"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------


def render_report(report: dict, top: int = 10) -> str:
    lines = []
    wall = report.get("wall_s") or 0.0
    lines.append(
        f"ProveReport schema={report.get('schema')} "
        f"label={report.get('label')!r} wall={wall:.3f}s "
        f"coverage={span_coverage(report) * 100:.1f}%"
    )
    spans = report.get("spans") or []

    def _emit(sp, depth):
        w = sp.get("wall_s") or 0.0
        pct = f"{100 * w / wall:5.1f}%" if wall else "     "
        extras = ""
        if sp.get("sync_s"):
            extras += f" sync={sp['sync_s']:.3f}s"
            if w:
                # occupancy: how much of the span the host spent BLOCKED
                # on the device (sync_s/wall) — the overlapped pipeline's
                # regression signal
                extras += f" occ={100 * sp['sync_s'] / w:.0f}%"
        if sp.get("overlap_s"):
            extras += f" ovl={sp['overlap_s']:.3f}s"
        attrs = sp.get("attrs")
        if isinstance(attrs, dict) and attrs.get("resident"):
            # the limb-residency flag (ISSUE 10): which representation
            # this span's kernels computed in, visible in the tree
            extras += " resident"
        if sp.get("error"):
            extras += f" ERROR={sp['error']!r}"
        lines.append(
            f"  {'  ' * depth}{sp.get('name'):<{max(4, 40 - 2 * depth)}}"
            f"{w:9.3f}s {pct}{extras}"
        )
        for c in sp.get("children", ()):
            _emit(c, depth + 1)

    for sp in spans:
        _emit(sp, 0)

    flat = [
        (path, sp.get("wall_s") or 0.0, sp.get("sync_s") or 0.0)
        for path, sp in flatten_spans(report)
        if not sp.get("children")
    ]
    flat.sort(key=lambda t: -t[1])
    if flat:
        lines.append(f"  top {min(top, len(flat))} leaf spans:")
        for path, w, s in flat[:top]:
            occ = f" sync={s:.3f}s occ={100 * s / w:.0f}%" if s and w else ""
            lines.append(f"    {w:9.3f}s{occ}  {path}")

    counters = (report.get("metrics") or {}).get("counters") or {}
    if counters:
        lines.append("  counters:")
        for k, v in counters.items():
            lines.append(f"    {k} = {v}")
    gauges = (report.get("metrics") or {}).get("gauges") or {}
    if gauges:
        lines.append("  gauges:")
        for k, v in gauges.items():
            lines.append(f"    {k} = {v}")
    ckpts = report.get("checkpoints") or []
    lines.append(f"  checkpoints: {len(ckpts)}")
    for e in ckpts:
        lines.append(
            f"    [{e.get('seq'):>3}] r{e.get('round')} "
            f"{e.get('label'):<28} {str(e.get('digest'))[:16]}…"
        )
    telemetry = report.get("telemetry")
    if isinstance(telemetry, dict):
        samples = telemetry.get("samples") or []
        keys = sorted(
            {k for s in samples if isinstance(s, dict) for k in s}
            - {"t_s"}
        )
        lines.append(
            f"  telemetry: {len(samples)} samples @ "
            f"{telemetry.get('interval_s')}s "
            f"({telemetry.get('ticks')} ticks) keys={keys}"
        )
    trace = report.get("trace")
    if isinstance(trace, dict):
        lines.append(f"  profiler trace: {trace.get('dir')}")
    cost = report.get("cost")
    if isinstance(cost, dict):
        tot = cost.get("total") or {}
        lines.append(
            f"  cost: total {tot.get('achieved_gflops')} GFLOP/s, "
            f"{tot.get('achieved_gbps')} GB/s, "
            f"regime={tot.get('regime')} "
            f"eff={tot.get('efficiency')} (--roofline for the "
            f"per-stage table)"
        )
    request = report.get("request")
    if isinstance(request, dict):
        lines.append(
            f"  request: {request.get('id')} "
            f"[{request.get('priority')}/{request.get('tenant')}] "
            f"bucket={request.get('bucket')} "
            f"placement={request.get('placement')} "
            f"queue={request.get('queue_latency_s')}s "
            f"wall={request.get('prove_wall_s')}s "
            f"cache_hit={request.get('cache_hit')}"
        )
    ledger = report.get("compile_ledger")
    if ledger:
        lines.append(
            f"  compile ledger: {ledger.get('num_kernels')} kernels, "
            f"precompile {ledger.get('precompile_total_s')}s, "
            f"{ledger.get('num_dispatch_compiles')} dispatch compiles"
        )
        hits = ledger.get("aot_hits") or 0
        misses = ledger.get("aot_misses") or 0
        if hits + misses:
            lines.append(
                f"  aot artifacts: {hits}/{hits + misses} kernels "
                f"deserialized "
                f"({100 * hits / (hits + misses):.1f}% hit rate), "
                f"deserialize {ledger.get('aot_deserialize_s')}s"
            )
    return "\n".join(lines)


def render_diff(diff: dict) -> str:
    lines = [
        f"wall: {diff.get('wall_a_s')}s -> {diff.get('wall_b_s')}s",
        f"checkpoints: {diff['num_checkpoints'][0]} vs "
        f"{diff['num_checkpoints'][1]}",
    ]
    fd = diff.get("first_checkpoint_divergence")
    if fd is None:
        lines.append("digest checkpoints: IDENTICAL (no divergence)")
    else:
        lines.append(
            f"FIRST DIVERGING CHECKPOINT: seq={fd.get('seq')} "
            f"round={fd.get('round')} label={fd.get('label')!r}"
        )
        lines.append(
            f"  a={fd.get('a_digest')}\n  b={fd.get('b_digest')}"
        )
        if fd.get("length_mismatch"):
            lines.append(f"  (length mismatch: {fd['length_mismatch']})")
    lines.append("span wall deltas (top by |delta|):")
    for d in diff.get("span_deltas", ()):
        a = "-" if d["a_s"] is None else f"{d['a_s']:.3f}"
        b = "-" if d["b_s"] is None else f"{d['b_s']:.3f}"
        dl = "-" if d["delta_s"] is None else f"{d['delta_s']:+.3f}"
        lines.append(f"  {dl:>10}s  {a:>9} -> {b:<9}  {d['span']}")
    if diff.get("counter_deltas"):
        lines.append("counter deltas:")
        for k, (a, b) in diff["counter_deltas"].items():
            lines.append(f"  {k}: {a} -> {b}")
    if diff.get("cost_deltas"):
        lines.append("cost (roofline) deltas:")
        for st, ent in diff["cost_deltas"].items():
            fa, fb = ent.get("efficiency", [None, None])
            dl = ent.get("efficiency_delta")
            dl_s = f" ({dl:+.4f})" if isinstance(dl, (int, float)) else ""
            ra, rb = ent.get("regime", [None, None])
            reg = ra if ra == rb else f"{ra}->{rb}"
            lines.append(
                f"  {st}: efficiency {fa} -> {fb}{dl_s} [{reg}]"
            )
    return "\n".join(lines)


def render_roofline(report: dict) -> str:
    """Render one line's `cost` record as a per-stage roofline table:
    measured wall, achieved GFLOP/s & GB/s against the device peaks,
    arithmetic intensity, regime and efficiency fraction."""
    cost = report.get("cost")
    if not isinstance(cost, dict):
        return "no cost record on this line (schema < 3, or " \
               "BOOJUM_TPU_COST=0 / no flight recorder during the prove)"
    lines = []
    dev = cost.get("device") or {}
    lines.append(
        f"roofline: device {dev.get('kind')!r} "
        f"peak {dev.get('peak_gflops')} GFLOP/s, "
        f"{dev.get('peak_hbm_gbps')} GB/s HBM"
        + (
            f", {dev.get('peak_ici_gbps')} GB/s ICI"
            if dev.get("peak_ici_gbps") else ""
        )
        + f" [{dev.get('source')}]"
    )
    header = (
        f"  {'stage':<24} {'wall_s':>10} {'GFLOP/s':>9} {'GB/s':>9}"
        f" {'int.':>8}  {'regime':<8}{'eff':>8}"
    )
    lines.append(header)

    def _num(v, nd=4):
        return f"{v:.{nd}g}" if isinstance(v, (int, float)) else "-"

    def _row(name, ent):
        if not isinstance(ent, dict):
            return
        eff = ent.get("efficiency")
        lines.append(
            f"  {name:<24}"
            f" {_num(ent.get('wall_s'), 6):>10}"
            f" {_num(ent.get('achieved_gflops')):>9}"
            f" {_num(ent.get('achieved_gbps')):>9}"
            f" {_num(ent.get('intensity_flop_per_byte')):>8}"
            f"  {ent.get('regime', '-'):<8}"
            + (f"{100 * eff:>7.2f}%" if isinstance(eff, (int, float))
               else f"{'-':>8}")
        )

    for name, ent in (cost.get("stages") or {}).items():
        _row(name, ent)
    if isinstance(cost.get("total"), dict):
        _row("TOTAL", cost["total"])
    mc = cost.get("model_check")
    if isinstance(mc, dict):
        lines.append(
            f"  model check: {mc.get('covered_kernels')} kernels vs XLA "
            f"actuals — flops ratio {mc.get('flops_ratio')}, bytes ratio "
            f"{mc.get('bytes_ratio')}"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Perf trend + regression gate (ISSUE 12): a per-stage trajectory over a
# history of artifacts — ProveReport JSONL files, bench.py JSON lines,
# the repo's BENCH_*.json round wrappers, bench_micro.py line files —
# with a gate that exits nonzero when the LAST point regresses beyond a
# noise threshold against the median of its predecessors.
# ---------------------------------------------------------------------------

# bench statuses whose value is not a steady-state measurement — an
# elapsed lower bound (no_prove) or a compile-laden warm-up wall
# (warm_only) — excluded from every trend series
_TREND_SKIP_STATUSES = ("no_prove", "warm_only")


def _trend_identity(d: dict) -> str:
    """Compact machine/software identity of one artifact line (the
    `host` block bench.py / bench_micro.py stamp): micro lines from two
    machines or jax versions must never share a gated series. The field
    backend is part of the identity too (ISSUE 20): a babybear point
    moves half the bytes of the same goldilocks geometry, so mixing the
    two in one gated series would mask (or fabricate) a regression."""
    h = d.get("host")
    parts = (
        [
            str(h.get(k))
            for k in ("host_fp", "device_kind", "backend", "jax", "jaxlib")
            if h.get(k) is not None
        ]
        if isinstance(h, dict)
        else []
    )
    cost = d.get("cost")
    fld = d.get("field") or (
        cost.get("field") if isinstance(cost, dict) else None
    )
    if fld and fld != "goldilocks":
        # goldilocks stays unsuffixed so the repo's pre-field history
        # (and the ""-identity legacy-adoption pathway) keeps gating
        parts.append(f"field={fld}")
    return "@".join(parts)


def _point_values_from_report(rep: dict) -> dict:
    values: dict = {}
    wall = rep.get("wall_s")
    if isinstance(wall, (int, float)):
        # total_wall gates prover performance, not artifact-store
        # temperature: a cold-cache process spends most of its wall in
        # aot_load/aot_warm (compile/deserialize), and gating on it
        # would fire on cache state — the same exclusion the stage
        # series get by keying on PROVE_STAGES
        w = float(wall)
        root = _prove_root(rep.get("spans"))
        for c in (root or {}).get("children", ()):
            cw = c.get("wall_s")
            if c.get("name") in CACHE_STATE_SPANS and isinstance(
                cw, (int, float)
            ):
                w -= float(cw)
        values["total_wall"] = {"value": max(0.0, w), "unit": "s"}
    for nm, w in stage_walls(
        rep.get("spans"), names=PROVE_STAGES
    ).items():
        values[f"stage:{nm}"] = {"value": w, "unit": "s"}
    cost = rep.get("cost")
    if isinstance(cost, dict):
        for st, ent in (cost.get("stages") or {}).items():
            eff = ent.get("efficiency") if isinstance(ent, dict) else None
            if isinstance(eff, (int, float)):
                values[f"efficiency:{st}"] = {
                    "value": float(eff), "unit": "frac"
                }
    # cross-host byte gauges (multi-host shard_map proves): dcn:<name>
    # series gate DCN traffic regressions on MULTICHIP rounds
    metrics = rep.get("metrics")
    if isinstance(metrics, dict):
        for k, v in (metrics.get("gauges") or {}).items():
            if (
                k.startswith("dcn.")
                and k.endswith("bytes")
                and isinstance(v, (int, float))
            ):
                values[f"dcn:{k[len('dcn.'):]}"] = {
                    "value": float(v), "unit": "B"
                }
    return values


def _point_values_from_bench(line: dict) -> dict:
    values: dict = {}
    status = str(line.get("status") or "")
    if any(s in status for s in _TREND_SKIP_STATUSES):
        return values
    v, unit = line.get("value"), str(line.get("unit") or "")
    metric = str(line.get("metric") or "")
    if isinstance(v, (int, float)):
        if unit == "s" and metric.endswith("_prove_wall"):
            values["total_wall"] = {"value": float(v), "unit": "s"}
        elif metric:
            values[metric] = {"value": float(v), "unit": unit}
    stages = line.get("stages")
    if isinstance(stages, dict):
        for nm, w in stages.items():
            if isinstance(w, (int, float)):
                values[f"stage:{nm}"] = {"value": float(w), "unit": "s"}
    # multihost worker/bench lines carrying a per-mode dcn gauge dict
    # (scripts/multihost_worker.py result stamps) feed the same dcn:
    # series as report lines
    dcn = line.get("dcn")
    if isinstance(dcn, dict):
        for k, v in dcn.items():
            if "bytes" not in k or not isinstance(v, (int, float)):
                continue
            name = k[len("dcn."):] if k.startswith("dcn.") else k
            values[f"dcn:{name}"] = {"value": float(v), "unit": "B"}
    return values


def _metric_line_from_tail(tail) -> dict | None:
    """The LAST JSON metric line embedded in a wrapper's captured
    stdout/stderr tail (bench.py emits exactly one; XLA noise around it
    is skipped). None when the run died before emitting one."""
    if not isinstance(tail, str) or not tail:
        return None
    for ln in reversed(tail.splitlines()):
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if isinstance(d, dict) and "metric" in d:
            return d
    return None


def load_trend_file(path: str) -> list[dict]:
    """Parse ONE artifact file into trend points (usually one point; a
    bench_micro line file yields one point carrying every metric).
    Unparseable files yield an empty list — the caller reports them."""
    base = os.path.basename(path)
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return []
    lines = [ln for ln in text.splitlines() if ln.strip()]
    docs = []
    try:
        # whole-file JSON first (BENCH_*.json wrappers are indented)
        docs = [json.loads(text)]
    except ValueError:
        for ln in lines:
            try:
                docs.append(json.loads(ln))
            except ValueError:
                continue
    if not docs:
        return []
    # round wrappers: BENCH {n, cmd, rc, parsed} and MULTICHIP
    # {n_devices, rc, ok, tail}. MULTICHIP wrappers carry no `parsed`
    # block (and no `n`): the metric line — when the run got far enough
    # to emit one — is recovered from the captured `tail`, and the
    # round number from the `_rNN` filename, so multi-host history
    # rides the same ordered, identity-grouped series as BENCH rounds
    if (
        len(docs) == 1
        and isinstance(docs[0], dict)
        and ("parsed" in docs[0] or ("tail" in docs[0] and "rc" in docs[0]))
    ):
        wrapper = docs[0]
        parsed = wrapper.get("parsed")
        if not isinstance(parsed, dict):
            parsed = _metric_line_from_tail(wrapper.get("tail"))
        order = wrapper.get("n")
        if not isinstance(order, (int, float)):
            m = re.search(r"_r(\d+)", base)
            order = int(m.group(1)) if m else None
        if not isinstance(parsed, dict):
            return []
        values = _point_values_from_bench(parsed)
        if not values:
            return []
        return [{
            "source": base, "label": base,
            "order": order if isinstance(order, (int, float)) else None,
            "identity": _trend_identity(parsed), "values": values,
        }]
    reports = [
        d for d in docs
        if isinstance(d, dict) and d.get("kind") == REPORT_KIND
    ]
    if reports:
        # a report artifact: the LAST line holding an actual prove span
        # is the settled (warm) prove — a gateway 429/shed reject line
        # (wall_s=0.0, no spans) can trail the artifact and must not
        # become its trend point (a 0.0 baseline fires false
        # regressions; a 0.0 head masks real ones). The FILE name is
        # the point label — two artifacts recording the same prove
        # label ("rep3") must still be distinct trend columns
        proved = [
            d for d in reports
            if any(
                sp.get("name") == "prove"
                for _p, sp in _walk_spans(d.get("spans") or [])
            )
        ]
        if not proved:
            return []
        rep = proved[-1]
        values = _point_values_from_report(rep)
        if not values:
            return []
        return [{
            "source": base, "label": base,
            "order": None, "identity": _trend_identity(rep),
            "values": values,
        }]
    # bench.py raw line(s) / bench_micro line file: fold every metric
    # line into one point (micro lines share one run identity)
    values: dict = {}
    identity = ""
    for d in docs:
        if not isinstance(d, dict):
            continue
        values.update(_point_values_from_bench(d))
        identity = identity or _trend_identity(d)
    if not values:
        return []
    return [{
        "source": base, "label": base, "order": None,
        "identity": identity, "values": values,
    }]


def load_trend_points(paths: list[str]) -> tuple[list[dict], list[str]]:
    """Expand paths (directories glob *.json/*.jsonl, sorted by name;
    files load directly, in the order given) into trend points plus
    notes about anything skipped."""
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            names = sorted(os.listdir(p))
            files.extend(
                os.path.join(p, n) for n in names
                if n.endswith((".json", ".jsonl"))
            )
        else:
            files.append(p)
    points: list[dict] = []
    notes: list[str] = []
    for f in files:
        pts = load_trend_file(f)
        for p in pts:
            p["path"] = f
        if pts:
            points.extend(pts)
        else:
            notes.append(f"{f}: no usable trend data (skipped)")
    # BENCH round wrappers carry the round number `n`: order THOSE
    # points by it (in place, other points keep their CLI/filename
    # positions) — lexicographic filenames put r10 before r9 otherwise
    idxs = [
        i for i, p in enumerate(points)
        if isinstance(p.get("order"), (int, float))
    ]
    for i, p in zip(
        idxs, sorted((points[i] for i in idxs), key=lambda p: p["order"])
    ):
        points[i] = p
    # duplicate labels (runA/report.jsonl vs runB/report.jsonl) would
    # collapse into one rendered column: disambiguate with the parent
    # directory
    seen: dict = {}
    for p in points:
        seen[p["label"]] = seen.get(p["label"], 0) + 1
    for p in points:
        if seen[p["label"]] > 1:
            parent = os.path.basename(os.path.dirname(p.get("path", "")))
            if parent:
                p["label"] = f"{parent}/{p['label']}"
    return points, notes


def _series_direction(unit: str) -> str | None:
    """'lower' / 'higher' = which direction is BETTER; None = not
    gated (dimensionless series ride the table only). Byte series
    (the dcn:* cross-host traffic gauges) gate lower-is-better: a
    multi-host round that suddenly moves more DCN bytes regressed."""
    if unit == "s":
        return "lower"
    if unit == "B":
        return "lower"
    if unit.endswith("/s"):
        return "higher"
    return None


def trend_series(points: list[dict]) -> dict:
    """{(identity, series_name): {"unit", "points": [(label, value)]}}
    in artifact order.

    Legacy artifacts predate the identity block (identity "") — when a
    metric's points span the empty identity and exactly ONE real one,
    the legacy points join that identity's series, so the repo's
    pre-identity BENCH history keeps gating new identity-stamped runs
    instead of being silently orphaned into an ungated 1-point series.
    Two or more real identities keep the split: attributing unlabeled
    history to one of several machines would gate apples against
    oranges."""
    idents_by_name: dict = {}
    for pt in points:
        ident = pt.get("identity") or ""
        for name in (pt.get("values") or {}):
            idents_by_name.setdefault(name, set()).add(ident)
    adopt = {}
    for name, idents in idents_by_name.items():
        real = sorted(i for i in idents if i)
        if "" in idents and len(real) == 1:
            adopt[name] = real[0]
    out: dict = {}
    for pt in points:
        for name, ent in (pt.get("values") or {}).items():
            ident = pt.get("identity") or ""
            if not ident:
                ident = adopt.get(name, "")
            slot = out.setdefault(
                (ident, name), {"unit": ent.get("unit", ""), "points": []}
            )
            slot["points"].append((pt.get("label"), float(ent["value"])))
    return out


def trend_gate(
    series: dict,
    threshold: float = 0.2,
    min_abs_s: float = 0.05,
    min_points: int = 2,
) -> list[dict]:
    """Regression verdicts: for every gated series with >= min_points
    points, compare the LAST point against the MEDIAN of its
    predecessors; a lower-is-better series regresses when the last point
    exceeds baseline*(1+threshold) (and by an absolute noise floor:
    min_abs_s for seconds — sub-50ms jitter is noise, not regression —
    1 KiB for byte series); a higher-is-better series regresses below
    baseline*(1-threshold)."""
    regressions = []
    for (identity, name), slot in sorted(series.items()):
        direction = _series_direction(slot.get("unit", ""))
        if direction is None:
            continue
        pts = slot["points"]
        if len(pts) < max(2, min_points):
            continue
        prior = sorted(v for _l, v in pts[:-1])
        base = _percentile(prior, 0.5)
        last_label, last = pts[-1]
        if base is None or base != base:
            continue
        bad = False
        if direction == "lower":
            unit = slot.get("unit")
            floor = {"s": min_abs_s, "B": 1024.0}.get(unit)
            bad = last > base * (1.0 + threshold) and (
                floor is None or (last - base) >= floor
            )
        else:
            bad = last < base * (1.0 - threshold)
        if bad:
            regressions.append({
                "series": name,
                "identity": identity,
                "baseline": round(base, 6),
                "last": round(last, 6),
                "last_label": last_label,
                "ratio": round(last / base, 4) if base else None,
                "direction": direction,
            })
    return regressions


def render_trend(
    series: dict,
    regressions: list[dict] | None = None,
    labels: list | None = None,
) -> str:
    """Text trajectory table: one row per series, one column per
    artifact, regressed series flagged. `labels` pins the column order
    to the ARTIFACT order (pass `[p["label"] for p in points]`); the
    fallback — first appearance across series — can interleave columns
    when early artifacts lack the first series."""
    regressed = {
        (r["identity"], r["series"]) for r in (regressions or ())
    }
    lines = []
    if labels is not None:
        ordered: list = []
        for lb in labels:
            if lb not in ordered:
                ordered.append(lb)
        labels = ordered
    else:
        labels = []
        for slot in series.values():
            for lbl, _v in slot["points"]:
                if lbl not in labels:
                    labels.append(lbl)
    lines.append(
        "trend over " + " -> ".join(str(lb) for lb in labels)
    )
    for (identity, name), slot in sorted(series.items()):
        by_label = dict(slot["points"])
        cells = []
        for lbl in labels:
            v = by_label.get(lbl)
            cells.append(f"{v:.4g}" if isinstance(v, float) else "-")
        flag = "  << REGRESSED" if (identity, name) in regressed else ""
        ident = f" [{identity}]" if identity else ""
        lines.append(
            f"  {name:<28}{ident} "
            + " | ".join(f"{c:>9}" for c in cells)
            + f"  ({slot.get('unit')}){flag}"
        )
    for r in regressions or ():
        lines.append(
            f"REGRESSION: {r['series']} {r['baseline']} -> {r['last']} "
            f"(x{r['ratio']}, {r['direction']}-is-better, "
            f"last={r['last_label']})"
        )
    return "\n".join(lines)


def default_report_path() -> str | None:
    """The BOOJUM_TPU_REPORT env target (None = reporting off)."""
    p = os.environ.get("BOOJUM_TPU_REPORT")
    return p or None
