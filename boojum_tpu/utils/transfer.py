"""Async host<->device transfers — the overlapped prove pipeline's seam.

The sequenced prover blocks the host on every device->host pull (four
separate `np.asarray` waits per evaluation round) and uploads the whole
witness in one synchronous `jnp.asarray`, so the device queue drains at
every transcript interaction. This module gives the prover three
overlap primitives, all bit-transparent (only WHEN bytes move changes,
never what is absorbed into the transcript):

- `HostFetch` / `start_fetch`: a BATCH of device->host pulls started with
  `copy_to_host_async` the moment the producing dispatches are enqueued;
  the host keeps dispatching (challenge-independent prep, transcript
  bookkeeping) and blocks ONCE for the whole batch at `wait()`. The
  in-flight window is charged to the current span as `overlap_s`, the
  blocked remainder as `sync_s`.
- `chunked_upload`: host->device upload of a column stack in bounded row
  chunks through `jax.device_put` (each enqueues asynchronously), joined
  by one on-device concatenate — the upload overlaps whatever host work
  follows (the setup-cap transcript round, in the prover).
- `to_host`: THE blocking single-array pull (multi-process global arrays
  gather first). `parallel.sharding.host_np` delegates here, so every
  blocking pull in the pipeline lands in the same metrics counters.

Every blocking wait counts into `host.blocking_syncs` (one per `to_host`,
one per `HostFetch` batch regardless of batch size) — the tier-1 guard
test asserts the overlapped prove issues strictly fewer than the
sequenced one. `BOOJUM_TPU_OVERLAP` (default on) gates all overlap
behavior; `=0` restores the fully sequenced transfer order.
"""

from __future__ import annotations

import os
import time

import numpy as np

from . import metrics as _metrics
from . import spans as _spans

# bytes per host->device chunk of `chunked_upload` (a few chunks per
# bench-scale witness: enough to overlap, not enough to fragment)
H2D_CHUNK_BYTES = 32 << 20


def env_flag(name: str, default: bool) -> bool:
    """Shared boolean env-knob parser: 1/true/on/yes, 0/false/off/no,
    unset/empty -> `default`; anything else raises (a typo'd knob must
    never silently pick a mode)."""
    v = os.environ.get(name, "").strip().lower()
    if v in ("1", "true", "on", "yes"):
        return True
    if v in ("0", "false", "off", "no"):
        return False
    if v == "":
        return default
    raise ValueError(
        f"{name}={v!r}: use 1/true/on/yes or 0/false/off/no"
    )


def env_flag_opt(name: str) -> bool | None:
    """Tri-state form of `env_flag`: True/False for an explicit setting,
    None when the variable is unset/empty (callers supply a context-
    dependent default, e.g. pallas_sweep's backend-dependent dispatch).
    Same spelling set, same raise-on-junk contract."""
    if not os.environ.get(name, "").strip():
        return None
    return env_flag(name, False)


def overlap_enabled() -> bool:
    """BOOJUM_TPU_OVERLAP: default ON; 0/false/off/no disables (the fully
    sequenced transfer order), 1/true/on/yes forces on."""
    return env_flag("BOOJUM_TPU_OVERLAP", True)


def _is_device_array(x) -> bool:
    import jax

    return isinstance(x, jax.Array)


def _needs_allgather(x) -> bool:
    import jax

    try:
        return (
            jax.process_count() > 1 and not x.is_fully_addressable
        )
    except Exception:
        return False


def _owning_processes(x) -> list[int]:
    """Sorted process indices owning any shard of a global array."""
    try:
        return sorted(
            {int(getattr(d, "process_index", 0)) for d in x.sharding.device_set}
        )
    except Exception:
        return []


def _addressable_nbytes(x) -> int:
    """Bytes of `x` already resident on THIS host's devices (shard
    metadata only — nothing is transferred)."""
    try:
        return int(
            sum(
                s.data.size * s.data.dtype.itemsize
                for s in x.addressable_shards
            )
        )
    except Exception:
        return 0


def to_host(x):
    """Blocking device->host pull; np.asarray that also works for
    MULTI-PROCESS global arrays (a sharded jax.Array spanning
    non-addressable devices cannot be fetched directly — gather it to
    every host first, billing the cross-host bytes to the `dcn.*`
    gauges). Plain numpy/host values pass straight through.

    When the cross-host gather itself fails, raise a clear error naming
    the owning processes and the addressable-shards escape hatch instead
    of falling through to np.asarray's opaque span-of-non-addressable-
    devices failure.

    This is the pipeline's unit of host blocking: one call = one
    `host.blocking_syncs` tick + d2h byte accounting (no-ops without a
    metrics registry)."""
    was_device = _is_device_array(x)
    if was_device and _needs_allgather(x):
        local_nbytes = _addressable_nbytes(x)
        try:
            from jax.experimental import multihost_utils

            out = np.asarray(
                multihost_utils.process_allgather(x, tiled=True)
            )
        except Exception as e:
            import jax

            owners = _owning_processes(x)
            raise RuntimeError(
                f"to_host: array {getattr(x, 'shape', '?')} spans "
                "non-addressable devices (owned by processes "
                f"{owners or '?'}; this is process {jax.process_index()} "
                f"of {jax.process_count()}) and the cross-host gather "
                f"(multihost_utils.process_allgather) failed: {e!r}. "
                "Only this host's addressable shards can be fetched "
                "without a collective — use "
                "[np.asarray(s.data) for s in x.addressable_shards] for "
                "the per-host partial view."
            ) from e
        # every gathered byte NOT already resident on this host's shards
        # arrived over the cross-process (DCN) fabric
        _metrics.count_dcn_host_gather(max(out.nbytes - local_nbytes, 0))
        _metrics.count_bytes_d2h(out.nbytes)
        _metrics.count("host.blocking_syncs")
        return out
    out = np.asarray(x)
    if was_device:
        _metrics.count_bytes_d2h(out.nbytes)
        _metrics.count("host.blocking_syncs")
    return out


def prefetch_async(x):
    """Start an async device->host copy of `x` (no wait, no accounting):
    by the time a later blocking pull touches it, the bytes are already
    in flight — or landed. Safe no-op for host values and backends
    without async copies."""
    try:
        if _is_device_array(x) and not _needs_allgather(x):
            x.copy_to_host_async()
    except Exception:
        pass


class HostFetch:
    """A batch of device->host pulls in flight.

    Construction starts every transfer (`copy_to_host_async`) without
    blocking; `wait()` resolves them all with ONE blocking sync, counts
    the batch's d2h bytes, and charges the current span: the window the
    batch was in flight while the host kept working is `overlap_s`, the
    blocked tail inside wait() is `sync_s`."""

    def __init__(self, arrays, label: str | None = None):
        self.arrays = list(arrays)
        self.label = label
        self._out: list | None = None
        self._t_start = time.perf_counter()
        for a in self.arrays:
            prefetch_async(a)

    def wait(self) -> list:
        if self._out is not None:
            return self._out
        t_wait = time.perf_counter()
        out = []
        nbytes = 0
        any_device = False
        for a in self.arrays:
            if _is_device_array(a):
                if _needs_allgather(a):
                    out.append(to_host(a))  # counts its own sync
                    continue
                any_device = True
                h = np.asarray(a)
                nbytes += h.nbytes
                out.append(h)
            else:
                out.append(np.asarray(a))
        if any_device:
            _metrics.count_bytes_d2h(nbytes)
            _metrics.count("host.blocking_syncs")
            _metrics.count("transfer.d2h_batches")
        now = time.perf_counter()
        overlap_s = t_wait - self._t_start
        sync_s = now - t_wait
        _metrics.gauge_add("transfer.overlap_s", overlap_s)
        _metrics.gauge_add("transfer.sync_s", sync_s)
        rec = _spans.current_recorder()
        if rec is not None:
            rec.add_sync(sync_s)
            rec.add_overlap(overlap_s)
        self._out = out
        return out


class _SequencedFetch:
    """start_fetch's overlap-off twin: nothing is started early; wait()
    performs one fully blocking `to_host` per array (the pre-overlap
    transfer order, one `host.blocking_syncs` tick each)."""

    def __init__(self, arrays, label: str | None = None):
        self.arrays = list(arrays)
        self.label = label
        self._out: list | None = None

    def wait(self) -> list:
        if self._out is None:
            self._out = [to_host(a) for a in self.arrays]
        return self._out


def start_fetch(arrays, label: str | None = None):
    """Begin a device->host batch: overlapped (`HostFetch`) when
    BOOJUM_TPU_OVERLAP is on, fully sequenced otherwise. Either way the
    caller gets `.wait() -> list[np.ndarray]`."""
    if overlap_enabled():
        return HostFetch(arrays, label=label)
    return _SequencedFetch(arrays, label=label)


def fetch_np(*arrays, label: str | None = None) -> list:
    """Pull several device arrays as one batch (one blocking sync with
    overlap on; per-array syncs with it off)."""
    return start_fetch(arrays, label=label).wait()


def upload_chunk_shapes(row_counts, n: int) -> list[int]:
    """The per-chunk row counts `chunked_upload` dispatches for a stack of
    (rows_i, n) host arrays — shared with prover/precompile.py so the
    on-device concatenate's shape key is enumerated ahead of dispatch."""
    per = max(1, H2D_CHUNK_BYTES // max(n * 8, 1))
    shapes = []
    for rows in row_counts:
        for i in range(0, int(rows), per):
            shapes.append(min(per, int(rows) - i))
    return shapes


def _concat_rows(*parts):
    import jax.numpy as jnp

    return jnp.concatenate(parts, axis=0)


_CONCAT_JIT = None


def _concat_jit():
    global _CONCAT_JIT
    if _CONCAT_JIT is None:
        import jax

        _CONCAT_JIT = jax.jit(_concat_rows)
    return _CONCAT_JIT


def chunked_upload(host_arrays, planes: bool = False):
    """Upload a list of (rows_i, n) host arrays as one (sum_rows, n)
    device stack.

    Overlap on: each bounded row chunk goes up through its own
    `jax.device_put` (async enqueue — the host returns to transcript work
    while the DMA runs) and ONE jitted on-device concatenate joins them;
    bit-identical to uploading the host-side concatenation. Overlap off:
    exactly the legacy single synchronous `jnp.asarray(np.concatenate)`.

    With `planes` (the limb-resident prove, ISSUE 10) each chunk splits
    ONCE on host (`limbs.split_np` — the H2D edge of the residency
    contract) and uploads as two u32 planes; returns the (lo, hi) device
    pair. Same chunk walk, same total bytes."""
    import jax
    import jax.numpy as jnp

    host_arrays = [np.asarray(a) for a in host_arrays]
    if planes:
        from ..field import limbs

        split_arrays = [limbs.split_np(a) for a in host_arrays]
        if not overlap_enabled():
            if len(split_arrays) == 1:
                lo, hi = split_arrays[0]
                return jnp.asarray(lo), jnp.asarray(hi)
            return (
                jnp.asarray(np.concatenate([s[0] for s in split_arrays])),
                jnp.asarray(np.concatenate([s[1] for s in split_arrays])),
            )
        n = host_arrays[0].shape[-1]
        per = max(1, H2D_CHUNK_BYTES // max(n * 8, 1))
        parts_lo, parts_hi = [], []
        for lo, hi in split_arrays:
            for i in range(0, lo.shape[0], per):
                parts_lo.append(jax.device_put(lo[i : i + per]))
                parts_hi.append(jax.device_put(hi[i : i + per]))
        _metrics.count("transfer.h2d_chunks", 2 * len(parts_lo))
        if len(parts_lo) == 1:
            return parts_lo[0], parts_hi[0]
        return _concat_jit()(*parts_lo), _concat_jit()(*parts_hi)
    if not overlap_enabled():
        if len(host_arrays) == 1:
            return jnp.asarray(host_arrays[0])
        return jnp.asarray(np.concatenate(host_arrays, axis=0))
    n = host_arrays[0].shape[-1]
    per = max(1, H2D_CHUNK_BYTES // max(n * 8, 1))
    parts = []
    for arr in host_arrays:
        for i in range(0, arr.shape[0], per):
            parts.append(jax.device_put(arr[i : i + per]))
    _metrics.count("transfer.h2d_chunks", len(parts))
    if len(parts) == 1:
        return parts[0]
    return _concat_jit()(*parts)
