"""Black-box flight-data recorder — forensics for hangs and kills.

The flight recorder (spans/report) answers "what did this prove do"
AFTER it finishes; the telemetry sampler answers "what is the process
doing" WHILE it runs. Neither survives the failure modes that actually
cost pod time: bench rounds r03/r04 died rc=124 inside `warmup_prove`
with nothing but a phase label to show for 1500 s, because everything
interesting was buffered in memory when `timeout -k` delivered SIGKILL.

This module is the black box that survives the crash:

- a heartbeat daemon thread stamps (phase, innermost open span,
  compile-ledger deltas, rss / device memory, monotonic progress
  counter) into a crash-safe append-only JSONL sidecar — every line is
  flushed AND fsynced before the next beat, so the sidecar is valid up
  to the last instant no matter how the process dies;
- a stall detector fires when the progress counter freezes for
  `BOOJUM_TPU_STALL_S` seconds and dumps all-thread Python stacks
  (faulthandler + `sys._current_frames`) plus the partial span tree
  into the sidecar and the `BOOJUM_TPU_REPORT` artifact;
- SIGTERM/SIGINT handlers produce the same dump before the process
  dies, so an external `timeout -k` kill still leaves forensics;
- per-phase deadline alarms (`bb.deadline("setup", 300)`) give a
  localized dump when one phase blows its budget instead of a silent
  global watchdog line.

Progress is a plain module-level int bumped by `tick()` from span
open (utils/spans.py) and Fiat–Shamir checkpoints (utils/report.py):
any Python-level forward motion resets the stall clock, so only a
genuinely wedged process (or one long device computation past the
stall budget — which is exactly what you want localized) trips it.

Enablement rides `BOOJUM_TPU_BLACKBOX` (truthy, or a sidecar path) or
`BOOJUM_TPU_STALL_S` (seconds); cadence rides
`BOOJUM_TPU_BLACKBOX_INTERVAL` (default 5 s). The module-level
current-blackbox slot follows the same install/current pattern as the
other collectors — a single immutable reference, swapped whole.
"""

from __future__ import annotations

import collections
import contextlib
import faulthandler
import json
import os
import signal
import sys
import tempfile
import threading
import time
import traceback

from . import profiling as _prof
from . import spans as _spans
from . import telemetry as _telemetry

BLACKBOX_KIND = "boojum_tpu.blackbox"
BLACKBOX_SCHEMA = 1
DEFAULT_INTERVAL_S = 5.0
# heartbeats replayed inside a dump record — the trail that shows what
# the process was doing in the minute before it wedged
DUMP_HEARTBEATS = 12
_TRUTHY = ("1", "true", "on", "yes")
_FALSY = ("", "0", "false", "off", "no")

# monotonic progress counter — a plain int (GIL-atomic enough: the
# stall detector only needs changed-or-not, never an exact count)
_PROGRESS = 0


def tick(n: int = 1) -> int:
    """Bump the process-wide progress counter. Called from span open
    and checkpoint(); any call resets the stall clock."""
    global _PROGRESS
    _PROGRESS += n
    return _PROGRESS


def progress() -> int:
    return _PROGRESS


def blackbox_interval_s() -> float:
    """BOOJUM_TPU_BLACKBOX_INTERVAL: heartbeat cadence in seconds
    (default 5.0; must be > 0)."""
    v = os.environ.get("BOOJUM_TPU_BLACKBOX_INTERVAL", "").strip()
    if not v:
        return DEFAULT_INTERVAL_S
    iv = float(v)
    if iv <= 0:
        raise ValueError(
            f"BOOJUM_TPU_BLACKBOX_INTERVAL={v!r}: must be > 0 seconds"
        )
    return iv


def stall_timeout_s() -> float | None:
    """BOOJUM_TPU_STALL_S: seconds of frozen progress before a stall
    dump fires (None = stall detection off)."""
    v = os.environ.get("BOOJUM_TPU_STALL_S", "").strip()
    if not v:
        return None
    sv = float(v)
    if sv <= 0:
        raise ValueError(f"BOOJUM_TPU_STALL_S={v!r}: must be > 0 seconds")
    return sv


def blackbox_enabled() -> bool:
    """The recorder arms when BOOJUM_TPU_BLACKBOX is truthy (or names a
    sidecar path) or when a stall budget is set."""
    v = os.environ.get("BOOJUM_TPU_BLACKBOX", "").strip()
    if v.lower() in _FALSY:
        return bool(os.environ.get("BOOJUM_TPU_STALL_S", "").strip())
    return True


def _sidecar_from_env() -> str | None:
    """A non-boolean BOOJUM_TPU_BLACKBOX value is the sidecar path;
    otherwise derive `<report>.blackbox` from BOOJUM_TPU_REPORT."""
    v = os.environ.get("BOOJUM_TPU_BLACKBOX", "").strip()
    if v and v.lower() not in _TRUTHY and v.lower() not in _FALSY:
        return v
    report = os.environ.get("BOOJUM_TPU_REPORT", "").strip()
    if report:
        return report + ".blackbox"
    return None


def _rss_kb() -> int | None:
    """Current RSS in KiB via /proc/self/statm (Linux); None elsewhere."""
    try:
        with open("/proc/self/statm") as f:
            fields = f.read().split()
        return int(fields[1]) * (os.sysconf("SC_PAGE_SIZE") // 1024)
    except Exception:
        return None


def _open_span(rec) -> tuple[str | None, str | None]:
    """The innermost still-open span of `rec`: its /-joined path
    ("prove/round3_quotient") AND its span_id (ISSUE 17: incidents join
    the stitched timeline through the id). Reads the sanitized tree()
    snapshot — open spans surface there with error="unclosed" — so the
    heartbeat thread never touches the recorder's thread-local stack."""
    if rec is None:
        return None, None
    try:
        roots = rec.tree()
    except Exception:
        return None, None
    best: list[str] | None = None
    best_sp: dict | None = None

    def _walk(sp, path):
        nonlocal best, best_sp
        path = path + [sp.get("name", "?")]
        open_here = sp.get("error") == "unclosed"
        deeper = False
        for c in sp.get("children", ()):
            if _walk(c, path):
                deeper = True
        if open_here and not deeper:
            if best is None or len(path) > len(best):
                best = path
                best_sp = sp
        return open_here or deeper

    for r in roots:
        _walk(r, [])
    if best is None:
        return None, None
    sid = best_sp.get("span_id") if isinstance(best_sp, dict) else None
    return "/".join(best), sid if isinstance(sid, str) else None


def _open_span_path(rec) -> str | None:
    return _open_span(rec)[0]


def _ledger_fields() -> dict:
    """A small cumulative slice of the compile ledger — the heartbeat
    diffs consecutive beats into `*_delta` fields so a beat stream
    shows WHEN compilation happened, not just that it did."""
    led = _prof.current_compile_ledger()
    if led is None:
        return {}
    try:
        s = led.summary()
    except Exception:
        return {}
    out = {}
    for k in (
        "num_kernels",
        "cache_hits",
        "cache_misses",
        "num_dispatch_compiles",
        "aot_hits",
        "aot_misses",
    ):
        if k in s:
            out[f"compile.{k}"] = s[k]
    return out


def _thread_stacks() -> list[dict]:
    """Structured per-thread stacks via sys._current_frames — the
    machine-readable complement of the faulthandler text."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(
            {
                "thread": names.get(ident, str(ident)),
                "stack": [
                    ln.rstrip()
                    for ln in traceback.format_stack(frame)[-12:]
                ],
            }
        )
    return out


def _faulthandler_text() -> str:
    """All-thread dump as faulthandler renders it. faulthandler writes
    only to real fds, so dump into a temp file and read it back."""
    try:
        with tempfile.TemporaryFile(mode="w+") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
            f.seek(0)
            return f.read()
    except Exception as e:
        return f"<faulthandler unavailable: {type(e).__name__}: {e}>"


class BlackBox:
    """One armed recorder: a heartbeat thread + stall/deadline/signal
    dump machinery over one append-only sidecar file."""

    def __init__(
        self,
        sidecar: str | None = None,
        interval_s: float | None = None,
        stall_s: float | None = None,
        label: str = "",
        report_path: str | None = None,
    ):
        self.sidecar = sidecar if sidecar is not None else _sidecar_from_env()
        self.interval_s = (
            blackbox_interval_s() if interval_s is None else float(interval_s)
        )
        if self.interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.stall_s = stall_timeout_s() if stall_s is None else stall_s
        self.label = label
        self.report_path = report_path
        self.t0 = time.perf_counter()
        self._phase: str = ""
        self._seq = 0
        self._heartbeats: collections.deque = collections.deque(
            maxlen=DUMP_HEARTBEATS
        )
        self._deadlines: dict[int, tuple[str, float]] = {}
        self._deadline_fired: set[int] = set()
        self._deadline_seq = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._fd = None
        self._last_progress = progress()
        self._last_change_t = time.perf_counter()
        self._stall_dumped = False
        self._ledger_prev: dict = {}
        self._prev_handlers: dict[int, object] = {}
        self._in_signal_dump = False
        self.dumps = 0

    # ---- phase / deadlines ----------------------------------------------
    def set_phase(self, phase: str):
        self._phase = phase
        tick()

    @property
    def phase(self) -> str:
        return self._phase

    @contextlib.contextmanager
    def deadline(self, name: str, seconds: float):
        """Declare "this block may take `seconds`": if it is still open
        when the budget expires, the heartbeat thread emits one
        localized dump (reason="deadline") naming the block."""
        with self._lock:
            self._deadline_seq += 1
            did = self._deadline_seq
            self._deadlines[did] = (
                name,
                time.perf_counter() + float(seconds),
            )
        try:
            yield
        finally:
            with self._lock:
                self._deadlines.pop(did, None)
                self._deadline_fired.discard(did)

    # ---- sidecar IO -------------------------------------------------------
    def _write_sidecar(self, rec: dict):
        if self.sidecar is None:
            return
        with self._lock:
            if self._fd is None:
                self._fd = open(self.sidecar, "a")
            self._fd.write(json.dumps(rec, sort_keys=True) + "\n")
            self._fd.flush()
            os.fsync(self._fd.fileno())

    def _write_report(self, rec: dict):
        """Append a dump into the ProveReport artifact (crash-safely:
        open/append/flush/fsync/close) so `prove_report.py --check`
        sees the forensics next to the prove lines."""
        path = self.report_path or os.environ.get("BOOJUM_TPU_REPORT")
        if not path:
            return
        try:
            with open(path, "a") as f:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except Exception:
            pass

    # ---- records ----------------------------------------------------------
    def _base_record(self, record: str) -> dict:
        self._seq += 1
        rec: dict = {
            "kind": BLACKBOX_KIND,
            "schema": BLACKBOX_SCHEMA,
            "record": record,
            "seq": self._seq,
            "t_s": round(time.perf_counter() - self.t0, 3),
            "unix_ts": time.time(),
            "pid": os.getpid(),
            "phase": self._phase,
            "progress": progress(),
        }
        if self.label:
            rec["label"] = self.label
        srec = _spans.current_recorder()
        sp, sid = _open_span(srec)
        if sp is not None:
            rec["span"] = sp
        if sid is not None:
            rec["span_id"] = sid
        # trace stamp: the live recorder's trace ties every beat and
        # stall/SIGTERM dump to the request it interrupted
        tid = getattr(srec, "trace_id", None)
        if isinstance(tid, str) and _spans.valid_trace_id(tid):
            rec["trace_id"] = tid
        return rec

    def heartbeat(self) -> dict:
        """Stamp one beat into the sidecar (flushed + fsynced)."""
        rec = self._base_record("heartbeat")
        rss = _rss_kb()
        if rss is not None:
            rec["rss_kb"] = rss
        sampler = _telemetry.current_sampler()
        if sampler is not None:
            latest = sampler.latest()
            if latest:
                for k in ("device_bytes_in_use", "live_bytes"):
                    if k in latest:
                        rec[k] = latest[k]
        led = _ledger_fields()
        for k, v in led.items():
            rec[k] = v
            prev = self._ledger_prev.get(k)
            if prev is not None and v != prev:
                rec[f"{k}_delta"] = v - prev
        self._ledger_prev = led
        self._heartbeats.append(rec)
        self._write_sidecar(rec)
        return rec

    def dump(self, reason: str, **extra) -> dict:
        """The forensic record: all-thread stacks + partial span tree +
        the recent heartbeat trail, written to the sidecar AND the
        report artifact, both fsynced."""
        rec = self._base_record("dump")
        rec["reason"] = reason
        rec.update(extra)
        rec["stacks"] = _thread_stacks()
        rec["faulthandler"] = _faulthandler_text()
        srec = _spans.current_recorder()
        if srec is not None:
            try:
                rec["spans"] = srec.tree()
            except Exception:
                pass
        rec["heartbeats"] = list(self._heartbeats)
        self.dumps += 1
        self._write_sidecar(rec)
        self._write_report(rec)
        try:
            where = f" in {rec['span']}" if rec.get("span") else ""
            print(
                f"[boojum-tpu] blackbox dump: reason={reason}"
                f" phase={self._phase or '?'}{where}"
                f" progress={rec['progress']}",
                file=sys.stderr,
                flush=True,
            )
        except Exception:
            pass
        return rec

    # ---- monitor loop -----------------------------------------------------
    def _check_stall(self, now: float):
        cur = progress()
        if cur != self._last_progress:
            self._last_progress = cur
            self._last_change_t = now
            self._stall_dumped = False
            return
        if (
            self.stall_s is not None
            and not self._stall_dumped
            and now - self._last_change_t >= self.stall_s
        ):
            self._stall_dumped = True
            self.dump(
                "stall",
                stall_s=self.stall_s,
                frozen_for_s=round(now - self._last_change_t, 3),
            )

    def _check_deadlines(self, now: float):
        with self._lock:
            expired = [
                (did, name, ts)
                for did, (name, ts) in self._deadlines.items()
                if now >= ts and did not in self._deadline_fired
            ]
            for did, _, _ in expired:
                self._deadline_fired.add(did)
        for _, name, ts in expired:
            self.dump(
                "deadline",
                deadline=name,
                overdue_s=round(now - ts, 3),
            )

    def _run(self):
        # sub-second poll when the stall/deadline budgets are tighter
        # than the heartbeat cadence, so a 0.2 s test budget fires fast
        poll = self.interval_s
        if self.stall_s is not None:
            poll = min(poll, max(self.stall_s / 4.0, 0.05))
        next_beat = 0.0
        while not self._stop.wait(poll):
            now = time.perf_counter()
            try:
                self._check_stall(now)
                self._check_deadlines(now)
                if now >= next_beat:
                    next_beat = now + self.interval_s
                    self.heartbeat()
            except Exception:
                # forensics must never take the workload down
                continue

    # ---- signals ----------------------------------------------------------
    def _signal_dump(self, signum, frame):
        if not self._in_signal_dump:
            self._in_signal_dump = True
            try:
                name = signal.Signals(signum).name.lower()
            except Exception:
                name = str(signum)
            try:
                self.dump(name, signum=int(signum))
            except Exception:
                pass
        prev = self._prev_handlers.get(signum, signal.SIG_DFL)
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            # re-deliver with the default disposition so the exit
            # status still says "killed by SIGTERM" to the parent
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    def _install_signals(self):
        if threading.current_thread() is not threading.main_thread():
            return
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev_handlers[sig] = signal.signal(
                    sig, self._signal_dump
                )
            except (ValueError, OSError):
                pass

    def _restore_signals(self):
        if threading.current_thread() is not threading.main_thread():
            return
        for sig, prev in list(self._prev_handlers.items()):
            try:
                if signal.getsignal(sig) == self._signal_dump:
                    signal.signal(sig, prev)
            except (ValueError, OSError, TypeError):
                pass
        self._prev_handlers.clear()

    # ---- lifecycle --------------------------------------------------------
    def start(self) -> "BlackBox":
        self._stop.clear()
        t = self._thread
        if t is not None and t.is_alive():
            return self
        self._install_signals()
        self._last_progress = progress()
        self._last_change_t = time.perf_counter()
        self.heartbeat()  # one synchronous baseline beat
        self._thread = threading.Thread(
            target=self._run, name="boojum-blackbox", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2 * self.interval_s + 1.0)
            if t.is_alive():
                return
        self._thread = None
        self._restore_signals()
        with self._lock:
            if self._fd is not None:
                try:
                    self._fd.close()
                except Exception:
                    pass
                self._fd = None

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()


# process-wide current-blackbox slot — immutable None or a BlackBox
# reference, same install/current pattern as the other collectors
_BLACKBOX: BlackBox | None = None


def current_blackbox() -> BlackBox | None:
    return _BLACKBOX


def install_blackbox(bb: BlackBox | None) -> BlackBox | None:
    """Swap the process-wide blackbox slot; returns the previous one.
    The caller owns start()/stop()."""
    global _BLACKBOX
    prev = _BLACKBOX
    _BLACKBOX = bb
    return prev


def ensure_started(
    label: str = "", report_path: str | None = None
) -> BlackBox | None:
    """Entry-point wiring: arm (and start) a process-wide blackbox when
    the env asks for one and none is installed yet. Idempotent — the
    second entry point to run just updates the label/phase context via
    set_phase. Returns the active blackbox (or None when disabled)."""
    bb = _BLACKBOX
    if bb is not None:
        if not bb.running():
            bb.start()
        return bb
    if not blackbox_enabled():
        return None
    bb = BlackBox(label=label, report_path=report_path)
    install_blackbox(bb)
    bb.start()
    return bb


def set_phase(phase: str):
    """Stamp the current coarse phase onto the active blackbox (no-op
    when none is armed); also a progress tick."""
    bb = _BLACKBOX
    if bb is not None:
        bb.set_phase(phase)
