"""Background telemetry sampler — the live service's time axis.

The flight recorder (spans/metrics/report) answers "what did THIS prove
do"; this module answers "what is the PROCESS doing right now": a daemon
thread snapshots `device.memory_stats()`, the `jax.live_arrays()`
census, and any registered provider callables (the proving service
registers queue depth, per-lane occupancy and in-flight count) on a
fixed cadence into

- current-value gauges on the sampler's own MetricsRegistry (what the
  HTTP `/metrics` endpoint renders as Prometheus text), plus
  `gauge_max` high-water marks, and
- a bounded ring of time-stamped samples — the `telemetry` record that
  `report.build_report` attaches to every ProveReport line while a
  sampler is running (schema 2), so a request line shows the queue and
  memory pressure that surrounded it.

Cadence rides BOOJUM_TPU_TELEMETRY_INTERVAL (seconds, default 1.0).
Sampling is best-effort by design: a provider that raises is skipped
for that tick (and counted on `telemetry.provider_errors`), never
crashing the service. The module-level current-sampler slot follows the
same install/current pattern as the other collectors — a single
immutable reference, swapped whole.
"""

from __future__ import annotations

import collections
import os
import threading
import time

from . import metrics as _metrics

DEFAULT_INTERVAL_S = 1.0
# ring-buffer bound: at the default 1 Hz cadence this is ~10 minutes of
# history; the per-report `telemetry` record is clipped harder (below)
MAX_SAMPLES = 600
# samples attached to one ProveReport line — enough to cover a prove's
# window without bloating the JSONL artifact
SNAPSHOT_SAMPLES = 60


def telemetry_interval_s() -> float:
    """BOOJUM_TPU_TELEMETRY_INTERVAL: sampler cadence in seconds
    (default 1.0; must be > 0)."""
    v = os.environ.get("BOOJUM_TPU_TELEMETRY_INTERVAL", "").strip()
    if not v:
        return DEFAULT_INTERVAL_S
    iv = float(v)
    if iv <= 0:
        raise ValueError(
            f"BOOJUM_TPU_TELEMETRY_INTERVAL={v!r}: must be > 0 seconds"
        )
    return iv


class TelemetrySampler:
    """Periodic snapshotter. `providers` map gauge-suffix -> zero-arg
    callable returning a number or a {suffix: number} dict; built-in
    sources (device memory, live-buffer census) always sample."""

    def __init__(
        self,
        interval_s: float | None = None,
        registry: "_metrics.MetricsRegistry | None" = None,
        max_samples: int = MAX_SAMPLES,
    ):
        self.interval_s = (
            telemetry_interval_s() if interval_s is None else float(interval_s)
        )
        if self.interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.registry = registry or _metrics.MetricsRegistry()
        self._providers: dict[str, object] = {}
        self._samples: collections.deque = collections.deque(
            maxlen=max_samples
        )
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._t0 = time.perf_counter()
        # the same instant on the wall clock: samples carry monotonic
        # t_s offsets, so the timeline stitcher (ISSUE 17) needs this
        # anchor to place them on the cross-host absolute axis
        self.t0_unix_ts = round(time.time(), 6)
        self.ticks = 0
        self.provider_errors = 0

    # ---- providers -------------------------------------------------------
    def add_provider(self, name: str, fn) -> None:
        """Register a sample source: `fn()` returns a number (recorded
        as `telemetry.<name>`) or a dict of {suffix: number} (recorded
        as `telemetry.<name>.<suffix>`)."""
        with self._lock:
            self._providers[name] = fn

    # ---- sampling --------------------------------------------------------
    def sample_once(self) -> dict:
        """Take one snapshot NOW (also what the daemon thread does each
        tick): returns the flat sample dict that entered the ring."""
        sample: dict = {
            "t_s": round(time.perf_counter() - self._t0, 3)
        }
        census = _metrics.live_buffer_census()
        if census is not None:
            sample["live_arrays"], sample["live_bytes"] = census
        dm = _metrics.device_memory_stats()
        if dm:
            sample["device_bytes_in_use"] = dm.get("bytes_in_use", 0)
            peak = dm.get("peak_bytes_in_use")
            if peak is not None:
                sample["device_peak_bytes_in_use"] = peak
        with self._lock:
            providers = list(self._providers.items())
        for name, fn in providers:
            # the value CONVERSION is inside the guard too: a provider
            # returning junk (None in a dict, a string) must be skipped
            # and counted, never crash the sampler — start() calls this
            # synchronously, so an escape would abort run_worker
            try:
                v = fn()
                if isinstance(v, dict):
                    for suffix, sv in v.items():
                        sample[f"{name}.{suffix}"] = float(sv)
                elif v is not None:
                    sample[name] = float(v)
            except Exception:
                self.provider_errors += 1
                self.registry.count("telemetry.provider_errors")
                continue
        for k, v in sample.items():
            if k == "t_s":
                continue
            self.registry.gauge_set(f"telemetry.{k}", float(v))
            self.registry.gauge_max(f"telemetry.{k}_high_water", float(v))
        with self._lock:
            self._samples.append(sample)
            self.ticks += 1
        self.registry.gauge_set("telemetry.ticks", self.ticks)
        return sample

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                # the sampler must never take the service down; one bad
                # tick (e.g. a backend probe raising mid-teardown) is
                # dropped, the next tick retries
                self.provider_errors += 1

    def start(self) -> "TelemetrySampler":
        # clear BEFORE the liveness check: a thread whose stop() timed
        # out mid-drain (wedged provider) resumes sampling instead of
        # observing the stale stop event and dying silently; if it was
        # already past its loop exit, the next start() sees a dead
        # handle and respawns
        self._stop.clear()
        t = self._thread
        if t is not None and t.is_alive():
            return self
        self.sample_once()  # one synchronous baseline sample
        self._thread = threading.Thread(
            target=self._run, name="boojum-telemetry", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2 * self.interval_s + 1.0)
            if t.is_alive():
                # a provider is wedged past the join budget: keep the
                # handle so running() stays truthful and a later start()
                # can never spawn a DUPLICATE sampler over the same ring
                return
        self._thread = None

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # ---- views -----------------------------------------------------------
    def snapshot(self, limit: int = SNAPSHOT_SAMPLES) -> dict:
        """The report-line `telemetry` record: cadence + tick count +
        the most recent `limit` samples (time-ordered)."""
        with self._lock:
            samples = list(self._samples)[-limit:]
        return {
            "interval_s": self.interval_s,
            "ticks": self.ticks,
            "t0_unix_ts": self.t0_unix_ts,
            "samples": samples,
        }

    def series(self, key: str) -> list[tuple[float, float]]:
        """(t_s, value) pairs of one sampled key — dashboard food."""
        with self._lock:
            return [
                (s["t_s"], s[key]) for s in self._samples if key in s
            ]

    def latest(self) -> dict:
        """The most recent sample ({} before the first tick) — what the
        blackbox heartbeat stamps for device-memory context without
        touching the backend from its own thread."""
        with self._lock:
            return dict(self._samples[-1]) if self._samples else {}


_SAMPLER: TelemetrySampler | None = None


def current_sampler() -> TelemetrySampler | None:
    return _SAMPLER


def install_sampler(
    sampler: TelemetrySampler | None,
) -> TelemetrySampler | None:
    """Swap the process-wide sampler slot (report.build_report reads it
    to attach the `telemetry` record); returns the previous one. The
    caller owns start()/stop()."""
    global _SAMPLER
    prev = _SAMPLER
    _SAMPLER = sampler
    return prev
