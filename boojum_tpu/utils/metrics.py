"""Prover metrics registry — the flight recorder's counter/gauge axis.

Counters (host↔device transfer bytes, NTT/Merkle/FRI invocation counts)
and gauges (device-memory high water, live-buffer census) accumulated
alongside the span tree. The module-level helpers (`count`, `gauge_max`,
`stage_boundary`) are no-op-cheap when no registry is installed — one
contextvar read, one global read and a None check — so the prover keeps
them threaded through its hot path permanently. Like the span recorder
(utils/spans.py), the active registry resolves contextvar-first: a
scoped registry (one packed service request) overrides the
process-global default within its execution context only.

Memory sources, best-effort by design:
- `device.memory_stats()` (bytes_in_use / peak_bytes_in_use) where the
  backend exposes it (TPU does; XLA:CPU usually returns None) — guarded,
  absent keys are simply omitted from the report.
- `jax.live_arrays()` census (count + total bytes) — works on every
  backend and is what the old BOOJUM_TPU_MEMLOG printed; here it lands in
  per-stage `boundaries` entries so HBM growth is attributable to a stage.
"""

from __future__ import annotations

import contextvars
import threading
import time


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self.counters: dict[str, int] = {}
        self.gauges: dict[str, float] = {}
        self.boundaries: list[dict] = []

    def count(self, name: str, n: int = 1):
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + int(n)

    def gauge_set(self, name: str, v: float):
        with self._lock:
            self.gauges[name] = v

    def gauge_max(self, name: str, v: float):
        with self._lock:
            if v > self.gauges.get(name, float("-inf")):
                self.gauges[name] = v

    def gauge_add(self, name: str, v: float):
        with self._lock:
            self.gauges[name] = self.gauges.get(name, 0.0) + float(v)

    def boundary(self, label: str):
        """Record a stage-boundary snapshot: live-buffer census plus (when
        the backend exposes it) device memory stats; also folds the peak
        readings into gauges so the report's summary carries high-water
        marks without walking the boundary list."""
        entry: dict = {
            "label": label,
            "t_s": round(time.perf_counter() - self._t0, 4),
        }
        census = live_buffer_census()
        if census is not None:
            entry["live_arrays"], entry["live_bytes"] = census
            self.gauge_max("mem.live_bytes_high_water", census[1])
        dm = device_memory_stats()
        if dm:
            entry["device_memory"] = dm
            peak = dm.get("peak_bytes_in_use")
            if peak is not None:
                self.gauge_max("mem.device_peak_bytes_in_use", peak)
            in_use = dm.get("bytes_in_use")
            if in_use is not None:
                self.gauge_max("mem.device_bytes_in_use_high_water", in_use)
        with self._lock:
            self.boundaries.append(entry)

    def fold(self, other: "MetricsRegistry"):
        """Accumulate another registry's snapshot into this one:
        counters ADD (events keep counting across requests), gauges
        LAST-WRITE (a gauge is a current-value reading, Prometheus
        semantics). The proving service folds each request's scoped
        registry into its service-lifetime one so /metrics shows the
        prove counter families after the per-request recorder is
        torn down."""
        snap = other.to_dict()
        with self._lock:
            for k, v in (snap.get("counters") or {}).items():
                self.counters[k] = self.counters.get(k, 0) + int(v)
            self.gauges.update(snap.get("gauges") or {})

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "counters": dict(sorted(self.counters.items())),
                "gauges": {
                    k: round(float(v), 4)
                    for k, v in sorted(self.gauges.items())
                },
                "boundaries": list(self.boundaries),
            }


# process-global DEFAULT context (bench/CLI posture); scoped registries
# (install_scoped_registry) override it per execution context so packed
# concurrent requests accumulate into disjoint registries
_REGISTRY: MetricsRegistry | None = None
_REGISTRY_CTX: contextvars.ContextVar[MetricsRegistry | None] = (
    contextvars.ContextVar("boojum_tpu.metrics_registry", default=None)
)


def current_registry() -> MetricsRegistry | None:
    """The ACTIVE registry: context-scoped when one is bound, else the
    process-global default."""
    reg = _REGISTRY_CTX.get()
    return reg if reg is not None else _REGISTRY


def install_registry(reg: MetricsRegistry | None) -> MetricsRegistry | None:
    """Swap the process-wide DEFAULT registry; returns the previous one."""
    global _REGISTRY
    prev = _REGISTRY
    _REGISTRY = reg
    return prev


def install_scoped_registry(reg: MetricsRegistry | None):
    """Bind `reg` to the CURRENT execution context only; returns a token
    for reset_scoped_registry."""
    return _REGISTRY_CTX.set(reg)


def reset_scoped_registry(token):
    _REGISTRY_CTX.reset(token)


def start_metrics() -> MetricsRegistry:
    reg = MetricsRegistry()
    install_registry(reg)
    return reg


def stop_metrics() -> MetricsRegistry | None:
    return install_registry(None)


# -- no-op-cheap module-level recording hooks --------------------------------


def count(name: str, n: int = 1):
    reg = current_registry()
    if reg is not None:
        reg.count(name, n)


def gauge_max(name: str, v: float):
    reg = current_registry()
    if reg is not None:
        reg.gauge_max(name, v)


def gauge_add(name: str, v: float):
    reg = current_registry()
    if reg is not None:
        reg.gauge_add(name, v)


def count_upload(x):
    """Tally a fresh host->device upload of a device array `x` (the
    prover's explicit upload seams — prover._dev_cached, the sequenced
    stage-2 table uploads); passes `x` through. A (lo, hi) limb plane
    pair (the resident prove's upload unit) counts both planes."""
    reg = current_registry()
    if reg is not None:
        try:
            if isinstance(x, tuple):
                count_bytes_h2d(
                    sum(int(a.size) * a.dtype.itemsize for a in x)
                )
            else:
                count_bytes_h2d(int(x.size) * x.dtype.itemsize)
        except Exception:
            pass
    return x


def count_bytes_h2d(nbytes: int):
    """Host->device upload accounting (counted at the prover's explicit
    upload seams; transfers inside compiled graphs are invisible here)."""
    reg = current_registry()
    if reg is not None:
        reg.count("transfer.h2d_bytes", nbytes)
        reg.count("transfer.h2d_ops")


def count_bytes_d2h(nbytes: int):
    reg = current_registry()
    if reg is not None:
        reg.count("transfer.d2h_bytes", nbytes)
        reg.count("transfer.d2h_ops")


def count_ici_all_to_all(crossing_bytes: float, dcn_bytes: float = 0.0):
    """Tally one explicit all-to-all layout pivot on the shard_map mesh
    (parallel/shard_sweep.py). `crossing_bytes` is the intra-host (ICI)
    portion of the global payload that actually crosses the interconnect;
    `dcn_bytes` is the cross-process (DCN) portion on a multi-host mesh —
    the caller owns the (D-1)/D topology math and the DCN split
    (parallel/multihost.dcn_fraction), this seam owns the gauge names:
    `ici.all_to_alls` / `ici.all_to_all_bytes` (and `ici.pivot_s` for the
    dispatch window, charged by shard_sweep's pivot timer), plus
    `dcn.all_to_alls` / `dcn.all_to_all_bytes` whenever the collective
    crossed a process boundary."""
    reg = current_registry()
    if reg is not None:
        reg.count("ici.all_to_alls")
        reg.gauge_add("ici.all_to_all_bytes", crossing_bytes)
        if dcn_bytes > 0:
            reg.count("dcn.all_to_alls")
            reg.gauge_add("dcn.all_to_all_bytes", dcn_bytes)


def count_ici_all_gather(crossing_bytes: float, dcn_bytes: float = 0.0):
    """Tally one explicit all-gather to replicated (caps, small node
    layers): `ici.all_gathers` / `ici.all_gather_bytes`, with the
    cross-process portion split out as `dcn.all_gathers` /
    `dcn.all_gather_bytes` (same contract as count_ici_all_to_all)."""
    reg = current_registry()
    if reg is not None:
        reg.count("ici.all_gathers")
        reg.gauge_add("ici.all_gather_bytes", crossing_bytes)
        if dcn_bytes > 0:
            reg.count("dcn.all_gathers")
            reg.gauge_add("dcn.all_gather_bytes", dcn_bytes)


def count_dcn_host_gather(dcn_bytes: float):
    """Tally one host-side gather of a non-fully-addressable global array
    (multihost_utils.process_allgather in transfer.to_host / the
    addressable-safe demesh): `dcn.host_gathers` / `dcn.host_gather_bytes`
    bill the bytes this process pulled from OTHER hosts over DCN."""
    reg = current_registry()
    if reg is not None:
        reg.count("dcn.host_gathers")
        reg.gauge_add("dcn.host_gather_bytes", dcn_bytes)


def count_service_cache(event: str, nbytes: int = 0):
    """Tally one device-resident cache-manager event (service/cache.py).
    `event` is "hit" | "miss" | "evict"; the seam owns the `service.*`
    gauge names so the cache manager, the report validator and the SLO
    summary can never disagree on them:
      service.cache.hits / .misses / .evictions   (counters)
      service.cache.evicted_bytes                 (gauge, evictions only)
    """
    reg = current_registry()
    if reg is None:
        return
    if event == "hit":
        reg.count("service.cache.hits")
    elif event == "miss":
        reg.count("service.cache.misses")
    elif event == "evict":
        reg.count("service.cache.evictions")
        reg.gauge_add("service.cache.evicted_bytes", float(nbytes))


def count_aot(event: str):
    """Tally one AOT artifact-store event (prover/aot.py). The seam owns
    the `aot.*` counter names so the artifact loader, the report
    validator and the SLO summary can never disagree on them:
      aot.hits / aot.misses            (warm pass, per kernel)
      aot.builds / aot.bundles_loaded  (per bundle)
      aot.bundle_misses / aot.stale_bundles / aot.corrupt_bundles
      aot.corrupt_entries
    """
    reg = current_registry()
    if reg is not None:
        reg.count(f"aot.{event}")


def gauge_aot_add(name: str, v: float):
    """Accumulate an `aot.<name>` gauge (deserialize_s, load_s,
    bundle_bytes — the artifact store's wall/size axis; the report
    validator requires deserialize_s whenever aot hits/misses were
    counted)."""
    reg = current_registry()
    if reg is not None:
        reg.gauge_add(f"aot.{name}", float(v))


def gauge_set_cost(name: str, v: float):
    """Set a `cost.<name>` gauge (the roofline record's per-stage
    achieved GFLOP/s, GB/s and efficiency fractions — utils/costmodel.py
    exports them here so /metrics and the report line's gauges carry the
    same numbers the `cost` record does)."""
    reg = current_registry()
    if reg is not None:
        reg.gauge_set(f"cost.{name}", float(v))


def gauge_service(name: str, v: float):
    """Set a `service.<name>` gauge (queue depth, pinned bytes, occupancy
    — the proving service's per-request SLO axis)."""
    reg = current_registry()
    if reg is not None:
        reg.gauge_set(f"service.{name}", float(v))


def stage_boundary(label: str):
    reg = current_registry()
    if reg is not None:
        reg.boundary(label)


# -- memory probes -----------------------------------------------------------


def live_buffer_census() -> tuple[int, int] | None:
    """(num_live_arrays, total_bytes) over jax.live_arrays(), or None when
    jax is unavailable."""
    try:
        import jax

        live = jax.live_arrays()
        return len(live), int(
            sum(a.size * a.dtype.itemsize for a in live)
        )
    except Exception:
        return None


def device_memory_stats() -> dict | None:
    """Aggregated device.memory_stats() over local devices: sums
    bytes_in_use, maxes peak_bytes_in_use. None/{} when the backend does
    not expose stats (XLA:CPU)."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return None
    in_use = 0
    peak = 0
    seen = False
    kinds = set()
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        seen = True
        kinds.add(getattr(d, "device_kind", str(d.platform)))
        in_use += int(stats.get("bytes_in_use", 0))
        peak = max(peak, int(stats.get("peak_bytes_in_use", 0)))
    if not seen:
        return None
    out = {"bytes_in_use": in_use, "device_kinds": sorted(kinds)}
    if peak:
        out["peak_bytes_in_use"] = peak
    return out
