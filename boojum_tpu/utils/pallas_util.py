"""Shared helpers for Pallas TPU kernels.

The framework enables `jax_enable_x64` globally (the field is 64-bit), which
makes BlockSpec index maps trace as i64 — Mosaic only legalizes i32 index
computations. `imap32` wraps an index map so every returned coordinate is cast
back to int32.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp


_FORCE_XLA = [False]
_LOCAL_OPERANDS = [False]


class force_xla:
    """Context manager pinning dispatchers to the XLA path (used while
    tracing GSPMD-sharded graphs, which pallas_call cannot partition)."""

    def __enter__(self):
        self._prev = _FORCE_XLA[0]
        _FORCE_XLA[0] = True
        return self

    def __exit__(self, *exc):
        _FORCE_XLA[0] = self._prev
        return False


class local_operands:
    """Trace-time marker that the dispatchers below are seeing per-chip
    LOCAL blocks — the bodies of parallel/shard_sweep.py's shard_map
    graphs enter it while they trace, so `pallas_enabled` can skip its
    active-mesh veto (that veto exists for PLAIN jits over mesh-sharded
    operands, which GSPMD cannot hand to a pallas_call). Same idiom as
    `force_xla`; force_xla still wins when both are active."""

    def __enter__(self):
        self._prev = _LOCAL_OPERANDS[0]
        _LOCAL_OPERANDS[0] = True
        return self

    def __exit__(self, *exc):
        _LOCAL_OPERANDS[0] = self._prev
        return False


def pallas_enabled(opt_in_env: str | None = None) -> bool:
    """True when the fused TPU kernels should be used.

    Requires the TPU backend, no active prover mesh (the sharded pipeline
    keeps plain XLA ops so GSPMD can partition them — pallas_call does not
    split under a NamedSharding; shard_map bodies announce their per-chip
    blocks via `local_operands` and keep the kernels), and no
    BOOJUM_TPU_PALLAS=0 override. With `opt_in_env`, additionally requires
    that env var to be "1" (used by kernels that currently trail the XLA
    path and are opt-in)."""
    if opt_in_env is not None and os.environ.get(opt_in_env, "0") != "1":
        return False
    if _FORCE_XLA[0]:
        return False
    from .transfer import env_flag

    if not env_flag("BOOJUM_TPU_PALLAS", True):
        return False
    try:
        if jax.default_backend() != "tpu":
            return False
    except Exception:
        return False
    if _LOCAL_OPERANDS[0]:
        return True
    from ..parallel.sharding import active_mesh

    return active_mesh() is None


def tpu_compiler_params(vmem_limit_bytes: int):
    """A pltpu CompilerParams instance tolerating both pallas API
    generations (`CompilerParams` was `TPUCompilerParams` before jax 0.5),
    or None when neither exists — so interpret-mode fallback, which the
    shard_map mesh path uses for CPU parity tests, imports everywhere.
    Shared by the Poseidon2 / limb-sweep / MXU-NTT kernel modules."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    return cls(vmem_limit_bytes=vmem_limit_bytes) if cls else None


def pick_tile(R: int, budget_rows: int) -> int:
    """A legal Mosaic tile for a row axis of R sublane rows: divides R
    (grid = R // tile must cover every output row — a non-divisor would
    silently leave trailing rows unwritten) AND is a multiple of 8 or R
    itself (the sublane block rule). Whole-R blocks are always legal.
    (Shared by the Poseidon2 and limb-sweep kernel families.)"""
    if R <= budget_rows:
        return R
    best = None
    t = 8
    while t <= min(R, budget_rows):
        if R % t == 0:
            best = t
        t *= 2
    if best is None:
        raise ValueError(
            f"no legal tile for R={R} (need R % 8 == 0 when R exceeds the "
            f"VMEM row budget {budget_rows})"
        )
    return best


def _to_i32(v):
    if isinstance(v, int):
        return jnp.int32(v)
    return jax.lax.convert_element_type(v, jnp.int32)


def imap32(fn):
    def wrapped(*args):
        out = fn(*args)
        if not isinstance(out, tuple):
            out = (out,)
        return tuple(_to_i32(v) for v in out)

    return wrapped
