"""Kernel cost model + roofline attribution (ISSUE 12).

The flight recorder answers "how LONG did each stage take"; this module
answers "how long SHOULD it have taken": an analytic cost sheet — field
muls/adds folded into XLA-flop equivalents, HBM bytes moved, ICI bytes
crossed — for every executable `prover/precompile.enumerate_kernels`
emits, parameterized on `ShapeBucket` geometry and the active variant
flags (limb_sweep / limb_resident / mesh / streamed). Joined with the
measured span walls and the `ici.*` / `transfer.*` gauges, it stamps a
validated `cost` record on every ProveReport line: achieved GFLOP/s and
GB/s per stage, the roofline regime (compute- vs memory-bound, from
arithmetic intensity against the device's machine balance) and the
efficiency fraction against peak — the instrument that says WHICH kernel
is leaving performance on the table, per line, per round (ICICLE's
per-kernel achieved-vs-peak posture, PAPERS.md).

Two layers share one set of per-family op-count primitives:

- `cost_sheet(specs)`: per-kernel, per-DISPATCH analytic cost derived
  from each KernelSpec's name + ShapeDtypeStruct args. This is the axis
  cross-checked against XLA's own `compiled.cost_analysis()` /
  `memory_analysis()` actuals, which prover/precompile.py and
  prover/aot.py capture at compile time into CompileLedger entries and
  the AOT bundle manifest (so zero-compile cold processes still carry
  actuals).
- `stage_costs(sb, ...)`: per-STAGE analytic totals over the whole
  prove (a kernel like `coset_eval_wit` dispatches Q times; the stage
  layer owns that multiplicity so the roofline record never needs
  per-dispatch bookkeeping).

Flop convention: XLA's HloCostAnalysis counts ONE flop per elementwise
arithmetic op per element — integer ops included — so "flops" here means
machine elementwise ops, not floating-point math. A Goldilocks field mul
on the emulated-u64 path lowers to ~W_MUL such ops (cross products +
reduce128 chain); the weights below are calibrated against the measured
`cost_analysis()` of the real 2^10 kernel library on XLA:CPU and the
agreement band is documented in BASELINE.md ("Cost model & trend
protocol") and pinned by tests/test_costmodel.py.

Everything here is import-light (stdlib only at module import; jax only
inside device probes) and fails soft: a cost-model bug must never fail a
prove — `attach_cost_record` logs and returns None on any internal
error.
"""

from __future__ import annotations

import json
import math
import os
import threading

# ---------------------------------------------------------------------------
# Field-op weights (XLA elementwise-op equivalents per element, calibrated
# against compiled.cost_analysis() of the 2^10 CPU kernel library — see
# tests/test_costmodel.py::test_analytic_model_within_tolerance_of_xla)
# ---------------------------------------------------------------------------

# one Goldilocks mul (mul + Goldilocks reduction as XLA lowers it on the
# u64 path: widening products, shifts, carry/select chain)
W_MUL = 22.0
# one Goldilocks add: add + overflow correction (compare/select)
W_ADD = 4.0
# one GF(p^2) extension mul: 3 base muls (Karatsuba) + combines
W_EXT_MUL = 3 * W_MUL + 4 * W_ADD
# Poseidon2 t=12 permutation, as measured: 8 full rounds (12 x^7
# sboxes + external MDS) + 22 partial rounds (1 sbox + internal
# diagonal) ≈ 5100 elementwise ops and ~2.2 kB of round-state traffic
P2_FLOPS_PER_PERM = 5100.0
P2_BYTES_PER_PERM = 2200.0
P2_RATE = 8  # sponge absorb rate (field elements per permutation)
# batch inversion as the XLA kernels actually do it (Fermat
# exponentiation chain per element, not the 3-mul Montgomery trick):
# ~64 squarings + ~32 muls of reduction-bearing math per element
BINV_FLOPS_PER_ELEM = 4900.0
BINV_BYTES_PER_ELEM = 1600.0
# one FRI 2-to-1 fold, per SURVIVING element: extension mul-accumulate
# plus the deinterleave gathers and challenge-table indexing
FOLD_FLOPS_PER_ELEM = 700.0
FOLD_BYTES_PER_ELEM = 220.0
# DEEP accumulation, per (column, point): ext mul-add against the
# inverted denominators
DEEP_FLOPS_PER_ELEM = 100.0
DEEP_BYTES_PER_ELEM = 32.0

# BabyBear (ISSUE 19): one u32 LANE per element, so every bytes term is
# elem_bytes/8 of its Goldilocks twin — that factor-2 is the whole point
# of the field backend and is pinned by tests/test_babybear.py. Flop
# weights deliberately REUSE the u64-path calibration (a BabyBear mul is
# one widening mul + mod, far under W_MUL): the `_bb` sheet's flops are
# a conservative upper bound until a device calibration pass lands; its
# bytes are exact per-lane.
BB_ELEM_BYTES = 4.0
# Poseidon2 t=16 BabyBear permutation: 8 full rounds (16 x^7 sboxes +
# M4-block external MDS) + 13 partial rounds over a 64-byte u32 state
P2BB_FLOPS_PER_PERM = 2600.0
P2BB_BYTES_PER_PERM = 1500.0


def _flops(muls: float, adds: float) -> float:
    return muls * W_MUL + adds * W_ADD


# ---------------------------------------------------------------------------
# Device peaks (nominal, documented — BASELINE.md). "flops" is the XLA
# elementwise-op convention above, so peaks are integer-ALU element ops/s,
# not marketed bf16 TFLOPS.
# ---------------------------------------------------------------------------

# device_kind substring ->
#   (peak integer GOP/s, HBM GB/s, ICI GB/s per link, DCN GB/s per host)
# DCN is the cross-host fabric (data-center network) a multi-process mesh's
# collectives cross; ~200 Gb/s NICs per TPU host -> 25 GB/s nominal.
DEVICE_PEAKS = (
    ("v5 lite", (394.0 * 16, 819.0, 186.0, 25.0)),  # v5e: 8 MXU-adj. VPUs
    ("v5e", (394.0 * 16, 819.0, 186.0, 25.0)),
    ("v4", (275.0 * 16, 1228.0, 300.0, 25.0)),
    ("v3", (123.0 * 16, 900.0, 140.0, 25.0)),
    # XLA:CPU single-core nominal: a few int64 lanes at a few GHz
    ("cpu", (20.0, 25.0, 0.0, 0.0)),
)
_DEFAULT_PEAKS = (50.0, 50.0, 0.0, 0.0)


def cost_enabled() -> bool:
    """BOOJUM_TPU_COST: stamp the `cost` roofline record on report lines
    and export `cost.*` gauges (default on; =0 disables the plane)."""
    from .transfer import env_flag

    return env_flag("BOOJUM_TPU_COST", True)


def device_peaks() -> dict:
    """The active device's nominal peaks: {kind, peak_gflops,
    peak_hbm_gbps, peak_ici_gbps, peak_dcn_gbps, source}.
    BOOJUM_TPU_COST_PEAKS="gflops,hbm_gbps[,ici_gbps[,dcn_gbps]]"
    overrides the table (source:"env"); an unknown device kind falls
    to a conservative default (source:"default")."""
    kind = "unknown"
    try:
        import jax

        dev = jax.devices()[0]
        kind = str(getattr(dev, "device_kind", dev.platform))
    except Exception:
        pass
    env = os.environ.get("BOOJUM_TPU_COST_PEAKS", "").strip()
    if env:
        # a malformed override falls back to the table (logged), never
        # silently disabling the whole cost plane via attach's guard
        try:
            parts = [float(x) for x in env.split(",")]
            gflops, hbm = parts[0], parts[1]
            ici = parts[2] if len(parts) > 2 else 0.0
            dcn = parts[3] if len(parts) > 3 else 0.0
            return {
                "kind": kind, "peak_gflops": gflops,
                "peak_hbm_gbps": hbm, "peak_ici_gbps": ici,
                "peak_dcn_gbps": dcn, "source": "env",
            }
        except (ValueError, IndexError):
            try:
                from .profiling import log as _plog

                _plog(
                    f"cost model: BOOJUM_TPU_COST_PEAKS={env!r} is not "
                    f'"gflops,hbm_gbps[,ici_gbps[,dcn_gbps]]" — using '
                    f"the device table"
                )
            except Exception:
                pass
    lk = kind.lower()
    for sub, peaks in DEVICE_PEAKS:
        if sub in lk:
            return {
                "kind": kind, "peak_gflops": peaks[0],
                "peak_hbm_gbps": peaks[1], "peak_ici_gbps": peaks[2],
                "peak_dcn_gbps": peaks[3], "source": "table",
            }
    return {
        "kind": kind, "peak_gflops": _DEFAULT_PEAKS[0],
        "peak_hbm_gbps": _DEFAULT_PEAKS[1],
        "peak_ici_gbps": _DEFAULT_PEAKS[2],
        "peak_dcn_gbps": _DEFAULT_PEAKS[3], "source": "default",
    }


# ---------------------------------------------------------------------------
# Per-family op-count primitives (shared by the kernel sheet and the
# stage totals — the two layers can never disagree on a family's math)
# ---------------------------------------------------------------------------


def ntt_cost(B: float, n: float, elem_bytes: float = 8.0) -> dict:
    """One batched size-n (i)NTT over B columns: n/2·log2(n) butterflies
    per column (1 mul + 2 adds each) plus a scale pass; each of the
    log2(n) stages re-reads and re-writes the full array. `elem_bytes`
    is the field element's device footprint (8 for Goldilocks limbs, 4
    for the BabyBear u32 lane)."""
    log_n = max(1.0, math.log2(max(n, 2)))
    muls = B * (n / 2) * log_n + B * n
    adds = B * n * log_n
    bytes_ = 2.0 * B * n * elem_bytes * log_n
    return {"flops": _flops(muls, adds), "hbm_bytes": bytes_}


def lde_cost(B: float, n: float, L: float,
             elem_bytes: float = 8.0) -> dict:
    """LDE from monomials at rate L: per coset a scale pass (n muls/col)
    plus a forward size-n NTT."""
    per = ntt_cost(B, n, elem_bytes)
    return {
        "flops": L * (per["flops"] + _flops(B * n, 0)),
        "hbm_bytes": L * per["hbm_bytes"] + B * n * elem_bytes * (L + 1),
    }


def sponge_cost(rows: float, width: float) -> dict:
    """Poseidon2 leaf sponges over `rows` rows of `width` field elements
    (rate-8 absorb)."""
    perms = rows * max(1.0, math.ceil(width / P2_RATE))
    return {
        "flops": perms * P2_FLOPS_PER_PERM,
        "hbm_bytes": perms * P2_BYTES_PER_PERM,
    }


def node_cost(N: float) -> dict:
    """Merkle node stack over N leaf digests: ~N 2-to-1 compressions
    (one permutation each) across all layers."""
    return {
        "flops": N * P2_FLOPS_PER_PERM,
        "hbm_bytes": N * P2_BYTES_PER_PERM,
    }


def binv_cost(m: float, elem_bytes: float = 8.0) -> dict:
    """Batch inversion of m elements (per-element Fermat chain, as the
    XLA kernels lower it)."""
    return {
        "flops": m * BINV_FLOPS_PER_ELEM,
        "hbm_bytes": m * BINV_BYTES_PER_ELEM * (elem_bytes / 8.0),
    }


def sweep_cost(domain: float, terms: float,
               elem_bytes: float = 8.0) -> dict:
    """The fused quotient sweep: `terms` alpha-weighted constraint terms
    evaluated over a `domain`-point coset domain, each an extension
    mul-accumulate on base-field operands."""
    muls = domain * terms * 3
    adds = domain * terms * 3
    return {
        "flops": _flops(muls, adds),
        "hbm_bytes": domain * terms * elem_bytes * 0.5,
    }


def deep_cost(cols: float, N: float, elem_bytes: float = 8.0) -> dict:
    """DEEP quotient accumulation: per column an extension
    mul-accumulate against the inverted denominators over N points."""
    return {
        "flops": cols * N * DEEP_FLOPS_PER_ELEM,
        "hbm_bytes": cols * N * DEEP_BYTES_PER_ELEM * (elem_bytes / 8.0),
    }


def fold_cost(m: float, k: int = 1, elem_bytes: float = 8.0) -> dict:
    """One FRI 2^k-to-1 fold chain from domain size m: each of the k
    halvings is an extension mul-accumulate (plus deinterleave gathers)
    over the surviving half."""
    flops = 0.0
    bytes_ = 0.0
    cur = m
    for _ in range(max(1, k)):
        flops += (cur / 2) * FOLD_FLOPS_PER_ELEM
        bytes_ += (cur / 2) * FOLD_BYTES_PER_ELEM * (elem_bytes / 8.0)
        cur /= 2
    return {"flops": flops, "hbm_bytes": bytes_}


def _zero() -> dict:
    return {"flops": 0.0, "hbm_bytes": 0.0}


def _acc(total: dict, part: dict, mult: float = 1.0):
    total["flops"] += mult * part.get("flops", 0.0)
    total["hbm_bytes"] += mult * part.get("hbm_bytes", 0.0)
    total["ici_bytes"] = total.get("ici_bytes", 0.0) + mult * part.get(
        "ici_bytes", 0.0
    )
    if part.get("dcn_bytes"):
        total["dcn_bytes"] = total.get("dcn_bytes", 0.0) + mult * part[
            "dcn_bytes"
        ]
    return total


# ---------------------------------------------------------------------------
# Per-kernel analytic sheet (the cross-check axis vs XLA actuals)
# ---------------------------------------------------------------------------


def _arg_bytes(a) -> int:
    if isinstance(a, (tuple, list)):
        return sum(_arg_bytes(x) for x in a)
    shape = getattr(a, "shape", None)
    if shape is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * getattr(getattr(a, "dtype", None), "itemsize", 8)


def _arg_shapes(args) -> list[tuple]:
    """Flat list of array shapes among the args (plane pairs flatten to
    their two u32 planes; static ints are skipped)."""
    return [
        tuple(int(d) for d in a.shape) for a in _flatten_args(args)
    ]


def _plane_pair(a) -> bool:
    """A (lo, hi) u32 plane pair — exactly two same-shape 4-byte-dtype
    arrays, the limb-resident argument unit (precompile._sdsp)."""
    if not (isinstance(a, (tuple, list)) and len(a) == 2):
        return False
    lo, hi = a
    sl, sh = getattr(lo, "shape", None), getattr(hi, "shape", None)
    if sl is None or sh is None or tuple(sl) != tuple(sh):
        return False
    return all(
        getattr(getattr(x, "dtype", None), "itemsize", 0) == 4
        for x in (lo, hi)
    )


def _main_elems(args) -> float:
    """Field elements of the LARGEST logical array argument (a (lo, hi)
    u32 plane pair is ONE logical argument — one field element per u32
    pair, i.e. bytes/8 either way)."""
    best = 0
    stack = list(args)
    while stack:
        a = stack.pop(0)
        if _plane_pair(a):
            best = max(best, _arg_bytes(a))
        elif isinstance(a, (tuple, list)):
            stack = list(a) + stack
        elif getattr(a, "shape", None) is not None:
            best = max(best, _arg_bytes(a))
    return best / 8.0


def _flatten_args(args):
    out = []
    stack = list(args)
    while stack:
        a = stack.pop(0)
        if isinstance(a, (tuple, list)):
            stack = list(a) + stack
            continue
        if getattr(a, "shape", None) is not None:
            out.append(a)
    return out


def kernel_cost(name: str, args, mesh_devices: int = 1) -> dict:
    """Analytic {flops, hbm_bytes, ici_bytes, family} for ONE dispatch of
    the named kernel with these (ShapeDtypeStruct) args. Families key on
    the enumeration's ledger names (prover/precompile.py); kernels
    outside every family get a generic elementwise estimate tagged
    family="fallback" — the tolerance cross-check only binds modeled
    families."""
    base = name.split(":", 1)[1] if ":" in name else name
    if "_bb" in base:
        # the BabyBear plane-free kernel set (prover/bb_kernels.py):
        # single u32 lanes, so elements = bytes/4 and every bytes term
        # scales by BB_ELEM_BYTES/8 against its Goldilocks twin
        return _kernel_cost_bb(base, name, args)
    in_bytes = sum(_arg_bytes(a) for a in args)
    E = _main_elems(args)  # field elements of the dominant operand
    shapes = _arg_shapes(args)
    D = max(1, int(mesh_devices))
    c: dict = {"flops": 0.0, "hbm_bytes": 0.0, "ici_bytes": 0.0}

    def fam(family: str, part: dict, ici: float = 0.0):
        c["flops"] = part.get("flops", 0.0)
        c["hbm_bytes"] = part.get("hbm_bytes", 0.0) or float(in_bytes * 2)
        c["ici_bytes"] = ici
        c["family"] = family
        return c

    # dominant-operand (B, n) for column-batched kernels
    Bn = shapes[0] if shapes else (1, 1)
    B = float(Bn[0]) if len(Bn) >= 2 else 1.0
    n = float(Bn[-1]) if Bn else 1.0

    if base.startswith(("imono", "mono")):
        return fam("ntt", ntt_cost(B, n))
    if base.startswith("fwd") or base.startswith("ntt"):
        return fam("ntt", ntt_cost(B, n))
    if "lde_pivot" in base:
        # per-chip LDE + the col->row all_to_all pivot (rate-L payload)
        L = _lde_rate_from(name, shapes)
        part = dict(lde_cost(B, n, L), ici_bytes=0.0)
        if "leaf" in base:
            part = _acc(part, sponge_cost(n * L, B))
        ici = B * n * 8 * L * (D - 1) / D if D > 1 else 0.0
        return fam("lde", part, ici=ici)
    if base.startswith("lde") or "lde_block" in base:
        L = _lde_rate_from(name, shapes)
        return fam("lde", lde_cost(B, n, L))
    if base.startswith("leaf_digests"):
        # args are (B, L, n): rows = L*n, width B
        rows = float(Bn[1] * Bn[2]) if len(Bn) >= 3 else n
        return fam("sponge", sponge_cost(rows, B))
    if base.startswith("absorb"):
        # (N, 12) state x (N, b) block: absorb b cols into N-row sponges
        blk = shapes[1] if len(shapes) > 1 else Bn
        rows = float(blk[0])
        width = float(blk[1]) if len(blk) > 1 else 1.0
        part = sponge_cost(rows, width)
        if "absorb_lde_block" in base:
            part = _acc(part, lde_cost(width, rows, 1.0))
        return fam("sponge", part)
    if base.startswith("node_layers") or base.startswith("node_step"):
        return fam("sponge", node_cost(n if len(Bn) < 2 else float(Bn[0])))
    if base.startswith("node_gather"):
        return fam(
            "ici", {"flops": 0.0, "hbm_bytes": float(in_bytes * 2)},
            ici=float(in_bytes) * (D - 1),
        )
    if base.startswith("coset_eval"):
        return fam("ntt", _acc(ntt_cost(B, n), {"flops": _flops(B * n, 0),
                                                "hbm_bytes": 0.0}))
    if base.startswith("coset_sweep_terms"):
        # xs arg is Q*n points; the alpha table length bounds the terms
        # (u64 path: the 1-D capA power arrays; resident path: the
        # (4, S_cols) host-built scalar table)
        domain = max((s[0] for s in shapes if len(s) == 1), default=n)
        cands = [
            s[0] for s in shapes
            if len(s) == 1 and s[0] not in (2,) and s[0] != domain
        ] or [s[1] for s in shapes if len(s) == 2 and s[0] == 4]
        terms = min(cands) if cands else 32
        return fam("sweep", sweep_cost(float(domain), float(terms)))
    if base.startswith("quotient_interp"):
        # coset interpolation: inverse-vandermonde solve over the Q
        # per-coset columns — inversion-chain-heavy, measured per elem
        tot = in_bytes / 8.0
        return fam("interp", {"flops": tot * 350.0,
                              "hbm_bytes": tot * 320.0})
    if base.startswith(("chunk_num_den", "lookup_denominators")):
        return fam("stage2", {
            "flops": E * 410.0, "hbm_bytes": in_bytes * 4.5,
        })
    if base.startswith("z_and_partials"):
        # the grand-product ratios invert their partials — binv-priced
        return fam("stage2", binv_cost(E))
    if base.startswith(("stage2_stack", "zshift")):
        return fam("stage2", {
            "flops": E * W_EXT_MUL, "hbm_bytes": in_bytes * 3.0,
        })
    if "binv" in base or base.startswith("ext_binv"):
        return fam("binv", binv_cost(E))
    if base.startswith(("alpha_powers", "deep_powers")):
        return fam("small", {"flops": E * W_EXT_MUL,
                             "hbm_bytes": in_bytes * 2.0})
    if base.startswith("deep_denoms"):
        # a broadcast subtract per point — cheap, no inversions here
        return fam("deep", {"flops": E * 8.0, "hbm_bytes": E * 40.0})
    if base.startswith("evals"):
        return fam("deep", {"flops": E * W_EXT_MUL,
                            "hbm_bytes": in_bytes * 3.0})
    if base.startswith("deep_codeword"):
        cols = sum(float(s[0]) for s in shapes if len(s) == 2)
        N = max((float(s[-1]) for s in shapes if len(s) == 2), default=n)
        part = deep_cost(cols, N)
        # the boundary col->row source re-layout of the (lo,hi) planes:
        # same convention as lde_pivot and the round5 stage total —
        # global payload, (D-1)/D of it crossing chips
        ici = N * 8 * 2 * (D - 1) / D if D > 1 else 0.0
        return fam("deep", part, ici=ici)
    if base.startswith("deep_block"):
        return fam("deep", deep_cost(B, n))
    if base.startswith("deep_combine"):
        return fam("deep", {"flops": E * 210.0, "hbm_bytes": E * 90.0})
    if base.startswith("deep_extras"):
        return fam("deep", {"flops": E * 600.0, "hbm_bytes": E * 64.0})
    # deep_regen:<ntt-spec> kernels strip to their inner ntt/lde names
    # above ("lde_b.._L.." etc.) and are owned by those branches
    if base.startswith(("fri_fold", "fri_leaf", "fri_commit")):
        k = _fold_k_from(name)
        part = fold_cost(E, k)
        if "leaf" in base or "commit" in base:
            # the pre-fold oracle commit: 2^k-leaf sponges over both
            # extension components
            part = _acc(
                part, sponge_cost(E / float(1 << k), float(2 << k))
            )
        return fam("fri", part)
    if base.startswith("fri_final"):
        return fam("ntt", ntt_cost(1.0, E))
    if base.startswith("witness_upload_concat"):
        return fam("transfer", {"flops": 0.0, "hbm_bytes": in_bytes * 2.0})
    # generic elementwise estimate
    return fam("fallback", {"flops": E * 8.0, "hbm_bytes": in_bytes * 2.0})


def _kernel_cost_bb(base: str, name: str, args) -> dict:
    """Analytic cost of one `_bb` kernel dispatch. Same families as the
    Goldilocks routing so the roofline and model_check aggregate them
    together; every entry additionally carries field="babybear" and
    elem_bytes=4 so a report consumer can attribute the byte halving."""
    eb = BB_ELEM_BYTES
    in_bytes = sum(_arg_bytes(a) for a in args)
    shapes = _arg_shapes(args)
    Bn = shapes[0] if shapes else (1, 1)
    B = float(Bn[0]) if len(Bn) >= 2 else 1.0
    n = float(Bn[-1]) if Bn else 1.0
    E = max(
        (float(_shape_elems(s)) for s in shapes), default=1.0
    )  # elements of the dominant operand — u32 lanes, one per element

    def fam(family: str, part: dict) -> dict:
        return {
            "flops": part.get("flops", 0.0),
            "hbm_bytes": part.get("hbm_bytes", 0.0)
            or float(in_bytes * 2),
            "ici_bytes": 0.0,
            "family": family,
            "field": "babybear",
            "elem_bytes": eb,
        }

    if base.startswith(("imono", "mono", "fwd", "ntt")):
        return fam("ntt", ntt_cost(B, n, elem_bytes=eb))
    if base.startswith("lde"):
        return fam("lde", lde_cost(B, n, _lde_rate_from(name, shapes),
                                   elem_bytes=eb))
    if base.startswith("leaf_digests"):
        # (B, N) columns -> N leaves of width B
        rows = n if len(Bn) >= 2 else float(Bn[0])
        perms = rows * max(1.0, math.ceil(B / P2_RATE))
        return fam("sponge", {
            "flops": perms * P2BB_FLOPS_PER_PERM,
            "hbm_bytes": perms * P2BB_BYTES_PER_PERM,
        })
    if base.startswith("node_layers"):
        leaves = float(Bn[0])
        return fam("sponge", {
            "flops": leaves * P2BB_FLOPS_PER_PERM,
            "hbm_bytes": leaves * P2BB_BYTES_PER_PERM,
        })
    if base.startswith("coset_sweep_terms"):
        domain = max((s[0] for s in shapes if len(s) == 1), default=n)
        # transition + boundary over 4 ext coordinates
        return fam("sweep", sweep_cost(float(domain), 8.0, elem_bytes=eb))
    if base.startswith("deep_accumulate"):
        N = max((float(s[-1]) for s in shapes if len(s) == 2), default=n)
        cols = 1.0 + sum(
            float(s[0]) for s in shapes if len(s) == 2 and s[-1] == N
        )
        return fam("deep", deep_cost(cols, N, elem_bytes=eb))
    if base.startswith("fri_fold"):
        return fam("fri", fold_cost(E / 4.0, _fold_k_from(name),
                                    elem_bytes=eb))
    if "binv" in base:
        return fam("binv", binv_cost(E, elem_bytes=eb))
    return fam("fallback", {"flops": E * 8.0,
                            "hbm_bytes": in_bytes * 2.0})


def _shape_elems(s) -> int:
    n = 1
    for d in s:
        n *= int(d)
    return n


def _lde_rate_from(name: str, shapes) -> float:
    """Recover the commit rate L from an lde-family kernel's name
    (lde_L<k>_..., *_lde8_*) or default 2 (the spec args carry only the
    monomial side)."""
    import re

    m = re.search(r"(?:lde|_L)(\d+)", name)
    if m:
        v = int(m.group(1))
        if 1 <= v <= 64:
            return float(v)
    return 2.0


def _fold_k_from(name: str) -> int:
    import re

    m = re.search(r"_k(\d+)", name)
    return int(m.group(1)) if m else 1


def xla_cost_of(compiled) -> dict | None:
    """The XLA-reported actuals of one compiled executable:
    {flops, bytes_accessed, arg_bytes, out_bytes, temp_bytes} — the
    cross-check axis captured at compile time (prover/precompile.py,
    prover/aot.py) into CompileLedger entries and AOT manifests. None
    when the backend exposes neither analysis (never an error: actuals
    are an observability bonus, not a compile requirement)."""
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            f = ca.get("flops")
            if isinstance(f, (int, float)) and f == f and f >= 0:
                out["flops"] = round(float(f), 1)
            b = ca.get("bytes accessed")
            if isinstance(b, (int, float)) and b == b and b >= 0:
                out["bytes_accessed"] = round(float(b), 1)
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        for key, attr in (
            ("arg_bytes", "argument_size_in_bytes"),
            ("out_bytes", "output_size_in_bytes"),
            ("temp_bytes", "temp_size_in_bytes"),
        ):
            v = getattr(ma, attr, None)
            if isinstance(v, (int, float)) and v >= 0:
                out[key] = int(v)
    except Exception:
        pass
    return out or None


def cost_sheet(specs, mesh_devices: int = 1) -> dict:
    """{kernel_name: analytic cost} over a KernelSpec list (one entry per
    executable, per-dispatch units)."""
    out = {}
    for spec in specs:
        try:
            out[spec.name] = kernel_cost(
                spec.name, spec.args, mesh_devices=mesh_devices
            )
        except Exception:  # noqa: BLE001 — one odd spec must not void
            out[spec.name] = {  # the whole sheet
                "flops": 0.0, "hbm_bytes": 0.0, "ici_bytes": 0.0,
                "family": "error",
            }
    return out


# ---------------------------------------------------------------------------
# Per-stage analytic totals (the roofline record's numerator)
# ---------------------------------------------------------------------------

# ONE definition of the prover's stage set (report.PROVE_STAGES): the
# roofline record and the trend gate must never disagree on what a
# "stage" is — cache-state spans (aot_load/aot_warm/overlap_prefetch)
# are deliberately excluded from both
from .report import PROVE_STAGES as STAGE_NAMES  # noqa: E402


def stage_costs(
    sb, config, mesh_devices: int = 1, dcn_fraction: float = 0.0
) -> dict:
    """Analytic per-stage {flops, hbm_bytes, ici_bytes[, dcn_bytes]} for
    one full prove of a circuit in this ShapeBucket — multiplicities
    (Q coset evals, per-oracle commits, the fold schedule) owned HERE,
    so the per-kernel sheet stays per-dispatch. On a multi-host mesh
    `dcn_fraction` (parallel/multihost.dcn_fraction) splits every
    modeled crossing-byte term into intra-host ici_bytes and cross-host
    dcn_bytes — the same topology split the measured dcn.* gauges
    carry."""
    from ..prover.fri import fold_schedule

    n = float(sb.trace_len)
    L = float(sb.lde_factor)
    N = float(sb.domain_len)
    Q = float(sb.quotient_degree)
    D = max(1, int(mesh_devices))
    terms = float(_total_alpha_terms(sb))

    def commit(B: float, mono: bool = True) -> dict:
        total = {"flops": 0.0, "hbm_bytes": 0.0, "ici_bytes": 0.0}
        if mono:
            _acc(total, ntt_cost(B, n))
        _acc(total, lde_cost(B, n, L))
        _acc(total, sponge_cost(N, B))
        _acc(total, node_cost(N))
        if D > 1:
            # col->row Merkle pivot (rate-L planes) + cap all_gather
            total["ici_bytes"] += B * n * 8 * L * (D - 1) / D
            total["ici_bytes"] += float(sb.cap_size) * 4 * 8 * (D - 1)
        return total

    stages: dict = {}
    # round 1: witness upload + commit
    r1 = commit(float(sb.B_wit))
    r1["hbm_bytes"] += sb.B_wit * n * 8  # H2D witness upload
    stages["round1_witness_commit"] = r1
    # round 2: grand product / lookup polys + stage-2 commit
    r2 = {"flops": 0.0, "hbm_bytes": 0.0, "ici_bytes": 0.0}
    _acc(r2, {"flops": sb.Ct * n * 2 * W_EXT_MUL,
              "hbm_bytes": sb.Ct * n * 8 * 4})
    _acc(r2, binv_cost(sb.num_chunks * n))
    if sb.lookups:
        _acc(r2, {"flops": sb.lookup_subargs * sb.lookup_width * n
                  * W_EXT_MUL,
                  "hbm_bytes": sb.lookup_subargs * n * 8 * 2})
        _acc(r2, binv_cost((sb.lookup_subargs + 1) * n))
    _acc(r2, commit(float(sb.S)))
    stages["round2_stage2_commit"] = r2
    # round 3: Q coset evals of every oracle + the fused sweep + interp
    # + quotient commit (LDE only; monomials come from the interp)
    r3 = {"flops": 0.0, "hbm_bytes": 0.0, "ici_bytes": 0.0}
    evaled = float(sb.B_wit + sb.B_setup + sb.S + 2)
    _acc(r3, ntt_cost(evaled, n), mult=Q)
    _acc(r3, sweep_cost(Q * n, terms))
    # quotient interpolation (inverse-vandermonde, per-elem calibrated)
    _acc(r3, {"flops": 2 * Q * n * 350.0, "hbm_bytes": 2 * Q * n * 320.0})
    _acc(r3, commit(float(sb.B_q), mono=False))
    stages["round3_quotient"] = r3
    # round 4: evaluations at z/zw (horner over monomials)
    stages["round4_evaluations"] = {
        "flops": float(sb.B_all + sb.S) * n * (W_MUL + W_ADD) * 2,
        "hbm_bytes": float(sb.B_all + sb.S) * n * 8,
        "ici_bytes": 0.0,
    }
    # round 5: DEEP accumulation over every committed column + FRI
    r5 = {"flops": 0.0, "hbm_bytes": 0.0, "ici_bytes": 0.0}
    _acc(r5, deep_cost(float(sb.B_all), N))
    _acc(r5, binv_cost(2 * N))
    sched = fold_schedule(
        int(n), config.fri_final_degree,
        getattr(config, "fri_folding_schedule", None),
    )
    cur = N
    for k in sched:
        _acc(r5, fold_cost(cur, int(k)))
        cur /= float(1 << int(k))
        _acc(r5, sponge_cost(cur / 16.0, 16.0))  # per-oracle leaf commit
        _acc(r5, node_cost(cur / 16.0))
    _acc(r5, ntt_cost(1.0, cur))  # final interpolation
    if D > 1:
        r5["ici_bytes"] += N * 8 * 2 * (D - 1) / D
    stages["round5_deep_fri"] = r5
    # queries: gathers + host assembly — bytes, no meaningful flops
    stages["queries"] = {
        "flops": float(sb.num_queries) * 1e3,
        "hbm_bytes": float(sb.num_queries)
        * (sb.B_all + 40.0) * 8 * math.log2(max(N, 2)),
        "ici_bytes": 0.0,
    }
    f = min(max(float(dcn_fraction), 0.0), 1.0)
    for st in stages.values():
        st.setdefault("ici_bytes", 0.0)
        if f > 0.0 and st["ici_bytes"] > 0.0:
            st["dcn_bytes"] = st["ici_bytes"] * f
            st["ici_bytes"] *= 1.0 - f
    # Field scaling (ISSUE 20): the stage formulas above model 8-byte
    # Goldilocks lanes; the BabyBear backend moves the SAME element
    # counts at 4 bytes each, so every traffic term is exactly eb/8 of
    # the Goldilocks sheet. Flops are left alone — the mod-p multiply
    # width is a per-kernel concern the kernel sheet already prices.
    try:
        from ..field.spec import is_babybear

        if is_babybear():
            scale = BB_ELEM_BYTES / 8.0
            for st in stages.values():
                for key in ("hbm_bytes", "ici_bytes", "dcn_bytes"):
                    if key in st:
                        st[key] *= scale
    except Exception:  # noqa: BLE001 — cost model must never fail a prove
        pass
    return stages


def _total_alpha_terms(sb) -> int:
    """total_alpha_terms exactly as enumerate_kernels derives it — via
    the gate set is unavailable here, so approximate from the bucket's
    chunk/lookup geometry plus a per-copy-column gate-term floor."""
    return (
        2 * sb.num_copy_cols + 1 + sb.num_chunks
        + ((sb.lookup_subargs + 1) if sb.lookups else 0)
    )


# ---------------------------------------------------------------------------
# The `cost` record: model x walls x gauges -> roofline
# ---------------------------------------------------------------------------

COST_SCHEMA = 1


def _stage_walls(span_tree: list) -> dict:
    """{stage_name: wall_s} from the prove root's direct children —
    the SAME extraction the trend series uses (report.stage_walls),
    filtered to the prover's stage names."""
    from .report import stage_walls

    return stage_walls(span_tree, names=STAGE_NAMES)


def roofline(entry: dict, wall_s: float, peaks: dict) -> dict:
    """Fold one {flops, hbm_bytes, ici_bytes} entry + its measured wall
    into achieved rates, regime and efficiency-vs-peak. Zero/invalid
    walls get NO achieved/efficiency fields (the validator rejects a
    record that claims efficiency over a zero denominator)."""
    def _sig(v):
        # 4 significant figures, never rounded to zero for positive v
        return float(f"{v:.4g}")

    out = dict(entry)
    # Gate on the ROUNDED wall: the record carries round(wall, 6), so a
    # sub-microsecond wall must not carry achieved fields the validator
    # would reject as efficiency-over-zero.
    wall_s = round(float(wall_s), 6) if wall_s is not None else None
    out["wall_s"] = wall_s
    flops = float(entry.get("flops", 0.0))
    hbm = float(entry.get("hbm_bytes", 0.0))
    intensity = flops / hbm if hbm > 0 else None
    if intensity is not None:
        out["intensity_flop_per_byte"] = _sig(intensity)
    pf = float(peaks.get("peak_gflops") or 0.0)
    pb = float(peaks.get("peak_hbm_gbps") or 0.0)
    balance = (pf / pb) if pb > 0 else None
    if intensity is not None and balance is not None:
        out["regime"] = "compute" if intensity >= balance else "memory"
    if not (isinstance(wall_s, (int, float)) and wall_s > 0):
        return out
    ag = flops / wall_s / 1e9
    ab = hbm / wall_s / 1e9
    out["achieved_gflops"] = _sig(ag)
    out["achieved_gbps"] = _sig(ab)
    ici = float(entry.get("ici_bytes", 0.0))
    if ici > 0:
        out["achieved_ici_gbps"] = _sig(ici / wall_s / 1e9)
    dcn = float(entry.get("dcn_bytes", 0.0))
    if dcn > 0:
        out["achieved_dcn_gbps"] = _sig(dcn / wall_s / 1e9)
    eff = None
    if out.get("regime") == "compute" and pf > 0:
        eff = ag / pf
    elif out.get("regime") == "memory" and pb > 0:
        eff = ab / pb
    if eff is not None:
        out["efficiency"] = _sig(eff)
    return out


def build_cost_record(
    sb,
    config,
    span_tree: list,
    metrics: dict | None = None,
    ledger_costs: dict | None = None,
    sheet: dict | None = None,
    mesh_devices: int = 1,
    peaks: dict | None = None,
    dcn_fraction: float = 0.0,
) -> dict:
    """Assemble the report line's `cost` record (pure: everything it
    reads is already a dict/dataclass, so tests drive it with synthetic
    trees)."""
    peaks = peaks or device_peaks()
    walls = _stage_walls(span_tree)
    stages = stage_costs(
        sb, config, mesh_devices=mesh_devices, dcn_fraction=dcn_fraction
    )
    rec_stages = {}
    total = {"flops": 0.0, "hbm_bytes": 0.0, "ici_bytes": 0.0}
    total_wall = 0.0
    for name, entry in stages.items():
        wall = walls.get(name)
        rec_stages[name] = roofline(
            {k: round(v, 1) for k, v in entry.items()}, wall, peaks
        )
        if isinstance(wall, (int, float)):
            total_wall += wall
        _acc(total, entry)
    try:
        from ..field.spec import active_field

        field_name = active_field()
    except Exception:
        field_name = "goldilocks"
    record: dict = {
        "schema": COST_SCHEMA,
        "field": field_name,
        "device": peaks,
        "stages": rec_stages,
        "total": roofline(
            {k: round(v, 1) for k, v in total.items()},
            total_wall if total_wall > 0 else None, peaks,
        ),
    }
    gauges = (metrics or {}).get("gauges") or {}
    counters = (metrics or {}).get("counters") or {}
    measured_ici = float(
        gauges.get("ici.all_to_all_bytes", 0.0) or 0.0
    ) + float(gauges.get("ici.all_gather_bytes", 0.0) or 0.0)
    if measured_ici > 0:
        record["total"]["ici_bytes_measured"] = round(measured_ici, 1)
    measured_dcn = sum(
        float(gauges.get(g, 0.0) or 0.0)
        for g in (
            "dcn.all_to_all_bytes",
            "dcn.all_gather_bytes",
            "dcn.host_gather_bytes",
        )
    )
    if measured_dcn > 0:
        record["total"]["dcn_bytes_measured"] = round(measured_dcn, 1)
    h2d = counters.get("transfer.h2d_bytes")
    d2h = counters.get("transfer.d2h_bytes")
    if isinstance(h2d, (int, float)) or isinstance(d2h, (int, float)):
        record["total"]["transfer_bytes_measured"] = round(
            float(h2d or 0) + float(d2h or 0), 1
        )
    if sheet:
        record["kernels"] = sorted(sheet)
    if ledger_costs:
        # the evidence claim: kernels whose XLA actuals this record is
        # built on — the report validator rejects names the compile
        # ledger never recorded
        record["attributed_kernels"] = sorted(
            name for name in ledger_costs if name in (sheet or {})
        )
        record["model_check"] = model_check(
            sheet or {}, ledger_costs
        )
    return record


def model_check(sheet: dict, ledger_costs: dict) -> dict:
    """Aggregate analytic-vs-XLA agreement over the kernels present in
    BOTH the analytic sheet and the ledger's captured actuals. Ratios
    are analytic/actual; the documented tolerance band is pinned by
    tests/test_costmodel.py and BASELINE.md."""
    a_flops = x_flops = a_bytes = x_bytes = 0.0
    covered = 0
    fams: dict = {}
    for name, actual in ledger_costs.items():
        ent = sheet.get(name)
        if not ent or not isinstance(actual, dict):
            continue
        xf = actual.get("flops")
        xb = actual.get("bytes_accessed")
        if not isinstance(xf, (int, float)) or not isinstance(
            xb, (int, float)
        ):
            continue
        covered += 1
        a_flops += float(ent.get("flops", 0.0))
        x_flops += float(xf)
        a_bytes += float(ent.get("hbm_bytes", 0.0))
        x_bytes += float(xb)
        slot = fams.setdefault(
            ent.get("family", "fallback"),
            {"kernels": 0, "af": 0.0, "xf": 0.0, "ab": 0.0, "xb": 0.0},
        )
        slot["kernels"] += 1
        slot["af"] += float(ent.get("flops", 0.0))
        slot["xf"] += float(xf)
        slot["ab"] += float(ent.get("hbm_bytes", 0.0))
        slot["xb"] += float(xb)
    out = {
        "covered_kernels": covered,
        "ledger_kernels": len(ledger_costs),
        "analytic_flops": round(a_flops, 1),
        "xla_flops": round(x_flops, 1),
        "analytic_hbm_bytes": round(a_bytes, 1),
        "xla_bytes_accessed": round(x_bytes, 1),
    }
    if x_flops > 0 and a_flops > 0:
        out["flops_ratio"] = round(a_flops / x_flops, 4)
    if x_bytes > 0 and a_bytes > 0:
        out["bytes_ratio"] = round(a_bytes / x_bytes, 4)
    out["families"] = {
        fam: {
            "kernels": s["kernels"],
            "flops_ratio": (
                round(s["af"] / s["xf"], 4) if s["xf"] > 0 else None
            ),
            "bytes_ratio": (
                round(s["ab"] / s["xb"], 4) if s["xb"] > 0 else None
            ),
        }
        for fam, s in sorted(fams.items())
    }
    return out


# ---------------------------------------------------------------------------
# The prover seam + process-level last-record snapshot (/metrics, bench)
# ---------------------------------------------------------------------------

_LAST_LOCK = threading.Lock()
_LAST_RECORD: dict | None = None


def _cached_sheet(assembly, config, mesh_shape=None, specs=None) -> dict:
    """The per-kernel analytic sheet of the DISPATCHED variant, cached
    ON THE ASSEMBLY per (bucket, variant) — same idiom as
    shape_key.shape_bucket; the enumeration walks the selector tree and
    must not re-run per prove. `specs` lets a caller that already
    enumerated (precompile's sweep) skip the second derivation.
    (Derived data, not collector state: two computations of the same
    key are identical, so there is nothing to bleed across packed
    requests.)"""
    from ..prover.aot import variant_fingerprint
    from ..prover.shape_key import bucket_key

    key = (
        bucket_key(assembly, config),
        json.dumps(variant_fingerprint(mesh_shape), sort_keys=True),
    )
    cache = getattr(assembly, "_cost_sheet_cache", None)
    if cache is None:
        cache = {}
        try:
            assembly._cost_sheet_cache = cache
        except Exception:
            cache = None
    if cache is not None and key in cache:
        return cache[key]
    if specs is None:
        from ..prover.precompile import enumerate_kernels

        specs = enumerate_kernels(assembly, config, mesh_shape=mesh_shape)
    D = _mesh_devices(mesh_shape)
    sheet = cost_sheet(specs, mesh_devices=D)
    if cache is not None:
        cache[key] = sheet
    return sheet


def prime_sheet(assembly, config, specs, mesh_shape=None) -> None:
    """Pre-populate the assembly's sheet cache from an ALREADY
    enumerated spec list — precompile calls this after its sweep so the
    first recorded prove never re-walks the enumeration inside its
    `prove` span. Fails soft like the rest of the plane."""
    try:
        if cost_enabled():
            _cached_sheet(assembly, config, mesh_shape=mesh_shape,
                          specs=specs)
    except Exception:
        pass


def _mesh_devices(mesh_shape) -> int:
    if mesh_shape is None:
        return 1
    if isinstance(mesh_shape, (tuple, list)):
        d = 1
        for x in mesh_shape:
            d *= int(x)
        return d
    try:
        d = 1
        for x in dict(mesh_shape.shape).values():
            d *= int(x)
        return d
    except Exception:
        return 1


# the registry families build_cost_record reports as MEASURED traffic;
# cumulative on a long-lived registry (bench multi-rep runs), so the
# prover snapshots them at prove start and the record carries the delta
_MEASURED_GAUGES = (
    "ici.all_to_all_bytes", "ici.all_gather_bytes",
    "dcn.all_to_all_bytes", "dcn.all_gather_bytes",
    "dcn.host_gather_bytes",
)
_MEASURED_COUNTERS = ("transfer.h2d_bytes", "transfer.d2h_bytes")


def measured_baseline() -> dict:
    """Prove-start snapshot of the measured-traffic families on the
    active registry. `attach_cost_record` subtracts it so a process
    that proves N times on one registry stamps per-PROVE ici/transfer
    bytes, not the running total. Fails soft ({} = no subtraction)."""
    try:
        from . import metrics as _metrics

        reg = _metrics.current_registry()
        if reg is None:
            return {}
        snap = reg.to_dict()
        g = snap.get("gauges") or {}
        c = snap.get("counters") or {}
        return {
            "gauges": {
                k: float(g.get(k) or 0.0) for k in _MEASURED_GAUGES
            },
            "counters": {
                k: float(c.get(k) or 0.0) for k in _MEASURED_COUNTERS
            },
        }
    except Exception:  # noqa: BLE001 — a snapshot bug must never
        return {}      # fail a prove


def _subtract_baseline(snap: dict, baseline: dict) -> dict:
    """Copy `snap` with the baseline's measured families subtracted
    (clamped at 0 — a registry swapped mid-prove starts fresh)."""
    out = dict(snap)
    for fam in ("gauges", "counters"):
        base = baseline.get(fam) or {}
        if not base:
            continue
        cur = dict(snap.get(fam) or {})
        for k, v in base.items():
            if k in cur and isinstance(cur[k], (int, float)):
                cur[k] = max(0.0, float(cur[k]) - v)
        out[fam] = cur
    return out


def attach_cost_record(
    assembly, config, mesh=None, baseline=None
) -> dict | None:
    """prover seam: at the end of a successful prove, join the analytic
    model with this prove's span walls / gauges / ledger actuals, stamp
    the `cost` record on the active FlightRecorder (rides the report
    line) and export `cost.*` gauges on the active metrics registry
    (rides /metrics). Fails soft — a cost-model bug must never fail a
    prove."""
    try:
        if not cost_enabled():
            return None
        from . import metrics as _metrics
        from . import report as _report
        from . import spans as _spans
        from .profiling import current_compile_ledger

        rec = _report.current_flight_recorder()
        if rec is None:
            # bench without BOOJUM_TPU_REPORT installs a bare
            # SpanRecorder: still compute the record (it lands on the
            # bench JSON line via last_cost_record), just with no
            # report line to stamp
            spans_rec = _spans.current_recorder()
            if spans_rec is None:
                return None
        else:
            spans_rec = rec.spans
        from ..prover.shape_key import shape_bucket

        sb = shape_bucket(assembly, config)
        mesh_shape = None
        dcn_frac = 0.0
        if mesh is not None:
            from ..prover.aot import _mesh_shape_list, _would_shard_map

            if _would_shard_map(mesh):
                mesh_shape = _mesh_shape_list(mesh)
                try:
                    from ..parallel.multihost import dcn_fraction

                    dcn_frac = dcn_fraction(mesh)
                except Exception:
                    dcn_frac = 0.0
        sheet = _cached_sheet(assembly, config, mesh_shape=mesh_shape)
        ledger = current_compile_ledger()
        ledger_costs = (
            ledger.kernel_costs(shape_key=sb.key)
            if ledger is not None else {}
        )
        reg = _metrics.current_registry()
        metrics_snap = reg.to_dict() if reg is not None else {}
        if baseline:
            metrics_snap = _subtract_baseline(metrics_snap, baseline)
        record = build_cost_record(
            sb, config,
            spans_rec.tree(),
            metrics_snap,
            ledger_costs=ledger_costs,
            sheet=sheet,
            mesh_devices=_mesh_devices(mesh_shape),
            dcn_fraction=dcn_frac,
        )
        if rec is not None:
            rec.cost = record
        for name, st in record["stages"].items():
            for key in ("achieved_gflops", "achieved_gbps", "efficiency"):
                v = st.get(key)
                if isinstance(v, (int, float)):
                    _metrics.gauge_set_cost(f"{name}.{key}", v)
        tot = record.get("total") or {}
        for key in ("achieved_gflops", "achieved_gbps", "efficiency"):
            v = tot.get(key)
            if isinstance(v, (int, float)):
                _metrics.gauge_set_cost(f"total.{key}", v)
        with _LAST_LOCK:
            global _LAST_RECORD
            _LAST_RECORD = record
        return record
    except Exception as e:  # noqa: BLE001
        try:
            from .profiling import log as _plog

            _plog(f"cost model: attach failed ({e!r}) — line gets no "
                  f"cost record")
        except Exception:
            pass
        return None


def last_cost_record() -> dict | None:
    """The most recent attached cost record (process-wide) — bench.py
    stamps it on its JSON line; the telemetry provider flattens it."""
    with _LAST_LOCK:
        return _LAST_RECORD


def telemetry_provider() -> dict:
    """Sampler provider: flat {stage.metric: value} gauges of the last
    attached cost record (rides /metrics as
    boojum_tpu_telemetry_cost_* and the report `telemetry` record)."""
    rec = last_cost_record()
    if not rec:
        return {}
    out: dict = {}
    for name, st in (rec.get("stages") or {}).items():
        for key in ("achieved_gflops", "achieved_gbps", "efficiency"):
            v = st.get(key)
            if isinstance(v, (int, float)):
                out[f"{name}.{key}"] = v
    return out
