"""Radix-2 NTT / coset LDE over Goldilocks, batched across trace columns.

TPU-native counterpart of the reference FFT layer
(`/root/reference/src/fft/mod.rs:398` fft_natural_to_bitreversed, `:464`
ifft_natural_to_natural, `:308` distribute_powers) and the LDE transform family
(`src/cs/implementations/utils.rs:270`). Instead of 16-lane SIMD butterflies,
every stage is one whole-array reshape+butterfly expressed in jnp; XLA fuses
the modular-arithmetic ops and tiles them on the VPU. Columns batch along
leading axes, so one call transforms the entire witness at once.

Domain conventions (chosen so FRI pairing and Merkle layout are contiguous):
- forward: natural input -> bit-reversed output (Gentleman-Sande / DIF)
- inverse: bit-reversed input -> natural output (Cooley-Tukey / DIT)
- LDE storage: shape (..., lde_factor, n); coset axis is indexed by the
  BIT-REVERSED coset index, each coset internally bit-reversed. Flattening the
  last two axes yields the full 2^(a+b) domain {g·w_N^i} in bit-reversed order
  of i (since brev_N(k·lde + j) = brev(j)·n + brev(k)): FRI fold pairs
  (x, -x) are then adjacent.
"""

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..field import gl
from ..field import extension as ext
from ..field import goldilocks as gf


def bitreverse_indices(log_n: int) -> np.ndarray:
    """Permutation perm[i] = bitreverse(i, log_n) as int32 numpy array."""
    n = 1 << log_n
    idx = np.arange(n, dtype=np.uint32)
    rev = np.zeros_like(idx)
    for b in range(log_n):
        rev |= ((idx >> b) & 1) << (log_n - 1 - b)
    return rev.astype(np.int32)


def powers_device(base: int, count: int) -> jax.Array:
    """[1, b, b^2, ..., b^(count-1)] as a host-built table.

    Host numpy + one upload (or a graph constant when called inside a
    trace): the previous log-doubling DEVICE loop dispatched ~2*log2(count)
    eager executables with shape-unique cache keys — through the tunneled
    compile service that was ~1s of compile round-trip EACH, every fresh
    process, for every twiddle/power table."""
    assert count & (count - 1) == 0, "count must be a power of two"
    # ensure_compile_time_eval: first touch may happen inside a jit trace,
    # where a bare jnp.asarray would yield a (leakable) constant tracer
    with jax.ensure_compile_time_eval():
        return jnp.asarray(_powers_np(base % gl.P, count))


@lru_cache(maxsize=64)
def _powers_np(base: int, count: int) -> np.ndarray:
    return gl.powers_np(base, count)


class NTTContext:
    """Cached twiddle tables for size-2^log_n transforms."""

    def __init__(self, log_n: int):
        assert 0 < log_n <= gl.TWO_ADICITY
        self.log_n = log_n
        self.n = 1 << log_n
        self.omega = gl.omega(log_n)
        self.omega_inv = gl.inv(self.omega)
        half = max(self.n // 2, 1)
        # contexts are cached across jit traces (lru_cache below): build the
        # tables under ensure_compile_time_eval even if first touched inside
        # a trace, or the cached arrays would be leaked tracers
        with jax.ensure_compile_time_eval():
            self.n_inv = jnp.uint64(gl.inv(self.n))
            self.tw = powers_device(self.omega, half) if self.n > 1 else None
            self.itw = (
                powers_device(self.omega_inv, half) if self.n > 1 else None
            )
            self.brev = jnp.asarray(bitreverse_indices(log_n))


@lru_cache(maxsize=None)
def get_ntt_context(log_n: int) -> NTTContext:
    return NTTContext(log_n)


def _mxu_ntt_ready(n: int, ctx) -> bool:
    """True when the MXU matmul-NTT kernel should take this transform.

    Default-ON on TPU (the kernel moves the multiply work onto the systolic
    array and beats the staged-XLA emulated-u64 path; parity is exact);
    BOOJUM_TPU_MXU_NTT=0 opts out."""
    from ..utils.pallas_util import pallas_enabled
    from ..utils.transfer import env_flag

    if not env_flag("BOOJUM_TPU_MXU_NTT", True):
        return False
    if not pallas_enabled():
        return False
    from . import mxu_ntt

    if not mxu_ntt.size_fits(n):
        return False
    # custom contexts (non-standard roots) keep the generic path
    return ctx is None or ctx is get_ntt_context(n.bit_length() - 1)


def fft_natural_to_bitreversed(
    a: jax.Array, ctx: NTTContext | None = None
) -> jax.Array:
    """DIF NTT along the last axis; output in bit-reversed order.

    Dispatches to the MXU matmul kernel on TPU (bit-identical results);
    the staged-XLA form below is the generic path."""
    if _mxu_ntt_ready(a.shape[-1], ctx):
        from . import mxu_ntt

        return mxu_ntt.fft_natural_to_bitreversed(a)
    return fft_natural_to_bitreversed_xla(a, ctx)


def ifft_bitreversed_to_natural(
    a: jax.Array, ctx: NTTContext | None = None
) -> jax.Array:
    """DIT inverse NTT (incl. 1/n) along the last axis; see the XLA form."""
    if _mxu_ntt_ready(a.shape[-1], ctx):
        from . import mxu_ntt

        return mxu_ntt.ifft_bitreversed_to_natural(a)
    return ifft_bitreversed_to_natural_xla(a, ctx)


def dif_stages(a: jax.Array, ctx: NTTContext, start: int, end: int) -> jax.Array:
    """Radix-2 DIF butterfly stages [start, end) of a size-ctx.n transform.

    Stage s combines elements ctx.n >> (s+1) apart; running stages [0, k)
    leaves 2^k independent plain sub-transforms of size n/2^k — which is
    what lets the hybrid MXU path (mxu_ntt.py) hand contiguous blocks to
    the matmul kernel bit-exactly."""
    n = ctx.n
    lead = a.shape[:-1]
    for s in range(start, end):
        block = n >> s
        half = block >> 1
        tw = ctx.tw[:: n // block][:half] if half > 1 else ctx.tw[:1]
        x = a.reshape(lead + (n // block, 2, half))
        u = x[..., 0, :]
        v = x[..., 1, :]
        top = gf.add(u, v)
        bot = gf.mul(gf.sub(u, v), tw)
        a = jnp.stack([top, bot], axis=-2).reshape(lead + (n,))
    return a


def dit_stages(a: jax.Array, ctx: NTTContext, start: int, end: int) -> jax.Array:
    """Radix-2 DIT butterfly stages [start, end) (no 1/n scaling)."""
    n = ctx.n
    lead = a.shape[:-1]
    for s in range(start, end):
        block = 2 << s
        half = block >> 1
        tw = ctx.itw[:: n // block][:half] if half > 1 else ctx.itw[:1]
        x = a.reshape(lead + (n // block, 2, half))
        u = x[..., 0, :]
        wv = gf.mul(x[..., 1, :], tw)
        top = gf.add(u, wv)
        bot = gf.sub(u, wv)
        a = jnp.stack([top, bot], axis=-2).reshape(lead + (n,))
    return a


@partial(jax.jit, static_argnums=(1,))
def fft_natural_to_bitreversed_xla(a: jax.Array, ctx: NTTContext | None = None) -> jax.Array:
    """DIF NTT along the last axis; output in bit-reversed order."""
    n = a.shape[-1]
    log_n = n.bit_length() - 1
    assert 1 << log_n == n
    if ctx is None:
        ctx = get_ntt_context(log_n)
    return dif_stages(a, ctx, 0, log_n)


@partial(jax.jit, static_argnums=(1,))
def ifft_bitreversed_to_natural_xla(a: jax.Array, ctx: NTTContext | None = None) -> jax.Array:
    """DIT inverse NTT along the last axis; input bit-reversed, output natural.

    Includes the 1/n scaling.
    """
    n = a.shape[-1]
    log_n = n.bit_length() - 1
    assert 1 << log_n == n
    if ctx is None:
        ctx = get_ntt_context(log_n)
    return gf.mul(dit_stages(a, ctx, 0, log_n), ctx.n_inv)


def ifft_natural_to_natural(a: jax.Array, ctx: NTTContext | None = None) -> jax.Array:
    """Interpolate monomial coefficients from values over H in natural order."""
    n = a.shape[-1]
    log_n = n.bit_length() - 1
    if ctx is None:
        ctx = get_ntt_context(log_n)
    return ifft_bitreversed_to_natural(a[..., ctx.brev], ctx)


@partial(jax.jit, static_argnums=(1,))
def distribute_powers(a: jax.Array, base: int) -> jax.Array:
    """a[..., i] *= base^i (the coset shift before a forward transform)."""
    n = a.shape[-1]
    return gf.mul(a, powers_device(base, n))


@partial(jax.jit, static_argnums=(1, 2))
def _lde_from_monomial_jit(
    coeffs: jax.Array,
    lde_factor: int,
    coset: int = gl.MULTIPLICATIVE_GENERATOR,
) -> jax.Array:
    """Low-degree-extend monomial coeffs (..., n) -> (..., lde_factor, n).

    Coset axis is indexed by bit-reversed coset index; each coset is the
    bit-reversed evaluations over {coset·w_N^j·<w_n>}. Flattening the last two
    axes gives the full LDE domain in bit-reversed enumeration.
    """
    n = coeffs.shape[-1]
    log_n = n.bit_length() - 1
    log_lde = lde_factor.bit_length() - 1
    assert 1 << log_lde == lde_factor
    ctx = get_ntt_context(log_n)
    scale = _lde_scale_cached(log_n, lde_factor, int(coset) % gl.P)
    scaled = gf.mul(coeffs[..., None, :], scale)  # (..., lde, n)
    return fft_natural_to_bitreversed(scaled, ctx)


def lde_scale_rows(
    log_n: int, lde_factor: int, coset: int = gl.MULTIPLICATIVE_GENERATOR
) -> jax.Array:
    """Public accessor for the cached (lde, n) coset-scale matrix (rows in
    bit-reversed coset order) — row c scales monomials onto LDE coset c."""
    return _lde_scale_cached(log_n, lde_factor, int(coset) % gl.P)


def warm_domain_caches(log_n: int, lde_factor: int) -> None:
    """Populate the challenge-independent transform caches for one
    (trace, rate) geometry: the size-n and full-domain twiddle contexts
    plus the coset-scale matrix. The overlapped prover calls this at
    round 0 (prover._prefetch_challenge_independent) so rounds 1-5 never
    pay a table build at a transcript barrier; safe to call any time —
    everything here is lru-cached and enqueue-only."""
    get_ntt_context(log_n)
    log_lde = lde_factor.bit_length() - 1
    if log_lde:
        get_ntt_context(log_n + log_lde)
    lde_scale_rows(log_n, lde_factor)


@lru_cache(maxsize=None)
def _lde_scale_cached(log_n: int, lde_factor: int, coset: int) -> jax.Array:
    """(lde, n) scale matrix shift_j^i (rows in bit-reversed coset order)."""
    n = 1 << log_n
    log_lde = lde_factor.bit_length() - 1
    w_full = gl.omega(log_n + log_lde)
    brev_lde = bitreverse_indices(log_lde)
    shifts = [
        gl.mul(coset % gl.P, gl.pow_(w_full, int(j))) for j in brev_lde
    ]
    with jax.ensure_compile_time_eval():
        return jnp.asarray(np.stack([_powers_np(s, n) for s in shifts]))


def lde_from_monomial(
    coeffs: jax.Array,
    lde_factor: int,
    coset: int = gl.MULTIPLICATIVE_GENERATOR,
) -> jax.Array:
    """Low-degree-extend monomial coeffs (..., n) -> (..., lde_factor, n).

    Coset axis is indexed by bit-reversed coset index; each coset is the
    bit-reversed evaluations over {coset*w_N*<w_n>}. Flattening the last two
    axes gives the full LDE domain in bit-reversed enumeration. Large column
    batches are processed in chunks to bound the transform's transient
    memory (see monomial_from_values). On TPU the coset-scale multiply and
    all butterfly stages run as ONE fused Pallas kernel per column/coset.
    """
    n = coeffs.shape[-1]
    if _mxu_ntt_ready(n, None):
        from . import mxu_ntt

        log_n = n.bit_length() - 1
        scale = _lde_scale_cached(log_n, lde_factor, int(coset) % gl.P)
        if coeffs.ndim < 2:
            return mxu_ntt.lde_from_monomial(coeffs, scale)
        B = coeffs.shape[0]
        per = _col_chunks(B, coeffs.size // B * 8 * lde_factor)
        if per is None:
            return mxu_ntt.lde_from_monomial(coeffs, scale)
        return _assemble_chunks(
            coeffs.shape[:-1] + (lde_factor, n),
            lambda i: mxu_ntt.lde_from_monomial(coeffs[i : i + per], scale),
            range(0, B, per),
        )
    if coeffs.ndim < 2:
        return _lde_from_monomial_jit(coeffs, lde_factor, coset)
    B = coeffs.shape[0]
    per = _col_chunks(B, coeffs.size // B * 8 * lde_factor)
    if per is None:
        return _lde_from_monomial_jit(coeffs, lde_factor, coset)
    return _assemble_chunks(
        coeffs.shape[:-1] + (lde_factor, n),
        lambda i: _lde_from_monomial_jit(coeffs[i : i + per], lde_factor, coset),
        range(0, B, per),
    )


@jax.jit
def _monomial_from_values_jit(values: jax.Array) -> jax.Array:
    return ifft_natural_to_natural(values)


# The unrolled radix-2 stages keep O(log n) live stage buffers; chunk big
# column batches so the transient peak stays bounded (the 2^20-row traces
# OOM'd 16 GB HBM inside one monolithic (B, L, n) transform otherwise).
_NTT_CHUNK_BUDGET = 128 << 20  # bytes of INPUT columns per chunk


def _col_chunks(total_cols: int, bytes_per_col: int):
    per = max(1, _NTT_CHUNK_BUDGET // max(bytes_per_col, 1))
    if per >= total_cols:
        return None
    return per


def _assemble_chunks(shape, produce, starts):
    """Write per-chunk results into a donated output buffer in place (a
    concatenate would transiently double the multi-GB footprint)."""
    out = jnp.zeros(shape, jnp.uint64)
    for i in starts:
        out = _write_block(out, produce(i), i)
    return out


@partial(jax.jit, donate_argnums=(0,), static_argnums=(2,))
def _write_block(buf, chunk, i: int):
    return jax.lax.dynamic_update_slice_in_dim(buf, chunk, i, axis=0)


def chunk_shapes(total_cols: int, bytes_per_col: int) -> list[int]:
    """Distinct column-chunk heights the chunked transform wrappers below
    actually dispatch for a (total_cols, …) batch — the shape key set a
    precompiler must cover (prover/precompile.py)."""
    per = _col_chunks(total_cols, bytes_per_col)
    if per is None:
        return [total_cols]
    return sorted({min(per, total_cols - i) for i in range(0, total_cols, per)})


def ntt_kernel_specs(B: int, log_n: int, lde_factor: int | None = None,
                     coset: int = gl.MULTIPLICATIVE_GENERATOR,
                     mono: bool = True) -> list:
    """(name, jitted_fn, args) triples for the exact top-level executables
    `monomial_from_values` (when `mono`) and `lde_from_monomial` (when
    `lde_factor` is given) dispatch for a (B, 2^log_n) column stack —
    mirroring the MXU-vs-XLA routing, the hybrid-size split and the
    column chunking, so `fn.lower(*args).compile()` populates the very
    cache keys the prover later hits. Args are ShapeDtypeStructs (plus
    static scalars); nothing here allocates device memory."""
    n = 1 << log_n

    def sds(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.uint64)

    specs = []
    if mono:
        specs += [
            (f"imono_b{b}_n{n}", _monomial_from_values_jit, (sds(b, n),))
            for b in chunk_shapes(B, n * 8)
        ]
    if lde_factor is None:
        return specs
    L = int(lde_factor)
    mxu = _mxu_ntt_ready(n, None)
    for b in chunk_shapes(B, n * 8 * L):
        if not mxu:
            specs.append((
                f"lde_b{b}_n{n}_L{L}",
                _lde_from_monomial_jit,
                (sds(b, n), L, int(coset) % gl.P),
            ))
            continue
        from . import mxu_ntt
        from ..field import limbs

        if log_n > mxu_ntt.MAX_LOG_N:
            # hybrid sizes: eager coset scale + one _fft_hybrid dispatch
            specs.append((
                f"lde_hybrid_b{b}_n{n}_L{L}",
                mxu_ntt._fft_hybrid,
                (sds(b, L, n), log_n, False),
            ))
            continue
        ctx = mxu_ntt.get_mxu_ctx(log_n)
        planes = jax.eval_shape(
            lambda a: limbs.split(a.reshape(-1, ctx.R, ctx.C)), sds(b, n)
        )
        s_planes = jax.eval_shape(
            lambda s: limbs.split(s.reshape(L, ctx.R, ctx.C)), sds(L, n)
        )
        specs.append((
            f"lde_mxu_b{b}_n{n}_L{L}",
            mxu_ntt._lde_planes,
            (planes, s_planes, log_n, False),
        ))
    return specs


def monomial_from_values(values: jax.Array) -> jax.Array:
    """Values over H (natural order) -> monomial coefficients (column
    batches chunked to bound transient memory)."""
    if values.ndim < 2:
        return _monomial_from_values_jit(values)
    B = values.shape[0]
    per = _col_chunks(B, values.size // B * 8)
    if per is None:
        return _monomial_from_values_jit(values)
    return _assemble_chunks(
        values.shape,
        lambda i: _monomial_from_values_jit(values[i : i + per]),
        range(0, B, per),
    )


@jax.jit
def _eval_with_pows(coeffs: jax.Array, p0: jax.Array, p1: jax.Array):
    c0 = gf.mul(coeffs, p0)
    c1 = gf.mul(coeffs, p1)
    # sum over last axis, mod p: reduce via pairwise modular adds
    return (_modsum(c0), _modsum(c1))


def eval_monomial_at_ext_point(coeffs: jax.Array, z, z_pows=None):
    """Evaluate base-field monomial polys (..., n) at an extension point z.

    z is a host scalar (c0, c1); returns ext pair of shape (...,). Uses a
    power table + reduction instead of a sequential Horner chain (the
    device-friendly analogue of the reference's barycentric evaluation,
    `/root/reference/src/cs/implementations/utils.rs:1025`). The reduction
    core is jitted; the z-dependent power table stays an array argument so
    new challenges never retrace.
    """
    n = coeffs.shape[-1]
    if z_pows is None:
        z_pows = ext_powers_device(z, n)
    return _eval_with_pows(coeffs, z_pows[0], z_pows[1])


@partial(jax.jit, static_argnums=(1,))
def _ext_powers_jit(z01, count: int):
    """Log-doubling power table built in ONE compiled graph (the eager
    version dispatched log2(count) growing-array ops per call — behind a
    network-tunneled device those round-trips dominated)."""
    p0 = jnp.ones((1,), jnp.uint64)
    p1 = jnp.zeros((1,), jnp.uint64)
    step = (z01[0], z01[1])  # z^cur, maintained by squaring
    cur = 1
    while cur < count:
        n0, n1 = ext.mul((p0, p1), step)
        p0 = jnp.concatenate([p0, n0])
        p1 = jnp.concatenate([p1, n1])
        step = ext.mul(step, step)
        cur *= 2
    return (p0, p1)


def ext_powers_device(z, count: int):
    """Powers [1, z, ..., z^(count-1)] of an ext scalar, as pair of arrays."""
    assert count & (count - 1) == 0
    z01 = jnp.asarray(np.array([int(z[0]), int(z[1])], dtype=np.uint64))
    return _ext_powers_jit(z01, count)


def _modsum(a: jax.Array) -> jax.Array:
    """Modular sum along the last axis via log-depth pairwise folding."""
    n = a.shape[-1]
    while n > 1:
        if n % 2 == 1:
            a = jnp.concatenate(
                [a, jnp.zeros(a.shape[:-1] + (1,), a.dtype)], axis=-1
            )
            n += 1
        a = gf.add(a[..., : n // 2], a[..., n // 2 :])
        n //= 2
    return a[..., 0]
