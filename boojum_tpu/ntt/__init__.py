from .ntt import (
    NTTContext,
    get_ntt_context,
    bitreverse_indices,
    fft_natural_to_bitreversed,
    ifft_bitreversed_to_natural,
    ifft_natural_to_natural,
    powers_device,
    ext_powers_device,
    distribute_powers,
    lde_from_monomial,
    monomial_from_values,
    eval_monomial_at_ext_point,
)
