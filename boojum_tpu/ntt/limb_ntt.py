"""Limb-plane NTT / coset LDE: the resident-mode transform layer (ISSUE 10).

`ntt.py` computes in XLA-emulated uint64 and `mxu_ntt.py` converts u64->limb
planes at every public entry — which is exactly the boundary tax the
limb-resident prove deletes. This module is the transform layer whose
CANONICAL representation is a `(lo, hi)` uint32 plane pair shaped like the
u64 array it replaces:

- twiddle/scale tables are built on HOST (numpy `_powers_np` + `split_np`),
  so no device-side u64<->limb conversion exists anywhere in the layer;
- the staged radix-2 butterflies are `field/limbs.py` ops (exact mod p,
  canonical in/out), so every value is bit-identical to the u64 path;
- where the MXU matmul kernel is native (TPU, 2^14..2^22), the plane entries
  feed `mxu_ntt._fft_planes/_ifft_planes/_lde_planes` DIRECTLY — the
  split/join wrappers of `mxu_ntt`'s u64 entries never run.

Layout convention: same shapes as the u64 arrays, as a pair of uint32
arrays. Big column batches chunk exactly like `ntt.monomial_from_values` /
`lde_from_monomial` (shared `_col_chunks`), writing into two donated u32
buffers.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..field import gl
from ..field import limbs

from .ntt import (
    _col_chunks,
    _mxu_ntt_ready,
    _powers_np,
    bitreverse_indices,
)


@lru_cache(maxsize=None)
class PlaneNTTContext:
    """Host-built twiddle planes for size-2^log_n transforms."""

    def __init__(self, log_n: int):
        self.log_n = log_n
        self.n = 1 << log_n
        self.omega = gl.omega(log_n)
        half = max(self.n // 2, 1)
        with jax.ensure_compile_time_eval():
            tw_lo, tw_hi = limbs.split_np(_powers_np(self.omega, half))
            itw_lo, itw_hi = limbs.split_np(
                _powers_np(gl.inv(self.omega), half)
            )
            self.tw = (jnp.asarray(tw_lo), jnp.asarray(tw_hi))
            self.itw = (jnp.asarray(itw_lo), jnp.asarray(itw_hi))
            self.brev = jnp.asarray(bitreverse_indices(log_n))
        self.n_inv = limbs.const_pair(gl.inv(self.n))


def _tw_slice(tw, n, block, half):
    if half > 1:
        return tw[0][:: n // block][:half], tw[1][:: n // block][:half]
    return tw[0][:1], tw[1][:1]


def dif_stages_p(p, ctx: PlaneNTTContext, start: int, end: int):
    """Radix-2 DIF stages [start, end) on planes (ntt.dif_stages twin)."""
    n = ctx.n
    lo, hi = p
    lead = lo.shape[:-1]
    for s in range(start, end):
        block = n >> s
        half = block >> 1
        tw = _tw_slice(ctx.tw, n, block, half)
        xl = lo.reshape(lead + (n // block, 2, half))
        xh = hi.reshape(lead + (n // block, 2, half))
        u = (xl[..., 0, :], xh[..., 0, :])
        v = (xl[..., 1, :], xh[..., 1, :])
        top = limbs.add(u, v)
        bot = limbs.mul(limbs.sub(u, v), tw)
        lo = jnp.stack([top[0], bot[0]], axis=-2).reshape(lead + (n,))
        hi = jnp.stack([top[1], bot[1]], axis=-2).reshape(lead + (n,))
    return lo, hi


def dit_stages_p(p, ctx: PlaneNTTContext, start: int, end: int):
    """Radix-2 DIT stages [start, end) on planes (no 1/n scaling)."""
    n = ctx.n
    lo, hi = p
    lead = lo.shape[:-1]
    for s in range(start, end):
        block = 2 << s
        half = block >> 1
        tw = _tw_slice(ctx.itw, n, block, half)
        xl = lo.reshape(lead + (n // block, 2, half))
        xh = hi.reshape(lead + (n // block, 2, half))
        u = (xl[..., 0, :], xh[..., 0, :])
        wv = limbs.mul((xl[..., 1, :], xh[..., 1, :]), tw)
        top = limbs.add(u, wv)
        bot = limbs.sub(u, wv)
        lo = jnp.stack([top[0], bot[0]], axis=-2).reshape(lead + (n,))
        hi = jnp.stack([top[1], bot[1]], axis=-2).reshape(lead + (n,))
    return lo, hi


# ---------------------------------------------------------------------------
# Staged-XLA plane transforms (jitted entries)
# ---------------------------------------------------------------------------


@jax.jit
def _fft_p_jit(p):
    n = p[0].shape[-1]
    log_n = n.bit_length() - 1
    ctx = PlaneNTTContext(log_n)
    return dif_stages_p(p, ctx, 0, log_n)


@jax.jit
def _ifft_p_jit(p):
    n = p[0].shape[-1]
    log_n = n.bit_length() - 1
    ctx = PlaneNTTContext(log_n)
    return limbs.mul_const(dit_stages_p(p, ctx, 0, log_n), ctx.n_inv)


@jax.jit
def _imono_p_jit(p):
    """Values over H (natural) -> monomials, on planes."""
    n = p[0].shape[-1]
    ctx = PlaneNTTContext(n.bit_length() - 1)
    p = (p[0][..., ctx.brev], p[1][..., ctx.brev])
    return limbs.mul_const(dit_stages_p(p, ctx, 0, ctx.log_n), ctx.n_inv)


@lru_cache(maxsize=None)
def _lde_scale_planes(log_n: int, lde_factor: int, coset: int):
    """Host-built (lde, n) coset-scale planes (ntt._lde_scale_cached twin)."""
    n = 1 << log_n
    log_lde = lde_factor.bit_length() - 1
    w_full = gl.omega(log_n + log_lde)
    brev_lde = bitreverse_indices(log_lde)
    shifts = [
        gl.mul(coset % gl.P, gl.pow_(w_full, int(j))) for j in brev_lde
    ]
    with jax.ensure_compile_time_eval():
        lo, hi = limbs.split_np(np.stack([_powers_np(s, n) for s in shifts]))
        return jnp.asarray(lo), jnp.asarray(hi)


@partial(jax.jit, static_argnums=(1, 2))
def _lde_p_jit(p, lde_factor: int, coset: int):
    n = p[0].shape[-1]
    log_n = n.bit_length() - 1
    scale = _lde_scale_planes(log_n, lde_factor, coset)
    scaled = limbs.mul((p[0][..., None, :], p[1][..., None, :]), scale)
    return _fft_body(scaled)


def _fft_body(p):
    n = p[0].shape[-1]
    log_n = n.bit_length() - 1
    return dif_stages_p(p, PlaneNTTContext(log_n), 0, log_n)


# ---------------------------------------------------------------------------
# MXU dispatch + hybrid sizes
# ---------------------------------------------------------------------------


def _mxu_fft_p(p, inverse: bool):
    from . import mxu_ntt

    n = p[0].shape[-1]
    log_n = n.bit_length() - 1
    if log_n > mxu_ntt.MAX_LOG_N:
        return _hybrid_p(p, log_n, inverse)
    ctx = mxu_ntt.get_mxu_ctx(log_n)
    lead = p[0].shape[:-1]
    flat = (p[0].reshape(-1, ctx.R, ctx.C), p[1].reshape(-1, ctx.R, ctx.C))
    fn = mxu_ntt._ifft_planes if inverse else mxu_ntt._fft_planes
    out = fn(flat, log_n, False)
    return out[0].reshape(lead + (n,)), out[1].reshape(lead + (n,))


@partial(jax.jit, static_argnums=(1, 2))
def _hybrid_p(p, log_n: int, inverse: bool):
    """2^17..2^22: plane XLA outer radix-2 stages + per-block MXU kernels
    (mxu_ntt._fft_hybrid/_ifft_hybrid twins)."""
    from . import mxu_ntt

    n = 1 << log_n
    outer = log_n - mxu_ntt.MAX_LOG_N
    ctx = PlaneNTTContext(log_n)
    lead = p[0].shape[:-1]
    if not inverse:
        p = dif_stages_p(p, ctx, 0, outer)
        blocks = (
            p[0].reshape(lead + (1 << outer, 1 << mxu_ntt.MAX_LOG_N)),
            p[1].reshape(lead + (1 << outer, 1 << mxu_ntt.MAX_LOG_N)),
        )
        out = _mxu_fft_p(blocks, False)
        return out[0].reshape(lead + (n,)), out[1].reshape(lead + (n,))
    blocks = (
        p[0].reshape(lead + (1 << outer, 1 << mxu_ntt.MAX_LOG_N)),
        p[1].reshape(lead + (1 << outer, 1 << mxu_ntt.MAX_LOG_N)),
    )
    out = _mxu_fft_p(blocks, True)
    out = (
        out[0].reshape(lead + (n,)),
        out[1].reshape(lead + (n,)),
    )
    out = dit_stages_p(out, ctx, mxu_ntt.MAX_LOG_N, log_n)
    return limbs.mul_const(out, limbs.const_pair(gl.inv(1 << outer)))


def fft_natural_to_bitreversed_p(p):
    """DIF NTT on planes along the last axis (bit-reversed output)."""
    if _mxu_ntt_ready(int(p[0].shape[-1]), None):
        return _mxu_fft_p(p, False)
    return _fft_p_jit(p)


def ifft_bitreversed_to_natural_p(p):
    """DIT inverse NTT on planes (incl. 1/n)."""
    if _mxu_ntt_ready(int(p[0].shape[-1]), None):
        return _mxu_fft_p(p, True)
    return _ifft_p_jit(p)


@partial(jax.jit, static_argnums=(1,))
def distribute_powers_p(p, base: int):
    """p[..., i] *= base^i on planes (host-built scale table)."""
    n = p[0].shape[-1]
    with jax.ensure_compile_time_eval():
        lo, hi = limbs.split_np(_powers_np(int(base) % gl.P, n))
        scale = (jnp.asarray(lo), jnp.asarray(hi))
    return limbs.mul(p, scale)


# ---------------------------------------------------------------------------
# Chunked public entries (monomial_from_values / lde_from_monomial twins)
# ---------------------------------------------------------------------------


@partial(jax.jit, donate_argnums=(0, 1), static_argnums=(4,))
def _write_block_p(buf_lo, buf_hi, chunk_lo, chunk_hi, i: int):
    return (
        jax.lax.dynamic_update_slice_in_dim(buf_lo, chunk_lo, i, axis=0),
        jax.lax.dynamic_update_slice_in_dim(buf_hi, chunk_hi, i, axis=0),
    )


def _assemble_chunks_p(shape, produce, starts):
    out_lo = jnp.zeros(shape, jnp.uint32)
    out_hi = jnp.zeros(shape, jnp.uint32)
    for i in starts:
        clo, chi = produce(i)
        out_lo, out_hi = _write_block_p(out_lo, out_hi, clo, chi, i)
    return out_lo, out_hi


def monomial_from_values_p(p):
    """Values over H -> monomial coefficients, on planes (chunked)."""
    lo, hi = p
    if lo.ndim < 2:
        return _imono_p_jit(p)
    B = lo.shape[0]
    per = _col_chunks(B, lo.size // B * 8)
    if per is None:
        return _imono_p_jit(p)
    return _assemble_chunks_p(
        lo.shape,
        lambda i: _imono_p_jit((lo[i : i + per], hi[i : i + per])),
        range(0, B, per),
    )


def _lde_one_p(p, lde_factor: int, coset: int):
    n = int(p[0].shape[-1])
    if _mxu_ntt_ready(n, None):
        from . import mxu_ntt

        log_n = n.bit_length() - 1
        if log_n > mxu_ntt.MAX_LOG_N:
            scale = _lde_scale_planes(log_n, lde_factor, coset)
            scaled = limbs.mul(
                (p[0][..., None, :], p[1][..., None, :]), scale
            )
            return _mxu_fft_p(scaled, False)
        ctx = mxu_ntt.get_mxu_ctx(log_n)
        lead = p[0].shape[:-1]
        flat = (
            p[0].reshape(-1, ctx.R, ctx.C),
            p[1].reshape(-1, ctx.R, ctx.C),
        )
        scale = _lde_scale_planes(log_n, lde_factor, coset)
        s_planes = (
            scale[0].reshape(lde_factor, ctx.R, ctx.C),
            scale[1].reshape(lde_factor, ctx.R, ctx.C),
        )
        out = mxu_ntt._lde_planes(flat, s_planes, log_n, False)
        return (
            out[0].reshape(lead + (lde_factor, n)),
            out[1].reshape(lead + (lde_factor, n)),
        )
    return _lde_p_jit(p, lde_factor, coset)


def lde_from_monomial_p(
    p, lde_factor: int, coset: int = int(gl.MULTIPLICATIVE_GENERATOR)
):
    """Monomial planes (..., n) -> (..., lde_factor, n) LDE planes."""
    coset = int(coset) % gl.P
    lo, hi = p
    n = lo.shape[-1]
    if lo.ndim < 2:
        return _lde_one_p(p, lde_factor, coset)
    B = lo.shape[0]
    per = _col_chunks(B, lo.size // B * 8 * lde_factor)
    if per is None:
        return _lde_one_p(p, lde_factor, coset)
    return _assemble_chunks_p(
        lo.shape[:-1] + (lde_factor, n),
        lambda i: _lde_one_p(
            (lo[i : i + per], hi[i : i + per]), lde_factor, coset
        ),
        range(0, B, per),
    )


# ---------------------------------------------------------------------------
# Precompile enumeration (ntt.ntt_kernel_specs twin, resident names)
# ---------------------------------------------------------------------------


def plane_ntt_kernel_specs(B: int, log_n: int, lde_factor: int | None = None,
                           coset: int = int(gl.MULTIPLICATIVE_GENERATOR),
                           mono: bool = True) -> list:
    """(name, jitted_fn, args) triples for the plane transforms a resident
    prove dispatches for a (B, 2^log_n) column stack — mirroring the
    MXU-vs-XLA routing and the chunk walk of the u64 ntt_kernel_specs."""
    from .ntt import chunk_shapes

    n = 1 << log_n

    def sdsp(*shape):
        s = jax.ShapeDtypeStruct(shape, jnp.uint32)
        return (s, s)

    specs = []
    if mono:
        specs += [
            (f"imono_limbres_b{b}_n{n}", _imono_p_jit, (sdsp(b, n),))
            for b in chunk_shapes(B, n * 8)
        ]
    if lde_factor is None:
        return specs
    L = int(lde_factor)
    coset = int(coset) % gl.P
    mxu = _mxu_ntt_ready(n, None)
    for b in chunk_shapes(B, n * 8 * L):
        if not mxu:
            specs.append((
                f"lde_limbres_b{b}_n{n}_L{L}", _lde_p_jit,
                (sdsp(b, n), L, coset),
            ))
            continue
        from . import mxu_ntt

        if log_n > mxu_ntt.MAX_LOG_N:
            specs.append((
                f"lde_hybrid_limbres_b{b}_n{n}_L{L}", _hybrid_p,
                (sdsp(b, L, n), log_n, False),
            ))
            continue
        ctx = mxu_ntt.get_mxu_ctx(log_n)
        specs.append((
            f"lde_mxu_limbres_b{b}_n{n}_L{L}", mxu_ntt._lde_planes,
            (sdsp(b, ctx.R, ctx.C), sdsp(L, ctx.R, ctx.C), log_n, False),
        ))
    return specs
