"""BabyBear NTT/LDE on bare u32 lanes (ISSUE 19).

The plane-free twin of the Goldilocks transform stack: two-adicity 27
clears every domain this repo builds, so the radix-2 structure carries
over unchanged — only the butterflies shrink from (lo, hi) limb-pair
carry chains to single u32 lanes (half the HBM traffic per stage).

Layout contract (simpler than ntt.py's bit-reversed pipeline — the
BabyBear prover is a self-contained leg, so it keeps everything in
NATURAL order):
  - `monomial_from_values_bb`: (..., n) natural-order evaluations over
    the size-n subgroup -> natural-order monomial coefficients (iNTT);
  - `values_from_monomial_bb`: the forward inverse of the above;
  - `lde_from_monomial_bb`: monomials -> natural-order evaluations over
    the coset shift*<w_N> of size N = n*lde_factor. Subcoset r of the
    N-domain is shift*w_N^r*<w_n>; its size-n NTT lands at positions
    j = r + q*L, so the (L, n) stack transposes straight into the
    natural-order N-point table.

Twiddle tables are cached per (log_n) on host (numpy powers) and baked
into the jitted graphs as constants, mirroring NTTContext.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..field import babybear as bb


def bitreverse_indices(n: int) -> np.ndarray:
    bits = n.bit_length() - 1
    idx = np.arange(n, dtype=np.uint32)
    out = np.zeros(n, dtype=np.uint32)
    for b in range(bits):
        out |= ((idx >> b) & 1).astype(np.uint32) << (bits - 1 - b)
    return out


@functools.lru_cache(maxsize=None)
def _twiddles(log_n: int, inverse: bool):
    """Per-stage DIT twiddle tables, natural-order radix-2: stage s
    (half = 2^s) uses w_{2^(s+1)}^k for k < half."""
    n = 1 << log_n
    w = bb.omega(log_n)
    if inverse:
        w = bb.inv_s(w)
    full = bb.powers_np(w, n // 2 if n > 1 else 1)
    stages = []
    for s in range(log_n):
        half = 1 << s
        step = n // (2 * half)
        stages.append(np.ascontiguousarray(full[:: step][:half]))
    return tuple(stages)


def _ntt_core(x, log_n: int, inverse: bool):
    """Iterative radix-2 over the last axis: bit-reverse permute then
    log_n DIT butterfly stages (natural in, natural out)."""
    n = 1 << log_n
    if n == 1:
        return x
    brev = jnp.asarray(bitreverse_indices(n))
    y = jnp.take(x, brev, axis=-1)
    stages = _twiddles(log_n, inverse)
    for s in range(log_n):
        half = 1 << s
        tw = jnp.asarray(stages[s])  # (half,)
        y = y.reshape(y.shape[:-1] + (n // (2 * half), 2 * half))
        even = y[..., :half]
        odd = bb.mul(y[..., half:], tw)
        y = jnp.concatenate([bb.add(even, odd), bb.sub(even, odd)], axis=-1)
        y = y.reshape(y.shape[:-2] + (n,))
    return y


@functools.partial(jax.jit, static_argnums=(1,))
def values_from_monomial_bb(mono, log_n: int):
    """Natural-order monomials -> natural-order subgroup evaluations."""
    return _ntt_core(mono, log_n, inverse=False)


@functools.partial(jax.jit, static_argnums=(1,))
def monomial_from_values_bb(values, log_n: int):
    """iNTT: natural-order evaluations -> monomial coefficients."""
    y = _ntt_core(values, log_n, inverse=True)
    n_inv = bb.inv_s(1 << log_n)
    return bb.mul_const(y, n_inv)


@functools.lru_cache(maxsize=None)
def _lde_scale_table(log_n: int, lde_factor: int, shift: int):
    """(L, n) scale rows: row r holds (shift * w_N^r)^i for i < n."""
    n = 1 << log_n
    N = n * lde_factor
    w_big = bb.omega(N.bit_length() - 1)
    rows = []
    for r in range(lde_factor):
        base = bb.mul_s(shift % bb.P, bb.pow_s(w_big, r))
        rows.append(bb.powers_np(base, n))
    return np.stack(rows)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def lde_from_monomial_bb(mono, log_n: int, lde_factor: int, shift: int):
    """(..., n) monomials -> (..., N) natural-order coset evaluations,
    N = n * lde_factor, domain shift*<w_N>. One scaled size-n NTT per
    subcoset, interleaved by transpose."""
    scale = jnp.asarray(_lde_scale_table(log_n, lde_factor, shift))
    # (..., 1, n) * (L, n) -> (..., L, n)
    scaled = bb.mul(mono[..., None, :], scale)
    evals = _ntt_core(scaled, log_n, inverse=False)  # (..., L, n)
    # position j = r + q*L <- subcoset r index q: transpose (L, n)->(n, L)
    out = jnp.swapaxes(evals, -1, -2)
    return out.reshape(out.shape[:-2] + ((1 << log_n) * lde_factor,))


# ---------------------------------------------------------------------------
# NumPy reference twins (compat/prove_reference_bb.py) — same layout
# contract, pure host
# ---------------------------------------------------------------------------


def ntt_np(x: np.ndarray, inverse: bool) -> np.ndarray:
    n = x.shape[-1]
    log_n = n.bit_length() - 1
    assert 1 << log_n == n
    if n == 1:
        return x.astype(np.uint32)
    y = np.take(x.astype(np.uint32), bitreverse_indices(n), axis=-1)
    stages = _twiddles(log_n, inverse)
    for s in range(log_n):
        half = 1 << s
        tw = stages[s]
        y = y.reshape(y.shape[:-1] + (n // (2 * half), 2 * half))
        even = y[..., :half]
        odd = bb.mul_np(y[..., half:], tw)
        y = np.concatenate(
            [bb.add_np(even, odd), bb.sub_np(even, odd)], axis=-1
        )
        y = y.reshape(y.shape[:-2] + (n,))
    if inverse:
        y = bb.mul_np(y, np.uint32(bb.inv_s(n)))
    return y


def lde_np(mono: np.ndarray, lde_factor: int, shift: int) -> np.ndarray:
    n = mono.shape[-1]
    log_n = n.bit_length() - 1
    scale = _lde_scale_table(log_n, lde_factor, shift)
    scaled = bb.mul_np(mono[..., None, :], scale)
    evals = ntt_np(scaled, inverse=False)
    out = np.swapaxes(evals, -1, -2)
    return np.ascontiguousarray(out).reshape(
        out.shape[:-2] + (n * lde_factor,)
    )
