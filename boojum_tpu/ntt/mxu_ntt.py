"""NTT on the MXU: four-step decomposition as exact int8 digit matmuls.

TPU-native counterpart of the reference's vectorized NTT tier
(`/root/reference/src/fft/mod.rs:852,1088` + the AVX-512/NEON MixedGL
butterflies in `src/field/goldilocks/avx512_impl.rs`): where those beat the
generic scalar path with hand-packed SIMD, this beats XLA's emulated-u64
butterflies by moving the multiply work onto the systolic array.

A size-n transform (n = R*C, R,C <= 256) is two matrix products against
CONSTANT DFT matrices plus one elementwise twiddle:

  forward  (natural -> bit-reversed):  out = ((D_R @ X) * T) @ D_C^T
  inverse  (bit-reversed -> natural):  out = F @ ((X * 1) @ E_inv * T_inv)

with
  X      = the column viewed as an (R, C) matrix, x[i] at X[i // C][i % C]
  D_R    = omega_R^(brev(a) * r)            (R x R)
  T      = omega_n^(c * brev(a))            (R x C)
  D_C    = omega_C^(brev(d) * c)            (C x C)
  E_inv  = omega_C^(-brev(c) * c')          (C x C)
  T_inv  = omega_n^(-c' * brev(r))          (R x C)
  F      = n^-1 * omega_R^(-r' * brev(r))   (R x R)

Both conventions come out so the row-major flattening of the result IS the
bit-reversed (resp. natural) order — no transposes anywhere.

Exact integer matmul on the MXU: every Goldilocks operand is written in
BALANCED base-256 — eight signed digits d_k in [-128, 127] — and the 64
per-(digit,digit) products run as int8 x int8 -> int32 dots, the MXU's
native (and fastest: 2x bf16 on v5e) integer mode, with exact int32
accumulation at any contraction length used here. Representability: the
8-digit balanced range is [-0x8080808080808080, 0x7F7F7F7F7F7F7F7F] (=: [m,
M], every byte -128 resp. +127), and p + m < M, so for every canonical x
either x itself (x <= M) or x - p (two's complement) has an exact form —
the in-kernel conversion is one conditional `+= 2^32-1` (== -p mod 2^64)
plus a byte-wise carry chain. The 64 product planes are accumulated into 15
signed diagonal planes on the VPU, biased non-negative, then folded mod p
with 2^64 = eps = 2^32 - 1, 2^96 = -1, 2^128 = -2^32 (mod p), and the
constant bias contribution is subtracted at the end.

Sizes 2^14..2^16 run as single fused kernels; 2^17..2^22 run the leading
(resp. trailing) radix-2 stages in XLA and drop bit-exactly into per-block
2^16 kernels (DIF stage s only combines elements 2^16 apart for s < log_n-16,
so the remaining per-block work is a plain 2^16 transform).

Outputs are bit-identical to the staged-XLA path (`ntt.py`): same twiddle
constants, exact integer arithmetic, canonical representatives.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..field import gl
from ..field import limbs
from ..utils.pallas_util import imap32

MIN_LOG_N = 14  # below this C < 128 lanes and the XLA path is already cheap
MAX_LOG_N = 16  # single-kernel ceiling; larger sizes go hybrid
MAX_HYBRID_LOG_N = 22

_u32 = jnp.uint32
_MASK8 = np.uint32(0xFF)
_P_LO = np.uint32(1)
_P_HI = np.uint32(0xFFFFFFFF)
_FULL = np.uint32(0xFFFFFFFF)

# Largest value representable in 8 balanced base-256 digits: 127 per byte.
# For canonical x > _M_BAL the kernel switches to the x - p representative
# (p + (minimum representable) < _M_BAL, so one switch always suffices).
_M_BAL = 0x7F7F7F7F7F7F7F7F
_M_WORD = np.uint32(0x7F7F7F7F)
# Diagonal bias making the signed diagonal planes non-negative before the
# unsigned fold: |Q_k| <= 8 pairs * 256 terms * 128*128 = 2^25.
_BIAS = np.int32(1 << 25)
_BIAS_TOTAL = sum((1 << 25) << (8 * k) for k in range(15)) % gl.P
_BIAS_PAIR = (
    np.uint32(_BIAS_TOTAL & 0xFFFFFFFF),
    np.uint32(_BIAS_TOTAL >> 32),
)

from ..utils.pallas_util import tpu_compiler_params

_COMPILER_PARAMS = tpu_compiler_params(100 * 1024 * 1024)


def _brev(log_n: int) -> np.ndarray:
    from .ntt import bitreverse_indices

    return bitreverse_indices(log_n).astype(np.int64)


def _pow_table(base: int, count: int) -> np.ndarray:
    return gl.powers_np(base, count)


def _digits8_np(x: np.ndarray):
    """u64 canonical -> (8, ..) int8 planes of balanced base-256 digits."""
    x = np.asarray(x, dtype=np.uint64)
    # x - p mod 2^64 == x + (2^32 - 1); numpy wraps mod 2^64
    u = np.where(x > np.uint64(_M_BAL), x + np.uint64(0xFFFFFFFF), x)
    digs = []
    carry = np.zeros(x.shape, dtype=np.int64)
    for k in range(8):
        b = ((u >> np.uint64(8 * k)) & np.uint64(0xFF)).astype(np.int64)
        t = b + carry
        ge = t >= 128
        digs.append((t - 256 * ge).astype(np.int8))
        carry = ge.astype(np.int64)
    return jnp.asarray(np.stack(digs))


def _pair_np(x: np.ndarray):
    lo, hi = limbs.split_np(x)
    return jnp.asarray(lo), jnp.asarray(hi)


class MXUNTTContext:
    """Baked constant matrices for one (log_R, log_C) split."""

    def __init__(self, log_n: int):
        assert MIN_LOG_N <= log_n <= MAX_LOG_N
        self.log_n = log_n
        self.n = 1 << log_n
        self.log_R = (log_n + 1) // 2
        self.log_C = log_n // 2
        R, C = 1 << self.log_R, 1 << self.log_C
        self.R, self.C = R, C

        wR = gl.omega(self.log_R)
        wC = gl.omega(self.log_C)
        wn = gl.omega(log_n)
        brR = _brev(self.log_R)
        brC = _brev(self.log_C)
        r_idx = np.arange(R, dtype=np.int64)
        c_idx = np.arange(C, dtype=np.int64)

        powsR = _pow_table(wR, R)
        powsC = _pow_table(wC, C)
        powsn = _pow_table(wn, self.n)
        powsRi = _pow_table(gl.inv(wR), R)
        powsCi = _pow_table(gl.inv(wC), C)
        powsni = _pow_table(gl.inv(wn), self.n)

        D_R = powsR[(brR[:, None] * r_idx[None, :]) % R]  # (R, R)
        D_C = powsC[(brC[:, None] * c_idx[None, :]) % C]  # (C, C)
        T = powsn[(brR[:, None] * c_idx[None, :]) % self.n]  # (R, C)
        E_inv = powsCi[(brC[:, None] * c_idx[None, :]) % C]  # (C, C): [c][c']
        T_inv = powsni[(brR[:, None] * c_idx[None, :]) % self.n]  # (R, C)
        n_inv = gl.inv(self.n)
        powsRi_scaled = np.array(
            [gl.mul(int(v), n_inv) for v in powsRi], dtype=np.uint64
        )
        F = powsRi_scaled[(r_idx[:, None] * brR[None, :]) % R]  # (R, R)

        with jax.ensure_compile_time_eval():
            self.dr = _digits8_np(D_R)  # (8, R, R)
            self.dct = _digits8_np(D_C.T.copy())  # (8, C, C)
            self.tw = _pair_np(T)
            self.einv = _digits8_np(E_inv)
            self.tw_inv = _pair_np(T_inv)
            self.f = _digits8_np(F)


@lru_cache(maxsize=None)
def get_mxu_ctx(log_n: int) -> MXUNTTContext:
    return MXUNTTContext(log_n)


# ---------------------------------------------------------------------------
# In-kernel exact GL matmul: int8 digit dots + int32 diagonals + mod-p fold
# ---------------------------------------------------------------------------


def _digit_planes(x):
    """(lo, hi) u32 pair (canonical) -> list of 8 int8 balanced-digit planes."""
    lo, hi = x
    gt = ((hi > _M_WORD) | ((hi == _M_WORD) & (lo > _M_WORD))).astype(_u32)
    # x + (2^32 - 1) where x > M  (== x - p mod 2^64, two's complement)
    lo2 = lo - gt
    hi2 = hi + (gt & (lo != 0).astype(_u32))
    planes = []
    carry = jnp.zeros_like(lo, dtype=jnp.int32)
    for w in (lo2, hi2):
        for j in range(4):
            b = (w >> np.uint32(8 * j)) & _MASK8 if j else w & _MASK8
            t = b.astype(jnp.int32) + carry
            ge = (t >= 128).astype(jnp.int32)
            planes.append((t - 256 * ge).astype(jnp.int8))
            carry = ge
    return planes


def _b2u(x):
    return x.astype(_u32)


def _addmod_any(a, b):
    """(a + b) mod p on u32 pairs, correct for ANY u64 representatives
    (unlike limbs.add, which assumes canonical inputs). Result < 2^64 and
    congruent mod p; not necessarily canonical."""
    lo = a[0] + b[0]
    c0 = _b2u(lo < b[0])
    hi_t = a[1] + b[1]
    c1 = _b2u(hi_t < b[1])
    hi = hi_t + c0
    c2 = _b2u(hi < c0)
    carry = c1 | c2  # the two sub-carries cannot both fire for u64 operands
    # += carry * eps (2^64 ≡ eps); the +eps can itself wrap once more
    lo2 = lo - carry
    d1 = carry & _b2u(lo != 0)
    c3 = d1 & _b2u(hi == _FULL)
    hi2 = hi + d1
    lo3 = lo2 - c3
    d2 = c3 & _b2u(lo2 != 0)
    hi3 = hi2 + d2  # cannot wrap a third time: value is < 2^33 by then
    return lo3, hi3


def _eps_times(v):
    """eps * v as a u64 pair, exact for any u32 v: v*2^32 - v."""
    return np.uint32(0) - v, v - _b2u(v != 0)


def _p_minus_small(v):
    """p - v for u32 v (v*2^96 ≡ -v mod p)."""
    lo = _P_LO - v
    borrow = _b2u(v > 1)
    return lo, _P_HI - borrow


def _p_minus_hi(v):
    """p - v*2^32 for u32 v (v*2^128 ≡ -v*2^32 mod p)."""
    return jnp.full_like(v, _P_LO), _P_HI - v


def _fold15_signed(Q):
    """15 SIGNED int32 diagonal planes (|Q_k| <= 2^25) -> canonical GL pair.

    Bias each plane non-negative, run the unsigned fold, subtract the baked
    bias total mod p."""
    Qb = [(q + _BIAS).astype(_u32) for q in Q]
    acc = _fold15(Qb)
    bias = (
        jnp.full_like(acc[0], _BIAS_PAIR[0]),
        jnp.full_like(acc[1], _BIAS_PAIR[1]),
    )
    return limbs.sub(acc, bias)


def _fold15(Q):
    """15 int32 diagonal planes (Q_k < 2^31) -> canonical GL (lo, hi) pair.

    W = sum_k Q_k * 2^(8k) accumulated exactly into five u32 words with wrap
    counters, then folded with 2^64 ≡ eps, 2^96 ≡ -1, 2^128 ≡ -2^32 (mod p).
    """
    w = [None] * 5
    cnt = [None] * 5

    def _add_word(j, val):
        if w[j] is None:
            w[j] = val
            return
        nw = w[j] + val
        c = _b2u(nw < val)
        cnt[j] = c if cnt[j] is None else cnt[j] + c
        w[j] = nw

    for k in range(15):
        q = Q[k].astype(_u32)
        j, m = divmod(k, 4)
        sh = 8 * m
        _add_word(j, (q << np.uint32(sh)) if sh else q)
        if sh:
            _add_word(j + 1, q >> np.uint32(32 - sh))
    zero = jnp.zeros_like(Q[0].astype(_u32))
    for j in range(5):
        if w[j] is None:
            w[j] = zero
    # resolve wrap counters upward (w4 stays tiny: W < 2^140, so no overflow)
    for j in range(4):
        if cnt[j] is not None:
            _add_word(j + 1, cnt[j])

    acc = (w[0], w[1])
    acc = _addmod_any(acc, _eps_times(w[2]))
    acc = _addmod_any(acc, _p_minus_small(w[3]))
    acc = _addmod_any(acc, _p_minus_hi(w[4]))
    return limbs._canonicalize(*acc)


def _gl_matmul(x, dref, side: str):
    """Exact GL matmul of data pair `x` against baked int8 digit planes.

    side='left':  result = D @ X   (contract over X's rows)
    side='right': result = X @ D   (contract over X's cols)
    """
    planes = _digit_planes(x)
    Q = [None] * 15
    for u in range(8):
        du = dref[u]
        for v in range(8):
            if side == "left":
                p = jnp.dot(du, planes[v], preferred_element_type=jnp.int32)
            else:
                p = jnp.dot(planes[v], du, preferred_element_type=jnp.int32)
            k = u + v
            Q[k] = p if Q[k] is None else Q[k] + p
    return _fold15_signed(Q)


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------
# G columns process per grid step (see _TARGET_N): the dots become
# (R, R) @ (R, G*C) / (G*R, C) @ (C, C), so the MXU sees an N dimension of
# G*C instead of C. The relayouts between the row-stacked and lane-stacked
# views are leading-axis transposes (sublane shuffles).


def _pair_t(x, perm):
    return (jnp.transpose(x[0], perm), jnp.transpose(x[1], perm))


def _tile_lanes(t, G, R, C):
    """(R, C) twiddle plane -> (R, G*C): repeat per column along lanes."""
    return jnp.broadcast_to(t[:, None, :], (R, G, C)).reshape(R, G * C)


def _tile_rows(t, G, R, C):
    """(R, C) twiddle plane -> (G*R, C): repeat per column along rows."""
    return jnp.broadcast_to(t[None], (G, R, C)).reshape(G * R, C)


def _fwd_body(ctx, x, dr, dct, tlo, thi, G):
    R, C = ctx.R, ctx.C
    if G > 1:
        # (G, R, C) -> (R, G*C): lane-stack the G column matrices
        x = _pair_t(x, (1, 0, 2))
        x = (x[0].reshape(R, G * C), x[1].reshape(R, G * C))
        tlo, thi = _tile_lanes(tlo, G, R, C), _tile_lanes(thi, G, R, C)
    y = _gl_matmul(x, dr, "left")
    y = limbs.mul(y, (tlo, thi))
    if G > 1:
        # (R, G*C) -> (G*R, C): row-stack for the right-multiply
        y = (y[0].reshape(R, G, C), y[1].reshape(R, G, C))
        y = _pair_t(y, (1, 0, 2))
        y = (y[0].reshape(G * R, C), y[1].reshape(G * R, C))
    return _gl_matmul(y, dct, "right")


def _fwd_kernel(ctx, G, dr, dct, tlo, thi, xl, xh, ol, oh):
    x = (xl[:], xh[:]) if G > 1 else (xl[0], xh[0])
    z = _fwd_body(ctx, x, dr, dct, tlo[:], thi[:], G)
    if G > 1:
        R, C = ctx.R, ctx.C
        ol[:] = z[0].reshape(G, R, C)
        oh[:] = z[1].reshape(G, R, C)
    else:
        ol[0] = z[0]
        oh[0] = z[1]


def _fwd_scaled_kernel(ctx, dr, dct, tlo, thi, sl, sh, xl, xh, ol, oh):
    x = limbs.mul((xl[0], xh[0]), (sl[0], sh[0]))
    z = _fwd_body(ctx, x, dr, dct, tlo[:], thi[:], 1)
    ol[0, 0] = z[0]
    oh[0, 0] = z[1]


def _inv_kernel(ctx, G, einv, f, tlo, thi, xl, xh, ol, oh):
    R, C = ctx.R, ctx.C
    if G > 1:
        x = (xl[:].reshape(G * R, C), xh[:].reshape(G * R, C))
        tlo_t, thi_t = _tile_rows(tlo[:], G, R, C), _tile_rows(thi[:], G, R, C)
    else:
        x = (xl[0], xh[0])
        tlo_t, thi_t = tlo[:], thi[:]
    y = _gl_matmul(x, einv, "right")
    y = limbs.mul(y, (tlo_t, thi_t))
    if G > 1:
        # (G*R, C) -> (R, G*C) for the left-multiply
        y = (y[0].reshape(G, R, C), y[1].reshape(G, R, C))
        y = _pair_t(y, (1, 0, 2))
        y = (y[0].reshape(R, G * C), y[1].reshape(R, G * C))
    z = _gl_matmul(y, f, "left")
    if G > 1:
        z = (z[0].reshape(R, G, C), z[1].reshape(R, G, C))
        z = _pair_t(z, (1, 0, 2))
        ol[:] = z[0]
        oh[:] = z[1]
    else:
        ol[0] = z[0]
        oh[0] = z[1]


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


def _const_spec(shape):
    nd = len(shape)
    return pl.BlockSpec(
        shape,
        imap32(lambda *_: (0,) * nd),
        memory_space=pltpu.VMEM,
    )


def _data_spec(R, C, G=1):
    return pl.BlockSpec(
        (G, R, C), imap32(lambda b: (b, 0, 0)), memory_space=pltpu.VMEM
    )


# Columns per grid step: the dot's N dimension becomes G*C. The MXU wants
# N >= ~1024 to stream (isolated dot throughput ~3x at G=4 vs G=1 for
# C=256); end-to-end NTT gain is smaller — the pipeline is DMA/layout
# bound — but G=4 is never slower, so it is the default.
_TARGET_N = 1024


def _pad_cols(planes, G):
    """Zero-pad the column batch to a multiple of G (returns B_orig)."""
    lo, hi = planes
    B = lo.shape[0]
    pad = (-B) % G
    if pad:
        z = jnp.zeros((pad,) + lo.shape[1:], lo.dtype)
        lo = jnp.concatenate([lo, z])
        hi = jnp.concatenate([hi, z])
    return (lo, hi), B


@partial(jax.jit, static_argnums=(1, 2))
def _fft_planes(planes, log_n: int, interpret: bool):
    ctx = get_mxu_ctx(log_n)
    R, C = ctx.R, ctx.C
    G = max(1, _TARGET_N // C)
    (lo, hi), B = _pad_cols(planes, G)
    spec = _data_spec(R, C, G)
    Bp = lo.shape[0]
    out_shape = jax.ShapeDtypeStruct((Bp, R, C), jnp.uint32)
    out = pl.pallas_call(
        partial(_fwd_kernel, ctx, G),
        grid=(Bp // G,),
        out_shape=[out_shape, out_shape],
        in_specs=[
            _const_spec((8, R, R)),
            _const_spec((8, C, C)),
            _const_spec((R, C)),
            _const_spec((R, C)),
            spec,
            spec,
        ],
        out_specs=[spec, spec],
        interpret=interpret,
        compiler_params=None if interpret else _COMPILER_PARAMS,
    )(ctx.dr, ctx.dct, *ctx.tw, lo, hi)
    return out[0][:B], out[1][:B]


@partial(jax.jit, static_argnums=(1, 2))
def _ifft_planes(planes, log_n: int, interpret: bool):
    ctx = get_mxu_ctx(log_n)
    R, C = ctx.R, ctx.C
    G = max(1, _TARGET_N // C)
    (lo, hi), B = _pad_cols(planes, G)
    spec = _data_spec(R, C, G)
    Bp = lo.shape[0]
    out_shape = jax.ShapeDtypeStruct((Bp, R, C), jnp.uint32)
    out = pl.pallas_call(
        partial(_inv_kernel, ctx, G),
        grid=(Bp // G,),
        out_shape=[out_shape, out_shape],
        in_specs=[
            _const_spec((8, C, C)),
            _const_spec((8, R, R)),
            _const_spec((R, C)),
            _const_spec((R, C)),
            spec,
            spec,
        ],
        out_specs=[spec, spec],
        interpret=interpret,
        compiler_params=None if interpret else _COMPILER_PARAMS,
    )(ctx.einv, ctx.f, *ctx.tw_inv, lo, hi)
    return out[0][:B], out[1][:B]


@partial(jax.jit, static_argnums=(2, 3))
def _lde_planes(coeff_planes, scale_planes, log_n: int, interpret: bool):
    """coeffs (B, R, C) x scale (L, R, C) -> (B, L, R, C), scale+NTT fused."""
    ctx = get_mxu_ctx(log_n)
    clo, chi = coeff_planes
    slo, shi = scale_planes
    B = clo.shape[0]
    L = slo.shape[0]
    R, C = ctx.R, ctx.C
    cspec = pl.BlockSpec(
        (1, R, C), imap32(lambda b, l: (b, 0, 0)), memory_space=pltpu.VMEM
    )
    sspec = pl.BlockSpec(
        (1, R, C), imap32(lambda b, l: (l, 0, 0)), memory_space=pltpu.VMEM
    )
    ospec = pl.BlockSpec(
        (1, 1, R, C),
        imap32(lambda b, l: (b, l, 0, 0)),
        memory_space=pltpu.VMEM,
    )
    out_shape = jax.ShapeDtypeStruct((B, L, R, C), jnp.uint32)
    return pl.pallas_call(
        partial(_fwd_scaled_kernel, ctx),
        grid=(B, L),
        out_shape=[out_shape, out_shape],
        in_specs=[
            _const_spec((8, R, R)),
            _const_spec((8, C, C)),
            _const_spec((R, C)),
            _const_spec((R, C)),
            sspec,
            sspec,
            cspec,
            cspec,
        ],
        out_specs=[ospec, ospec],
        interpret=interpret,
        compiler_params=None if interpret else _COMPILER_PARAMS,
    )(ctx.dr, ctx.dct, *ctx.tw, slo, shi, clo, chi)


# ---------------------------------------------------------------------------
# Public entry points (uint64 in / uint64 out)
# ---------------------------------------------------------------------------


def size_fits(n: int) -> bool:
    return (1 << MIN_LOG_N) <= n <= (1 << MAX_HYBRID_LOG_N)


def _to_planes(a: jax.Array, R: int, C: int):
    lead = a.shape[:-1]
    flat = a.reshape(-1, R, C)
    return limbs.split(flat), lead


def _from_planes(planes, lead, n):
    return limbs.join(planes).reshape(lead + (n,))


def fft_natural_to_bitreversed(a: jax.Array, interpret: bool = False):
    n = a.shape[-1]
    log_n = n.bit_length() - 1
    if log_n > MAX_LOG_N:
        return _fft_hybrid(a, log_n, interpret)
    ctx = get_mxu_ctx(log_n)
    planes, lead = _to_planes(a, ctx.R, ctx.C)
    out = _fft_planes(planes, log_n, interpret)
    return _from_planes(out, lead, n)


def ifft_bitreversed_to_natural(a: jax.Array, interpret: bool = False):
    n = a.shape[-1]
    log_n = n.bit_length() - 1
    if log_n > MAX_LOG_N:
        return _ifft_hybrid(a, log_n, interpret)
    ctx = get_mxu_ctx(log_n)
    planes, lead = _to_planes(a, ctx.R, ctx.C)
    out = _ifft_planes(planes, log_n, interpret)
    return _from_planes(out, lead, n)


def lde_from_monomial(coeffs: jax.Array, scale: jax.Array, interpret: bool = False):
    """coeffs (..., n), scale (lde, n) -> (..., lde, n); fused scale+NTT."""
    n = coeffs.shape[-1]
    log_n = n.bit_length() - 1
    lde = scale.shape[0]
    if log_n > MAX_LOG_N:
        from ..field import goldilocks as gf

        scaled = gf.mul(coeffs[..., None, :], scale)
        return _fft_hybrid(scaled, log_n, interpret)
    ctx = get_mxu_ctx(log_n)
    planes, lead = _to_planes(coeffs, ctx.R, ctx.C)
    s_planes = limbs.split(scale.reshape(lde, ctx.R, ctx.C))
    out = _lde_planes(planes, s_planes, log_n, interpret)
    return limbs.join(out).reshape(lead + (lde, n))


# ---------------------------------------------------------------------------
# Hybrid sizes (2^17..2^22): XLA outer radix-2 stages + per-block kernels
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnums=(1, 2))
def _fft_hybrid(a: jax.Array, log_n: int, interpret: bool):
    from .ntt import dif_stages, get_ntt_context

    n = 1 << log_n
    outer = log_n - MAX_LOG_N
    ctx = get_ntt_context(log_n)
    a = dif_stages(a, ctx, 0, outer)
    lead = a.shape[:-1]
    blocks = a.reshape(lead + (1 << outer, 1 << MAX_LOG_N))
    out = fft_natural_to_bitreversed(blocks, interpret)
    return out.reshape(lead + (n,))


@partial(jax.jit, static_argnums=(1, 2))
def _ifft_hybrid(a: jax.Array, log_n: int, interpret: bool):
    from ..field import goldilocks as gf
    from .ntt import dit_stages, get_ntt_context

    n = 1 << log_n
    outer = log_n - MAX_LOG_N
    ctx = get_ntt_context(log_n)
    lead = a.shape[:-1]
    blocks = a.reshape(lead + (1 << outer, 1 << MAX_LOG_N))
    # per-block inverse includes 1/2^16; outer stages + leftover 1/2^outer
    out = ifft_bitreversed_to_natural(blocks, interpret).reshape(lead + (n,))
    out = dit_stages(out, ctx, MAX_LOG_N, log_n)
    return gf.mul(out, jnp.uint64(gl.inv(1 << outer)))
