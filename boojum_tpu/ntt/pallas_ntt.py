"""Radix-2 NTT as fused Pallas TPU kernels over u32 limb planes.

The TPU-kernel counterpart of the reference's vectorized NTTs
(`/root/reference/src/fft/mod.rs:852,1088` — MixedGL butterflies with
interleaved twiddles): one column (or LDE coset) stays resident in VMEM for
ALL log2(n) butterfly stages, so the transform costs one HBM read and one
write instead of a round-trip per stage (the XLA-staged form's floor once the
per-stage fusions are materialized).

Layout: a length-n column is viewed as (n/128, 128) — sublanes x lanes.
- stages with butterfly distance d >= 128 pair whole sublane groups:
  a 4D reshape (blocks, 2, d/128, 128) splits u/v with no data movement;
- stages with d < 128 pair elements within a lane row: `jnp.roll` along the
  lane axis fetches the partner, a lane-index mask selects the u/v role
  (the standard rotate-and-select vector butterfly).

Twiddle VALUES are sliced from the same cached power tables the XLA path uses
(`ntt.NTTContext`), packed per stage into (rows, 128) planes — outputs are
bit-identical to `fft_natural_to_bitreversed`/`ifft_bitreversed_to_natural`
by construction (same butterfly formulas, same constants, exact integer ops).

The forward kernel optionally fuses the coset-scale multiply (LDE: scale by
shift^i before transforming), saving the (cols, lde, n) scaled intermediate
the XLA path materializes.

Dispatch: `ntt.py` routes here (opt-in, BOOJUM_TPU_PALLAS_NTT=1) for
2^11 <= n <= 2^16 — one column's full stage chain fits the VMEM budget up
to 2^16 (the 2^17 inverse OOMs its scoped allocation); larger transforms
and CPU keep the staged-XLA path. A two-level (four-step) decomposition
for >=2^17 is future work.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..field import gl
from ..field import limbs
from ..utils.pallas_util import imap32

_LANE = 128


def _as_rows(x: np.ndarray) -> np.ndarray:
    return x.reshape(-1, _LANE)


class PallasNTTContext:
    """Packed per-stage twiddle planes for one transform size."""

    def __init__(self, log_n: int):
        from .ntt import get_ntt_context

        self.log_n = log_n
        n = self.n = 1 << log_n
        ctx = get_ntt_context(log_n)
        with jax.ensure_compile_time_eval():
            tw = np.asarray(ctx.tw)  # omega^j, j < n/2
            itw = np.asarray(ctx.itw)
        self.n_inv = limbs.const_pair(gl.inv(n))

        # forward (DIF): stage s has half-distance d = n >> (s+1),
        # twiddle[j] = omega^(j << s)
        fwd_rows, self.fwd_row_offs = [], []
        fwd_lanes = []
        self.fwd_stages = []
        off = 0
        for s in range(log_n):
            d = n >> (s + 1)
            if d >= _LANE:
                self.fwd_stages.append(("row", d, off))
                self.fwd_row_offs.append(off)
                fwd_rows.append(_as_rows(tw[:: 1 << s][:d]))
                off += d // _LANE
            else:
                # lane vector: t[j] = tw[(j % d) << s] (valid for both halves)
                j = np.arange(_LANE)
                vec = tw[((j % d) << s) % (n // 2)] if d > 0 else None
                self.fwd_stages.append(("lane", d, len(fwd_lanes)))
                fwd_lanes.append(vec)

        # inverse (DIT): stage s has half-distance d = 1 << s,
        # twiddle[j] = omega_inv^(j << (log_n - s - 1))
        inv_rows = []
        inv_lanes = []
        self.inv_stages = []
        off = 0
        for s in range(log_n):
            d = 1 << s
            shift = log_n - s - 1
            if d >= _LANE:
                self.inv_stages.append(("row", d, off))
                inv_rows.append(_as_rows(itw[:: 1 << shift][:d]))
                off += d // _LANE
            else:
                j = np.arange(_LANE)
                vec = itw[((j % d) << shift) % (n // 2)]
                self.inv_stages.append(("lane", d, len(inv_lanes)))
                inv_lanes.append(vec)

        def pack(rows, lanes):
            rows_arr = (
                np.concatenate(rows, axis=0)
                if rows
                else np.zeros((1, _LANE), np.uint64)
            )
            lanes_arr = (
                np.stack(lanes)
                if lanes
                else np.zeros((1, _LANE), np.uint64)
            )
            # pad lane-stage count to a sublane multiple
            pad = (-lanes_arr.shape[0]) % 8
            if pad:
                lanes_arr = np.concatenate(
                    [lanes_arr, np.zeros((pad, _LANE), np.uint64)]
                )
            return (
                tuple(map(jnp.asarray, limbs.split_np(rows_arr))),
                tuple(map(jnp.asarray, limbs.split_np(lanes_arr))),
            )

        # contexts are lru-cached across traces: materialize the device
        # arrays eagerly even when first touched inside a jit trace
        with jax.ensure_compile_time_eval():
            self.fwd_tw = pack(fwd_rows, fwd_lanes)
            self.inv_tw = pack(inv_rows, inv_lanes)


@lru_cache(maxsize=None)
def get_pallas_ctx(log_n: int) -> PallasNTTContext:
    return PallasNTTContext(log_n)


# ---------------------------------------------------------------------------
# Kernel bodies (operate on (R, 128) limb-pair values)
# ---------------------------------------------------------------------------


def _lane_iota(shape):
    return jax.lax.broadcasted_iota(jnp.int32, shape, len(shape) - 1)


def _where(mask, a, b):
    return (
        jnp.where(mask, a[0], b[0]),
        jnp.where(mask, a[1], b[1]),
    )


def _reshape(x, shape):
    return x[0].reshape(shape), x[1].reshape(shape)


def _stack2(a, b, axis):
    return (
        jnp.stack([a[0], b[0]], axis=axis),
        jnp.stack([a[1], b[1]], axis=axis),
    )


def _fwd_stages_body(ctx: PallasNTTContext, x, trow, tlane):
    """All DIF stages on an (R, 128) limb pair; returns same shape."""
    R = ctx.n // _LANE
    for kind, d, off in ctx.fwd_stages:
        if kind == "row":
            rows_d = d // _LANE
            blocks = R // (2 * rows_d)
            x4 = _reshape(x, (blocks, 2, rows_d, _LANE))
            u = (x4[0][:, 0], x4[1][:, 0])
            v = (x4[0][:, 1], x4[1][:, 1])
            tw = (
                trow[0][off : off + rows_d],
                trow[1][off : off + rows_d],
            )
            top = limbs.add(u, v)
            bot = limbs.mul(limbs.sub(u, v), tw)
            x = _reshape(_stack2(top, bot, 1), (R, _LANE))
        else:
            tw = (tlane[0][off : off + 1], tlane[1][off : off + 1])
            r1 = (
                jnp.roll(x[0], -d, axis=-1),
                jnp.roll(x[1], -d, axis=-1),
            )
            r2 = (
                jnp.roll(x[0], d, axis=-1),
                jnp.roll(x[1], d, axis=-1),
            )
            mask = (_lane_iota(x[0].shape) & jnp.int32(2 * d - 1)) < jnp.int32(d)
            top = limbs.add(x, r1)
            bot = limbs.mul(limbs.sub(r2, x), tw)
            x = _where(mask, top, bot)
    return x


def _inv_stages_body(ctx: PallasNTTContext, x, trow, tlane):
    """All DIT stages + 1/n scale on an (R, 128) limb pair."""
    R = ctx.n // _LANE
    for kind, d, off in ctx.inv_stages:
        if kind == "lane":
            tw = (tlane[0][off : off + 1], tlane[1][off : off + 1])
            r1 = (
                jnp.roll(x[0], -d, axis=-1),
                jnp.roll(x[1], -d, axis=-1),
            )
            r2 = (
                jnp.roll(x[0], d, axis=-1),
                jnp.roll(x[1], d, axis=-1),
            )
            mask = (_lane_iota(x[0].shape) & jnp.int32(2 * d - 1)) < jnp.int32(d)
            wv_first = limbs.mul(r1, tw)
            wv_self = limbs.mul(x, tw)
            x = _where(
                mask, limbs.add(x, wv_first), limbs.sub(r2, wv_self)
            )
        else:
            rows_d = d // _LANE
            blocks = R // (2 * rows_d)
            x4 = _reshape(x, (blocks, 2, rows_d, _LANE))
            u = (x4[0][:, 0], x4[1][:, 0])
            v = (x4[0][:, 1], x4[1][:, 1])
            tw = (
                trow[0][off : off + rows_d],
                trow[1][off : off + rows_d],
            )
            wv = limbs.mul(v, tw)
            x = _reshape(
                _stack2(limbs.add(u, wv), limbs.sub(u, wv), 1), (R, _LANE)
            )
    return limbs.mul_const(x, ctx.n_inv)


def _fwd_kernel(ctx, trl, trh, tll, tlh, xl, xh, ol, oh):
    x = _fwd_stages_body(ctx, (xl[0], xh[0]), (trl[:], trh[:]), (tll[:], tlh[:]))
    ol[0] = x[0]
    oh[0] = x[1]


def _fwd_scaled_kernel(ctx, trl, trh, tll, tlh, sl, sh, xl, xh, ol, oh):
    x = limbs.mul((xl[0], xh[0]), (sl[0], sh[0]))
    x = _fwd_stages_body(ctx, x, (trl[:], trh[:]), (tll[:], tlh[:]))
    ol[0] = x[0]
    oh[0] = x[1]


def _inv_kernel(ctx, trl, trh, tll, tlh, xl, xh, ol, oh):
    x = _inv_stages_body(ctx, (xl[0], xh[0]), (trl[:], trh[:]), (tll[:], tlh[:]))
    ol[0] = x[0]
    oh[0] = x[1]


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


def _tw_specs(ctx):
    rows = ctx.fwd_tw[0][0].shape[0]
    lanes = ctx.fwd_tw[1][0].shape[0]
    row_spec = pl.BlockSpec(
        (rows, _LANE), imap32(lambda *_: (0, 0)), memory_space=pltpu.VMEM
    )
    lane_spec = pl.BlockSpec(
        (lanes, _LANE), imap32(lambda *_: (0, 0)), memory_space=pltpu.VMEM
    )
    return [row_spec, row_spec, lane_spec, lane_spec]


def _itw_specs(ctx):
    rows = ctx.inv_tw[0][0].shape[0]
    lanes = ctx.inv_tw[1][0].shape[0]
    row_spec = pl.BlockSpec(
        (rows, _LANE), imap32(lambda *_: (0, 0)), memory_space=pltpu.VMEM
    )
    lane_spec = pl.BlockSpec(
        (lanes, _LANE), imap32(lambda *_: (0, 0)), memory_space=pltpu.VMEM
    )
    return [row_spec, row_spec, lane_spec, lane_spec]


def _col_spec(R):
    return pl.BlockSpec(
        (1, R, _LANE), imap32(lambda b: (b, 0, 0)), memory_space=pltpu.VMEM
    )


@partial(jax.jit, static_argnums=(1, 2))
def _fft_planes(planes, log_n: int, interpret: bool):
    """(B, R, 128) limb planes -> transformed, grid over B."""
    ctx = get_pallas_ctx(log_n)
    lo, hi = planes
    B, R, _ = lo.shape
    spec = _col_spec(R)
    out_shape = jax.ShapeDtypeStruct((B, R, _LANE), jnp.uint32)
    return pl.pallas_call(
        partial(_fwd_kernel, ctx),
        grid=(B,),
        out_shape=[out_shape, out_shape],
        in_specs=_tw_specs(ctx) + [spec, spec],
        out_specs=[spec, spec],
        interpret=interpret,
        compiler_params=None if interpret else _COMPILER_PARAMS,
    )(*ctx.fwd_tw[0], *ctx.fwd_tw[1], lo, hi)


@partial(jax.jit, static_argnums=(1, 2))
def _ifft_planes(planes, log_n: int, interpret: bool):
    ctx = get_pallas_ctx(log_n)
    lo, hi = planes
    B, R, _ = lo.shape
    spec = _col_spec(R)
    out_shape = jax.ShapeDtypeStruct((B, R, _LANE), jnp.uint32)
    return pl.pallas_call(
        partial(_inv_kernel, ctx),
        grid=(B,),
        out_shape=[out_shape, out_shape],
        in_specs=_itw_specs(ctx) + [spec, spec],
        out_specs=[spec, spec],
        interpret=interpret,
        compiler_params=None if interpret else _COMPILER_PARAMS,
    )(*ctx.inv_tw[0], *ctx.inv_tw[1], lo, hi)


@partial(jax.jit, static_argnums=(2, 3))
def _lde_planes(coeff_planes, scale_planes, log_n: int, interpret: bool):
    """coeffs (B, R, 128) x scale (L, R, 128) -> (B, L, R, 128) planes."""
    ctx = get_pallas_ctx(log_n)
    clo, chi = coeff_planes
    slo, shi = scale_planes
    B, R, _ = clo.shape
    L = slo.shape[0]
    cspec = pl.BlockSpec(
        (1, R, _LANE), imap32(lambda b, l: (b, 0, 0)), memory_space=pltpu.VMEM
    )
    sspec = pl.BlockSpec(
        (1, R, _LANE), imap32(lambda b, l: (l, 0, 0)), memory_space=pltpu.VMEM
    )
    ospec = pl.BlockSpec(
        (1, 1, R, _LANE),
        imap32(lambda b, l: (b, l, 0, 0)),
        memory_space=pltpu.VMEM,
    )
    out_shape = jax.ShapeDtypeStruct((B, L, R, _LANE), jnp.uint32)
    return pl.pallas_call(
        partial(_lde_kernel, ctx),
        grid=(B, L),
        out_shape=[out_shape, out_shape],
        in_specs=_tw_specs(ctx) + [sspec, sspec, cspec, cspec],
        out_specs=[ospec, ospec],
        interpret=interpret,
        compiler_params=None if interpret else _COMPILER_PARAMS,
    )(*ctx.fwd_tw[0], *ctx.fwd_tw[1], slo, shi, clo, chi)


def _lde_kernel(ctx, trl, trh, tll, tlh, sl, sh, xl, xh, ol, oh):
    x = limbs.mul((xl[0], xh[0]), (sl[0], sh[0]))
    x = _fwd_stages_body(ctx, x, (trl[:], trh[:]), (tll[:], tlh[:]))
    ol[0, 0] = x[0]
    oh[0, 0] = x[1]


# ---------------------------------------------------------------------------
# Public entry points (uint64 in / uint64 out)
# ---------------------------------------------------------------------------

MIN_LOG_N = 11  # below this the XLA path's dispatch cost is negligible
MAX_LOG_N = 16  # above this one column's stage chain exceeds VMEM (2^17
# forward compiles but the inverse body's extra temporaries OOM the 100 MiB
# scoped budget; >=2^17 sizes go through the XLA path until the two-level
# decomposition lands)

# The unrolled stage chain keeps several live column copies; the default
# 16 MiB scoped-vmem budget is too tight for 2^16+ columns (v5e has 128 MiB
# physical VMEM — raise the cap rather than splitting the kernel).
_COMPILER_PARAMS = pltpu.CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)


def size_fits(n: int) -> bool:
    return (1 << MIN_LOG_N) <= n <= (1 << MAX_LOG_N)


def _to_planes(a: jax.Array):
    """(..., n) u64 -> ((B, R, 128) lo, hi), remembering the lead shape."""
    lead = a.shape[:-1]
    n = a.shape[-1]
    flat = a.reshape(-1, n // _LANE, _LANE)
    return limbs.split(flat), lead


def _from_planes(planes, lead, n):
    return limbs.join(planes).reshape(lead + (n,))


def fft_natural_to_bitreversed(a: jax.Array, interpret: bool = False):
    n = a.shape[-1]
    log_n = n.bit_length() - 1
    planes, lead = _to_planes(a)
    out = _fft_planes(planes, log_n, interpret)
    return _from_planes(out, lead, n)


def ifft_bitreversed_to_natural(a: jax.Array, interpret: bool = False):
    n = a.shape[-1]
    log_n = n.bit_length() - 1
    planes, lead = _to_planes(a)
    out = _ifft_planes(planes, log_n, interpret)
    return _from_planes(out, lead, n)


def lde_from_monomial(
    coeffs: jax.Array, scale: jax.Array, interpret: bool = False
):
    """coeffs (..., n), scale (lde, n) -> (..., lde, n); fused scale+NTT."""
    n = coeffs.shape[-1]
    log_n = n.bit_length() - 1
    lde = scale.shape[0]
    planes, lead = _to_planes(coeffs)
    s_planes = limbs.split(scale.reshape(lde, n // _LANE, _LANE))
    out = _lde_planes(planes, s_planes, log_n, interpret)
    return limbs.join(out).reshape(lead + (lde, n))
