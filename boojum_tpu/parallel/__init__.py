from .sharding import (
    make_mesh,
    sharded_prove_fragment,
    col_sharding,
    leaf_sharding,
)
