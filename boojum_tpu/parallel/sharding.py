"""Multi-chip sharding of the proving pipeline over a jax device mesh.

The reference is single-node rayon data parallelism (SURVEY.md §2.4;
`/root/reference/src/worker/mod.rs:5`). The TPU-native scaling axes are:

- ``col``  — trace columns. Through round 3 every polynomial op (iNTT, coset
  LDE, gate sweep) is per-column, so columns shard across chips with ZERO
  communication; this is the tensor-parallel analogue.
- ``row``  — the LDE domain. Merkle leaf hashing consumes ALL columns of one
  domain row, so between the per-column NTT phase and the hashing phase the
  layout pivots from column-sharded to row-sharded — one all-to-all that XLA
  inserts from sharding constraints (the framework never writes a collective
  by hand; GSPMD propagates them over ICI).

Merkle caps, transcript inputs and FRI final polys are tiny and replicated.
"""

from __future__ import annotations

import logging
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..field import gl
from ..field import goldilocks as gf
from ..field import extension as ext_f
# the explicitly-XLA sponge entry points: this module's arrays carry
# NamedShardings for GSPMD to partition, which pallas_call cannot split
from ..hashes.poseidon2 import leaf_hash_xla as leaf_hash
from ..hashes.poseidon2 import node_hash_xla as node_hash
from ..ntt import lde_from_monomial, monomial_from_values, powers_device


_ACTIVE_MESH: list = [None]


def active_mesh() -> Mesh | None:
    """The mesh the prover is currently sharding over (None = single chip)."""
    return _ACTIVE_MESH[0]


def mesh_mode() -> str | None:
    """How the active mesh executes: None (no mesh), "shard_map" (each chip
    runs the native kernels on its local shard, collectives written
    explicitly — parallel/shard_sweep.py), or "gspmd" (the legacy implicit
    path: NamedSharding constraints, XLA inserts the collectives).

    BOOJUM_TPU_MESH_MODE=shard_map|gspmd forces a mode. Unset defaults to
    shard_map on EVERY topology, including multi-process (DCN-spanning)
    meshes under jax.distributed: the explicit collectives ride the same
    all_gather/all_to_all primitives across hosts, the de-mesh fallbacks
    are addressable-safe (shard_sweep.demesh gathers non-addressable
    arrays per host), and the cross-host byte bill lands in the dcn.*
    gauges. gspmd remains the forced legacy escape hatch."""
    m = active_mesh()
    if m is None:
        return None
    v = os.environ.get("BOOJUM_TPU_MESH_MODE", "").strip().lower()
    if v in ("shard_map", "sm"):
        return "shard_map"
    if v == "gspmd":
        return "gspmd"
    if v:
        raise ValueError(
            f"BOOJUM_TPU_MESH_MODE={v!r}: use shard_map or gspmd"
        )
    return "shard_map"


def shard_map_mesh() -> Mesh | None:
    """The active mesh when it executes via shard_map, else None — the
    single dispatch predicate the prover/fri/streaming kernels key on."""
    return active_mesh() if mesh_mode() == "shard_map" else None


class prover_mesh:
    """Context manager activating a device mesh for a full `prove()` run.

    Inside the context the prover device-puts its polynomial-batch inputs
    column-sharded and pivots Merkle leaves to row sharding; every jitted
    stage then auto-partitions from its operand shardings (GSPMD inserts
    the collectives). All field ops are exact integer ops with a fixed
    reduction structure, so the sharded proof is byte-identical to the
    single-device proof.
    """

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def __enter__(self):
        self._prev = _ACTIVE_MESH[0]
        _ACTIVE_MESH[0] = self.mesh
        return self.mesh

    def __exit__(self, *exc):
        _ACTIVE_MESH[0] = self._prev
        return False


_SHARD_COLS_WARNED: set = set()


def _note_shard_axis(axis: str, shape, ncol: int):
    """Audit trail for shard_cols' divisibility fallback: the chosen axis
    lands on the current flight-recorder span as an attribute, and every
    fallback away from 'col' logs ONE warning per (shape, mesh) so mesh
    runs silently sharding the wrong axis become visible."""
    from ..utils.spans import span_attr

    span_attr("shard_cols_axis", axis)
    if axis == "col":
        return
    key = (axis, tuple(shape), ncol)
    if key in _SHARD_COLS_WARNED:
        return
    _SHARD_COLS_WARNED.add(key)
    logging.getLogger("boojum_tpu").warning(
        "shard_cols: batch axis %s does not divide the %d-way 'col' mesh "
        "axis; sharding %s instead",
        shape,
        ncol,
        "the domain axis" if axis.startswith("domain") else "nothing",
    )


def shard_cols(arr):
    """Column-shard a (C, ...) polynomial batch over the active mesh (no-op
    when no mesh is active). Column counts are arbitrary (e.g. 15 oracle
    columns over a 4-way axis), and NamedSharding demands divisibility, so
    when 'col' does not divide the batch axis the (power-of-two) domain axis
    is sharded instead — the row axis always divides it. Fallbacks are
    logged once and recorded as a span attribute (_note_shard_axis)."""
    m = active_mesh()
    if m is None:
        return arr
    ncol, nrow = m.shape["col"], m.shape["row"]
    nd = arr.ndim
    if arr.shape[0] % ncol == 0:
        spec = P("col", *([None] * (nd - 1)))
        _note_shard_axis("col", arr.shape, ncol)
    elif arr.shape[-1] % (ncol * nrow) == 0:
        spec = P(*([None] * (nd - 1)), ("col", "row"))
        _note_shard_axis("domain(col,row)", arr.shape, ncol)
    elif arr.shape[-1] % nrow == 0:
        spec = P(*([None] * (nd - 1)), "row")
        _note_shard_axis("domain(row)", arr.shape, ncol)
    else:
        _note_shard_axis("none", arr.shape, ncol)
        return arr
    return jax.device_put(arr, NamedSharding(m, spec))


def shard_leaves(arr):
    """Row-shard a (num_leaves, width) leaf batch over BOTH mesh axes (the
    col->row layout pivot before Merkle leaf hashing). Falls back to the
    largest mesh axis dividing the (power-of-two) leaf count on non-pow2
    meshes, and to no sharding when nothing divides."""
    m = active_mesh()
    if m is None:
        return arr
    n = arr.shape[0]
    ncol, nrow = m.shape["col"], m.shape["row"]
    if n % (ncol * nrow) == 0:
        axes = ("col", "row")
    elif n % ncol == 0:
        axes = ("col",)
    elif n % nrow == 0:
        axes = ("row",)
    else:
        return arr
    spec = P(axes, *([None] * (arr.ndim - 1)))
    return jax.device_put(arr, NamedSharding(m, spec))


def default_col_axis(n: int) -> int:
    """Favor the column axis (columns carry the zero-communication phase):
    the largest power of two <= sqrt-ish of the device count dividing it."""
    col_axis = 1 << (n.bit_length() // 2)
    while n % col_axis:
        col_axis //= 2
    return col_axis


def make_mesh(devices=None, col_axis: int | None = None) -> Mesh:
    """2D ('col', 'row') mesh over the given (or all) devices.

    col_axis devices shard trace columns; the rest shard LDE-domain rows.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if col_axis is None:
        col_axis = default_col_axis(n)
    row_axis = n // col_axis
    dev_grid = np.array(devices).reshape(col_axis, row_axis)
    return Mesh(dev_grid, axis_names=("col", "row"))


def col_sharding(mesh: Mesh) -> NamedSharding:
    """(C, n) polynomial storage: columns across 'col', rows replicated."""
    return NamedSharding(mesh, P("col", None))


def leaf_sharding(mesh: Mesh) -> NamedSharding:
    """(num_leaves, width) leaf storage: leaves across BOTH mesh axes."""
    return NamedSharding(mesh, P(("col", "row"), None))


def _num_den_products(copy_vals, sigma_vals, non_residues, beta, gamma):
    """Copy-permutation numerator/denominator column products (column axis
    collapses via a log tree of ext muls; with a column-sharded operand, XLA
    turns the tree into a psum-style reduction over ICI)."""
    C, n = copy_vals.shape
    omega = gl.omega(n.bit_length() - 1)
    xs = powers_device(omega, n)
    b0, b1 = beta[0], beta[1]
    g0, g1 = gamma[0], gamma[1]
    ks = non_residues
    kx = gf.mul(xs[None, :], ks[:, None])  # (C, n)
    num = (
        gf.add(gf.add(copy_vals, gf.mul(kx, b0)), g0),
        gf.add(gf.mul(kx, b1), g1),
    )
    den = (
        gf.add(gf.add(copy_vals, gf.mul(sigma_vals, b0)), g0),
        gf.add(gf.mul(sigma_vals, b1), g1),
    )

    def tree_prod(pair):
        c0, c1 = pair
        while c0.shape[0] > 1:
            if c0.shape[0] % 2:
                c0 = jnp.concatenate([c0, jnp.ones((1, c0.shape[1]), jnp.uint64)])
                c1 = jnp.concatenate([c1, jnp.zeros((1, c1.shape[1]), jnp.uint64)])
            h = c0.shape[0] // 2
            c0, c1 = ext_f.mul((c0[:h], c1[:h]), (c0[h:], c1[h:]))
        return c0[0], c1[0]

    return tree_prod(num), tree_prod(den)


def _z_from_ratio(ratio):
    """Exclusive prefix product of the per-row ratio (shared log-doubling
    scan — see prover.stages._ext_prefix_prod)."""
    from ..prover.stages import _ext_prefix_prod

    incl = _ext_prefix_prod(ratio)
    one = jnp.ones((1,), jnp.uint64)
    zero = jnp.zeros((1,), jnp.uint64)
    return (
        jnp.concatenate([one, incl[0][:-1]]),
        jnp.concatenate([zero, incl[1][:-1]]),
    )


def _commit_fragment(copy_vals, lde_factor, cap_size, mesh):
    """Per-column iNTT -> coset LDE -> Merkle digest layers with the
    col->row layout pivot."""
    from ..utils.pallas_util import force_xla

    C, n = copy_vals.shape
    with force_xla():
        mono = monomial_from_values(copy_vals)  # column-sharded, no comm
        lde = lde_from_monomial(mono, lde_factor)  # (C, L, n) per-column
    leaves = lde.reshape(C, -1).T  # (L*n, C): the layout pivot
    leaves = jax.lax.with_sharding_constraint(leaves, leaf_sharding(mesh))
    digests = leaf_hash(leaves)  # (L*n, 4) row-sharded
    while digests.shape[0] > cap_size:
        digests = node_hash(digests[0::2], digests[1::2])
    return jax.lax.with_sharding_constraint(
        digests, NamedSharding(mesh, P(None, None))
    )


def _prove_fragment(copy_vals, sigma_vals, non_residues, beta, gamma,
                    lde_factor, cap_size, mesh):
    """Single-graph form of the rounds-1+2 core (used by the driver's
    single-chip COMPILE check; execution goes through the sequenced phases
    of sharded_prove_fragment)."""
    cap = _commit_fragment(copy_vals, lde_factor, cap_size, mesh)
    num_p, den_p = _num_den_products(
        copy_vals, sigma_vals, non_residues, beta, gamma
    )
    ratio = ext_f.mul(num_p, ext_f.batch_inverse(den_p))
    z = _z_from_ratio(ratio)
    return cap, z


def sharded_prove_fragment(mesh: Mesh, lde_factor: int = 4, cap_size: int = 4):
    """The prove fragment over `mesh`, as a SEQUENCE of jitted phases.

    Inputs: copy_vals/sigma_vals (C, n) uint64; non_residues (C,) uint64;
    beta/gamma (2,) uint64 extension scalars.

    Phased rather than one fused jit for two reasons: the extension-field
    batch inversion must sit at a top-level jit boundary (XLA:CPU has
    produced never-terminating executables when its inversion chain is
    inlined into large modules — see prover/stages.py), and each phase's
    GSPMD partitioning stays small and predictable.
    """
    cs = col_sharding(mesh)
    rep = NamedSharding(mesh, P())

    commit = jax.jit(
        lambda cv: _commit_fragment(cv, lde_factor, cap_size, mesh),
        in_shardings=(cs,),
    )
    numden = jax.jit(
        _num_den_products, in_shardings=(cs, cs, rep, rep, rep)
    )
    ratio_z = jax.jit(
        lambda num_p, den_inv: _z_from_ratio(ext_f.mul(num_p, den_inv))
    )

    def run(copy_vals, sigma_vals, non_residues, beta, gamma):
        cap = commit(copy_vals)
        num_p, den_p = numden(copy_vals, sigma_vals, non_residues, beta, gamma)
        den_inv = ext_f.batch_inverse(den_p)
        return cap, ratio_z(num_p, den_inv)

    return run


def host_np(x):
    """np.asarray that also works for MULTI-PROCESS global arrays: a
    sharded jax.Array spanning non-addressable devices cannot be fetched
    directly (jax raises), so gather it to every host first. Single-process
    (and plain numpy/host values) pass straight through.

    Delegates to utils.transfer.to_host — the pipeline's single blocking
    d2h seam, where the flight recorder's d2h byte counter and the
    `host.blocking_syncs` tick live (no-ops without a metrics registry).
    Batched/prefetched pulls go through transfer.start_fetch instead."""
    from ..utils.transfer import to_host

    return to_host(x)
