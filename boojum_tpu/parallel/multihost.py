"""Multi-host (DCN) scaling for the prover.

The reference is strictly single-node (SURVEY.md §2.4: rayon + atomics, no
MPI/NCCL); this module is the part of the distributed backend the reference
never had. Design, by communication budget:

- **ICI (intra-host)**: the `('col', 'row')` mesh from `sharding.make_mesh`
  — columns shard the zero-communication polynomial phases, the Merkle leaf
  pivot is one all-to-all. GSPMD inserts collectives from shardings; all of
  them ride ICI.
- **DCN (cross-host)**: two supported modes, picked by workload shape:

  1. **Proof-parallel** (`distribute_proofs`): each host proves whole
     circuits from a shared queue. ZK proving fleets are embarrassingly
     parallel across proofs (zkSync-style provers scale exactly this way),
     so this is the default: zero DCN traffic during proving, results are
     independent proofs.
  2. **Trace-sharded** (`hybrid_mesh`): one proof whose trace exceeds a
     host's HBM shards columns ACROSS hosts: the mesh's 'col' axis spans
     (dcn x ici) so each host holds a column slice, per-column NTT/LDE/
     sweep phases still run with zero cross-host traffic, and only the
     leaf-pivot all-to-all and the (tiny, replicated) caps/challenges
     cross DCN — one bulk collective per commit, the minimum any
     single-proof distribution can pay. Cross-host FRI folds stay local
     because fold pairs are adjacent in the bit-reversed layout (the
     domain axis is never sharded across DCN).

`prove(assembly, setup, config, mesh=hybrid_mesh(...))` then works
unchanged: the prover's sharding constraints are mesh-shape-agnostic.

Initialization follows the standard jax.distributed recipe; on a
single-process run every helper degrades to the local-mesh behavior so the
same driver script runs on a laptop, one TPU host, or a DCN-connected pod
slice. (This host only has one process — multi-process behavior exercises
the same code paths jax uses for any GSPMD program, which is what the
single-host mesh tests pin down; see tests/test_multihost.py.)
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from .sharding import make_mesh


def _multihost_env_detected() -> bool:
    """True when the environment advertises a multi-host launch (TPU pod /
    cluster launcher env vars jax.distributed auto-detects from) — a failed
    bring-up in such an environment must raise, not degrade silently."""
    import os

    for var in (
        "MEGASCALE_COORDINATOR_ADDRESS",
        "TPU_WORKER_HOSTNAMES",
        "JAX_COORDINATOR_ADDRESS",
        "COORDINATOR_ADDRESS",
        "SLURM_JOB_NUM_NODES",
        "OMPI_COMM_WORLD_SIZE",
    ):
        v = os.environ.get(var, "")
        if var in ("SLURM_JOB_NUM_NODES", "OMPI_COMM_WORLD_SIZE"):
            if v.isdigit() and int(v) > 1:
                return True
        elif var == "TPU_WORKER_HOSTNAMES":
            # a single hostname (e.g. 'localhost' from single-host TPU
            # plumbing) is not a multi-host launch
            if len([h for h in v.split(",") if h.strip()]) > 1:
                return True
        elif v:
            return True
    return False


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Bring up jax.distributed when running under a multi-process launcher.

    Returns True when a multi-process runtime is (already) active. On TPU
    pods the three arguments auto-detect from the environment; passing them
    explicitly supports CPU/GPU clusters (reference: jax.distributed docs).
    A second call is a no-op (jax.distributed tolerates re-init only via
    its own error, which we swallow to keep driver scripts idempotent)."""
    # Detect an already-initialized distributed runtime WITHOUT touching
    # jax.process_count(): that would initialize the local backend, after
    # which jax.distributed.initialize() hard-fails ("must be called before
    # any JAX computations").
    try:
        from jax._src import distributed as _dist

        if _dist.global_state.client is not None:
            _enable_cpu_collectives()
            return jax.process_count() > 1
    except Exception:
        pass
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (RuntimeError, ValueError) as e:
        if coordinator_address is not None:
            # an explicitly configured cluster that fails to come up must
            # NOT silently degrade to N duplicate single-process runs
            raise
        msg = str(e).lower()
        if "already" in msg and "initial" in msg:
            pass  # idempotent re-init: fine
        elif jax.process_count() > 1:
            pass  # runtime is up despite the error
        elif num_processes not in (None, 1) or _multihost_env_detected():
            # a configured OR auto-detected pod bring-up that FAILED must
            # surface, not degrade every host to a duplicate run
            raise
        # else: genuine single-process run without a coordinator
    _enable_cpu_collectives()
    return jax.process_count() > 1


def _enable_cpu_collectives() -> None:
    """Select the gloo TCP collectives backend for XLA:CPU when a
    distributed runtime is up. XLA:CPU ships with NO cross-process
    collectives by default, so every multiprocess CPU computation — the
    prover's cross-host shard_map/GSPMD graphs, and even device_put onto
    a process-spanning NamedSharding (its value-equality check compiles
    a global psum) — dies with "Multiprocess computations aren't
    implemented on the CPU backend". Must run BEFORE the backend
    initializes (the flag is read at CPU client creation); on TPU the
    flag only affects the auxiliary CPU client, so it is safe to set
    whenever the distributed client exists."""
    try:
        from jax._src import distributed as _dist

        if _dist.global_state.client is None:
            return
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass


def hybrid_mesh(col_axis_per_host: int | None = None) -> Mesh:
    """('col', 'row') mesh whose 'col' axis spans hosts (DCN) x local chips.

    Layout: devices grid-shaped (num_hosts * local_col, local_row) with the
    host (DCN) dimension OUTERMOST in 'col' — trace columns split across
    hosts first, so each host owns a contiguous column slice and every
    per-column phase is host-local. 'row' stays within a host (the leaf
    pivot's all-to-all then has one DCN hop on the column axis only).

    Single-process: identical to make_mesh(all local devices)."""
    if jax.process_count() <= 1:
        return make_mesh(jax.devices(), col_axis=col_axis_per_host)

    from .sharding import default_col_axis

    per_host = jax.local_device_count()
    hosts = jax.process_count()
    if col_axis_per_host is None:
        col_axis_per_host = default_col_axis(per_host)
    row_axis = per_host // col_axis_per_host
    # jax.devices() is globally ordered process-major: reshaping
    # (hosts * local_col, local_row) keeps each host's devices contiguous
    # along 'col'. That ordering is a platform contract, not a law — build
    # from an explicit (process_index, id) sort and VERIFY the host-local
    # column-slice invariant rather than assuming it.
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    grid = np.array(devs).reshape(hosts * col_axis_per_host, row_axis)
    for h in range(hosts):
        block = grid[h * col_axis_per_host : (h + 1) * col_axis_per_host]
        owners = {d.process_index for d in block.ravel()}
        if len(owners) != 1:
            raise RuntimeError(
                "hybrid_mesh: device grid is not host-contiguous along "
                f"'col' (host block {h} spans processes {sorted(owners)}); "
                "per-column phases would cross DCN"
            )
    return Mesh(grid, axis_names=("col", "row"))


def mesh_process_topology(mesh: Mesh) -> dict:
    """Per-process device census of a mesh — the mesh-axis -> process-
    topology mapping the DCN/ICI gauge split is computed from.

    Returns {"devices": D, "processes": P, "local_devices": {pid: d_p}}
    where d_p counts the mesh devices owned by process pid. Works on any
    topology (a single-process mesh reports P == 1)."""
    devs = list(np.asarray(mesh.devices).ravel())
    counts: dict[int, int] = {}
    for d in devs:
        pid = int(getattr(d, "process_index", 0))
        counts[pid] = counts.get(pid, 0) + 1
    return {
        "devices": len(devs),
        "processes": len(counts),
        "local_devices": counts,
    }


def dcn_fraction(mesh: Mesh) -> float:
    """Fraction of a uniform collective's CROSSING bytes that cross the
    process (DCN) boundary on this mesh; 0.0 on a single-process mesh.

    For a D-device mesh split d_p devices per process, a uniform
    all-to-all / all-gather moves each shard to every OTHER device with
    equal weight, so of the D*(D-1) ordered (src, dst) device pairs the
    cross-process ones number D^2 - sum_p d_p^2. The fraction

        (D^2 - sum_p d_p^2) / (D^2 - D)

    is therefore the same for both collective shapes — callers split the
    crossing-byte bill into intra-host ICI and cross-host DCN portions
    with one number per mesh."""
    topo = mesh_process_topology(mesh)
    d = topo["devices"]
    if d <= 1 or topo["processes"] <= 1:
        return 0.0
    sq = sum(c * c for c in topo["local_devices"].values())
    return float(d * d - sq) / float(d * (d - 1))


def distribute_proofs(jobs, prove_fn, process_id: int | None = None,
                      process_count: int | None = None):
    """Round-robin whole proving jobs across hosts (proof-parallel mode).

    jobs: a sequence; prove_fn(job) -> proof. Each process proves the slice
    `jobs[pid::count]` on its local devices and returns
    [(index, proof), ...] for its share — collecting across hosts is the
    caller's transport concern (file system, RPC), matching how proving
    fleets shard work without any device-level communication."""
    pid = jax.process_index() if process_id is None else process_id
    count = jax.process_count() if process_count is None else process_count
    out = []
    for i in range(pid, len(jobs), count):
        out.append((i, prove_fn(jobs[i])))
    return out
