"""shard_map mesh execution: native per-chip kernels + explicit collectives.

The GSPMD mesh path (parallel/sharding.py) lets XLA insert every collective
from NamedSharding constraints — which is exactly why it cannot run the
fused Pallas kernels: GSPMD cannot partition a `pallas_call`, so the
multi-chip prover fell back to the slowest u64-emulated XLA bodies right
where the FLOPs are (ISSUE 5). This module is the explicit counterpart:

- every heavy kernel — the per-column iNTT/LDE, the fused coset-sweep
  terms kernel, the limb FRI fold, the Poseidon2 leaf sponge — runs inside
  `jax.experimental.shard_map` over the ('col','row') mesh, so each chip
  traces the kernel at its LOCAL block shape and Pallas never sees a
  sharded operand;
- the col->row Merkle layout pivot is ONE hand-written `lax.all_to_all`
  on the rate-L column blocks (DIZK's lesson: the distributed prover lives
  or dies on how this pivot is orchestrated), and replicated outputs (caps,
  gathered node layers) are ONE explicit `lax.all_gather` — both charged
  to `ici.*` gauges so the interconnect bill is a first-class metric;
- digests, checkpoints and proof bytes are bit-identical to the
  single-chip path: the per-chip kernels are the same exact-integer field
  ops over a partition of the data, and the collectives only move bytes.

Column batches whose count does not divide the device count are zero-padded
to a multiple (padding columns iNTT/LDE to zeros and are sliced off after
the pivot, BEFORE any sponge absorb — so hashing sees exactly the real
columns, in order). All wrappers are lru-cached per (mesh, static shape)
and jitted, so new challenges/proofs never retrace.
"""

from __future__ import annotations

import time
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..field import goldilocks as gf
from ..utils import metrics as _metrics
from ..utils.pallas_util import local_operands

_AXES = ("col", "row")


def mesh_devices(mesh: Mesh) -> int:
    return int(mesh.shape["col"] * mesh.shape["row"])


def mesh_from_shape(shape) -> Mesh:
    """A ('col','row') mesh of the given (ncol, nrow) shape over the first
    ncol*nrow local devices — precompile.enumerate_kernels(mesh_shape=...)
    uses this to enumerate the `_sm` kernel variants for a target mesh
    without one being active (e.g. on the forced-8-device CPU in tier-1)."""
    ncol, nrow = int(shape[0]), int(shape[1])
    devs = jax.devices()
    if len(devs) < ncol * nrow:
        raise ValueError(
            f"mesh shape {shape} needs {ncol * nrow} devices, "
            f"have {len(devs)}"
        )
    grid = np.array(devs[: ncol * nrow]).reshape(ncol, nrow)
    return Mesh(grid, axis_names=_AXES)


def _interp() -> bool:
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


# ---------------------------------------------------------------------------
# ICI accounting — the explicit collectives' byte/time bill
# ---------------------------------------------------------------------------


@lru_cache(maxsize=None)
def _dcn_frac(mesh: Mesh) -> float:
    """Cross-process fraction of this mesh's crossing bytes (cached per
    mesh; 0.0 on any single-process topology)."""
    from .multihost import dcn_fraction

    return dcn_fraction(mesh)


def _ici_all_to_all(nbytes_global: int, mesh: Mesh):
    """Tally one all-to-all layout pivot: (D-1)/D of the global payload
    crosses the interconnect (each chip keeps its own 1/D slice). On a
    multi-host mesh the crossing bytes split intra-host (ici.*) vs
    cross-process (dcn.*) by the mesh's process topology."""
    D = mesh_devices(mesh)
    crossing = nbytes_global * (D - 1) / max(D, 1)
    f = _dcn_frac(mesh)
    _metrics.count_ici_all_to_all(crossing * (1.0 - f), crossing * f)


def _ici_all_gather(nbytes_global: int, mesh: Mesh):
    """Tally one all-gather to replicated: every chip receives the
    (D-1)/D it does not hold — D*(D-1)/D = (D-1) payloads total. Same
    ici/dcn split as the pivot (the crossing fraction is topology-
    identical for both collective shapes)."""
    D = mesh_devices(mesh)
    crossing = nbytes_global * (D - 1)
    f = _dcn_frac(mesh)
    _metrics.count_ici_all_gather(crossing * (1.0 - f), crossing * f)


class _pivot_timer:
    """Wall-clock window of a pivot-containing dispatch, accumulated into
    the `ici.pivot_s` gauge. This measures the host-side dispatch window
    (the device work is async), which is what the overlapped pipeline can
    actually lose to a pivot; device-side collective time shows up in the
    stage spans as usual."""

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        _metrics.gauge_add("ici.pivot_s", time.perf_counter() - self._t0)
        return False


# ---------------------------------------------------------------------------
# Padding + sharding of column batches
# ---------------------------------------------------------------------------


def padded_cols(B: int, D: int) -> int:
    return -(-B // D) * D


def pad_cols_sharded(arr, mesh: Mesh):
    """Zero-pad a (B, ...) column batch to a multiple of the device count
    and lay it out column-sharded over BOTH mesh axes (each chip holds a
    contiguous stripe of columns — the layout every per-column shard_map
    kernel here consumes)."""
    D = mesh_devices(mesh)
    B = int(arr.shape[0])
    Bp = padded_cols(B, D)
    if Bp != B:
        pad = jnp.zeros((Bp - B,) + tuple(arr.shape[1:]), arr.dtype)
        arr = jnp.concatenate([arr, pad], axis=0)
    spec = P(_AXES, *([None] * (arr.ndim - 1)))
    return jax.device_put(arr, NamedSharding(mesh, spec))


# ---------------------------------------------------------------------------
# Commit pipeline: iNTT -> LDE -> all_to_all pivot -> local leaf sponge
# ---------------------------------------------------------------------------


@lru_cache(maxsize=None)
def _mono_fn(mesh: Mesh):
    """Per-chip inverse NTT over the local column stripe (values over H ->
    monomials). No communication: columns are independent."""
    from ..ntt import monomial_from_values

    def body(vals):
        # local_operands: the block is per-chip, so the NTT dispatcher may
        # keep its MXU kernel despite the active mesh (same in every
        # shard_map body below)
        with local_operands():
            return monomial_from_values(vals)

    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(P(_AXES, None),),
            out_specs=P(_AXES, None), check_rep=False,
        )
    )


def leaf_limb_ok(width: int, rows_local: int) -> bool:
    """Whether the fused Poseidon2 limb sponge can take a local
    (rows_local, width) leaf block: 128-lane row tiling and the kernel's
    VMEM width cap (hashes/poseidon2.leaf_hash mirrors the cap)."""
    from ..prover.pallas_sweep import limb_sweep_enabled

    return (
        limb_sweep_enabled()
        and rows_local % 128 == 0
        and rows_local > 0
        and width <= 1024
    )


@lru_cache(maxsize=None)
def _lde_pivot_leaf_fn(mesh: Mesh, L: int, B_real: int, use_limb: bool):
    """Rate-L LDE of the local monomial stripe, the explicit col->row
    all_to_all pivot, and the per-chip leaf sponge — one shard_map graph.

    Returns (lde (Bp, L, n) column-sharded, digests (N, 4) row-sharded).
    Padding columns pivot along with the real ones and are sliced off
    BEFORE the sponge (absorption sees exactly the committed columns)."""
    from ..hashes.poseidon2 import leaf_hash_xla
    from ..ntt import lde_from_monomial

    interp = _interp()

    def body(mono_blk):
        b = mono_blk.shape[0]
        with local_operands():
            lde = lde_from_monomial(mono_blk, L)  # (b, L, n) local
        flat = lde.reshape(b, -1)
        # THE layout pivot: split the full domain D ways, concat the
        # column stripes received from every chip — (Bp, N/D) local
        piv = jax.lax.all_to_all(
            flat, _AXES, split_axis=1, concat_axis=0, tiled=True
        )
        leaves = piv.T[:, :B_real]  # (N/D, B): rows of real columns
        if use_limb:
            from ..hashes import pallas_poseidon2 as pp2

            dig = pp2.sponge_hash(leaves, interpret=interp)
        else:
            dig = leaf_hash_xla(leaves)
        return lde, dig

    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(P(_AXES, None),),
            out_specs=(P(_AXES, None, None), P(_AXES, None)),
            check_rep=False,
        )
    )


@lru_cache(maxsize=None)
def _lde_pivot_cols_fn(mesh: Mesh, L: int, b_real: int):
    """Streamed-commit block pivot: local LDE of one column block, the
    explicit all_to_all, and the transpose to this chip's row range —
    (N, b_real) row-sharded leaf columns ready for the carried sponge."""
    from ..ntt import lde_from_monomial

    def body(mono_blk):
        b = mono_blk.shape[0]
        with local_operands():
            lde = lde_from_monomial(mono_blk, L)
        flat = lde.reshape(b, -1)
        piv = jax.lax.all_to_all(
            flat, _AXES, split_axis=1, concat_axis=0, tiled=True
        )
        return piv.T[:, :b_real]

    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(P(_AXES, None),),
            out_specs=P(_AXES, None), check_rep=False,
        )
    )


@lru_cache(maxsize=None)
def _node_step_fn(mesh: Mesh):
    """One Merkle node layer, per chip: adjacent digest pairs are local as
    long as the local row count is even (the caller guarantees it). The
    `node_hash` dispatcher picks the Pallas sponge on TPU — shard_map
    hands it the LOCAL block, so unlike the GSPMD path the kernel is
    never lost to the partitioner."""
    from ..hashes.poseidon2 import node_hash

    def body(d):
        with local_operands():
            return node_hash(d[0::2], d[1::2])

    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(P(_AXES, None),),
            out_specs=P(_AXES, None), check_rep=False,
        )
    )


@lru_cache(maxsize=None)
def _all_gather_fn(mesh: Mesh, ndim: int):
    """Explicit all_gather of a leading-axis-sharded array to replicated
    (caps / small node layers / transcript inputs)."""

    def body(x):
        return jax.lax.all_gather(x, _AXES, axis=0, tiled=True)

    spec_in = P(_AXES, *([None] * (ndim - 1)))
    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(spec_in,),
            out_specs=P(*([None] * ndim)), check_rep=False,
        )
    )


def all_gather_replicated(arr, mesh: Mesh):
    out = _all_gather_fn(mesh, arr.ndim)(arr)
    _ici_all_gather(int(arr.size) * arr.dtype.itemsize, mesh)
    return out


# node counts at or below this finish replicated in one fused graph (the
# same latency-vs-size trade as merkle._FUSE_THRESHOLD)
_SM_GATHER_THRESHOLD = 1 << 12


def node_plan(n_leaves: int, cap_size: int, D: int):
    """(per-chip node-step input sizes, all_gather input size | None) for
    a mesh Merkle tree of `n_leaves` digests: 2-to-1 layers run per chip
    while pairs stay shard-local and the count is worth sharding, the
    remainder gathers and finishes replicated. Shared by node_layers_sm
    and precompile.enumerate_kernels so the enumerated `_sm` set cannot
    drift from the dispatched one."""
    steps = []
    cur = n_leaves
    while (
        cur > cap_size
        and cur > _SM_GATHER_THRESHOLD
        and cur // 2 >= D
        and (cur // D) % 2 == 0
    ):
        steps.append(cur)
        cur //= 2
    return steps, (cur if cur > cap_size else None)


def node_layers_sm(digests, cap_size: int, mesh: Mesh):
    """All Merkle node layers from row-sharded leaf digests: per-chip
    2-to-1 hashing while pairs stay shard-local, then ONE explicit
    all_gather and the fused replicated tail. Layer values (and count)
    are identical to merkle._node_layers."""
    from ..merkle import _tree_tail_layers

    steps, gather = node_plan(
        int(digests.shape[0]), cap_size, mesh_devices(mesh)
    )
    layers = [digests]
    cur = digests
    for _ in steps:
        cur = _node_step_fn(mesh)(cur)
        layers.append(cur)
    if gather is not None:
        rep = all_gather_replicated(cur, mesh)
        layers.extend(_tree_tail_layers(rep, cap_size))
    return tuple(layers)


def commit_from_mono_sm(mono, L: int, cap_size: int, mesh: Mesh):
    """Materialized commit of a (B, n) monomial stack over the mesh:
    shard_map LDE + explicit pivot + per-chip leaf sponge + node layers.
    Returns (lde (B, L, n), layers) — same contract as the meshless
    lde_from_monomial + commit_layers_device pair, bit-identical values."""
    B, n = int(mono.shape[0]), int(mono.shape[-1])
    D = mesh_devices(mesh)
    N = n * L
    use_limb = leaf_limb_ok(B, N // D)
    mono_p = pad_cols_sharded(mono, mesh)
    fn = _lde_pivot_leaf_fn(mesh, L, B, use_limb)
    with _pivot_timer():
        lde_p, digests = fn(mono_p)
    _ici_all_to_all(int(mono_p.shape[0]) * N * 8, mesh)
    if use_limb:
        _metrics.count("merkle.limb_leaf_sponges")
    _metrics.count("merkle.sm_commits")
    lde = lde_p[:B] if lde_p.shape[0] != B else lde_p
    return lde, node_layers_sm(digests, cap_size, mesh)


def streamed_leaf_digests_sm(mono, L: int, mesh: Mesh):
    """Streamed commit over the mesh: each chip absorbs ITS OWN row range
    of every column block into a carried local sponge state. Per block:
    local LDE of the block's column stripe, the explicit all_to_all pivot,
    then streaming._absorb_cols on the row-sharded (N, b) columns (the
    absorb itself needs no communication — the sponge state is row-local).
    Only the final digests leave the chip (node_layers_sm gathers the
    cap). The loop is streaming.double_buffered_absorb, so block b+1's
    LDE + pivot collective are in flight while block b absorbs. Absorb
    order equals the meshless streamed commit exactly, so digests are
    bit-identical."""
    from ..prover.streaming import COL_BLOCK, double_buffered_absorb

    B, n = int(mono.shape[0]), int(mono.shape[-1])
    N = n * L
    state = jax.device_put(
        jnp.zeros((N, 12), jnp.uint64),
        NamedSharding(mesh, P(_AXES, None)),
    )

    def _cols(i):
        b = min(COL_BLOCK, B - i)
        blk_p = pad_cols_sharded(mono[i : i + b], mesh)
        fn = _lde_pivot_cols_fn(mesh, L, b)
        with _pivot_timer():
            cols = fn(blk_p)
        _ici_all_to_all(int(blk_p.shape[0]) * N * 8, mesh)
        _metrics.count("stream.sm_blocks")
        return cols

    state = double_buffered_absorb(state, range(0, B, COL_BLOCK), _cols)
    return state[:, :4]


def commit_pipeline_sm(values, L: int, cap_size: int, stream: bool,
                       mesh: Mesh):
    """The shard_map twin of prover._commit_pipeline: values over H ->
    (mono, lde | None, tree layers)."""
    B = int(values.shape[0])
    vp = pad_cols_sharded(values, mesh)
    mono_p = _mono_fn(mesh)(vp)
    mono = mono_p[:B] if mono_p.shape[0] != B else mono_p
    _metrics.count("ntt.monomial_from_values")
    if stream:
        digests = streamed_leaf_digests_sm(mono, L, mesh)
        _metrics.count("merkle.streamed_commits")
        return mono, None, node_layers_sm(digests, cap_size, mesh)
    lde, layers = commit_from_mono_sm(mono, L, cap_size, mesh)
    _metrics.count("ntt.lde_from_monomial")
    _metrics.count("merkle.commits")
    return mono, lde, layers


# ---------------------------------------------------------------------------
# Round 3: coset evaluation (with pivot) + row-sharded terms sweep
# ---------------------------------------------------------------------------


@lru_cache(maxsize=None)
def _coset_eval_fn(mesh: Mesh, B_real: int):
    """Per-coset group evaluation over the mesh: per-chip scale + forward
    NTT of the local column stripe, then the explicit all_to_all pivot to
    row sharding — the layout the terms sweep consumes. Keyed on the real
    column count (the pad is sliced off after the pivot); jit keys the
    rest by shape."""
    from ..ntt.ntt import fft_natural_to_bitreversed

    def body(mono_blk, scale_row):
        with local_operands():
            v = fft_natural_to_bitreversed(
                gf.mul(mono_blk, scale_row[None, :])
            )
        return jax.lax.all_to_all(
            v, _AXES, split_axis=1, concat_axis=0, tiled=True
        )

    smf = shard_map(
        body, mesh=mesh, in_specs=(P(_AXES, None), P(None)),
        out_specs=P(None, _AXES), check_rep=False,
    )

    @jax.jit
    def fn(mono_p, scale_q, c_arr):
        scale_row = jax.lax.dynamic_index_in_dim(
            scale_q, c_arr, 0, keepdims=False
        )
        return smf(mono_p, scale_row)[:B_real]

    return fn


def coset_eval_q_sm(mono_p, scale_q, c_arr, B_real: int, mesh: Mesh):
    """shard_map twin of prover._coset_eval_q; `mono_p` comes from
    pad_cols_sharded (done once per round, not per coset)."""
    fn = _coset_eval_fn(mesh, B_real)
    with _pivot_timer():
        out = fn(mono_p, scale_q, c_arr)
    _ici_all_to_all(int(mono_p.shape[0] * mono_p.shape[-1]) * 8, mesh)
    return out


def sweep_shard_map(core, mesh: Mesh):
    """Wrap a per-coset terms core (limb Pallas kernel or the u64 body —
    both are pointwise across the domain) in shard_map over row-sharded
    oracle evaluations. The xs/L0/1-Z_H coset slices happen OUTSIDE the
    map on the replicated full-rate tables (slice boundaries are coset
    multiples of n, so resharding the slice is communication-free); the
    challenge scalars and alpha/γ-power tables replicate."""
    row = P(None, _AXES)
    vec = P(_AXES)
    rep = P(None)
    smf = shard_map(
        core, mesh=mesh,
        in_specs=(
            row, row, row, row, vec, vec, vec,
            rep, rep, rep, rep, rep, rep,
        ),
        out_specs=(vec, vec), check_rep=False,
    )

    def body(
        wit_v, setup_v, s2_v, zs_v, c_arr,
        xs_q, l0_q, zhinv_q, ap0, ap1, beta01, gamma01, lkb01, lkg01,
    ):
        n = wit_v.shape[-1]
        start = c_arr * n
        xs_sl = jax.lax.dynamic_slice_in_dim(xs_q, start, n)
        l0_sl = jax.lax.dynamic_slice_in_dim(l0_q, start, n)
        zhinv_sl = jax.lax.dynamic_slice_in_dim(zhinv_q, start, n)
        return smf(
            wit_v, setup_v, s2_v, zs_v, xs_sl, l0_sl, zhinv_sl,
            ap0, ap1, beta01, gamma01, lkb01, lkg01,
        )

    return jax.jit(body)


# ---------------------------------------------------------------------------
# Round 5: DEEP codeword per chip (pointwise across the domain)
# ---------------------------------------------------------------------------


@lru_cache(maxsize=None)
def _deep_fn(mesh: Mesh, nsrc: int, num_zw: int, num_lk: int, num_pi: int):
    """The whole DEEP accumulation — main sum + extra terms — as ONE
    shard_map graph over domain shards. Every term is pointwise across the
    domain (per position: Σ ch_i·(f_i(x) − y_i)/(x − z) plus the z·ω /
    lookup-at-0 / public-input opens), so each chip computes its N/D slice
    with the exact same integer ops as the meshless graph and the BODY
    needs no collective. The (B, N) sources arrive column-sharded from the
    commit pipelines, so the jit boundary re-lays them to the domain
    sharding the in_specs demand — that pivot is charged to the ici.*
    gauges by deep_codeword_sm (it is round 5's dominant ICI payload). This exists for correctness as much as speed: a plain jit over
    mesh-sharded u64 operands goes through XLA's SPMD partitioner, which
    miscompiles this very accumulation (first divergence of the whole
    prove lands on fri_cap_0 — h itself comes out wrong on the
    forced-8-device CPU mesh). shard_map hands the body per-chip blocks,
    so the partitioner never sees it."""
    from ..prover.prover import _deep_extras_fn, _deep_main_sum

    row = P(None, _AXES)
    vec = P(_AXES)
    rep = P(None)

    def body(
        srcs, y0s, y1s, c0s, c1s, inv_xz, inv_xzw,
        cols_zw, cols_lk, inv_x, cols_pi, pi_denoms, pi_vals,
        y_zw, y_lk0, ch0e, ch1e,
    ):
        h = _deep_main_sum(list(srcs), y0s, y1s, c0s, c1s, inv_xz)
        return _deep_extras_fn(num_zw, num_lk, num_pi)(
            h, cols_zw, cols_lk, cols_pi, inv_xzw, inv_x, pi_denoms,
            y_zw, y_lk0, pi_vals, ch0e, ch1e,
        )

    in_specs = (
        (row,) * nsrc, rep, rep, rep, rep, (vec, vec), (vec, vec),
        row, row, vec if num_lk else rep, row, row, rep,
        (rep, rep), (rep, rep), rep, rep,
    )
    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=in_specs,
            out_specs=(vec, vec), check_rep=False,
        )
    )


def deep_codeword_sm(
    mesh: Mesh, deep_sources, y0s, y1s, c0s, c1s, inv_xz, prep,
    y_zw, y_lk0, ch0e, ch1e, num_zw: int, num_lk: int, num_pi: int,
):
    """shard_map twin of the fused round-5 body in prover._prove_impl
    (_deep_main_sum + _deep_extras_fn). `deep_sources` must all be
    materialized (B, N) arrays — the streamed MonomialSource oracles
    regenerate inside plain jits and take the de-meshed fallback in
    prover.py instead. Returns the ext codeword pair row-sharded over
    ('col','row') — exactly the layout the per-chip FRI fold and commit
    graphs consume."""
    fn = _deep_fn(mesh, len(deep_sources), num_zw, num_lk, num_pi)
    _metrics.count("deep.sm_codewords")
    # the sources are column-sharded (commit-pipeline layout); entering
    # the domain-sharded shard_map re-lays them out at the jit boundary —
    # bill that pivot like the explicit ones, it is round 5's dominant
    # interconnect movement
    _ici_all_to_all(
        sum(int(a.size) * a.dtype.itemsize for a in deep_sources), mesh
    )
    s2_cols = prep["s2_cols"]
    with _pivot_timer():
        return fn(
            tuple(deep_sources), y0s, y1s, c0s, c1s,
            inv_xz, prep["inv_xzw"],
            s2_cols[:num_zw], s2_cols[num_zw:], prep["inv_x"],
            prep["cols_pi"], prep["pi_denoms"], prep["pi_vals"],
            y_zw, y_lk0, ch0e, ch1e,
        )


# ---------------------------------------------------------------------------
# FRI fold over row shards (pairs are adjacent in brev layout -> local)
# ---------------------------------------------------------------------------


def fold_shards_ok(size: int, k: int, mesh: Mesh) -> bool:
    """A k-fold chain stays shard-local iff every intermediate local size
    is even: size must be divisible by D·2^k — the same predicate also
    guards the per-chip oracle commit (the 2^k-points-per-leaf regroup
    must land on whole local rows)."""
    return size % (mesh_devices(mesh) << k) == 0


@lru_cache(maxsize=None)
def _fri_leaf_fn(mesh: Mesh, k: int):
    """Per-chip FRI oracle leaf hashing: regroup 2^k brev-consecutive
    domain points (interleaved c0,c1) per leaf and sponge them — the leaf
    subtrees are fully shard-local under fold_shards_ok. The `leaf_hash`
    dispatcher picks the Pallas sponge on TPU over the local block."""
    from ..hashes.poseidon2 import leaf_hash

    def body(c0, c1):
        arr = jnp.stack([c0, c1], axis=-1)
        leaves = arr.reshape(c0.shape[0] >> k, -1)
        with local_operands():
            return leaf_hash(leaves)

    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(P(_AXES), P(_AXES)),
            out_specs=P(_AXES, None), check_rep=False,
        )
    )


def fri_commit_sm(cur, k: int, cap_size: int, mesh: Mesh):
    """Commit one FRI oracle over the mesh: per-chip leaf sponges over the
    row-sharded codeword, then node_layers_sm (per-chip 2-to-1 layers, one
    cap all_gather). Layer values are identical to merkle._tree_layers."""
    dig = _fri_leaf_fn(mesh, k)(cur[0], cur[1])
    _metrics.count("fri.sm_commits")
    return node_layers_sm(dig, cap_size, mesh)


def _demesh_array(arr, dev):
    """One jax.Array onto a single LOCAL device. Fully-addressable arrays
    move with a plain device_put; a multi-host global array spanning
    non-addressable devices (for which that device_put is illegal) is
    gathered to THIS host first — transfer.to_host rides
    multihost_utils.process_allgather and bills the cross-host bytes to
    the dcn.* gauges — then re-lands on the local device. Every process
    gathers the same global value, so downstream single-device graphs
    stay bit-identical across hosts."""
    if getattr(arr, "is_fully_addressable", True):
        return jax.device_put(arr, dev)
    from ..utils import transfer as _transfer

    return jax.device_put(_transfer.to_host(arr), dev)


def demesh(arr):
    """Pull an array (or ext pair / MonomialSource / plane structures)
    onto one local device — the correctness fallback where a mesh layout
    would send a plain jit through the SPMD partitioner (legacy GSPMD
    round 5, streamed DEEP sources, deep FRI fold tails). Addressable-
    safe: on multi-host meshes non-addressable arrays gather to every
    host (billed as dcn.host_gather_bytes) instead of attempting the
    cross-process device_put that PR 5's single-device pull performed."""
    from ..prover.streaming import MonomialPlanesSource, MonomialSource

    dev = jax.local_devices()[0]
    if isinstance(arr, MonomialSource):
        return MonomialSource(_demesh_array(arr.mono, dev), arr.L)
    if isinstance(arr, MonomialPlanesSource):
        return MonomialPlanesSource(demesh(arr.mono), arr.L)
    if isinstance(arr, tuple):
        return tuple(demesh(a) for a in arr)
    if isinstance(arr, jax.Array):
        return _demesh_array(arr, dev)
    return arr


# ---------------------------------------------------------------------------
# Limb-resident twins (ISSUE 10): the same per-chip kernels + explicit
# collectives over (lo, hi) u32 plane pairs. Each pivot/gather moves two
# u32 planes instead of one u64 array — same total bytes, HALF the
# per-element payload width — and every body computes in the limb domain
# (ntt/limb_ntt.py, poseidon2 plane sponges), so values (digests, caps,
# terms) are bit-identical to the u64 mesh path.
# ---------------------------------------------------------------------------


def pad_cols_sharded_p(p, mesh: Mesh):
    """Plane twin of pad_cols_sharded."""
    return pad_cols_sharded(p[0], mesh), pad_cols_sharded(p[1], mesh)


def _ici_all_to_all_p(nbytes_global_pair: int, mesh: Mesh):
    """Two u32-plane collectives = one logical pivot: bill each plane
    (halved per-element width; the byte total equals the u64 pivot's)."""
    _ici_all_to_all(nbytes_global_pair // 2, mesh)
    _ici_all_to_all(nbytes_global_pair // 2, mesh)


@lru_cache(maxsize=None)
def _mono_fn_p(mesh: Mesh):
    """Per-chip plane inverse NTT over the local column stripe."""
    from ..ntt.limb_ntt import monomial_from_values_p

    def body(vals_p):
        with local_operands():
            return monomial_from_values_p(vals_p)

    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(P(_AXES, None),),
            out_specs=P(_AXES, None), check_rep=False,
        )
    )


def _pivot_planes(flat_p):
    """The col->row layout pivot on a plane pair: one all_to_all per
    plane (u32 payloads)."""
    return (
        jax.lax.all_to_all(
            flat_p[0], _AXES, split_axis=1, concat_axis=0, tiled=True
        ),
        jax.lax.all_to_all(
            flat_p[1], _AXES, split_axis=1, concat_axis=0, tiled=True
        ),
    )


@lru_cache(maxsize=None)
def _lde_pivot_leaf_fn_p(mesh: Mesh, L: int, B_real: int):
    """Plane twin of _lde_pivot_leaf_fn: per-chip plane LDE, the plane
    pivot, and the per-chip plane leaf sponge (fused kernel on TPU, XLA
    limb rounds elsewhere — hashes/poseidon2.leaf_hash_planes)."""
    from ..hashes.poseidon2 import leaf_hash_planes
    from ..ntt.limb_ntt import lde_from_monomial_p

    def body(mono_p):
        b = mono_p[0].shape[0]
        with local_operands():
            lde = lde_from_monomial_p(mono_p, L)
        flat = (lde[0].reshape(b, -1), lde[1].reshape(b, -1))
        piv = _pivot_planes(flat)
        leaves = (piv[0].T[:, :B_real], piv[1].T[:, :B_real])
        with local_operands():
            dig = leaf_hash_planes(leaves)
        return lde, dig

    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(P(_AXES, None),),
            out_specs=(P(_AXES, None, None), P(_AXES, None)),
            check_rep=False,
        )
    )


@lru_cache(maxsize=None)
def _lde_pivot_cols_fn_p(mesh: Mesh, L: int, b_real: int):
    """Plane twin of _lde_pivot_cols_fn (streamed block pivot)."""
    from ..ntt.limb_ntt import lde_from_monomial_p

    def body(mono_p):
        b = mono_p[0].shape[0]
        with local_operands():
            lde = lde_from_monomial_p(mono_p, L)
        piv = _pivot_planes((lde[0].reshape(b, -1), lde[1].reshape(b, -1)))
        return piv[0].T[:, :b_real], piv[1].T[:, :b_real]

    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(P(_AXES, None),),
            out_specs=P(_AXES, None), check_rep=False,
        )
    )


@lru_cache(maxsize=None)
def _node_step_fn_p(mesh: Mesh):
    """Plane twin of _node_step_fn."""
    from ..hashes.poseidon2 import node_hash_planes

    def body(d_p):
        with local_operands():
            return node_hash_planes(
                (d_p[0][0::2], d_p[1][0::2]), (d_p[0][1::2], d_p[1][1::2])
            )

    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(P(_AXES, None),),
            out_specs=P(_AXES, None), check_rep=False,
        )
    )


def all_gather_replicated_p(p, mesh: Mesh):
    """Plane twin of all_gather_replicated (two u32 gathers)."""
    out = (
        _all_gather_fn(mesh, p[0].ndim)(p[0]),
        _all_gather_fn(mesh, p[1].ndim)(p[1]),
    )
    _ici_all_gather(int(p[0].size) * p[0].dtype.itemsize, mesh)
    _ici_all_gather(int(p[1].size) * p[1].dtype.itemsize, mesh)
    return out


def node_layers_sm_p(digests_p, cap_size: int, mesh: Mesh):
    """Plane twin of node_layers_sm."""
    from ..merkle import _tree_tail_layers_planes

    steps, gather = node_plan(
        int(digests_p[0].shape[0]), cap_size, mesh_devices(mesh)
    )
    layers = [digests_p]
    cur = digests_p
    for _ in steps:
        cur = _node_step_fn_p(mesh)(cur)
        layers.append(cur)
    if gather is not None:
        rep = all_gather_replicated_p(cur, mesh)
        layers.extend(_tree_tail_layers_planes(rep, cap_size))
    return tuple(layers)


def commit_from_mono_sm_p(mono_p, L: int, cap_size: int, mesh: Mesh):
    """Plane twin of commit_from_mono_sm."""
    B, n = int(mono_p[0].shape[0]), int(mono_p[0].shape[-1])
    N = n * L
    mono_pp = pad_cols_sharded_p(mono_p, mesh)
    fn = _lde_pivot_leaf_fn_p(mesh, L, B)
    with _pivot_timer():
        lde_p, digests = fn(mono_pp)
    _ici_all_to_all_p(int(mono_pp[0].shape[0]) * N * 8, mesh)
    _metrics.count("merkle.sm_commits")
    _metrics.count("merkle.resident_commits")
    if lde_p[0].shape[0] != B:
        lde_p = (lde_p[0][:B], lde_p[1][:B])
    return lde_p, node_layers_sm_p(digests, cap_size, mesh)


def streamed_leaf_digests_sm_p(mono_p, L: int, mesh: Mesh):
    """Plane twin of streamed_leaf_digests_sm: per-chip plane absorb of
    each pivoted block (streaming._absorb_cols_p)."""
    from ..prover.streaming import (
        COL_BLOCK,
        _absorb_cols_p,
        double_buffered_absorb,
    )

    B, n = int(mono_p[0].shape[0]), int(mono_p[0].shape[-1])
    N = n * L
    sh = NamedSharding(mesh, P(_AXES, None))
    state = (
        jax.device_put(jnp.zeros((N, 12), jnp.uint32), sh),
        jax.device_put(jnp.zeros((N, 12), jnp.uint32), sh),
    )

    def _cols(i):
        b = min(COL_BLOCK, B - i)
        blk_p = pad_cols_sharded_p(
            (mono_p[0][i : i + b], mono_p[1][i : i + b]), mesh
        )
        fn = _lde_pivot_cols_fn_p(mesh, L, b)
        with _pivot_timer():
            cols = fn(blk_p)
        _ici_all_to_all_p(int(blk_p[0].shape[0]) * N * 8, mesh)
        _metrics.count("stream.sm_blocks")
        return cols

    state = double_buffered_absorb(
        state, range(0, B, COL_BLOCK), _cols, absorb=_absorb_cols_p
    )
    return state[0][:, :4], state[1][:, :4]


def commit_pipeline_sm_p(values_p, L: int, cap_size: int, stream: bool,
                         mesh: Mesh):
    """Plane twin of commit_pipeline_sm."""
    B = int(values_p[0].shape[0])
    vp = pad_cols_sharded_p(values_p, mesh)
    mono_pp = _mono_fn_p(mesh)(vp)
    if mono_pp[0].shape[0] != B:
        mono_pp = (mono_pp[0][:B], mono_pp[1][:B])
    _metrics.count("ntt.monomial_from_values")
    _metrics.count("ntt.resident_transforms")
    if stream:
        digests = streamed_leaf_digests_sm_p(mono_pp, L, mesh)
        _metrics.count("merkle.streamed_commits")
        _metrics.count("merkle.resident_commits")
        return mono_pp, None, node_layers_sm_p(digests, cap_size, mesh)
    lde, layers = commit_from_mono_sm_p(mono_pp, L, cap_size, mesh)
    _metrics.count("ntt.lde_from_monomial")
    _metrics.count("merkle.commits")
    return mono_pp, lde, layers


@lru_cache(maxsize=None)
def _coset_eval_fn_p(mesh: Mesh, B_real: int):
    """Plane twin of _coset_eval_fn: per-chip plane scale+NTT, plane
    pivot to row sharding."""
    from ..field import limbs
    from ..ntt.limb_ntt import fft_natural_to_bitreversed_p

    def body(mono_p, scale_row_p):
        with local_operands():
            v = fft_natural_to_bitreversed_p(
                limbs.mul(
                    mono_p, (scale_row_p[0][None, :], scale_row_p[1][None, :])
                )
            )
        return (
            jax.lax.all_to_all(
                v[0], _AXES, split_axis=1, concat_axis=0, tiled=True
            ),
            jax.lax.all_to_all(
                v[1], _AXES, split_axis=1, concat_axis=0, tiled=True
            ),
        )

    smf = shard_map(
        body, mesh=mesh, in_specs=(P(_AXES, None), P(None)),
        out_specs=P(None, _AXES), check_rep=False,
    )

    @jax.jit
    def fn(mono_p, scale_q_p, c_arr):
        scale_row = (
            jax.lax.dynamic_index_in_dim(
                scale_q_p[0], c_arr, 0, keepdims=False
            ),
            jax.lax.dynamic_index_in_dim(
                scale_q_p[1], c_arr, 0, keepdims=False
            ),
        )
        out = smf(mono_p, scale_row)
        return out[0][:B_real], out[1][:B_real]

    return fn


def coset_eval_q_sm_p(mono_p, scale_q_p, c_arr, B_real: int, mesh: Mesh):
    """Plane twin of coset_eval_q_sm."""
    fn = _coset_eval_fn_p(mesh, B_real)
    with _pivot_timer():
        out = fn(mono_p, scale_q_p, c_arr)
    _ici_all_to_all_p(int(mono_p[0].shape[0] * mono_p[0].shape[-1]) * 8, mesh)
    return out


def sweep_shard_map_p(core_p, mesh: Mesh):
    """Plane twin of sweep_shard_map: wraps the RESIDENT per-coset terms
    core (plane stacks + host-built scalar table) in shard_map over
    row-sharded plane evaluations."""
    row = P(None, _AXES)
    vec = P(_AXES)
    rep = P(None)
    smf = shard_map(
        core_p, mesh=mesh,
        in_specs=(
            row, row, row, row, vec, vec, vec, rep,
        ),
        out_specs=(vec, vec), check_rep=False,
    )

    def body(
        wit_p, setup_p, s2_p, zs_p, c_arr,
        xs_q_p, l0_q_p, zhinv_q_p, table,
    ):
        n = wit_p[0].shape[-1]
        start = c_arr * n

        def _sl(p):
            return (
                jax.lax.dynamic_slice_in_dim(p[0], start, n),
                jax.lax.dynamic_slice_in_dim(p[1], start, n),
            )

        return smf(
            wit_p, setup_p, s2_p, zs_p,
            _sl(xs_q_p), _sl(l0_q_p), _sl(zhinv_q_p), table,
        )

    return jax.jit(body)


@lru_cache(maxsize=None)
def _deep_fn_p(mesh: Mesh, nsrc: int, num_zw: int, num_lk: int, num_pi: int):
    """Plane twin of _deep_fn: the whole resident DEEP accumulation as
    ONE shard_map graph over domain shards."""
    from ..prover.resident import _deep_extras_fn_p, _deep_main_sum_p

    row = P(None, _AXES)
    vec = P(_AXES)
    rep = P(None)

    def body(
        srcs, y0s, y1s, c0s, c1s, inv_xz, inv_xzw,
        cols_zw, cols_lk, inv_x, cols_pi, pi_denoms, pi_vals,
        y_zw, y_lk0, ch0e, ch1e,
    ):
        h = _deep_main_sum_p(list(srcs), y0s, y1s, c0s, c1s, inv_xz)
        return _deep_extras_fn_p(num_zw, num_lk, num_pi)(
            h, cols_zw, cols_lk, cols_pi, inv_xzw, inv_x, pi_denoms,
            y_zw, y_lk0, pi_vals, ch0e, ch1e,
        )

    in_specs = (
        (row,) * nsrc, rep, rep, rep, rep, (vec, vec), (vec, vec),
        row, row, vec if num_lk else rep, row, row, rep,
        (rep, rep), (rep, rep), rep, rep,
    )
    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=in_specs,
            out_specs=(vec, vec), check_rep=False,
        )
    )


def deep_codeword_sm_p(
    mesh: Mesh, deep_sources, y0s, y1s, c0s, c1s, inv_xz, prep,
    y_zw, y_lk0, ch0e, ch1e, num_zw: int, num_lk: int, num_pi: int,
):
    """Plane twin of deep_codeword_sm; returns the ext codeword PLANE
    pair row-sharded — the layout the resident per-chip FRI graphs
    consume."""
    fn = _deep_fn_p(mesh, len(deep_sources), num_zw, num_lk, num_pi)
    _metrics.count("deep.sm_codewords")
    _ici_all_to_all(
        sum(
            int(a.size) * a.dtype.itemsize
            for pair in deep_sources
            for a in pair
        ),
        mesh,
    )
    s2_cols = prep["s2_cols"]
    cols_zw = (s2_cols[0][:num_zw], s2_cols[1][:num_zw])
    cols_lk = (s2_cols[0][num_zw:], s2_cols[1][num_zw:])
    with _pivot_timer():
        return fn(
            tuple(deep_sources), y0s, y1s, c0s, c1s,
            inv_xz, prep["inv_xzw"],
            cols_zw, cols_lk, prep["inv_x"],
            prep["cols_pi"], prep["pi_denoms"], prep["pi_vals"],
            y_zw, y_lk0, ch0e, ch1e,
        )


@lru_cache(maxsize=None)
def _fri_leaf_fn_p(mesh: Mesh, k: int):
    """Plane twin of _fri_leaf_fn."""
    from ..hashes.poseidon2 import leaf_hash_planes

    def body(c0, c1):
        n_loc = c0[0].shape[0]
        llo = jnp.stack([c0[0], c1[0]], axis=-1).reshape(n_loc >> k, -1)
        lhi = jnp.stack([c0[1], c1[1]], axis=-1).reshape(n_loc >> k, -1)
        with local_operands():
            return leaf_hash_planes((llo, lhi))

    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(P(_AXES), P(_AXES)),
            out_specs=P(_AXES, None), check_rep=False,
        )
    )


def fri_commit_sm_p(cur, k: int, cap_size: int, mesh: Mesh):
    """Plane twin of fri_commit_sm."""
    dig = _fri_leaf_fn_p(mesh, k)(cur[0], cur[1])
    _metrics.count("fri.sm_commits")
    return node_layers_sm_p(dig, cap_size, mesh)
