"""boojum_tpu — a TPU-native PLONKish + FRI proof system over the Goldilocks field.

A ground-up JAX/XLA/Pallas implementation with the capabilities of Boojum
(zkSync Era's prover, see /root/reference): PLONKish arithmetization with copy
constraints, log-derivative lookups, FRI commitment, gate/gadget libraries and
recursion — designed TPU-first: trace columns are device arrays, the hot path
(NTT/LDE, Poseidon2 Merkle trees, gate-evaluation sweeps, FRI folds) is
batched/vmapped XLA, and multi-chip scaling shards trace columns over an ICI
mesh with XLA collectives.
"""

import jax

# The whole framework computes over GF(2^64 - 2^32 + 1); we need 64-bit ints.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
