"""boojum_tpu — a TPU-native PLONKish + FRI proof system over the Goldilocks field.

A ground-up JAX/XLA/Pallas implementation with the capabilities of Boojum
(zkSync Era's prover, see /root/reference): PLONKish arithmetization with copy
constraints, log-derivative lookups, FRI commitment, gate/gadget libraries and
recursion — designed TPU-first: trace columns are device arrays, the hot path
(NTT/LDE, Poseidon2 Merkle trees, gate-evaluation sweeps, FRI folds) is
batched/vmapped XLA, and multi-chip scaling shards trace columns over an ICI
mesh with XLA collectives.
"""

import os

import jax

# The whole framework computes over GF(2^64 - 2^32 + 1); we need 64-bit ints.
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: the prover pipelines are large jitted graphs
# keyed by (shape, geometry); caching them on disk means only the first-ever
# run of a given circuit shape pays XLA compile time. Opt out with
# BOOJUM_TPU_NO_COMPILE_CACHE=1 or by pre-setting jax_compilation_cache_dir.
from ._hostfp import host_fingerprint as _host_fingerprint


if not os.environ.get("BOOJUM_TPU_NO_COMPILE_CACHE"):
    try:
        # a host process (bench.py, conftest.py, multihost_worker.py) that
        # already pinned its cache dir also chose its own persistence
        # thresholds — leave BOTH alone (this import used to silently
        # revert bench's min_compile_time_secs=0.0 back to 1.0, dropping
        # every sub-second kernel from the cache the precompile sweep
        # fills)
        if not jax.config.jax_compilation_cache_dir:
            # one cache dir PER PLATFORM STRING and PER HOST FINGERPRINT: a
            # remote-TPU process (e.g. JAX_PLATFORMS=axon) gets its
            # host-side CPU AOT pieces compiled by the remote service with
            # the REMOTE machine's features, and loading those entries in a
            # local CPU process SIGILLs — and the same applies to local CPU
            # entries carried to a different host (see _host_fingerprint)
            _plat = (
                os.environ.get("JAX_PLATFORMS", "").strip().replace(",", "-")
                or "default"
            )
            jax.config.update(
                "jax_compilation_cache_dir",
                os.environ.get(
                    "BOOJUM_TPU_COMPILE_CACHE",
                    os.path.expanduser(
                        f"~/.cache/boojum_tpu_xla-{_plat}-{_host_fingerprint()}"
                    ),
                ),
            )
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", 0
            )
    except Exception:
        pass

__version__ = "0.1.0"
